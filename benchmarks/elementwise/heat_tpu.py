#!/usr/bin/env python
"""Elementwise-chain (fusion) microbenchmark — eager vs fused dispatch.

A chained normalize → scale → clip pipeline (7 elementwise ops end to
end), the steady-state weight-update-shaped traffic that
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv:2004.13336) identifies as a dominant small-op cost. With
``HEAT_TPU_FUSION=0`` each op dispatches (and first compiles) its own XLA
program; with fusion on (the default) the whole chain defers into one
FusedExpr DAG and executes as ONE cached program (core/fusion.py).

This runner measures BOTH modes in one process and prints a comparison
line::

    {"elementwise_compare": {"eager": {...}, "fused": {...},
     "fused_programs": 1, "chain_ops": 7, "speedup": ...}}

``fused_programs`` counts the programs the fusion registry actually
compiled for the chain (the dispatch-count oracle scripts/run_ci.sh
asserts on), and each mode's row carries best/mean wall clock over
``--trials`` runs.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import base_parser, bootstrap, load_or_make


CHAIN_OPS = 7  # sub, div, mul, add, clip, mul, add — see pipeline()


def pipeline(ht, data, mean, std):
    """normalize → scale → clip: 7 elementwise ops, zero reductions."""
    z = (data - mean) / (std + 1e-6)          # sub, add, div
    z = z * 0.125 + 0.5                       # mul, add
    z = ht.clip(z, 0.0, 1.0) * 255.0          # clip, mul
    return z


def _time_mode(ht, data, mean, std, trials, sync):
    from heat_tpu.core import fusion, program_cache

    f0 = fusion.stats()
    site0 = dict(program_cache.stats()["sites"].get(
        "fusion", {"hits": 0, "misses": 0}))
    with ht.telemetry.CompileWatcher() as cw:
        t0 = time.perf_counter()
        sync(pipeline(ht, data, mean, std))
        first_call = time.perf_counter() - t0
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        sync(pipeline(ht, data, mean, std))
        times.append(time.perf_counter() - t0)
    f1 = fusion.stats()
    site1 = dict(program_cache.stats()["sites"].get(
        "fusion", {"hits": 0, "misses": 0}))
    return {
        "compile_seconds": round(cw.seconds, 4),
        "first_call_seconds": round(first_call, 4),
        "programs_compiled": cw.backend_compiles,
        "best_seconds": round(min(times), 6),
        "mean_seconds": round(sum(times) / len(times), 6),
        "deferred_ops": f1["deferred"] - f0["deferred"],
        "flushes": f1["flushes"] - f0["flushes"],
        "fused_programs_compiled": site1["misses"] - site0["misses"],
    }


def main():
    parser = base_parser(
        "heat_tpu elementwise-chain (fusion) microbenchmark")
    parser.add_argument(
        "--split", type=int, default=0,
        help="distribution axis of the operand (default 0)")
    args = parser.parse_args()
    ht = bootstrap(args)

    data = load_or_make(ht, args, split=args.split)
    import numpy as np

    mean = ht.array(np.float32(0.1))
    std = ht.array(np.float32(1.3))

    def sync(out):
        return float(out.larray[(0,) * out.ndim])

    rows = {}
    for mode, flag in (("eager", "0"), ("fused", "1")):
        os.environ["HEAT_TPU_FUSION"] = flag
        rows[mode] = _time_mode(ht, data, mean, std, args.trials, sync)
        print(json.dumps({"mode": mode, **rows[mode]}), flush=True)
    os.environ.pop("HEAT_TPU_FUSION", None)

    compare = {
        "chain_ops": CHAIN_OPS,
        "eager": rows["eager"],
        "fused": rows["fused"],
        "fused_programs": rows["fused"]["fused_programs_compiled"],
        "speedup": round(
            rows["eager"]["best_seconds"]
            / max(rows["fused"]["best_seconds"], 1e-9), 3),
    }
    from heat_tpu import telemetry

    summary = {"elementwise_compare": compare}
    if telemetry.enabled():
        summary.update(telemetry.report.bench_fields())
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
