#!/usr/bin/env python
"""Statistical-moments scaling benchmark (reference:
benchmarks/statistical_moments/config.json — mean/var over cityscapes
rows). One jitted pass computes mean+var; on single-device TPU f32 both
route through the one-HBM-read Welford kernel (core/pallas_moments.py)
and CSE into one kernel execution."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import load_or_make, run


def add_args(p):
    pass


def build(ht, args):
    return load_or_make(ht, args, split=0)


def fit_factory(ht, args, data):
    import jax

    # heatlint: disable=HL001 -- the benchmark times ONE fused probe
    # program it compiles itself; registry reuse across trials would fold
    # the dispatch cost the harness exists to measure
    @jax.jit
    def one_pass(buf):
        from heat_tpu.core.dndarray import DNDarray

        X = DNDarray(buf, data.shape, data.dtype, data.split, data.device,
                     data.comm, True)
        return (ht.mean(X, axis=0) + ht.var(X, axis=0)).larray

    def fit():
        return one_pass(data.larray)

    def sync(m):
        return float(m[0])

    return fit, sync


if __name__ == "__main__":
    run("heat_tpu statistical-moments scaling benchmark", add_args, build,
        fit_factory)
