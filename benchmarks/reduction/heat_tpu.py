#!/usr/bin/env python
"""Through-reduction fusion microbenchmark — eager vs fused dispatch of
normalize→scale→sum and mean/var moment chains (ISSUE 7, core/fusion.py
``absorb_reduce`` / ``defer_matmul``).

PR 4's elementwise bench stops at the reduction: every chain ending in a
``sum``/``mean``/``var`` still paid one flush program PLUS one eager
reduce dispatch. Fusion 2.0 absorbs the chain into the reduction's
program, so the whole normalize-then-reduce pipeline is ONE cached
program whose collective tail rides in the same trace. This runner
measures THREE modes in one process:

* ``eager``  — ``HEAT_TPU_FUSION=0``: one XLA dispatch per op (PR 3).
* ``flush``  — fusion on, ``HEAT_TPU_FUSION_REDUCE=0``: the chain fuses
  but flushes at the reduction (PR 4 behavior, the knob-off baseline).
* ``fused``  — both on: chain+reduction absorbed (this PR).

and prints a comparison line::

    {"reduction_compare": {"eager": {...}, "flush": {...}, "fused": {...},
     "fused_programs": 1, "dense_programs": 1, "digest_match": true, ...}}

``programs_compiled`` counts backend compiles on the cold first call (the
dispatch-count oracle scripts/run_ci.sh asserts on: fused must compile
>= 3x fewer programs than eager for the normalize→scale→sum chain and
exactly ONE program for the chain; the DP-forward ``dense`` —
matmul+bias+relu — must also be ONE program).

Digest semantics (what is and is not bit-pinned): ``digest_chain`` hashes
the map+reduce result — bit-identical between ``fused`` and ``flush``
(the absorbed program computes the same masked chain + sum). The moment
chain's ``var`` re-derives the shared centered chain INSIDE the absorbed
program, which legally re-tiles the f32 reduction — so ``digest_moments``
is bit-pinned only within a mode (knob-off == PR 6 by code-path identity)
and fused-vs-flush is checked via ``moments_allclose`` instead.
"""

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import base_parser, bootstrap, load_or_make


CHAIN_OPS = 5  # sub, add, div, mul + sum — see chain_reduce()
MOMENT_OPS = 4  # sub, mul + mean, var over the shared centered chain


def chain_reduce(ht, data, mean, std):
    """normalize → scale → sum along the split axis: the canonical
    map+reduce shape (4 elementwise ops + 1 reduction)."""
    z = (data - mean) / (std + 1e-6) * 0.125
    return ht.sum(z, axis=0)


def moment_chain(ht, data, mean):
    """Centered second-moment pipeline: the statistical-moments bench
    pattern (chain → mean AND chain → var share the sub-DAG)."""
    d = (data - mean) * 2.0
    return ht.mean(d, axis=0), ht.var(d, axis=0)


def dense_forward(ht, x, w, b):
    """The DP-forward building block: matmul + bias + relu as ONE cached
    program via the deferred matmul kernel node (nn/functional.dense)."""
    from heat_tpu.nn import functional as F

    return F.dense(x, w, bias=b, activation="relu")


def _digest(*arrays):
    h = hashlib.sha256()
    for a in arrays:
        import numpy as np

        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    return h.hexdigest()


def _time_mode(ht, data, mean, std, trials):
    import numpy as np

    from heat_tpu.core import fusion, program_cache

    def run_once():
        s = chain_reduce(ht, data, mean, std)
        mu, var = moment_chain(ht, data, mean)
        return s.numpy(), mu.numpy(), var.numpy()

    def fusion_sites():
        return {
            k: dict(v)
            for k, v in program_cache.stats()["sites"].items()
            if k.startswith("fusion")
        }

    f0 = fusion.stats()
    sites0 = fusion_sites()
    with ht.telemetry.CompileWatcher() as cw:
        t0 = time.perf_counter()
        out = run_once()
        first_call = time.perf_counter() - t0
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        run_once()
        times.append(time.perf_counter() - t0)
    f1 = fusion.stats()

    # per-MODE site deltas (the process-cumulative totals would leak the
    # fused mode's fusion_reduce entries into whichever mode runs later —
    # the CI disarm assert reads these per row)
    sites1 = fusion_sites()
    site_delta = {}
    for k, row1 in sites1.items():
        base = sites0.get(k, {"hits": 0, "misses": 0})
        d = {f: row1[f] - base.get(f, 0) for f in ("hits", "misses")}
        if d["hits"] or d["misses"]:
            site_delta[k] = d
    row = {
        "compile_seconds": round(cw.seconds, 4),
        "first_call_seconds": round(first_call, 4),
        "programs_compiled": cw.backend_compiles,
        "best_seconds": round(min(times), 6),
        "mean_seconds": round(sum(times) / len(times), 6),
        "reductions_absorbed": f1["reductions_absorbed"] - f0["reductions_absorbed"],
        "fallbacks": f1["fallbacks"] - f0["fallbacks"],
        "digest_chain": _digest(out[0]),
        "digest_moments": _digest(out[1], out[2]),
        "site_misses": site_delta,
    }
    return row, out


def _count_chain_programs(ht, data, mean, std):
    """Cold-compile count for the 5-op normalize→scale→sum chain alone."""
    with ht.telemetry.CompileWatcher() as cw:
        chain_reduce(ht, data, mean, std).numpy()
    return cw.backend_compiles


def _count_dense_programs(ht, args):
    import numpy as np

    rng = np.random.default_rng(7)
    x = ht.array(
        rng.standard_normal((4096, args.features)).astype(np.float32),
        split=0,
    )
    w = ht.array(rng.standard_normal((args.features, 32)).astype(np.float32))
    b = ht.array(rng.standard_normal(32).astype(np.float32))
    with ht.telemetry.CompileWatcher() as cw:
        dense_forward(ht, x, w, b).numpy()
    return cw.backend_compiles


def main():
    parser = base_parser(
        "heat_tpu through-reduction fusion microbenchmark")
    parser.add_argument(
        "--split", type=int, default=0,
        help="distribution axis of the operand (default 0)")
    args = parser.parse_args()
    ht = bootstrap(args)
    import numpy as np

    data = load_or_make(ht, args, split=args.split)
    mean = ht.array(np.float32(0.1))
    std = ht.array(np.float32(1.3))

    modes = (
        ("eager", {"HEAT_TPU_FUSION": "0"}),
        ("flush", {"HEAT_TPU_FUSION": "1", "HEAT_TPU_FUSION_REDUCE": "0"}),
        ("fused", {"HEAT_TPU_FUSION": "1", "HEAT_TPU_FUSION_REDUCE": "1"}),
    )
    rows = {}
    outs = {}
    chain_programs = {}
    for mode, env in modes:
        os.environ.update(env)
        # distinct leading extent per mode → every mode cold-compiles its
        # own programs (jax caches by shape, so reusing the shape would
        # credit later modes with the first mode's compiles)
        d = data[: data.shape[0] - {"eager": 0, "flush": 1, "fused": 2}[mode]]
        chain_programs[mode] = _count_chain_programs(ht, d, mean, std)
        rows[mode], outs[mode] = _time_mode(ht, data, mean, std, args.trials)
        rows[mode]["chain_programs_compiled"] = chain_programs[mode]
        print(json.dumps({"mode": mode, **rows[mode]}), flush=True)
    dense_programs = _count_dense_programs(ht, args)
    for k in ("HEAT_TPU_FUSION", "HEAT_TPU_FUSION_REDUCE"):
        os.environ.pop(k, None)

    moments_close = bool(
        np.allclose(outs["fused"][1], outs["flush"][1], rtol=1e-5, atol=1e-7)
        and np.allclose(outs["fused"][2], outs["flush"][2], rtol=1e-5, atol=1e-7)
    )
    compare = {
        "chain_ops": CHAIN_OPS,
        "moment_ops": MOMENT_OPS,
        "eager": rows["eager"],
        "flush": rows["flush"],
        "fused": rows["fused"],
        "chain_programs": chain_programs,
        "fused_programs": chain_programs["fused"],
        "dense_programs": dense_programs,
        # the map+reduce bit-identity pin: fused chain+sum == knob-off
        # flush-then-sum, bit for bit (run_ci.sh asserts this)
        "digest_chain_match": (
            rows["fused"]["digest_chain"] == rows["flush"]["digest_chain"]
        ),
        # moment chain: fused var legally re-tiles the f32 reduction →
        # tolerance check, not a bit pin (see module docstring)
        "moments_allclose": moments_close,
        "speedup_vs_eager": round(
            rows["eager"]["best_seconds"]
            / max(rows["fused"]["best_seconds"], 1e-9), 3),
    }
    import jax

    from heat_tpu import telemetry

    # bench honesty (ROADMAP standing weakness): record whether this run
    # actually measured an accelerator — CPU-mesh numbers validate dispatch
    # counts and scaling shape, not chip throughput
    compare["on_chip"] = jax.default_backend() == "tpu"
    summary = {"reduction_compare": compare}
    if telemetry.enabled():
        summary.update(telemetry.report.bench_fields())
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
