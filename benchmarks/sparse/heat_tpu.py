#!/usr/bin/env python
"""Sparse container microbenchmark — spmv/spmm vs the dense matmul
across densities, the budget-bounded transpose, and a Spectral
eNeighbour end-to-end row (ISSUE 13, heat_tpu/sparse).

What the dense stack could not express: an (n, n) operator at 0.1%
density holds ~n²/1000 elements, but every dense pipeline pays the full
n² in bytes and flops. This runner measures where the crossover sits on
the attached backend:

* per density (0.1% / 1% / 10%): one ``spmv`` (row-split result — zero
  wire), one replicated-result ``spmv`` (the audited all-reduce tail),
  one ``spmm`` over ``--features`` dense columns, and the dense
  ``matmul`` twin on the same masked operand;
* ``digest_match``: the row-split spmv against a dense reference
  mask-matmul evaluated **in the same per-row element order**
  (vectorized left-fold over element ranks) — BIT-identical, the
  ``run_ci.sh`` sparse gate's oracle;
* the transpose slab exchange, monolithic vs stage-decomposed
  (``slab=`` forced to capacity/4 — the deterministic form of the
  HEAT_TPU_HBM_BUDGET planning), digest-pinned bit-identical;
* a Spectral eNeighbour end-to-end row: the sparse pipeline
  (SparseDNDarray Laplacian + spmv Lanczos) vs the legacy dense one,
  with label agreement.

Summary line ``{"sparse_compare": ...}`` carries the honest
``on_chip`` + ``cpu_fallback`` pair like every bench in this tree.
"""

import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import base_parser, bootstrap

DENSITIES = (0.001, 0.01, 0.1)


def _digest(*arrays):
    import numpy as np

    h = hashlib.sha256()
    for a in arrays:
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    return h.hexdigest()


def sequential_reference(dense, x):
    """The dense mask-matmul evaluated in CSR element order: a
    vectorized left-fold over per-row element ranks, so each row's sum
    accumulates its stored entries left to right — the exact order the
    CSR segment reduction applies. Bit-comparable to the row-split spmv
    (trailing +0.0 pad adds are bitwise no-ops on a +0.0-initialized
    accumulator)."""
    import numpy as np

    m, n = dense.shape
    rows, cols = np.nonzero(dense)
    contrib = (dense[rows, cols] * x[cols]).astype(
        np.promote_types(dense.dtype, x.dtype)
    )
    counts = np.zeros(m, dtype=np.int64)
    np.add.at(counts, rows, 1)
    K = int(counts.max(initial=0))
    rank = np.arange(rows.shape[0]) - np.concatenate(
        [[0], np.cumsum(counts)[:-1]]
    )[rows]
    C = np.zeros((m, K), dtype=contrib.dtype)
    C[rows, rank] = contrib
    acc = np.zeros(m, dtype=contrib.dtype)
    for k in range(K):
        acc = acc + C[:, k]
    return acc


def _time(fn, trials):
    t0 = time.perf_counter()
    out = fn()
    first = time.perf_counter() - t0
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return out, first, min(times)


def density_row(ht, n, k, density, trials, audit):
    import numpy as np

    from heat_tpu import sparse, telemetry

    rng = np.random.default_rng(42)
    dense_h = rng.standard_normal((n, n)).astype(np.float32)
    dense_h[rng.random((n, n)) > density] = 0.0
    xh = rng.standard_normal(n).astype(np.float32)
    Xh = rng.standard_normal((n, k)).astype(np.float32)

    A = sparse.csr_from_dense(dense_h)
    D = ht.array(dense_h, split=0)
    x = ht.array(xh)
    X = ht.array(Xh)

    with telemetry.CompileWatcher() as cw:
        y_split, first_spmv, best_spmv = _time(
            lambda: np.asarray(
                sparse.spmv(A, x, audit=audit).larray
            ),
            trials,
        )
    spmv_compiles = cw.backend_compiles
    _, _, best_spmv_rep = _time(
        lambda: np.asarray(sparse.spmv(A, x, out_split=None).larray), trials
    )
    _, _, best_spmm = _time(
        lambda: np.asarray(sparse.spmm(A, X, audit=audit).larray), trials
    )
    _, _, best_dense_mv = _time(
        lambda: np.asarray(ht.matmul(D, x).larray), trials
    )
    _, _, best_dense_mm = _time(
        lambda: np.asarray(ht.matmul(D, X).larray), trials
    )

    ref = sequential_reference(dense_h, xh)
    got = np.asarray(sparse.spmv(A, x).numpy())
    row = {
        "density": density,
        "n": n,
        "nnz": A.nnz,
        "capacity": A.capacity,
        "spmv_best_s": round(best_spmv, 6),
        "spmv_replicated_best_s": round(best_spmv_rep, 6),
        "spmm_best_s": round(best_spmm, 6),
        "dense_matvec_best_s": round(best_dense_mv, 6),
        "dense_matmul_best_s": round(best_dense_mm, 6),
        "spmv_first_call_s": round(first_spmv, 6),
        "spmv_programs_compiled": spmv_compiles,
        "spmv_vs_dense": round(best_dense_mv / max(best_spmv, 1e-9), 3),
        "spmm_vs_dense": round(best_dense_mm / max(best_spmm, 1e-9), 3),
        # the CI gate's oracle: same per-row element order -> same bits
        "digest_spmv": _digest(got),
        "digest_reference": _digest(ref),
        "digest_match": bool(np.array_equal(got, ref)),
        "allclose_replicated": bool(np.allclose(
            np.asarray(sparse.spmv(A, x, out_split=None).numpy()),
            dense_h @ xh, rtol=1e-4, atol=1e-5,
        )),
    }
    return row, A


def transpose_row(ht, A, trials):
    import numpy as np

    from heat_tpu import sparse

    mono, _, best_mono = _time(lambda: sparse.transpose(A), trials)
    slab = max(1, A.capacity // 4)
    chunk, _, best_chunk = _time(
        lambda: sparse.transpose(A, slab=slab), trials
    )
    stages = max(1, -(-A.capacity // slab))
    return {
        "nnz": A.nnz,
        "capacity": A.capacity,
        "monolithic_best_s": round(best_mono, 6),
        "chunked_best_s": round(best_chunk, 6),
        "chunked_slab": slab,
        "chunked_stages": stages,
        "digest_match": bool(
            np.array_equal(
                np.asarray(mono.values), np.asarray(chunk.values)
            ) and np.array_equal(
                np.asarray(mono.indices), np.asarray(chunk.indices)
            )
        ),
    }


def spectral_row(ht, n, trials):
    import numpy as np

    rng = np.random.default_rng(0)
    pts = np.concatenate([
        rng.standard_normal((n // 2, 8)) * 0.3,
        rng.standard_normal((n - n // 2, 8)) * 0.3 + 4.0,
    ]).astype(np.float32)
    X = ht.array(pts, split=0)

    def fit(sparse_flag):
        sp = ht.cluster.Spectral(
            n_clusters=2, gamma=0.5, laplacian="eNeighbour",
            threshold=0.1, boundary="lower", n_lanczos=min(48, n),
            sparse=sparse_flag,
        )
        sp.fit(X)
        return sp.labels_.numpy()

    ls, _, best_sparse = _time(lambda: fit(True), max(1, trials - 1))
    ld, _, best_dense = _time(lambda: fit(False), max(1, trials - 1))
    agree = max(float((ls == ld).mean()), float((ls == 1 - ld).mean()))
    return {
        "n": n,
        "sparse_best_s": round(best_sparse, 6),
        "dense_best_s": round(best_dense, 6),
        "sparse_vs_dense": round(best_dense / max(best_sparse, 1e-9), 3),
        "label_agreement": agree,
    }


def main():
    parser = base_parser("heat_tpu sparse container microbenchmark")
    parser.add_argument(
        "--densities", default=",".join(str(d) for d in DENSITIES),
        help="comma-separated density sweep (default 0.001,0.01,0.1)")
    parser.add_argument(
        "--spectral-n", type=int, default=256,
        help="rows of the Spectral end-to-end row (0 skips it)")
    args = parser.parse_args()
    ht = bootstrap(args)
    import jax
    import numpy as np

    from heat_tpu import telemetry

    devs = jax.devices()
    on_chip = devs[0].platform != "cpu"
    cpu_fallback = (
        None if on_chip else
        ("forced virtual cpu mesh (--mesh)" if args.mesh
         else "default backend is cpu (no accelerator attached)")
    )
    n = int(args.n)
    densities = [float(d) for d in args.densities.split(",") if d.strip()]

    rows = []
    last_A = None
    for d in densities:
        row, A = density_row(
            ht, n, args.features, d, args.trials, args.audit
        )
        rows.append(row)
        last_A = A
        print(json.dumps({"sparse_density": row}), flush=True)

    tr = transpose_row(ht, last_A, args.trials)
    print(json.dumps({"sparse_transpose": tr}), flush=True)

    spec = None
    if args.spectral_n:
        spec = spectral_row(ht, int(args.spectral_n), args.trials)
        print(json.dumps({"sparse_spectral": spec}), flush=True)

    summary = {
        "bench": "sparse",
        "n": n,
        "features": args.features,
        "densities": rows,
        "transpose": tr,
        "spectral": spec,
        "digest_match_all": bool(all(r["digest_match"] for r in rows)),
        "on_chip": on_chip,
        "cpu_fallback": cpu_fallback,
        "devices": {"count": len(devs), "kind": devs[0].device_kind},
    }
    if telemetry.enabled():
        summary.update(telemetry.report.bench_fields())
    print(json.dumps({"sparse_compare": summary}), flush=True)


if __name__ == "__main__":
    main()
