#!/usr/bin/env python
"""Hierarchy-aware tiered collectives microbenchmark (ISSUE 15).

Measures the tiered (in-node reduce-scatter → cross-node all-reduce over
the 1/local shard → in-node all-gather) lowering of the wrapper
collectives against the flat ring, per payload size × cross-tier wire
mode, on a declared ``node×local`` topology:

* per (payload, mode): flat vs tiered wall clock (best-of-trials), the
  exact-mode digest match (bit-identity for exactly-summable payloads),
  and — the honest number on an emulated mesh — the **predicted per-tier
  bytes from the AUDITED programs**: the emitted replica-group structure
  assigns every instruction to its tier, so `total/cross(DCN)` wire
  bytes come from the compiled HLO, not the model alone (the model is
  diffed against it: any drift fails the row);
* a ZeRO row: `ZeroOptimizer` vs `DataParallelOptimizer` step wall and
  the per-device optimizer-state bytes (the watermark the memory win
  funds).

CPU cannot show the DCN bandwidth win — every virtual device shares one
memory bus — so the summary carries the standing honesty pair:
``on_chip`` and, when false, ``cpu_fallback`` naming exactly that. The
audited byte accounting is the number that transfers to real hardware;
the wall clocks are structural (dispatch + staging overhead) only.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import base_parser, bootstrap

SIZES = (1 << 16, 1 << 20, 1 << 22)
MODES = ("off", "bf16", "int8", "blockwise")


def main():
    p = base_parser("hierarchy-aware tiered collectives microbenchmark")
    p.add_argument("--topology", default="2x2",
                   help="node×local factorization (sets HEAT_TPU_TOPOLOGY)")
    p.add_argument("--sizes", type=int, nargs="*", default=list(SIZES))
    args = p.parse_args()
    os.environ["HEAT_TPU_TOPOLOGY"] = args.topology
    ht = bootstrap(args)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from heat_tpu.telemetry import collectives as model, hlo

    comm = ht.get_comm()
    pdev = comm.size
    topo = comm.topology()
    devs = jax.devices()
    on_chip = devs[0].platform != "cpu"
    cpu_fallback = (
        None if on_chip else
        "virtual CPU mesh: all tiers share one memory bus, so wall "
        "clocks are structural only; per-tier bytes are audited from "
        "the compiled programs (the transferable figure)"
    )
    spec = comm.spec(0, 2)

    def psum_prog(precision):
        def kernel(v):
            return comm.psum(v, precision=precision)

        return lambda v: jax.shard_map(
            kernel, mesh=comm.mesh, in_specs=spec, out_specs=spec
        )(v)

    def best(fn, x):
        fn(x).block_until_ready()  # compile + warm
        times = []
        for _ in range(args.trials):
            t0 = time.perf_counter()
            fn(x).block_until_ready()
            times.append(time.perf_counter() - t0)
        return min(times)

    for n in args.sizes:
        rng = np.random.default_rng(0)
        xi = jnp.asarray(
            np.round(rng.standard_normal((pdev, n // pdev)) * 8).astype(
                np.float32
            )
        )
        xs = jax.device_put(xi, comm.sharding(0, 2))
        row = {"numel": n, "topology": topo.describe(), "modes": {}}
        os.environ["HEAT_TPU_HIERARCHICAL"] = "0"
        flat_s = best(psum_prog(None), xs)
        flat_digest = np.asarray(psum_prog(None)(xs)).tobytes()
        flat_aud = hlo.audit_computation(psum_prog(None), xs)
        row["flat"] = {
            "best_s": round(flat_s, 6),
            "wire_bytes": flat_aud.total_wire(),
        }
        os.environ["HEAT_TPU_HIERARCHICAL"] = "1"
        for mode in MODES:
            prec = None if mode == "off" else mode
            fn = psum_prog(prec)
            t = best(fn, xs)
            aud = hlo.audit_computation(fn, xs)
            pred = model.hierarchical_allreduce_cost(
                n // pdev, 4, topo.node, topo.local, mode
            )
            rep = hlo.compare(aud, pred)
            cross = sum(
                c.wire_bytes for c in aud.collectives
                if [list(g) for g in c.groups] == topo.cross_groups()
            )
            audit_ok = rep.ok
            if mode == "bf16" and not rep.ok and not on_chip:
                # XLA CPU legalizes a summing bf16 all-reduce to f32
                # (the PR 9 caveat) — the predicted halving is TPU
                # truth; name the expected divergence instead of
                # reporting a bare failure
                audit_ok = "cpu-bf16-legalized-to-f32"
            entry = {
                "best_s": round(t, 6),
                "audited_wire_bytes": aud.total_wire(),
                "audited_cross_bytes": cross,
                "predicted_dcn_bytes": pred.dcn_bytes,
                "audit_ok": audit_ok,
            }
            if mode == "off":
                entry["digest_match_flat"] = (
                    np.asarray(fn(xs)).tobytes() == flat_digest
                )
            row["modes"][mode] = entry
        print(json.dumps({"hierarchy_psum": row}), flush=True)

    # -- ZeRO row -------------------------------------------------------------
    import optax

    os.environ.pop("HEAT_TPU_HIERARCHICAL", None)
    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(
        rng.standard_normal((2048, 64)).astype(np.float32)
    )}
    grads = {"w": jnp.asarray(
        rng.standard_normal((2048, 64)).astype(np.float32)
    )}
    zo = ht.optim.ZeroOptimizer(optax.adam(1e-2))
    dp = ht.optim.DataParallelOptimizer(optax.adam(1e-2))
    zs, ds = zo.init(params), dp.init(params)

    def zstep():
        return zo.step(params, zs, grads)

    def dstep():
        return dp.step(params, ds, grads)

    def best_step(fn):
        fn()
        times = []
        for _ in range(args.trials):
            t0 = time.perf_counter()
            out = fn()
            jax.tree.leaves(out[0])[0].block_until_ready()
            times.append(time.perf_counter() - t0)
        return min(times)

    zrow = {
        "zero_step_best_s": round(best_step(zstep), 6),
        "replicated_step_best_s": round(best_step(dstep), 6),
        "zero_state_bytes_per_device": zo.state_bytes_per_device(zs),
        "replicated_state_bytes": int(sum(
            np.asarray(l).nbytes for l in jax.tree.leaves(ds)
        )),
    }
    print(json.dumps({"hierarchy_zero": zrow}), flush=True)

    summary = {
        "mesh": pdev,
        "topology": topo.describe(),
        "sizes": list(args.sizes),
        "on_chip": on_chip,
        "cpu_fallback": cpu_fallback,
    }
    if ht.telemetry.enabled():
        from heat_tpu import telemetry

        summary.update(telemetry.report.bench_fields())
    print(json.dumps({"hierarchy_compare": summary}), flush=True)


if __name__ == "__main__":
    main()
