#!/usr/bin/env python
"""Full-FSDP microbenchmark (ISSUE 18, heat_tpu/nn/fsdp.py).

Three variants of the same training loop — **replicated** (the
DataParallel baseline, ``HEAT_TPU_FSDP=0``), **fsdp** (sharded
parameters, serial gathers, ``HEAT_TPU_FSDP_PREFETCH=0``) and
**fsdp_prefetch** (the default overlap window) — reporting per variant:

* step wall clock (best-of-trials) of the compiled train step;
* the per-device parameter + optimizer-state watermark
  (``addressable_shards`` accounting — the figure the run_ci.sh gate
  pins strictly below the replicated baseline);
* for the FSDP variants, the **audited** weight-gather wire bytes of
  the compiled forward, diffed leaf-by-leaf against
  ``fsdp_gather_cost`` (zero drift required), and the trajectory
  divergence from the replicated baseline after ``--steps`` steps
  (exact wire: documented-ulp; lossy wire: the quant_error_bound
  contract).

CPU cannot show the gather/compute overlap win — every virtual device
shares one memory bus — so the summary carries the standing honesty
pair: ``on_chip`` and, when false, ``cpu_fallback`` naming exactly
that. The audited bytes and the memory watermarks are the numbers that
transfer to real hardware; wall clocks are structural only. A summing
bf16 wire on the CPU backend is legalized to f32 by XLA
(``collective_prec.allreduce_wire_dtype``) — rows name that divergence
(``cpu-bf16-legalized-to-f32``) instead of reporting a bare drift.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import base_parser, bootstrap

VARIANTS = ("replicated", "fsdp", "fsdp_prefetch")


def _build(ht, variant, stages_n, width, d_in, prefetch):
    import flax.linen as fnn
    import optax

    from heat_tpu.nn.fsdp import FSDP

    os.environ["HEAT_TPU_FSDP"] = "0" if variant == "replicated" else "1"
    stages = [fnn.Dense(width) for _ in range(stages_n - 1)]
    stages.append(fnn.Dense(d_in))
    depth = prefetch if variant == "fsdp_prefetch" else 0
    return FSDP(stages, optimizer=optax.adam(1e-3), prefetch=depth)


def run_variants(ht, *, stages_n=4, width=512, d_in=256, batch=32,
                 steps=3, trials=3, prefetch=1):
    """The comparison table: one dict per variant (see module docstring)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from heat_tpu.parallel import fsdp as F
    from heat_tpu.telemetry import collectives as model, hlo

    comm = ht.get_comm()
    p = comm.size
    rng = np.random.default_rng(0)
    x = rng.standard_normal((batch, d_in)).astype(np.float32)
    y = rng.standard_normal((batch, d_in)).astype(np.float32)

    def loss_fn(out, yy):
        return jnp.mean((out - yy) ** 2)

    rows = {}
    baseline_leaves = None
    for variant in VARIANTS:
        net = _build(ht, variant, stages_n, width, d_in, prefetch)
        logical = net.init(jax.random.PRNGKey(0), x)
        params = net.shard_params(logical)
        state = net.init_opt_state(params)
        step = net.make_train_step(loss_fn)
        xb, yb = net.shard_batch(x, y)

        def one():
            return step(params, state, xb, yb)

        one()  # compile + warm
        times = []
        for _ in range(trials):
            t0 = time.perf_counter()
            out = one()
            jax.tree_util.tree_leaves(out[0])[0].block_until_ready()
            times.append(time.perf_counter() - t0)

        row = {
            "step_best_s": round(min(times), 6),
            "param_bytes_per_device": net.param_bytes_per_device(params),
            "state_bytes_per_device": F.bytes_per_device(state),
        }

        # short trajectory for the parity figure
        pp, ss = params, state
        for _ in range(steps):
            pp, ss, _ = step(pp, ss, xb, yb)
        leaves = [np.asarray(l) for l in
                  jax.tree_util.tree_leaves(net.unshard_params(pp))]
        if variant == "replicated":
            baseline_leaves = leaves
        else:
            row["max_abs_drift_vs_replicated"] = float(max(
                np.abs(a - b).max()
                for a, b in zip(leaves, baseline_leaves)
            ))
            # per-leaf audited gather bytes vs the cost model: compile
            # the forward and diff its all-gather volume against
            # fsdp_gather_cost summed over the sharded leaves
            plan = net._plan
            axis = comm.axis_name

            def fwd_kernel(ps, xx):
                return net._forward_local(
                    ps, xx, plan, net.prefetch, remat=False
                )

            p_specs = plan.unflatten(
                [P(axis) if l.sharded else P() for l in plan.leaves]
            )
            # heatlint: disable=HL001 -- fresh independent jit is the
            # audit subject: the auditor compiles THIS program's HLO,
            # separate from the cached train step it cross-checks
            fn = jax.jit(jax.shard_map(
                fwd_kernel, mesh=comm.mesh,
                in_specs=(p_specs, P(axis)), out_specs=P(axis),
            ))
            aud = hlo.audit_computation(fn, params, xb)
            topo = comm.topology()
            predicted = sum(
                model.fsdp_gather_cost(
                    l.chunk, 4, topo.node, topo.local, l.wire
                ).bytes
                for l in plan.leaves if l.sharded
            )
            audited = sum(
                c.wire_bytes for c in aud.collectives
                if c.op == "all-gather"
            )
            row["gather_wire_bytes"] = {
                "predicted": predicted,
                "audited": audited,
                "audit_ok": audited == predicted,
            }
        rows[variant] = row
    return rows


def main():
    ap = base_parser("full-FSDP sharded-parameter training microbenchmark")
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--prefetch", type=int, default=1)
    ap.add_argument("--artifact", type=str, default=None,
                    help="append result lines to this JSONL file")
    args = ap.parse_args()
    ht = bootstrap(args)

    import jax

    comm = ht.get_comm()
    on_chip = jax.devices()[0].platform != "cpu"
    rows = run_variants(
        ht, stages_n=args.stages, width=args.width, d_in=args.features,
        batch=args.batch, steps=args.steps, trials=args.trials,
        prefetch=args.prefetch,
    )
    summary = {
        "mesh": comm.size,
        "topology": comm.topology().describe(),
        "stages": args.stages,
        "width": args.width,
        "on_chip": on_chip,
        "cpu_fallback": (
            None if on_chip else
            "virtual CPU mesh: all devices share one memory bus, so "
            "step walls are structural only; the per-device memory "
            "watermarks and audited gather bytes are the transferable "
            "figures"
        ),
    }
    if ht.telemetry.enabled():
        from heat_tpu import telemetry

        summary.update(telemetry.report.bench_fields())
    lines = [{"fsdp_step": rows}, {"fsdp_compare": summary}]
    for obj in lines:
        print(json.dumps(obj), flush=True)
    if args.artifact:
        with open(args.artifact, "a") as f:
            for obj in lines:
                f.write(json.dumps(obj) + "\n")


def bench_field(stages_n=3, width=128, d_in=64, batch=16):
    """The ``fsdp`` detail row for bench.py summaries
    (docs/BENCHMARKS.md): a QUICK replicated / fsdp / fsdp+prefetch
    comparison — step wall, per-device parameter + state watermark,
    audited-vs-predicted gather wire bytes. Memory and byte figures
    transfer to real hardware; on a CPU host the walls are structural
    (the parent bench's on_chip bit governs how to read them)."""
    import heat_tpu as ht

    # heatlint: disable=HL005 -- save/restore of the caller's raw env
    # value around run_variants' per-variant pins, not a config read
    prev = os.environ.get("HEAT_TPU_FSDP")
    try:
        return run_variants(
            ht, stages_n=stages_n, width=width, d_in=d_in, batch=batch,
            steps=2, trials=2, prefetch=1,
        )
    finally:
        if prev is None:
            os.environ.pop("HEAT_TPU_FSDP", None)
        else:
            os.environ["HEAT_TPU_FSDP"] = prev


if __name__ == "__main__":
    main()
