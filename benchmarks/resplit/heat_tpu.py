#!/usr/bin/env python
"""Redistribution (resplit) scaling microbenchmark.

No reference analog (the reference's `resplit_` moves bytes through
explicit MPI Alltoallv, so its cost was always visible in profiles); here
the relayout is an XLA-emitted all-to-all and this runner is how its cost
is measured. Each fit round-trips a row-split operand through ``split=1``
and back — two all-to-alls of analytic volume ``B·(p-1)/p`` each
(telemetry/collectives.py). With ``HEAT_TPU_TELEMETRY=1`` the summary's
``telemetry.phases.resplit`` row carries the byte accounting.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import load_or_make, run


def add_args(p):
    p.add_argument("--digest", action="store_true",
                   help="print one {'result_sha256': ...} JSON line — the "
                        "bit-exactness oracle the chaos CI step compares "
                        "between a fault-free and a fault-injected run "
                        "(scripts/run_ci.sh)")


def build(ht, args):
    return load_or_make(ht, args, split=0)


def fit_factory(ht, args, data):
    def fit():
        return data.resplit(1).resplit(0)

    printed = []

    def sync(out):
        if args.digest and not printed:
            import hashlib
            import json

            import numpy as np

            h = hashlib.sha256(np.ascontiguousarray(out.numpy()).tobytes())
            print(json.dumps({"result_sha256": h.hexdigest()}), flush=True)
            printed.append(1)
        return float(out.larray[0, 0])

    return fit, sync


if __name__ == "__main__":
    run("heat_tpu resplit (redistribution) scaling benchmark",
        add_args, build, fit_factory)
