"""Shared runner plumbing for the scaling-benchmark harness.

Every per-algorithm runner (reference: per-framework scripts like
benchmarks/kmeans/heat-gpu.py:1-27) goes through here: mesh bootstrap,
workload construction (synthetic or HDF5 via ``ht.load``), timed trials,
and JSON reporting.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def base_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--n", type=int, default=100_000,
                   help="rows of the synthetic workload")
    p.add_argument("--features", type=int, default=64,
                   help="columns of the synthetic workload")
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--file", type=str, default=None,
                   help="HDF5 file to load instead of synthetic data "
                        "(reference data parity: cityscapes/SUSY/eurad)")
    p.add_argument("--dataset", type=str, default=None,
                   help="dataset name inside --file")
    p.add_argument("--mesh", type=int, default=0,
                   help="force an n-device virtual CPU mesh (0 = use the "
                        "attached platform as-is)")
    p.add_argument("--audit", action="store_true",
                   help="enable telemetry plus the HLO collective auditor: "
                        "every instrumented op lower-compiles its program "
                        "and diffs the collectives XLA actually emitted "
                        "against the analytic cost model; the summary gains "
                        "a telemetry.hlo_collectives section "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--plan", choices=("auto", "monolithic", "chunked",
                                      "alltoall"),
                   default=None,
                   help="relayout planning policy for this run (sets "
                        "HEAT_TPU_RELAYOUT_PLAN; ISSUE 6, "
                        "docs/TUNING_RUNBOOK.md §0.8). With telemetry on, "
                        "the summary gains a telemetry.relayout_plan "
                        "block of the planner's decisions")
    p.add_argument("--compile-cache", metavar="DIR",
                   # heatlint: disable=HL005 -- read before `import heat_tpu`:
                   # bootstrap() must set the cache dir env BEFORE the package
                   # (which reads it at import) loads
                   default=os.environ.get("HEAT_TPU_COMPILE_CACHE") or None,
                   help="persistent on-disk XLA compilation cache directory "
                        "(default: $HEAT_TPU_COMPILE_CACHE). Repeated sweep "
                        "processes over the same workload skip backend "
                        "compiles entirely — compile_seconds in the summary "
                        "drops to the cache-deserialization cost "
                        "(docs/TUNING_RUNBOOK.md)")
    p.add_argument("--tune-db", metavar="DIR",
                   # heatlint: disable=HL005 -- read before `import heat_tpu`:
                   # mirrors --compile-cache, the env must be set before the
                   # backend probe / package import
                   default=os.environ.get("HEAT_TPU_TUNE_DB") or None,
                   help="persistent tuning-DB directory (default: "
                        "$HEAT_TPU_TUNE_DB). Arms the autotuner "
                        "(HEAT_TPU_AUTOTUNE=1): persisted knob winners for "
                        "this mesh are adopted at dispatch time, so a "
                        "repeated bench process starts *tuned* with zero "
                        "measured trials (docs/AUTOTUNE.md)")
    return p


def bootstrap(args):
    """Apply --mesh BEFORE jax initializes a backend, then import heat_tpu."""
    if getattr(args, "plan", None):
        os.environ["HEAT_TPU_RELAYOUT_PLAN"] = args.plan
    if getattr(args, "compile_cache", None):
        # FIRST, before anything imports heat_tpu (force_virtual_cpu_mesh
        # below already does): program_cache reads the env at import and
        # wires jax's persistent compilation cache from it
        os.environ["HEAT_TPU_COMPILE_CACHE"] = args.compile_cache
    if getattr(args, "tune_db", None):
        # same ordering contract as the compile cache; --tune-db arms
        # the autotuner UNLESS the environment already pins
        # HEAT_TPU_AUTOTUNE (an explicit =0 must keep a baseline run
        # untuned even when HEAT_TPU_TUNE_DB is exported globally)
        os.environ["HEAT_TPU_TUNE_DB"] = args.tune_db
        os.environ.setdefault("HEAT_TPU_AUTOTUNE", "1")
    if args.mesh:
        # one canonical copy of the XLA_FLAGS/JAX_PLATFORMS dance, shared
        # with the telemetry audit CLI (backend init is lazy, so importing
        # the package to reach the helper is safe)
        from heat_tpu.utils.backend_probe import force_virtual_cpu_mesh

        force_virtual_cpu_mesh(args.mesh)
    import heat_tpu as ht

    if getattr(args, "audit", False):
        # ground-truth collective accounting rides on the telemetry event
        # stream, so --audit implies recording
        if not ht.telemetry.enabled():
            ht.telemetry.enable()
        ht.telemetry.hlo.enable_audit()
    return ht


def load_or_make(ht, args, *, dtype=None, split=0):
    """The benchmark operand: ``ht.load`` when --file is given (per-slab
    range reads on multi-host, io.py), synthetic ``randn`` otherwise."""
    dtype = dtype or ht.float32
    if args.file:
        if not args.dataset:
            raise SystemExit("--file requires --dataset (the HDF5 dataset "
                             "name inside the file)")
        data = ht.load(args.file, dataset=args.dataset, split=split)
        return data.astype(dtype) if data.dtype != dtype else data
    return ht.random.randn(args.n, args.features, dtype=dtype, split=split)


def timed_trials(args, fit, sync):
    """Run ``fit`` ``args.trials`` times; print one JSON line per trial
    (the reference prints per-trial wall-clock, heat-gpu.py:22-27) and a
    summary with the best time. With ``HEAT_TPU_TELEMETRY=1`` the summary
    gains a ``telemetry`` block: per-phase compile/execute/bytes-moved
    columns plus the memory high-water mark; with ``--audit`` also an
    ``hlo_collectives`` section of ground-truth emitted collective
    counts/bytes and the drift verdict (docs/OBSERVABILITY.md)."""
    times = []
    for trial in range(args.trials):
        t0 = time.perf_counter()
        out = fit()
        sync(out)  # device-queue barrier: timing must include the work
        dt = time.perf_counter() - t0
        times.append(dt)
        print(json.dumps({"trial": trial, "seconds": round(dt, 4)}),
              flush=True)
    summary = {
        "best_seconds": round(min(times), 4),
        "mean_seconds": round(sum(times) / len(times), 4),
        "trials": args.trials,
        "devices": _device_info(),
    }
    from heat_tpu import autotune, telemetry

    if telemetry.enabled():
        telemetry.memory.watermark("post_trials")
        summary.update(telemetry.report.bench_fields())
    if autotune.enabled():
        # what the tuner did for THIS run: trials, DB hits, adopted
        # config per site (docs/AUTOTUNE.md; --tune-db arms this)
        summary["autotune"] = autotune.bench_field()
    print(json.dumps(summary), flush=True)
    return summary


def _device_info():
    import jax

    d = jax.devices()
    return {"count": len(d), "kind": d[0].device_kind}


def run(description, add_args, build, fit_factory):
    """Standard runner main: parse → bootstrap → build workload →
    timed trials. ``add_args(parser)`` adds algorithm flags;
    ``build(ht, args)`` returns the operand(s); ``fit_factory(ht, args,
    operands)`` returns (fit, sync)."""
    parser = base_parser(description)
    add_args(parser)
    args = parser.parse_args()
    ht = bootstrap(args)
    operands = build(ht, args)
    fit, sync = fit_factory(ht, args, operands)
    # The first call compiles AND executes; the two must not be blended
    # into one "compile_seconds" (the old behavior — advisor round-5
    # finding). A CompileWatcher accumulates the XLA trace/lower/backend
    # compile durations that fire during the call — the same stages an AOT
    # `jit(f).lower(...).compile()` runs (`fit` itself mixes host logic
    # with device ops, so it cannot be lowered whole) — giving the honest
    # split: compile_seconds (pipeline time) vs first_call_seconds (wall).
    with ht.telemetry.CompileWatcher() as cw:
        t0 = time.perf_counter()
        sync(fit())
        first_call = time.perf_counter() - t0
    print(json.dumps({
        "compile_seconds": round(cw.seconds, 4),
        "first_call_seconds": round(first_call, 4),
    }), flush=True)
    if ht.telemetry.enabled():
        # drop ONLY the warmup call's span events: their wall-clock
        # contains compile time, and leaving them in would re-blend
        # compile into the per-phase execute_seconds the summary reports.
        # The compile and collective_trace events must survive: for
        # jit-cached fits they fire only while the warmup traces/compiles,
        # so a full clear() would permanently empty the summary's
        # telemetry.compile_seconds / traced_collectives fields. (Ops that
        # build a fresh traced closure per call — the shard_map ring
        # kernels — re-trace on every trial, so those accumulated fields
        # scale with --trials; the top-level compile_seconds printed above
        # is the warmup-window number either way.) The JSONL sink keeps
        # the full stream (append-only) regardless.
        ht.telemetry.get_registry().clear(kinds=("span",))
    timed_trials(args, fit, sync)


if __name__ == "__main__":
    print("import me from a per-algorithm runner", file=sys.stderr)
    sys.exit(2)
