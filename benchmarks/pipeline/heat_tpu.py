#!/usr/bin/env python
"""MPMD pipeline-parallelism microbenchmark (ISSUE 19,
``heat_tpu/nn/pipeline.py``).

Two variants of the same training loop — **gpipe** and **1f1b** — over a
stage-per-node-group mapping, reporting per schedule:

* step wall clock (best-of-trials) of the compiled ``pipeline.step``
  program;
* the **measured** bubble accounting from the per-tick telemetry spans
  (total bubble cells, steady-window bubble ticks, bubble fraction),
  reconciled exactly against the analytic ``ScheduleTable`` — the 1F1B
  claim is that steady-window idles drop (total bubble cells are
  IDENTICAL across schedules at one ``(S, M)``);
* the ``memory_analysis`` activation watermark (temp bytes) of the
  compiled step — GPipe stashes all ``M`` in-flight microbatch inputs,
  1F1B caps the stash at ``min(S, M)``;
* the audited inter-stage hop wire bytes diffed against
  ``pipeline_hop_cost`` (zero drift);
* a cross-schedule digest: the parameter bytes after ``--steps`` steps
  must be BIT-identical between the schedules (pure scheduling).

CPU cannot show the bubble win as wall clock — every virtual device
shares one memory bus, so the schedules serialize identically — hence
the standing honesty pair: ``on_chip`` and, when false,
``cpu_fallback`` naming exactly that. The measured bubble ticks, the
watermarks, and the audited hop bytes are the figures that transfer to
real hardware; wall clocks are structural only.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import base_parser, bootstrap

VARIANTS = ("gpipe", "1f1b")


def run_variants(ht, *, n_layers=4, d_in=64, batch=32, n_stages=None,
                 microbatches=8, steps=3, trials=3):
    """The comparison table: one dict per schedule (see module docstring)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from heat_tpu import telemetry as tm
    from heat_tpu.parallel import pipeline as pl
    from heat_tpu.parallel import schedule as sch
    from heat_tpu.telemetry import collectives as model, hlo

    comm = ht.get_comm()
    p = comm.size
    mapping = sch.plan_stages(p, n_stages)
    S = mapping.n_stages
    M = min(microbatches, batch)
    while batch % M:
        M -= 1
    mb = batch // M
    L = n_layers if n_layers % S == 0 else S * max(1, n_layers // S)
    opt = optax.adam(1e-3)

    rng = np.random.default_rng(0)
    layers = [
        {"w": jnp.asarray(rng.standard_normal((d_in, d_in)) * 0.3,
                          jnp.float32),
         "b": jnp.asarray(rng.standard_normal((d_in,)) * 0.1, jnp.float32)}
        for _ in range(L)
    ]
    x = jnp.asarray(rng.standard_normal((batch, d_in)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((batch, d_in)), jnp.float32)
    mx = x.reshape(M, mb, d_in)
    my = y.reshape(M, mb, d_in)

    def loss_fn(out, yy):
        return jnp.mean((out - yy) ** 2)

    layout = pl.plan_pipeline(layers, mapping)
    topo = comm.topology()
    hop = model.pipeline_hop_cost(
        mb, d_in, 4, p, stride=mapping.local,
        local=topo.local if topo.nontrivial else None,
    )

    rows = {}
    digests = {}
    for name in VARIANTS:
        table = sch.build_schedule(S, M, name)

        def layer_fn(w, h, _v=name):  # per-variant identity: fresh trace
            return jnp.tanh(h @ w["w"] + w["b"])

        rows_p = pl.shard_pipeline_params(layers, layout, comm)
        st = opt.init(rows_p)

        # trace under telemetry so the per-tick spans are emitted, then
        # reconcile the measured bubble accounting with the table
        sink = tempfile.mktemp(suffix=".jsonl")
        reg = tm.enable(sink)
        n0 = len(reg.events)
        try:
            step = pl.pipeline_step_program(
                layer_fn, layout, mapping, table, comm=comm,
                loss_fn=loss_fn, optimizer=opt)
            rows_p, st, loss = step(rows_p, st, mx, my)
            events = list(reg.events)[n0:]
        finally:
            tm.disable()
            if os.path.exists(sink):
                os.unlink(sink)
        ticks = [e for e in events if e.get("name") == "pipeline_tick"]
        steady = sum(e["bubble"] for e in ticks if e["phase"] == "steady")
        total = sum(e["bubble"] for e in ticks)

        def one():
            return step(rows_p, st, mx, my)

        one()  # warm the steady input layouts
        times = []
        for _ in range(trials):
            t0 = time.perf_counter()
            out = one()
            jax.tree_util.tree_leaves(out[0])[0].block_until_ready()
            times.append(time.perf_counter() - t0)

        # short trajectory for the cross-schedule digest
        pp, ss = rows_p, st
        for _ in range(steps):
            pp, ss, _ = step(pp, ss, mx, my)
        digests[name] = b"".join(
            np.asarray(l).tobytes()
            for layer in pl.unshard_pipeline_params(pp, layout)
            for l in jax.tree_util.tree_leaves(layer)
        )

        # heatlint: disable=HL001 -- one-shot lowering for the
        # memory_analysis watermark, never executed; the training steps
        # above all go through the cached pipeline.step program
        ma = jax.jit(step).lower(rows_p, ss, mx, my).compile() \
            .memory_analysis()
        watermark = int(getattr(ma, "temp_size_in_bytes", 0) or 0)

        audit = hlo.audit_computation(step, rows_p, ss, mx, my)
        audited = sum(
            c.wire_bytes for c in audit.collectives
            if c.op == "collective-permute"
        )
        predicted = 2 * (table.n_ticks - 1) * hop.bytes

        rows[name] = {
            "step_best_s": round(min(times), 6),
            "ticks": table.n_ticks,
            "bubble": {
                "measured_cells": total,
                "measured_steady_ticks": steady,
                "analytic_cells": table.bubble_cells(),
                "analytic_steady_ticks": table.steady_bubble_ticks(),
                "fraction": round(table.bubble_fraction(), 6),
                "reconciled": (total == table.bubble_cells()
                               and steady == table.steady_bubble_ticks()),
            },
            "activation_watermark_bytes": watermark,
            "stash_depth": table.stash_depth(),
            "hop_wire_bytes": {
                "predicted": predicted,
                "audited": audited,
                "dcn_per_hop": hop.dcn_bytes,
                "audit_ok": audited == predicted,
            },
        }
    rows["cross_schedule_bit_identical"] = (
        digests["gpipe"] == digests["1f1b"]
    )
    rows["mapping"] = mapping.describe()
    rows["microbatches"] = M
    return rows


def main():
    ap = base_parser("MPMD pipeline 1F1B/GPipe training microbenchmark")
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--stages", type=int, default=0,
                    help="stage count (0 = plan_stages auto)")
    ap.add_argument("--microbatches", type=int, default=8)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--artifact", type=str, default=None,
                    help="append result lines to this JSONL file")
    args = ap.parse_args()
    ht = bootstrap(args)

    import jax

    comm = ht.get_comm()
    on_chip = jax.devices()[0].platform != "cpu"
    rows = run_variants(
        ht, n_layers=args.layers, d_in=args.features, batch=args.batch,
        n_stages=args.stages or None, microbatches=args.microbatches,
        steps=args.steps, trials=args.trials,
    )
    summary = {
        "mesh": comm.size,
        "topology": comm.topology().describe(),
        "layers": args.layers,
        "on_chip": on_chip,
        "cpu_fallback": (
            None if on_chip else
            "virtual CPU mesh: all devices share one memory bus, so the "
            "schedules serialize identically and step walls are "
            "structural only; the measured bubble ticks, activation "
            "watermarks, and audited hop bytes are the transferable "
            "figures"
        ),
    }
    if ht.telemetry.enabled():
        from heat_tpu import telemetry

        summary.update(telemetry.report.bench_fields())
    lines = [{"pipeline_step": rows}, {"pipeline_compare": summary}]
    for obj in lines:
        print(json.dumps(obj), flush=True)
    if args.artifact:
        with open(args.artifact, "a") as f:
            for obj in lines:
                f.write(json.dumps(obj) + "\n")


def bench_field(n_layers=4, d_in=32, batch=16):
    """The ``pipeline`` detail row for bench.py summaries
    (docs/BENCHMARKS.md): a QUICK gpipe / 1f1b comparison — step wall,
    measured-vs-analytic bubble accounting, activation watermark,
    audited hop bytes, cross-schedule digest. The watermark and byte
    figures transfer to real hardware; on a CPU host the walls are
    structural (the parent bench's on_chip bit governs how to read
    them)."""
    import heat_tpu as ht

    return run_variants(
        ht, n_layers=n_layers, d_in=d_in, batch=batch,
        microbatches=4, steps=2, trials=2,
    )


if __name__ == "__main__":
    main()
