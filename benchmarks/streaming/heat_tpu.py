#!/usr/bin/env python
"""Streaming benchmark: out-of-core fit under a pinned memory budget,
then a versioned rolling replica update under open-loop load (ISSUE 16).

No reference analog (the reference framework streams through torch
DataLoaders; it has no bounded-memory fit-while-serve story). Phases,
each one JSONL line:

* ``{"stream_fit": ...}`` — write the synthetic workload to row-major
  files, pin ``HEAT_TPU_HBM_BUDGET``, and drive
  :class:`heat_tpu.streaming.ChunkStream` →
  :class:`~heat_tpu.streaming.StreamingMoments`. Reports rows/s
  ingested, the chunk-bytes watermark vs the load-all bytes (the
  out-of-core claim: ``watermark_below_load_all`` must be true when the
  budget is pinned below the file set), digest parity of the streamed
  moments against the in-memory full-pass reference, and the
  steady-stream compile ledger (``site_stats("streaming.")`` — one miss
  for the steady chunk shape, zero for every later chunk);
* ``{"rolling": ...}`` — the fit-while-serve headline: a 2-replica
  pool serves version 1 while checkpoints v2 and v3 are rolled through
  it replica-by-replica (:func:`heat_tpu.streaming.rolling_update`)
  under the SAME open-loop Poisson load as an undisturbed steady
  window. Reports p99 during the roll vs steady state, zero failed
  requests (the router's ``retry_in_flight`` at-least-once re-dispatch
  over idempotent queries), every surviving replica on the final
  version, and each replica's ``steady_backend_compiles`` (must be 0 —
  replacements warm from the shared compile cache);
* final summary — the ``on_chip`` + ``cpu_fallback`` honesty pair. The
  stream-fit phase runs on the attached platform (the pallas Welford
  kernel on TPU, masked XLA on CPU) and reports which one ran; replica
  processes ALWAYS run virtual CPU meshes (an attached accelerator
  cannot be shared across processes), so the rolling phase is a CPU
  number by construction and says so in-band.

``--artifact PATH`` appends the emitted lines (the committed
``artifacts/bench_streaming_r16.jsonl``). The CI streaming gate
(scripts/run_ci.sh) runs both phases small and asserts the
watermark/digest/zero-compile/zero-failure verdicts.
"""

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

from benchmarks._harness import base_parser, bootstrap

ROLL_CPU_REASON = (
    "replica processes run on virtual cpu meshes (an attached accelerator "
    "cannot be shared across replica processes)"
)


def add_args(p):
    p.add_argument("--files", type=int, default=2,
                   help="number of files the workload is sharded into")
    p.add_argument("--hbm-budget", default="64M",
                   help="HEAT_TPU_HBM_BUDGET pinned for the stream-fit "
                        "phase (chunks are sized from a quarter of it; "
                        "pick it below the file-set bytes to exercise "
                        "the out-of-core path). 'off' = unpinned")
    p.add_argument("--hdf5", action="store_true",
                   help="write HDF5 files instead of npy (needs h5py)")
    p.add_argument("--replicas", type=int, default=2,
                   help="replica count of the rolling-update pool")
    p.add_argument("--replica-mesh", type=int, default=4,
                   help="virtual CPU mesh size of every replica process")
    p.add_argument("--versions", type=int, default=3,
                   help="total endpoint versions rolled through the pool "
                        "(v1 serves at start; v2..vN roll in live)")
    p.add_argument("--requests", type=int, default=400,
                   help="requests per serving load window")
    p.add_argument("--rate", type=float, default=120.0,
                   help="offered Poisson rate, requests/second (the SAME "
                        "for the steady and the under-roll window)")
    p.add_argument("--streams", type=int, default=4,
                   help="concurrent loadgen submitter threads")
    p.add_argument("--serve-features", type=int, default=16,
                   help="feature width of the served cdist endpoint")
    p.add_argument("--skip-rolling", action="store_true",
                   help="stream-fit phase only (no subprocess pool)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workdir", default=None,
                   help="data/checkpoint/shared-cache directory (default: "
                        "a fresh temp dir)")
    p.add_argument("--artifact", default=None,
                   help="append the emitted JSONL lines to this file")


def _emit(lines, obj):
    print(json.dumps(obj), flush=True)
    lines.append(obj)


def _write_files(args, workdir):
    """Shard the synthetic workload into row-major files; return
    (paths, dataset, the full array kept host-side for the in-memory
    reference)."""
    rng = np.random.default_rng(args.seed)
    full = rng.standard_normal((args.n, args.features)).astype(np.float32)
    per = -(-args.n // args.files)
    paths, dataset = [], None
    for i in range(args.files):
        block = full[i * per:(i + 1) * per]
        if not len(block):
            break
        if args.hdf5:
            import h5py

            dataset = "data"
            p = os.path.join(workdir, f"shard{i}.h5")
            with h5py.File(p, "w") as f:
                f.create_dataset(dataset, data=block)
        else:
            p = os.path.join(workdir, f"shard{i}.npy")
            np.save(p, block)
        paths.append(p)
    return paths, dataset, full


def _stream_fit(ht, args, lines, workdir):
    from heat_tpu import streaming, telemetry
    from heat_tpu.core import program_cache

    paths, dataset, full = _write_files(args, workdir)
    # heatlint: disable=HL005 -- deliberate benchmark-phase pin: the
    # bounded-memory claim is only a claim under a declared budget
    if args.hbm_budget and args.hbm_budget != "off":
        os.environ["HEAT_TPU_HBM_BUDGET"] = args.hbm_budget

    cs = streaming.ChunkStream(paths, dataset)
    sm = streaming.StreamingMoments()
    before = program_cache.site_stats("streaming.moments")
    t0 = time.perf_counter()
    for chunk in cs:
        sm.partial_fit(chunk)
    wall = time.perf_counter() - t0
    after = program_cache.site_stats("streaming.moments")

    # in-memory full-pass reference (host f64 — the order-independent
    # ground truth the streamed carry must agree with)
    ref_mean = full.astype(np.float64).mean(axis=0)
    ref_var = full.astype(np.float64).var(axis=0)
    mean_err = float(np.abs(sm.mean - ref_mean).max())
    var_err = float(np.abs(sm.var() - ref_var).max())

    watermark = None
    if telemetry.enabled():
        watermark = telemetry.get_registry().watermarks.get(
            "streaming.chunk_bytes"
        )
    row = {
        "rows": cs.rows_read,
        "files": len(paths),
        "format": "hdf5" if args.hdf5 else "npy",
        "chunks": cs.chunks_read,
        "chunk_rows": cs.chunk_rows,
        "seconds": round(wall, 4),
        "rows_per_s": round(cs.rows_read / wall, 1) if wall > 0 else None,
        "hbm_budget": args.hbm_budget,
        "chunk_bytes": cs.chunk_bytes(),
        "chunk_bytes_watermark": int(watermark) if watermark else None,
        "load_all_bytes": cs.load_all_bytes(),
        "watermark_below_load_all":
            cs.chunk_bytes() < cs.load_all_bytes(),
        "digest": {
            "mean_max_abs_err": mean_err,
            "var_max_abs_err": var_err,
            "match": bool(mean_err < 1e-4 and var_err < 1e-4),
        },
        "compiles": {
            "misses": after["misses"] - before["misses"],
            "hits": after["hits"] - before["hits"],
            # one program per distinct chunk shape (a ragged final
            # chunk is one more honest miss); everything else re-enters
            "steady_zero_compile":
                (after["misses"] - before["misses"])
                <= min(2, cs.chunks_read),
        },
    }
    _emit(lines, {"stream_fit": row})
    return row


def _versioned_checkpoints(ht, args, workdir):
    """v1..vN checkpoints of the same cdist endpoint with scaled
    parameters — same avals, so every publish/roll is a zero-compile
    program-argument swap."""
    rng = np.random.default_rng(args.seed + 3)
    y1 = rng.standard_normal(
        (128, args.serve_features)
    ).astype(np.float32)
    ckpts = []
    srv = ht.serve.Server()
    ep = ht.serve.cdist_query(y1)
    srv.register("cdist", ep)
    for v in range(1, args.versions + 1):
        if v > 1:
            srv.publish(
                "cdist", ep.with_params([y1 * float(v)], version=v),
                warm=False,
            )
        ck = os.path.join(workdir, f"v{v}.ckpt")
        srv.save(ck)
        ckpts.append(ck)
    srv.close()
    return ckpts


def _replica_net(pool):
    out = []
    for h in pool.replicas:
        if h.state != "up" or not h.alive():
            continue
        try:
            st = pool.stats(h.index)
        except Exception as e:  # noqa: BLE001 — a dead replica is data
            out.append({"replica": h.index, "error": repr(e)})
            continue
        out.append({
            "replica": h.index,
            "steady_backend_compiles":
                st.get("net", {}).get("steady_backend_compiles"),
            "versions": st.get("versions"),
        })
    return out


def _rolling(ht, args, lines, workdir):
    from benchmarks.serving import loadgen
    from heat_tpu import streaming
    from heat_tpu.serve.net import ReplicaPool, Router

    ckpts = _versioned_checkpoints(ht, args, workdir)
    env = {
        "HEAT_TPU_COMPILE_CACHE": os.path.join(workdir, "xla_cache"),
        "HEAT_TPU_SERVE_MAX_BATCH": "4",
        "HEAT_TPU_SERVE_QUEUE_MAX": "64",
    }
    reqs = loadgen.make_requests(
        {"cdist": args.serve_features}, args.requests, args.seed,
        max_rows=1,
    )
    pool = ReplicaPool(
        ckpts[0], args.replicas, mesh=args.replica_mesh, env=env,
        log_dir=os.path.join(workdir, "logs"),
    )
    row = {"versions": len(ckpts), "replicas": args.replicas}
    try:
        t0 = time.perf_counter()
        pool.start()
        row["pool_ready_seconds"] = round(time.perf_counter() - t0, 3)
        # retry_in_flight: queries are idempotent and a draining replica
        # may reset accepted connections — the zero-failure roll contract
        router = Router(pool, retries=3, workers=8, poll_ms=100.0,
                        retry_in_flight=True)
        try:
            steady = loadgen.run_open_loop(
                router, reqs, args.rate, seed=args.seed,
                streams=args.streams,
            )
            row["steady"] = {
                "achieved_qps": steady["achieved_qps"],
                "completed": steady["completed"],
                "failed": steady["failed"],
                "p50_s": steady["latency"].get("p50_s"),
                "p99_s": steady["latency"].get("p99_s"),
            }

            # the under-roll window: the SAME load runs while v2..vN
            # roll through the pool replica-by-replica
            result = {}

            def load():
                result["report"] = loadgen.run_open_loop(
                    router, reqs, args.rate, seed=args.seed + 1,
                    streams=args.streams,
                )

            t = threading.Thread(target=load, daemon=True)
            t.start()
            rolls = []
            for ck in ckpts[1:]:
                rolls.append(streaming.rolling_update(pool, router, ck))
            t.join()
            under = result["report"]
            net = _replica_net(pool)
            row["rolls"] = [
                {"seconds": r["seconds"], "steps": len(r["steps"])}
                for r in rolls
            ]
            row["under_roll"] = {
                "achieved_qps": under["achieved_qps"],
                "completed": under["completed"],
                "failed": under["failed"],
                "p50_s": under["latency"].get("p50_s"),
                "p99_s": under["latency"].get("p99_s"),
            }
            row["p99_roll_over_steady"] = (
                round(row["under_roll"]["p99_s"] / row["steady"]["p99_s"], 2)
                if row["steady"].get("p99_s") else None
            )
            row["zero_failed_requests"] = (
                steady["failed"] == 0 and under["failed"] == 0
            )
            row["per_replica"] = net
            row["all_on_final_version"] = all(
                (r.get("versions") or {}).get("cdist") == len(ckpts)
                for r in net
            )
            row["steady_backend_compiles_ok"] = all(
                r.get("steady_backend_compiles") == 0 for r in net
            )
        finally:
            router.close()
    finally:
        pool.close()
    _emit(lines, {"rolling": row})
    return row


def main():
    p = base_parser("heat_tpu streaming benchmark (out-of-core fit + "
                    "versioned rolling replica update)")
    add_args(p)
    args = p.parse_args()
    ht = bootstrap(args)
    import jax

    from heat_tpu import telemetry

    devs = jax.devices()
    on_chip = devs[0].platform != "cpu"
    lines = []
    workdir = args.workdir or tempfile.mkdtemp(prefix="heat_tpu_stream_")
    os.makedirs(workdir, exist_ok=True)

    stream_row = _stream_fit(ht, args, lines, workdir)
    rolling_row = None
    if not args.skip_rolling:
        rolling_row = _rolling(ht, args, lines, workdir)

    summary = {
        "bench": "streaming",
        "rows": args.n,
        "features": args.features,
        "stream_fit": {
            "rows_per_s": stream_row.get("rows_per_s"),
            "watermark_below_load_all":
                stream_row.get("watermark_below_load_all"),
            "digest_match": stream_row.get("digest", {}).get("match"),
            "steady_zero_compile":
                stream_row.get("compiles", {}).get("steady_zero_compile"),
            # the stream-fit phase runs on the attached platform
            "on_chip": on_chip,
            **({} if on_chip else {
                "cpu_fallback":
                    "default backend is cpu (no accelerator attached)",
            }),
        },
        "rolling": None if rolling_row is None else {
            "p99_steady_s": rolling_row.get("steady", {}).get("p99_s"),
            "p99_under_roll_s":
                rolling_row.get("under_roll", {}).get("p99_s"),
            "p99_roll_over_steady":
                rolling_row.get("p99_roll_over_steady"),
            "zero_failed_requests":
                rolling_row.get("zero_failed_requests"),
            "all_on_final_version":
                rolling_row.get("all_on_final_version"),
            "steady_backend_compiles_ok":
                rolling_row.get("steady_backend_compiles_ok"),
            # replicas are subprocesses: always a CPU number
            "on_chip": False,
            "cpu_fallback": ROLL_CPU_REASON,
        },
        "on_chip": on_chip and rolling_row is None,
        "cpu_fallback": (
            None if on_chip and rolling_row is None
            else ROLL_CPU_REASON if rolling_row is not None
            else "default backend is cpu (no accelerator attached)"
        ),
        "devices": {"count": len(devs), "kind": devs[0].device_kind},
    }
    if telemetry.enabled():
        summary.update(telemetry.report.bench_fields())
    _emit(lines, summary)

    if args.artifact:
        with open(args.artifact, "a") as f:
            for obj in lines:
                f.write(json.dumps(obj) + "\n")


if __name__ == "__main__":
    main()
