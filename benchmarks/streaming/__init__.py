"""Streaming benchmarks (ISSUE 16): out-of-core ingestion throughput +
bounded-memory watermark, and the versioned rolling-update serving
p99-under-roll vs steady-state comparison."""
