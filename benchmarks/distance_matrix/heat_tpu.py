#!/usr/bin/env python
"""Distance-matrix scaling benchmark (reference:
benchmarks/distance_matrix/config.json — ht.spatial.cdist on SUSY h5,
split=0). ``--ring`` uses the ppermute ring kernel (the reference's
ring-MPI design, distance.py:209); the default quadratic-expansion GEMM
form dispatches the fused Pallas epilogue kernel on TPU."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import load_or_make, run


def add_args(p):
    p.add_argument("--ring", action="store_true",
                   help="ppermute ring schedule instead of the GEMM form")


def build(ht, args):
    return load_or_make(ht, args, split=0)


def fit_factory(ht, args, data):
    def fit():
        if args.ring:
            return ht.spatial.cdist(data, data, ring=True)
        return ht.spatial.cdist(data, data, quadratic_expansion=True)

    def sync(d):
        return float(d.larray[0, 0])

    return fit, sync


if __name__ == "__main__":
    run("heat_tpu cdist scaling benchmark", add_args, build, fit_factory)
