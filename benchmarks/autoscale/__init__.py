"""Autoscaling control-plane benchmark package (ISSUE 20).

* :mod:`.profiles` — deterministic offered-load shapes (step / spike /
  diurnal) and the seeded inhomogeneous-Poisson arrival schedules built
  from them (thinning — unit-testable without running any server);
* :mod:`.run` — the loadgen runner behind the committed
  ``artifacts/bench_autoscale_r20.jsonl``: controller-vs-static
  replica-seconds pricing, the two-tenant weighted-fair overload phase,
  hedged-retry tail trimming under injected stragglers, and the chaos
  SIGKILL-replacement phase the CI autoscale gate asserts.
"""
