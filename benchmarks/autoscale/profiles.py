"""Offered-load profiles for the autoscale benchmark (ISSUE 20).

A profile is a *shape*: a function of normalized time ``u in [0, 1)``
returning the rate multiplier in ``(0, 1]`` applied to the peak offered
rate. :func:`schedule` turns a shape into concrete arrival offsets of an
inhomogeneous Poisson process via thinning (candidates at the peak rate,
each kept with probability ``shape(u)``), from a seeded RNG — the same
determinism contract as ``benchmarks.serving.loadgen.poisson_schedule``:
same seed → same schedule, no server required to test the generator.

The three shipped shapes exercise the three controller behaviors the
artifact prices:

* ``step``   — low / 3× sustained high / low thirds: sustained-backlog
  scale-up, then the drain-idle scale-down;
* ``spike``  — a short 10%-of-duration burst: cooldown hysteresis (one
  decisive scale-up, no flapping on the edges);
* ``diurnal`` — a raised-cosine day: gradual ramp both ways, capacity
  tracking demand instead of the static-max worst case.
"""

from __future__ import annotations

from typing import Callable, Union

import numpy as np

__all__ = ["PROFILES", "rate_at", "schedule"]


def _step(u: float) -> float:
    return 1.0 if 1.0 / 3.0 <= u < 2.0 / 3.0 else 0.15


def _spike(u: float) -> float:
    return 1.0 if 0.45 <= u < 0.55 else 0.12


def _diurnal(u: float) -> float:
    # squared raised cosine: a quiet "night" at the edges (8% of peak),
    # peak mid-"day", never zero — the squared term keeps the trough
    # wide the way real diurnal traffic is, instead of spending most of
    # the day near peak
    return 0.08 + 0.92 * float(np.sin(np.pi * u)) ** 4


PROFILES = {"step": _step, "spike": _spike, "diurnal": _diurnal}


def rate_at(
    profile: Union[str, Callable[[float], float]],
    t: float,
    duration_s: float,
    peak_rate: float,
) -> float:
    """Instantaneous offered rate (requests/second) at time ``t``."""
    shape = PROFILES[profile] if isinstance(profile, str) else profile
    u = min(max(t / float(duration_s), 0.0), 1.0 - 1e-12)
    return float(peak_rate) * float(shape(u))


def schedule(
    profile: Union[str, Callable[[float], float]],
    duration_s: float,
    peak_rate: float,
    seed: int = 0,
) -> np.ndarray:
    """Arrival offsets (seconds from start, strictly increasing) of an
    inhomogeneous Poisson process whose rate is
    ``peak_rate * shape(t / duration_s)``, via thinning."""
    shape = PROFILES[profile] if isinstance(profile, str) else profile
    if duration_s <= 0 or peak_rate <= 0:
        raise ValueError("need positive duration and peak rate")
    rng = np.random.default_rng(seed)
    out = []
    t = 0.0
    while True:
        t += rng.exponential(1.0 / peak_rate)
        if t >= duration_s:
            break
        keep = shape(t / duration_s)
        if not 0.0 <= keep <= 1.0:
            raise ValueError(f"shape({t / duration_s:.3f}) = {keep} "
                             "outside [0, 1]")
        if rng.random() < keep:
            out.append(t)
    return np.asarray(out, dtype=np.float64)
