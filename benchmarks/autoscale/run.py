#!/usr/bin/env python
"""Autoscaling control-plane benchmark: SLO-driven replica scaling,
priority-aware admission, and hedged tail-latency retries (ISSUE 20).

No reference analog (the reference framework's MPI world is static).
The runner fits/checkpoints two endpoints ONCE (``kmeans`` — the
latency-sensitive tenant — and ``cdist`` — the bulk tenant), then every
phase spawns replica processes born from that checkpoint, warming from
the shared persistent compile cache. Phases, each one JSONL line:

* ``{"autoscale_row": ...}`` per offered-load profile (step / spike /
  diurnal inhomogeneous-Poisson schedules from
  :mod:`benchmarks.autoscale.profiles`) — the headline: an
  :class:`~heat_tpu.serve.net.AutoscaleController` holds the declared
  p99 SLO while **replica-seconds** (the controller's live-footprint
  integral) price at least 2x better than static max provisioning
  (``max_replicas`` running the whole wall). Each row records the
  scale-up/scale-down trail, the drain-down-to-min verdict, and every
  replica's ``steady_backend_compiles`` (must be 0 — scale-ups
  warm-start from the shared cache, never retrace);
* ``{"two_tenant": ...}`` — overload fairness: bulk ``cdist`` offered
  well past capacity next to a modest latency ``kmeans`` stream, under
  weighted-fair admission (``latency=8, bulk=1``) and a bounded router
  queue. The verdicts: the latency tenant's p99 holds its SLO AND the
  bulk tenant still gets at least its weighted-fair share of routed
  requests (priority is isolation, not starvation);
* ``{"hedge": ...}`` — tail trimming: one straggler replica (injected
  latency faults via ``HEAT_TPU_FAULTS``) next to a clean one, the same
  schedule driven with hedging off then on. The verdicts: hedged p99
  beats the baseline, and the hedge fraction stays at or under the
  configured hard cap (first-wins semantics are pinned by unit test);
* ``{"chaos": ...}`` — self-healing: a replica SIGKILLed mid-load
  (raw ``proc.kill()``, so only the controller's liveness probe can
  notice) is replaced within a bounded number of ticks with zero
  failed requests (``retry_in_flight=True``) and zero steady-state
  compiles on the respawned replica;
* final summary — ``on_chip`` + ``cpu_fallback`` honesty (replica
  processes always run virtual CPU meshes).

``--artifact PATH`` appends the emitted lines (the committed
``artifacts/bench_autoscale_r20.jsonl``). The CI autoscale gate
(scripts/run_ci.sh) runs ``--profiles step --chaos`` small and asserts
the scale-up/drain-down/zero-failed/bounded-replacement verdicts.
"""

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

from benchmarks._harness import base_parser, bootstrap
from benchmarks.autoscale import profiles
from benchmarks.serving import loadgen
from benchmarks.serving.net import CPU_FALLBACK_REASON, _replica_net


def add_args(p):
    p.set_defaults(n=4000, features=32)
    p.add_argument("--profiles", default="step,spike,diurnal",
                   help="comma-separated offered-load profiles to run "
                        "(empty string skips the autoscale phase)")
    p.add_argument("--duration", type=float, default=30.0,
                   help="seconds per profile schedule")
    p.add_argument("--peak-rate", type=float, default=150.0,
                   help="peak offered rate, requests/second (profiles "
                        "scale this by their shape)")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4,
                   help="controller ceiling — ALSO the static provisioning "
                        "the replica-seconds ratio prices against")
    p.add_argument("--slo-p99", type=float, default=3.0,
                   help="declared p99 SLO (seconds) on the cdist endpoint")
    p.add_argument("--tick-s", type=float, default=0.25,
                   help="controller tick interval")
    p.add_argument("--up-cooldown-s", type=float, default=1.0)
    p.add_argument("--down-cooldown-s", type=float, default=2.0)
    p.add_argument("--backlog-high", type=float, default=4.0)
    p.add_argument("--backlog-ticks", type=int, default=2)
    p.add_argument("--idle-low", type=float, default=0.5)
    p.add_argument("--idle-ticks", type=int, default=4)
    p.add_argument("--drain-wait", type=float, default=25.0,
                   help="post-load seconds to wait for drain-down to min")
    p.add_argument("--streams", type=int, default=4,
                   help="concurrent loadgen submitter threads")
    p.add_argument("--workers", type=int, default=16,
                   help="router client worker threads")
    p.add_argument("--max-inflight", type=int, default=4,
                   help="router per-replica in-flight budget (0 = "
                        "unlimited) — with the gather window below this "
                        "bounds per-replica throughput, the committed "
                        "pacing regime (see benchmarks/serving/net.py)")
    p.add_argument("--wait-ms", type=float, default=25.0,
                   help="per-replica micro-batch gather window")
    p.add_argument("--queue-max", type=int, default=512,
                   help="per-replica admission queue bound")
    p.add_argument("--replica-mesh", type=int, default=2,
                   help="virtual CPU mesh size of every replica process")
    # two-tenant overload phase
    p.add_argument("--two-tenant", action="store_true",
                   help="run the weighted-fair two-tenant overload phase")
    p.add_argument("--tenant-replicas", type=int, default=2)
    p.add_argument("--tenant-duration", type=float, default=12.0)
    p.add_argument("--latency-rate", type=float, default=30.0,
                   help="offered rate of the latency-sensitive kmeans "
                        "tenant")
    p.add_argument("--bulk-rate", type=float, default=400.0,
                   help="offered rate of the bulk cdist tenant (past "
                        "capacity — the overload)")
    p.add_argument("--latency-weight", type=float, default=8.0,
                   help="weighted-fair weight of the latency class "
                        "(bulk weighs 1)")
    p.add_argument("--priority-queue-max", type=int, default=64,
                   help="bounded router admission queue for the phase")
    # hedge phase
    p.add_argument("--hedge", action="store_true",
                   help="run the hedged-retry straggler phase")
    p.add_argument("--hedge-duration", type=float, default=15.0)
    p.add_argument("--hedge-rate", type=float, default=20.0)
    p.add_argument("--hedge-delay-ms", type=float, default=75.0,
                   help="fixed hedge delay (the artifact pins the regime; "
                        "production defaults derive it from p95)")
    p.add_argument("--hedge-cap", type=float, default=0.35,
                   help="hedge-fraction hard cap for the phase")
    p.add_argument("--straggle-delay", type=float, default=0.3,
                   help="injected latency-fault delay on the straggler")
    p.add_argument("--straggle-p", type=float, default=0.5,
                   help="injected latency-fault probability")
    # chaos phase
    p.add_argument("--chaos", action="store_true",
                   help="run the SIGKILL-replacement phase")
    p.add_argument("--chaos-replicas", type=int, default=2)
    p.add_argument("--chaos-duration", type=float, default=12.0)
    p.add_argument("--chaos-rate", type=float, default=20.0)
    p.add_argument("--replace-tick-bound", type=int, default=3,
                   help="max controller ticks allowed between the kill "
                        "and the replacement (the bounded-ticks verdict)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workdir", default=None,
                   help="checkpoint + shared-cache directory (default: a "
                        "fresh temp dir; every phase shares one compile "
                        "cache within the run)")
    p.add_argument("--artifact", default=None,
                   help="append the emitted JSONL lines to this file")


def _emit(lines, obj):
    print(json.dumps(obj), flush=True)
    lines.append(obj)


def _pool_env(args, workdir):
    env = {
        "HEAT_TPU_COMPILE_CACHE": os.path.join(workdir, "xla_cache"),
        "HEAT_TPU_SERVE_MAX_BATCH": "4",
        "HEAT_TPU_SERVE_MAX_WAIT_MS": str(args.wait_ms),
        "HEAT_TPU_SERVE_QUEUE_MAX": str(args.queue_max),
    }
    # heatlint: disable=HL005 -- pass-through of the parent's already-set
    # env into the replica subprocess env dict, not a knob read
    for var in ("HEAT_TPU_TUNE_DB", "HEAT_TPU_AUTOTUNE",
                "HEAT_TPU_TELEMETRY"):
        if os.environ.get(var):
            env[var] = os.environ[var]
    return env


def _drive(router, requests, offsets, *, streams=4, timeout=120.0):
    """Open-loop drive of ``requests`` at precomputed arrival
    ``offsets`` (seconds from start) — the inhomogeneous-schedule twin
    of ``loadgen.run_open_loop`` (which generates its own fixed-rate
    schedule). Latency percentiles live in the ROUTER's per-endpoint
    stats; this returns the completion/shed/failure accounting."""
    from heat_tpu.serve import ServerOverloadedError

    n = len(requests)
    futures = [None] * n
    shed_errors = [None] * n
    submit_errors = [None] * n
    t0 = time.perf_counter()

    def submitter(stream):
        for i in range(stream, n, streams):
            name, payload = requests[i]
            delay = t0 + offsets[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                futures[i] = router.submit(name, payload)
            except ServerOverloadedError as e:
                shed_errors[i] = repr(e)
            except Exception as e:  # noqa: BLE001 — failed, never silent
                submit_errors[i] = repr(e)

    threads = [
        threading.Thread(target=submitter, args=(s,), daemon=True)
        for s in range(streams)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    shed = failed = 0
    errors = []
    deadline = time.monotonic() + timeout
    for i, (name, _payload) in enumerate(requests):
        if futures[i] is None:
            if submit_errors[i] is not None:
                failed += 1
                errors.append(f"request {i} ({name}): {submit_errors[i]}")
            else:
                shed += 1
            continue
        try:
            futures[i].result(max(0.001, deadline - time.monotonic()))
        except ServerOverloadedError:
            shed += 1
        except Exception as e:  # noqa: BLE001 — a failed request is data
            failed += 1
            errors.append(f"request {i} ({name}): {e!r}")
    wall = time.perf_counter() - t0
    ok = n - shed - failed
    return {
        "requests": n,
        "completed": ok,
        "failed": failed,
        "shed": shed,
        "errors": errors[:8],
        "wall_seconds": round(wall, 4),
        "achieved_qps": round(ok / wall, 2) if wall > 0 else 0.0,
    }


def _live(pool):
    return sum(
        1 for h in pool.replicas if h.state == "up" and h.alive()
    )


def _p99(router, endpoint):
    lat = router.stats()["endpoints"].get(endpoint, {}).get("latency", {})
    return lat.get("p99_s")


def _controller(args, pool, router, **over):
    from heat_tpu.serve.net import AutoscaleController

    kw = dict(
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        backlog_high=args.backlog_high, backlog_ticks=args.backlog_ticks,
        idle_low=args.idle_low, idle_ticks=args.idle_ticks,
        up_cooldown_s=args.up_cooldown_s,
        down_cooldown_s=args.down_cooldown_s,
        tick_interval_s=args.tick_s,
        slo_check_every=4,
    )
    kw.update(over)
    return AutoscaleController(pool, router, **kw)


def _profile_phase(args, ckpt, workdir, log_dir, profile):
    from heat_tpu.serve.net import ReplicaPool, Router
    from heat_tpu.telemetry.cluster import SLO

    offsets = profiles.schedule(
        profile, args.duration, args.peak_rate, seed=args.seed
    )
    reqs = loadgen.make_requests(
        {"cdist": args.features}, len(offsets), args.seed + 3, max_rows=1
    )
    t0 = time.perf_counter()
    pool = ReplicaPool(
        ckpt, args.min_replicas, mesh=args.replica_mesh,
        env=_pool_env(args, workdir),
        log_dir=os.path.join(log_dir, f"as_{profile}"),
    ).start()
    router = Router(
        pool, workers=args.workers,
        max_inflight=args.max_inflight or None, retry_in_flight=True,
        slos=[SLO("cdist", p99_s=args.slo_p99)],
    )
    ctrl = _controller(args, pool, router).start()
    try:
        report = _drive(router, reqs, offsets, streams=args.streams)
        drain_deadline = time.monotonic() + args.drain_wait
        while time.monotonic() < drain_deadline:
            if _live(pool) <= args.min_replicas:
                break
            time.sleep(args.tick_s)
        ctrl.stop()
        wall = time.perf_counter() - t0
        cstats = ctrl.stats()
        p99 = _p99(router, "cdist")
        static = args.max_replicas * wall
        ratio = (
            round(static / cstats["replica_seconds"], 2)
            if cstats["replica_seconds"] else None
        )
        net = _replica_net(pool)
        return {
            "profile": profile,
            "offered": {"peak_rate": args.peak_rate,
                        "duration_s": args.duration,
                        "requests": len(reqs)},
            **{k: report[k] for k in ("completed", "failed", "shed",
                                      "achieved_qps")},
            "p99_s": p99,
            "slo_p99_s": args.slo_p99,
            "p99_within_slo": p99 is not None and p99 <= args.slo_p99,
            "controller": cstats,
            "max_replicas_seen": max(
                (r["obs"]["replicas"] for r in ctrl.history), default=0
            ),
            "drained_to_min": _live(pool) <= args.min_replicas,
            "replica_seconds": cstats["replica_seconds"],
            "static_replica_seconds": round(static, 3),
            "replica_seconds_ratio": ratio,
            "steady_backend_compiles": [
                r.get("steady_backend_compiles") for r in net
            ],
            "wall_seconds": round(wall, 3),
        }
    finally:
        ctrl.stop()
        router.close()
        pool.close()


def _two_tenant_phase(args, ckpt, workdir, log_dir, features):
    from heat_tpu.serve.net import ReplicaPool, Router

    n_lat = max(1, int(args.tenant_duration * args.latency_rate))
    n_bulk = max(1, int(args.tenant_duration * args.bulk_rate))
    reqs_lat = loadgen.make_requests(
        {"kmeans": features["kmeans"]}, n_lat, args.seed + 5, max_rows=1
    )
    reqs_bulk = loadgen.make_requests(
        {"cdist": features["cdist"]}, n_bulk, args.seed + 6, max_rows=1
    )
    off_lat = loadgen.poisson_schedule(n_lat, args.latency_rate,
                                       args.seed + 7)
    off_bulk = loadgen.poisson_schedule(n_bulk, args.bulk_rate,
                                        args.seed + 8)
    pool = ReplicaPool(
        ckpt, args.tenant_replicas, mesh=args.replica_mesh,
        env=_pool_env(args, workdir),
        log_dir=os.path.join(log_dir, "two_tenant"),
    ).start()
    router = Router(
        pool, workers=args.workers,
        max_inflight=args.max_inflight or None,
        priorities={"latency": args.latency_weight, "bulk": 1.0},
        endpoint_priorities={"kmeans": "latency", "cdist": "bulk"},
        priority_queue_max=args.priority_queue_max,
    )
    try:
        results = {}

        def _tenant(key, reqs, offs):
            results[key] = _drive(router, reqs, offs,
                                  streams=max(2, args.streams // 2))

        ts = [
            threading.Thread(target=_tenant,
                             args=("latency", reqs_lat, off_lat),
                             daemon=True),
            threading.Thread(target=_tenant,
                             args=("bulk", reqs_bulk, off_bulk),
                             daemon=True),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        st = router.stats()
        classes = st["priority"]["classes"]
        routed_l = classes.get("latency", {}).get("routed", 0)
        routed_b = classes.get("bulk", {}).get("routed", 0)
        fair_share = 1.0 / (1.0 + args.latency_weight)
        bulk_share = routed_b / max(1, routed_b + routed_l)
        p99_lat = _p99(router, "kmeans")
        return {
            "replicas": args.tenant_replicas,
            "weights": {"latency": args.latency_weight, "bulk": 1.0},
            "priority_queue_max": args.priority_queue_max,
            "offered": {"latency_rate": args.latency_rate,
                        "bulk_rate": args.bulk_rate,
                        "duration_s": args.tenant_duration},
            "latency_tenant": {**results["latency"], "p99_s": p99_lat},
            "bulk_tenant": {**results["bulk"],
                            "p99_s": _p99(router, "cdist")},
            "routed": {"latency": routed_l, "bulk": routed_b},
            "priority_sheds": st["router"]["priority_sheds"],
            "bulk_fair_share": round(fair_share, 4),
            "bulk_routed_share": round(bulk_share, 4),
            "bulk_gets_fair_share": bulk_share >= fair_share,
            "latency_slo_p99_s": args.slo_p99,
            "latency_p99_within_slo":
                p99_lat is not None and p99_lat <= args.slo_p99,
            "latency_failed": results["latency"]["failed"],
        }
    finally:
        router.close()
        pool.close()


def _hedge_phase(args, ckpt, workdir, log_dir):
    from heat_tpu.serve.net import ReplicaPool, Router

    env = _pool_env(args, workdir)
    pool = ReplicaPool(
        ckpt, 1, mesh=args.replica_mesh, env=env,
        log_dir=os.path.join(log_dir, "hedge"),
    ).start()
    try:
        # the straggler: same checkpoint, latency faults injected into
        # its serve-side execution (resilience fault grammar, ISSUE 17)
        pool.env_overrides = dict(env, HEAT_TPU_FAULTS=(
            f"serve.*:kind=latency:delay={args.straggle_delay}"
            f":p={args.straggle_p}"
        ))
        pool.spawn()
        n = max(1, int(args.hedge_duration * args.hedge_rate))
        reqs = loadgen.make_requests(
            {"cdist": args.features}, n, args.seed + 9, max_rows=1
        )
        offs = loadgen.poisson_schedule(n, args.hedge_rate, args.seed + 10)
        rows = {}
        for mode, kw in (
            ("baseline", dict(hedge=False)),
            ("hedged", dict(hedge=True,
                            hedge_delay_ms=args.hedge_delay_ms,
                            hedge_max_fraction=args.hedge_cap)),
        ):
            router = Router(pool.urls(), workers=args.workers, **kw)
            try:
                rep = _drive(router, reqs, offs, streams=args.streams)
                st = router.stats()["router"]
                rows[mode] = {
                    **{k: rep[k] for k in ("completed", "failed", "shed")},
                    "p99_s": _p99(router, "cdist"),
                    "hedges": st["hedges"],
                    "hedge_wins": st["hedge_wins"],
                    "requests_routed": st["requests"],
                }
            finally:
                router.close()
        base_p99 = rows["baseline"]["p99_s"]
        hedged_p99 = rows["hedged"]["p99_s"]
        fraction = (
            rows["hedged"]["hedges"]
            / max(1, rows["hedged"]["requests_routed"])
        )
        return {
            "straggler_fault": {"delay_s": args.straggle_delay,
                                "p": args.straggle_p},
            "hedge_delay_ms": args.hedge_delay_ms,
            "hedge_cap": args.hedge_cap,
            "baseline": rows["baseline"],
            "hedged": rows["hedged"],
            "hedge_fraction": round(fraction, 4),
            "fraction_within_cap": fraction <= args.hedge_cap,
            "p99_improved":
                base_p99 is not None and hedged_p99 is not None
                and hedged_p99 < base_p99,
        }
    finally:
        pool.close()


def _chaos_phase(args, ckpt, workdir, log_dir):
    from heat_tpu.serve.net import ReplicaPool, Router

    pool = ReplicaPool(
        ckpt, args.chaos_replicas, mesh=args.replica_mesh,
        env=_pool_env(args, workdir),
        log_dir=os.path.join(log_dir, "chaos"),
    ).start()
    router = Router(
        pool, workers=args.workers,
        max_inflight=args.max_inflight or None, retry_in_flight=True,
    )
    ctrl = _controller(
        args, pool, router,
        min_replicas=args.chaos_replicas,
        max_replicas=args.chaos_replicas + 1,
    ).start()
    try:
        n = max(1, int(args.chaos_duration * args.chaos_rate))
        reqs = loadgen.make_requests(
            {"cdist": args.features}, n, args.seed + 11, max_rows=1
        )
        offs = loadgen.poisson_schedule(n, args.chaos_rate, args.seed + 12)
        result = {}

        def _load():
            result["report"] = _drive(router, reqs, offs,
                                      streams=args.streams)

        t = threading.Thread(target=_load, daemon=True)
        t.start()
        time.sleep(0.4 * args.chaos_duration)
        victim = next(
            h for h in reversed(pool.replicas)
            if h.state == "up" and h.alive()
        )
        ticks_at_kill = ctrl.ticks
        # RAW SIGKILL — pool state stays "up", so ONLY the controller's
        # liveness probe can notice and repair (the self-healing claim)
        victim.proc.kill()
        t_kill = time.perf_counter()
        t.join(timeout=180)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if ctrl.counts["replacements"] >= 1:
                break
            time.sleep(args.tick_s)
        ctrl.stop()
        replace_rows = [r for r in ctrl.history if r["action"] == "replace"]
        ticks_to_replace = (
            replace_rows[0]["tick"] - ticks_at_kill if replace_rows
            else None
        )
        report = result.get("report") or {}
        net = _replica_net(pool)
        live_net = [r for r in net if "steady_backend_compiles" in r]
        replacement = live_net[-1] if live_net else {}
        return {
            "replicas": args.chaos_replicas,
            "offered_rate": args.chaos_rate,
            "killed_replica": victim.index,
            **{k: report.get(k) for k in ("requests", "completed",
                                          "failed", "shed")},
            "replaced": bool(replace_rows),
            "ticks_to_replace": ticks_to_replace,
            "replace_tick_bound": args.replace_tick_bound,
            "replaced_within_bound":
                ticks_to_replace is not None
                and ticks_to_replace <= args.replace_tick_bound,
            "replacement_wall_seconds": round(
                time.perf_counter() - t_kill, 3
            ),
            "replacement": replacement,
            "replacement_steady_compiles":
                replacement.get("steady_backend_compiles"),
            "zero_failed": (report.get("failed") or 0) == 0,
            "controller": ctrl.stats(),
        }
    finally:
        ctrl.stop()
        router.close()
        pool.close()


def main():
    p = base_parser("heat_tpu autoscaling control-plane benchmark "
                    "(controller loadgen, two-tenant fairness, hedged "
                    "retries, chaos replacement)")
    add_args(p)
    args = p.parse_args()
    ht = bootstrap(args)
    import jax

    from benchmarks.serving.heat_tpu import build_endpoints
    from heat_tpu import telemetry

    devs = jax.devices()
    lines = []
    workdir = args.workdir or tempfile.mkdtemp(prefix="heat_tpu_autoscale_")
    os.makedirs(workdir, exist_ok=True)
    log_dir = os.path.join(workdir, "logs")
    os.makedirs(log_dir, exist_ok=True)
    ckpt = os.path.join(workdir, "endpoints.ckpt")

    # fit once, checkpoint: kmeans = the latency tenant, cdist = bulk
    eps = build_endpoints(ht, args, ["kmeans"])
    rng = np.random.default_rng(args.seed)
    eps["cdist"] = ht.serve.cdist_query(
        rng.standard_normal((256, args.features)).astype(np.float32)
    )
    server = ht.serve.Server()
    for name, ep in eps.items():
        server.register(name, ep)
    server.save(ckpt)
    server.close()
    features = {n: eps[n].features for n in eps}

    profile_rows = []
    for profile in [s.strip() for s in args.profiles.split(",") if s.strip()]:
        row = _profile_phase(args, ckpt, workdir, log_dir, profile)
        profile_rows.append(row)
        _emit(lines, {"autoscale_row": row})

    two_tenant = None
    if args.two_tenant:
        two_tenant = _two_tenant_phase(args, ckpt, workdir, log_dir,
                                       features)
        _emit(lines, {"two_tenant": two_tenant})

    hedge = None
    if args.hedge:
        hedge = _hedge_phase(args, ckpt, workdir, log_dir)
        _emit(lines, {"hedge": hedge})

    chaos = None
    if args.chaos:
        chaos = _chaos_phase(args, ckpt, workdir, log_dir)
        _emit(lines, {"chaos": chaos})

    summary = {
        "bench": "autoscale",
        "profiles": {
            r["profile"]: {
                "p99_within_slo": r["p99_within_slo"],
                "replica_seconds_ratio": r["replica_seconds_ratio"],
                "failed": r["failed"],
                "drained_to_min": r["drained_to_min"],
                "scale_ups": r["controller"]["scale_ups"],
                "scale_downs": r["controller"]["scale_downs"],
            }
            for r in profile_rows
        },
        "replica_seconds_ratio_min": min(
            (r["replica_seconds_ratio"] for r in profile_rows
             if r["replica_seconds_ratio"] is not None),
            default=None,
        ),
        "bounds": {"min_replicas": args.min_replicas,
                   "max_replicas": args.max_replicas},
        "two_tenant": two_tenant,
        "hedge": hedge,
        "chaos": chaos,
        "steady_backend_compiles_ok": all(
            c == 0
            for r in profile_rows for c in r["steady_backend_compiles"]
            if c is not None
        ),
        "on_chip": False,
        "cpu_fallback": CPU_FALLBACK_REASON,
        "devices": {"count": len(devs), "kind": devs[0].device_kind},
    }
    if telemetry.enabled():
        summary.update(telemetry.report.bench_fields())
    _emit(lines, summary)

    if args.artifact:
        with open(args.artifact, "a") as f:
            for obj in lines:
                f.write(json.dumps(obj) + "\n")


def bench_field(duration=8.0, peak_rate=60.0, mesh=2):
    """The ``autoscale`` detail row for bench.py summaries
    (docs/BENCHMARKS.md): a QUICK step-profile probe — one cdist
    endpoint, controller between 1 and 2 replicas — reporting the
    scale-up/drain trail and the replica-seconds ratio vs static max.
    Replica processes always run virtual CPU meshes, so the row carries
    its own ``on_chip``/``cpu_fallback`` verdict (the bench-honesty
    contract)."""
    import heat_tpu as ht
    from heat_tpu.serve.net import AutoscaleController, ReplicaPool, Router

    workdir = tempfile.mkdtemp(prefix="heat_tpu_autoscale_probe_")
    ckpt = os.path.join(workdir, "endpoints.ckpt")
    rng = np.random.default_rng(0)
    y = rng.standard_normal((128, 16)).astype(np.float32)
    server = ht.serve.Server()
    server.register("cdist", ht.serve.cdist_query(y))
    server.save(ckpt)
    server.close()
    offs = profiles.schedule("step", duration, peak_rate, seed=0)
    reqs = loadgen.make_requests({"cdist": 16}, len(offs), 0, max_rows=1)
    env = {
        "HEAT_TPU_COMPILE_CACHE": os.path.join(workdir, "xla_cache"),
        "HEAT_TPU_SERVE_MAX_BATCH": "4",
        "HEAT_TPU_SERVE_QUEUE_MAX": "256",
        "HEAT_TPU_SERVE_MAX_WAIT_MS": "25",
    }
    t0 = time.perf_counter()
    pool = ReplicaPool(
        ckpt, 1, mesh=mesh, env=env,
        log_dir=os.path.join(workdir, "logs"),
    ).start()
    router = Router(pool, workers=8, max_inflight=1, retry_in_flight=True)
    ctrl = AutoscaleController(
        pool, router, min_replicas=1, max_replicas=2,
        backlog_high=4.0, backlog_ticks=2, idle_low=0.5, idle_ticks=6,
        up_cooldown_s=1.0, down_cooldown_s=2.0, tick_interval_s=0.2,
    ).start()
    try:
        rep = _drive(router, reqs, offs, streams=2)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and _live(pool) > 1:
            time.sleep(0.2)
        ctrl.stop()
        wall = time.perf_counter() - t0
        cstats = ctrl.stats()
        ratio = (
            round(2 * wall / cstats["replica_seconds"], 2)
            if cstats["replica_seconds"] else None
        )
        return {
            "scale_ups": cstats["scale_ups"],
            "scale_downs": cstats["scale_downs"],
            "failed": rep["failed"],
            "p99_s": _p99(router, "cdist"),
            "replica_seconds_ratio": ratio,
            "drained_to_min": _live(pool) <= 1,
            "on_chip": False,
            "cpu_fallback": CPU_FALLBACK_REASON,
        }
    finally:
        ctrl.stop()
        router.close()
        pool.close()


if __name__ == "__main__":
    main()
