#!/usr/bin/env python
"""Horizontally-scaled serving benchmark: multi-process loadgen against a
replica pool behind the least-loaded router (ISSUE 12).

No reference analog (the reference framework has no serving tier). The
runner fits small estimators ONCE, checkpoints them, and then every
replica process is *born* from that checkpoint — warming from the shared
persistent XLA compile cache (and tuning DB when armed), the property
that makes horizontal scale-out cheap. Phases, each one JSONL line:

* ``{"pool": ...}`` — per-replica spawn/warm-up reports (ready wall,
  warm-up compile counts/seconds — replica 2..N should deserialize, not
  compile, when the shared cache is already hot);
* ``{"digest_probe": ...}`` — the router-vs-direct bit-identity oracle:
  the same seeded request set driven through an in-process Server and
  through the router over HTTP must produce IDENTICAL response digests
  (wire round-trip is bitwise; exact-mode answers are
  batch-composition-independent);
* ``{"scaling": [...]}`` — the headline: the SAME open-loop Poisson
  schedule at the SAME offered rate against 1, 2, ... N replicas (equal
  per-replica admission budgets via env knobs). Completed QPS at one
  replica is the single-process ceiling; N replicas should lift it
  near-linearly while p99 falls out of the queueing regime. Every row
  carries each replica's ``steady_backend_compiles`` (must be 0 — the
  remote zero-compile oracle).

  **Pacing regime.** Each replica's capacity is deliberately bounded by
  its recorded per-replica budget: the micro-batch gather window
  (``--wait-ms``) plus the router's per-replica in-flight budget
  (``--max-inflight``, default 1 outstanding batch). One replica
  therefore serializes on its own window+dispatch+wire cycle, and N
  replicas run N such pipelines concurrently — the scale factor
  measures the horizontal architecture (router, transport, shared-cache
  warm start), not host-core contention, which is what makes the number
  reproducible on small shared CI hosts. Raising the budgets shifts the
  bottleneck back to CPU, where scaling is capped by physical cores
  (both configs are honest; the summary records which one ran);
* ``{"chaos": ...}`` — kill one replica mid-load (SIGKILL): the router
  evicts it, siblings absorb the traffic, and ONLY the killed replica's
  in-flight requests fail; a freshly spawned replacement joins via
  ``Router.add_target`` and the post-kill probe answers bit-identically
  to the direct single-dispatch reference;
* final summary — ``on_chip`` + ``cpu_fallback`` honesty: replica
  processes ALWAYS run virtual CPU meshes (an attached accelerator
  cannot be shared across processes), so this bench is a CPU number by
  construction and says so in-band.

``--artifact PATH`` appends the emitted lines (the committed
``artifacts/bench_serving_net_r12.jsonl``). The CI serving-net gate
(scripts/run_ci.sh) runs ``--replicas-list 2 --chaos`` small and asserts
the digest/recovery/zero-compile verdicts.
"""

import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

from benchmarks._harness import base_parser, bootstrap

CPU_FALLBACK_REASON = (
    "replica processes run on virtual cpu meshes (an attached accelerator "
    "cannot be shared across replica processes)"
)


def add_args(p):
    p.add_argument("--replicas-list", default="1,2,4",
                   help="comma-separated replica counts to sweep at equal "
                        "offered load")
    p.add_argument("--requests", type=int, default=1200,
                   help="requests per scaling phase")
    p.add_argument("--rate", type=float, default=1200.0,
                   help="offered Poisson arrival rate, requests/second "
                        "(the SAME for every replica count)")
    p.add_argument("--streams", type=int, default=4,
                   help="concurrent loadgen submitter threads")
    p.add_argument("--endpoints", default="cdist,dense",
                   help="comma-separated endpoint subset "
                        "(kmeans,lasso,gnb,dense,knn,rbf,cdist)")
    p.add_argument("--replica-mesh", type=int, default=4,
                   help="virtual CPU mesh size of every replica process")
    p.add_argument("--max-batch", type=int, default=4,
                   help="per-replica micro-batch ladder top (the bounded "
                        "per-replica batch budget)")
    p.add_argument("--queue-max", type=int, default=64,
                   help="per-replica admission queue bound (bounds the "
                        "queueing tail; excess load sheds 503)")
    p.add_argument("--wait-ms", type=float, default=2.0,
                   help="per-replica micro-batch gather window")
    p.add_argument("--workers", type=int, default=16,
                   help="router client worker threads (the router's max "
                        "total in-flight)")
    p.add_argument("--max-inflight", type=int, default=1,
                   help="router per-replica in-flight budget (the client "
                        "half of the per-replica admission discipline; "
                        "0 = unlimited). With the budget at 1, a replica "
                        "serves strictly one request at a time, so the "
                        "single-replica arm measures the serialized "
                        "per-request wall (gather window + dispatch + "
                        "wire) and N replicas run N such pipelines "
                        "concurrently")
    p.add_argument("--max-rows", type=int, default=1,
                   help="max rows per request payload")
    p.add_argument("--digest-requests", type=int, default=120,
                   help="requests in the router-vs-direct digest probe")
    p.add_argument("--digest-rate", type=float, default=150.0,
                   help="offered rate of the digest probe (below "
                        "saturation: zero sheds on both sides)")
    p.add_argument("--chaos", action="store_true",
                   help="run the kill-one-replica phase")
    p.add_argument("--chaos-rate", type=float, default=None,
                   help="offered rate during chaos (default: rate/2 — the "
                        "surviving replicas must absorb it)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workdir", default=None,
                   help="checkpoint + shared-cache directory (default: a "
                        "fresh temp dir — every replica count still shares "
                        "one compile cache within the run)")
    p.add_argument("--artifact", default=None,
                   help="append the emitted JSONL lines to this file")


def _emit(lines, obj):
    print(json.dumps(obj), flush=True)
    lines.append(obj)


def _pool_env(args, workdir):
    env = {
        "HEAT_TPU_COMPILE_CACHE": os.path.join(workdir, "xla_cache"),
        "HEAT_TPU_SERVE_MAX_BATCH": str(args.max_batch),
        "HEAT_TPU_SERVE_MAX_WAIT_MS": str(args.wait_ms),
        "HEAT_TPU_SERVE_QUEUE_MAX": str(args.queue_max),
    }
    # the tuning DB rides along exactly like the compile cache when the
    # parent run is armed (docs/AUTOTUNE.md): replicas start tuned
    # heatlint: disable=HL005 -- pass-through of the parent's already-set
    # env into the replica subprocess env dict, not a knob read
    for var in ("HEAT_TPU_TUNE_DB", "HEAT_TPU_AUTOTUNE",
                "HEAT_TPU_TELEMETRY"):
        if os.environ.get(var):
            env[var] = os.environ[var]
    return env


def _spawn(args, ckpt, n, workdir, log_dir):
    from heat_tpu.serve.net import ReplicaPool, Router

    t0 = time.perf_counter()
    pool = ReplicaPool(
        ckpt, n, mesh=args.replica_mesh, env=_pool_env(args, workdir),
        log_dir=log_dir,
    ).start()
    router = Router(
        pool, workers=args.workers,
        max_inflight=args.max_inflight or None,
    )
    return pool, router, round(time.perf_counter() - t0, 3)


def _replica_net(pool):
    """Per-replica ``net`` stats blocks (steady compiles, http tallies)."""
    out = []
    for h in pool.replicas:
        if h.state != "up" or not h.alive():
            out.append({"replica": h.index, "state": h.state})
            continue
        try:
            st = pool.stats(h.index)
        except Exception as e:  # noqa: BLE001 — a dead replica is data
            out.append({"replica": h.index, "state": "unreachable",
                        "error": repr(e)})
            continue
        net = st.get("net", {})
        out.append({
            "replica": h.index,
            "steady_backend_compiles": net.get("steady_backend_compiles"),
            "http_requests": net.get("http_requests"),
            "warmup": h.ready.get("warmup") if h.ready else None,
            "shed": st.get("shed"),
            "pending": st.get("pending"),
        })
    return out


def _reference_answers(ht, eps, seed):
    """Direct single-dispatch reference per endpoint (fresh jit, like the
    PR 8 post_ok oracle) — the chaos recovery probe compares routed
    answers against these, bitwise."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed + 17)
    out = {}
    for name, ep in sorted(eps.items()):
        probe = rng.standard_normal((2, ep.features)).astype(ep.dtype)
        # heatlint: disable=HL001 -- fresh independent jit is the oracle:
        # compiled outside the server's cached program to prove bit-equality
        ref = np.asarray(jax.jit(ep.build())(jnp.asarray(probe), *ep.params))
        out[name] = (probe, ref)
    return out


def _probe_router(router, refs, timeout=30.0):
    """post_ok: every endpoint's routed answer must match the direct
    reference bit-for-bit."""
    ok = True
    for name, (probe, ref) in refs.items():
        try:
            got = router.predict(name, probe, timeout=timeout)
        except Exception:  # noqa: BLE001 — a dead tier is the finding
            return False
        if np.asarray(got).tobytes() != ref.tobytes():
            ok = False
    return ok


def main():
    p = base_parser("heat_tpu horizontally-scaled serving benchmark "
                    "(replica pool + router, multi-process loadgen)")
    add_args(p)
    args = p.parse_args()
    ht = bootstrap(args)
    import jax

    from benchmarks.serving import loadgen
    from benchmarks.serving.heat_tpu import build_endpoints
    from heat_tpu import telemetry

    devs = jax.devices()
    lines = []
    replicas_list = sorted(
        {int(v) for v in args.replicas_list.split(",") if v.strip()}
    )
    names = [s.strip() for s in args.endpoints.split(",") if s.strip()]

    workdir = args.workdir or tempfile.mkdtemp(prefix="heat_tpu_srvnet_")
    os.makedirs(workdir, exist_ok=True)
    log_dir = os.path.join(workdir, "logs")
    os.makedirs(log_dir, exist_ok=True)
    ckpt = os.path.join(workdir, "endpoints.ckpt")

    # -- fit once, checkpoint, reference answers ------------------------------
    eps = build_endpoints(ht, args, [n for n in names if n != "cdist"])
    if "cdist" in names:
        rng = np.random.default_rng(args.seed)
        eps["cdist"] = ht.serve.cdist_query(
            rng.standard_normal((256, args.features)).astype(np.float32)
        )
    server = ht.serve.Server()
    for name, ep in eps.items():
        server.register(name, ep)
    server.save(ckpt)
    server.close()
    refs = _reference_answers(ht, eps, args.seed)

    features = {n: eps[n].features for n in eps}
    dtypes = {n: eps[n].dtype for n in eps}
    reqs = loadgen.make_requests(
        features, args.requests, args.seed,
        max_rows=args.max_rows, dtypes=dtypes,
    )
    digest_reqs = loadgen.make_requests(
        features, args.digest_requests, args.seed + 1,
        max_rows=args.max_rows, dtypes=dtypes,
    )

    # -- direct (in-process) digest reference ---------------------------------
    direct = ht.serve.Server.restore(ckpt)
    direct.warmup()
    direct_probe = loadgen.run_open_loop(
        direct, digest_reqs, args.digest_rate, seed=args.seed,
        streams=args.streams,
    )
    direct.close()

    # -- scaling sweep: equal offered load, growing replica count -------------
    scaling = []
    digest_probe = None
    for n in replicas_list:
        pool, router, spawn_wall = _spawn(
            args, ckpt, n, workdir, os.path.join(log_dir, f"r{n}")
        )
        try:
            if digest_probe is None:
                routed_probe = loadgen.run_open_loop(
                    router, digest_reqs, args.digest_rate, seed=args.seed,
                    streams=args.streams,
                )
                digest_probe = {
                    "requests": args.digest_requests,
                    "direct_digest": direct_probe["digest"],
                    "routed_digest": routed_probe["digest"],
                    "match": routed_probe["digest"] == direct_probe["digest"],
                    "direct_clean": direct_probe["failed"] == 0
                    and direct_probe["shed"] == 0,
                    "routed_clean": routed_probe["failed"] == 0
                    and routed_probe["shed"] == 0,
                }
                _emit(lines, {"digest_probe": digest_probe})
            report = loadgen.run_open_loop(
                router, reqs, args.rate, seed=args.seed,
                streams=args.streams,
            )
            net = _replica_net(pool)
            row = {
                "replicas": n,
                "spawn_wall_seconds": spawn_wall,
                "achieved_qps": report["achieved_qps"],
                "completed": report["completed"],
                "failed": report["failed"],
                "shed": report["shed"],
                "p50_s": report["latency"].get("p50_s"),
                "p99_s": report["latency"].get("p99_s"),
                "steady_backend_compiles": [
                    r.get("steady_backend_compiles") for r in net
                ],
                "per_replica": net,
                "router": router.stats()["router"],
            }
            scaling.append(row)
            _emit(lines, {"scaling_row": row})
        finally:
            router.close()
            pool.close()
    _emit(lines, {"scaling": scaling})

    # -- chaos: kill one replica mid-load -------------------------------------
    chaos = None
    if args.chaos:
        n = max(replicas_list)
        rate = args.chaos_rate or args.rate / 2
        pool, router, _ = _spawn(
            args, ckpt, n, workdir, os.path.join(log_dir, "chaos")
        )
        try:
            result = {}

            def _load():
                result["report"] = loadgen.run_open_loop(
                    router, reqs, rate, seed=args.seed,
                    streams=args.streams,
                )

            t = threading.Thread(target=_load, daemon=True)
            t.start()
            # kill roughly mid-schedule
            time.sleep(0.4 * args.requests / rate)
            victim = pool.replicas[n - 1].index
            victim_inflight = router.stats()["replicas"].get(
                pool.handle(victim).url, {}
            ).get("inflight", 0)
            pool.kill(victim)
            t_kill = time.perf_counter()
            t.join(timeout=180)
            report = result.get("report") or {}
            # recovery: a fresh replacement replica joins the rotation
            repl = pool.spawn()
            router.add_target(repl.url)
            post_ok = _probe_router(router, refs)
            chaos = {
                "replicas": n,
                "offered_rate": rate,
                "killed_replica": victim,
                "inflight_at_kill": victim_inflight,
                "completed": report.get("completed"),
                "failed": report.get("failed"),
                "shed": report.get("shed"),
                "p99_s": (report.get("latency") or {}).get("p99_s"),
                "router": router.stats()["router"],
                "max_inflight_bound": args.workers,
                "failed_within_inflight_bound":
                    (report.get("failed") or 0) <= args.workers,
                "replacement_replica": repl.index,
                "replacement_join_seconds":
                    round(time.perf_counter() - t_kill, 3),
                "post_ok": post_ok,
            }
            _emit(lines, {"chaos": chaos})
        finally:
            router.close()
            pool.close()

    # -- summary (bench-honesty contract) -------------------------------------
    by_n = {row["replicas"]: row for row in scaling}
    base = by_n.get(replicas_list[0], {})
    top = by_n.get(replicas_list[-1], {})
    summary = {
        "bench": "serving_net",
        "requests": args.requests,
        "offered_rate": args.rate,
        "endpoints": sorted(eps),
        "replica_mesh": args.replica_mesh,
        "per_replica_budget": {
            "max_batch": args.max_batch,
            "queue_max": args.queue_max,
            "wait_ms": args.wait_ms,
            "router_max_inflight": args.max_inflight or None,
        },
        "qps_by_replicas": {
            str(r["replicas"]): r["achieved_qps"] for r in scaling
        },
        "p99_by_replicas": {
            str(r["replicas"]): r["p99_s"] for r in scaling
        },
        "scale_factor": (
            round(top["achieved_qps"] / base["achieved_qps"], 2)
            if base.get("achieved_qps") else None
        ),
        "digest_probe": digest_probe,
        "chaos": chaos,
        "steady_backend_compiles_ok": all(
            c == 0
            for r in scaling for c in r["steady_backend_compiles"]
            if c is not None
        ),
        "on_chip": False,
        "cpu_fallback": CPU_FALLBACK_REASON,
        "devices": {"count": len(devs), "kind": devs[0].device_kind},
    }
    if telemetry.enabled():
        summary.update(telemetry.report.bench_fields())
    _emit(lines, summary)

    if args.artifact:
        with open(args.artifact, "a") as f:
            for obj in lines:
                f.write(json.dumps(obj) + "\n")


def bench_field(replicas=(1, 2), requests=60, rate=80.0, mesh=4):
    """The ``serving_net`` detail row for bench.py summaries
    (docs/BENCHMARKS.md): a QUICK replica-scaling probe — tiny endpoint
    set, ``replicas`` pool sizes at equal offered load — reporting the
    QPS table and scale factor. Replicas always run virtual CPU meshes,
    so the row carries its own ``on_chip``/``cpu_fallback`` verdict
    regardless of the parent bench's backend (the bench-honesty
    contract)."""
    import heat_tpu as ht
    from benchmarks.serving import loadgen
    from heat_tpu.serve.net import ReplicaPool, Router

    workdir = tempfile.mkdtemp(prefix="heat_tpu_srvnet_probe_")
    ckpt = os.path.join(workdir, "endpoints.ckpt")
    rng = np.random.default_rng(0)
    y = rng.standard_normal((128, 16)).astype(np.float32)
    server = ht.serve.Server()
    server.register("cdist", ht.serve.cdist_query(y))
    server.save(ckpt)
    server.close()
    reqs = loadgen.make_requests({"cdist": 16}, requests, 0, max_rows=1)
    env = {
        "HEAT_TPU_COMPILE_CACHE": os.path.join(workdir, "xla_cache"),
        "HEAT_TPU_SERVE_MAX_BATCH": "4",
        "HEAT_TPU_SERVE_QUEUE_MAX": "64",
        # the committed-artifact pacing regime (see the r12 artifact):
        # per-replica throughput bounded by the gather window + one
        # in-flight batch, so the scale factor measures the
        # architecture, not host CPU contention
        "HEAT_TPU_SERVE_MAX_WAIT_MS": "25",
    }
    out = {
        "qps": {}, "p99_s": {},
        "on_chip": False, "cpu_fallback": CPU_FALLBACK_REASON,
    }
    for n in replicas:
        pool = ReplicaPool(
            ckpt, int(n), mesh=mesh, env=env,
            log_dir=os.path.join(workdir, f"logs_r{n}"),
        ).start()
        router = Router(pool, workers=8, max_inflight=1)
        try:
            report = loadgen.run_open_loop(router, reqs, rate, streams=2)
            out["qps"][str(n)] = report["achieved_qps"]
            out["p99_s"][str(n)] = report["latency"].get("p99_s")
        finally:
            router.close()
            pool.close()
    first, last = str(replicas[0]), str(replicas[-1])
    if out["qps"].get(first):
        out["scale_factor"] = round(
            out["qps"][last] / out["qps"][first], 2
        )
    return out


if __name__ == "__main__":
    main()
