#!/usr/bin/env python
"""Tracing-overhead benchmark: the cost of the distributed request-trace
plane on the in-process serving hot path (ISSUE 17).

No reference analog (the reference framework has neither a serving tier
nor request tracing). The runner mounts the PR 8 endpoint set, then
drives the SAME seeded open-loop Poisson schedule through four tracing
postures, one JSONL ``{"mode_row": ...}`` each:

* ``telemetry_off`` — the true baseline: every tracing call site is the
  usual single ``telemetry.enabled()`` flag check;
* ``off`` — telemetry recording on, ``HEAT_TPU_TRACE_REQUESTS=0``: the
  headline "tracing off" posture (one extra knob read at ingress, zero
  per-hop work) — the row the overhead percentages are measured against;
* ``sampled`` — ``HEAT_TPU_TRACE_SAMPLE=<--sample>`` (default 0.1): the
  production posture, hop spans for ~10% of requests;
* ``full`` — sample rate 1.0: every request decomposes into its
  queue → coalesce → pad → execute → reply spans (worst case).

Every row carries achieved QPS, p50/p99, the response **digest** — all
four modes must match bit-for-bit (tracing never touches payloads; the
summary's ``digest_match`` pins it) — and the mode's ``tracing.sampled``
/ ``tracing.spans`` counters (off must be 0/0, full must sample every
request). The final summary reports per-mode overhead as a fraction of
the ``off`` row's QPS, plus the ``on_chip`` / ``cpu_fallback`` honesty
fields (bench-honesty contract: a CPU-mesh number says so in-band).

``--artifact PATH`` appends the emitted lines (the committed
``artifacts/bench_tracing_r17.jsonl``).
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

from benchmarks._harness import base_parser, bootstrap


def add_args(p):
    p.add_argument("--requests", type=int, default=600,
                   help="requests in the open-loop schedule (the same "
                        "seeded schedule for every mode)")
    p.add_argument("--rate", type=float, default=600.0,
                   help="offered Poisson arrival rate, requests/second")
    p.add_argument("--streams", type=int, default=2,
                   help="concurrent submitter threads")
    p.add_argument("--endpoints", default="dense,cdist",
                   help="comma-separated endpoint subset "
                        "(kmeans,lasso,gnb,dense,knn,rbf,cdist)")
    p.add_argument("--max-batch", type=int, default=8,
                   help="micro-batch ladder top")
    p.add_argument("--sample", type=float, default=0.1,
                   help="HEAT_TPU_TRACE_SAMPLE of the `sampled` mode")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--artifact", default=None,
                   help="append the emitted JSONL lines to this file")


def _emit(lines, obj):
    print(json.dumps(obj), flush=True)
    lines.append(obj)


def _run_mode(ht, args, eps, reqs, mode, env):
    """One posture: fresh Server (per-mode histograms and counters start
    clean), warmup outside the timed window, one open-loop run."""
    from benchmarks.serving import loadgen
    from heat_tpu import telemetry

    # benchmark-runner env staging for an in-process mode switch (the
    # knobs are read per-request at ingress, so this is the same
    # mechanism a deployment uses)
    saved = {}
    for k, v in env.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    reg = None
    sink = None
    try:
        if env.get("HEAT_TPU_TELEMETRY") == "1":
            sink = tempfile.NamedTemporaryFile(
                mode="w", suffix=".jsonl", delete=False
            )
            reg = telemetry.enable(sink.name)
            reg.clear()
        server = ht.serve.Server(max_batch=args.max_batch)
        for name, ep in eps.items():
            server.register(name, ep)
        server.warmup()
        report = loadgen.run_open_loop(
            server, reqs, args.rate, seed=args.seed, streams=args.streams,
        )
        counters = dict(reg.counters) if reg is not None else {}
        server.close()
        return {
            "mode": mode,
            "achieved_qps": report["achieved_qps"],
            "completed": report["completed"],
            "failed": report["failed"],
            "shed": report["shed"],
            "p50_s": report["latency"].get("p50_s"),
            "p99_s": report["latency"].get("p99_s"),
            "digest": report["digest"],
            "tracing": {
                "sampled": int(counters.get("tracing.sampled", 0)),
                "spans": int(counters.get("tracing.spans", 0)),
            },
        }
    finally:
        if reg is not None:
            telemetry.disable()
            reg.clear()
        if sink is not None:
            sink.close()
            os.unlink(sink.name)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main():
    p = base_parser("heat_tpu request-tracing overhead benchmark "
                    "(off vs sampled vs 100%, bit-identity pinned)")
    add_args(p)
    args = p.parse_args()
    ht = bootstrap(args)
    import jax

    from benchmarks.serving import loadgen
    from benchmarks.serving.heat_tpu import build_endpoints

    devs = jax.devices()
    on_chip = devs[0].platform != "cpu"
    cpu_fallback = (
        None if on_chip else
        ("forced virtual cpu mesh (--mesh)" if args.mesh
         else "default backend is cpu (no accelerator attached)")
    )
    lines = []
    names = [s.strip() for s in args.endpoints.split(",") if s.strip()]

    eps = build_endpoints(ht, args, [n for n in names if n != "cdist"])
    if "cdist" in names:
        rng = np.random.default_rng(args.seed)
        eps["cdist"] = ht.serve.cdist_query(
            rng.standard_normal((128, args.features)).astype(np.float32)
        )
    reqs = loadgen.make_requests(
        {n: eps[n].features for n in eps},
        args.requests, args.seed,
        dtypes={n: eps[n].dtype for n in eps},
    )

    modes = (
        ("telemetry_off", {"HEAT_TPU_TELEMETRY": "0"}),
        ("off", {"HEAT_TPU_TELEMETRY": "1",
                 "HEAT_TPU_TRACE_REQUESTS": "0"}),
        ("sampled", {"HEAT_TPU_TELEMETRY": "1",
                     "HEAT_TPU_TRACE_REQUESTS": "1",
                     "HEAT_TPU_TRACE_SAMPLE": str(args.sample)}),
        ("full", {"HEAT_TPU_TELEMETRY": "1",
                  "HEAT_TPU_TRACE_REQUESTS": "1",
                  "HEAT_TPU_TRACE_SAMPLE": "1.0"}),
    )
    rows = []
    for mode, env in modes:
        row = _run_mode(ht, args, eps, reqs, mode, env)
        rows.append(row)
        _emit(lines, {"mode_row": row})

    by_mode = {r["mode"]: r for r in rows}
    base = by_mode["off"]
    overhead = {
        m: (round(1.0 - by_mode[m]["achieved_qps"] / base["achieved_qps"],
                  4)
            if base["achieved_qps"] else None)
        for m in ("sampled", "full")
    }
    summary = {
        "bench": "serving_tracing",
        "requests": args.requests,
        "offered_rate": args.rate,
        "streams": args.streams,
        "endpoints": sorted(eps),
        "max_batch": args.max_batch,
        "sample_rate": args.sample,
        "qps_by_mode": {r["mode"]: r["achieved_qps"] for r in rows},
        "p99_by_mode": {r["mode"]: r["p99_s"] for r in rows},
        "overhead_vs_off": overhead,
        # tracing must never touch answers: one digest across all modes
        "digest_match": len({r["digest"] for r in rows}) == 1,
        "off_counters_zero": by_mode["off"]["tracing"] == {
            "sampled": 0, "spans": 0,
        },
        "full_sampled_all": (
            by_mode["full"]["tracing"]["sampled"] >= args.requests
        ),
        "on_chip": on_chip,
        "cpu_fallback": cpu_fallback,
        "devices": {"count": len(devs), "kind": devs[0].device_kind},
    }
    _emit(lines, summary)

    if args.artifact:
        with open(args.artifact, "a") as f:
            for obj in lines:
                f.write(json.dumps(obj) + "\n")


if __name__ == "__main__":
    main()
