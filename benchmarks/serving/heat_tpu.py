#!/usr/bin/env python
"""Serving benchmark: open-loop Poisson load against a live heat_tpu.serve
Server (ISSUE 8).

No reference analog (the reference framework has no serving front end).
The runner fits small estimators, mounts them as endpoints, pre-traces the
batch ladder with ``server.warmup()``, then drives a seeded open-loop
Poisson arrival stream at ``--rate`` requests/s across ``--streams``
concurrent submitter threads. It prints JSONL:

* ``{"warmup": ...}`` — ladder size and backend compiles paid up front;
* ``{"serving_compare": ...}`` — the CI gate's oracle: program-registry
  misses and backend compiles **during the load window** (steady state
  must be 0/0), achieved QPS vs offered rate, latency percentiles,
  failed/shed counts, the response digest (bit-identity across fault
  injection), and ``post_ok`` (a post-load probe per endpoint matching a
  direct single-dispatch answer bit-for-bit — the recover check);
* a final summary carrying ``on_chip`` + ``cpu_fallback`` (bench-honesty
  contract: a CPU-mesh number must say so in-band) and, with
  ``HEAT_TPU_TELEMETRY=1``, the ``telemetry.serving`` block
  (docs/OBSERVABILITY.md schema).

``--artifact PATH`` appends the emitted lines to a JSONL artifact (the
committed ``artifacts/bench_serving_r08.jsonl``).

Fault interplay: inject with ``HEAT_TPU_FAULTS='serve.*:...'`` and arm
``HEAT_TPU_RETRIES`` — dispatch-level retries happen per *batch* inside
the server, so a clean and an injected run must produce identical
digests (scripts/run_ci.sh serving gate pins exactly that).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

from benchmarks._harness import base_parser, bootstrap

ENDPOINTS = ("kmeans", "lasso", "gnb", "dense", "knn", "rbf")


def add_args(p):
    p.add_argument("--requests", type=int, default=400,
                   help="total requests in the open-loop schedule")
    p.add_argument("--rate", type=float, default=400.0,
                   help="offered Poisson arrival rate, requests/second")
    p.add_argument("--streams", type=int, default=2,
                   help="concurrent submitter threads")
    p.add_argument("--endpoints", default="kmeans,lasso,gnb,dense",
                   help=f"comma-separated subset of {ENDPOINTS}")
    p.add_argument("--max-batch", type=int, default=16,
                   help="micro-batch ladder top (HEAT_TPU_SERVE_MAX_BATCH)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--digest", action="store_true",
                   help="include the response sha256 in serving_compare "
                        "(the CI fault-injection bit-identity oracle)")
    p.add_argument("--artifact", default=None,
                   help="append the emitted JSONL lines to this file")


def _emit(lines, obj):
    print(json.dumps(obj), flush=True)
    lines.append(obj)


def build_endpoints(ht, args, names):
    """Fit the small estimators and return {name: (endpoint, features,
    dtype)} — seeded, so every process builds identical endpoints."""
    rng = np.random.default_rng(args.seed)
    n, d = args.n, args.features
    xn = rng.standard_normal((n, d)).astype(np.float32)
    x = ht.array(xn, split=0)
    out = {}
    if "kmeans" in names:
        km = ht.cluster.KMeans(
            n_clusters=8, max_iter=20, random_state=args.seed
        ).fit(x)
        out["kmeans"] = ht.serve.kmeans_predict(km)
    if "lasso" in names:
        y = ht.array(
            (xn @ rng.standard_normal(d) + 0.1).astype(np.float32), split=0
        )
        out["lasso"] = ht.serve.lasso_predict(
            ht.regression.Lasso(lam=0.05, max_iter=10).fit(x, y)
        )
    if "gnb" in names:
        labels = ht.array((xn[:, 0] > 0).astype(np.int64), split=0)
        out["gnb"] = ht.serve.gaussian_nb_predict(
            ht.naive_bayes.GaussianNB().fit(x, labels)
        )
    if "dense" in names:
        w = rng.standard_normal((d, 8)).astype(np.float32)
        b = rng.standard_normal(8).astype(np.float32)
        out["dense"] = ht.serve.dense_forward(w, b, activation="relu")
    if "knn" in names:
        labels = ht.array((xn[:, 0] > 0).astype(np.int64), split=0)
        knn = ht.classification.KNeighborsClassifier(n_neighbors=5).fit(
            x[: min(n, 512)], labels[: min(n, 512)]
        )
        out["knn"] = ht.serve.knn_classify(knn)
    if "rbf" in names:
        out["rbf"] = ht.serve.rbf_query(xn[:64], sigma=1.0)
    return out


def main():
    p = base_parser("heat_tpu serving benchmark (open-loop Poisson load)")
    add_args(p)
    args = p.parse_args()
    ht = bootstrap(args)
    import jax

    from benchmarks.serving import loadgen
    from heat_tpu.core import program_cache
    from heat_tpu import telemetry

    devs = jax.devices()
    on_chip = devs[0].platform != "cpu"
    cpu_fallback = (
        None if on_chip else
        ("forced virtual cpu mesh (--mesh)" if args.mesh
         else "default backend is cpu (no accelerator attached)")
    )
    lines = []
    names = [s.strip() for s in args.endpoints.split(",") if s.strip()]
    unknown = set(names) - set(ENDPOINTS)
    if unknown:
        raise SystemExit(f"unknown endpoints {sorted(unknown)}")

    eps = build_endpoints(ht, args, names)
    server = ht.serve.Server(max_batch=args.max_batch)
    for name, ep in eps.items():
        server.register(name, ep)
    warm = server.warmup()
    _emit(lines, {"warmup": warm})

    reqs = loadgen.make_requests(
        {n: eps[n].features for n in eps},
        args.requests, args.seed,
        dtypes={n: eps[n].dtype for n in eps},
    )
    before = program_cache.site_stats("serve.")
    with telemetry.CompileWatcher() as cw:
        report = loadgen.run_open_loop(
            server, reqs, args.rate, seed=args.seed, streams=args.streams,
        )
    after = program_cache.site_stats("serve.")

    # shed-and-recover probe: after the load window (faults, sheds and all)
    # every endpoint must still answer — and answer bit-identically to a
    # direct single dispatch of the same program outside the server
    import jax.numpy as jnp

    post_ok = True
    # GEMM-mode sweep check (ISSUE 9 satellite / ROADMAP PR 8 remaining):
    # when the run serves the fast GEMM kernels (HEAT_TPU_SERVE_EXACT=0),
    # every endpoint's probe answer must still be allclose to the
    # bit-stable exact-mode kernel's answer for the same inputs — the
    # digest of the exact-mode references is recorded so two sweeps can
    # be compared across processes.
    import hashlib

    gemm_mode = not ht.serve.endpoints.exact_mode()
    exact_check = {
        "gemm_mode": gemm_mode, "checked": 0, "allclose": True,
        "max_abs_diff": 0.0, "exact_digest": hashlib.sha256(),
    }
    probe_rng = np.random.default_rng(args.seed + 1)
    for name, ep in sorted(eps.items()):
        probe = probe_rng.standard_normal((2, ep.features)).astype(ep.dtype)
        try:
            got = server.predict(name, probe, timeout=30.0)
        except Exception:  # noqa: BLE001 — a dead server is the finding
            post_ok = False
            continue
        # a FRESH jit of the same pure function: identical HLO, compiled
        # independently of the server's cached program (eager dispatch
        # would re-associate reductions op-by-op and break bit-equality)
        # heatlint: disable=HL001 -- a FRESH jit is the oracle: compiled
        # independently of the server's cached program to prove bit-equality
        ref = np.asarray(jax.jit(ep.build())(jnp.asarray(probe), *ep.params))
        if got.tobytes() != ref.tobytes():
            post_ok = False
        # exact-kernel twin of the same endpoint (same params, exact=True)
        exact_ep = ht.serve.Endpoint(
            ep.kind, ep.params, {**ep.config, "exact": True},
            features=ep.features, dtype=ep.dtype,
        )
        exact_ref = np.asarray(
            # heatlint: disable=HL001 -- fresh independent compile, as above
            jax.jit(exact_ep.build())(jnp.asarray(probe), *exact_ep.params)
        )
        exact_check["checked"] += 1
        exact_check["exact_digest"].update(exact_ref.tobytes())
        if exact_ref.dtype.kind in "fc":
            diff = float(np.max(np.abs(got.astype(np.float64)
                                       - exact_ref.astype(np.float64))))
            exact_check["max_abs_diff"] = max(
                exact_check["max_abs_diff"], diff
            )
            if not np.allclose(got, exact_ref, rtol=1e-4, atol=1e-5):
                exact_check["allclose"] = False
        elif got.tobytes() != exact_ref.tobytes():
            # label-valued endpoints: GEMM-vs-exact may legally flip a
            # tie-break only at exactly-equidistant probes; random probes
            # are never equidistant, so labels must match outright
            exact_check["allclose"] = False
    exact_check["exact_digest"] = exact_check["exact_digest"].hexdigest()[:16]

    compare = {
        "misses_during_load": after["misses"] - before["misses"],
        "backend_compiles_during_load": cw.backend_compiles,
        "post_ok": post_ok,
        "exact_check": exact_check,
        **{k: v for k, v in report.items()
           if k not in ("digest",) or args.digest},
    }
    _emit(lines, {"serving_compare": compare})

    summary = {
        "bench": "serving",
        "requests": args.requests,
        "offered_rate": args.rate,
        "streams": args.streams,
        "endpoints": sorted(eps),
        "max_batch": args.max_batch,
        "achieved_qps": report["achieved_qps"],
        "p99_s": report["latency"].get("p99_s"),
        "serve_exact_mode": not gemm_mode,
        "exact_check": {k: v for k, v in exact_check.items()},
        "on_chip": on_chip,
        "cpu_fallback": cpu_fallback,
        "devices": {"count": len(devs), "kind": devs[0].device_kind},
        "server": server.stats(),
    }
    if telemetry.enabled():
        telemetry.memory.watermark("post_load")
        summary.update(telemetry.report.bench_fields())
    _emit(lines, summary)
    server.close()

    if args.artifact:
        with open(args.artifact, "a") as f:
            for obj in lines:
                f.write(json.dumps(obj) + "\n")


if __name__ == "__main__":
    main()
