#!/usr/bin/env python
"""Cluster observability driver: the ISSUE 17 end-to-end demo and CI
gate (2-replica pool + router under open-loop load).

Three phases, one JSONL line each, plus a final ``{"bench":
"cluster_obs"}`` summary the run_ci.sh checker asserts on:

* ``{"phase_off": ...}`` — tracing OFF (``HEAT_TPU_TRACE_REQUESTS=0``
  fleet-wide): the reference digest, plus every replica's ``/metrics``
  tracing counters (must be 0/0 — the off posture does no per-hop work)
  and the fleet-merge totals (merged per-endpoint requests must equal
  the loadgen completions exactly);
* ``{"phase_on": ...}`` — tracing ON at sample rate 1.0: the SAME seeded
  schedule must produce a BIT-IDENTICAL digest (tracing never touches
  payloads); every sampled request's trace id must appear on the full
  hop chain ``router.queue → router.post → serve.queue → serve.coalesce
  → serve.pad → serve.execute → serve.reply`` across the router's own
  events plus the scraped replica ``/trace`` events; the merged Perfetto
  export must carry one pid track per process (each with its explicit
  ``clock_sync`` record); and an in-process control run pins the
  merge-plumbing exactness — ``summarize_cluster`` over one scrape
  reproduces the server's own per-endpoint p99 bit-for-bit, while the
  pool's merged (server-side) p99 must sit within one histogram bucket
  width of the router's client-observed p99;
* ``{"phase_slo": ...}`` — the resilience injector adds
  ``--fault-delay`` seconds of latency to every replica-side program
  execution while the router declares a ``--slo-p99`` objective the
  delayed fleet cannot meet: the windowed burn rate must exceed the
  threshold and ``Router.check_slos()`` must emit ``slo_burn`` events
  (the paired ``serve_net.slo_burns`` counter proves it).

``--artifact PATH`` appends the emitted lines. Replicas always run
virtual CPU meshes (an accelerator cannot be shared across processes),
so every number here is a CPU number by construction.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import numpy as np

from benchmarks._harness import base_parser, bootstrap


def add_args(p):
    p.add_argument("--requests", type=int, default=80,
                   help="requests per load phase (the same seeded "
                        "schedule for off and on)")
    p.add_argument("--rate", type=float, default=120.0,
                   help="offered Poisson arrival rate, requests/second")
    p.add_argument("--streams", type=int, default=2,
                   help="concurrent loadgen submitter threads")
    p.add_argument("--endpoints", default="cdist,dense",
                   help="comma-separated endpoint subset")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--replica-mesh", type=int, default=4,
                   help="virtual CPU mesh size of every replica process")
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--wait-ms", type=float, default=2.0)
    p.add_argument("--queue-max", type=int, default=256)
    p.add_argument("--slo-requests", type=int, default=24,
                   help="requests in the SLO burn phase")
    p.add_argument("--slo-rate", type=float, default=30.0)
    p.add_argument("--slo-p99", type=float, default=0.05,
                   help="the deliberately-unmeetable p99 objective of "
                        "the burn phase")
    p.add_argument("--fault-delay", type=float, default=0.25,
                   help="injected per-execution latency (seconds) that "
                        "drives the SLO breach")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--workdir", default=None)
    p.add_argument("--artifact", default=None,
                   help="append the emitted JSONL lines to this file")


def _emit(lines, obj):
    print(json.dumps(obj), flush=True)
    lines.append(obj)


def _pool_env(args, workdir, extra=None):
    env = {
        "HEAT_TPU_COMPILE_CACHE": os.path.join(workdir, "xla_cache"),
        "HEAT_TPU_SERVE_MAX_BATCH": str(args.max_batch),
        "HEAT_TPU_SERVE_MAX_WAIT_MS": str(args.wait_ms),
        "HEAT_TPU_SERVE_QUEUE_MAX": str(args.queue_max),
        "HEAT_TPU_TELEMETRY": "1",
    }
    env.update(extra or {})
    return env


def _tracing_counters(scrapes):
    """Per-url ``(sampled, spans)`` out of ``/metrics`` scrapes."""
    out = {}
    for url, payload in scrapes.items():
        c = (payload or {}).get("counters", {}) or {}
        out[url] = {
            "sampled": int(c.get("tracing.sampled", 0)),
            "spans": int(c.get("tracing.spans", 0)),
        }
    return out


def _hop_completeness(router_events, scraped_traces):
    """For every ingress-sampled trace id, which of the seven canonical
    hops carry it (membership via the batch ``trace_ids`` lists too).
    Returns (ids, complete_ids, per-hop span counts)."""
    from heat_tpu.serve import tracing

    events = list(router_events)
    for payload in scraped_traces.values():
        events.extend((payload or {}).get("events", []) or [])
    spans = [e for e in events if e.get("kind") == "trace_span"]
    ids = sorted({
        e["trace_id"] for e in spans
        if e.get("ingress") and e.get("name") == "router.queue"
    })
    by_hop = {name: set() for name in tracing.HOPS}
    counts = {name: 0 for name in tracing.HOPS}
    for e in spans:
        name = e.get("name")
        if name in by_hop:
            counts[name] += 1
            by_hop[name].update(tracing.span_trace_ids(e))
    complete = [
        t for t in ids if all(t in by_hop[h] for h in tracing.HOPS)
    ]
    return ids, complete, counts


def main():
    p = base_parser("heat_tpu cluster observability driver (merged "
                    "tracing + fleet metrics + SLO burn; the ISSUE 17 "
                    "CI gate)")
    add_args(p)
    args = p.parse_args()
    ht = bootstrap(args)

    from benchmarks.serving import loadgen
    from benchmarks.serving.heat_tpu import build_endpoints
    from heat_tpu import telemetry
    from heat_tpu.serve import metrics as serve_metrics
    from heat_tpu.serve.net import ReplicaPool, Router
    from heat_tpu.telemetry.cluster import SLO, summarize_cluster

    lines = []
    names = [s.strip() for s in args.endpoints.split(",") if s.strip()]

    workdir = args.workdir or tempfile.mkdtemp(prefix="heat_tpu_clobs_")
    os.makedirs(workdir, exist_ok=True)
    ckpt = os.path.join(workdir, "endpoints.ckpt")

    eps = build_endpoints(ht, args, [n for n in names if n != "cdist"])
    if "cdist" in names:
        rng = np.random.default_rng(args.seed)
        eps["cdist"] = ht.serve.cdist_query(
            rng.standard_normal((128, args.features)).astype(np.float32)
        )
    server = ht.serve.Server()
    for name, ep in eps.items():
        server.register(name, ep)
    server.save(ckpt)
    server.close()

    features = {n: eps[n].features for n in eps}
    dtypes = {n: eps[n].dtype for n in eps}
    reqs = loadgen.make_requests(
        features, args.requests, args.seed, max_rows=1, dtypes=dtypes,
    )

    # the driver hosts the router, so its own tracing posture is staged
    # through the same env the replicas get (benchmark-runner env
    # staging, not a knob read)
    sink = os.path.join(workdir, "driver_events.jsonl")
    reg = telemetry.enable(sink)
    reg.clear()

    def _run_pool(extra_env, slos=None, requests=None, rate=None,
                  log_name="pool"):
        pool = ReplicaPool(
            ckpt, args.replicas, mesh=args.replica_mesh,
            env=_pool_env(args, workdir, extra_env),
            log_dir=os.path.join(workdir, f"logs_{log_name}"),
        ).start()
        router = Router(pool, workers=8, slos=slos)
        report = loadgen.run_open_loop(
            router, requests if requests is not None else reqs,
            rate if rate is not None else args.rate,
            seed=args.seed, streams=args.streams,
        )
        return pool, router, report

    # -- phase A: tracing OFF -------------------------------------------------
    os.environ["HEAT_TPU_TRACE_REQUESTS"] = "0"
    pool, router, rep_off = _run_pool(
        {"HEAT_TPU_TRACE_REQUESTS": "0"}, log_name="off"
    )
    try:
        scrapes = router.scrape_metrics()
        merged_off = summarize_cluster(scrapes)
        phase_off = {
            "digest": rep_off["digest"],
            "completed": rep_off["completed"],
            "failed": rep_off["failed"],
            "shed": rep_off["shed"],
            "replica_tracing": _tracing_counters(scrapes),
            "driver_tracing": {
                "sampled": int(reg.counters.get("tracing.sampled", 0)),
                "spans": int(reg.counters.get("tracing.spans", 0)),
            },
            "merged_requests_total": sum(
                ep["requests"] for ep in merged_off["endpoints"].values()
            ),
            "scrape_failures": merged_off["scrape_failures"],
        }
        _emit(lines, {"phase_off": phase_off})
    finally:
        router.close()
        pool.close()

    # -- phase B: tracing ON, sample 1.0 --------------------------------------
    os.environ["HEAT_TPU_TRACE_REQUESTS"] = "1"
    os.environ["HEAT_TPU_TRACE_SAMPLE"] = "1.0"
    reg.clear()
    pool, router, rep_on = _run_pool(
        {"HEAT_TPU_TRACE_REQUESTS": "1", "HEAT_TPU_TRACE_SAMPLE": "1.0"},
        log_name="on",
    )
    try:
        time.sleep(0.3)  # let the last batch's reply hop land
        summary = router.cluster_summary()
        traces = router.scrape_traces()
        sync = router.clock_sync()
        ids, complete, hop_counts = _hop_completeness(reg.events, traces)
        trace_path = os.path.join(workdir, "merged_trace.json")
        router.export_cluster_trace(trace_path)
        doc = json.load(open(trace_path))
        pids = {e["pid"] for e in doc["traceEvents"]}
        sync_pids = {e["pid"] for e in doc["traceEvents"]
                     if e.get("cat") == "clock_sync"}

        # merged (server-side) p99 vs the router's client-observed p99:
        # the server histogram must sit within ~one bucket width BELOW
        # the client number (client = server + wire + router queue)
        growth = serve_metrics._GROWTH
        p99 = {}
        for name, ep in summary["endpoints"].items():
            client = (rep_on["per_endpoint"].get(name) or {}).get("p99_s")
            merged = ep["latency"].get("p99_s")
            p99[name] = {
                "merged_s": merged,
                "client_s": client,
                "within_bucket_of_client": bool(
                    merged and client
                    and merged <= client * growth * 1.05
                ),
            }

        # in-process control: one scrape through the merge plumbing must
        # reproduce the server's own per-endpoint p99 EXACTLY (raw
        # buckets -> wire JSON -> merge -> quantile is lossless)
        direct = ht.serve.Server.restore(ckpt)
        direct.warmup()
        rep_direct = loadgen.run_open_loop(
            direct, reqs, args.rate, seed=args.seed, streams=args.streams,
        )
        m = json.loads(json.dumps(direct.metrics()))
        direct.close()
        s_inproc = summarize_cluster({"inproc": m})
        p99_exact = all(
            round(s_inproc["endpoints"][n]["latency"]["p99_s"], 6)
            == (rep_direct["per_endpoint"][n] or {}).get("p99_s")
            for n in s_inproc["endpoints"]
        )

        phase_on = {
            "digest": rep_on["digest"],
            "digest_match_off": rep_on["digest"] == rep_off["digest"],
            "completed": rep_on["completed"],
            "failed": rep_on["failed"],
            "shed": rep_on["shed"],
            "sampled_ids": len(ids),
            "complete_ids": len(complete),
            "hop_span_counts": hop_counts,
            "replica_tracing": _tracing_counters(router.scrape_metrics()),
            "merged_requests_total": sum(
                ep["requests"] for ep in summary["endpoints"].values()
            ),
            "fleet_qps": {
                n: ep["qps"] for n, ep in summary["endpoints"].items()
            },
            "p99": p99,
            "p99_exact_match_inproc": p99_exact,
            "clock_sync": {
                url: {"offset_s": round(s["offset"], 6),
                      "uncertainty_s": round(s["uncertainty"], 6)}
                for url, s in sync.items()
            },
            "merged_trace": {
                "path": trace_path,
                "pids": len(pids),
                "clock_sync_tracks": len(sync_pids),
                "trace_spans": sum(
                    1 for e in doc["traceEvents"]
                    if e.get("cat") == "trace_span"
                ),
            },
        }
        _emit(lines, {"phase_on": phase_on})
    finally:
        router.close()
        pool.close()

    # -- phase C: injected latency drives SLO burn ----------------------------
    reg.clear()
    slo_reqs = loadgen.make_requests(
        {"cdist": features.get("cdist", args.features)},
        args.slo_requests, args.seed + 2, max_rows=1,
    )
    fault = (f"serve.*:kind=latency:delay={args.fault_delay}:p=1.0"
             f":seed={args.seed}")
    pool, router, rep_slo = _run_pool(
        {"HEAT_TPU_TRACE_REQUESTS": "1", "HEAT_TPU_TRACE_SAMPLE": "1.0",
         "HEAT_TPU_FAULTS": fault},
        slos=[SLO("cdist", p99_s=args.slo_p99, availability=0.999)],
        requests=slo_reqs, rate=args.slo_rate, log_name="slo",
    )
    try:
        rows = router.check_slos()
        burn_events = [
            e for e in reg.events
            if e.get("kind") == "serve_net" and e.get("event") == "slo_burn"
        ]
        cdist_row = next(
            (r for r in rows if r["endpoint"] == "cdist"), {}
        )
        phase_slo = {
            "fault": fault,
            "completed": rep_slo["completed"],
            "failed": rep_slo["failed"],
            "shed": rep_slo["shed"],
            "slo": rows,
            "burn_rate": cdist_row.get("burn_rate"),
            "breach": cdist_row.get("breach"),
            "slo_burn_events": len(burn_events),
            "slo_burns_counter": int(
                reg.counters.get("serve_net.slo_burns", 0)
            ),
        }
        _emit(lines, {"phase_slo": phase_slo})
    finally:
        router.close()
        pool.close()

    summary_line = {
        "bench": "cluster_obs",
        "requests": args.requests,
        "offered_rate": args.rate,
        "replicas": args.replicas,
        "endpoints": sorted(eps),
        "off_tracing_zero": all(
            c == {"sampled": 0, "spans": 0}
            for c in phase_off["replica_tracing"].values()
        ) and phase_off["driver_tracing"] == {"sampled": 0, "spans": 0},
        "off_clean": rep_off["failed"] == 0 and rep_off["shed"] == 0,
        "on_clean": rep_on["failed"] == 0 and rep_on["shed"] == 0,
        "digest_match": phase_on["digest_match_off"],
        "metrics_merge_match": (
            phase_off["merged_requests_total"] == rep_off["completed"]
            and phase_on["merged_requests_total"] == rep_on["completed"]
        ),
        "sampled_ids": phase_on["sampled_ids"],
        "complete_ids": phase_on["complete_ids"],
        "hops_complete": (
            phase_on["sampled_ids"] > 0
            and phase_on["complete_ids"] == phase_on["sampled_ids"]
        ),
        "p99_within_bucket": all(
            v["within_bucket_of_client"]
            for v in phase_on["p99"].values()
        ),
        "p99_exact_match_inproc": phase_on["p99_exact_match_inproc"],
        "merged_trace_ok": (
            phase_on["merged_trace"]["pids"] >= 1 + args.replicas
            and phase_on["merged_trace"]["clock_sync_tracks"]
            == phase_on["merged_trace"]["pids"]
            and phase_on["merged_trace"]["trace_spans"] > 0
        ),
        "slo_breach": bool(phase_slo["breach"]),
        "slo_burn_emitted": phase_slo["slo_burn_events"] >= 1
        and phase_slo["slo_burns_counter"] >= 1,
        "on_chip": False,
        "cpu_fallback": "replica processes run on virtual cpu meshes "
                        "(an attached accelerator cannot be shared "
                        "across replica processes)",
    }
    _emit(lines, summary_line)
    telemetry.disable()

    if args.artifact:
        with open(args.artifact, "a") as f:
            for obj in lines:
                f.write(json.dumps(obj) + "\n")


if __name__ == "__main__":
    main()
