"""Open-loop Poisson-arrival load generator for ``heat_tpu.serve``.

Open loop means the arrival process does **not** wait for completions
(the schedule is fixed before the run): unlike closed-loop "submit,
wait, repeat" drivers, latency degradation cannot throttle the offered
rate, so queueing collapse is *visible* instead of silently self-limited
— the standard methodology for serving benchmarks. Arrivals are
exponential inter-arrival times (Poisson process) from a seeded RNG, so
a run is fully reproducible: same seed → same schedule, same payloads,
same per-request answers (batching composition may differ run to run,
but in exact serving mode answers are batch-composition-independent —
that is what makes the digest below a meaningful bit-identity oracle).

Used by ``benchmarks/serving/heat_tpu.py`` (the committed-artifact
runner), the CI serving gate (scripts/run_ci.sh), and tests.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["poisson_schedule", "make_requests", "run_open_loop"]


def poisson_schedule(n: int, rate: float, seed: int = 0) -> np.ndarray:
    """``n`` arrival offsets (seconds from start) of a Poisson process
    with ``rate`` arrivals/second."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate, size=n)
    return np.cumsum(gaps)


def make_requests(
    endpoints: Dict[str, int],
    n: int,
    seed: int = 0,
    *,
    max_rows: int = 4,
    dtypes: Optional[Dict[str, np.dtype]] = None,
) -> List[Tuple[str, np.ndarray]]:
    """``n`` deterministic (endpoint, payload) pairs round-robined over
    ``endpoints`` (name → feature count). Row counts cycle 1..max_rows,
    payloads are seeded standard normals — request ``i`` is identical
    across runs and processes."""
    names = sorted(endpoints)
    rng = np.random.default_rng(seed)
    out: List[Tuple[str, np.ndarray]] = []
    for i in range(n):
        name = names[i % len(names)]
        rows = 1 + (i // len(names)) % max_rows
        dt = (dtypes or {}).get(name, np.float32)
        payload = rng.standard_normal((rows, endpoints[name])).astype(dt)
        out.append((name, payload))
    return out


def run_open_loop(
    server,
    requests: Sequence[Tuple[str, np.ndarray]],
    rate: float,
    *,
    seed: int = 0,
    streams: int = 2,
    timeout: float = 60.0,
) -> dict:
    """Drive ``requests`` at ``rate``/s total over ``streams`` concurrent
    submitter threads (each owning an interleaved slice of the one
    global schedule), then gather every future.

    Returns a report dict::

        {"requests", "failed", "shed", "errors": [repr...],
         "offered_rate", "achieved_qps", "wall_seconds",
         "latency": {"p50_s", "p95_s", "p99_s", "mean_s", "max_s"},
         "per_endpoint": {name: {"requests", "failed", "p99_s", ...}},
         "digest": sha256-hex over successful responses in request order}

    ``achieved_qps`` counts completed (non-shed, non-failed) requests
    over the first-submit → last-completion wall window. The digest
    covers (endpoint, request index, response bytes) for every
    *successful* request — bit-stable across batching compositions in
    exact serving mode, which is what the CI chaos comparison pins.
    """
    from heat_tpu.serve import ServerOverloadedError

    n = len(requests)
    sched = poisson_schedule(n, rate, seed)
    futures: List[Optional[object]] = [None] * n
    shed_errors: List[Optional[str]] = [None] * n
    submit_errors: List[Optional[str]] = [None] * n
    t0 = time.perf_counter()

    def submitter(stream: int) -> None:
        for i in range(stream, n, streams):
            name, payload = requests[i]
            delay = t0 + sched[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            try:
                futures[i] = server.submit(name, payload)
            except ServerOverloadedError as e:
                shed_errors[i] = repr(e)
            except Exception as e:  # noqa: BLE001 — a dead submitter
                # stream must surface as FAILED requests, never as
                # silent sheds (the CI clean gate checks failed==0)
                submit_errors[i] = repr(e)

    threads = [
        threading.Thread(target=submitter, args=(s,), daemon=True)
        for s in range(streams)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    per_ep: Dict[str, dict] = {}
    errors: List[str] = []
    digest = hashlib.sha256()
    shed = failed = 0
    deadline = time.monotonic() + timeout
    for i, (name, _payload) in enumerate(requests):
        row = per_ep.setdefault(
            name, {"requests": 0, "failed": 0, "shed": 0}
        )
        row["requests"] += 1
        if futures[i] is None:
            if submit_errors[i] is not None:
                failed += 1
                row["failed"] += 1
                errors.append(f"request {i} ({name}): {submit_errors[i]}")
            else:
                shed += 1
                row["shed"] += 1
            continue
        try:
            out = futures[i].result(max(0.001, deadline - time.monotonic()))
        except ServerOverloadedError:
            # a 503 resolved THROUGH the future (the router learns a
            # request was shed only after offering it to every sibling,
            # unlike the in-process server's synchronous admission gate)
            # is still a shed, not a failure
            shed += 1
            row["shed"] += 1
            continue
        except Exception as e:  # noqa: BLE001 — a failed request is data
            failed += 1
            row["failed"] += 1
            errors.append(f"request {i} ({name}): {e!r}")
            continue
        digest.update(name.encode())
        digest.update(str(i).encode())
        digest.update(np.ascontiguousarray(out).tobytes())
    wall = time.perf_counter() - t0

    # latency from the server's own per-endpoint histograms (submit →
    # future resolution, recorded by the batcher thread); the loadgen
    # adds the offered-vs-achieved arithmetic on top. The overall row is
    # conservative: worst per-endpoint percentile, count-weighted mean.
    stats = server.stats()["endpoints"]
    counts = 0
    worst = {"p50_s": 0.0, "p95_s": 0.0, "p99_s": 0.0, "mean_s": 0.0,
             "max_s": 0.0}
    for name, srow in stats.items():
        lat = srow.get("latency", {})
        c = lat.get("count", 0)
        if name in per_ep:
            for k in ("p50_s", "p95_s", "p99_s", "mean_s", "max_s"):
                if k in lat:
                    per_ep[name][k] = round(lat[k], 6)
        if not c:
            continue
        counts += c
        worst["mean_s"] += lat.get("mean_s", 0.0) * c
        for k in ("p50_s", "p95_s", "p99_s", "max_s"):
            worst[k] = max(worst[k], lat.get(k, 0.0) or 0.0)
    if counts:
        worst["mean_s"] = round(worst["mean_s"] / counts, 6)
        for k in ("p50_s", "p95_s", "p99_s", "max_s"):
            worst[k] = round(worst[k], 6)
    ok = n - shed - failed
    return {
        "requests": n,
        "completed": ok,
        "failed": failed,
        "shed": shed,
        "errors": errors[:8],
        "offered_rate": rate,
        "achieved_qps": round(ok / wall, 2) if wall > 0 else 0.0,
        "wall_seconds": round(wall, 4),
        "latency": worst if counts else {},
        "per_endpoint": per_ep,
        "digest": digest.hexdigest(),
    }
