#!/usr/bin/env python
"""Lasso scaling benchmark (reference: benchmarks/lasso/config.json —
coordinate descent on eurad h5, 1e7 samples strong scaling). The whole
fit is ONE compiled dispatch (lax.while_loop over epochs,
regression/lasso.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import load_or_make, run


def add_args(p):
    p.add_argument("--sweeps", type=int, default=100)
    p.add_argument("--lam", type=float, default=0.01)


def build(ht, args):
    x = load_or_make(ht, args, split=0)
    y = ht.matmul(x, ht.random.randn(x.shape[1], 1, dtype=x.dtype))
    return x, y


def fit_factory(ht, args, operands):
    x, y = operands

    def fit():
        est = ht.regression.Lasso(lam=args.lam, max_iter=args.sweeps,
                                  tol=0.0)
        est.fit(x, y)
        return est.theta

    def sync(theta):
        return float(theta.larray.reshape(-1)[0])

    return fit, sync


if __name__ == "__main__":
    run("heat_tpu lasso scaling benchmark", add_args, build, fit_factory)
