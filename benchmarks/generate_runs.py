#!/usr/bin/env python
"""Enumerate the scaling sweep into a shell script — the TPU-native
analog of the reference's SLURM jobscript generator
(benchmarks/generate_jobscripts.py:12-50). No scheduler is assumed: each
line is a plain `python` invocation (mesh forcing happens in-process via
the runner's ``--mesh`` flag, benchmarks/_harness.bootstrap); on a
SLURM-fronted pod the same lines drop into srun wrappers.
"""

from __future__ import annotations

import argparse
import json
import os
import shlex

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
ALGOS = ("kmeans", "distance_matrix", "statistical_moments", "lasso",
         "resplit", "elementwise", "reduction", "serving", "sparse",
         "hierarchy")


def _param_flags(params: dict) -> list[str]:
    # config "params" keys map 1:1 to runner flags; sizes map to --n
    out = []
    for k, v in params.items():
        out += [f"--{k}", str(v)]
    return out


def enumerate_runs(algos=ALGOS, python="python3"):
    """Yield (algo, benchmark, mode, mesh, n, argv) for every scale point.

    ``python`` is the interpreter token emitted into each line — plain
    ``python3`` by default so the generated script runs on any host/venv,
    including python3-only boxes with no ``python`` alias (baking
    ``sys.executable`` in tied the sweep to the generating machine's
    interpreter path — advisor round-5 finding)."""
    for algo in algos:
        cfg_path = os.path.join(HERE, algo, "config.json")
        with open(cfg_path) as f:
            cfg = json.load(f)
        runner = os.path.join("benchmarks", algo, cfg["runner"])
        base = _param_flags(cfg.get("params", {}))
        base += ["--trials", str(cfg.get("trials", 3))]
        for name, bench in cfg["benchmarks"].items():
            meshes = bench["mesh"]
            strong = bench["size"]["strong"]
            weak = bench["size"]["weak"]
            if len(weak) not in (1, len(meshes)):
                raise ValueError(
                    f"{algo}/{name}: weak sizes must match the mesh list "
                    f"({len(weak)} vs {len(meshes)})"
                )
            for i, mesh in enumerate(meshes):
                w = weak[i] if len(weak) == len(meshes) else weak[0]
                points = [("strong", strong)]
                if w == strong:
                    # identical argv — tag one run with both modes instead
                    # of re-running a multi-minute scale point for no data
                    points = [("strong+weak", strong)]
                else:
                    points.append(("weak", w))
                for mode, n in points:
                    argv = [python, runner,
                            "--n", str(n), "--mesh", str(mesh)] + base
                    yield algo, name, mode, mesh, n, argv


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="runs.sh")
    ap.add_argument("--algos", default=",".join(ALGOS),
                    help="comma-separated subset")
    ap.add_argument("--python", default="python3",
                    help="interpreter emitted into the script (default: "
                         "plain `python3`, resolved by the executing host's "
                         "environment; pass an absolute path to pin one)")
    args = ap.parse_args()
    algos = [a.strip() for a in args.algos.split(",") if a.strip()]
    for a in algos:
        if a not in ALGOS:
            raise SystemExit(f"unknown algorithm {a!r}; choose from {ALGOS}")

    lines = ["#!/bin/bash", "set -e", f"cd {shlex.quote(REPO)}"]
    count = 0
    for algo, name, mode, mesh, n, argv in enumerate_runs(algos, args.python):
        tag = f"{algo}/{name} {mode} mesh={mesh} n={n}"
        lines.append(f"echo '=== {tag} ==='")
        lines.append(" ".join(shlex.quote(a) for a in argv))
        count += 1
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.chmod(args.out, 0o755)
    print(f"wrote {args.out}: {count} scale points over {len(algos)} "
          "algorithms")


if __name__ == "__main__":
    main()
