#!/usr/bin/env python
"""KMeans scaling benchmark (reference: benchmarks/kmeans/heat-gpu.py,
config.json — cityscapes h5, 8 clusters, 30 iterations, 10 trials).
On TPU the fit dispatches the fused Pallas Lloyd kernel when applicable
(cluster/pallas_lloyd.py); elsewhere the one-program XLA lax.while_loop
fit."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks._harness import load_or_make, run


def add_args(p):
    p.add_argument("--clusters", type=int, default=8)
    p.add_argument("--iterations", type=int, default=30)


def build(ht, args):
    return load_or_make(ht, args, split=0)


def fit_factory(ht, args, data):
    def fit():
        km = ht.cluster.KMeans(
            n_clusters=args.clusters, init="random",
            max_iter=args.iterations, tol=0.0, random_state=1,
        )
        km.fit(data)
        return km.cluster_centers_

    def sync(centers):
        return float(centers.larray[0, 0])

    return fit, sync


if __name__ == "__main__":
    run("heat_tpu KMeans scaling benchmark", add_args, build, fit_factory)
