#!/usr/bin/env bash
# CI sweep (reference: Jenkinsfile:19-27 runs the whole suite under
# `mpirun -n {1..8}`). The TPU-native analog re-runs the suite over virtual
# CPU meshes of several sizes — divisible and ragged — so every sharding
# path is exercised at every world size.
set -euo pipefail
cd "$(dirname "$0")/.."

for n in 1 2 3 5 8; do
    echo "=== suite @ ${n} virtual devices ==="
    HEAT_TPU_TEST_DEVICES=$n python -m pytest tests/ -q -p no:cacheprovider
done
echo "=== all device counts green ==="
