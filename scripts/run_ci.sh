#!/usr/bin/env bash
# CI sweep (reference: Jenkinsfile:19-27 runs the whole suite under
# `mpirun -n {1..8}` with coverage, then merges the per-size coverage files
# and archives junit XML, Jenkinsfile:33-44). The TPU-native analog re-runs
# the suite over virtual CPU meshes of several sizes — divisible and ragged
# — so every sharding path is exercised at every world size.
#
# Usage:
#   scripts/run_ci.sh                 # plain sweep (1 2 3 5 8)
#   CI_REPORT_DIR=out scripts/run_ci.sh
#       # + junit XML per device count (out/junit_<n>.xml) and, when the
#       # `coverage` module is available, per-size coverage data merged
#       # into one report (out/coverage.txt) — the Jenkinsfile analog
#   HEAT_TPU_CI_SIZES="2 8" scripts/run_ci.sh   # custom size list
#   HEAT_TPU_CI_CHUNKS=4 scripts/run_ci.sh
#       # run each size's suite in N fresh-process chunks of test files —
#       # bounds accumulated XLA state (a 3-device full pass aborts flakily
#       # inside XLA after ~300 tests in one process on this host)
set -euo pipefail
cd "$(dirname "$0")/.."

# optional-I/O gate check (VERDICT r4 weak 7): the HDF5/NetCDF suites skip
# silently when their backends are missing — in CI that silence is a lie,
# so fail loudly up front instead. HEAT_TPU_CI_ALLOW_MISSING_IO=1 opts out
# for deliberately minimal environments.
if [ -z "${HEAT_TPU_CI_ALLOW_MISSING_IO:-}" ]; then
    JAX_PLATFORMS=cpu python - <<'EOF'
import os
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import jax; jax.config.update("jax_platforms", "cpu")
import heat_tpu as ht
missing = [name for name, ok in (
    ("hdf5 (h5py)", ht.supports_hdf5()),
    ("netcdf (netCDF4 or scipy)", ht.supports_netcdf()),
) if not ok]
if missing:
    raise SystemExit(
        "CI env is missing optional I/O backends: " + ", ".join(missing)
        + " - their test suites would silently skip. Install the backend "
        "or set HEAT_TPU_CI_ALLOW_MISSING_IO=1."
    )
print("I/O backends present: hdf5 + netcdf")
EOF
fi

SIZES=${HEAT_TPU_CI_SIZES:-"1 2 3 5 8"}
REPORT=${CI_REPORT_DIR:-}

# heatlint gate (ISSUE 10): the static analyzer enforces the dispatch /
# collective / precision / knob invariants (docs/STATIC_ANALYSIS.md) over
# the package, benchmarks, examples, driver, and scripts. It runs FIRST —
# an invariant regression fails in seconds, before any suite compiles.
# Passes on the committed baseline (.heatlint-baseline.json) and inline
# suppressions; fails on any NEW finding. HEAT_TPU_CI_SKIP_HEATLINT=1
# opts out.
HEATLINT_FAILED=""
if [ -z "${HEAT_TPU_CI_SKIP_HEATLINT:-}" ]; then
    echo "=== heatlint static-analysis gate ==="
    heatlint_out=$(mktemp)
    if JAX_PLATFORMS=cpu python -m heat_tpu.analysis \
            heat_tpu benchmarks examples bench.py scripts \
            | tee "$heatlint_out"; then
        echo "=== heatlint gate ok ==="
    else
        echo "=== heatlint gate FAILED — new invariant violations above ==="
        HEATLINT_FAILED=" heatlint"
    fi
    if [ -n "$REPORT" ]; then
        mkdir -p "$REPORT"
        cp "$heatlint_out" "${REPORT}/heatlint.log" || true
    fi
    rm -f "$heatlint_out"
fi

# Persistent XLA compile cache shared across the whole sweep (ISSUE 3): the
# suite is compile-bound, and retried chunks / repeated sizes / the per-
# module jax.clear_caches() in conftest all recompile programs a previous
# process already built. One on-disk cache makes those backend compiles a
# deserialization. HEAT_TPU_CI_NO_COMPILE_CACHE=1 opts out (e.g. to measure
# true cold-compile time).
if [ -z "${HEAT_TPU_CI_NO_COMPILE_CACHE:-}" ]; then
    if [ -z "${HEAT_TPU_COMPILE_CACHE:-}" ]; then
        # we created it, we clean it up — a caller-provided cache dir is
        # theirs to keep (that is the cross-run reuse case)
        export HEAT_TPU_COMPILE_CACHE=$(mktemp -d -t heat_tpu_cc.XXXXXX)
        OWN_COMPILE_CACHE=$HEAT_TPU_COMPILE_CACHE
        trap '[ -n "${OWN_COMPILE_CACHE:-}" ] && rm -rf "$OWN_COMPILE_CACHE"' EXIT
    fi
    echo "=== persistent compile cache: ${HEAT_TPU_COMPILE_CACHE} ==="
fi

have_coverage=0
if [ -n "$REPORT" ]; then
    mkdir -p "$REPORT"
    # drop artifacts of previous (possibly aborted or differently-sized)
    # runs so the merge below only sees this sweep's data
    rm -f "$REPORT"/.coverage* "$REPORT"/junit_*.xml "$REPORT"/coverage.txt \
        "$REPORT"/resilience_report.log
    if python -c "import coverage" 2>/dev/null; then
        have_coverage=1
    fi
fi

CHUNKS=${HEAT_TPU_CI_CHUNKS:-1}
FAILED_SIZES=""
RETRIED_ABORTS=""

# Unified resilience report (ISSUE 5): every fault-tolerance event of the
# sweep — retried SIGABRT chunks, chaos-step verdicts — lands here in one
# `<utc-ts> kind=<what> key=value...` line format, archived to
# ${REPORT}/resilience_report.log when a report dir is set.
log_resilience() {
    local line="$(date -u +%FT%TZ) $*"
    echo "$line"
    if [ -n "$REPORT" ]; then
        echo "$line" >> "${REPORT}/resilience_report.log"
    fi
}

# entries in the persistent compile cache (each "-cache" file is one XLA
# executable some process had to backend-compile)
cc_count() {
    if [ -n "${HEAT_TPU_COMPILE_CACHE:-}" ] && [ -d "${HEAT_TPU_COMPILE_CACHE}" ]; then
        ls "${HEAT_TPU_COMPILE_CACHE}" 2>/dev/null | grep -c -- '-cache$' || true
    else
        echo 0
    fi
}

for n in $SIZES; do
    echo "=== suite @ ${n} virtual devices (${CHUNKS} chunk(s)) ==="
    cc_before=$(cc_count)
    rc=0
    ran_chunks=0
    for ((k = 0; k < CHUNKS; k++)); do
        # round-robin test files into chunks; each chunk is a fresh process
        mapfile -t files < <(ls tests/test_*.py | awk -v k=$k -v c=$CHUNKS 'NR % c == k')
        [ ${#files[@]} -eq 0 ] && continue
        args=(-q -p no:cacheprovider)
        if [ -n "$REPORT" ]; then
            if [ "$CHUNKS" = 1 ]; then
                args+=("--junitxml=${REPORT}/junit_${n}.xml")
            else
                args+=("--junitxml=${REPORT}/junit_${n}_${k}.xml")
            fi
        fi
        # rc 134 = SIGABRT: the XLA CPU client nondeterministically
        # corrupts the glibc heap on this host ("corrupted size vs.
        # prev_size", seen ONLY on odd virtual-mesh sizes; the abort
        # detonates at an arbitrary LATER allocation, so it is not a
        # test failure). A fresh process gets a fresh heap layout —
        # retry an aborted chunk once, but ONLY in the known flake
        # configuration (odd size): an abort at an even size is a new
        # native crash and must fail loudly, not be masked. Every retry
        # is recorded (stdout + ${REPORT}/resilience_report.log) so a
        # rising abort rate stays visible in the archived artifacts.
        for attempt in 1 2; do
            crc=0
            if [ "$have_coverage" = 1 ]; then
                HEAT_TPU_TEST_DEVICES=$n COVERAGE_FILE="${REPORT}/.coverage.${n}.${k}" \
                    python -m coverage run --source=heat_tpu -m pytest "${files[@]}" "${args[@]}" || crc=$?
            else
                HEAT_TPU_TEST_DEVICES=$n python -m pytest "${files[@]}" "${args[@]}" || crc=$?
            fi
            [ "$crc" != 134 ] && break
            if [ $((n % 2)) -eq 0 ]; then
                echo "=== chunk ${k} aborted (SIGABRT) at EVEN size ${n} — outside the known flake scope, NOT retrying ==="
                break
            fi
            [ "$attempt" = 2 ] && break
            RETRIED_ABORTS="$RETRIED_ABORTS size=${n}/chunk=${k}"
            log_resilience "kind=sigabrt-retry size=${n} chunk=${k} attempt=${attempt} rc=134 note=known-xla-cpu-heap-flake"
            echo "=== chunk ${k} aborted (SIGABRT, known XLA CPU heap flake at odd size ${n}) — retrying once ==="
        done
        # pytest rc 5 = no tests collected in this chunk — not a failure
        # on its own, but at least one chunk must actually run tests
        if [ "$crc" = 0 ]; then
            ran_chunks=$((ran_chunks + 1))
        elif [ "$crc" != 5 ]; then
            rc=$crc
        fi
    done
    if [ "$ran_chunks" = 0 ] && [ "$rc" = 0 ]; then
        echo "=== suite @ ${n} devices ran NO tests — failing the size ==="
        rc=2
    fi
    if [ -n "${HEAT_TPU_COMPILE_CACHE:-}" ]; then
        cc_after=$(cc_count)
        echo "=== compile-count @ ${n} devices: $((cc_after - cc_before)) new XLA executables (cache total ${cc_after}) ==="
    fi
    if [ "$rc" != 0 ]; then
        echo "=== suite @ ${n} devices FAILED (rc=$rc) — continuing sweep ==="
        FAILED_SIZES="$FAILED_SIZES $n"
    fi
done

# HLO collective audit: run the resplit redistribution microbenchmark with
# the predicted-vs-emitted auditor on and fail on any drift above tolerance
# (telemetry/hlo.py; HEAT_TPU_HLO_TOLERANCE overrides the default 10%).
# This is the schedule-level regression oracle: a jax/XLA upgrade that
# changes the emitted collectives breaks HERE, not in a wall-clock graph.
# HEAT_TPU_CI_SKIP_AUDIT=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_AUDIT:-}" ]; then
    echo "=== hlo collective audit (resplit microbenchmark, 4-device mesh) ==="
    audit_out=$(mktemp)
    audit_rc=0
    if HEAT_TPU_TELEMETRY=1 python benchmarks/resplit/heat_tpu.py \
        --n 4096 --features 64 --trials 1 --mesh 4 --audit > "$audit_out"; then
        python - "$audit_out" <<'EOF' || audit_rc=$?
import json, sys

summary = None
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        continue
    if "telemetry" in obj:
        summary = obj
if summary is None:
    raise SystemExit("audit: no summary line with a telemetry block")
hlo = summary["telemetry"].get("hlo_collectives")
if not hlo or not hlo.get("audits"):
    raise SystemExit(f"audit: auditor recorded no audits: {hlo}")
if hlo.get("drift", 0) > 0:
    raise SystemExit(
        "audit: predicted-vs-emitted drift detected:\n"
        + json.dumps(hlo, indent=2)
    )
print(f"audit ok: {hlo['audits']} audits, 0 drift")
EOF
    else
        audit_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$audit_out" "${REPORT}/audit_resplit.jsonl" || true
    fi
    rm -f "$audit_out"
    if [ "$audit_rc" != 0 ]; then
        echo "=== hlo collective audit FAILED (rc=$audit_rc) ==="
        FAILED_SIZES="$FAILED_SIZES audit"
    fi
fi

# Warm-cache regression check (ISSUE 3): run the resplit microbenchmark
# twice with a FRESH persistent compile cache — the second process must
# report lower compile_seconds than the first (it deserializes executables
# the first one built instead of re-running XLA). This pins the cross-
# process compile-skip behavior the sweep above relies on.
# HEAT_TPU_CI_SKIP_WARMCACHE=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_WARMCACHE:-}" ]; then
    echo "=== persistent compile cache warm/reuse check (resplit microbenchmark x2) ==="
    warm_dir=$(mktemp -d -t heat_tpu_warm.XXXXXX)
    warm_rc=0
    cold_out=$(mktemp); warm_out=$(mktemp)
    if HEAT_TPU_COMPILE_CACHE="$warm_dir" python benchmarks/resplit/heat_tpu.py \
            --n 2048 --features 32 --trials 1 --mesh 4 > "$cold_out" \
       && HEAT_TPU_COMPILE_CACHE="$warm_dir" python benchmarks/resplit/heat_tpu.py \
            --n 2048 --features 32 --trials 1 --mesh 4 > "$warm_out"; then
        python - "$cold_out" "$warm_out" <<'EOF' || warm_rc=$?
import json, sys

def compile_seconds(path):
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "compile_seconds" in obj:
            return obj["compile_seconds"]
    raise SystemExit(f"warm-cache: no compile_seconds line in {path}")

cold, warm = compile_seconds(sys.argv[1]), compile_seconds(sys.argv[2])
print(f"warm-cache: cold compile_seconds={cold} warm compile_seconds={warm}")
if not warm < cold:
    raise SystemExit(
        f"warm-cache: second process did not get cheaper compiles "
        f"(cold={cold}, warm={warm}) — persistent compile cache broken?"
    )
print("warm-cache ok")
EOF
    else
        warm_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$cold_out" "${REPORT}/warmcache_cold.jsonl" || true
        cp "$warm_out" "${REPORT}/warmcache_warm.jsonl" || true
    fi
    rm -f "$cold_out" "$warm_out"
    rm -rf "$warm_dir"
    if [ "$warm_rc" != 0 ]; then
        echo "=== warm-cache check FAILED (rc=$warm_rc) ==="
        FAILED_SIZES="$FAILED_SIZES warmcache"
    fi
fi

# Fusion dispatch check (ISSUE 4): run the elementwise-chain microbenchmark
# (normalize→scale→clip, 7 ops) in both dispatch modes and assert the fused
# chain compiled FEWER XLA programs than eager while matching or beating its
# wall clock — the defer-and-fuse engine's regression oracle
# (core/fusion.py). HEAT_TPU_CI_SKIP_FUSION=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_FUSION:-}" ]; then
    echo "=== fusion dispatch check (elementwise microbenchmark, 4-device mesh) ==="
    fusion_out=$(mktemp)
    fusion_rc=0
    # a fresh compile-cache-free run: the program-count comparison must see
    # real backend compiles, not deserializations from the sweep's cache
    if env -u HEAT_TPU_COMPILE_CACHE python benchmarks/elementwise/heat_tpu.py \
            --n 100000 --features 64 --trials 2 --mesh 4 > "$fusion_out"; then
        python - "$fusion_out" <<'EOF' || fusion_rc=$?
import json, sys

cmp = None
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        continue
    if "elementwise_compare" in obj:
        cmp = obj["elementwise_compare"]
if cmp is None:
    raise SystemExit("fusion: no elementwise_compare summary line")
eager, fused = cmp["eager"], cmp["fused"]
print(
    f"fusion: eager programs={eager['programs_compiled']} "
    f"best={eager['best_seconds']}s | fused programs={fused['programs_compiled']} "
    f"best={fused['best_seconds']}s | chain flushed as "
    f"{cmp['fused_programs']} cached program(s)"
)
if not fused["programs_compiled"] < eager["programs_compiled"]:
    raise SystemExit(
        f"fusion: fused chain did not compile fewer programs than eager "
        f"(fused={fused['programs_compiled']}, eager={eager['programs_compiled']})"
    )
if cmp["fused_programs"] != 1:
    raise SystemExit(
        f"fusion: the 7-op chain should flush as exactly ONE registry "
        f"program, got {cmp['fused_programs']}"
    )
if fused["deferred_ops"] == 0:
    raise SystemExit("fusion: no ops deferred — engine disabled?")
print("fusion ok")
EOF
    else
        fusion_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$fusion_out" "${REPORT}/fusion_elementwise.jsonl" || true
    fi
    rm -f "$fusion_out"
    if [ "$fusion_rc" != 0 ]; then
        echo "=== fusion dispatch check FAILED (rc=$fusion_rc) ==="
        FAILED_SIZES="$FAILED_SIZES fusion"
    fi
    # Bit-for-bit parity spot check: the fusion test module's numeric
    # oracles re-run with fusion forced OFF (the sweep above already ran
    # them with the default ON), pinning HEAT_TPU_FUSION=0 == eager.
    echo "=== fusion-off parity spot check (tests/test_fusion.py eager mode) ==="
    if ! HEAT_TPU_FUSION=0 python -m pytest tests/test_fusion.py \
            -q -p no:cacheprovider -k "NumpyParity or FusionOff"; then
        echo "=== fusion-off parity check FAILED ==="
        FAILED_SIZES="$FAILED_SIZES fusion-off"
    fi
fi

# Fusion 2.0 step (ISSUE 7): run the reduction microbenchmark (normalize→
# scale→sum + mean/var moment chains) in eager / flush-at-reduction /
# fully-fused modes and assert (a) the fused moment chain dispatches FEWER
# programs than eager and the map+reduce chain compiles as exactly ONE
# program, (b) the DP-forward dense (matmul+bias+relu) is ONE program,
# (c) the fused chain+sum digests bit-identical to the knob-off baseline,
# and (d) HEAT_TPU_FUSION_REDUCE=0 really disarms absorption (zero
# reductions_absorbed, no fusion_reduce registry entries).
# HEAT_TPU_CI_SKIP_FUSION_REDUCE=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_FUSION_REDUCE:-}" ]; then
    echo "=== fusion-reduce dispatch check (reduction microbenchmark, 4-device mesh) ==="
    fr_out=$(mktemp)
    fr_rc=0
    # compile-cache-free: the program-count comparison must see real
    # backend compiles, not deserializations from the sweep's cache
    if env -u HEAT_TPU_COMPILE_CACHE python benchmarks/reduction/heat_tpu.py \
            --n 100000 --features 64 --trials 2 --mesh 4 > "$fr_out"; then
        python - "$fr_out" <<'EOF' || fr_rc=$?
import json, sys

cmp = None
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        continue
    if "reduction_compare" in obj:
        cmp = obj["reduction_compare"]
if cmp is None:
    raise SystemExit("fusion-reduce: no reduction_compare summary line")
eager, flush, fused = cmp["eager"], cmp["flush"], cmp["fused"]
cp = cmp["chain_programs"]
print(
    f"fusion-reduce: chain programs eager={cp['eager']} flush={cp['flush']} "
    f"fused={cp['fused']} | moment programs eager={eager['programs_compiled']} "
    f"fused={fused['programs_compiled']} | dense={cmp['dense_programs']} "
    f"| absorbed={fused['reductions_absorbed']}"
)
if cp["fused"] != 1:
    raise SystemExit(
        f"fusion-reduce: the map+reduce chain should compile as exactly ONE "
        f"program, got {cp['fused']}"
    )
if cp["eager"] < 3 * cp["fused"]:
    raise SystemExit(
        f"fusion-reduce: fused chain must compile >=3x fewer programs than "
        f"eager (eager={cp['eager']}, fused={cp['fused']})"
    )
if not fused["programs_compiled"] < eager["programs_compiled"]:
    raise SystemExit(
        f"fusion-reduce: fused moment chain did not dispatch fewer programs "
        f"than eager (fused={fused['programs_compiled']}, "
        f"eager={eager['programs_compiled']})"
    )
if cmp["dense_programs"] != 1:
    raise SystemExit(
        f"fusion-reduce: matmul+bias+relu (dense) should be ONE cached "
        f"program, got {cmp['dense_programs']}"
    )
if not cmp["digest_chain_match"]:
    raise SystemExit(
        "fusion-reduce: fused chain+sum digest differs from the knob-off "
        "flush-then-reduce baseline (bit-identity pin)"
    )
if not cmp["moments_allclose"]:
    raise SystemExit(
        "fusion-reduce: fused moment chain drifted beyond tolerance vs the "
        "knob-off baseline"
    )
if fused["reductions_absorbed"] == 0:
    raise SystemExit("fusion-reduce: nothing absorbed — engine disabled?")
if flush["reductions_absorbed"] != 0 or "fusion_reduce" in flush["site_misses"]:
    raise SystemExit(
        "fusion-reduce: HEAT_TPU_FUSION_REDUCE=0 did not disarm absorption"
    )
print("fusion-reduce ok")
EOF
    else
        fr_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$fr_out" "${REPORT}/fusion_reduction.jsonl" || true
    fi
    rm -f "$fr_out"
    if [ "$fr_rc" != 0 ]; then
        echo "=== fusion-reduce dispatch check FAILED (rc=$fr_rc) ==="
        FAILED_SIZES="$FAILED_SIZES fusion-reduce"
    fi
    # Knob-off parity spot check: the fusion-reduce numeric oracles re-run
    # with absorption forced OFF, pinning HEAT_TPU_FUSION_REDUCE=0 ==
    # flush-at-reduction dispatch.
    echo "=== fusion-reduce knob-off parity spot check (tests/test_fusion_reduce.py) ==="
    if ! HEAT_TPU_FUSION_REDUCE=0 python -m pytest tests/test_fusion_reduce.py \
            -q -p no:cacheprovider \
            -k "NumpyParity or (NanVariants and not nan_chain_absorbs)"; then
        echo "=== fusion-reduce knob-off parity check FAILED ==="
        FAILED_SIZES="$FAILED_SIZES fusion-reduce-off"
    fi
fi

# Planner step (ISSUE 6): the resplit whose monolithic program exceeds a
# tight HEAT_TPU_HBM_BUDGET must succeed through the planner's chunked
# program chain with (a) every stage's memory_analysis() temp bytes within
# the budget and (b) a result sha256 BIT-IDENTICAL to the unconstrained
# monolithic run. The budget is computed IN-PROCESS (live bytes + half the
# monolithic program's measured temp+output need) because the flip point
# depends on live bytes at decision time — a fixed env value would race
# allocator state. HEAT_TPU_CI_SKIP_PLANNER=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_PLANNER:-}" ]; then
    echo "=== planner step: budget-constrained resplit via chunked plan (4-device mesh) ==="
    planner_rc=0
    planner_out=$(mktemp)
    XLA_FLAGS="--xla_force_host_platform_device_count=4" JAX_PLATFORMS=cpu \
        HEAT_TPU_TELEMETRY=1 python - <<'EOF' > "$planner_out" 2>&1 || planner_rc=$?
import hashlib
import json
import os

import numpy as np

import heat_tpu as ht
from heat_tpu import telemetry
from heat_tpu.core import relayout_planner as rp
from heat_tpu.resilience import memory_guard

comm = ht.get_comm()
assert comm.size == 4, f"expected a 4-device mesh, got {comm.size}"
n, m = 4096, 256
xn = np.arange(n * m, dtype=np.float32).reshape(n, m)
x = ht.array(xn, split=0)

# unconstrained run: auto with no budget stays monolithic
ref = x.resplit(1)
sha_ref = hashlib.sha256(
    np.ascontiguousarray(ref.numpy()).tobytes()
).hexdigest()
del ref

# measure the program FIRST, then gc, then read live — the ordering
# maybe_plan itself uses, so the flip arithmetic is deterministic
need = memory_guard.program_bytes(x._relayout_executable(1), (x.larray,))
assert need > 0, "memory_analysis unavailable — cannot gate the planner"
import gc

gc.collect()
live = memory_guard._live_total()
budget = live + need // 2  # the monolithic program can no longer fit
os.environ["HEAT_TPU_HBM_BUDGET"] = str(budget)

reg = telemetry.get_registry()
reg.clear()
y = x.resplit(1)
sha = hashlib.sha256(np.ascontiguousarray(y.numpy()).tobytes()).hexdigest()
events = [e for e in reg.events if e["kind"] == "relayout_plan"]
assert events, "budgeted resplit recorded no relayout_plan event"
ev = events[0]
assert ev["plan"] == "chunked", f"expected a chunked plan, got {ev}"

plan = rp.plan(
    (n, m), 4, 0, 1, comm, budget=budget, live=live, measured_need=need
)
mem = rp.plan_memory(plan, x.larray, comm)
assert 0 <= mem["peak_temp_bytes"] <= budget, (mem, budget)
assert mem["peak_temp_bytes"] < need, (mem, need)
assert sha == sha_ref, (
    f"chunked plan diverged from monolithic result ({sha} != {sha_ref})"
)
print(json.dumps({
    "planner": "ok", "budget": budget, "live": live,
    "monolithic_need": need, "chunks": ev["chunks"],
    "peak_stage_temp_bytes": mem["peak_temp_bytes"],
    "digest": sha[:12],
}))
EOF
    cat "$planner_out"
    if [ -n "$REPORT" ]; then
        cp "$planner_out" "${REPORT}/planner_gate.log" || true
    fi
    rm -f "$planner_out"
    if [ "$planner_rc" != 0 ]; then
        echo "=== planner step FAILED (rc=$planner_rc) ==="
        FAILED_SIZES="$FAILED_SIZES planner"
    fi
fi

# Collective-precision step (ISSUE 9): resplit + DP-step microbench under
# every HEAT_TPU_COLLECTIVE_PREC mode on the 4-device mesh. Gates:
#   (a) the HLO-audited emitted wire bytes of each compressed program
#       match the analytic compressed prediction (zero drift), and the
#       audited byte REDUCTION clears the acceptance floor — resplit
#       >=1.9x under bf16 and >=3.5x under int8/blockwise, DP gradient
#       all-reduce >=3.5x under int8/blockwise (the CPU backend
#       legalizes a bf16 all-reduce payload to f32, so bf16-DP only
#       gates "not worse"; the true 2x is the resplit's, whose bf16
#       payload travels as its u16 bit pattern);
#   (b) HEAT_TPU_COLLECTIVE_PREC=off (the default) stays BIT-identical
#       to the unknobbed baseline;
#   (c) each mode's executed error stays within the pinned bound.
# HEAT_TPU_CI_SKIP_COLLPREC=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_COLLPREC:-}" ]; then
    echo "=== collective-precision step: quantized wire audit (4-device mesh) ==="
    collprec_rc=0
    collprec_out=$(mktemp)
    XLA_FLAGS="--xla_force_host_platform_device_count=4" JAX_PLATFORMS=cpu \
        python - <<'EOF' > "$collprec_out" 2>&1 || collprec_rc=$?
import json

import jax.numpy as jnp
import numpy as np
import optax

import heat_tpu as ht
from heat_tpu.telemetry import collectives, hlo

comm = ht.get_comm()
p = comm.size
assert p == 4, f"expected a 4-device mesh, got {p}"
MODES = ("off", "bf16", "int8", "blockwise")
rng = np.random.default_rng(0)
report = {"mesh": p}

# -- resplit microbench ------------------------------------------------------
shape = (4096, 256)
xn = rng.standard_normal(shape).astype(np.float32)
x = ht.array(xn, split=0)
baseline = x.resplit(1).numpy()
assert baseline.tobytes() == xn.tobytes(), "exact resplit corrupted data"
wires, errs = {}, {}
for m in MODES:
    fn = x._relayout_executable(1, precision=m)
    aud = hlo.audit_computation(fn, x.larray)
    phys = [comm.padded_size(shape[0]), comm.padded_size(shape[1])]
    pred = collectives.relayout_cost(phys, 4, 0, 1, p, precision=m)
    rep = hlo.compare(aud, pred)
    if not rep.ok:
        raise SystemExit(
            f"collective-prec: {m} resplit audit drifted: "
            f"{json.dumps(rep.summary())}"
        )
    wires[m] = aud.total_wire()
    out = np.asarray(fn(x.larray))
    errs[m] = float(np.abs(out - baseline).max() / np.abs(xn).max())
if baseline.tobytes() != np.asarray(
    x._relayout_executable(1, precision="off")(x.larray)
).tobytes():
    raise SystemExit("collective-prec: off mode is not bit-identical")
for m, floor in (("bf16", 1.9), ("int8", 3.5), ("blockwise", 3.5)):
    got = wires["off"] / wires[m]
    if got < floor:
        raise SystemExit(
            f"collective-prec: resplit {m} audited reduction {got:.2f}x "
            f"below the {floor}x floor ({wires})"
        )
bounds = {"off": 0.0, "bf16": 2.0 ** -7, "int8": 1.05 / 127,
          "blockwise": 1.05 / 127}
for m in MODES:
    if errs[m] > bounds[m]:
        raise SystemExit(
            f"collective-prec: resplit {m} error {errs[m]:.5f} over the "
            f"pinned bound {bounds[m]:.5f}"
        )
report["resplit"] = {"wire_bytes": wires, "max_rel_err": errs}

# -- DP-step microbench ------------------------------------------------------
D = 512
xb = rng.standard_normal((128, D)).astype(np.float32)
yb = rng.standard_normal((128, 1)).astype(np.float32)

def loss_fn(params, bx, by):
    return jnp.mean((bx @ params["w"] - by) ** 2)

dp_wires, dp_final = {}, {}
for m in MODES:
    dp = ht.nn.DataParallel(
        lambda pr, bx: bx @ pr["w"], optimizer=optax.sgd(0.05),
        blocking_parameter_updates=True,
    )
    params = {"w": jnp.zeros((D, 1))}
    opt_state = optax.sgd(0.05).init(params)
    step = dp.make_train_step(loss_fn, optax.sgd(0.05), precision=m)
    batch = dp.shard_batch(xb, yb)
    aud = hlo.audit_computation(step, params, opt_state, *batch)
    dp_wires[m] = aud.total_wire()
    if m in ("int8", "blockwise"):
        pred = collectives.allreduce_cost(D, 4, p, precision=m)
        loss_ar = collectives.allreduce_cost(1, 4, p)
        rep = hlo.compare(aud, collectives.CollectiveCost(
            pred.kind + "+all-reduce", pred.bytes + loss_ar.bytes
        ))
        if not rep.ok:
            raise SystemExit(
                f"collective-prec: {m} DP-step audit drifted: "
                f"{json.dumps(rep.summary())}"
            )
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, *batch)
    dp_final[m] = np.asarray(params["w"])
for m, floor in (("int8", 3.5), ("blockwise", 3.5)):
    got = dp_wires["off"] / dp_wires[m]
    if got < floor:
        raise SystemExit(
            f"collective-prec: DP {m} audited reduction {got:.2f}x below "
            f"the {floor}x floor ({dp_wires})"
        )
if dp_wires["bf16"] > dp_wires["off"]:
    raise SystemExit(
        f"collective-prec: bf16 DP wire not smaller than off ({dp_wires})"
    )
for m in ("bf16", "int8", "blockwise"):
    drift = float(np.abs(dp_final[m] - dp_final["off"]).max())
    if drift > 5e-2:
        raise SystemExit(
            f"collective-prec: {m} DP trajectory drifted {drift} from "
            "exact after 8 steps"
        )
report["dp_step"] = {"wire_bytes": dp_wires}
print(json.dumps({"collective_prec": "ok", **report}))
EOF
    cat "$collprec_out"
    if [ -n "$REPORT" ]; then
        cp "$collprec_out" "${REPORT}/collective_prec_gate.log" || true
    fi
    rm -f "$collprec_out"
    if [ "$collprec_rc" != 0 ]; then
        echo "=== collective-precision step FAILED (rc=$collprec_rc) ==="
        FAILED_SIZES="$FAILED_SIZES collective-prec"
    fi
fi

# Chaos step (ISSUE 5): run the resplit microbenchmark twice — fault-free,
# then under deterministic fault injection (one synthetic transient per
# matched site: the relayout dispatch and every collective wrapper) with
# retries armed. The guarded dispatch must absorb the faults: the run
# succeeds, its result digest is BIT-IDENTICAL to the fault-free run, the
# summary records resilience.retries >= 1, and the fault-free run carries
# no resilience counters at all (the zero-overhead-when-disarmed oracle).
# HEAT_TPU_CI_SKIP_CHAOS=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_CHAOS:-}" ]; then
    echo "=== chaos step: resplit microbenchmark under fault injection ==="
    chaos_rc=0
    clean_out=$(mktemp); chaos_out=$(mktemp)
    if env -u HEAT_TPU_FAULTS -u HEAT_TPU_RETRIES HEAT_TPU_TELEMETRY=1 \
            python benchmarks/resplit/heat_tpu.py \
            --n 2048 --features 32 --trials 1 --mesh 4 --digest > "$clean_out" \
       && HEAT_TPU_TELEMETRY=1 HEAT_TPU_RETRIES=3 HEAT_TPU_RETRY_BASE=0.01 \
            HEAT_TPU_FAULTS='relayout:kind=resource:calls=1;collective.*:kind=reset:calls=1' \
            python benchmarks/resplit/heat_tpu.py \
            --n 2048 --features 32 --trials 1 --mesh 4 --digest > "$chaos_out"; then
        python - "$clean_out" "$chaos_out" <<'EOF' || chaos_rc=$?
import json, sys

def parse(path):
    digest, summary = None, None
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "result_sha256" in obj:
            digest = obj["result_sha256"]
        if "telemetry" in obj:
            summary = obj
    return digest, summary

clean_digest, clean_summary = parse(sys.argv[1])
chaos_digest, chaos_summary = parse(sys.argv[2])
if not clean_digest or not chaos_digest:
    raise SystemExit("chaos: missing result_sha256 line (need --digest)")
if clean_summary is None or chaos_summary is None:
    raise SystemExit("chaos: missing telemetry summary line")
if chaos_digest != clean_digest:
    raise SystemExit(
        f"chaos: fault-injected run diverged from fault-free run "
        f"({chaos_digest} != {clean_digest}) — retries are not transparent"
    )
res = chaos_summary["telemetry"].get("resilience") or {}
if res.get("retries", 0) < 1:
    raise SystemExit(
        f"chaos: injected faults produced no recorded retries: {res}"
    )
if res.get("gave_up", 0):
    raise SystemExit(f"chaos: a guarded site gave up: {res}")
clean_res = clean_summary["telemetry"].get("resilience")
if clean_res:
    raise SystemExit(
        f"chaos: fault-free run carries resilience counters {clean_res} — "
        "the disarmed path is not zero-overhead"
    )
print(
    f"chaos ok: bit-identical digest {chaos_digest[:12]}…, "
    f"retries={res['retries']}, faults_injected={res.get('faults_injected')}, "
    "fault-free run clean"
)
EOF
    else
        chaos_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$clean_out" "${REPORT}/chaos_clean.jsonl" || true
        cp "$chaos_out" "${REPORT}/chaos_faulted.jsonl" || true
    fi
    rm -f "$clean_out" "$chaos_out"
    if [ "$chaos_rc" != 0 ]; then
        log_resilience "kind=chaos verdict=FAIL rc=${chaos_rc}"
        echo "=== chaos step FAILED (rc=$chaos_rc) ==="
        FAILED_SIZES="$FAILED_SIZES chaos"
    else
        log_resilience "kind=chaos verdict=ok sites='relayout collective.*' retries-armed=3"
    fi
fi

# Serving gate (ISSUE 8): a short open-loop Poisson run against a live
# heat_tpu.serve server, three phases —
#   clean:  ZERO program-registry misses and ZERO backend compiles after
#           warmup() (the zero-compile steady-state acceptance oracle),
#           no failures, p99 under a generous bound, post-load probe ok;
#   retry:  one injected transient per serve site with retries armed —
#           the guarded per-batch retry must absorb every fault
#           (retries>=1, no gave_up, zero failed requests) and the
#           response digest must be BIT-IDENTICAL to the clean run;
#   shed:   the same faults with retries DISARMED — the affected batches
#           shed cleanly (failed>=1, futures resolve with the error, no
#           hang) and the server recovers (post_ok). calls=6 lands the
#           injection past the 5 warmup executions of the --max-batch 16
#           ladder (buckets 1,2,4,8,16), i.e. on the first load batches.
# HEAT_TPU_CI_SKIP_SERVING=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_SERVING:-}" ]; then
    echo "=== serving gate: open-loop load vs live server (4-device mesh) ==="
    serve_rc=0
    serve_clean=$(mktemp); serve_retry=$(mktemp); serve_shed=$(mktemp)
    SERVE_ARGS="--n 2048 --features 32 --mesh 4 --requests 240 --rate 400 --max-batch 16 --digest"
    if env -u HEAT_TPU_FAULTS -u HEAT_TPU_RETRIES HEAT_TPU_TELEMETRY=1 \
            python benchmarks/serving/heat_tpu.py $SERVE_ARGS > "$serve_clean" \
       && HEAT_TPU_TELEMETRY=1 HEAT_TPU_RETRIES=3 HEAT_TPU_RETRY_BASE=0.01 \
            HEAT_TPU_FAULTS='serve.*:kind=reset:calls=6' \
            python benchmarks/serving/heat_tpu.py $SERVE_ARGS > "$serve_retry" \
       && env -u HEAT_TPU_RETRIES HEAT_TPU_TELEMETRY=1 \
            HEAT_TPU_FAULTS='serve.*:kind=resource:calls=6' \
            python benchmarks/serving/heat_tpu.py $SERVE_ARGS > "$serve_shed"; then
        python - "$serve_clean" "$serve_retry" "$serve_shed" <<'EOF' || serve_rc=$?
import json, sys

def parse(path):
    cmp_, summary = None, None
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "serving_compare" in obj:
            cmp_ = obj["serving_compare"]
        if obj.get("bench") == "serving":
            summary = obj
    if cmp_ is None or summary is None:
        raise SystemExit(f"serving: missing serving_compare/summary in {path}")
    return cmp_, summary

clean, clean_sum = parse(sys.argv[1])
retry, retry_sum = parse(sys.argv[2])
shed, _ = parse(sys.argv[3])

# clean phase: zero-compile steady state + SLO
if clean["misses_during_load"] != 0 or clean["backend_compiles_during_load"] != 0:
    raise SystemExit(
        f"serving: steady state recompiled after warmup "
        f"(misses={clean['misses_during_load']}, "
        f"backend_compiles={clean['backend_compiles_during_load']})"
    )
if clean["failed"] or not clean["post_ok"]:
    raise SystemExit(f"serving: clean run failed requests: {clean}")
p99 = clean["latency"].get("p99_s")
if p99 is None or p99 > 2.0:
    raise SystemExit(f"serving: clean p99 {p99}s exceeds the 2s CI bound")
res = clean_sum.get("telemetry", {}).get("resilience")
if res:
    raise SystemExit(f"serving: fault-free run carries resilience counters {res}")

# retry phase: per-batch retries absorb the faults, answers bit-identical
rres = retry_sum.get("telemetry", {}).get("resilience") or {}
if rres.get("retries", 0) < 1:
    raise SystemExit(f"serving: injected faults produced no retries: {rres}")
if rres.get("gave_up", 0) or retry["failed"]:
    raise SystemExit(f"serving: retry phase lost requests: {retry} {rres}")
if retry["digest"] != clean["digest"]:
    raise SystemExit(
        f"serving: fault-injected digest diverged from clean "
        f"({retry['digest']} != {clean['digest']}) — retries not transparent"
    )

# shed phase: retries disarmed -> affected batches shed, server recovers
if shed["failed"] < 1:
    raise SystemExit(f"serving: shed phase absorbed faults with no retries armed? {shed}")
if not shed["post_ok"]:
    raise SystemExit(f"serving: server did not recover after shedding: {shed}")
print(
    f"serving ok: 0 recompiles, p99={p99}s, qps={clean['achieved_qps']} "
    f"(offered {clean['offered_rate']}), retry digest bit-identical "
    f"(retries={rres.get('retries')}), shed-and-recover "
    f"(failed={shed['failed']}, post_ok)"
)
EOF
    else
        serve_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$serve_clean" "${REPORT}/serving_clean.jsonl" || true
        cp "$serve_retry" "${REPORT}/serving_retry.jsonl" || true
        cp "$serve_shed" "${REPORT}/serving_shed.jsonl" || true
    fi
    rm -f "$serve_clean" "$serve_retry" "$serve_shed"
    if [ "$serve_rc" != 0 ]; then
        log_resilience "kind=serving verdict=FAIL rc=${serve_rc}"
        echo "=== serving gate FAILED (rc=$serve_rc) ==="
        FAILED_SIZES="$FAILED_SIZES serving"
    else
        log_resilience "kind=serving verdict=ok phases='clean retry shed' sites='serve.*'"
    fi
fi

# Autotune gate (ISSUE 11): tune the resplit + reduction + serving
# microbench workloads on the 4-device mesh against a fresh tuning DB,
# then replay the SAME tunes from a second process. Gates:
#   tune phase:   every site's tuned wall <= the measured default wall
#                 (the default config is candidate 0 under the identical
#                 protocol); an exact/neutral pick is BIT-identical to
#                 the default result; a lossy pick measures within the
#                 stated error budget (the int8 single-hop bound the
#                 collective-precision step pins);
#   replay phase: a fresh process pointed at the same HEAT_TPU_TUNE_DB
#                 reaches every tuned config with ZERO measured trials
#                 (db-hit warm start) and its steady-state dispatch
#                 under the adopted config backend-compiles nothing.
# HEAT_TPU_CI_SKIP_AUTOTUNE=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_AUTOTUNE:-}" ]; then
    echo "=== autotune gate: measured-feedback tuning + second-process warm start (4-device mesh) ==="
    at_rc=0
    at_db=$(mktemp -d -t heat_tpu_tune.XXXXXX)
    at_script=$(mktemp)
    at_tune_out=$(mktemp); at_replay_out=$(mktemp)
    cat > "$at_script" <<'EOF'
import json
import os

import numpy as np

import heat_tpu as ht
from heat_tpu import _knobs as knobs
from heat_tpu import autotune as at
from heat_tpu import telemetry
from heat_tpu.autotune import cost, trials

PHASE = os.environ["HEAT_TPU_CI_AUTOTUNE_PHASE"]  # tune | replay
BUDGET = 1.05 / 127  # the int8 single-hop bound (collective-prec gate)
replay = PHASE == "replay"

comm = ht.get_comm()
assert comm.size == 4, f"expected a 4-device mesh, got {comm.size}"
reg = telemetry.get_registry()
rng = np.random.default_rng(0)
report = {"phase": PHASE, "sites": {}}


def check(res, exact_ref=None, lossy_knob=None):
    rec = res.record
    if replay:
        assert res.from_db and res.trials_run == 0, (
            f"{res.site}: second process ran trials "
            f"(from_db={res.from_db}, trials={res.trials_run})"
        )
    else:
        assert not res.from_db and res.trials_run > 0, res
        assert rec["tuned_wall"] <= rec["baseline_wall"], (
            f"{res.site}: tuned wall {rec['tuned_wall']} worse than the "
            f"measured default {rec['baseline_wall']}"
        )
    # validation contract: lossy picks carry a bounded measured error,
    # everything else is digest-validated (bit-identical to default)
    if rec["validation"] == "allclose":
        assert rec["max_rel_err"] <= rec["error_budget"], rec
    else:
        assert rec["max_rel_err"] == 0.0, rec
    if exact_ref is not None:
        out = np.asarray(exact_ref["run"]())  # under the ADOPTED config
        if lossy_knob and res.config.get(lossy_knob) not in (None, "off"):
            err = trials.max_rel_err(out, exact_ref["value"])
            assert err <= BUDGET, (
                f"{res.site}: adopted lossy config error {err} over "
                f"budget {BUDGET}"
            )
        else:
            assert out.tobytes() == exact_ref["value"].tobytes(), (
                f"{res.site}: exact pick not bit-identical to default"
            )
    report["sites"][res.site] = {
        "config": res.config, "trials": res.trials_run,
        "from_db": res.from_db,
        "baseline_wall": rec["baseline_wall"],
        "tuned_wall": rec["tuned_wall"],
        "validation": rec["validation"],
        "max_rel_err": rec["max_rel_err"],
    }


# -- resplit: exact + lossy lattice under the int8 budget --------------------
n, d = 2048, 64
x = ht.array(rng.standard_normal((n, d)).astype(np.float32), split=0)
exact_resplit = np.asarray(x.resplit(1).larray)  # untuned default result
res = at.tune(
    "resplit", lambda: x.resplit(1).larray,
    signature=("resplit", (n, d), 0, 1),
    search=["HEAT_TPU_RELAYOUT_PLAN", "HEAT_TPU_COLLECTIVE_PREC"],
    error_budget=BUDGET, trials_per_config=2, prune_to=6,
    cost_fn=cost.relayout_cost_fn(x.shape, 4, 0, 1, comm.size),
)
check(
    res,
    exact_ref={"run": lambda: x.resplit(1).larray, "value": exact_resplit},
    lossy_knob="HEAT_TPU_COLLECTIVE_PREC",
)

# -- reduction: exact-class fusion knobs, bit-identity required --------------
xr = ht.array(rng.standard_normal((4096, 64)).astype(np.float32), split=0)


def red_work():
    return ((xr - 0.5) * 2.0 + 1.0).sum(axis=0).larray


exact_red = np.asarray(red_work())
res = at.tune(
    "reduction", red_work,
    signature=("reduction", (4096, 64), 0),
    search=["HEAT_TPU_FUSION", "HEAT_TPU_FUSION_REDUCE"],
    trials_per_config=2,
)
check(res, exact_ref={"run": red_work, "value": exact_red})

# -- serving: neutral gather-window knob, digest-validated -------------------
w = rng.standard_normal((d, 8)).astype(np.float32)
b = rng.standard_normal(8).astype(np.float32)
endpoint = ht.serve.dense_forward(w, b, activation="relu")
payloads = [rng.standard_normal(d).astype(np.float32) for _ in range(24)]
servers = {}


def serve_work():
    key = knobs.raw("HEAT_TPU_SERVE_MAX_WAIT_MS")
    srv = servers.get(key)
    if srv is None:
        srv = ht.serve.Server(max_batch=8)
        srv.register("dense", endpoint)
        srv.warmup()
        servers[key] = srv
    futs = [srv.submit("dense", p) for p in payloads]
    return np.stack([f.result() for f in futs])


try:
    exact_serve = serve_work()
    res = at.tune(
        "serving", serve_work,
        signature=("serving", ("dense",), d, 8),
        search=["HEAT_TPU_SERVE_MAX_WAIT_MS"],
        trials_per_config=2,
    )
    check(res, exact_ref={"run": serve_work, "value": exact_serve})
finally:
    for srv in servers.values():
        srv.close()

if replay:
    # zero measured trials across ALL sites (counter oracle), and the
    # steady-state dispatch under the adopted configs compiles nothing
    assert reg.counters.get("autotune.trials", 0) == 0, dict(reg.counters)
    x.resplit(1).larray  # first dispatch under the adopted config
    with telemetry.CompileWatcher() as cw:
        x.resplit(1).larray
    assert cw.backend_compiles == 0, (
        f"steady-state dispatch compiled {cw.backend_compiles} programs"
    )
    report["steady_state_backend_compiles"] = cw.backend_compiles

print(json.dumps({"autotune_gate": "ok", **report}))
EOF
    at_env=(XLA_FLAGS="--xla_force_host_platform_device_count=4"
            JAX_PLATFORMS=cpu HEAT_TPU_TELEMETRY=1
            HEAT_TPU_AUTOTUNE=1 HEAT_TPU_TUNE_DB="$at_db"
            PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}")
    if env "${at_env[@]}" HEAT_TPU_CI_AUTOTUNE_PHASE=tune \
            python "$at_script" > "$at_tune_out" 2>&1 \
       && env "${at_env[@]}" HEAT_TPU_CI_AUTOTUNE_PHASE=replay \
            python "$at_script" > "$at_replay_out" 2>&1; then
        tail -1 "$at_tune_out"
        tail -1 "$at_replay_out"
        echo "autotune ok: tuned <= default on all sites, replay ran zero trials"
    else
        at_rc=$?
        cat "$at_tune_out" "$at_replay_out"
    fi
    if [ -n "$REPORT" ]; then
        cp "$at_tune_out" "${REPORT}/autotune_tune.jsonl" || true
        cp "$at_replay_out" "${REPORT}/autotune_replay.jsonl" || true
    fi
    rm -f "$at_script" "$at_tune_out" "$at_replay_out"
    rm -rf "$at_db"
    if [ "$at_rc" != 0 ]; then
        echo "=== autotune gate FAILED (rc=$at_rc) ==="
        FAILED_SIZES="$FAILED_SIZES autotune"
    fi
fi

# Serving-net gate (ISSUE 12): a 2-replica pool on the 4-dev CPU mesh
# behind the least-loaded router, one shared compile cache. Gates:
#   digest:   the same seeded request set through an in-process Server
#             and through the router over HTTP produces BIT-IDENTICAL
#             response digests (wire round-trip is bitwise; zero sheds
#             on both sides);
#   warm:     every replica reports steady_backend_compiles == 0 in
#             /stats — the CompileWatcher armed post-warmup saw nothing
#             (the warm-started second replica is the headline: it
#             reached steady state from the SHARED cache);
#   chaos:    SIGKILL one replica mid-load — only its in-flight
#             requests fail (bounded by the router worker count), and
#             the post-kill recovery probe (fresh replica spawned from
#             the checkpoint, joined via add_target) answers
#             bit-identically to the direct single-dispatch reference.
# HEAT_TPU_CI_SKIP_SERVING_NET=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_SERVING_NET:-}" ]; then
    echo "=== serving-net gate: 2-replica pool + router (4-device mesh) ==="
    snet_rc=0
    snet_out=$(mktemp)
    if HEAT_TPU_TELEMETRY=1 python benchmarks/serving/net.py \
            --n 256 --features 16 --mesh 4 --replica-mesh 4 \
            --replicas-list 2 --requests 80 --rate 120 \
            --digest-requests 40 --digest-rate 60 \
            --endpoints cdist,dense --chaos > "$snet_out"; then
        python - "$snet_out" <<'EOF' || snet_rc=$?
import json, sys

summary = None
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        continue
    if obj.get("bench") == "serving_net":
        summary = obj
if summary is None:
    raise SystemExit("serving-net: no summary line")

dp = summary["digest_probe"] or {}
if not (dp.get("match") and dp.get("direct_clean") and dp.get("routed_clean")):
    raise SystemExit(f"serving-net: router-vs-direct digest diverged: {dp}")

if not summary["steady_backend_compiles_ok"]:
    raise SystemExit(
        "serving-net: a replica backend-compiled in steady state "
        "(warm start from the shared cache failed): "
        f"{summary['qps_by_replicas']}"
    )

chaos = summary["chaos"] or {}
if not chaos.get("post_ok"):
    raise SystemExit(
        f"serving-net: post-kill recovery probe not bit-identical: {chaos}"
    )
if not chaos.get("failed_within_inflight_bound"):
    raise SystemExit(
        f"serving-net: killing one replica lost more than its in-flight "
        f"requests (failed={chaos.get('failed')}, "
        f"bound={chaos.get('max_inflight_bound')})"
    )
if (chaos.get("completed") or 0) + (chaos.get("failed") or 0) + \
        (chaos.get("shed") or 0) != summary["requests"]:
    raise SystemExit(f"serving-net: chaos phase dropped requests: {chaos}")

print(
    f"serving-net ok: digest bit-identical router-vs-direct, "
    f"steady compiles 0 across replicas, chaos lost "
    f"{chaos.get('failed')} in-flight (bound "
    f"{chaos.get('max_inflight_bound')}), replacement joined in "
    f"{chaos.get('replacement_join_seconds')}s, post_ok"
)
EOF
    else
        snet_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$snet_out" "${REPORT}/serving_net.jsonl" || true
    fi
    rm -f "$snet_out"
    if [ "$snet_rc" != 0 ]; then
        echo "=== serving-net gate FAILED (rc=$snet_rc) ==="
        FAILED_SIZES="$FAILED_SIZES serving-net"
    fi
fi

# Sparse gate (ISSUE 13, heat_tpu/sparse): the density-sweep
# microbenchmark on the 4-device mesh must show
#   (a) the row-split spmv digest BIT-identical to the dense reference
#       mask-matmul evaluated in the same per-row element order, at
#       every density (0.1%/1%/10%),
#   (b) the budget-bounded transpose (stage-decomposed slab exchange)
#       bit-identical to the monolithic exchange,
#   (c) zero HLO-audit drift on every audited sparse collective site
#       (--audit arms the auditor over the whole run), and
#   (d) the Spectral eNeighbour end-to-end row agreeing with the dense
#       pipeline's labels exactly.
# HEAT_TPU_CI_SKIP_SPARSE=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_SPARSE:-}" ]; then
    echo "=== sparse gate: density sweep + transpose + spectral (4-device mesh) ==="
    sp_rc=0
    sp_out=$(mktemp)
    if HEAT_TPU_TELEMETRY=1 python benchmarks/sparse/heat_tpu.py \
            --n 512 --features 8 --trials 2 --mesh 4 --audit \
            --spectral-n 128 > "$sp_out"; then
        python - "$sp_out" <<'EOF' || sp_rc=$?
import json, sys

summary = None
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        continue
    if "sparse_compare" in obj:
        summary = obj["sparse_compare"]
if summary is None:
    raise SystemExit("sparse: no sparse_compare summary line")

bad = [r["density"] for r in summary["densities"] if not r["digest_match"]]
if bad:
    raise SystemExit(
        f"sparse: spmv digest diverged from the dense reference "
        f"mask-matmul at densities {bad}"
    )
tr = summary["transpose"]
if tr["chunked_stages"] < 2:
    raise SystemExit(
        f"sparse: transpose did not decompose ({tr['chunked_stages']} stage)"
    )
if not tr["digest_match"]:
    raise SystemExit(
        "sparse: stage-decomposed transpose diverged from the monolithic "
        "exchange"
    )
hlo = (summary.get("telemetry") or {}).get("hlo_collectives") or {}
if hlo.get("audits", 0) < 1:
    raise SystemExit("sparse: --audit recorded no HLO audits")
if hlo.get("drift", 0) != 0:
    raise SystemExit(
        f"sparse: HLO audit drift on sparse collective sites: "
        f"{ {k: v for k, v in (hlo.get('sites') or {}).items() if v.get('drift')} }"
    )
spec = summary.get("spectral") or {}
if spec.get("label_agreement") != 1.0:
    raise SystemExit(
        f"sparse: Spectral sparse-vs-dense labels disagree "
        f"({spec.get('label_agreement')})"
    )
print(
    f"sparse ok: digest bit-identical at densities "
    f"{[r['density'] for r in summary['densities']]}, transpose "
    f"{tr['chunked_stages']}-stage bit-identical, "
    f"{hlo.get('audits')} audits zero-drift, spectral agreement 1.0"
)
EOF
    else
        sp_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$sp_out" "${REPORT}/sparse.jsonl" || true
    fi
    rm -f "$sp_out"
    if [ "$sp_rc" != 0 ]; then
        echo "=== sparse gate FAILED (rc=$sp_rc) ==="
        FAILED_SIZES="$FAILED_SIZES sparse"
    fi
fi

# Hierarchy gate (ISSUE 15): on the emulated 2x2 mesh — flat-vs-tiered
# digest bit-identity for exact payloads, audited cross-node wire-byte
# reduction >= the 1/local shard factor (x the PR 9 compression factor
# under a cross-tier precision), DASO send bit-equivalence through the
# shared tier primitive, and the ZeRO sharded-state watermark strictly
# below the replicated base. HEAT_TPU_CI_SKIP_HIERARCHY=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_HIERARCHY:-}" ]; then
    echo "=== hierarchy gate: tiered collectives + ZeRO (emulated 2x2 mesh) ==="
    hier_rc=0
    hier_out=$(mktemp)
    XLA_FLAGS="--xla_force_host_platform_device_count=4" JAX_PLATFORMS=cpu \
        HEAT_TPU_TOPOLOGY=2x2 \
        python - <<'EOF' > "$hier_out" 2>&1 || hier_rc=$?
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax

import heat_tpu as ht
from heat_tpu.telemetry import collectives as model, hlo

comm = ht.get_comm()
p = comm.size
assert p == 4, f"expected a 4-device mesh, got {p}"
topo = comm.topology()
assert (topo.node, topo.local) == (2, 2), topo
report = {"mesh": p, "topology": topo.describe()}
spec = comm.spec(0, 2)


def run(kernel, x):
    return jax.shard_map(
        kernel, mesh=comm.mesh, in_specs=spec, out_specs=spec
    )(x)


# -- flat-vs-tiered digest bit-identity (exact payloads) ---------------------
rng = np.random.default_rng(0)
xi = jnp.asarray(np.round(rng.standard_normal((4, 1027)) * 8).astype(np.float32))
xs = jax.device_put(xi, comm.sharding(0, 2))
digests = {}
for hier in ("0", "1"):
    os.environ["HEAT_TPU_HIERARCHICAL"] = hier
    out = {
        "psum": np.asarray(run(lambda v: comm.psum(v), xs)),
        "gather": np.asarray(run(lambda v: comm.all_gather(v)[: v.shape[0]], xs)),
        "rs": np.asarray(run(lambda v: comm.reduce_scatter(v).reshape(1, -1), xs)),
    }
    digests[hier] = {k: v.tobytes() for k, v in out.items()}
for k in digests["0"]:
    if digests["0"][k] != digests["1"][k]:
        raise SystemExit(f"hierarchy: {k} tiered digest != flat digest")

# -- audited cross-node byte reduction >= local shard factor ------------------
n = 4096
xb = jax.device_put(jnp.ones((4, n), jnp.float32), comm.sharding(0, 2))
os.environ["HEAT_TPU_HIERARCHICAL"] = "0"
aud_flat = hlo.audit_computation(
    lambda v: jax.shard_map(lambda b: comm.psum(b), mesh=comm.mesh,
                            in_specs=spec, out_specs=spec)(v), xb)
os.environ["HEAT_TPU_HIERARCHICAL"] = "1"
aud_hier = hlo.audit_computation(
    lambda v: jax.shard_map(lambda b: comm.psum(b), mesh=comm.mesh,
                            in_specs=spec, out_specs=spec)(v), xb)
flat_ar = [c for c in aud_flat.collectives if c.op == "all-reduce"]
cross = [c for c in aud_hier.collectives if c.op == "all-reduce"]
assert len(flat_ar) == 1 and len(cross) == 1
if flat_ar[0].in_bytes != cross[0].in_bytes * topo.local:
    raise SystemExit(
        f"hierarchy: cross-node payload {cross[0].in_bytes} is not the "
        f"1/{topo.local} shard of the flat {flat_ar[0].in_bytes}"
    )
reduction = flat_ar[0].wire_bytes / cross[0].wire_bytes
if reduction < topo.local:
    raise SystemExit(
        f"hierarchy: cross wire reduction {reduction:.2f}x below the "
        f"{topo.local}x shard factor"
    )
pred = model.hierarchical_allreduce_cost(n, 4, topo.node, topo.local)
rep = hlo.compare(aud_hier, pred)
if not rep.ok:
    raise SystemExit(
        f"hierarchy: tiered psum audit drifted: {json.dumps(rep.summary())}"
    )
report["cross_reduction"] = round(reduction, 2)

# x the PR 9 compression factor under a cross-tier precision
aud_q = hlo.audit_computation(
    lambda v: jax.shard_map(lambda b: comm.psum(b, precision="int8"),
                            mesh=comm.mesh, in_specs=spec,
                            out_specs=spec)(v), xb)
pred_q = model.hierarchical_allreduce_cost(n, 4, topo.node, topo.local, "int8")
rep_q = hlo.compare(aud_q, pred_q)
if not rep_q.ok:
    raise SystemExit(
        f"hierarchy: int8 cross-tier audit drifted: "
        f"{json.dumps(rep_q.summary())}"
    )
if pred_q.dcn_bytes * 3.5 > pred.dcn_bytes:
    raise SystemExit(
        f"hierarchy: int8 cross tier did not compress "
        f"({pred_q.dcn_bytes} vs exact {pred.dcn_bytes})"
    )
report["dcn_bytes"] = {"exact": pred.dcn_bytes, "int8": pred_q.dcn_bytes}

# -- DASO send bit-equivalence through the tier primitive ---------------------
os.environ.pop("HEAT_TPU_HIERARCHICAL", None)
from jax.sharding import PartitionSpec as P

daso = ht.optim.DASO(optax.sgd(0.05), total_epochs=2)
params = daso.stack_params(
    {"w": jnp.asarray(rng.standard_normal((24, 3)).astype(np.float32))}
)


def legacy_send(params):
    cast = daso.cast_dtype

    def kernel(params):
        params = jax.tree.map(lambda x: x[0], params)

        def one(x):
            rep = jax.lax.pmean(x, "local")
            return jax.lax.psum(rep.astype(cast), "node")[None]

        return jax.tree.map(one, params)

    stacked = P(("node", "local"))
    specs_p = jax.tree.map(lambda _: stacked, params)
    return jax.shard_map(
        kernel, mesh=daso.mesh, in_specs=(specs_p,), out_specs=specs_p
    )(params)


got = daso._get_global_send()(params)
want = legacy_send(params)
for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
    if np.asarray(a).tobytes() != np.asarray(b).tobytes():
        raise SystemExit("hierarchy: DASO tiered send != legacy send bits")

# -- ZeRO watermark: sharded state strictly below replicated ------------------
params0 = {"w": jnp.asarray(rng.standard_normal((512, 8)).astype(np.float32))}
zo = ht.optim.ZeroOptimizer(optax.adam(1e-2))
dp = ht.optim.DataParallelOptimizer(optax.adam(1e-2))
zb = zo.state_bytes_per_device(zo.init(params0))
db = sum(np.asarray(l).nbytes for l in jax.tree.leaves(dp.init(params0)))
if not (0 < zb < db):
    raise SystemExit(
        f"hierarchy: ZeRO state bytes/device {zb} not strictly below "
        f"replicated {db}"
    )
# and the trajectory matches the replicated base
grads = jax.tree.map(
    lambda l: jnp.asarray(rng.standard_normal(l.shape).astype(np.float32)),
    params0,
)
zp, zs = params0, zo.init(params0)
pp, ps = params0, dp.init(params0)
for _ in range(4):
    zp, zs = zo.step(zp, zs, grads)
    pp, ps = dp.step(pp, ps, grads)
drift = max(
    float(np.abs(np.asarray(a) - np.asarray(b)).max())
    for a, b in zip(jax.tree.leaves(zp), jax.tree.leaves(pp))
)
if drift > 1e-6:
    raise SystemExit(f"hierarchy: ZeRO trajectory drifted {drift}")
report["zero_state_bytes"] = {"sharded_per_device": zb, "replicated": db}
print(json.dumps({"hierarchy": "ok", **report}))
EOF
    cat "$hier_out"
    if [ -n "$REPORT" ]; then
        cp "$hier_out" "${REPORT}/hierarchy_gate.log" || true
    fi
    rm -f "$hier_out"
    if [ "$hier_rc" != 0 ]; then
        echo "=== hierarchy gate FAILED (rc=$hier_rc) ==="
        FAILED_SIZES="$FAILED_SIZES hierarchy"
    fi
fi

# FSDP gate (ISSUE 18): on the emulated 2x2 mesh — the big-model
# scenario end to end: a model whose REPLICATED parameters+state exceed
# a pinned HEAT_TPU_HBM_BUDGET trains under FSDP with the per-device
# watermark strictly below both the budget and the replicated base;
# knob-off dispatch bit-identical to the DataParallel program; enabled
# trajectory within documented-ulp (1e-6) of the replicated baseline
# (exact wire — the reduction ORDER differs, bits may not); prefetch
# depths bit-identical to each other (pure scheduling); per-layer
# audited gather wire bytes == fsdp_gather_cost with ZERO drift; and
# zero steady-state compiles at the fsdp_train_step site.
# HEAT_TPU_CI_SKIP_FSDP=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_FSDP:-}" ]; then
    echo "=== fsdp gate: sharded-parameter training (emulated 2x2 mesh) ==="
    fsdp_rc=0
    fsdp_out=$(mktemp)
    XLA_FLAGS="--xla_force_host_platform_device_count=4" JAX_PLATFORMS=cpu \
        HEAT_TPU_TOPOLOGY=2x2 \
        python - <<'EOF' > "$fsdp_out" 2>&1 || fsdp_rc=$?
import json
import os

import flax.linen as fnn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import heat_tpu as ht
from heat_tpu.core import program_cache
from heat_tpu.nn.fsdp import FSDP
from heat_tpu.parallel import fsdp as F
from heat_tpu.telemetry import collectives as model, hlo

comm = ht.get_comm()
p = comm.size
assert p == 4, f"expected a 4-device mesh, got {p}"
topo = comm.topology()
assert (topo.node, topo.local) == (2, 2), topo
report = {"mesh": p, "topology": topo.describe()}

STAGES = [fnn.Dense(96), fnn.Dense(96), fnn.Dense(32)]
OPT = optax.adam(1e-3)
rng = np.random.default_rng(0)
x = rng.standard_normal((8, 32)).astype(np.float32)
y = rng.standard_normal((8, 32)).astype(np.float32)


def loss_fn(out, yy):
    return jnp.mean((out - yy) ** 2)


def build(enabled, prefetch=1):
    os.environ["HEAT_TPU_FSDP"] = "1" if enabled else "0"
    return FSDP(list(STAGES), optimizer=OPT, prefetch=prefetch)


def run(net, steps=4):
    params = net.shard_params(net.init(jax.random.PRNGKey(0), x))
    state = net.init_opt_state(params)
    step = net.make_train_step(loss_fn)
    xb, yb = net.shard_batch(x, y)
    for _ in range(steps):
        params, state, loss = step(params, state, xb, yb)
    return net, params, state, step, (xb, yb)


def digest(net, params):
    return b"".join(
        np.asarray(l).tobytes()
        for l in jax.tree_util.tree_leaves(net.unshard_params(params))
    )


# -- knob-off dispatch is the DataParallel program, bit for bit ---------------
off_net, off_p, _, _, _ = run(build(enabled=False))


def full_forward(params, xx):
    for m, sp in zip(STAGES, params):
        xx = m.apply(sp, xx)
    return xx


dp = ht.nn.DataParallel(
    full_forward, comm, OPT, blocking_parameter_updates=True
)
dpp = jax.device_put(
    off_net.init(jax.random.PRNGKey(0), x), comm.replicated()
)
dps = jax.device_put(OPT.init(dpp), comm.replicated())
dstep = dp.make_train_step(
    lambda params, xx, yy: loss_fn(full_forward(params, xx), yy)
)
xb, yb = dp.shard_batch(x, y)
for _ in range(4):
    dpp, dps, _ = dstep(dpp, dps, xb, yb)
if digest(off_net, off_p) != b"".join(
    np.asarray(l).tobytes() for l in jax.tree_util.tree_leaves(dpp)
):
    raise SystemExit("fsdp: knob-off dispatch != DataParallel bits")

# -- big-model scenario: replicated exceeds the budget, FSDP fits -------------
on_net, on_p, on_s, on_step, on_batch = run(build(enabled=True))
rep_params = jax.device_put(
    on_net.init(jax.random.PRNGKey(0), x), comm.replicated()
)
rb = F.bytes_per_device(rep_params) + F.bytes_per_device(
    jax.device_put(OPT.init(rep_params), comm.replicated())
)
fb = F.bytes_per_device(on_p) + F.bytes_per_device(on_s)
budget = (fb + rb) // 2
os.environ["HEAT_TPU_HBM_BUDGET"] = str(budget)
# train MORE steps with the guard budget pinned: the sharded layout must
# keep fitting where the replicated layout could not
pp, ss = on_p, on_s
for _ in range(2):
    pp, ss, _ = on_step(pp, ss, *on_batch)
if not (0 < fb < budget < rb):
    raise SystemExit(
        f"fsdp: watermark {fb} not strictly below budget {budget} "
        f"below replicated {rb}"
    )
report["bytes_per_device"] = {
    "fsdp": fb, "replicated": rb, "hbm_budget": budget,
}

# -- enabled trajectory within documented ulp of the replicated base ----------
drift = max(
    float(np.abs(np.asarray(a) - np.asarray(b)).max())
    for a, b in zip(
        jax.tree_util.tree_leaves(on_net.unshard_params(on_p)),
        jax.tree_util.tree_leaves(off_net.unshard_params(off_p)),
    )
)
if drift > 1e-6:
    raise SystemExit(f"fsdp: trajectory drifted {drift} > 1e-6")
report["trajectory_drift"] = drift

# -- prefetch depths are pure scheduling: bit-identical -----------------------
d0 = digest(*run(build(enabled=True, prefetch=0))[:2])
d2 = digest(*run(build(enabled=True, prefetch=2))[:2])
if d0 != d2:
    raise SystemExit("fsdp: prefetch depth changed the bits")

# -- per-layer audited gather bytes == cost model, zero drift -----------------
plan = on_net._plan
axis = comm.axis_name
p_specs = plan.unflatten(
    [P(axis) if l.sharded else P() for l in plan.leaves]
)
fwd = jax.jit(jax.shard_map(
    lambda ps, xx: on_net._forward_local(
        ps, xx, plan, on_net.prefetch, remat=False
    ),
    mesh=comm.mesh, in_specs=(p_specs, P(axis)), out_specs=P(axis),
))
aud = hlo.audit_computation(fwd, on_p, on_batch[0])
predicted = sum(
    model.fsdp_gather_cost(
        l.chunk, 4, topo.node, topo.local, l.wire
    ).bytes
    for l in plan.leaves if l.sharded
)
audited = sum(
    c.wire_bytes for c in aud.collectives if c.op == "all-gather"
)
if audited != predicted:
    raise SystemExit(
        f"fsdp: audited gather bytes {audited} != predicted {predicted}"
    )
report["gather_wire_bytes"] = {"audited": audited, "predicted": predicted}

# -- zero steady-state compiles ----------------------------------------------
before = program_cache.site_stats("fsdp_train_step")
pp, ss = on_p, on_s
for _ in range(3):
    pp, ss, _ = on_step(pp, ss, *on_batch)
again = on_net.make_train_step(loss_fn)
after = program_cache.site_stats("fsdp_train_step")
if after["misses"] != before["misses"] or again is not on_step:
    raise SystemExit(
        f"fsdp: steady state recompiled ({before} -> {after})"
    )
report["train_step_site"] = after
print(json.dumps({"fsdp": "ok", **report}))
EOF
    cat "$fsdp_out"
    if [ -n "$REPORT" ]; then
        cp "$fsdp_out" "${REPORT}/fsdp_gate.log" || true
    fi
    rm -f "$fsdp_out"
    if [ "$fsdp_rc" != 0 ]; then
        echo "=== fsdp gate FAILED (rc=$fsdp_rc) ==="
        FAILED_SIZES="$FAILED_SIZES fsdp"
    fi
fi

# Pipeline gate (ISSUE 19): on an emulated 4x2 mesh (stages == node
# groups) —
#   (a) the 1f1b training digest is BIT-identical to gpipe (same loss,
#       params, and optimizer state bytes: pure scheduling),
#   (b) measured per-tick telemetry bubbles reconcile EXACTLY with the
#       analytic ScheduleTable for both schedules, and 1f1b's
#       steady-window bubble ticks are strictly fewer (12 -> 10 at
#       S=4, M=8),
#   (c) the 1f1b activation watermark (memory_analysis temp bytes) is
#       strictly below gpipe's,
#   (d) the audited inter-stage hop is zero-drift: emitted
#       collective-permute count == 2*(n_ticks-1), per-instruction wire
#       == pipeline_hop_cost, and the DCN split re-derived from the
#       emitted source-target pairs == the model's dcn_bytes exactly,
#   (e) a run SIGKILLed after checkpointing resumes onto a DIFFERENT
#       node x local factorization AND schedule with a bit-identical
#       continued trajectory, and
#   (f) zero steady-state compiles at the pipeline.step site.
# HEAT_TPU_CI_SKIP_PIPELINE=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_PIPELINE:-}" ]; then
    echo "=== pipeline gate: 1F1B over node groups (emulated 4x2 mesh) ==="
    pipe_rc=0
    pipe_out=$(mktemp)
    XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
        HEAT_TPU_TOPOLOGY=4x2 \
        python - <<'EOF' > "$pipe_out" 2>&1 || pipe_rc=$?
import json
import os
import signal
import subprocess
import sys
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax

import heat_tpu as ht
from heat_tpu import telemetry as tm
from heat_tpu.core import program_cache
from heat_tpu.nn import Pipeline
from heat_tpu.parallel import pipeline as pl
from heat_tpu.parallel import schedule as sch
from heat_tpu.telemetry import collectives as model, hlo

comm = ht.get_comm()
p = comm.size
assert p == 8, f"expected an 8-device mesh, got {p}"
report = {"mesh": p, "topology": comm.topology().describe()}

S, M, L, DIN = 4, 8, 4, 8
OPT = optax.adam(1e-2)


def layer_fn(w, h):
    return jnp.tanh(h @ w["w"] + w["b"])


def loss_fn(out, yy):
    return jnp.mean((out - yy) ** 2)


def make_layers():
    rng = np.random.default_rng(0)
    return [
        {"w": jnp.asarray(rng.standard_normal((DIN, DIN)) * 0.3,
                          jnp.float32),
         "b": jnp.asarray(rng.standard_normal((DIN,)) * 0.1, jnp.float32)}
        for _ in range(L)
    ]


def make_data():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((16, DIN)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((16, DIN)), jnp.float32)
    return x, y


def run(schedule, n_stages=S, steps=4):
    pipe = Pipeline(layer_fn, L, comm, OPT, loss_fn, n_stages=n_stages,
                    n_microbatches=M, schedule=schedule)
    params = pipe.shard_params(make_layers())
    state = pipe.init_opt_state(params)
    step = pipe.make_train_step()
    x, y = make_data()
    loss = None
    for _ in range(steps):
        params, state, loss = step(params, state, x, y)
    return pipe, params, state, step, (x, y), loss


def digest(pipe, params, state, loss):
    blobs = [
        np.asarray(l).tobytes()
        for layer in pipe.unshard_params(params)
        for l in jax.tree_util.tree_leaves(layer)
    ]
    blobs.append(np.asarray(loss).tobytes())
    return b"".join(blobs)


# -- (a) 1f1b digest bit-identical to gpipe -----------------------------------
g_pipe, g_p, g_s, g_step, g_batch, g_loss = run("gpipe")
f_pipe, f_p, f_s, f_step, f_batch, f_loss = run("1f1b")
if digest(g_pipe, g_p, g_s, g_loss) != digest(f_pipe, f_p, f_s, f_loss):
    raise SystemExit("pipeline: 1f1b digest differs from gpipe")
if np.asarray(g_loss).tobytes() != np.asarray(f_loss).tobytes():
    raise SystemExit("pipeline: schedule changed the loss bytes")
report["digest_bit_identical"] = True
report["loss"] = float(g_loss)

# -- (b) measured per-tick bubbles == analytic table, 1f1b strictly wins ------
measured = {}
for name in ("gpipe", "1f1b"):
    table = sch.build_schedule(S, M, name)
    mapping = sch.StageMapping(p, S)
    layers = make_layers()
    layout = pl.plan_pipeline(layers, mapping)
    rows = pl.shard_pipeline_params(layers, layout, comm)
    st = OPT.init(rows)
    x, y = make_data()
    mx, my = x.reshape(M, 2, DIN), y.reshape(M, 2, DIN)

    def fresh_layer(w, h):  # new callable => fresh trace under telemetry
        return jnp.tanh(h @ w["w"] + w["b"])

    sink = tempfile.mktemp(suffix=".jsonl")
    reg = tm.enable(sink)
    n0 = len(reg.events)
    try:
        step = pl.pipeline_step_program(
            fresh_layer, layout, mapping, table, comm=comm,
            loss_fn=loss_fn, optimizer=OPT)
        step(rows, st, mx, my)
        events = list(reg.events)[n0:]
    finally:
        tm.disable()
        os.path.exists(sink) and os.unlink(sink)
    ticks = [e for e in events if e.get("name") == "pipeline_tick"]
    if len(ticks) != table.n_ticks:
        raise SystemExit(
            f"pipeline: {name} traced {len(ticks)} tick spans, "
            f"table has {table.n_ticks}"
        )
    steady = sum(e["bubble"] for e in ticks if e["phase"] == "steady")
    total = sum(e["bubble"] for e in ticks)
    if steady != table.steady_bubble_ticks():
        raise SystemExit(
            f"pipeline: {name} measured {steady} steady bubbles, "
            f"table says {table.steady_bubble_ticks()}"
        )
    if total != table.bubble_cells():
        raise SystemExit(
            f"pipeline: {name} measured {total} bubble cells, "
            f"table says {table.bubble_cells()}"
        )
    measured[name] = {"steady_bubble_ticks": steady,
                      "bubble_cells": total,
                      "bubble_fraction": table.bubble_fraction()}
if not (measured["1f1b"]["steady_bubble_ticks"]
        < measured["gpipe"]["steady_bubble_ticks"]):
    raise SystemExit(f"pipeline: 1f1b did not win steady bubbles {measured}")
report["schedules"] = measured

# -- (c) 1f1b activation watermark strictly below gpipe -----------------------
def temp_bytes(name):
    table = sch.build_schedule(S, M, name)
    mapping = sch.StageMapping(p, S)
    layers = make_layers()
    layout = pl.plan_pipeline(layers, mapping)
    rows = pl.shard_pipeline_params(layers, layout, comm)
    st = OPT.init(rows)
    x, y = make_data()
    mx, my = x.reshape(M, 2, DIN), y.reshape(M, 2, DIN)
    step = pl.pipeline_step_program(
        layer_fn, layout, mapping, table, comm=comm,
        loss_fn=loss_fn, optimizer=OPT)
    ma = jax.jit(step).lower(rows, st, mx, my).compile().memory_analysis()
    return int(getattr(ma, "temp_size_in_bytes", 0) or 0)


g_temp, f_temp = temp_bytes("gpipe"), temp_bytes("1f1b")
if g_temp and f_temp:
    if not f_temp < g_temp:
        raise SystemExit(
            f"pipeline: 1f1b watermark {f_temp} not below gpipe {g_temp}"
        )
    report["activation_watermark"] = {"gpipe": g_temp, "1f1b": f_temp}
else:
    report["activation_watermark"] = "unavailable"

# -- (d) audited inter-stage hop: zero drift incl. the DCN split --------------
mapping = sch.StageMapping(p, S)
table = sch.build_schedule(S, M, "gpipe")
layers = make_layers()
layout = pl.plan_pipeline(layers, mapping)
rows = pl.shard_pipeline_params(layers, layout, comm)
st = OPT.init(rows)
x, y = make_data()
mx, my = x.reshape(M, 2, DIN), y.reshape(M, 2, DIN)
step = pl.pipeline_step_program(
    layer_fn, layout, mapping, table, comm=comm,
    loss_fn=loss_fn, optimizer=OPT)
audit = hlo.audit_computation(step, rows, st, mx, my)
perms = [c for c in audit.collectives if c.op == "collective-permute"]
hop = model.pipeline_hop_cost(
    2, DIN, 4, p, stride=mapping.local, local=comm.topology().local)
if hop.dcn_bytes != hop.bytes:
    raise SystemExit(
        "pipeline: stages==node groups must make the whole hop DCN"
    )
if len(perms) != 2 * (table.n_ticks - 1):
    raise SystemExit(
        f"pipeline: {len(perms)} permutes, expected {2 * (table.n_ticks - 1)}"
    )
emitted = emitted_dcn = 0
for c in perms:
    if c.wire_bytes != hop.bytes:
        raise SystemExit(
            f"pipeline: hop drift {c.wire_bytes} != {hop.bytes}"
        )
    pairs = [tuple(pr) for pr in c.groups]
    per_pair = c.wire_bytes // len(pairs)
    nl = comm.topology().local
    cross = [pr for pr in pairs if pr[0] // nl != pr[1] // nl]
    emitted += c.wire_bytes
    emitted_dcn += per_pair * len(cross)
if emitted != 2 * (table.n_ticks - 1) * hop.bytes:
    raise SystemExit("pipeline: total hop bytes drift")
if emitted_dcn != 2 * (table.n_ticks - 1) * hop.dcn_bytes:
    raise SystemExit(
        f"pipeline: DCN split drift {emitted_dcn} != "
        f"{2 * (table.n_ticks - 1) * hop.dcn_bytes}"
    )
report["hop_audit"] = {
    "permutes": len(perms), "wire_bytes": emitted,
    "dcn_bytes": emitted_dcn, "drift": 0,
}

# -- (e) SIGKILLed run resumes on a different factorization, bit-exact --------
ckpt_dir = tempfile.mkdtemp(prefix="pipe_gate_") + "/ckpt"
child = r"""
import os, signal
import jax.numpy as jnp
import numpy as np
import optax
import heat_tpu as ht
from heat_tpu.nn import Pipeline

comm = ht.get_comm()
S, M, L, DIN = 4, 8, 4, 8

def layer_fn(w, h):
    return jnp.tanh(h @ w["w"] + w["b"])

def loss_fn(out, yy):
    return jnp.mean((out - yy) ** 2)

rng = np.random.default_rng(0)
layers = [
    {"w": jnp.asarray(rng.standard_normal((DIN, DIN)) * 0.3, jnp.float32),
     "b": jnp.asarray(rng.standard_normal((DIN,)) * 0.1, jnp.float32)}
    for _ in range(L)
]
rng = np.random.default_rng(1)
x = jnp.asarray(rng.standard_normal((16, DIN)), jnp.float32)
y = jnp.asarray(rng.standard_normal((16, DIN)), jnp.float32)

pipe = Pipeline(layer_fn, L, comm, optax.adam(1e-2), loss_fn,
                n_stages=S, n_microbatches=M, schedule="1f1b")
params = pipe.shard_params(layers)
state = pipe.init_opt_state(params)
step = pipe.make_train_step()
for _ in range(2):
    params, state, loss = step(params, state, x, y)
pipe.save_checkpoint(os.environ["PIPE_GATE_CKPT"], params, state, step=2)
print("checkpointed at step 2", flush=True)
params, state, loss = step(params, state, x, y)  # dies mid-run
os.kill(os.getpid(), signal.SIGKILL)
"""
env = dict(os.environ, PIPE_GATE_CKPT=ckpt_dir)
proc = subprocess.run([sys.executable, "-c", child], env=env,
                      capture_output=True, text=True, timeout=600)
if proc.returncode != -signal.SIGKILL:
    raise SystemExit(
        f"pipeline: chaos child rc={proc.returncode}\n{proc.stdout}"
        f"\n{proc.stderr}"
    )
if "checkpointed at step 2" not in proc.stdout:
    raise SystemExit(f"pipeline: child never checkpointed\n{proc.stderr}")

# the uninterrupted reference (same seeds/schedule as the killed run)
ref_pipe, ref_p, ref_s, _, _, ref_loss = run("1f1b")
# restore onto 2 stages x 4 local AND the other schedule
res_pipe = Pipeline(layer_fn, L, comm, OPT, loss_fn, n_stages=2,
                    n_microbatches=M, schedule="gpipe")
res_params, res_state, cursor = res_pipe.resume(ckpt_dir, make_layers())
if cursor != 2:
    raise SystemExit(f"pipeline: resumed cursor {cursor} != 2")
res_step = res_pipe.make_train_step()
x, y = make_data()
res_loss = None
for _ in range(2):
    res_params, res_state, res_loss = res_step(res_params, res_state, x, y)
if np.asarray(ref_loss).tobytes() != np.asarray(res_loss).tobytes():
    raise SystemExit("pipeline: restored loss trajectory diverged")
ref_final = ref_pipe.unshard_params(ref_p)
res_final = res_pipe.unshard_params(res_params)
for ja, jb in zip(ref_final, res_final):
    for la, lb in zip(jax.tree_util.tree_leaves(ja),
                      jax.tree_util.tree_leaves(jb)):
        if np.asarray(la).tobytes() != np.asarray(lb).tobytes():
            raise SystemExit(
                "pipeline: restored params diverged from uninterrupted run"
            )
report["elastic"] = {
    "killed_at": "step 3 (SIGKILL)", "resumed_onto": "2x4 gpipe",
    "trajectory": "bit-identical",
}

# -- (f) zero steady-state compiles at the pipeline.step site -----------------
before = program_cache.site_stats("pipeline.step")
with tm.CompileWatcher() as watch:
    for _ in range(3):
        g_p, g_s, _ = g_step(g_p, g_s, *g_batch)
after = program_cache.site_stats("pipeline.step")
if after["misses"] != before["misses"]:
    raise SystemExit(
        f"pipeline: steady state recompiled ({before} -> {after})"
    )
if watch.backend_seconds != 0.0:
    raise SystemExit(
        f"pipeline: steady state hit the backend "
        f"({watch.backend_seconds}s)"
    )
report["step_site"] = after
print(json.dumps({"pipeline": "ok", **report}))
EOF
    cat "$pipe_out"
    if [ -n "$REPORT" ]; then
        cp "$pipe_out" "${REPORT}/pipeline_gate.log" || true
    fi
    rm -f "$pipe_out"
    if [ "$pipe_rc" != 0 ]; then
        echo "=== pipeline gate FAILED (rc=$pipe_rc) ==="
        FAILED_SIZES="$FAILED_SIZES pipeline"
    fi
fi

# Streaming gate (ISSUE 16, heat_tpu/streaming): a 2-file HDF5 stream
# under a pinned HEAT_TPU_HBM_BUDGET that forbids materializing the file
# set must show
#   (a) the out-of-core chunk-bytes watermark strictly below the
#       load-all bytes (the bounded-memory ingestion claim),
#   (b) digest parity of the streamed moments carry against the
#       in-memory full-pass reference,
#   (c) a zero-compile steady stream (one cached-program miss for the
#       steady chunk shape, hits for every later chunk), and
#   (d) the rolling replica update: a 2-replica pool rolls v2 and v3
#       through live open-loop traffic with ZERO failed requests, every
#       survivor on the final version, and zero steady-state backend
#       compiles on the replacements (shared-cache warm start).
# HEAT_TPU_CI_SKIP_STREAMING=1 opts out.
if [ -z "${HEAT_TPU_CI_SKIP_STREAMING:-}" ]; then
    echo "=== streaming gate: out-of-core fit + rolling update (4-device mesh) ==="
    stream_rc=0
    stream_out=$(mktemp)
    stream_fmt="--hdf5"
    python -c "import h5py" 2>/dev/null || stream_fmt=""
    if HEAT_TPU_TELEMETRY=1 python benchmarks/streaming/heat_tpu.py \
            --n 40000 --features 16 --files 2 $stream_fmt \
            --mesh 4 --replica-mesh 4 --replicas 2 --versions 3 \
            --hbm-budget 2M --requests 120 --rate 100 > "$stream_out"; then
        python - "$stream_out" <<'EOF' || stream_rc=$?
import json, sys

summary = None
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        continue
    if obj.get("bench") == "streaming":
        summary = obj
if summary is None:
    raise SystemExit("streaming: no summary line")

sf = summary["stream_fit"] or {}
if not sf.get("watermark_below_load_all"):
    raise SystemExit(
        f"streaming: chunk watermark not below the load-all bytes: {sf}"
    )
if not sf.get("digest_match"):
    raise SystemExit(
        f"streaming: streamed moments diverged from the in-memory fit: {sf}"
    )
if not sf.get("steady_zero_compile"):
    raise SystemExit(
        f"streaming: the steady stream kept compiling: {sf}"
    )

roll = summary["rolling"] or {}
if not roll.get("zero_failed_requests"):
    raise SystemExit(
        f"streaming: requests failed during the rolling update: {roll}"
    )
if not roll.get("all_on_final_version"):
    raise SystemExit(
        f"streaming: a replica is not on the final version: {roll}"
    )
if not roll.get("steady_backend_compiles_ok"):
    raise SystemExit(
        "streaming: a rolled replica backend-compiled in steady state "
        f"(shared-cache warm start failed): {roll}"
    )

print(
    f"streaming ok: watermark below load-all, digest parity, steady "
    f"zero-compile, roll to v3 with 0 failed requests "
    f"(p99 roll/steady = {roll.get('p99_roll_over_steady')})"
)
EOF
    else
        stream_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$stream_out" "${REPORT}/streaming.jsonl" || true
    fi
    rm -f "$stream_out"
    if [ "$stream_rc" != 0 ]; then
        echo "=== streaming gate FAILED (rc=$stream_rc) ==="
        FAILED_SIZES="$FAILED_SIZES streaming"
    fi
fi

if [ -z "${HEAT_TPU_CI_SKIP_CLUSTER_OBS:-}" ]; then
    echo "=== cluster-observability gate: merged tracing + fleet metrics + SLO burn (2-replica pool) ==="
    clobs_rc=0
    clobs_out=$(mktemp)
    if python benchmarks/serving/cluster_obs.py \
            --n 256 --features 16 --requests 40 --rate 80 \
            --slo-requests 12 --slo-rate 20 > "$clobs_out"; then
        python - "$clobs_out" <<'EOF' || clobs_rc=$?
import json, sys

summary = None
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        continue
    if obj.get("bench") == "cluster_obs":
        summary = obj
if summary is None:
    raise SystemExit("cluster-obs: no summary line")

if not (summary.get("off_clean") and summary.get("on_clean")):
    raise SystemExit(f"cluster-obs: load phases not clean: {summary}")
if not summary.get("off_tracing_zero"):
    raise SystemExit(
        "cluster-obs: tracing-off run recorded tracing counters "
        f"(the off posture must do zero per-hop work): {summary}"
    )
if not summary.get("digest_match"):
    raise SystemExit(
        "cluster-obs: tracing changed the answers (digest mismatch "
        f"between off and sampled-1.0 runs): {summary}"
    )
if not summary.get("metrics_merge_match"):
    raise SystemExit(
        "cluster-obs: merged /metrics request totals diverged from "
        f"the loadgen completions: {summary}"
    )
if not summary.get("hops_complete"):
    raise SystemExit(
        "cluster-obs: a sampled trace id is missing hop spans "
        f"({summary.get('complete_ids')}/{summary.get('sampled_ids')} "
        f"complete): {summary}"
    )
if not summary.get("p99_exact_match_inproc"):
    raise SystemExit(
        "cluster-obs: summarize_cluster p99 diverged from the "
        f"server's own histogram quantile: {summary}"
    )
if not summary.get("p99_within_bucket"):
    raise SystemExit(
        "cluster-obs: merged server-side p99 not within one bucket "
        f"width of the client-observed p99: {summary}"
    )
if not summary.get("merged_trace_ok"):
    raise SystemExit(
        "cluster-obs: merged Perfetto export missing pid tracks or "
        f"clock_sync records: {summary}"
    )
if not summary.get("slo_breach"):
    raise SystemExit(
        "cluster-obs: injected latency did not drive the SLO burn "
        f"rate above threshold: {summary}"
    )
if not summary.get("slo_burn_emitted"):
    raise SystemExit(
        "cluster-obs: breach detected but no slo_burn event/counter "
        f"emitted: {summary}"
    )

print(
    f"cluster-obs ok: digest bit-identity off/on, zero off-counters, "
    f"{summary.get('complete_ids')}/{summary.get('sampled_ids')} trace "
    f"ids complete across all hops, exact merged p99, SLO burn "
    f"breach + slo_burn emitted"
)
EOF
    else
        clobs_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$clobs_out" "${REPORT}/cluster_obs.jsonl" || true
    fi
    rm -f "$clobs_out"
    if [ "$clobs_rc" != 0 ]; then
        echo "=== cluster-observability gate FAILED (rc=$clobs_rc) ==="
        FAILED_SIZES="$FAILED_SIZES cluster-obs"
    fi
fi

if [ -z "${HEAT_TPU_CI_SKIP_AUTOSCALE:-}" ]; then
    echo "=== autoscale gate: SLO-driven scale-up/drain-down + chaos SIGKILL replacement (ISSUE 20) ==="
    autoscale_rc=0
    autoscale_out=$(mktemp)
    if python benchmarks/autoscale/run.py \
            --n 500 --features 16 --replica-mesh 1 \
            --profiles step --duration 15 --peak-rate 150 \
            --max-replicas 3 --drain-wait 25 \
            --chaos --chaos-duration 10 --chaos-rate 20 > "$autoscale_out"; then
        python - "$autoscale_out" <<'EOF' || autoscale_rc=$?
import json, sys

summary = None
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    try:
        obj = json.loads(line)
    except json.JSONDecodeError:
        continue
    if obj.get("bench") == "autoscale":
        summary = obj
if summary is None:
    raise SystemExit("autoscale: no summary line")

step = (summary.get("profiles") or {}).get("step") or {}
if step.get("failed") != 0:
    raise SystemExit(
        f"autoscale: step-load phase had failed requests: {step}"
    )
if not step.get("drained_to_min"):
    raise SystemExit(
        "autoscale: controller did not drain back down to the minimum "
        f"footprint after the load step ended: {step}"
    )
if not summary.get("steady_backend_compiles_ok"):
    raise SystemExit(
        "autoscale: a scaled-up replica compiled in steady state (the "
        f"shared-cache warm start is broken): {summary}"
    )
chaos = summary.get("chaos") or {}
if not chaos.get("replaced_within_bound"):
    raise SystemExit(
        "autoscale: SIGKILLed replica not replaced within "
        f"{chaos.get('replace_tick_bound')} controller ticks: {chaos}"
    )
if not chaos.get("zero_failed"):
    raise SystemExit(
        "autoscale: chaos kill surfaced failed requests despite "
        f"retry_in_flight: {chaos}"
    )
if chaos.get("replacement_steady_compiles") != 0:
    raise SystemExit(
        "autoscale: the chaos-respawned replica compiled in steady "
        f"state: {chaos}"
    )
if not (step.get("scale_ups") or 0) >= 1:
    raise SystemExit(
        f"autoscale: controller never scaled up under the step load: {step}"
    )
print(
    "autoscale ok: step load scaled up then drained to min with "
    f"0 failed, chaos replacement in {chaos.get('ticks_to_replace')} "
    "tick(s) with 0 failed and 0 steady compiles"
)
EOF
    else
        autoscale_rc=$?
    fi
    if [ -n "$REPORT" ]; then
        cp "$autoscale_out" "${REPORT}/autoscale.jsonl" || true
    fi
    rm -f "$autoscale_out"
    if [ "$autoscale_rc" != 0 ]; then
        echo "=== autoscale gate FAILED (rc=$autoscale_rc) ==="
        FAILED_SIZES="$FAILED_SIZES autoscale"
    fi
fi

if [ "$have_coverage" = 1 ]; then
    # merge the per-size coverage files, as the reference CI merges its
    # 8 mpirun passes (Jenkinsfile:33-44 / codecov)
    (cd "$REPORT" && python -m coverage combine .coverage.* \
        && python -m coverage report --include='*/heat_tpu/*' > coverage.txt \
        && tail -1 coverage.txt)
fi
FAILED_SIZES="$FAILED_SIZES$HEATLINT_FAILED"
if [ -n "$RETRIED_ABORTS" ]; then
    # surfaced even on a green sweep: silent retries would hide a rising
    # native-crash rate (advisor round-5 finding)
    echo "=== retried SIGABRT chunks (known XLA CPU heap flake):$RETRIED_ABORTS ==="
fi
if [ -n "$FAILED_SIZES" ]; then
    echo "=== FAILED at device counts:$FAILED_SIZES ==="
    exit 1
fi
echo "=== all device counts green ==="
