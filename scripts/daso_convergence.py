"""DASO-vs-blocking-DP convergence artifact (VERDICT r4 item 9).

Trains the same classifier (identical data, init, and batch schedule) two
ways and records both loss/accuracy curves:

* **blocking DP**: synchronous data parallelism — the gradient psum-mean
  equals the global-batch gradient, so the reference curve is plain Adam on
  the global batch (what `nn.DataParallel`'s blocking train step computes);
* **DASO**: the 2-level hierarchical async schedule (warmup -> cycling with
  skip decay -> cooldown) from `heat_tpu.optim.DASO`, as in
  `examples/nn/daso_training.py` (reference: examples/nn/imagenet-DASO.py).

Writes `artifacts/daso_convergence_r5.json` and asserts the curves agree:
DASO's final eval accuracy within `ACC_TOL` of blocking DP's and both
converged. Run on the virtual mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python scripts/daso_convergence.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples", "nn"))

import jax
import numpy as np
import optax

import daso_training as ex  # the example IS the workload definition
from heat_tpu.optim import DASO

ACC_TOL = 0.03  # final eval accuracy agreement
EPOCHS = 10
BATCHES = 16
BATCH = 128


def run_blocking_dp(x, y, x_eval, y_eval):
    params = ex.init_params()
    opt = optax.adam(2e-3)
    opt_state = opt.init(params)
    # heatlint: disable=HL001 -- single-process convergence reference:
    # a fresh standalone jit keeps this script's oracle independent of the
    # registry under test
    step = jax.jit(
        lambda p, s, xb, yb: (lambda l, g: (optax.apply_updates(p, opt.update(g, s, p)[0]), opt.update(g, s, p)[1], l))(
            *jax.value_and_grad(ex.loss_fn)(p, xb, yb)
        )
    )
    losses, accs = [], []
    for _ in range(EPOCHS):
        total = 0.0
        for b in range(BATCHES):
            lo = b * BATCH
            params, opt_state, loss = step(params, opt_state, x[lo : lo + BATCH], y[lo : lo + BATCH])
            total += float(loss)
        losses.append(total / BATCHES)
        accs.append(ex.accuracy(params, x_eval, y_eval))
    return losses, accs


def run_daso(x, y, x_eval, y_eval):
    daso = DASO(
        optax.adam(2e-3),
        total_epochs=EPOCHS,
        warmup_epochs=2,
        cooldown_epochs=2,
        max_global_skips=4,
        verbose=False,
    )
    daso.set_loss(ex.loss_fn)
    daso.last_batch = BATCHES - 1
    params = daso.stack_params(ex.init_params())
    opt_state = daso.init(params)
    losses, accs, phases = [], [], []
    for _ in range(EPOCHS):
        total = 0.0
        for b in range(BATCHES):
            lo = b * BATCH
            params, opt_state, loss = daso.step(
                params, opt_state, (x[lo : lo + BATCH], y[lo : lo + BATCH])
            )
            total += float(loss)
        avg = total / BATCHES
        daso.epoch_loss_logic(avg)
        losses.append(avg)
        accs.append(ex.accuracy(daso.unstack_params(params), x_eval, y_eval))
        phases.append(
            {"global_skip": daso.global_skip, "local_skip": daso.local_skip,
             "batches_to_wait": daso.batches_to_wait}
        )
    return losses, accs, phases


def main():
    n = BATCHES * BATCH
    x, y = ex.make_data(n, seed=0)
    x_eval, y_eval = ex.make_data(1024, seed=1)

    dp_loss, dp_acc = run_blocking_dp(x, y, x_eval, y_eval)
    da_loss, da_acc, phases = run_daso(x, y, x_eval, y_eval)

    delta_acc = abs(da_acc[-1] - dp_acc[-1])
    record = {
        "workload": "10-class blobs, 2-layer MLP, adam 2e-3, "
                    f"{EPOCHS} epochs x {BATCHES} batches x {BATCH}",
        "mesh_devices": jax.device_count(),
        "blocking_dp": {"loss": dp_loss, "eval_acc": dp_acc},
        "daso": {"loss": da_loss, "eval_acc": da_acc, "phases": phases},
        "final_acc_delta": delta_acc,
        "acc_tol": ACC_TOL,
        "agree": bool(delta_acc <= ACC_TOL and da_acc[-1] >= 0.95 and dp_acc[-1] >= 0.95),
    }
    out = os.path.join(os.path.dirname(__file__), "..", "artifacts",
                       "daso_convergence_r5.json")
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps({k: record[k] for k in ("final_acc_delta", "agree")}))
    assert record["agree"], record
    print(f"curves agree: DASO {da_acc[-1]:.2%} vs blocking DP {dp_acc[-1]:.2%}")


if __name__ == "__main__":
    main()
