"""On-chip tuning sweep (round 5): one JSON line per experiment.

Run on the real TPU to (a) verify the new Pallas cdist/Lloyd kernels beat
the XLA forms, (b) find the matmul steady-state MFU config, (c) measure the
moments pass against the HBM roofline. Each experiment is isolated — a
failure prints an error line and the sweep continues. Usage:

    python scripts/tpu_tune.py [--only cdist,kmeans,matmul,moments,rbf,lm,attn_bwd]

Keep sizes bench-equal so winners can be baked straight into bench.py.
"""

import argparse
import json
import sys
import time

import numpy as np


def _sync(arr):
    return float(arr[(0,) * arr.ndim])


def _time(fn, repeats=2):
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def emit(**kw):
    print(json.dumps(kw), flush=True)


def run_guarded(name, fn):
    try:
        fn()
    except Exception as e:  # noqa: BLE001
        emit(exp=name, error=repr(e))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None)
    args = ap.parse_args()
    only = set(args.only.split(",")) if args.only else None

    def want(name):
        return only is None or name in only

    import jax
    import jax.numpy as jnp

    import heat_tpu as ht

    emit(device=jax.devices()[0].device_kind, n=len(jax.devices()))

    # ---------------- cdist: pallas kernel vs XLA form -------------------
    m, k, reps = 16384, 128, 10
    if want("cdist"):
        x = ht.random.rand(m, k, dtype=ht.float32, split=0)

        def bench_cdist(tag, fn):
            fn()  # compile
            t = _time(fn)
            emit(exp=f"cdist_{tag}", gflops=round(reps * 2.0 * m * m * k / t / 1e9, 1),
                 seconds=round(t, 3))

        def run_pallas():
            from heat_tpu.spatial.pallas_cdist import euclid_pallas

            out = None
            for _ in range(reps):
                out = euclid_pallas(x.larray, x.larray)
            return _sync(out)

        def run_xla():
            from heat_tpu.spatial.distance import _local_dist, _quadratic_euclidean

            out = None
            for _ in range(reps):
                out = _local_dist(_quadratic_euclidean, x.larray, x.larray, jnp.float32)
            return _sync(out)

        run_guarded("cdist_pallas", lambda: bench_cdist("pallas", run_pallas))
        run_guarded("cdist_xla", lambda: bench_cdist("xla", run_xla))
        # block-size sweep for the pallas kernel
        from heat_tpu.spatial.pallas_cdist import euclid_pallas

        for bm, bn in ((256, 1024), (512, 512), (512, 1024), (512, 2048), (1024, 1024)):
            def run_blk(bm=bm, bn=bn):
                out = None
                for _ in range(reps):
                    out = euclid_pallas(x.larray, x.larray, block_m=bm, block_n=bn)
                _sync(out)

            def do(bm=bm, bn=bn, run_blk=run_blk):
                run_blk()
                t = _time(run_blk)
                emit(exp=f"cdist_pallas_bm{bm}_bn{bn}",
                     gflops=round(reps * 2.0 * m * m * k / t / 1e9, 1))

            run_guarded(f"cdist_blk_{bm}_{bn}", do)

        # precision-strategy sweep: Mosaic's lowering cost for the
        # in-kernel dot is not uniform (HIGH may lower off the MXU);
        # measure each strategy against the XLA quadratic form above
        for prec in ("DEFAULT", "HIGH", "HIGHEST", "bf16x3"):
            def run_prec(prec=prec):
                out = None
                for _ in range(reps):
                    out = euclid_pallas(
                        x.larray, x.larray, precision=prec,
                    )
                _sync(out)

            def do_prec(prec=prec, run_prec=run_prec):
                run_prec()
                t = _time(run_prec)
                emit(exp=f"cdist_pallas_prec_{prec}",
                     gflops=round(reps * 2.0 * m * m * k / t / 1e9, 1))

            run_guarded(f"cdist_prec_{prec}", do_prec)

    # ---------------- rbf fused epilogue ---------------------------------
    if want("rbf"):
        x = ht.random.rand(8192, 128, dtype=ht.float32, split=0)

        def run_rbf():
            out = None
            for _ in range(reps):
                out = ht.spatial.rbf(x, sigma=1.0, quadratic_expansion=True)
            return _sync(out.larray)

        def do_rbf():
            run_rbf()
            t = _time(run_rbf)
            emit(exp="rbf_fused", gflops=round(reps * 2.0 * 8192 * 8192 * 128 / t / 1e9, 1))

        run_guarded("rbf", do_rbf)

    # ---------------- kmeans: pallas lloyd vs XLA ------------------------
    if want("kmeans"):
        ns, d, kc, iters = 2_000_000, 64, 64, 50
        xs = ht.random.randn(ns, d, dtype=ht.float32, split=0)

        def fit(tag, force_xla):
            km = ht.cluster.KMeans(n_clusters=kc, init="random", max_iter=iters,
                                   tol=0.0, random_state=1)
            if force_xla:
                import heat_tpu.cluster.pallas_lloyd as pli

                orig = pli.pallas_lloyd_applicable
                pli.pallas_lloyd_applicable = lambda *a: False
                try:
                    km.fit(xs)
                finally:
                    pli.pallas_lloyd_applicable = orig
            else:
                km.fit(xs)
            return _sync(km.cluster_centers_.larray)

        for tag, force in (("pallas", False), ("xla", True)):
            def do(tag=tag, force=force):
                fit(tag, force)  # compile
                t = _time(lambda: fit(tag, force))
                emit(exp=f"kmeans_{tag}",
                     gflops=round(iters * 4.0 * ns * kc * d / t / 1e9, 1),
                     seconds=round(t, 3))

            run_guarded(f"kmeans_{tag}", do)

        # precision tier of the in-kernel scores dot, on the single-device
        # fit kernel directly (bench shapes; single-chip only — on a
        # multi-chip mesh the estimator dispatches to the sharded variant
        # and a direct single-device call on a sharded buffer would not be
        # comparable)
        from heat_tpu.cluster.pallas_lloyd import lloyd_fit_pallas

        if ht.get_comm().size > 1:
            emit(exp="kmeans_pallas_prec", skipped="multi-device mesh")
        for prec in (("DEFAULT", "HIGH", "bf16x3")
                     if ht.get_comm().size == 1 else ()):
            def do_lp(prec=prec):
                run = lambda: _sync(lloyd_fit_pallas(
                    xs.larray, xs.larray[:kc], ns, iters, 0.0, precision=prec
                )[0])
                run()
                t = _time(run)
                emit(exp=f"kmeans_pallas_prec_{prec}",
                     gflops=round(iters * 4.0 * ns * kc * d / t / 1e9, 1))

            run_guarded(f"kmeans_prec_{prec}", do_lp)

    # ---------------- matmul steady-state sweep --------------------------
    if want("matmul"):
        from heat_tpu.core.dndarray import DNDarray

        def chain_fn(a, y0, reps_):
            def chain(abuf, ybuf):
                A = DNDarray(abuf, a.shape, a.dtype, a.split, a.device, a.comm, True)
                Y = DNDarray(ybuf, y0.shape, y0.dtype, y0.split, y0.device, y0.comm, True)
                for _ in range(reps_):
                    Y = ht.matmul(A, Y)
                return Y.larray

            return jax.jit(chain)

        for n_, reps_ in ((8192, 30), (8192, 60), (16384, 10), (4096, 100)):
            def do(n_=n_, reps_=reps_):
                ab = (ht.random.rand(n_, n_, dtype=ht.float32, split=0) / float(n_)).astype(ht.bfloat16)
                yb = ht.random.rand(n_, n_, dtype=ht.float32, split=0).astype(ht.bfloat16)
                jc = chain_fn(ab, yb, reps_)
                run = lambda: _sync(jc(ab.larray, yb.larray).astype(jnp.float32))
                run()
                t = _time(run)
                gf = reps_ * 2.0 * n_ ** 3 / t / 1e9
                emit(exp=f"matmul_bf16_n{n_}_r{reps_}", gflops=round(gf, 1),
                     mfu_v5e=round(gf / 197e3, 3), seconds=round(t, 3))

            run_guarded(f"matmul_{n_}_{reps_}", do)

    # ---------------- lm_step remat-policy comparison --------------------
    if want("lm"):
        import optax

        from heat_tpu.nn import TransformerLM

        (v, dm, nh, nl, b, t, lreps) = (32768, 1024, 16, 12, 8, 1024, 8)
        key = jax.random.PRNGKey(0)
        toks = jax.random.randint(key, (b, t), 0, v, dtype=jnp.int32)

        for pol, bwd in ((None, "two_pass"), ("dots", "two_pass"),
                         (None, "fused"), ("dots", "fused")):
            def do(pol=pol, bwd=bwd):
                lm = TransformerLM(
                    vocab_size=v, d_model=dm, num_heads=nh, num_layers=nl,
                    max_len=t, attn_impl="flash", remat=True,
                    remat_policy=pol, dtype=jnp.bfloat16,
                    flash_bwd_impl=bwd,
                )
                params = lm.init(key, toks)
                opt = optax.adamw(1e-3)
                opt_state = opt.init(params)
                n_params = sum(
                    int(np.prod(l.shape))
                    for path, l in jax.tree_util.tree_leaves_with_path(params)
                    if not any(getattr(k_, "key", None) in ("embed", "pos")
                               for k_ in path)
                )

                def loss_fn(p, tk):
                    lg = lm.apply(p, tk)
                    return optax.softmax_cross_entropy_with_integer_labels(
                        lg[:, :-1].astype(jnp.float32), tk[:, 1:]
                    ).mean()

                @jax.jit
                def steps(p, s, tk):
                    def body(_, carry):
                        p_, s_ = carry
                        _, g = jax.value_and_grad(loss_fn)(p_, tk)
                        u, s_ = opt.update(g, s_, p_)
                        return optax.apply_updates(p_, u), s_

                    return jax.lax.fori_loop(0, lreps, body, (p, s))

                def run():
                    p, _ = steps(params, opt_state, toks)
                    return _sync(jax.tree.leaves(p)[0].astype(jnp.float32))

                run()
                tm = _time(run)
                gf = lreps * 6.0 * n_params * b * t / tm / 1e9
                emit(exp=f"lm_step_remat_{pol or 'full'}_bwd_{bwd}",
                     gflops=round(gf, 1), mfu_v5e=round(gf / 197e3, 3))

            run_guarded(f"lm_{pol}_{bwd}", do)

    # ---------------- attention backward block sweep ---------------------
    if want("attn_bwd"):
        from heat_tpu.parallel import flash_attention

        (b, t, h, d, areps) = (4, 4096, 8, 128, 10)
        akey = jax.random.PRNGKey(1)
        kq, kk, kv = jax.random.split(akey, 3)
        aq = jax.random.normal(kq, (b, t, h, d), dtype=jnp.bfloat16)
        ak = jax.random.normal(kk, (b, t, h, d), dtype=jnp.bfloat16)
        av = jax.random.normal(kv, (b, t, h, d), dtype=jnp.bfloat16)

        for impl, (bq, bk) in [
            (im, blks)
            for im in ("two_pass", "fused")
            for blks in ((256, 512), (512, 512), (512, 1024), (1024, 512),
                         (1024, 1024), (256, 1024), (512, 2048))
        ]:
            def do_ab(impl=impl, bq=bq, bk=bk):
                def loss(q_, k_, v_):
                    return flash_attention(
                        q_, k_, v_, causal=True, block_q=bq, block_k=bk,
                        bwd_impl=impl,
                    ).astype(jnp.float32).sum()

                @jax.jit
                def chain(q, k, v):
                    def body(_, carry):
                        q_, k_, v_ = carry
                        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q_, k_, v_)
                        return (q_ + dq * jnp.bfloat16(1e-3),
                                k_ + dk * jnp.bfloat16(1e-3),
                                v_ + dv * jnp.bfloat16(1e-3))

                    return jax.lax.fori_loop(0, areps, body, (q, k, v))[0]

                run = lambda: _sync(chain(aq, ak, av).astype(jnp.float32))
                run()
                tm = _time(run)
                gf = areps * 9.0 * b * h * t * t * d / tm / 1e9
                emit(exp=f"attn_bwd_{impl}_bq{bq}_bk{bk}", gflops=round(gf, 1),
                     mfu_v5e=round(gf / 197e3, 3))

            run_guarded(f"attn_bwd_{impl}_{bq}_{bk}", do_ab)

    # ---------------- moments vs HBM roofline ----------------------------
    if want("moments"):
        nm, dm, mreps = 8_000_000, 64, 10
        xm = ht.random.randn(nm, dm, dtype=ht.float32, split=0)

        @jax.jit
        def one_pass(buf):
            from heat_tpu.core.dndarray import DNDarray

            X = DNDarray(buf, xm.shape, xm.dtype, xm.split, xm.device, xm.comm, True)
            return (ht.mean(X, axis=0) + ht.var(X, axis=0)).larray

        def run_m():
            out = None
            for _ in range(mreps):
                out = one_pass(xm.larray)
            return _sync(out)

        def do_m():
            run_m()
            t = _time(run_m)
            gf = mreps * 4.0 * nm * dm / t / 1e9
            bytes_read = mreps * nm * dm * 4
            emit(exp="moments", gflops=round(gf, 1),
                 effective_gbps=round(bytes_read / t / 1e9, 1),
                 note="gbps assumes ONE read of X per pass")

        run_guarded("moments", do_m)


if __name__ == "__main__":
    main()
