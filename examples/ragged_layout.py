#!/usr/bin/env python
"""Rank-proportional work WITHOUT ragged shards — ``ht.ragged``, the
first-class substitute for the reference's ``redistribute_(target_map)``
(PARITY.md, "redistribute_ and ragged target maps").

The reference framework lets MPI rank ``r`` own an arbitrary number of
split-dim rows ("rank 0 holds 7, rank 1 holds 2") because Alltoallv makes
ragged layouts first-class. The XLA layout model has exactly ONE physical
layout per ``(gshape, split, mesh)`` — equal ceil-rule shards with a tail
pad — so that design point is formally closed here. What the reference
*uses* ragged maps for survives as :class:`heat_tpu.Ragged`
(heat_tpu/core/ragged.py), toured below:

1. **Masked proportional work** — the data stays canonical; the ragged
   intent ("position ``i`` processes ``counts[i]`` rows") is metadata:
   ``r.owner`` / ``r.mask(i)`` ride the same sharding as the data, so
   each device touches only its assigned rows inside one compiled
   program. Numerically identical to the ragged-layout computation it
   substitutes (asserted below).

2. **Free redistribution** — ``r.redistribute(new_counts)`` rewrites the
   intent without moving a byte (the reference pays an Alltoallv);
   ``r.resplit(axis)`` changes the physical layout through the
   communication-aware relayout planner, which decomposes the move into
   bounded-memory chunks near the HBM ceiling instead of raising.

3. **Mesh reshape** — when the imbalance is *structural* (a fast group of
   devices should take more of the batch than a slow group), factor the
   flat mesh into a 2-D ``(group, worker)`` mesh and shard the big axis
   over only one of the factors; the other factor carries the skew.

Run:  python examples/ragged_layout.py            (4 virtual CPU devices)
"""

import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import jax.numpy as jnp
import numpy as np

import heat_tpu as ht


def main():
    comm = ht.get_comm()
    p = comm.size
    n, d = 14, 3
    x = ht.array(
        np.arange(n * d, dtype=np.float32).reshape(n, d), split=0
    )

    print(f"mesh: {p} positions; canonical lshape_map (ceil rule):")
    print(x.lshape_map[:, 0], "rows per position — the ONE physical layout")

    # ----------------------------------------------------------------- 1
    # The ragged intent: position i should process counts[i] rows
    # (rank-proportional work, e.g. matched to heterogeneous I/O rates).
    counts = np.zeros(p, dtype=np.int64)
    weights = np.arange(1, p + 1, dtype=np.float64)
    counts[:] = np.floor(weights / weights.sum() * n).astype(np.int64)
    counts[-1] += n - counts.sum()  # remainder to the last position
    print(f"\nragged intent (rows per position): {counts.tolist()}")

    # redistribute_ to that map is formally closed — show the documented raise
    want = x.lshape_map.copy()
    start = 0
    for i, c in enumerate(counts):
        want[i, 0] = c
    try:
        x.redistribute_(target_map=want)
    except NotImplementedError as e:
        print(f"redistribute_(ragged map) raises as documented:\n  {e}\n")

    # First-class substitute: ht.ragged carries the intent as metadata on
    # the canonical layout. Row j belongs to position r.owner[j]; the
    # mask r.mask(i) is what "position i's work" means — no ragged shards.
    r = ht.ragged(x, counts)
    print(f"first-class layout: {r}")
    print("owner map:", r.owner.numpy().tolist())

    # Example workload: per-position partial sums of x's rows — computed
    # (a) with the masked canonical layout, (b) with the ragged slices the
    # reference would hold. The two must agree exactly.
    masked = []
    for i in range(p):
        mask = r.mask(i).astype(ht.float32).reshape((n, 1))
        masked.append((x * mask).sum(axis=0).numpy())
    ragged_ref = []
    xs = x.numpy()
    start = 0
    for c in counts:
        ragged_ref.append(xs[start:start + c].sum(axis=0))
        start += c
    np.testing.assert_allclose(np.stack(masked), np.stack(ragged_ref),
                               rtol=1e-6)
    print("masked canonical layout == ragged-layout result: OK")
    print("per-position row sums:\n", np.stack(masked))

    # block views are the rows a ragged shard would hold...
    np.testing.assert_allclose(
        r.block(0).numpy(), x.numpy()[: int(counts[0])], rtol=0
    )
    # ...and redistributing the intent moves ZERO bytes (the reference's
    # redistribute_ ships the whole array through Alltoallv for this)
    flipped = r.redistribute(counts[::-1].copy())
    assert flipped.array is r.array
    print(f"redistribute({list(map(int, counts[::-1]))}): zero-copy OK")

    # ----------------------------------------------------------------- 2
    # Structural skew via mesh reshape: a (group, worker) factorization.
    # Group 0 gets 1 worker, group 1 gets p-1 workers — batch rows shard
    # over 'worker' only, so group 1 processes (p-1)x the rows of group 0
    # per program step. The skew lives in the MESH, the layout stays
    # canonical within each group.
    if p >= 2:
        devices = np.asarray(jax.devices()[:p])
        mesh = jax.sharding.Mesh(
            devices.reshape(2, p // 2), ("group", "worker")
        )
        spec = jax.sharding.PartitionSpec("worker")
        rows = jnp.arange(8.0)
        sharded = jax.device_put(
            rows, jax.sharding.NamedSharding(mesh, spec)
        )
        print(
            f"\nmesh reshape: {dict(zip(mesh.axis_names, mesh.devices.shape))}"
            f" — 'worker' shards the batch, 'group' carries the skew"
        )
        for s in sharded.addressable_shards:
            print(f"  {s.device}: rows {s.index[0].start}..{s.index[0].stop}")

    print("\ndone — see PARITY.md 'redistribute_ and ragged target maps'")


if __name__ == "__main__":
    main()
