"""KNN classification demo on the bundled iris dataset (reference
examples/classification/demo_knn.py — which loads iris.h5 and runs
leave-fold-out KNN verification; here the dataset comes from
heat_tpu.datasets and the whole script runs on the mesh unchanged).

Run: python examples/classification/demo_knn.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../..")))

import numpy as np

import heat_tpu as ht
from heat_tpu.classification import KNeighborsClassifier


def calculate_accuracy(pred: ht.DNDarray, truth: ht.DNDarray) -> float:
    """Fraction of matching integer labels."""
    return float((pred.numpy() == truth.numpy()).mean())


def main():
    X, Y = ht.datasets.load_iris(split=0)

    # leave-one-fold-out verification, the reference demo's scheme: hold out
    # every k-th sample as the test fold, train on the rest
    folds = 5
    accuracies = []
    n = X.shape[0]
    for fold in range(folds):
        mask = np.zeros(n, dtype=bool)
        mask[fold::folds] = True
        train_idx = ht.array(np.nonzero(~mask)[0])
        test_idx = ht.array(np.nonzero(mask)[0])

        knn = KNeighborsClassifier(n_neighbors=5)
        knn.fit(X[train_idx], Y[train_idx])
        pred = knn.predict(X[test_idx])
        acc = calculate_accuracy(pred, Y[test_idx])
        accuracies.append(acc)
        print(f"fold {fold}: accuracy {acc:.3f}")

    print(f"mean accuracy over {folds} folds: {np.mean(accuracies):.3f}")


if __name__ == "__main__":
    main()
