"""Long-context attention over a sequence-sharded mesh.

The capability the reference's ring-pipelined kernels point at (SURVEY §5):
attention over a sequence far longer than one chip's activation budget, K/V
circulated over the ICI ring with flash-style renormalization. Compares the
ring and ulysses schedules against each other.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/long_context/ring_attention_demo.py
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

import heat_tpu as ht
from heat_tpu.parallel import ring_attention, ulysses_attention


def main(batch=1, seq=2048, heads=8, head_dim=64):
    # seq=2048 keeps the CPU-mesh demo quick; on a real TPU slice push this
    # to 128k+ — per-chip activation memory stays O(seq/p)
    comm = ht.get_comm()
    p = comm.size
    seq = (seq // p) * p
    rng = np.random.default_rng(0)
    shape = (batch, seq, heads, head_dim)
    sharding = comm.sharding(1, 4)  # shard the sequence axis
    q = jax.device_put(jnp.asarray(rng.standard_normal(shape), jnp.bfloat16), sharding)
    k = jax.device_put(jnp.asarray(rng.standard_normal(shape), jnp.bfloat16), sharding)
    v = jax.device_put(jnp.asarray(rng.standard_normal(shape), jnp.bfloat16), sharding)

    for name, fn in [("ring", ring_attention), ("ulysses", ulysses_attention)]:
        out = fn(q, k, v, comm=comm, causal=True)  # compile
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        out = fn(q, k, v, comm=comm, causal=True)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        flops = 4.0 * batch * heads * seq * seq * head_dim / 2  # causal half
        print(
            f"{name:8s}: seq={seq} over {p} shards -> {dt * 1e3:.1f} ms, "
            f"{flops / dt / 1e12:.2f} TFLOP/s"
        )

    o1 = ring_attention(q, k, v, comm=comm, causal=True)
    o2 = ulysses_attention(q, k, v, comm=comm, causal=True)
    print("ring vs ulysses max |diff|:", float(jnp.abs(o1 - o2).max()))


if __name__ == "__main__":
    main()
