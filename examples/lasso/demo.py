"""Lasso path demo on the bundled diabetes dataset (reference
examples/lasso/demo.py — computes the coefficient path over a lambda
sweep and plots it; here plotting is matplotlib-gated and the path
prints as text so the demo runs headless on the mesh).

Run: python examples/lasso/demo.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../..")))

import numpy as np

import heat_tpu as ht
from heat_tpu.regression import Lasso

FEATURES = ["age", "sex", "bmi", "bp", "s1", "s2", "s3", "s4", "s5", "s6"]


def main():
    X, y = ht.datasets.load_diabetes(split=0)
    y = y.expand_dims(1)

    # column-normalize as the reference demo does before fitting
    X = X / ht.sqrt(ht.mean(X**2, axis=0))

    lamda = np.logspace(0, 4, 10) / 10
    theta_list = []
    for la in lamda:
        est = Lasso(lam=float(la), max_iter=100)
        est.fit(X, y)
        theta_list.append(est.theta.numpy().flatten())
    theta_lasso = np.stack(theta_list).T[1:, :]  # drop intercept row

    print("lambda:    " + "  ".join(f"{la:8.3f}" for la in lamda))
    for name, row in zip(FEATURES, theta_lasso):
        print(f"{name:>6}: " + "  ".join(f"{v:8.4f}" for v in row))
    nonzero = (np.abs(theta_lasso) > 1e-8).sum(axis=0)
    print("active coefficients per lambda:", nonzero.tolist())

    try:
        from matplotlib import pyplot as plt

        plt.figure(figsize=(8, 5))
        for name, row in zip(FEATURES, theta_lasso):
            plt.plot(lamda, row, label=name)
        plt.xscale("log")
        plt.xlabel("lambda")
        plt.ylabel("coefficient")
        plt.title("Lasso paths - heat_tpu implementation")
        plt.legend()
        out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lasso_paths.png")
        plt.savefig(out, dpi=120)
        print(f"wrote {out}")
    except ImportError:
        print("(matplotlib not installed - skipping the plot)")


if __name__ == "__main__":
    main()
