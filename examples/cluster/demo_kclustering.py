"""Clustering demo (reference: examples/cluster/demo_kClustering.py).

Fits KMeans / KMedians / KMedoids on synthetic Gaussian blobs sharded over
the mesh and reports inertia + centers. Run on TPU as-is, or on a virtual
mesh with:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/cluster/demo_kclustering.py
"""

import numpy as np

import heat_tpu as ht


def make_blobs(n=4000, d=8, k=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10, 10, size=(k, d))
    data = np.concatenate(
        [c + rng.standard_normal((n // k, d)) for c in centers], axis=0
    ).astype(np.float32)
    rng.shuffle(data)
    return data


def main():
    data = ht.array(make_blobs(), split=0)
    for cls in (ht.cluster.KMeans, ht.cluster.KMedians, ht.cluster.KMedoids):
        est = cls(n_clusters=4, init="kmeans++", max_iter=50, random_state=1)
        est.fit(data)
        print(
            f"{cls.__name__}: {est.n_iter_} iters, "
            f"inertia {float(est.inertia_):.2f}, "
            f"centers shape {tuple(est.cluster_centers_.shape)}"
        )


if __name__ == "__main__":
    main()
