"""Train the flagship TransformerLM on a synthetic language task.

The reference's flagship examples (examples/nn/mnist.py, imagenet-DASO.py)
demonstrate converged training of its DP stack; this is the same
demonstration for the model family this framework adds: a causal LM with
the pluggable attention core, trained data-parallel over the mesh, with
per-epoch held-out perplexity.

Task: next-token prediction on sequences from a random 3-gram grammar —
enough structure that a 2-layer LM drives perplexity far below the
uniform-vocabulary baseline within a minute on the virtual mesh.

Run:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/nn/lm_training.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../..")))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import heat_tpu as ht
from heat_tpu.core import program_cache
from heat_tpu.nn import TransformerLM

VOCAB = 32
SEQ = 64
D_MODEL = 64
HEADS = 4
LAYERS = 2
BATCH = 32
STEPS_PER_EPOCH = 40
EPOCHS = 6


def make_corpus(n_seqs, seed):
    """Sequences from a fixed random 3-gram table: P(t | t-2, t-1)."""
    master = np.random.default_rng(7)
    # each (prev2, prev1) context strongly prefers 4 of the 32 tokens
    table = master.dirichlet(np.full(VOCAB, 0.05), size=(VOCAB, VOCAB))
    rng = np.random.default_rng(seed)
    seqs = np.zeros((n_seqs, SEQ), dtype=np.int32)
    seqs[:, :2] = rng.integers(0, VOCAB, (n_seqs, 2))
    for t in range(2, SEQ):
        p = table[seqs[:, t - 2], seqs[:, t - 1]]
        cum = p.cumsum(axis=1)
        u = rng.random((n_seqs, 1))
        seqs[:, t] = (u > cum).sum(axis=1)
    return jnp.asarray(seqs)


def main():
    comm = ht.get_comm()
    # flash = the Pallas kernel: native on TPU; on the CPU demo mesh it
    # would run under the (slow) interpreter, so use the XLA core there
    impl = "flash" if jax.default_backend() == "tpu" else "local"
    print(f"mesh: {comm.size} devices, attention core: {impl}")

    lm = TransformerLM(vocab_size=VOCAB, d_model=D_MODEL, num_heads=HEADS,
                       num_layers=LAYERS, max_len=SEQ, attn_impl=impl)
    train = make_corpus(BATCH * STEPS_PER_EPOCH, seed=1)
    heldout = make_corpus(256, seed=2)

    params = lm.init(jax.random.PRNGKey(0), train[:2])
    opt = optax.adamw(1e-2)
    opt_state = opt.init(params)

    def loss_fn(p, toks):
        logits = lm.apply(p, toks[:, :-1])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, toks[:, 1:]
        ).mean()

    # dispatch through the program registry — the sanctioned jit site
    # (heatlint HL001): the demo's step/eval programs get the same cache
    # keying, HLO-audit visibility, and retrace telemetry as the framework
    def _step_fn(p, s, toks):
        l, g = jax.value_and_grad(loss_fn)(p, toks)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, l

    step = program_cache.cached_program(
        "example.lm_train_step", (impl, D_MODEL, LAYERS), lambda: _step_fn,
        comm=comm,
    )
    eval_loss = program_cache.cached_program(
        "example.lm_eval_loss", (impl, D_MODEL, LAYERS), lambda: loss_fn,
        comm=comm,
    )

    # batches sharded over the mesh's data axis — the DP layout
    shard = comm.sharding(0, 2)
    ppl0 = float(jnp.exp(eval_loss(params, jax.device_put(heldout, shard))))
    print(f"initial held-out perplexity {ppl0:.1f} (uniform = {VOCAB})")

    for epoch in range(EPOCHS):
        for i in range(STEPS_PER_EPOCH):
            batch = jax.device_put(train[i * BATCH:(i + 1) * BATCH], shard)
            params, opt_state, l = step(params, opt_state, batch)
        ppl = float(jnp.exp(eval_loss(params, jax.device_put(heldout, shard))))
        print(f"epoch {epoch}: train loss {float(l):.3f}, held-out perplexity {ppl:.2f}")

    assert ppl < ppl0 / 2, "LM failed to learn the 3-gram structure"
    print("converged: perplexity", round(ppl, 2), "vs uniform", VOCAB)


if __name__ == "__main__":
    main()
