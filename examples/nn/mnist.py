"""Data-parallel training example (reference: examples/nn/mnist.py).

Trains a small MLP classifier with `heat_tpu.nn.DataParallel` +
`heat_tpu.utils.data.DataLoader`. Uses torchvision MNIST when available and
synthetic digit-like blobs otherwise, so the example runs in any image.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import heat_tpu as ht
from heat_tpu.nn import DataParallel
from heat_tpu.utils.data import DataLoader, Dataset


def load_data(n=8192, train=True):
    try:
        from heat_tpu.utils.data import MNISTDataset

        ds = MNISTDataset("/tmp/mnist-data", train=train)
        return ds
    except ImportError:
        # synthetic 10-class blobs shaped like flattened digits
        rng = np.random.default_rng(0 if train else 1)
        protos = np.random.default_rng(42).standard_normal((10, 784)).astype(np.float32)
        labels = rng.integers(0, 10, n).astype(np.int32)
        images = protos[labels] + 0.3 * rng.standard_normal((n, 784)).astype(
            np.float32
        )
        return Dataset(
            ht.array(images, split=0), targets=ht.array(labels, split=0)
        )


def init_params(rng_key, d_in=784, d_hidden=128, n_classes=10):
    k1, k2 = jax.random.split(rng_key)
    return {
        "w1": jax.random.normal(k1, (d_in, d_hidden)) * 0.05,
        "b1": jnp.zeros((d_hidden,)),
        "w2": jax.random.normal(k2, (d_hidden, n_classes)) * 0.05,
        "b2": jnp.zeros((n_classes,)),
    }


def apply(params, x):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def loss_fn(params, x, y):
    logits = apply(params, x)
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def evaluate(dp, params, dataset, batch_size=512):
    """Accuracy over a dataset (the reference example's evaluated run).
    shuffle=False: deterministic pass so every epoch scores the same set."""
    loader = DataLoader(dataset, batch_size=batch_size, shuffle=False)
    correct = total = 0
    for xb, yb in loader:
        xb = xb.reshape(xb.shape[0], -1) / 255.0 if xb.ndim > 2 else xb
        logits = dp(params, xb)
        pred = jnp.argmax(logits, axis=-1)
        correct += int(jnp.sum(pred == jnp.asarray(yb)))
        total += int(pred.shape[0])
    return correct / max(total, 1)


def main(epochs=3, batch_size=256, lr=1e-3):
    dataset = load_data()
    eval_set = load_data(n=2048, train=False)
    loader = DataLoader(dataset, batch_size=batch_size)
    dp = DataParallel(apply, optimizer=optax.adam(lr),
                      blocking_parameter_updates=True)
    step = dp.make_train_step(loss_fn)

    params = jax.device_put(
        init_params(jax.random.key(0)), dp.comm.replicated()
    )
    opt_state = dp.optimizer.init(params)

    acc = 0.0
    for epoch in range(epochs):
        total, nb = 0.0, 0
        for xb, yb in loader:
            xb = xb.reshape(xb.shape[0], -1) / 255.0 if xb.ndim > 2 else xb
            params, opt_state, loss = step(params, opt_state, xb, yb)
            total += float(loss)
            nb += 1
        acc = evaluate(dp, params, eval_set)
        print(
            f"epoch {epoch}: loss {total / nb:.4f} ({nb} batches), "
            f"eval accuracy {acc:.2%}"
        )
    return acc


if __name__ == "__main__":
    main()
