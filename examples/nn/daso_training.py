"""Hierarchical asynchronous training with DASO (reference:
examples/nn/imagenet-DASO.py, condensed to a classifier that converges).

Shows the full DASO loop on a real model: 2-level (node x local) mesh,
warmup -> cycling -> cooldown phases, plateau-driven skip decay, the delayed
cross-node bf16 parameter merge — plus an evaluated accuracy each epoch (the
reference's flagship example trains ResNet on ImageNet and reports top-1).
Runs on a virtual mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/nn/daso_training.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import heat_tpu as ht
from heat_tpu.optim import DASO


N_CLASSES = 10
D_IN = 64
D_HIDDEN = 64


def make_data(n, seed):
    """Synthetic 10-class blobs (separable; accuracy should reach ~100%)."""
    rng = np.random.default_rng(seed)
    protos = np.random.default_rng(42).standard_normal((N_CLASSES, D_IN)).astype(np.float32)
    labels = rng.integers(0, N_CLASSES, n).astype(np.int32)
    feats = protos[labels] + 0.4 * rng.standard_normal((n, D_IN)).astype(np.float32)
    return jnp.asarray(feats), jnp.asarray(labels)


def init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w1": jnp.asarray(rng.standard_normal((D_IN, D_HIDDEN)).astype(np.float32) * 0.1),
        "b1": jnp.zeros((D_HIDDEN,), jnp.float32),
        "w2": jnp.asarray(rng.standard_normal((D_HIDDEN, N_CLASSES)).astype(np.float32) * 0.1),
        "b2": jnp.zeros((N_CLASSES,), jnp.float32),
    }


def apply(params, x):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def loss_fn(params, xb, yb):
    return optax.softmax_cross_entropy_with_integer_labels(apply(params, xb), yb).mean()


def accuracy(params, x, y):
    pred = jnp.argmax(apply(params, x), axis=-1)
    return float(jnp.mean((pred == y).astype(jnp.float32)))


def main(epochs=8, batches_per_epoch=16, batch_size=128):
    n = batches_per_epoch * batch_size
    x, y = make_data(n, seed=0)
    x_eval, y_eval = make_data(1024, seed=1)

    daso = DASO(
        optax.adam(2e-3),
        total_epochs=epochs,
        warmup_epochs=2,
        cooldown_epochs=2,
        max_global_skips=4,
        verbose=False,
    )
    daso.set_loss(loss_fn)
    daso.last_batch = batches_per_epoch - 1

    params = daso.stack_params(init_params())
    opt_state = daso.init(params)

    acc = 0.0
    for epoch in range(epochs):
        total = 0.0
        for b in range(batches_per_epoch):
            lo = b * batch_size
            batch = (x[lo : lo + batch_size], y[lo : lo + batch_size])
            params, opt_state, loss = daso.step(params, opt_state, batch)
            total += float(loss)
        avg = total / batches_per_epoch
        daso.epoch_loss_logic(avg)
        acc = accuracy(daso.unstack_params(params), x_eval, y_eval)
        print(
            f"epoch {epoch}: loss {avg:.4f}, eval accuracy {acc:.2%} "
            f"(gs={daso.global_skip} ls={daso.local_skip} btw={daso.batches_to_wait})"
        )
    return acc


if __name__ == "__main__":
    final_acc = main()
    assert final_acc >= 0.95, f"DASO training failed to converge: {final_acc:.2%}"
    print(f"converged: final eval accuracy {final_acc:.2%}")
