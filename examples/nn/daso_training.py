"""Hierarchical asynchronous training with DASO (reference:
examples/nn/imagenet-DASO.py, condensed).

Shows the full DASO loop: 2-level (node x local) mesh, warmup -> cycling ->
cooldown phases, plateau-driven skip decay, and the delayed cross-node bf16
parameter merge. Runs on a virtual mesh:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/nn/daso_training.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import heat_tpu as ht
from heat_tpu.optim import DASO


def main(epochs=10, batches_per_epoch=8, batch_size=64):
    rng = np.random.default_rng(0)
    d = 32
    n = batches_per_epoch * batch_size
    x = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    w_true = jnp.asarray(rng.standard_normal((d, 1)), jnp.float32)
    y = x @ w_true + 0.01 * jnp.asarray(rng.standard_normal((n, 1)), jnp.float32)

    def loss_fn(params, xb, yb):
        return jnp.mean((xb @ params["w"] - yb) ** 2)

    daso = DASO(
        optax.adam(5e-2),
        total_epochs=epochs,
        warmup_epochs=2,
        cooldown_epochs=2,
        max_global_skips=4,
        verbose=True,
    )
    daso.set_loss(loss_fn)
    daso.last_batch = batches_per_epoch - 1

    params = daso.stack_params({"w": jnp.zeros((d, 1), jnp.float32)})
    opt_state = daso.init(params)

    for epoch in range(epochs):
        total = 0.0
        for b in range(batches_per_epoch):
            lo = b * batch_size
            batch = (x[lo : lo + batch_size], y[lo : lo + batch_size])
            params, opt_state, loss = daso.step(params, opt_state, batch)
            total += float(loss)
        avg = total / batches_per_epoch
        daso.epoch_loss_logic(avg)
        print(
            f"epoch {epoch}: loss {avg:.5f} "
            f"(gs={daso.global_skip} ls={daso.local_skip} btw={daso.batches_to_wait})"
        )

    final = daso.unstack_params(params)
    err = float(jnp.abs(final["w"] - w_true).max())
    print(f"max |w - w_true| = {err:.4f}")


if __name__ == "__main__":
    main()
