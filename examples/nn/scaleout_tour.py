"""Scale-out tour: pipeline + expert + FSDP sharding on one mesh.

Three round-trip demonstrations of the parallelism toolkit on the same
8-device (virtual) mesh, each checked against its single-shard oracle:

1. **GPipe pipeline** (`pipeline_apply`): an 8-stage MLP runs the
   microbatched schedule; output must match running the stages
   sequentially on one device.
2. **Expert parallelism** (`nn.MoEMLP` with ``comm=``): the expert axis
   shards over the mesh; logits must match the unsharded layer.
3. **FSDP** (`shard_pytree`): parameters and Adam state shard over the
   mesh; a short training run must match the replicated run step for
   step.

Run:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/nn/scaleout_tour.py
"""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join(os.path.dirname(__file__), "../..")))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import heat_tpu as ht
from heat_tpu.nn import MoEMLP
from heat_tpu.parallel import (
    pipeline_apply,
    shard_pytree,
    stack_stage_params,
)


def tour_pipeline(comm):
    p = comm.size
    dim, batch, micro = 16, 32, 4
    rng = np.random.default_rng(0)
    stages = [
        {
            "w": jnp.asarray(rng.standard_normal((dim, dim)) / np.sqrt(dim), jnp.float32),
            "b": jnp.zeros((dim,), jnp.float32),
        }
        for _ in range(p)
    ]

    def stage_fn(params, h):
        return jnp.tanh(h @ params["w"] + params["b"])

    x = jnp.asarray(rng.standard_normal((batch, dim)), jnp.float32)
    stacked = stack_stage_params(stages)
    got = pipeline_apply(stage_fn, stacked, x, comm=comm, n_microbatches=micro)

    want = x
    for s in stages:
        want = stage_fn(s, want)
    err = float(jnp.abs(got - want).max())
    print(f"[pipeline] {p} stages x {micro} microbatches: max |Δ| vs sequential = {err:.2e}")
    assert err < 1e-5


def tour_experts(comm):
    p = comm.size
    b, t, d = 4, 16, 32
    rng = jax.random.PRNGKey(1)
    x = jax.random.normal(rng, (b, t, d), jnp.float32)

    sharded = MoEMLP(n_experts=2 * p, d_ff=64, comm=comm)
    single = MoEMLP(n_experts=2 * p, d_ff=64, comm=None)
    params = single.init(rng, x)
    got = sharded.apply(params, x)
    want = single.apply(params, x)
    err = float(jnp.abs(got - want).max())
    print(f"[experts]  {2 * p} experts over {p} positions: max |Δ| vs unsharded = {err:.2e}")
    assert err < 1e-4


def tour_fsdp(comm):
    rng = np.random.default_rng(2)
    n, d = 64, 128
    X = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
    w_true = jnp.asarray(rng.standard_normal((d, 1)), jnp.float32)
    y = X @ w_true

    def loss_fn(params):
        return jnp.mean((X @ params["w"] + params["b"] - y) ** 2)

    opt = optax.adam(1e-1)

    def train(shard):
        params = {"w": jnp.zeros((d, 1)), "b": jnp.zeros((1,))}
        state = opt.init(params)
        if shard:
            params = shard_pytree(params, comm, min_size=64)
            state = shard_pytree(state, comm, min_size=64)
        losses = []
        for _ in range(60):
            l, g = jax.value_and_grad(loss_fn)(params)
            u, state = opt.update(g, state)
            params = optax.apply_updates(params, u)
            losses.append(float(l))
        return losses

    rep, shd = train(False), train(True)
    drift = max(abs(a - b) for a, b in zip(rep, shd))
    print(
        f"[fsdp]     60 Adam steps, sharded-vs-replicated loss drift = {drift:.2e} "
        f"(loss {shd[0]:.1f} → {shd[-1]:.4f})"
    )
    assert drift < 1e-4
    assert shd[-1] < shd[0] / 100


def main():
    comm = ht.get_comm()
    print(f"mesh: {comm}")
    tour_pipeline(comm)
    tour_experts(comm)
    tour_fsdp(comm)
    print("scale-out tour: all three schedules match their oracles")


if __name__ == "__main__":
    main()
