"""Multi-host end-to-end demo: bootstrap, sharded I/O, distributed fit.

Run one copy of this script per host process (the analog of the
reference's ``mpirun -np N python script.py`` launch):

    # terminal 1                               # terminal 2
    python demo_multihost.py 0 2 localhost:12345
    python demo_multihost.py 1 2 localhost:12345

On managed TPU pods, call ``ht.init_distributed()`` with no arguments —
the coordinator is auto-detected. For a laptop demo the script forces the
CPU backend with a few virtual devices per process.

What it shows, in order:
1. `init_distributed` — the `MPI_WORLD` analog (one mesh over every
   device of every host).
2. Sharded CSV/HDF5/npy loads: each process range-reads ONLY its slab.
3. Distributed ops and a KMeans fit across the host boundary.
4. Sharded saves: per-process slab writes, no host gathers the array.
"""

import os
import sys

RANK, NPROCS, COORD = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

# laptop demo: a virtual 2-device CPU mesh per process (delete these three
# lines on a real TPU pod)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import numpy as np

import heat_tpu as ht

comm = ht.init_distributed(
    coordinator_address=COORD, num_processes=NPROCS, process_id=RANK
)
print(f"[{RANK}] mesh: {comm}")

# --- sharded load: this process reads only its canonical row slab --------
path = "/tmp/demo_multihost.npy"
n, d = 10_000, 8
if RANK == 0:
    rng = np.random.default_rng(0)
    blobs = np.concatenate(
        [rng.normal(c, 0.5, size=(n // 4, d)) for c in (-3, -1, 1, 3)]
    ).astype(np.float32)
    np.save(path + ".tmp.npy", blobs)
    os.replace(path + ".tmp.npy", path)
else:
    import time

    while not os.path.exists(path):
        time.sleep(0.1)

x = ht.load_npy(path, split=0)  # memmap: only this slab's pages are read
print(f"[{RANK}] loaded {x.shape} split={x.split}, local rows {x.lshape[0]}")

# --- distributed compute across the host boundary ------------------------
mu = ht.mean(x, axis=0)
sd = ht.std(x, axis=0)
print(f"[{RANK}] column mean[0]={float(mu[0].item()):.3f} std[0]={float(sd[0].item()):.3f}")

km = ht.cluster.KMeans(n_clusters=4, init="probability_based", max_iter=20,
                       random_state=0)
km.fit(x)
print(f"[{RANK}] kmeans inertia {km.inertia_:.1f} after {km.n_iter_} iters")

# --- sharded save: per-process slab writes -------------------------------
labels = km.predict(x)
out = "/tmp/demo_multihost_labels.npy"
ht.save_npy(labels.astype(ht.float32), out)
if RANK == 0:
    back = np.load(out)
    print(f"[0] wrote {back.shape} labels; cluster sizes "
          f"{np.bincount(back.astype(int)).tolist()}")
print(f"[{RANK}] done")
