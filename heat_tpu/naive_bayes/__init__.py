"""Naive Bayes classifiers (reference: heat/naive_bayes/)."""

from .gaussianNB import *
