"""Gaussian naive Bayes classifier.

Re-design of reference heat/naive_bayes/gaussianNB.py:12-529 (fit/partial_fit
with incremental mean/variance merge :131, joint log likelihood :391,
logsumexp :407). Class-conditional moments are computed as one-hot GEMMs on
the padded sharded sample buffer — the incremental MPI merge of the
reference becomes a single psum inserted by XLA; `partial_fit` keeps the
reference's streaming moment-merge semantics on host scalars.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray

__all__ = ["GaussianNB"]


class GaussianNB(BaseEstimator, ClassificationMixin):
    """Gaussian naive Bayes (reference gaussianNB.py:12).

    Parameters
    ----------
    priors : DNDarray, optional
        Class priors; estimated from data when None.
    var_smoothing : float
        Fraction of the largest feature variance added to all variances.
    """

    def __init__(self, priors: Optional[DNDarray] = None, var_smoothing: float = 1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing
        self.classes_ = None
        self.theta_ = None
        self.var_ = None
        self.class_prior_ = None
        self.class_count_ = None
        self.epsilon_ = None

    def fit(self, x: DNDarray, y: DNDarray, sample_weight=None, _classes=None) -> "GaussianNB":
        """Estimate per-class feature means/variances (reference
        gaussianNB.py `fit` → __partial_fit :131). ``sample_weight`` scales
        each sample's contribution to counts, means and variances."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if x.ndim != 2:
            raise ValueError(f"expected x to be a 2-D tensor, is {x.ndim}-D")
        yl = y._replicated().ravel()
        xl = x._masked(0).astype(jnp.float64)
        w = (jnp.arange(xl.shape[0]) < x.shape[0]).astype(xl.dtype)
        if sample_weight is not None:
            sw = (
                sample_weight._replicated()
                if isinstance(sample_weight, DNDarray)
                else jnp.asarray(sample_weight)
            ).astype(xl.dtype).ravel()
            if sw.shape[0] != x.shape[0]:
                raise ValueError("sample_weight length must match number of samples")
            w = w.at[: sw.shape[0]].multiply(sw)

        # np.unique both deduplicates and SORTS — partial_fit's searchsorted
        # moment merge below relies on classes_ being sorted
        classes = np.unique(np.asarray(yl)) if _classes is None else np.unique(np.asarray(_classes))
        self.classes_ = DNDarray.from_logical(jnp.asarray(classes), None, x.device, x.comm)
        k = len(classes)

        # pad y to physical length for the one-hot GEMM
        ypad = jnp.zeros((xl.shape[0],), dtype=yl.dtype)
        ypad = ypad.at[: yl.shape[0]].set(yl)
        onehot = (ypad[:, None] == jnp.asarray(classes)[None, :]).astype(xl.dtype) * w[:, None]
        counts = jnp.sum(onehot, axis=0)  # (k,)
        sums = onehot.T @ xl  # (k, d)
        means = sums / jnp.maximum(counts, 1.0)[:, None]
        sq = onehot.T @ (xl * xl)
        var = sq / jnp.maximum(counts, 1.0)[:, None] - means * means

        self.epsilon_ = float(
            self.var_smoothing * jnp.max(jnp.var(xl, axis=0, where=(w > 0)[:, None]))
        )
        var = var + self.epsilon_

        self.theta_ = DNDarray.from_logical(means, None, x.device, x.comm)
        self.var_ = DNDarray.from_logical(var, None, x.device, x.comm)
        self.class_count_ = DNDarray.from_logical(counts, None, x.device, x.comm)
        if self.priors is None:
            prior = counts / jnp.sum(counts)
        else:
            prior = self.priors._replicated()
            if prior.shape[0] != k:
                raise ValueError("Number of priors must match number of classes.")
            if not np.isclose(float(jnp.sum(prior)), 1.0):
                raise ValueError("The sum of the priors should be 1.")
        self.class_prior_ = DNDarray.from_logical(prior, None, x.device, x.comm)
        return self

    def partial_fit(self, x: DNDarray, y: DNDarray, classes: Optional[DNDarray] = None) -> "GaussianNB":
        """Incremental fit on a batch (reference gaussianNB.py `partial_fit`;
        moment merge per Chan et al., reference __update_mean_variance
        :131)."""
        if self.theta_ is None:
            if classes is None:
                raise ValueError("classes must be passed on the first call to partial_fit")
            return self.fit(x, y, _classes=np.asarray(classes.numpy() if isinstance(classes, DNDarray) else classes))
        # merge batch moments with stored moments
        old_n = self.class_count_._replicated()
        old_mu = self.theta_._replicated()
        old_var = self.var_._replicated() - self.epsilon_

        tmp = GaussianNB(var_smoothing=self.var_smoothing)
        tmp.fit(x, y)
        new_classes = tmp.classes_.numpy()
        ref_classes = self.classes_.numpy()
        if not np.array_equal(np.intersect1d(new_classes, ref_classes), new_classes):
            raise ValueError("partial_fit batch contains unseen classes")
        idx = jnp.asarray(np.searchsorted(ref_classes, new_classes))
        b_n = jnp.zeros_like(old_n).at[idx].set(tmp.class_count_._replicated())
        b_mu = jnp.zeros_like(old_mu).at[idx].set(tmp.theta_._replicated())
        b_var = jnp.zeros_like(old_var).at[idx].set(tmp.var_._replicated() - tmp.epsilon_)

        n_tot = old_n + b_n
        safe = jnp.maximum(n_tot, 1.0)
        mu_tot = (old_n[:, None] * old_mu + b_n[:, None] * b_mu) / safe[:, None]
        ssd = (
            old_n[:, None] * old_var
            + b_n[:, None] * b_var
            + (old_n * b_n / safe)[:, None] * (old_mu - b_mu) ** 2
        )
        var_tot = ssd / safe[:, None]

        self.epsilon_ = max(self.epsilon_, tmp.epsilon_)
        self.class_count_ = DNDarray.from_logical(n_tot, None, x.device, x.comm)
        self.theta_ = DNDarray.from_logical(mu_tot, None, x.device, x.comm)
        self.var_ = DNDarray.from_logical(var_tot + self.epsilon_, None, x.device, x.comm)
        if self.priors is None:
            self.class_prior_ = DNDarray.from_logical(n_tot / jnp.sum(n_tot), None, x.device, x.comm)
        return self

    def __joint_log_likelihood(self, x: DNDarray) -> jnp.ndarray:
        """log P(c) + Σ log N(x_i; μ_c, σ_c²) (reference gaussianNB.py:391)."""
        xl = x.larray.astype(jnp.float64)
        mu = self.theta_._replicated()
        var = self.var_._replicated()
        prior = self.class_prior_._replicated()
        log_prior = jnp.log(prior)[None, :]
        n_ij = -0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)[None, :]
        diff = xl[:, None, :] - mu[None, :, :]  # (m, k, d)
        quad = -0.5 * jnp.sum(diff * diff / var[None, :, :], axis=2)
        return log_prior + n_ij + quad

    def predict(self, x: DNDarray) -> DNDarray:
        """Most probable class per sample (reference gaussianNB.py:480)."""
        if self.theta_ is None:
            raise RuntimeError("fit needs to be called before predict")
        jll = self.__joint_log_likelihood(x)
        classes = self.classes_._replicated()
        pred = jnp.take(classes, jnp.argmax(jll, axis=1))
        return DNDarray(pred, (x.shape[0],), types.canonical_heat_type(pred.dtype), x.split, x.device, x.comm, True)

    def predict_log_proba(self, x: DNDarray) -> DNDarray:
        """Log class probabilities via logsumexp (reference gaussianNB.py:407)."""
        jll = self.__joint_log_likelihood(x)
        log_prob = jll - jax.scipy.special.logsumexp(jll, axis=1, keepdims=True)
        k = log_prob.shape[1]
        return DNDarray(
            log_prob, (x.shape[0], k), types.float64, x.split, x.device, x.comm, True
        )

    def predict_proba(self, x: DNDarray) -> DNDarray:
        """Class probabilities (reference gaussianNB.py:537)."""
        lp = self.predict_log_proba(x)
        return DNDarray(
            jnp.exp(lp.larray), lp.shape, lp.dtype, lp.split, lp.device, lp.comm, True
        )
