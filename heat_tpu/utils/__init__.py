"""Populated by the data-utils build stage."""
