"""Vision transform passthrough (reference: heat/utils/vision_transforms.py
forwards every name to ``torchvision.transforms``). torchvision is optional;
names resolve lazily so importing this module never requires it."""

from __future__ import annotations

__all__ = []


def __getattr__(name):
    try:
        from torchvision import transforms as _transforms
    except ImportError as e:
        raise ImportError(
            f"heat_tpu.utils.vision_transforms.{name} requires torchvision, "
            "which is not installed"
        ) from e
    try:
        return getattr(_transforms, name)
    except AttributeError:
        raise AttributeError(
            f"torchvision.transforms has no attribute {name}"
        ) from None
