"""Hang-safe backend probing shared by bench.py and __graft_entry__.

The TPU plugin can hang (not just fail) backend initialization, and a hung
in-process init is unrecoverable — so the default platform is probed in a
SUBPROCESS with a timeout, optionally retried with backoff. The reference has
no analog (MPI init either works or aborts); this is TPU-runtime plumbing.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time
from typing import List, Optional, Tuple

__all__ = ["probe_default_platform", "force_virtual_cpu_mesh"]


def force_virtual_cpu_mesh(n: int) -> None:
    """Point jax at an ``n``-device virtual CPU mesh. Must run before the
    first *backend use* (``jax.devices()`` / first dispatch) — importing
    jax earlier is fine, backend init is lazy. One canonical copy of the
    dance (the benchmark harness ``--mesh`` flag and the
    ``python -m heat_tpu.telemetry.audit --mesh`` CLI both go through
    here):

    * splice ``--xla_force_host_platform_device_count=n`` into
      ``XLA_FLAGS``, replacing an inherited count (a test env's value
      must not win over an explicit request);
    * pin ``JAX_PLATFORMS=cpu`` in the environment AND the live jax
      config — a sitecustomize (the axon TPU plugin) can force another
      platform, so the env var alone is not enough.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    want = f"--xla_force_host_platform_device_count={int(n)}"
    m = re.search(r"--xla_force_host_platform_device_count=\d+", flags)
    flags = flags.replace(m.group(0), want) if m else (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

_PROBE_CODE = "import jax; d = jax.devices(); print('PROBE', d[0].platform, len(d))"


def probe_default_platform(
    retries: int = 1, timeout: float = 150.0, budget: Optional[float] = None
) -> Tuple[Optional[str], int, List[str]]:
    """Probe the default JAX platform in a subprocess.

    Returns ``(platform, device_count, diagnostics)`` — ``platform`` is None
    when every attempt failed (crash, timeout, unparseable output).

    ``budget`` caps the WHOLE probe phase's wall time (timeouts, backoffs
    and all): once it is exhausted, remaining attempts are skipped and the
    skip is named in the diagnostics. BENCH_r04 burned ~25 min of driver
    budget on 10 x 150 s probe timeouts before any benching started — the
    budget makes that class of run impossible by construction.
    """
    diags: List[str] = []
    t0 = time.monotonic()

    def left() -> Optional[float]:
        return None if budget is None else budget - (time.monotonic() - t0)

    for attempt in range(retries):
        # budget check BEFORE any backoff sleep: a sleep must never burn
        # the remaining budget for an attempt that would then be skipped
        if budget is not None and left() < 1.0:
            diags.append(
                f"attempt {attempt}: skipped (probe budget {budget:.0f}s "
                "exhausted)"
            )
            break
        if attempt:
            # a wedged accelerator tunnel can take minutes to recycle —
            # back off rather than burning the attempts in 10s (but never
            # past the phase budget: leave time for the attempt itself)
            backoff = min(30 * attempt, 120)
            if budget is not None:
                backoff = min(backoff, max(0.0, left() - 1.0))
            time.sleep(backoff)
            if budget is not None and left() < 1.0:
                diags.append(
                    f"attempt {attempt}: skipped (probe budget "
                    f"{budget:.0f}s exhausted)"
                )
                break
        t = timeout
        if budget is not None:
            t = min(timeout, max(1.0, left()))
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_CODE],
                capture_output=True,
                text=True,
                timeout=t,
            )
            toks = r.stdout.split()
            if r.returncode == 0 and "PROBE" in toks:
                i = toks.index("PROBE")
                plat, n = toks[i + 1], int(toks[i + 2])
                diags.append(f"attempt {attempt}: ok ({plat} x{n})")
                return plat, n, diags
            diags.append(
                f"attempt {attempt}: rc={r.returncode} "
                f"stderr={r.stderr.strip()[-300:]!r}"
            )
        except Exception as e:  # noqa: BLE001 — the probe must never crash callers
            diags.append(f"attempt {attempt}: {type(e).__name__}: {e}")
    return None, 0, diags
