"""Offline dataset preprocessing helpers (reference:
heat/utils/data/_utils.py — ImageNet TFRecord→HDF5 merging and DALI index
generation used by the DASO ImageNet example).

These are *offline tooling*, not runtime components: the reference runs them
once on a login node to produce the HDF5 shards its `PartialH5Dataset`
streams. The TPU-native data path consumes the same HDF5 output (see
`partial_dataset.PartialH5Dataset`), so the preprocessing functions keep the
reference signatures and gate on their heavyweight optional deps
(tensorflow for TFRecord parsing; DALI never runs on TPU hosts — its index
format is plain text offsets, generated here without DALI)."""

from __future__ import annotations

import os
import struct

__all__ = ["dali_tfrecord2idx", "merge_files_imagenet_tfrecord"]


def dali_tfrecord2idx(train_dir, train_idx_dir, val_dir, val_idx_dir):
    """Write DALI-style index files (record byte offsets) for every TFRecord
    in ``train_dir``/``val_dir`` (reference _utils.py:13-44). Pure file
    arithmetic — no DALI or tensorflow required: a TFRecord is a sequence of
    ``[u64 length][u32 crc][payload][u32 crc]`` frames."""
    for src_dir, idx_dir in ((train_dir, train_idx_dir), (val_dir, val_idx_dir)):
        os.makedirs(idx_dir, exist_ok=True)
        for name in sorted(os.listdir(src_dir)):
            src = os.path.join(src_dir, name)
            if not os.path.isfile(src):
                continue
            lines = []
            with open(src, "rb") as f:
                while True:
                    pos = f.tell()
                    header = f.read(8)
                    if len(header) < 8:
                        break
                    (length,) = struct.unpack("<Q", header)
                    f.seek(4, 1)  # length crc
                    f.seek(length, 1)
                    f.seek(4, 1)  # payload crc
                    lines.append(f"{pos} {f.tell() - pos}")
            with open(os.path.join(idx_dir, name + ".idx"), "w") as out:
                out.write("\n".join(lines) + ("\n" if lines else ""))


def merge_files_imagenet_tfrecord(folder_name, output_folder=None):
    """Merge ImageNet TFRecord shards into the two HDF5 files the streaming
    loader consumes (reference _utils.py:47-). Requires tensorflow (TFRecord
    payload parsing) and h5py; both are optional deps and the function
    raises ImportError naming the missing one."""
    try:
        import h5py  # noqa: F401
    except ImportError as e:
        raise ImportError("merge_files_imagenet_tfrecord requires h5py") from e
    try:
        import tensorflow  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "merge_files_imagenet_tfrecord requires tensorflow for TFRecord "
            "parsing; run this offline step in a TF-enabled environment "
            "(the output HDF5 is what the TPU data path consumes)"
        ) from e
    raise NotImplementedError(
        "TFRecord payload schema parsing is environment-specific; this "
        "offline step is documented in the reference (_utils.py:47-226) and "
        "its HDF5 output format (datasets 'images'/'metas') is what "
        "PartialH5Dataset streams"
    )
