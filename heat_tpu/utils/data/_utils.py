"""Offline dataset preprocessing helpers (reference:
heat/utils/data/_utils.py — ImageNet TFRecord→HDF5 merging and DALI index
generation used by the DASO ImageNet example).

These are *offline tooling*, not runtime components: the reference runs them
once on a login node to produce the HDF5 shards its `PartialH5Dataset`
streams. The TPU-native data path consumes the same HDF5 output (see
`partial_dataset.PartialH5Dataset`), so the preprocessing functions keep the
reference signatures — but need NO tensorflow: TFRecord framing and the
tf.train.Example protobuf are parsed directly (h5py + PIL are the only
optional deps; DALI never runs on TPU hosts — its index format is plain
text offsets, generated here without DALI)."""

from __future__ import annotations

import os
import struct

__all__ = ["dali_tfrecord2idx", "merge_files_imagenet_tfrecord"]


def dali_tfrecord2idx(train_dir, train_idx_dir, val_dir, val_idx_dir):
    """Write DALI-style index files (record byte offsets) for every TFRecord
    in ``train_dir``/``val_dir`` (reference _utils.py:13-44). Pure file
    arithmetic — no DALI or tensorflow required: a TFRecord is a sequence of
    ``[u64 length][u32 crc][payload][u32 crc]`` frames."""
    for src_dir, idx_dir in ((train_dir, train_idx_dir), (val_dir, val_idx_dir)):
        os.makedirs(idx_dir, exist_ok=True)
        for name in sorted(os.listdir(src_dir)):
            src = os.path.join(src_dir, name)
            if not os.path.isfile(src):
                continue
            lines = [
                f"{pos} {frame_len}"
                for pos, frame_len, _ in _iter_tfrecord_frames(src, read_payload=False)
            ]
            with open(os.path.join(idx_dir, name + ".idx"), "w") as out:
                out.write("\n".join(lines) + ("\n" if lines else ""))


# -- minimal protobuf wire-format reader (tf.train.Example) -------------------
# The reference parses Examples with tensorflow (reference _utils.py:160-210);
# the wire format is ~40 lines of varint arithmetic, so this offline step
# needs no TF at all. Message layout: Example{1: Features{1: map<string,
# Feature>}}, Feature{1: BytesList, 2: FloatList, 3: Int64List}, each list
# field 1 repeated (floats/ints possibly packed).


def _read_varint(buf, i):
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _iter_fields(buf):
    """Yield ``(field_number, wire_type, value)`` over one message."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i : i + ln]
            i += ln
        elif wt == 5:
            v = buf[i : i + 4]
            i += 4
        elif wt == 1:
            v = buf[i : i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fn, wt, v


def _parse_example(buf):
    """tf.train.Example bytes → {feature_name: [values...]}."""
    feats = {}
    for fn, _, features in _iter_fields(buf):
        if fn != 1:
            continue
        for fn2, _, entry in _iter_fields(features):
            if fn2 != 1:
                continue
            key, feature = None, b""
            for fn3, _, v3 in _iter_fields(entry):
                if fn3 == 1:
                    key = v3.decode("utf-8")
                elif fn3 == 2:
                    feature = v3
            vals = []
            for fn4, _, lst in _iter_fields(feature):
                for fn5, wt5, v5 in _iter_fields(lst):
                    if fn5 != 1:
                        continue
                    if fn4 == 1:  # BytesList
                        vals.append(v5)
                    elif fn4 == 2:  # FloatList
                        if wt5 == 2:  # packed
                            vals.extend(struct.unpack(f"<{len(v5) // 4}f", v5))
                        else:
                            vals.append(struct.unpack("<f", v5)[0])
                    elif fn4 == 3:  # Int64List
                        if wt5 == 2:  # packed varints
                            j = 0
                            while j < len(v5):
                                x, j = _read_varint(v5, j)
                                vals.append(x)
                        else:
                            vals.append(v5)
            if key is not None:
                feats[key] = vals
    return feats


def _iter_tfrecord_frames(path, read_payload=True):
    """Yield ``(offset, frame_length, payload)`` per TFRecord frame — the
    single frame walker shared by the merge and the DALI indexer.

    ``read_payload=False`` seeks over payload+CRC instead of reading it
    (payload yields as None) — the indexer only needs offsets, so an
    ImageNet-scale shard costs a few KB of header reads, not a full-file
    read. Truncation is still detected (a short frame raises ValueError
    naming the file and offset — tf.data raises DataLossError there); CRC
    words are skipped unverified."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        while True:
            pos = f.tell()
            header = f.read(8)
            if not header:
                return
            if len(header) < 8:
                raise ValueError(f"truncated TFRecord header in {path} at byte {pos}")
            (length,) = struct.unpack("<Q", header)
            if read_payload:
                crc1 = f.read(4)
                payload = f.read(length)
                crc2 = f.read(4)
                if len(crc1) < 4 or len(payload) < length or len(crc2) < 4:
                    raise ValueError(
                        f"truncated TFRecord frame in {path} at byte {pos} "
                        f"(declared {length} payload bytes)"
                    )
            else:
                payload = None
                end = pos + 16 + length
                if end > size:
                    raise ValueError(
                        f"truncated TFRecord frame in {path} at byte {pos} "
                        f"(declared {length} payload bytes)"
                    )
                f.seek(end)
            yield pos, 16 + length, payload


def _iter_tfrecord(path):
    """Yield raw Example payloads of a TFRecord file."""
    for _, _, payload in _iter_tfrecord_frames(path):
        yield payload


def merge_files_imagenet_tfrecord(folder_name, output_folder=None):
    """Merge ImageNet TFRecord shards into the HDF5 files the streaming
    loader consumes (reference _utils.py:47-226; same output schema:
    ``images`` = base64 of the decoded RGB array per image, ``metadata`` =
    (N, 9) float64 ``[height, width, channels, label-1, bbox xmin/xmax/
    ymin/ymax, bbox label]``, ``file_info`` = (N, 4) ``[format, filename,
    synset, text]``) — decode an image via
    ``np.frombuffer(base64.binascii.a2b_base64(s), np.uint8).reshape(h, w, 3)``.

    TF-free re-design: TFRecord framing and the Example protobuf are parsed
    directly (see `_parse_example`), JPEG decoding uses PIL. Shards named
    ``train*`` feed ``imagenet_merged.h5``, ``val*`` feeds
    ``imagenet_merged_validation.h5``.
    """
    import base64

    try:
        import h5py
    except ImportError as e:
        raise ImportError("merge_files_imagenet_tfrecord requires h5py") from e
    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError(
            "merge_files_imagenet_tfrecord requires PIL for JPEG decoding"
        ) from e
    import io as _io

    import numpy as np

    output_folder = output_folder if output_folder is not None else folder_name
    names = sorted(os.listdir(folder_name))
    groups = {
        "imagenet_merged.h5": [n for n in names if n.startswith("train")],
        "imagenet_merged_validation.h5": [n for n in names if n.startswith("val")],
    }
    dt = h5py.string_dtype(encoding="ascii")
    flush_every = 256  # bound peak memory: ~0.2 GB of decoded images per block
    for out_name, shards in groups.items():
        if not shards:
            continue
        out_path = os.path.join(output_folder, out_name)
        with h5py.File(out_path, "w") as out:
            out.create_dataset("images", (0,), chunks=True, maxshape=(None,), dtype=dt)
            out.create_dataset("metadata", (0, 9), chunks=True, maxshape=(None, 9))
            out.create_dataset(
                "file_info", (0, 4), chunks=True, maxshape=(None, 4), dtype="S10"
            )
            size = 0
            imgs, metas, infos = [], [], []

            def flush():
                nonlocal size, imgs, metas, infos
                if not imgs:
                    return
                new_size = size + len(imgs)
                out["images"].resize((new_size,))
                out["images"][size:new_size] = imgs
                out["metadata"].resize((new_size, 9))
                out["metadata"][size:new_size] = np.asarray(metas, dtype=np.float64)
                out["file_info"].resize((new_size, 4))
                out["file_info"][size:new_size] = np.asarray(infos, dtype="S10")
                size = new_size
                imgs, metas, infos = [], [], []

            for shard in shards:
                shard_path = os.path.join(folder_name, shard)
                if not os.path.isfile(shard_path):
                    continue
                for payload in _iter_tfrecord(shard_path):
                    feats = _parse_example(payload)
                    raw = feats["image/encoded"][0]
                    arr = np.asarray(
                        Image.open(_io.BytesIO(raw)).convert("RGB"), dtype=np.uint8
                    )
                    imgs.append(base64.binascii.b2a_base64(arr.tobytes()).decode("ascii"))
                    h, w = arr.shape[:2]
                    label = int(feats["image/class/label"][0]) - 1
                    try:
                        bb = [
                            float(feats["image/object/bbox/xmin"][0]),
                            float(feats["image/object/bbox/xmax"][0]),
                            float(feats["image/object/bbox/ymin"][0]),
                            float(feats["image/object/bbox/ymax"][0]),
                            int(feats["image/object/bbox/label"][0]) - 1,
                        ]
                    except (KeyError, IndexError):
                        # reference fallback (its _utils.py:193-198): full-image
                        # box in PIXEL units with label sentinel -2 — consumers
                        # must branch on label == -2 before interpreting units
                        bb = [0.0, float(w), 0.0, float(h), -2]
                    metas.append([float(h), float(w), 3.0, float(label)] + bb)
                    infos.append(
                        [
                            feats.get("image/format", [b""])[0][:10],
                            feats.get("image/filename", [b""])[0][:10],
                            feats.get("image/class/synset", [b""])[0][:10],
                            feats.get("image/class/text", [b""])[0][:10],
                        ]
                    )
                    if len(imgs) >= flush_every:
                        flush()
            flush()
