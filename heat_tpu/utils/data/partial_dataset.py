"""Out-of-core streaming datasets (reference:
heat/utils/data/partial_dataset.py).

The reference's :class:`PartialH5Dataset` (reference partial_dataset.py:32)
streams windows of an HDF5 file that is too large for memory: a background
**loader thread** reads the next window from disk while the current one is
being consumed, and a converter thread shapes batches (GIL caveats
documented at :43-45). Same architecture here — a `threading.Thread` + a
bounded `queue.Queue` of prefetched windows, with host→device transfer of
each batch overlapped by JAX's async dispatch. Works against any mapping
whose values support numpy-style slicing (h5py File, np.memmap, np arrays),
so the H5-specific class is a thin subclass gated on h5py.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.communication import sanitize_comm

__all__ = ["PartialDataset", "PartialH5Dataset", "PartialDataLoaderIter", "PartialH5DataLoaderIter"]


class PartialDataset:
    """Windowed streaming dataset over sliceable columns.

    Parameters
    ----------
    columns : dict[str, sliceable]
        Named arrays (same leading length) — e.g. ``{"data": f["images"],
        "targets": f["labels"]}`` for an open h5py file.
    initial_load : int
        Rows of the first resident window (reference ``initial_load``).
    load_length : int
        Rows fetched per background read (reference ``load_length``).
    transform : callable, optional
        Applied to each *window* dict of numpy arrays before batching.
    """

    def __init__(
        self,
        columns,
        initial_load: int = 4096,
        load_length: int = 1024,
        transform: Optional[Callable] = None,
        comm=None,
    ):
        if not columns:
            raise ValueError("columns must be a non-empty mapping")
        self.columns = dict(columns)
        lengths = {k: v.shape[0] for k, v in self.columns.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"column lengths differ: {lengths}")
        self.total_size = next(iter(lengths.values()))
        self.initial_load = min(initial_load, self.total_size)
        self.load_length = max(1, load_length)
        self.transform = transform
        self.comm = sanitize_comm(comm)
        self.ishuffle = False
        self.test_set = False
        self.partial_dataset = True  # reference duck-type marker

    def windows(self) -> Iterator[dict]:
        """Yield dicts of numpy windows, prefetched by a background thread
        (reference's loader-thread design, partial_dataset.py:20-30)."""
        q: queue.Queue = queue.Queue(maxsize=2)
        SENTINEL = object()
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that also watches the stop flag, so an abandoned
            # consumer (caller broke out of the loop) can't leave this
            # thread blocked on a full queue forever
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def loader():
            # the sentinel must reach the queue on *every* exit path — a
            # read/transform error otherwise leaves the consumer blocked on
            # q.get() forever; exceptions travel through the queue so the
            # consuming thread re-raises them
            try:
                pos = 0
                length = self.initial_load
                while pos < self.total_size and not stop.is_set():
                    hi = min(pos + length, self.total_size)
                    win = {
                        k: np.asarray(v[pos:hi]) for k, v in self.columns.items()
                    }
                    if self.transform is not None:
                        win = self.transform(win)
                    if not put(win):
                        return
                    pos = hi
                    length = self.load_length
            except BaseException as e:  # noqa: BLE001 - relayed to consumer
                put(e)
            finally:
                put(SENTINEL)

        t = threading.Thread(target=loader, daemon=True)
        t.start()
        try:
            while True:
                win = q.get()
                if win is SENTINEL:
                    break
                if isinstance(win, BaseException):
                    raise win
                yield win
        finally:
            # normal exhaustion or early abandonment (GeneratorExit): wake
            # the loader, drain anything buffered, and reap the thread so
            # repeated partial epochs can't stack blocked threads
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join()

    def __len__(self) -> int:
        return self.total_size


class PartialH5Dataset(PartialDataset):
    """Stream datasets out of an HDF5 file (reference partial_dataset.py:32).

    Parameters
    ----------
    file : str
        Path to the HDF5 file.
    dataset_names : str or list of str
        Dataset keys to stream (reference default ``"data"``).
    """

    def __init__(
        self,
        file: str,
        comm=None,
        dataset_names="data",
        transform: Optional[Callable] = None,
        initial_load: int = 4096,
        load_length: int = 1024,
    ):
        try:
            import h5py
        except ImportError as e:  # pragma: no cover - h5py in test image
            raise ImportError("PartialH5Dataset requires h5py") from e
        self.file = file
        self._h5 = h5py.File(file, "r")
        names = [dataset_names] if isinstance(dataset_names, str) else list(dataset_names)
        columns = {name: self._h5[name] for name in names}
        super().__init__(
            columns,
            initial_load=initial_load,
            load_length=load_length,
            transform=transform,
            comm=comm,
        )

    def close(self) -> None:
        self._h5.close()


class PartialDataLoaderIter:
    """Batch iterator over a PartialDataset (reference
    PartialH5DataLoaderIter, partial_dataset.py:224).

    Emits mesh-sharded device batches; incomplete tails within a window are
    carried over to the next window, the final global tail is dropped
    (reference forces ``drop_last=True`` for partial datasets,
    datatools.py:88-89)."""

    def __init__(self, dataset: PartialDataset, batch_size: int, shuffle: bool = True, seed: int = 0):
        p = dataset.comm.size
        if batch_size % p:
            raise ValueError(
                f"batch_size ({batch_size}) must be divisible by mesh size ({p})"
            )
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        carry: Optional[dict] = None
        bs = self.batch_size
        comm = self.dataset.comm
        for win in self.dataset.windows():
            if carry is not None:
                win = {
                    k: np.concatenate([carry[k], win[k]], axis=0) for k in win
                }
            n = next(iter(win.values())).shape[0]
            if self.shuffle:
                prm = self._rng.permutation(n)
                win = {k: v[prm] for k, v in win.items()}
            nb = n // bs
            for i in range(nb):
                lo = i * bs
                yield tuple(
                    jax.device_put(
                        jnp.asarray(v[lo : lo + bs]), comm.sharding(0, v.ndim)
                    )
                    for v in win.values()
                )
            rem = n - nb * bs
            carry = {k: v[n - rem :] for k, v in win.items()} if rem else None


# reference-parity name (reference partial_dataset.py:224)
PartialH5DataLoaderIter = PartialDataLoaderIter
