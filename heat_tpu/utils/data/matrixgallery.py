"""Synthetic test matrices (reference: heat/utils/data/matrixgallery.py)."""

from __future__ import annotations

from typing import Optional, Type, Union

from ... import core
from ...core.dndarray import DNDarray
from ...core.types import datatype

__all__ = ["parter"]


def parter(
    n: int,
    split: Optional[int] = None,
    device=None,
    comm=None,
    dtype: Type[datatype] = None,
) -> DNDarray:
    """The Parter matrix ``A[i, j] = 1 / (j - i + 0.5)`` — a Toeplitz matrix
    whose singular values cluster at π (reference matrixgallery.py:15-61).

    ``split`` ∈ {None, 0, 1} chooses the sharded axis of the result.
    """
    dtype = dtype if dtype is not None else core.float32
    if split not in (None, 0, 1):
        raise ValueError(f"expected split in {{None, 0, 1}}, but was {split}")
    a = core.arange(n, dtype=dtype, device=device, comm=comm)
    II = a.expand_dims(0)  # row index varies along axis 1
    JJ = a.expand_dims(1)  # column index varies along axis 0
    out = 1.0 / (II - JJ + 0.5)
    return out if split is None else core.resplit(out, split)
