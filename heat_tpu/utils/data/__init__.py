"""heat_tpu.utils.data — datasets, loaders, streaming IO, matrix gallery
(reference: heat/utils/data/__init__.py)."""

from . import matrixgallery
from .datatools import DataLoader, Dataset, dataset_ishuffle, dataset_shuffle
from .partial_dataset import (
    PartialDataLoaderIter,
    PartialH5DataLoaderIter,
    PartialDataset,
    PartialH5Dataset,
)

__all__ = [
    "DataLoader",
    "Dataset",
    "dataset_shuffle",
    "dataset_ishuffle",
    "PartialDataset",
    "PartialH5Dataset",
    "PartialDataLoaderIter",
    "PartialH5DataLoaderIter",
    "matrixgallery",
]


def __getattr__(name):
    # torchvision-gated members resolve lazily so the package imports
    # without torchvision
    if name == "MNISTDataset":
        from .mnist import MNISTDataset

        return MNISTDataset
    raise AttributeError(f"module heat_tpu.utils.data has no attribute {name}")
