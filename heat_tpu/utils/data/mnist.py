"""MNIST dataset split across the mesh (reference: heat/utils/data/mnist.py).

The reference subclasses ``torchvision.datasets.MNIST`` and keeps each
rank's slice (reference mnist.py:16-129). torchvision is an optional
dependency here; when present, :class:`MNISTDataset` loads via torchvision
and wraps the arrays as a mesh-sharded :class:`heat_tpu.utils.data.Dataset`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...core import factories
from .datatools import Dataset

__all__ = ["MNISTDataset"]


class MNISTDataset(Dataset):
    """MNIST as a sharded Dataset.

    Parameters
    ----------
    root : str
        torchvision download/cache directory.
    train : bool
        Training or test split.
    split : int or None
        Shard axis for the image array (0 or None, as for any Dataset).
    """

    def __init__(
        self,
        root: str,
        train: bool = True,
        transform=None,
        target_transform=None,
        download: bool = True,
        split: Optional[int] = 0,
        ishuffle: bool = False,
        test_set: bool = False,
        comm=None,
    ):
        try:
            from torchvision import datasets as tv_datasets
        except ImportError as e:
            raise ImportError(
                "MNISTDataset requires torchvision, which is not installed"
            ) from e
        tv = tv_datasets.MNIST(
            root,
            train=train,
            transform=transform,
            target_transform=target_transform,
            download=download,
        )
        if transform is not None or target_transform is not None:
            # torchvision applies transforms in __getitem__; materialize
            # through it so they actually take effect (reading tv.data raw
            # would silently skip them)
            samples = [tv[i] for i in range(len(tv))]
            images = np.stack([np.asarray(s[0]) for s in samples]).astype(np.float32)
            labels = np.asarray([s[1] for s in samples], dtype=np.int32)
        else:
            images = np.asarray(tv.data, dtype=np.float32)
            labels = np.asarray(tv.targets, dtype=np.int32)
        data = factories.array(images, split=split, comm=comm)
        targets = factories.array(labels, split=split, comm=comm)
        super().__init__(
            data, targets=targets, ishuffle=ishuffle, test_set=test_set or not train
        )
