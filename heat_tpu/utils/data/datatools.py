"""Datasets and loaders over sharded arrays (reference:
heat/utils/data/datatools.py).

The reference's :class:`DataLoader` wraps torch's loader over each rank's
local shard and re-shuffles *across ranks* after every epoch by sending half
of each rank's rows to the next rank and locally permuting
(``dataset_shuffle``, reference datatools.py:246-299 — an approximate global
shuffle built from p2p sends). Under the single-controller TPU runtime the
global array is addressable as one sharded `jax.Array`, so the cross-process
shuffle is *exact*: one threefry permutation gather, compiled by XLA into
the same all-to-all traffic the reference hand-writes, with better mixing.
``dataset_ishuffle`` keeps the reference's async contract: the gather is
dispatched eagerly at epoch end and consumed (block-on-ready) at next epoch
start, overlapping reshuffle communication with host-side epoch turnover.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dndarray import DNDarray

__all__ = ["DataLoader", "Dataset", "dataset_shuffle", "dataset_ishuffle"]


class Dataset:
    """A dataset over one or more aligned DNDarrays (reference
    datatools.py:143-244).

    Holds ``data`` (and optionally ``targets``) split along axis 0. The
    reference slices every rank's shard to the *minimum* shard length so all
    ranks iterate the same number of batches (reference datatools.py:147-155
    "slice off the remaining elements"); the analog here is trimming the
    global length to a multiple of the mesh size at iteration time — done by
    the DataLoader's batching, which only emits mesh-divisible batches.

    Parameters
    ----------
    array : DNDarray
        The samples, split=0 (or replicated).
    targets : DNDarray, optional
        Aligned labels.
    ishuffle : bool
        Use non-blocking (dispatch-early) shuffles between epochs.
    test_set : bool
        Never shuffle when True.
    """

    def __init__(
        self,
        array: DNDarray,
        targets: Optional[DNDarray] = None,
        ishuffle: bool = False,
        test_set: bool = False,
    ):
        if not isinstance(array, DNDarray):
            raise TypeError(f"array must be a DNDarray, got {type(array)}")
        if array.split not in (None, 0):
            raise ValueError(f"Dataset arrays must be split=0 or None, got {array.split}")
        if targets is not None and not isinstance(targets, DNDarray):
            raise TypeError(f"targets must be a DNDarray, got {type(targets)}")
        self.htdata = array
        self.httargets = targets
        self.comm = array.comm
        self.ishuffle = ishuffle
        self.test_set = test_set
        self._pending: Optional[List[jax.Array]] = None
        self._rng_key = jax.random.key(0)

    # -- reference-parity accessors ------------------------------------------

    @property
    def data(self) -> jax.Array:
        """The sample buffer: the logical global array on a single
        controller; under multi-host, THIS PROCESS's canonical slab — the
        reference's local-shard Dataset semantics (datatools.py:143)."""
        return self._host_view(self.htdata)

    @property
    def targets(self):
        return None if self.httargets is None else self._host_view(self.httargets)

    @staticmethod
    def _host_view(arr: DNDarray) -> jax.Array:
        if jax.process_count() > 1 and arr.split is not None:
            from ...core.io import _local_block

            return jnp.asarray(_local_block(arr)[0])
        return arr._logical()

    def __len__(self) -> int:
        return self.htdata.shape[0]

    def __getitem__(self, index):
        items = [self.data[index]]
        if self.httargets is not None:
            items.append(self.targets[index])
        return tuple(items) if len(items) > 1 else items[0]

    # -- shuffling ------------------------------------------------------------

    def _arrays(self) -> List[DNDarray]:
        out = [self.htdata]
        if self.httargets is not None:
            out.append(self.httargets)
        return out

    def Shuffle(self) -> None:
        """Blocking global shuffle of data (and targets) along axis 0
        (reference Dataset.Shuffle -> dataset_shuffle)."""
        dataset_shuffle(self, [["data", "htdata"], ["targets", "httargets"]])

    def Ishuffle(self) -> None:
        """Dispatch the shuffle without waiting (reference Dataset.Ishuffle
        -> dataset_ishuffle); harvested by the next epoch's iterator."""
        dataset_ishuffle(self, [["data", "htdata"], ["targets", "httargets"]])


def _shuffle_arrays(dataset, blocking: bool) -> None:
    """Common engine: one permutation applied to every attached array."""
    if dataset.test_set:
        return
    n = len(dataset)
    dataset._rng_key, sub = jax.random.split(dataset._rng_key)
    perm = jax.random.permutation(sub, n)

    shuffled = []
    for arr in dataset._arrays():
        if arr.split is not None and arr.comm.size > 1:
            # distributed: the sharded-gather permutation (the exact global
            # cross-shard shuffle) — canonical physical output, multi-host
            from ...core.indexing import _advanced_take

            shuffled.append(_advanced_take(arr, 0, jnp.asarray(perm)).larray)
        else:
            shuffled.append(jnp.take(arr._logical(), perm, axis=0))
    if blocking:
        _apply_shuffled(dataset, shuffled)
        jax.block_until_ready([a.larray for a in dataset._arrays()])
        dataset._pending = None
    else:
        # async contract: dispatch now, harvest at next epoch start
        dataset._pending = shuffled


def _apply_shuffled(dataset, shuffled) -> None:
    for arr, out in zip(dataset._arrays(), shuffled):
        if arr.split is not None and arr.comm.size > 1:
            arr.larray = out  # already the canonical physical layout
        else:
            arr.larray = DNDarray.from_logical(
                out, arr.split, arr.device, arr.comm
            ).larray


def _harvest_pending(dataset) -> None:
    """Apply a previously dispatched Ishuffle (reference dataset_irecv,
    datatools.py:343-375)."""
    if dataset._pending is None:
        return
    _apply_shuffled(dataset, dataset._pending)
    dataset._pending = None


def dataset_shuffle(dataset, attrs: List[list]) -> None:
    """Blocking cross-shard shuffle (reference datatools.py:246-299).

    ``attrs`` is accepted for signature parity; the permutation is always
    applied consistently to every array attached to the dataset."""
    _shuffle_arrays(dataset, blocking=True)


def dataset_ishuffle(dataset, attrs: List[list]) -> None:
    """Non-blocking cross-shard shuffle (reference datatools.py:301-341):
    dispatched immediately, harvested by the next iterator."""
    _shuffle_arrays(dataset, blocking=False)


class DataLoader:
    """Iterable over mesh-sharded batches with inter-epoch global shuffling
    (reference datatools.py:16-141).

    Yields tuples of `jax.Array`s (data[, targets]) batch-sharded along axis
    0 over the dataset's mesh — ready to feed a DataParallel/DASO train
    step. Batches are always mesh-divisible: the effective batch size is
    rounded down to a multiple of the mesh size and, like the reference
    (which slices each rank's shard to the common minimum), at most one
    ragged tail batch per epoch is dropped unless it is exactly divisible.

    Parameters
    ----------
    dataset : Dataset or DNDarray
        A DNDarray is wrapped in a :class:`Dataset` automatically.
    batch_size : int
        Global batch size.
    shuffle : bool
        Reshuffle between epochs (first epoch iterates in storage order,
        matching the reference's shuffle-after-first-iter logic).
    drop_last : bool
        Drop the final non-divisible batch. Forced True when the batch
        cannot be made mesh-divisible otherwise.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = True,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
    ):
        if isinstance(dataset, DNDarray):
            dataset = Dataset(dataset)
        if not isinstance(dataset, Dataset):
            raise TypeError(
                f"dataset must be a heat_tpu Dataset or DNDarray, got {type(dataset)}"
            )
        self.dataset = dataset
        self.ishuffle = dataset.ishuffle
        self.shuffle = shuffle
        p = dataset.comm.size
        if batch_size < p:
            raise ValueError(
                f"batch_size ({batch_size}) must be >= mesh size ({p})"
            )
        self.batch_size = (batch_size // p) * p
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self._first_iter = True
        self.last_epoch = False

    def _mh_geometry(self):
        """Multi-host batch geometry: rows-per-batch for THIS process and
        the common batch count (every process's slab sliced to the common
        minimum — the reference's per-rank slice-off, datatools.py:147-155).
        Pure chunk arithmetic, identical on every process — no comm."""
        comm = self.dataset.comm
        n = len(self.dataset)
        per_dev = self.batch_size // comm.size
        counts, _ = comm.counts_displs(n)
        proc_rows: dict = {}
        proc_ldc: dict = {}
        for dev, cnt in zip(comm.devices, counts):
            proc_rows[dev.process_index] = proc_rows.get(dev.process_index, 0) + cnt
            proc_ldc[dev.process_index] = proc_ldc.get(dev.process_index, 0) + 1
        nb = min(
            proc_rows[pi] // (per_dev * proc_ldc[pi]) if proc_ldc[pi] else 0
            for pi in proc_ldc
        )
        my_rows = per_dev * proc_ldc.get(jax.process_index(), 0)
        return my_rows, nb

    def __len__(self) -> int:
        if (
            jax.process_count() > 1
            and self.dataset.htdata.split is not None
        ):
            return self._mh_geometry()[1]
        n = len(self.dataset)
        p = self.dataset.comm.size
        full, rem = divmod(n, self.batch_size)
        # the tail batch is emitted at its largest mesh-divisible size; only
        # rem % p rows are ever lost per epoch — the same bound as the
        # reference's per-rank slice-off (datatools.py:147-155)
        if rem >= p and not self.drop_last:
            return full + 1
        return full

    def _epoch_turnover(self) -> None:
        """Shuffle logic between epochs (reference
        _full_dataset_shuffle_iter, datatools.py:124-141)."""
        if not self.shuffle or self.dataset.test_set:
            return
        if not self.ishuffle:
            if self._first_iter:
                self._first_iter = False
            else:
                self.dataset.Shuffle()
        else:
            # harvest the permutation dispatched at the *previous* epoch's
            # turnover first, then dispatch the next one — reversing this
            # order would consume the fresh dispatch synchronously and the
            # overlap the async contract promises would never happen
            if self._first_iter:
                self._first_iter = False
            else:
                _harvest_pending(self.dataset)
            if not self.last_epoch:
                self.dataset.Ishuffle()

    def __iter__(self) -> Iterator:
        self._epoch_turnover()
        comm = self.dataset.comm
        data = self.dataset.data
        targets = self.dataset.targets
        if jax.process_count() > 1 and self.dataset.htdata.split is not None:
            # multi-host: each process batches ITS slab; per-batch blocks
            # assemble into globally-sharded arrays (the reference's
            # iterate-your-shard design). `data` is already the local slab.
            my_rows, nb = self._mh_geometry()
            bs = self.batch_size

            def assemble(local, ndim_shape):
                return jax.make_array_from_process_local_data(
                    comm.sharding(0, len(ndim_shape)), local, ndim_shape
                )

            for i in range(nb):
                lo = i * my_rows
                xb = assemble(
                    np.asarray(data[lo : lo + my_rows]),
                    (bs,) + tuple(data.shape[1:]),
                )
                if targets is None:
                    batch = (xb,)
                else:
                    yb = assemble(
                        np.asarray(targets[lo : lo + my_rows]),
                        (bs,) + tuple(targets.shape[1:]),
                    )
                    batch = (xb, yb)
                yield self.collate_fn(*batch) if self.collate_fn else batch
            return
        n = data.shape[0]
        bs = self.batch_size
        nb = len(self)
        for i in range(nb):
            lo = i * bs
            cur = min(bs, n - lo)
            cur -= cur % comm.size
            xb = jax.device_put(
                data[lo : lo + cur], comm.sharding(0, data.ndim)
            )
            if targets is None:
                batch = (xb,)
            else:
                yb = jax.device_put(
                    targets[lo : lo + cur], comm.sharding(0, targets.ndim)
                )
                batch = (xb, yb)
            yield self.collate_fn(*batch) if self.collate_fn else batch
