"""Graph algorithms (reference: heat/graph/)."""

from .laplacian import *
from .components import *
