"""Graph Laplacian construction (reference: heat/graph/laplacian.py:12-141)."""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray

__all__ = ["Laplacian"]


class Laplacian:
    """Build a graph Laplacian from pairwise similarities (reference
    laplacian.py:12).

    Parameters
    ----------
    similarity : callable
        DNDarray (n, d) → similarity/adjacency matrix (n, n) — e.g.
        `ht.spatial.rbf`.
    definition : 'simple' | 'norm_sym'
        L = D − A, or L = I − D^−1/2 A D^−1/2 (reference :73, :97).
    mode : 'fully_connected' | 'eNeighbour'
        Keep the full weighted graph, or threshold into an
        epsilon-neighborhood graph.
    threshold_key : 'upper' | 'lower'
        For eNeighbour: keep edges whose weight is below ('upper') or above
        ('lower') `threshold_value` (reference boundary semantics).
    threshold_value : float
    """

    def __init__(
        self,
        similarity: Callable,
        weighted: bool = True,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: int = 10,
    ):
        self.similarity_metric = similarity
        self.weighted = weighted
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError(
                "Only simple and normalized symmetric graph laplacians are supported at the moment"
            )
        if mode not in ("eNeighbour", "fully_connected"):
            raise NotImplementedError(
                "Only eNeighborhood and fully-connected graphs supported at the moment."
            )
        self.definition = definition
        self.mode = mode
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours

    def _normalized_symmetric_L(self, A: jnp.ndarray) -> jnp.ndarray:
        """L = I − D^−1/2 A D^−1/2 (reference laplacian.py:73)."""
        d = jnp.sum(A, axis=1)
        d_inv_sqrt = jnp.where(d > 0, 1.0 / jnp.sqrt(d), 0.0)
        L = -A * d_inv_sqrt[:, None] * d_inv_sqrt[None, :]
        L = L.at[jnp.diag_indices(L.shape[0])].set(1.0)
        return L

    def _simple_L(self, A: jnp.ndarray) -> jnp.ndarray:
        """L = D − A (reference laplacian.py:97)."""
        d = jnp.sum(A, axis=1)
        L = -A
        L = L.at[jnp.diag_indices(L.shape[0])].add(d)
        return L

    def construct(self, X: DNDarray) -> DNDarray:
        """Similarity → adjacency → Laplacian (reference laplacian.py:110)."""
        S = self.similarity_metric(X)
        A = S._replicated()
        if self.mode == "eNeighbour":
            key, val = self.epsilon
            if key == "upper":
                mask = A < val
            else:
                mask = A > val
            A = jnp.where(mask, A if self.weighted else jnp.ones_like(A), jnp.zeros_like(A))
        # no self-loops
        A = A.at[jnp.diag_indices(A.shape[0])].set(0.0)
        if self.definition == "norm_sym":
            L = self._normalized_symmetric_L(A)
        else:
            L = self._simple_L(A)
        return DNDarray.from_logical(L, X.split, X.device, X.comm)
