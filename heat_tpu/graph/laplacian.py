"""Graph Laplacian construction (reference: heat/graph/laplacian.py:12-141).

ISSUE 13: the eNeighbour mode — a thresholded similarity graph, i.e. a
*sparse* object by construction — now produces a
:class:`heat_tpu.sparse.SparseDNDarray` instead of a masked dense
matrix, and builds it **without ever materializing the full dense
similarity**: the pairwise kernel runs in row blocks sized by
:func:`heat_tpu.resilience.memory_guard.temp_budget` (the same
row-blocking discipline ``spatial.cdist``'s broadcast kernels use), each
block is thresholded and compacted immediately, so peak live bytes stay
O(n·block + nnz) where the old path pinned O(n²). A graph denser than
``HEAT_TPU_SPARSE_DENSE_THRESHOLD`` falls back to the dense pipeline (a
CSR that dense moves more bytes than the GEMM it replaces).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from heat_tpu import _knobs as knobs

from .. import telemetry
from ..core import program_cache, types
from ..core.dndarray import DNDarray

__all__ = ["Laplacian"]


class Laplacian:
    """Build a graph Laplacian from pairwise similarities (reference
    laplacian.py:12).

    Parameters
    ----------
    similarity : callable
        DNDarray (n, d) → similarity/adjacency matrix (n, n) — e.g.
        `ht.spatial.rbf`.
    definition : 'simple' | 'norm_sym'
        L = D − A, or L = I − D^−1/2 A D^−1/2 (reference :73, :97).
    mode : 'fully_connected' | 'eNeighbour'
        Keep the full weighted graph, or threshold into an
        epsilon-neighborhood graph.
    threshold_key : 'upper' | 'lower'
        For eNeighbour: keep edges whose weight is below ('upper') or above
        ('lower') `threshold_value` (reference boundary semantics).
    threshold_value : float
    sparse : bool, optional
        eNeighbour output representation: ``None`` (default) builds a
        :class:`~heat_tpu.sparse.SparseDNDarray` and densifies only past
        the ``HEAT_TPU_SPARSE_DENSE_THRESHOLD`` density knob; ``True``
        forces sparse regardless of density; ``False`` restores the
        legacy dense path bit-for-bit. Ignored for fully_connected
        graphs (which are dense by definition).
    pair_similarity : callable, optional
        Two-operand block form ``(x_rows, x) -> (rows, n) similarity`` —
        what lets the sparse path chunk construction under the memory
        budget. Without it the sparse path computes the full similarity
        through ``similarity`` first (correct, but the O(n²) guarantee
        is lost); ``cluster.Spectral`` always passes the block form.
    """

    def __init__(
        self,
        similarity: Callable,
        weighted: bool = True,
        definition: str = "norm_sym",
        mode: str = "fully_connected",
        threshold_key: str = "upper",
        threshold_value: float = 1.0,
        neighbours: int = 10,
        sparse: Optional[bool] = None,
        pair_similarity: Optional[Callable] = None,
    ):
        self.similarity_metric = similarity
        self.weighted = weighted
        if definition not in ("simple", "norm_sym"):
            raise NotImplementedError(
                "Only simple and normalized symmetric graph laplacians are supported at the moment"
            )
        if mode not in ("eNeighbour", "fully_connected"):
            raise NotImplementedError(
                "Only eNeighborhood and fully-connected graphs supported at the moment."
            )
        self.definition = definition
        self.mode = mode
        self.epsilon = (threshold_key, threshold_value)
        self.neighbours = neighbours
        self.sparse = sparse
        self.pair_similarity = pair_similarity

    def _normalized_symmetric_L(self, A: jnp.ndarray) -> jnp.ndarray:
        """L = I − D^−1/2 A D^−1/2 (reference laplacian.py:73)."""
        d = jnp.sum(A, axis=1)
        d_inv_sqrt = jnp.where(d > 0, 1.0 / jnp.sqrt(d), 0.0)
        L = -A * d_inv_sqrt[:, None] * d_inv_sqrt[None, :]
        L = L.at[jnp.diag_indices(L.shape[0])].set(1.0)
        return L

    def _simple_L(self, A: jnp.ndarray) -> jnp.ndarray:
        """L = D − A (reference laplacian.py:97)."""
        d = jnp.sum(A, axis=1)
        L = -A
        L = L.at[jnp.diag_indices(L.shape[0])].add(d)
        return L

    # -- sparse eNeighbour path (ISSUE 13) ------------------------------------

    def _threshold_mask(self, block: np.ndarray) -> np.ndarray:
        key, val = self.epsilon
        return block < val if key == "upper" else block > val

    def _sparse_adjacency_coo(self, X: DNDarray):
        """Budget-chunked thresholding: similarity row blocks sized by
        ``memory_guard.temp_budget`` are compacted to COO immediately —
        the full (n, n) similarity never exists, on device or host. Each
        row gets an explicit diagonal slot (value 0 — no self-loops) so
        the Laplacian value rewrite below never needs a structural
        insert. Returns host triplets sorted by (row, col)."""
        from ..resilience import memory_guard

        n = X.shape[0]
        dt = types.promote_types(X.dtype, types.float32)
        item = dt.byte_size()
        # one (block, n) similarity slab per step, bounded like
        # spatial.cdist's broadcast temporaries
        budget = memory_guard.temp_budget(1 << 28)
        bs = max(1, min(n, budget // max(1, n * item)))
        x_log = X._replicated()
        x_rep = DNDarray.from_logical(x_log, None, X.device, X.comm)
        s_full = None
        if self.pair_similarity is None:
            # no block form available: ONE full-similarity pass, hoisted
            # out of the loop (the O(n²)-free guarantee is lost either
            # way — documented in the class docstring — but it must be
            # paid once, not once per block), thresholded in host blocks
            s_full = self.similarity_metric(x_rep)
        rows_l, cols_l, vals_l = [], [], []
        tel = telemetry.enabled()
        reg = telemetry.get_registry() if tel else None
        for lo in range(0, n, bs):
            hi = min(n, lo + bs)
            if s_full is not None:
                sb = s_full[lo:hi, :]
            else:
                xb = DNDarray.from_logical(
                    x_log[lo:hi], None, X.device, X.comm
                )
                sb = self.pair_similarity(xb, x_rep)
            s_host = np.asarray(sb.numpy(), dtype=dt.char())
            mask = self._threshold_mask(s_host)
            diag = np.arange(lo, hi)
            mask[diag - lo, diag] = True  # explicit diagonal slots
            r_, c_ = np.nonzero(mask)
            v_ = (
                s_host[r_, c_] if self.weighted
                else np.ones(r_.shape[0], dtype=s_host.dtype)
            )
            v_[c_ == r_ + lo] = 0.0  # no self-loops
            rows_l.append(r_.astype(np.int64) + lo)
            cols_l.append(c_.astype(np.int64))
            vals_l.append(v_)
            if tel:
                # the regression oracle for the O(n²)-free claim: peak
                # device bytes across construction stay under the dense
                # footprint (tests/test_sparse.py pins it)
                reg.high_water(
                    "sparse.laplacian_live_bytes",
                    telemetry.memory.live_bytes()["total"],
                )
        return (
            np.concatenate(rows_l), np.concatenate(cols_l),
            np.concatenate(vals_l), bs, dt,
        )

    def _sparse_laplacian_values(self, A, d: DNDarray, dt):
        """Rewrite the adjacency values into Laplacian values in place of
        structure (one cached shard_map program, site
        ``sparse.laplacian``): the explicit diagonal slots become 1
        (norm_sym) or the degree (simple), off-diagonals scale by
        −D^{-1/2}·D^{-1/2} (norm_sym) or negate (simple). Shard-local —
        the only collective the sparse Laplacian ever pays is the degree
        spmv's all-reduce tail."""
        from ..sparse.container import SparseDNDarray
        from ..sparse.ops import _slot_rows

        comm = A.comm
        e_spec = comm.spec(0, 1)
        rep = comm.spec(None, 1)
        definition = self.definition

        def build():
            def body(ip, ix, vals, dvec):
                rows_local = _slot_rows(ip, ix.shape[0])
                r = ip.shape[0] - 1
                row_g = comm.axis_index() * r + rows_local
                valid = (
                    jnp.arange(ix.shape[0], dtype=ip.dtype) < ip[-1]
                )
                row_c = jnp.clip(row_g, 0, dvec.shape[0] - 1)
                on_diag = ix == row_c
                if definition == "norm_sym":
                    dinv = jnp.where(
                        dvec > 0, 1.0 / jnp.sqrt(dvec),
                        jnp.zeros((), dvec.dtype),
                    )
                    out = jnp.where(
                        on_diag,
                        jnp.ones((), vals.dtype),
                        -vals * dinv[row_c] * dinv[ix],
                    )
                else:
                    out = jnp.where(on_diag, dvec[row_c], -vals)
                return jnp.where(valid, out, jnp.zeros((), vals.dtype))

            def call(ip, ix, vals, dvec):
                import jax

                return jax.shard_map(
                    body, mesh=comm.mesh,
                    in_specs=(e_spec, e_spec, e_spec, rep),
                    out_specs=e_spec,
                )(ip, ix, vals, dvec)

            return call

        prog = program_cache.cached_program(
            "sparse.laplacian", (definition, dt.char()), build, comm=comm
        )
        new_vals = prog(
            A.indptr, A.indices, A.values.astype(dt.jnp_type()),
            d.larray.astype(dt.jnp_type()),
        )
        return SparseDNDarray.from_shard_arrays(
            A.indptr, A.indices, new_vals, A.shape, A.counts,
            device=A.device, comm=A.comm, dtype=dt,
        )

    def _construct_sparse(self, X: DNDarray):
        """The eNeighbour sparse pipeline: chunked threshold → density
        gate → degree spmv → value rewrite. Falls back to the dense
        path past the density knob (returns None to signal it)."""
        from .. import sparse as htsparse

        n = X.shape[0]
        rows, cols, vals, bs, dt = self._sparse_adjacency_coo(X)
        density = rows.shape[0] / float(n * n)
        limit = knobs.get("HEAT_TPU_SPARSE_DENSE_THRESHOLD")
        if self.sparse is None and limit is not None and density > limit:
            if telemetry.enabled():
                reg = telemetry.get_registry()
                reg.add("sparse.dense_fallback", 1)
                reg.emit(
                    "sparse", "laplacian", event="dense_fallback",
                    density=density, limit=limit, rows=n,
                )
            return None
        from ..core import factories

        A = htsparse.csr_from_coo(
            rows, cols, vals, (n, n), comm=X.comm, device=X.device
        )
        ones = factories.ones(
            n, dtype=dt, device=X.device, comm=X.comm
        )
        d = htsparse.spmv(A, ones, out_split=None)
        L = self._sparse_laplacian_values(A, d, dt)
        if telemetry.enabled():
            reg = telemetry.get_registry()
            reg.add("sparse.laplacian", 1)
            reg.emit(
                "sparse", "laplacian", event="laplacian", rows=n,
                nnz=L.nnz, density=density, block_rows=bs,
            )
        return L

    def construct(self, X: DNDarray):
        """Similarity → adjacency → Laplacian (reference laplacian.py:110).

        eNeighbour graphs return a
        :class:`~heat_tpu.sparse.SparseDNDarray` (unless ``sparse=False``
        or the density gate trips); fully-connected graphs return the
        dense :class:`DNDarray` as before."""
        if self.mode == "eNeighbour" and self.sparse is not False:
            L = self._construct_sparse(X)
            if L is not None:
                return L
        S = self.similarity_metric(X)
        A = S._replicated()
        if self.mode == "eNeighbour":
            key, val = self.epsilon
            if key == "upper":
                mask = A < val
            else:
                mask = A > val
            A = jnp.where(mask, A if self.weighted else jnp.ones_like(A), jnp.zeros_like(A))
        # no self-loops
        A = A.at[jnp.diag_indices(A.shape[0])].set(0.0)
        if self.definition == "norm_sym":
            L = self._normalized_symmetric_L(A)
        else:
            L = self._simple_L(A)
        return DNDarray.from_logical(L, X.split, X.device, X.comm)
