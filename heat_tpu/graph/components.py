"""Connected components via iterated label-propagation spmv (ISSUE 13).

The classic min-label relay: every vertex starts as its own label (its
index) and repeatedly adopts the minimum label among its neighbours.
Each relaxation round is ONE structure-only sparse matvec —
:func:`heat_tpu.sparse.spmv` with ``reduce='min'``/``pattern=True``, the
shard-local CSR segment-min plus the (never-compressed) pmin tail — so
the whole algorithm dispatches the same cached program per round, zero
steady-state recompiles, and converges in at most the graph diameter
rounds (the host checks the fixed point between rounds; labels are a
small replicated int vector, exactly the centroid-read pattern)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..core import types
from ..core.dndarray import DNDarray

__all__ = ["connected_components"]


def connected_components(
    A,
    *,
    assume_symmetric: bool = False,
    max_iter: Optional[int] = None,
) -> DNDarray:
    """Component labels of the graph whose edges are ``A``'s stored
    entries (values are ignored — structure-only propagation).

    ``A`` is a :class:`~heat_tpu.sparse.SparseDNDarray` (a dense square
    DNDarray is compacted first). Undirected semantics: unless
    ``assume_symmetric=True``, the transpose pattern joins each round so
    one-directional stored edges still merge their endpoints (the
    transpose is the audited all-to-all slab exchange, paid once).
    Returns the ``(n,)`` int64 replicated label vector — two vertices
    share a component iff they share a label; labels are each
    component's minimum vertex index."""
    from .. import sparse as htsparse

    if isinstance(A, DNDarray):
        A = htsparse.csr_from_dense(A)
    if not isinstance(A, htsparse.SparseDNDarray):
        raise TypeError(
            f"expected a SparseDNDarray (or dense DNDarray), got {type(A)}"
        )
    n, n2 = A.shape
    if n != n2:
        raise ValueError(f"adjacency must be square, got {A.shape}")
    from ..core import factories

    At = None if assume_symmetric else A.transpose()
    labels = factories.array(
        np.arange(n, dtype=np.int64), device=A.device, comm=A.comm
    )
    limit = n if max_iter is None else int(max_iter)
    rounds = 0
    prev = labels.numpy()
    with telemetry.span("sparse.components", gshape=[n, n], nnz=A.nnz):
        for _ in range(max(1, limit)):
            cand = htsparse.spmv(
                A, labels, reduce="min", pattern=True, out_split=None
            )
            new_log = jnp.minimum(labels.larray, cand.larray)
            if At is not None:
                cand_t = htsparse.spmv(
                    At, labels, reduce="min", pattern=True, out_split=None
                )
                new_log = jnp.minimum(new_log, cand_t.larray)
            rounds += 1
            cur = np.asarray(new_log)
            labels = DNDarray(
                new_log, (n,), types.int64, None, A.device, A.comm, True
            )
            if np.array_equal(cur, prev):
                break
            prev = cur
    if telemetry.enabled():
        reg = telemetry.get_registry()
        reg.add("sparse.components", 1)
        reg.emit(
            "sparse", "components", event="components", rows=n,
            rounds=rounds,
            n_components=int(np.unique(prev).shape[0]),
        )
    return labels
