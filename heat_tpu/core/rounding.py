"""Rounding and absolute-value ops (reference: heat/core/rounding.py)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from . import fusion, types
from ._operations import binary_op, local_op
from .dndarray import DNDarray

__all__ = ["abs", "absolute", "ceil", "clip", "fabs", "floor", "modf", "round", "sign", "trunc"]


@fusion.register_elementwise
def _modf_frac(a):
    """Fractional part of ``jnp.modf`` as a module-level registered op —
    a lambda here would trip ``fusion.fallbacks`` on every modf call
    (closures are refused by the program-cache keying rules)."""
    return jnp.modf(a)[0]


@fusion.register_elementwise
def _modf_int(a):
    """Integral part of ``jnp.modf`` (see :func:`_modf_frac`)."""
    return jnp.modf(a)[1]


def abs(x, out=None, dtype=None) -> DNDarray:
    """Elementwise absolute value (reference rounding.py `abs`)."""
    if dtype is not None and not issubclass(types.canonical_heat_type(dtype), types.datatype):
        raise TypeError("dtype must be a heat data type")
    res = local_op(jnp.abs, x, out)
    if dtype is not None:
        res = res.astype(types.canonical_heat_type(dtype), copy=False)
    return res


absolute = abs


def ceil(x, out=None) -> DNDarray:
    return local_op(jnp.ceil, x, out)


def clip(x: DNDarray, min, max, out=None) -> DNDarray:
    """Clip values to [min, max] (reference rounding.py `clip`). Passed as
    keyword config (not a closure) so scalar-bound clips join fused
    elementwise chains (core/fusion.py)."""
    if min is None and max is None:
        raise ValueError("either min or max must be set")
    return local_op(jnp.clip, x, out, min=min, max=max)


def sign(x, out=None) -> DNDarray:
    """Elementwise sign indicator (extension: numpy surface the reference
    lacks; its closest is logical.signbit)."""
    return local_op(jnp.sign, x, out)


def fabs(x, out=None) -> DNDarray:
    """Float absolute value (reference rounding.py `fabs`)."""
    res = local_op(jnp.abs, x, out=None)
    if issubclass(res.dtype, types.integer):
        res = res.astype(types.float32, copy=False)
    if out is not None:
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def floor(x, out=None) -> DNDarray:
    return local_op(jnp.floor, x, out)


def modf(x: DNDarray, out=None):
    """Fractional and integral parts (reference rounding.py `modf`). Both
    parts are registered fusable ops, so they join pending chains instead
    of flushing them (PR 4 left these as lambda fallbacks)."""
    frac = local_op(_modf_frac, x)
    intg = local_op(_modf_int, x)
    if out is not None:
        if not isinstance(out, tuple) or len(out) != 2:
            raise TypeError("expected out to be None or a tuple of two DNDarrays")
        out[0].larray = frac.larray
        out[1].larray = intg.larray
        return out
    return (frac, intg)


def round(x: DNDarray, decimals: int = 0, out=None, dtype=None) -> DNDarray:
    """Round to `decimals` digits (reference rounding.py `round`)."""
    res = local_op(jnp.round, x, out, decimals=decimals)
    if dtype is not None:
        res = res.astype(types.canonical_heat_type(dtype), copy=False)
    return res


def trunc(x, out=None) -> DNDarray:
    return local_op(jnp.trunc, x, out)


DNDarray.__abs__ = lambda self: abs(self)
DNDarray.abs = lambda self, out=None, dtype=None: abs(self, out, dtype)
DNDarray.ceil = lambda self, out=None: ceil(self, out)
DNDarray.clip = lambda self, a_min=None, a_max=None, out=None: clip(self, a_min, a_max, out)
DNDarray.fabs = lambda self, out=None: fabs(self, out)
DNDarray.floor = lambda self, out=None: floor(self, out)
DNDarray.modf = lambda self, out=None: modf(self, out)
DNDarray.round = lambda self, decimals=0, out=None, dtype=None: round(self, decimals, out, dtype)
DNDarray.trunc = lambda self, out=None: trunc(self, out)
