"""Arithmetic operations (reference: heat/core/arithmetics.py, 31 exports).

Every function is an instance of the four generic wrappers in
`_operations`; the reference's per-op MPI choreography (Exscan for cumsum,
Allreduce for sum/prod, edge-slice sends for diff) is replaced by single jnp
calls whose collectives XLA derives from the sharding.
"""

from __future__ import annotations

import builtins
from typing import Optional, Union

import jax.numpy as jnp

from . import types
from ._operations import binary_op, cum_op, local_op, reduce_op
from .dndarray import DNDarray

__all__ = [
    "add",
    "bitwise_and",
    "bitwise_not",
    "bitwise_or",
    "bitwise_xor",
    "cumprod",
    "cumproduct",
    "copysign",
    "cumsum",
    "diff",
    "div",
    "divide",
    "floordiv",
    "floor_divide",
    "fmod",
    "hypot",
    "invert",
    "left_shift",
    "mod",
    "mul",
    "multiply",
    "nanprod",
    "nansum",
    "neg",
    "negative",
    "pos",
    "positive",
    "pow",
    "power",
    "prod",
    "remainder",
    "right_shift",
    "sub",
    "subtract",
    "sum",
]


def add(t1, t2, out=None) -> DNDarray:
    """Elementwise addition (reference arithmetics.py `add`)."""
    return binary_op(jnp.add, t1, t2, out)


def _check_int_or_bool(*ts):
    for t in ts:
        if isinstance(t, DNDarray) and not issubclass(t.dtype, (types.integer, types.bool)):
            raise TypeError(f"operation not supported for input type {t.dtype}")
        if isinstance(t, builtins.float):
            raise TypeError("operation not supported for float scalars")


def bitwise_and(t1, t2, out=None) -> DNDarray:
    _check_int_or_bool(t1, t2)
    return binary_op(jnp.bitwise_and, t1, t2, out)


def bitwise_or(t1, t2, out=None) -> DNDarray:
    _check_int_or_bool(t1, t2)
    return binary_op(jnp.bitwise_or, t1, t2, out)


def bitwise_xor(t1, t2, out=None) -> DNDarray:
    _check_int_or_bool(t1, t2)
    return binary_op(jnp.bitwise_xor, t1, t2, out)


def bitwise_not(t, out=None) -> DNDarray:
    _check_int_or_bool(t)
    return local_op(jnp.bitwise_not, t, out)


invert = bitwise_not


def cumprod(a: DNDarray, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative product along axis (reference arithmetics.py `cumprod`;
    Exscan-based there, one masked jnp.cumprod here)."""
    return cum_op(jnp.cumprod, a, axis, neutral=1, out=out, dtype=dtype)


cumproduct = cumprod


def cumsum(a: DNDarray, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative sum along axis (reference arithmetics.py `cumsum`)."""
    return cum_op(jnp.cumsum, a, axis, neutral=0, out=out, dtype=dtype)


def diff(a: DNDarray, n: int = 1, axis: int = -1) -> DNDarray:
    """n-th discrete difference along axis (reference arithmetics.py `diff`,
    which sends boundary slices between ranks).

    Off the split axis this is purely shard-local (physical buffer, zero
    communication). Along the split axis it is a HALO stencil: each shard
    ppermutes its leading ``n`` rows to the previous shard, extends its
    block, and diffs locally — the reference's boundary-slice send as one
    shard_map kernel. The logical gather only remains for the corner cases
    where the result's chunking changes (tiny arrays, n close to the
    extent)."""
    if n == 0:
        return a
    if n < 0:
        raise ValueError(f"order must be non-negative but was {n}")
    from .stride_tricks import sanitize_axis

    axis = sanitize_axis(a.shape, axis)
    s = a.split
    if s is not None and axis != s:
        # shard-local: the split dim (and its pads) is untouched
        buf = jnp.diff(a.larray, n=n, axis=axis)
        gshape = tuple(
            max(dim - n, 0) if d == axis else dim for d, dim in enumerate(a.shape)
        )
        return DNDarray(buf, gshape, a.dtype, s, a.device, a.comm, True)
    if s is not None and a.comm.size > 1:
        comm = a.comm
        chunk = a.larray.shape[s] // comm.size
        n_out = a.shape[s] - n
        # fast path needs: halo fits in a chunk, and the result keeps the
        # same chunking (so shard-local outputs are already canonical; any
        # pad-contaminated rows land in the result's own pad region)
        if 0 < n <= chunk and n_out > 0 and -(-n_out // comm.size) == chunk:
            from ..parallel.halo import halo_stencil

            buf = halo_stencil(
                a.larray, n, lambda ext: jnp.diff(ext, n=n, axis=s),
                comm=comm, axis=s, sides="next",
            )
            gshape = tuple(
                n_out if d == s else dim for d, dim in enumerate(a.shape)
            )
            return DNDarray(buf, gshape, a.dtype, s, a.device, a.comm, True)
    res = jnp.diff(a._logical(), n=n, axis=axis)
    return DNDarray.from_logical(res, a.split, a.device, a.comm)


def div(t1, t2, out=None) -> DNDarray:
    """Elementwise true division (reference arithmetics.py `div`)."""
    return binary_op(jnp.true_divide, t1, t2, out)


divide = div


def floordiv(t1, t2, out=None) -> DNDarray:
    return binary_op(jnp.floor_divide, t1, t2, out)


floor_divide = floordiv


def fmod(t1, t2, out=None) -> DNDarray:
    """Elementwise C-style remainder (sign of dividend; reference
    arithmetics.py `fmod`)."""
    return binary_op(jnp.fmod, t1, t2, out)


def left_shift(t1, t2, out=None) -> DNDarray:
    _check_int_or_bool(t1)
    return binary_op(jnp.left_shift, t1, t2, out)


def mod(t1, t2, out=None) -> DNDarray:
    """Elementwise python-style modulo (sign of divisor; reference
    arithmetics.py `mod` = remainder)."""
    return binary_op(jnp.mod, t1, t2, out)


remainder = mod


def mul(t1, t2, out=None) -> DNDarray:
    return binary_op(jnp.multiply, t1, t2, out)


multiply = mul


def neg(t, out=None) -> DNDarray:
    return local_op(jnp.negative, t, out)


negative = neg


def pos(t, out=None) -> DNDarray:
    return local_op(jnp.positive, t, out)


positive = pos


def pow(t1, t2, out=None) -> DNDarray:
    return binary_op(jnp.power, t1, t2, out)


power = pow


def prod(a: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Product of elements over axis (reference arithmetics.py `prod` via
    __reduce_op + MPI.PROD)."""
    return reduce_op(jnp.prod, a, axis, neutral=1, out=out, keepdims=keepdims)


def nanprod(a: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Product treating NaN as 1 (reference arithmetics.py `nanprod`).
    Rides the same ``reduce_op`` machinery as :func:`prod` — including
    Fusion 2.0 chain absorption. Exact ints cannot hold NaN, so they
    route to :func:`prod` (identical numpy semantics)."""
    if not jnp.issubdtype(a.dtype.jnp_type(), jnp.inexact):
        return prod(a, axis, out=out, keepdims=keepdims)
    return reduce_op(jnp.nanprod, a, axis, neutral=1, out=out, keepdims=keepdims)


def nansum(a: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Sum treating NaN as 0 (reference arithmetics.py `nansum`)."""
    if not jnp.issubdtype(a.dtype.jnp_type(), jnp.inexact):
        return sum(a, axis, out=out, keepdims=keepdims)
    return reduce_op(jnp.nansum, a, axis, neutral=0, out=out, keepdims=keepdims)


def right_shift(t1, t2, out=None) -> DNDarray:
    _check_int_or_bool(t1)
    return binary_op(jnp.right_shift, t1, t2, out)


def sub(t1, t2, out=None) -> DNDarray:
    return binary_op(jnp.subtract, t1, t2, out)


subtract = sub


def sum(a: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Sum of elements over axis (reference arithmetics.py `sum` via
    __reduce_op + MPI.SUM; one jnp.sum here, psum inserted by XLA)."""
    return reduce_op(jnp.sum, a, axis, neutral=0, out=out, keepdims=keepdims)


# ---- DNDarray operator attachment (the reference assigns these in
# dndarray.py itself; we attach from the op modules to avoid import cycles)

DNDarray.__add__ = lambda self, other: add(self, other)
DNDarray.__radd__ = lambda self, other: add(other, self)
DNDarray.__iadd__ = lambda self, other: add(self, other)
DNDarray.__sub__ = lambda self, other: sub(self, other)
DNDarray.__rsub__ = lambda self, other: sub(other, self)
DNDarray.__isub__ = lambda self, other: sub(self, other)
DNDarray.__mul__ = lambda self, other: mul(self, other)
DNDarray.__rmul__ = lambda self, other: mul(other, self)
DNDarray.__imul__ = lambda self, other: mul(self, other)
DNDarray.__truediv__ = lambda self, other: div(self, other)
DNDarray.__rtruediv__ = lambda self, other: div(other, self)
DNDarray.__itruediv__ = lambda self, other: div(self, other)
DNDarray.__floordiv__ = lambda self, other: floordiv(self, other)
DNDarray.__rfloordiv__ = lambda self, other: floordiv(other, self)
DNDarray.__mod__ = lambda self, other: mod(self, other)
DNDarray.__rmod__ = lambda self, other: mod(other, self)
DNDarray.__pow__ = lambda self, other: pow(self, other)
DNDarray.__rpow__ = lambda self, other: pow(other, self)
DNDarray.__neg__ = lambda self: neg(self)
DNDarray.__pos__ = lambda self: pos(self)
DNDarray.__invert__ = lambda self: bitwise_not(self)
DNDarray.__and__ = lambda self, other: bitwise_and(self, other)
DNDarray.__rand__ = lambda self, other: bitwise_and(other, self)
DNDarray.__or__ = lambda self, other: bitwise_or(self, other)
DNDarray.__ror__ = lambda self, other: bitwise_or(other, self)
DNDarray.__xor__ = lambda self, other: bitwise_xor(self, other)
DNDarray.__rxor__ = lambda self, other: bitwise_xor(other, self)
DNDarray.__lshift__ = lambda self, other: left_shift(self, other)
DNDarray.__rshift__ = lambda self, other: right_shift(self, other)

DNDarray.sum = lambda self, axis=None, out=None, keepdims=False: sum(self, axis, out, keepdims)
DNDarray.prod = lambda self, axis=None, out=None, keepdims=False: prod(self, axis, out, keepdims)
DNDarray.cumsum = lambda self, axis, dtype=None, out=None: cumsum(self, axis, dtype, out)
DNDarray.cumprod = lambda self, axis, dtype=None, out=None: cumprod(self, axis, dtype, out)


def copysign(a, b, out=None) -> DNDarray:
    """Magnitude of ``a`` with the sign of ``b`` (extension: numpy surface
    the reference lacks)."""
    return binary_op(jnp.copysign, a, b, out)


def hypot(a, b, out=None) -> DNDarray:
    """Elementwise ``sqrt(a**2 + b**2)`` (extension: numpy surface the
    reference lacks)."""
    return binary_op(jnp.hypot, a, b, out)
