"""Shape/axis helpers (reference: heat/core/stride_tricks.py:11-195)."""

from __future__ import annotations

import itertools
from typing import Sequence, Tuple, Union

import numpy as np

__all__ = ["broadcast_shape", "sanitize_axis", "sanitize_shape", "sanitize_slice"]


def broadcast_shape(shape_a: Sequence[int], shape_b: Sequence[int]) -> Tuple[int, ...]:
    """Broadcast two shapes per numpy rules, raising ValueError on mismatch
    (reference stride_tricks.py:11-54)."""
    try:
        return tuple(np.broadcast_shapes(tuple(shape_a), tuple(shape_b)))
    except ValueError:
        raise ValueError(
            f"operands could not be broadcast, input shapes {tuple(shape_a)} {tuple(shape_b)}"
        ) from None


def sanitize_axis(
    shape: Sequence[int], axis: Union[int, Sequence[int], None]
) -> Union[int, Tuple[int, ...], None]:
    """Validate and wrap an axis (or tuple of axes) into [0, ndim)
    (reference stride_tricks.py:57-117)."""
    ndim = len(shape)
    if axis is None:
        return None
    if isinstance(axis, (list, tuple, np.ndarray)):
        out = []
        for a in axis:
            if not isinstance(a, (int, np.integer)):
                raise TypeError(f"axis must be None or int or tuple of ints, got {axis!r}")
            a = int(a)
            if a < -ndim or a >= max(ndim, 1):
                raise ValueError(f"axis {a} is out of bounds for {ndim}-dimensional array")
            out.append(a % max(ndim, 1))
        if len(set(out)) != len(out):
            raise ValueError("duplicate axes given")
        return tuple(out)
    if isinstance(axis, (int, np.integer)):
        axis = int(axis)
        if ndim == 0 and axis in (-1, 0):
            return 0 if axis == 0 else 0
        if axis < -ndim or axis >= max(ndim, 1):
            raise ValueError(f"axis {axis} is out of bounds for {ndim}-dimensional array")
        return axis % max(ndim, 1)
    raise TypeError(f"axis must be None or int or tuple of ints, got {type(axis)}")


def sanitize_shape(shape: Union[int, Sequence[int]], lval: int = 0) -> Tuple[int, ...]:
    """Validate a shape specifier into a tuple of ints >= lval
    (reference stride_tricks.py:120-162)."""
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    elif np.isscalar(shape):
        raise TypeError(f"expected sequence object with length >= 0 or a single integer")
    shape = tuple(shape)
    for dim in shape:
        if not isinstance(dim, (int, np.integer)):
            raise TypeError(f"expected integer dimensions, got {type(dim)}")
        if int(dim) < lval:
            raise ValueError(f"negative dimensions are not allowed, got {dim}")
    return tuple(int(d) for d in shape)


def sanitize_slice(sl: slice, max_dim: int) -> slice:
    """Normalize a slice to explicit non-negative start/stop/step against a
    dimension of length max_dim (reference stride_tricks.py:165-195)."""
    if not isinstance(sl, slice):
        raise TypeError("can only be used for slices")
    start, stop, step = sl.indices(max_dim)
    return slice(start, stop, step)
