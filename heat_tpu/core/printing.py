"""Formatted array printing (reference: heat/core/printing.py:20-167).

The reference gathers edge items of each shard to rank 0 and defers to torch
print options; here the logical array is globally addressable, so printing
defers to numpy's formatter (with the same threshold/edgeitems controls).
"""

from __future__ import annotations

import numpy as np

__all__ = ["get_printoptions", "set_printoptions"]

# numpy-managed state; expose the reference's API names
_LOCAL_PRINT = False


def get_printoptions() -> dict:
    """Current print options (reference printing.py:20)."""
    return dict(np.get_printoptions())


def set_printoptions(
    precision=None,
    threshold=None,
    edgeitems=None,
    linewidth=None,
    profile=None,
    sci_mode=None,
):
    """Configure print options (reference printing.py:27; torch-style
    ``profile`` presets are honored)."""
    if profile == "default":
        np.set_printoptions(precision=4, threshold=1000, edgeitems=3, linewidth=80)
    elif profile == "short":
        np.set_printoptions(precision=2, threshold=1000, edgeitems=2, linewidth=80)
    elif profile == "full":
        np.set_printoptions(precision=4, threshold=np.inf, edgeitems=3, linewidth=80)
    kwargs = {}
    if precision is not None:
        kwargs["precision"] = precision
    if threshold is not None:
        kwargs["threshold"] = threshold
    if edgeitems is not None:
        kwargs["edgeitems"] = edgeitems
    if linewidth is not None:
        kwargs["linewidth"] = linewidth
    if kwargs:
        np.set_printoptions(**kwargs)


def __str__(dndarray) -> str:
    """Render a DNDarray (reference printing.py:61 `__str__`/`_tensor_str`)."""
    try:
        values = np.array2string(
            dndarray.numpy(), separator=", ", prefix="DNDarray("
        )
    except Exception as e:  # pragma: no cover - debugging aid
        values = f"<unprintable: {e}>"
    return (
        f"DNDarray({values}, dtype=ht.{dndarray.dtype.__name__}, "
        f"device={dndarray.device}, split={dndarray.split})"
    )
