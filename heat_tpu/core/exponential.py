"""Exponential and logarithmic ops (reference: heat/core/exponential.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import binary_op, local_op
from .dndarray import DNDarray

__all__ = [
    "exp",
    "expm1",
    "exp2",
    "log",
    "log2",
    "log10",
    "log1p",
    "logaddexp",
    "logaddexp2",
    "sqrt",
    "square",
]


def exp(x, out=None) -> DNDarray:
    return local_op(jnp.exp, x, out)


def expm1(x, out=None) -> DNDarray:
    return local_op(jnp.expm1, x, out)


def exp2(x, out=None) -> DNDarray:
    return local_op(jnp.exp2, x, out)


def log(x, out=None) -> DNDarray:
    return local_op(jnp.log, x, out)


def log2(x, out=None) -> DNDarray:
    return local_op(jnp.log2, x, out)


def log10(x, out=None) -> DNDarray:
    return local_op(jnp.log10, x, out)


def log1p(x, out=None) -> DNDarray:
    return local_op(jnp.log1p, x, out)


def logaddexp(t1, t2, out=None) -> DNDarray:
    """log(exp(t1)+exp(t2)) (reference exponential.py `logaddexp`)."""
    return binary_op(jnp.logaddexp, t1, t2, out)


def logaddexp2(t1, t2, out=None) -> DNDarray:
    return binary_op(jnp.logaddexp2, t1, t2, out)


def sqrt(x, out=None) -> DNDarray:
    return local_op(jnp.sqrt, x, out)


def square(x, out=None) -> DNDarray:
    return local_op(jnp.square, x, out)


DNDarray.exp = lambda self, out=None: exp(self, out)
DNDarray.exp2 = lambda self, out=None: exp2(self, out)
DNDarray.expm1 = lambda self, out=None: expm1(self, out)
DNDarray.log = lambda self, out=None: log(self, out)
DNDarray.log2 = lambda self, out=None: log2(self, out)
DNDarray.log10 = lambda self, out=None: log10(self, out)
DNDarray.log1p = lambda self, out=None: log1p(self, out)
DNDarray.sqrt = lambda self, out=None: sqrt(self, out)
DNDarray.square = lambda self, out=None: square(self, out)
