"""Linear algebra basics.

Re-design of reference heat/core/linalg/basics.py (2046 LoC). The reference's
centerpiece is a hand-written block-cyclic SUMMA matmul with per-iteration
Bcasts (basics.py:304-778, after Gu et al. 2017); on TPU that whole algorithm
*is* XLA: `jnp.matmul` on sharded operands emits the same all-gather/
reduce-scatter schedule onto the MXU, so `matmul` here is mask-pads +
`jnp.matmul` + result-split bookkeeping. Ring-based `outer`
(reference :1056) likewise collapses to one outer product with sharding
propagation.
"""

from __future__ import annotations

import builtins
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import types
from .._operations import binary_op, local_op, reduce_op
from ..dndarray import DNDarray
from ..stride_tricks import sanitize_axis

__all__ = [
    "dot",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "trace",
    "transpose",
    "tril",
    "triu",
    "vecdot",
    "vector_norm",
]


def dot(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None) -> Union[DNDarray, float]:
    """Dot product with numpy dispatch rules (reference basics.py:42:
    1-D × 1-D is a local dot + Allreduce :85-87)."""
    if isinstance(a, DNDarray) and isinstance(b, DNDarray) and a.ndim == 1 and b.ndim == 1:
        if a.shape != b.shape:
            raise ValueError("shapes are not aligned")
        # physical-shape mismatch means exactly one side is replicated
        # (equal 1-D gshapes with equal splits pad identically); resplit the
        # replicated side — it moves no distributed bytes — so the product
        # runs on padded buffers and XLA inserts the psum
        if a.larray.shape != b.larray.shape:
            if a.split is None:
                a = a.resplit(b.split)
            else:
                b = b.resplit(a.split)
        am = a._masked(0) if a.pad_count else a.larray
        bm = b._masked(0) if b.pad_count else b.larray
        res = jnp.dot(am, bm)
        ret = DNDarray(res, (), types.canonical_heat_type(res.dtype), None, a.device, a.comm, True)
        if out is not None:
            out.larray = res.astype(out.dtype.jnp_type())
            return out
        return ret
    if a.ndim <= 2 and b.ndim <= 2:
        ret = matmul(a, b)
        if out is not None:
            out.larray = ret.larray
            return out
        return ret
    raise NotImplementedError("ht.dot not implemented for N-D × M-D arrays")


def matmul(a: DNDarray, b: DNDarray, allow_resplit: bool = False) -> DNDarray:
    """Matrix product of two (1-D, 2-D, or batched N-D) DNDarrays (reference
    basics.py:108-778). Split rules for 2-D operands:

    =============  =============  ============
    a.split        b.split        result split
    =============  =============  ============
    None           None           None
    0              any            0
    None/1         1              1
    1              0/None         0 (contraction crosses the mesh; XLA
                                   reduce-scatters back to rows)
    =============  =============  ============

    Pads along contraction dims are zero-masked, so they contribute nothing;
    pads along carried dims stay pad. N-D batched matmul is an extension over
    the reference (which supports up to 2-D).

    With Fusion 2.0 on (``HEAT_TPU_FUSION_REDUCE``, default) the matmul is
    a lazy *kernel node* (core/fusion.py `defer_matmul`): pending operand
    chains graft in as its pre-map, trailing elementwise ops (bias add,
    activation) graft on as its epilogue, and the whole thing flushes as
    ONE cached program. Shapes the kernel path cannot express (vector
    promotions needing repair slices) run the eager dispatch below,
    unchanged."""
    from .. import factories

    if not isinstance(a, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("both operands must be DNDarrays")
    if a.ndim == 1 and b.ndim == 1:
        return dot(a, b)

    out_dtype = types.promote_types(a.dtype, b.dtype)

    # vector promotions (numpy semantics)
    a_vec = a.ndim == 1
    b_vec = b.ndim == 1

    # Determine the logical output shape
    a_shape = (1,) + a.shape if a_vec else a.shape
    b_shape = b.shape + (1,) if b_vec else b.shape
    if a_shape[-1] != b_shape[-2]:
        raise ValueError(
            f"If the last dimension of a ({a.shape[-1]}) is not the same size "
            f"as the second-to-last dimension of b ({b.shape[-2 if b.ndim > 1 else -1]})."
        )

    comm = a.comm

    # logical output shape
    batch = tuple(np.broadcast_shapes(a_shape[:-2], b_shape[:-2])) if (len(a_shape) > 2 or len(b_shape) > 2) else ()
    out_gshape = batch + (a_shape[-2], b_shape[-1])
    if a_vec:
        out_gshape = out_gshape[:-2] + (out_gshape[-1],)
    if b_vec:
        out_gshape = out_gshape[:-1]

    # result split bookkeeping (2-D core rules; batch dims keep their split)
    ndim_out = len(out_gshape)
    out_split: Optional[int] = None
    if a.split is not None:
        if not a_vec and a.split == a.ndim - 2:
            out_split = ndim_out - (2 if not b_vec else 1)
        elif a.split < a.ndim - 2:
            out_split = a.split  # batch dim
        elif a.split == a.ndim - 1 and not b_vec:
            out_split = ndim_out - 2 if not a_vec else None
    if out_split is None and b.split is not None:
        if not b_vec and b.split == b.ndim - 1:
            out_split = ndim_out - 1
        elif b.ndim > 2 and b.split < b.ndim - 2:
            out_split = b.split
        elif not b_vec and b.split == b.ndim - 2 and not a_vec:
            out_split = ndim_out - 2
    if out_split is not None and out_split >= ndim_out:
        out_split = None

    from .. import fusion

    if fusion.active():
        deferred = fusion.defer_matmul(
            a, b, out_dtype.jnp_type(), out_gshape, out_split,
            a.device, comm,
        )
        if deferred is not None:
            return deferred

    am = a._masked(0) if a.pad_count else a.larray
    bm = b._masked(0) if b.pad_count else b.larray
    am = am.astype(out_dtype.jnp_type())
    bm = bm.astype(out_dtype.jnp_type())

    # physical operands: when a contraction-side pad exists on one operand,
    # the other operand's matching dim must be padded too
    if a.ndim >= 2 and a.split == a.ndim - 1 and a.pad_count:
        pad = [(0, 0)] * b.ndim
        pad[-2 if b.ndim > 1 else 0] = (0, am.shape[-1] - bm.shape[-2 if b.ndim > 1 else 0])
        bm = jnp.pad(bm, pad)
    elif b.ndim >= 2 and b.split == b.ndim - 2 and b.pad_count:
        pad = [(0, 0)] * a.ndim
        pad[-1] = (0, bm.shape[-2] - am.shape[-1])
        am = jnp.pad(am, pad)
    elif b.ndim == 1 and b.split == 0 and b.pad_count:
        pad = [(0, 0)] * a.ndim
        pad[-1] = (0, bm.shape[0] - am.shape[-1])
        am = jnp.pad(am, pad)
    elif a.ndim == 1 and a.split == 0 and a.pad_count and b.ndim > 1:
        pad = [(0, 0)] * b.ndim
        pad[-2] = (0, am.shape[0] - bm.shape[-2])
        bm = jnp.pad(bm, pad)

    result = jnp.matmul(am, bm)

    # restore the invariant: physical == padded_shape(out_gshape, out_split)
    expected = comm.padded_shape(out_gshape, out_split)
    if tuple(result.shape) != expected:
        sl = []
        for d in range(result.ndim):
            want = expected[d] if d < len(expected) else None
            sl.append(slice(0, want))
        if result.ndim == len(expected):
            result = result[tuple(sl)]
            if tuple(result.shape) != expected:
                return DNDarray.from_logical(
                    result[tuple(slice(0, n) for n in out_gshape)], out_split, a.device, comm, out_dtype
                )
        else:
            return DNDarray.from_logical(jnp.reshape(result, out_gshape), out_split, a.device, comm, out_dtype)

    return DNDarray(result, out_gshape, out_dtype, out_split, a.device, comm, True)


def matrix_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Matrix norm over an axis pair (reference basics.py `matrix_norm`)."""
    from .. import arithmetics, exponential, rounding, statistics

    if axis is None:
        if x.ndim == 2:
            row_axis, col_axis = 0, 1
        else:
            raise ValueError("input is not a matrix, specify axis")
    else:
        row_axis, col_axis = (sanitize_axis(x.shape, a) for a in axis)
    if row_axis == col_axis:
        raise ValueError("axis entries must be different")

    def _two_stage(sum_axis, ext_axis, extremum):
        # the first reduction drops sum_axis (unless keepdims), shifting the
        # second reduction's axis index
        second = ext_axis if keepdims or ext_axis < sum_axis else ext_axis - 1
        return extremum(
            arithmetics.sum(rounding.abs(x), axis=sum_axis, keepdims=keepdims),
            axis=second,
            keepdims=keepdims,
        )

    if ord == 1:
        return _two_stage(row_axis, col_axis, statistics.max)
    if ord == -1:
        return _two_stage(row_axis, col_axis, statistics.min)
    if ord == float("inf"):
        return _two_stage(col_axis, row_axis, statistics.max)
    if ord == -float("inf"):
        return _two_stage(col_axis, row_axis, statistics.min)
    if ord in (None, "fro"):
        return exponential.sqrt(
            arithmetics.sum(arithmetics.mul(x, x), axis=(row_axis, col_axis), keepdims=keepdims)
        )
    raise ValueError(f"Invalid norm order {ord!r} for matrices")


def norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector/matrix norm dispatch (reference basics.py `norm`)."""
    if axis is None and ord is None:
        from .. import arithmetics, exponential

        flat_sq = arithmetics.sum(arithmetics.mul(x, x))
        return exponential.sqrt(flat_sq)
    if axis is None and x.ndim <= 1:
        return vector_norm(x, axis=None, keepdims=keepdims, ord=ord)
    if axis is None and x.ndim == 2:
        return matrix_norm(x, axis=None, keepdims=keepdims, ord=ord)
    if isinstance(axis, (tuple, list)) and len(axis) == 2:
        return matrix_norm(x, axis=axis, keepdims=keepdims, ord=ord)
    return vector_norm(x, axis=axis, keepdims=keepdims, ord=ord)


def outer(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, split: Optional[int] = None) -> DNDarray:
    """Outer product of two vectors (reference basics.py:1056 ring-exchanges
    chunks). With a split=0 result the row operand stays on its padded
    physical buffer (pad rows become pad rows) and only the column operand
    replicates — which the reference's ring also streams through every
    rank; the big operand never gathers."""
    if not isinstance(a, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("both operands must be DNDarrays")
    if a.ndim != 1 or b.ndim != 1:
        raise TypeError("outer expects 1-D operands")
    if split is None:
        # default the result split to the operand that is already
        # distributed, so no distributed bytes move
        split = 0 if a.split is not None else (1 if b.split is not None else None)
    if split in (0, 1) and a.comm.size > 1:
        if split == 0:
            if a.split is None:
                a = a.resplit(0)  # replicated → split moves no bytes
            res = a.larray[:, None] * _replicate_vec(b)[None, :]
        else:
            if b.split is None:
                b = b.resplit(0)
            res = _replicate_vec(a)[:, None] * b.larray[None, :]
        return _wrap_out(
            DNDarray(
                res, (a.shape[0], b.shape[0]),
                types.canonical_heat_type(res.dtype), split, a.device, a.comm, True,
            ),
            out,
        )
    a_flat = a._logical().ravel()
    b_flat = b._logical().ravel()
    res = jnp.outer(a_flat, b_flat)
    return _wrap_out(DNDarray.from_logical(res, split, a.device, a.comm), out)


def _replicate_vec(v: DNDarray):
    """Logical 1-D values replicated on every device — a device-side
    all_gather of the padded buffer + local pad slice; never the host
    logical view (multi-host safe)."""
    if v.split is None:
        return v.larray
    import jax

    buf = jax.device_put(v.larray, v.comm.sharding(None, 1))
    return buf[: v.shape[0]]


def _wrap_out(ret: DNDarray, out: Optional[DNDarray]) -> DNDarray:
    if out is not None:
        out.larray = ret.larray
        return out
    return ret


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of a onto b (reference basics.py `projection`)."""
    from .. import arithmetics

    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"a, b must be vectors, got {a.ndim}, {b.ndim} dimensions")
    scale = arithmetics.div(dot(a, b), dot(b, b))
    return arithmetics.mul(scale, b)


def trace(a: DNDarray, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out=None):
    """Sum along diagonals (reference basics.py:1313). 2-D split matrices
    sum their shard's diagonal slice locally — a per-row (or per-column)
    take on the physical buffer with out-of-band positions masked to 0 —
    and XLA reduces across shards; no gather."""
    if a.ndim >= 2:
        axis1 = sanitize_axis(a.shape, axis1)
        axis2 = sanitize_axis(a.shape, axis2)
    if (
        a.ndim == 2
        and a.split is not None
        and a.comm.size > 1
        and (axis1, axis2) in ((0, 1), (1, 0))
    ):
        off = -offset if (axis1, axis2) == (1, 0) else offset
        buf = a.larray
        n, m = a.shape
        if a.split == 0:
            # row r holds diag element (r, r+off)
            pos = jnp.arange(buf.shape[0])
            cols = pos + off
            valid = (pos < n) & (cols >= 0) & (cols < m)
            picked = jnp.take_along_axis(
                buf, jnp.clip(cols, 0, m - 1)[:, None], axis=1
            )[:, 0]
        else:
            # column c holds diag element (c-off, c)
            pos = jnp.arange(buf.shape[1])
            rows = pos - off
            valid = (pos < m) & (rows >= 0) & (rows < n)
            picked = jnp.take_along_axis(
                buf, jnp.clip(rows, 0, n - 1)[None, :], axis=0
            )[0, :]
        res = jnp.where(valid, picked, jnp.zeros((), dtype=buf.dtype)).sum()
        if dtype is not None:
            res = res.astype(types.canonical_heat_type(dtype).jnp_type())
        ret = DNDarray(res, (), types.canonical_heat_type(res.dtype), None, a.device, a.comm, True)
        return _wrap_out(ret, out)
    log = a._logical()
    res = jnp.trace(log, offset=offset, axis1=axis1, axis2=axis2)
    if dtype is not None:
        res = res.astype(types.canonical_heat_type(dtype).jnp_type())
    if res.ndim == 0:
        ret = DNDarray(res, (), types.canonical_heat_type(res.dtype), None, a.device, a.comm, True)
    else:
        ret = DNDarray.from_logical(res, None, a.device, a.comm)
    return _wrap_out(ret, out)


def transpose(a: DNDarray, axes: Optional[Sequence[int]] = None) -> DNDarray:
    """Permute dimensions (reference basics.py:1735: local permute +
    split-axis remap; identical here, on the padded buffer — the pad travels
    with the split dim, so no relayout)."""
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    else:
        axes = tuple(sanitize_axis(a.shape, ax) for ax in axes)
        if len(axes) != a.ndim or len(set(axes)) != a.ndim:
            raise ValueError(f"axes do not match tensor of dimension {a.ndim}")
    res = jnp.transpose(a.larray, axes)
    out_split = axes.index(a.split) if a.split is not None else None
    out_gshape = tuple(a.shape[ax] for ax in axes)
    return DNDarray(res, out_gshape, a.dtype, out_split, a.device, a.comm, True)


def _tri_op(m: DNDarray, k: int, op) -> DNDarray:
    """Lower/upper triangle helper (reference basics.py:1805). Index-mask is
    positional, so it applies directly to the padded buffer for 2-D arrays
    (pad rows/cols stay pad)."""
    if m.ndim < 1:
        raise TypeError("input needs to be a tensor with at least 1 dimension")
    if m.ndim == 1:
        log = m._logical()
        n = log.shape[0]
        mat = jnp.tile(log, (n, 1))
        res = op(mat, k)
        return DNDarray.from_logical(res, 0 if m.split is not None else None, m.device, m.comm, m.dtype)
    res = op(m.larray, k)
    return DNDarray(res, m.shape, m.dtype, m.split, m.device, m.comm, True)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower triangle (reference basics.py `tril`)."""
    return _tri_op(m, k, jnp.tril)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper triangle (reference basics.py `triu`)."""
    return _tri_op(m, k, jnp.triu)


def vecdot(x1: DNDarray, x2: DNDarray, axis: Optional[int] = None, keepdims: bool = False) -> DNDarray:
    """Vector dot product along an axis (reference basics.py `vecdot`)."""
    from .. import arithmetics

    m = arithmetics.mul(x1, x2)
    if axis is None:
        axis = m.ndim - 1
    return arithmetics.sum(m, axis=axis, keepdims=keepdims)


def vector_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector norm (reference basics.py `vector_norm`)."""
    from .. import arithmetics, exponential, rounding, statistics

    if axis is not None and not isinstance(axis, (builtins.int, np.integer)):
        raise TypeError("axis must be an integer or None for vectors")
    absx = rounding.abs(x)
    if ord is None or ord == 2:
        return exponential.sqrt(arithmetics.sum(arithmetics.mul(x, x), axis=axis, keepdims=keepdims))
    if ord == float("inf"):
        return statistics.max(absx, axis=axis, keepdims=keepdims)
    if ord == -float("inf"):
        return statistics.min(absx, axis=axis, keepdims=keepdims)
    if ord == 0:
        from .. import relational

        nz = relational.ne(x, 0)
        return arithmetics.sum(nz.astype(types.float32), axis=axis, keepdims=keepdims)
    if isinstance(ord, (builtins.int, builtins.float)):
        p = arithmetics.pow(absx, float(ord))
        s = arithmetics.sum(p, axis=axis, keepdims=keepdims)
        return arithmetics.pow(s, 1.0 / float(ord))
    raise ValueError(f"Invalid norm order {ord!r} for vectors")


DNDarray.__matmul__ = lambda self, other: matmul(self, other)
DNDarray.transpose = lambda self, axes=None: transpose(self, axes)
DNDarray.dot = lambda self, other, out=None: dot(self, other, out)
DNDarray.tril = lambda self, k=0: tril(self, k)
DNDarray.triu = lambda self, k=0: triu(self, k)
