"""Iterative solvers written in framework ops (reference:
heat/core/linalg/solver.py:10-184). Because they are expressed in DNDarray
arithmetic, distribution is inherited — identical design here."""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray

__all__ = ["cg", "lanczos"]


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for s.p.d. ``A x = b`` (reference solver.py:13 —
    textbook CG in ht ops; matmul/elementwise carry the distribution)."""
    from .. import arithmetics
    from .basics import matmul, dot

    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError("A, b and x0 need to be of type ht.DNDarray")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    r = arithmetics.sub(b, matmul(A, x0))
    p = r
    rsold = dot(r, r)
    x = x0

    for _ in range(len(b)):
        Ap = matmul(A, p)
        alpha = rsold.item() / dot(p, Ap).item()
        x = arithmetics.add(x, arithmetics.mul(alpha, p))
        r = arithmetics.sub(r, arithmetics.mul(alpha, Ap))
        rsnew = dot(r, r)
        if float(rsnew.item()) ** 0.5 < 1e-10:
            if out is not None:
                out.larray = x.larray
                return out
            return x
        beta = rsnew.item() / rsold.item()
        p = arithmetics.add(r, arithmetics.mul(beta, p))
        rsold = rsnew

    if out is not None:
        out.larray = x.larray
        return out
    return x


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization with full reorthogonalization (reference
    solver.py:68: Krylov iteration with Gram-Schmidt against all previous
    Lanczos vectors, used by spectral clustering). Returns (V, T) with
    ``V (n×m)`` orthonormal Krylov basis and ``T (m×m)`` tridiagonal."""
    from .basics import matmul

    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be of type ht.DNDarray, but was {type(A)}")
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")
    if not isinstance(m, int) or m <= 0:
        raise TypeError(f"m must be a positive integer, got {m}")

    n = A.shape[0]
    a_log = A._logical().astype(jnp.float64)

    if v0 is None:
        import numpy as _np

        rng = _np.random.default_rng(0)
        v = jnp.asarray(rng.standard_normal(n))
        v = v / jnp.linalg.norm(v)
    else:
        v = v0._logical().astype(jnp.float64)
        v = v / jnp.linalg.norm(v)

    V = [v]
    alphas = []
    betas = [0.0]
    w = a_log @ v
    alpha = jnp.dot(w, v)
    w = w - alpha * v
    alphas.append(alpha)
    for i in range(1, m):
        beta = jnp.linalg.norm(w)
        if float(beta) < 1e-13:
            # breakdown: restart with a random orthogonal vector
            import numpy as _np

            rng = _np.random.default_rng(i)
            vr = jnp.asarray(rng.standard_normal(n))
            for u in V:
                vr = vr - jnp.dot(vr, u) * u
            v_next = vr / jnp.linalg.norm(vr)
            beta = jnp.asarray(0.0)
        else:
            v_next = w / beta
            # full re-orthogonalization (reference reorthogonalizes against V)
            for u in V:
                v_next = v_next - jnp.dot(v_next, u) * u
            v_next = v_next / jnp.linalg.norm(v_next)
        V.append(v_next)
        betas.append(float(beta))
        w = a_log @ v_next
        alpha = jnp.dot(w, v_next)
        w = w - alpha * v_next - jnp.asarray(betas[i]) * V[i - 1]
        alphas.append(alpha)

    V_mat = jnp.stack(V, axis=1)  # (n, m)
    T_mat = (
        jnp.diag(jnp.asarray(alphas))
        + jnp.diag(jnp.asarray(betas[1:]), k=1)
        + jnp.diag(jnp.asarray(betas[1:]), k=-1)
    )
    dt = types.promote_types(A.dtype, types.float32)
    V_ht = DNDarray.from_logical(V_mat.astype(dt.jnp_type()), A.split, A.device, A.comm, dt)
    T_ht = DNDarray.from_logical(T_mat.astype(dt.jnp_type()), None, A.device, A.comm, dt)
    if V_out is not None:
        V_out.larray = V_ht.larray
        T_out.larray = T_ht.larray
        return V_out, T_out
    return V_ht, T_ht
