"""Iterative solvers written in framework ops (reference:
heat/core/linalg/solver.py:10-184). Because they are expressed in DNDarray
arithmetic, distribution is inherited — identical design here.

Operator protocol (ISSUE 13): the kernels take their matrix as a
``matvec`` **operator** — a tuple of program-argument leaves plus a pure
traceable ``mv(leaves, x, n)`` — instead of hard-coding ``a @ x``. A
dense :class:`DNDarray` resolves to the padded sharded buffer with the
historical masked matvec (bit-identical programs to the pre-protocol
kernels); any object exposing ``_matvec_spec(dt)`` — e.g.
:class:`heat_tpu.sparse.SparseDNDarray`, whose matvec is the shard-local
CSR contraction + audited all-reduce tail — drops in without the solver
knowing its layout. The operator kind joins the program-cache key, so a
dense and a sparse Lanczos never share an executable and each stays
zero-recompile on repeat.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray

__all__ = ["cg", "lanczos"]


def _dense_matvec(leaves, x, n: int):
    """The historical dense matvec: ``leaves[0]`` may be the PADDED
    split-0 physical buffer (n_pad, n) with zeroed pad rows — the matvec
    stays sharded (XLA partitions it) and only the logical slice relays
    per step."""
    return (leaves[0] @ x)[:n]


def _operator(A, dt):
    """Resolve ``A`` into ``(leaves, mv, kind_key, comm_or_None)``.

    ``leaves`` are the program arguments (a pytree — the kernels take
    them as one tuple), ``mv(leaves, x, n)`` the pure traceable matvec,
    ``kind_key`` the static signature fragment for the program cache,
    and the comm is non-None when the leaves are sharded (the kernel
    variants then pin replicated ``out_shardings`` — an XLA-chosen
    output sharding can hit jax's device-order reshard assertion in the
    downstream device_put under multi-host)."""
    if isinstance(A, DNDarray):
        if A.split == 0 and A.comm.size > 1:
            # keep A sharded: the matvec partitions over the mesh (pad
            # rows are zeroed and sliced off inside the kernel)
            return (
                (A._masked(0).astype(dt.jnp_type()),),
                _dense_matvec, ("dense",), A.comm,
            )
        return (
            (A._replicated().astype(dt.jnp_type()),),
            _dense_matvec, ("dense",), None,
        )
    spec = getattr(A, "_matvec_spec", None)
    if spec is None:
        raise TypeError(
            f"A must be a DNDarray or expose _matvec_spec (e.g. "
            f"heat_tpu.sparse.SparseDNDarray), got {type(A)}"
        )
    leaves, mv, kind_key = spec(dt)
    return leaves, mv, kind_key, (A.comm if A.comm.size > 1 else None)


def _is_operator(A) -> bool:
    return isinstance(A, DNDarray) or hasattr(A, "_matvec_spec")


def _cg_kernel(mv, a, b: "jnp.ndarray", x0: "jnp.ndarray", n: int):
    """Whole CG iteration as ONE compiled program: `lax.while_loop` with the
    convergence test on-device (reference solver.py:13 drives the loop from
    the host with four `.item()` syncs per iteration; here zero scalars cross
    to the host until the solve finishes). ``a`` is the operator leaf tuple
    (dense: the possibly-padded sharded buffer; sparse: the CSR shards) and
    ``mv`` the statically-bound matvec — only (n,) vectors carry between
    steps."""
    import jax.lax as lax

    def matvec(x):
        return mv(a, x, n)

    tol2 = jnp.asarray(1e-20, dtype=b.dtype)  # (1e-10)^2, tested on r.r

    r0 = b - matvec(x0)
    rs0 = jnp.dot(r0, r0)

    def cond(carry):
        _x, _r, _p, rsold, it = carry
        return (it < n) & (rsold >= tol2)

    def body(carry):
        x, r, p, rsold, it = carry
        Ap = matvec(p)
        alpha = rsold / jnp.dot(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = jnp.dot(r, r)
        p = r + (rsnew / rsold) * p
        return x, r, p, rsnew, it + 1

    x, _r, _p, _rs, _it = lax.while_loop(
        cond, body, (x0, r0, r0, rs0, jnp.asarray(0, dtype=jnp.int32))
    )
    return x


def _cg_init_kernel(mv, a, b: "jnp.ndarray", x0: "jnp.ndarray", n: int):
    """Initial CG carry ``(x, r, p, rsold, it)`` — the pre-loop segment of
    :func:`_cg_kernel`, split out so the checkpointed driver can resume the
    iteration mid-solve (resilience hooks, ISSUE 5)."""
    r0 = b - mv(a, x0, n)
    rs0 = jnp.dot(r0, r0)
    return x0, r0, r0, rs0, jnp.asarray(0, dtype=jnp.int32)


def _cg_chunk_kernel(
    mv,
    a,
    x: "jnp.ndarray",
    r: "jnp.ndarray",
    p: "jnp.ndarray",
    rsold: "jnp.ndarray",
    it: "jnp.ndarray",
    n: int,
    k: int,
):
    """Up to ``k`` more CG iterations from an arbitrary carry — the loop
    body is byte-identical to :func:`_cg_kernel`'s, so a chunked run (and
    hence a checkpoint/resume cycle) applies the exact same per-iteration
    math as one uninterrupted solve."""
    import jax.lax as lax

    def matvec(v):
        return mv(a, v, n)

    tol2 = jnp.asarray(1e-20, dtype=x.dtype)
    lim = jnp.minimum(it + k, n)

    def cond(carry):
        _x, _r, _p, rsold, it = carry
        return (it < lim) & (rsold >= tol2)

    def body(carry):
        x, r, p, rsold, it = carry
        Ap = matvec(p)
        alpha = rsold / jnp.dot(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = jnp.dot(r, r)
        p = r + (rsnew / rsold) * p
        return x, r, p, rsnew, it + 1

    return lax.while_loop(cond, body, (x, r, p, rsold, it))


def cg(
    A,
    b: DNDarray,
    x0: DNDarray,
    out: Optional[DNDarray] = None,
    *,
    checkpoint_every: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
) -> DNDarray:
    """Conjugate gradients for s.p.d. ``A x = b`` (reference solver.py:13).

    The entire solve — matvecs, vector updates, and the residual-norm
    convergence check — runs as one jitted `lax.while_loop` dispatch, the
    same treatment `lanczos` gets below; A stays sharded (split=0 matvecs
    partition over the mesh) and no scalar reaches the host mid-solve.
    ``A`` may be a dense :class:`DNDarray` or any operator exposing
    ``_matvec_spec`` (a :class:`heat_tpu.sparse.SparseDNDarray` runs its
    matvecs as the shard-local CSR contraction — ISSUE 13).

    ``checkpoint_every=k`` (resilience hook, ISSUE 5) instead drives the
    solve as exact ``k``-iteration windows, checkpointing the CG carry
    ``(x, r, p, rsold, it)`` to ``checkpoint_path`` after each window via
    :func:`heat_tpu.resilience.save_checkpoint`; ``resume=True`` continues
    a killed solve from the last completed window with bit-identical
    results to an uninterrupted run (the window kernel's body is the same
    per-iteration math)."""
    if (
        not _is_operator(A)
        or not isinstance(b, DNDarray)
        or not isinstance(x0, DNDarray)
    ):
        raise TypeError("cg expects DNDarray (or sparse operator) A, and "
                        "DNDarray b and x0")
    if A.ndim != 2:
        raise RuntimeError(f"cg expects a 2-D matrix A, got {A.ndim}-D")
    if b.ndim != 1:
        raise RuntimeError(f"cg expects a 1-D right-hand side b, got {b.ndim}-D")
    if x0.ndim != 1:
        raise RuntimeError(f"cg expects a 1-D initial guess x0, got {x0.ndim}-D")

    n = A.shape[0]
    dt = types.promote_types(
        types.promote_types(A.dtype, b.dtype), types.promote_types(x0.dtype, types.float32)
    )
    leaves, mv, kind_key, op_comm = _operator(A, dt)
    kernel_jit = _cg_jit(mv, kind_key, op_comm)
    b_log = b._replicated().astype(dt.jnp_type())
    x0_log = x0._replicated().astype(dt.jnp_type())

    if checkpoint_every is not None:
        if checkpoint_every <= 0:
            raise ValueError(
                f"checkpoint_every must be positive, got {checkpoint_every}"
            )
        if not checkpoint_path:
            raise ValueError("checkpoint_every requires checkpoint_path")
        x_log = _cg_checkpointed(
            mv, kind_key, op_comm, leaves, b_log, x0_log, n,
            int(checkpoint_every), checkpoint_path, resume,
        )
    elif resume:
        raise ValueError("resume=True requires checkpoint_every")
    else:
        x_log = kernel_jit(leaves, b_log, x0_log, n)
    if not bool(jnp.all(jnp.isfinite(x_log))):
        # breakdown (p^T A p = 0 ⇒ alpha = inf inside the kernel) exits the
        # while_loop via the NaN residual; surface it loudly — the solve is
        # only defined for s.p.d. A. One host sync, after the loop finishes.
        raise RuntimeError(
            "cg broke down (non-finite iterate) — A must be symmetric "
            "positive definite"
        )

    x = DNDarray.from_logical(x_log, x0.split, x0.device, x0.comm, dt)
    if out is not None:
        out.larray = x.larray
        return out
    return x


def _lanczos_kernel(mv, a, v0: "jnp.ndarray", m: int, n: int):
    """The whole Lanczos iteration as ONE compiled program (jit over
    static ``m``/``n``): `lax.fori_loop` over Krylov steps with masked full
    reorthogonalization against a fixed (m, n) basis buffer, breakdown
    restarts selected by `jnp.where` instead of host branches. One dispatch,
    no per-iteration eager collectives — a Python loop of eager sharded
    matvecs can interleave two in-flight collective programs on the
    in-process CPU backend and deadlock (observed; and on TPU it would pay
    a dispatch round-trip per step).

    ``a`` is the operator leaf tuple (dense: possibly the PADDED split-0
    physical buffer with zeroed pad rows; sparse: the sharded CSR
    buffers, whose matvec is a shard_map contraction + all-reduce tail
    embedded in this very trace). Krylov vectors are length ``n``."""
    import jax

    def norm(x):
        return jnp.sqrt(jnp.sum(x * x))

    def matvec(x):
        return mv(a, x, n)

    v = v0 / norm(v0)
    Vb = jnp.zeros((m, n), dtype=v0.dtype).at[0].set(v)
    alphas = jnp.zeros((m,), dtype=v0.dtype)
    betas = jnp.zeros((m,), dtype=v0.dtype)
    w = matvec(v)
    alpha = jnp.dot(w, v)
    w = w - alpha * v
    alphas = alphas.at[0].set(alpha)
    key = jax.random.PRNGKey(0)

    # breakdown threshold scaled to the compute dtype's resolution
    eps = 1e-13 if v0.dtype == jnp.float64 else 1e-6

    def body(i, carry):
        Vb, alphas, betas, w = carry
        beta = norm(w)
        ok = beta > eps
        # breakdown: restart with a pseudo-random vector (deterministic in i)
        restart = jax.random.normal(jax.random.fold_in(key, i), (n,), dtype=v0.dtype)
        v_next = jnp.where(ok, w / jnp.where(ok, beta, 1.0), restart)
        # masked full re-orthogonalization against columns < i
        proj = (Vb @ v_next) * (jnp.arange(m) < i)
        v_next = v_next - Vb.T @ proj
        v_next = v_next / norm(v_next)
        beta_rec = jnp.where(ok, beta, 0.0)
        Vb = Vb.at[i].set(v_next)
        betas = betas.at[i].set(beta_rec)
        w = matvec(v_next)
        alpha = jnp.dot(w, v_next)
        w = w - alpha * v_next - beta_rec * Vb[i - 1]
        alphas = alphas.at[i].set(alpha)
        return Vb, alphas, betas, w

    import jax.lax as lax

    Vb, alphas, betas, _ = lax.fori_loop(1, m, body, (Vb, alphas, betas, w))
    return Vb.T, alphas, betas


def _lanczos_init_kernel(mv, a, v0: "jnp.ndarray", m: int, n: int):
    """Initial Lanczos carry ``(Vb, alphas, betas, w)`` — the pre-loop
    segment of :func:`_lanczos_kernel`, split out for the checkpointed
    driver (resilience hooks, ISSUE 5)."""

    def norm(x):
        return jnp.sqrt(jnp.sum(x * x))

    def matvec(x):
        return mv(a, x, n)

    v = v0 / norm(v0)
    Vb = jnp.zeros((m, n), dtype=v0.dtype).at[0].set(v)
    alphas = jnp.zeros((m,), dtype=v0.dtype)
    betas = jnp.zeros((m,), dtype=v0.dtype)
    w = matvec(v)
    alpha = jnp.dot(w, v)
    w = w - alpha * v
    alphas = alphas.at[0].set(alpha)
    return Vb, alphas, betas, w


def _lanczos_chunk_kernel(
    mv,
    a,
    Vb: "jnp.ndarray",
    alphas: "jnp.ndarray",
    betas: "jnp.ndarray",
    w: "jnp.ndarray",
    i0: "jnp.ndarray",
    m: int,
    n: int,
    k: int,
):
    """Krylov steps ``[i0, min(i0+k, m))`` from an arbitrary carry. The
    body is byte-identical to :func:`_lanczos_kernel`'s — deterministic in
    the step index ``i`` (the breakdown restart folds ``i`` into a fixed
    key), so chunked execution reproduces the uninterrupted iteration
    exactly."""
    import jax
    import jax.lax as lax

    def norm(x):
        return jnp.sqrt(jnp.sum(x * x))

    def matvec(x):
        return mv(a, x, n)

    key = jax.random.PRNGKey(0)
    eps = 1e-13 if Vb.dtype == jnp.float64 else 1e-6

    def body(i, carry):
        Vb, alphas, betas, w = carry
        beta = norm(w)
        ok = beta > eps
        restart = jax.random.normal(jax.random.fold_in(key, i), (n,), dtype=Vb.dtype)
        v_next = jnp.where(ok, w / jnp.where(ok, beta, 1.0), restart)
        proj = (Vb @ v_next) * (jnp.arange(m) < i)
        v_next = v_next - Vb.T @ proj
        v_next = v_next / norm(v_next)
        beta_rec = jnp.where(ok, beta, 0.0)
        Vb = Vb.at[i].set(v_next)
        betas = betas.at[i].set(beta_rec)
        w = matvec(v_next)
        alpha = jnp.dot(w, v_next)
        w = w - alpha * v_next - beta_rec * Vb[i - 1]
        alphas = alphas.at[i].set(alpha)
        return Vb, alphas, betas, w

    lim = jnp.minimum(i0 + k, m)
    return lax.fori_loop(i0, lim, body, (Vb, alphas, betas, w))


from .. import program_cache


def _cg_jit(mv, kind_key, comm):
    """cg program memoized per (operator kind, comm, layout family) in
    the process-global registry. The comm variant pins replicated
    out_shardings for sharded operator leaves (same multi-host
    reshard-assertion guard as `_lanczos_jit_for`)."""
    if comm is None:
        return program_cache.cached_program(
            "cg", ("plain", kind_key), lambda: partial(_cg_kernel, mv),
            static_argnums=(3,),
        )
    return program_cache.cached_program(
        "cg", ("replicated", kind_key), lambda: partial(_cg_kernel, mv),
        comm=comm, out_shardings=comm.replicated(), static_argnums=(3,),
    )


def _cg_chunk_jits(mv, kind_key, comm):
    """(init, chunk) cached programs for the checkpointed CG driver —
    ``comm=None`` for replicated operator leaves, else replicated
    out_shardings over the sharded-matvec mesh."""
    if comm is None:
        init = program_cache.cached_program(
            "cg_init", ("plain", kind_key),
            lambda: partial(_cg_init_kernel, mv), static_argnums=(3,),
        )
        chunk = program_cache.cached_program(
            "cg_chunk", ("plain", kind_key),
            lambda: partial(_cg_chunk_kernel, mv), static_argnums=(6, 7),
        )
    else:
        rep = comm.replicated()
        init = program_cache.cached_program(
            "cg_init", ("replicated", kind_key),
            lambda: partial(_cg_init_kernel, mv), comm=comm,
            out_shardings=(rep,) * 5, static_argnums=(3,),
        )
        chunk = program_cache.cached_program(
            "cg_chunk", ("replicated", kind_key),
            lambda: partial(_cg_chunk_kernel, mv), comm=comm,
            out_shardings=(rep,) * 5, static_argnums=(6, 7),
        )
    return init, chunk


def _cg_checkpointed(mv, kind_key, op_comm, leaves, b_log, x0_log, n, every,
                     path, resume):
    """Window-driven CG with checkpoint/resume (see :func:`cg`). Progress
    is measured by the carried iteration counter, so a window that makes
    no progress (converged, or iteration budget reached) terminates the
    loop regardless of host-side tolerance arithmetic."""
    import numpy as np

    from ... import resilience

    init_jit, chunk_jit = _cg_chunk_jits(mv, kind_key, op_comm)
    carry = None
    if resume and resilience.checkpoint.exists(path):
        leaves_ckpt, extra = resilience.load_checkpoint(path, with_extra=True)
        if extra.get("algo") != "cg" or len(leaves_ckpt) != 3:
            raise resilience.CheckpointError(
                f"{path!r} is a {extra.get('algo')!r} checkpoint, not cg"
            )
        x, r, p = leaves_ckpt
        dt = b_log.dtype
        carry = (
            jnp.asarray(x, dt), jnp.asarray(r, dt), jnp.asarray(p, dt),
            jnp.asarray(extra["rsold"], dt),
            jnp.asarray(extra["it"], jnp.int32),
        )
    if carry is None:
        carry = init_jit(leaves, b_log, x0_log, n)
    while True:
        it_before = int(carry[4])
        if it_before >= n:
            break
        carry = chunk_jit(leaves, *carry[:5], n, every)
        it_after = int(carry[4])
        if it_after == it_before:
            break  # converged (rsold under tolerance) — no progress made
        x, r, p, rsold, _it = carry
        resilience.save_checkpoint(
            [np.asarray(x), np.asarray(r), np.asarray(p)], path,
            extra={"algo": "cg", "it": it_after, "rsold": float(rsold)},
        )
    return carry[0]


def _lanczos_jit(mv, kind_key, comm):
    """lanczos program memoized per (operator kind, comm, layout family).
    The comm variant pins explicit replicated out_shardings for sharded
    operator leaves — an XLA-chosen output sharding can otherwise hit
    jax's device-order reshard assertion in the downstream device_put
    under multi-host."""
    if comm is None:
        return program_cache.cached_program(
            "lanczos", ("plain", kind_key),
            lambda: partial(_lanczos_kernel, mv), static_argnums=(2, 3),
        )
    return program_cache.cached_program(
        "lanczos", ("replicated", kind_key),
        lambda: partial(_lanczos_kernel, mv), comm=comm,
        out_shardings=(
            comm.replicated(), comm.replicated(), comm.replicated()
        ),
        static_argnums=(2, 3),
    )


def _lanczos_chunk_jits(mv, kind_key, comm):
    """(init, chunk) cached programs for the checkpointed Lanczos driver
    (``comm=None`` → replicated operator leaves)."""
    if comm is None:
        init = program_cache.cached_program(
            "lanczos_init", ("plain", kind_key),
            lambda: partial(_lanczos_init_kernel, mv), static_argnums=(2, 3),
        )
        chunk = program_cache.cached_program(
            "lanczos_chunk", ("plain", kind_key),
            lambda: partial(_lanczos_chunk_kernel, mv),
            static_argnums=(6, 7, 8),
        )
    else:
        rep = comm.replicated()
        init = program_cache.cached_program(
            "lanczos_init", ("replicated", kind_key),
            lambda: partial(_lanczos_init_kernel, mv), comm=comm,
            out_shardings=(rep,) * 4, static_argnums=(2, 3),
        )
        chunk = program_cache.cached_program(
            "lanczos_chunk", ("replicated", kind_key),
            lambda: partial(_lanczos_chunk_kernel, mv), comm=comm,
            out_shardings=(rep,) * 4, static_argnums=(6, 7, 8),
        )
    return init, chunk


def _lanczos_checkpointed(mv, kind_key, op_comm, leaves, v, m, n, every,
                          path, resume):
    """Window-driven Lanczos with checkpoint/resume (see :func:`lanczos`).
    The trip count is exact (no convergence test), so windows advance by
    ``every`` steps until ``m``."""
    import numpy as np

    from ... import resilience

    init_jit, chunk_jit = _lanczos_chunk_jits(mv, kind_key, op_comm)
    carry = None
    i = 1
    if resume and resilience.checkpoint.exists(path):
        leaves_ckpt, extra = resilience.load_checkpoint(path, with_extra=True)
        if extra.get("algo") != "lanczos" or len(leaves_ckpt) != 4:
            raise resilience.CheckpointError(
                f"{path!r} is a {extra.get('algo')!r} checkpoint, not lanczos"
            )
        Vb, alphas, betas, w = leaves_ckpt
        dt = v.dtype
        carry = (
            jnp.asarray(Vb, dt), jnp.asarray(alphas, dt),
            jnp.asarray(betas, dt), jnp.asarray(w, dt),
        )
        i = int(extra["i"])
    if carry is None:
        carry = init_jit(leaves, v, m, n)
    while i < m:
        carry = chunk_jit(
            leaves, *carry, jnp.asarray(i, jnp.int32), m, n, every
        )
        i = min(i + every, m)
        resilience.save_checkpoint(
            [np.asarray(x) for x in carry], path,
            extra={"algo": "lanczos", "i": i},
        )
    Vb, alphas, betas, _w = carry
    return Vb.T, alphas, betas


def lanczos(
    A,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
    *,
    checkpoint_every: Optional[int] = None,
    checkpoint_path: Optional[str] = None,
    resume: bool = False,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization with full reorthogonalization (reference
    solver.py:68: Krylov iteration with Gram-Schmidt against all previous
    Lanczos vectors, used by spectral clustering). Returns (V, T) with
    ``V (n×m)`` orthonormal Krylov basis and ``T (m×m)`` tridiagonal.
    The iteration itself runs as one jit dispatch (see `_lanczos_kernel`),
    in the input's promoted dtype (f64 inputs iterate at f64). ``A`` may
    be a dense :class:`DNDarray` or any operator exposing
    ``_matvec_spec`` — a :class:`heat_tpu.sparse.SparseDNDarray` runs
    each Krylov matvec as the shard-local CSR contraction with the
    all-reduce tail inside this very program (ISSUE 13: the Spectral
    pipeline's matvecs become spmv without materializing O(n²)).

    ``checkpoint_every=k`` (resilience hook, ISSUE 5) instead runs the
    Krylov iteration as exact ``k``-step windows, checkpointing the carry
    to ``checkpoint_path`` after each; ``resume=True`` continues a killed
    run from the last completed window — the step body is deterministic in
    the step index, so the chunked results match the uninterrupted run."""
    if not _is_operator(A):
        raise TypeError(
            f"A needs to be a ht.DNDarray or sparse operator, but was {type(A)}"
        )
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")
    if not isinstance(m, int) or m <= 0:
        raise TypeError(f"m must be a positive integer, got {m}")

    n = A.shape[0]
    dt = types.promote_types(A.dtype, types.float32)
    leaves, mv, kind_key, op_comm = _operator(A, dt)
    kernel_jit = _lanczos_jit(mv, kind_key, op_comm)

    if v0 is None:
        import numpy as _np

        rng = _np.random.default_rng(0)
        v = jnp.asarray(rng.standard_normal(n), dtype=dt.jnp_type())
    else:
        v = v0._replicated().astype(dt.jnp_type())

    if checkpoint_every is not None:
        if checkpoint_every <= 0:
            raise ValueError(
                f"checkpoint_every must be positive, got {checkpoint_every}"
            )
        if not checkpoint_path:
            raise ValueError("checkpoint_every requires checkpoint_path")
        V_mat, alphas, betas = _lanczos_checkpointed(
            mv, kind_key, op_comm, leaves, v, m, n,
            int(checkpoint_every), checkpoint_path, resume,
        )
    elif resume:
        raise ValueError("resume=True requires checkpoint_every")
    else:
        V_mat, alphas, betas = kernel_jit(leaves, v, m, n)

    T_mat = (
        jnp.diag(alphas)
        + jnp.diag(betas[1:], k=1)
        + jnp.diag(betas[1:], k=-1)
    )
    V_ht = DNDarray.from_logical(V_mat.astype(dt.jnp_type()), A.split, A.device, A.comm, dt)
    T_ht = DNDarray.from_logical(T_mat.astype(dt.jnp_type()), None, A.device, A.comm, dt)
    if V_out is not None:
        V_out.larray = V_ht.larray
        T_out.larray = T_ht.larray
        return V_out, T_out
    return V_ht, T_ht
