"""Distributed QR decomposition.

Re-design of reference heat/core/linalg/qr.py:17-1018, which implements a
tiled CAQR over `SquareDiagTiles` with hand-written Householder merges and
Bcasts of local Q blocks (after Zheng+2018 / Hadri+2010). On TPU the
row-split case is the classic **TSQR** (communication-avoiding QR) expressed
as a `shard_map`: local QR per shard, all-gather of the small R factors, a
redundant replicated QR of the stacked Rs, and one local GEMM to update Q —
two MXU GEMM stages and a single ICI all-gather instead of the reference's
O(tiles²) message choreography.

The column-split case (reference qr.py:849-1018, a per-tile-column loop of
local QRs + Bcasts) is re-designed as **CholeskyQR2** over two shard_map
kernels: a ring Gram kernel building ``G = AᵀA`` tile-by-tile (the cdist
ring schedule — no device ever holds more than one circulating block), a
replicated Cholesky of the small ``G``, and a `psum_scatter` panel solve
``Q = A·R⁻¹`` that returns column-sharded Q directly. One refinement pass
restores orthogonality to ~machine eps for κ(A) up to ~1/√eps; if the first
Cholesky breaks down, a shifted Cholesky (Fukaya et al. 2020) plus an extra
refinement pass extends the reach. The matrix is never gathered — per-device
peak memory is the local block plus one circulating block.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import program_cache, types
from ..dndarray import DNDarray
from ... import telemetry

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")


def _gram_ring(buf: jax.Array, comm, audit_cost=None) -> jax.Array:
    """``G = AᵀA`` for a column-sharded (pad-zeroed) physical buffer
    ``(m, n_phys)``; returns G ``(n_phys, n_phys)`` replicated.

    Ring schedule: device i keeps its transposed block stationary, the
    blocks circulate; step t computes tile ``G[my cols, origin's cols]``.
    p steps × one (c, m)·(m, c) MXU GEMM each; comm = m·n around the ring
    plus the final n² all-gather of row blocks. ``audit_cost`` (an
    analytic CollectiveCost) turns on the HLO collective audit of the
    kernel program (telemetry/hlo.py)."""
    from .. import relayout_planner

    p = comm.size
    axis = comm.axis_name
    n_phys = buf.shape[1]
    c = n_phys // p  # per-device column-block width (used by the tile writes)
    # double-buffered overlap schedule (ISSUE 6): hop before the tile
    # GEMM (so the permute rides under the compute) and peel the final
    # dead hop — p-1 hops, bit-identical tiles/updates; the serial p-hop
    # kernel is restored by HEAT_TPU_RING_OVERLAP=0
    overlap = relayout_planner.ring_overlap() and p > 1

    xt = buf.T  # (n_phys, m) split=0 — local transpose, no relayout

    def kernel(xt_blk):
        rank = jax.lax.axis_index(axis)

        def tile_into(t, circ, acc):
            origin = (rank - t) % p
            tile = xt_blk @ circ.T  # (c, c)
            return jax.lax.dynamic_update_slice(
                acc, tile, (jnp.int32(0), (origin * c).astype(jnp.int32))
            )

        acc0 = jax.lax.pcast(
            jnp.zeros((xt_blk.shape[0], n_phys), dtype=buf.dtype),
            axis,
            to="varying",
        )
        if overlap:
            def body(t, carry):
                circ, acc = carry
                # the Gram/Cholesky factorization amplifies wire error
                # quadratically — the QR rings never compress
                cnext = comm.ring_permute(circ, precision="off")
                acc = tile_into(t, circ, acc)
                return cnext, acc

            circ, acc = jax.lax.fori_loop(0, p - 1, body, (xt_blk, acc0))
            acc = tile_into(p - 1, circ, acc)
        else:
            def body(t, carry):
                circ, acc = carry
                acc = tile_into(t, circ, acc)
                # the comm wrapper (not raw lax.ppermute) so the hop is
                # named in telemetry's trace-time collective record
                circ = comm.ring_permute(circ, precision="off")
                return circ, acc

            _, acc = jax.lax.fori_loop(0, p, body, (xt_blk, acc0))
        return jax.lax.all_gather(acc, axis, tiled=True)  # replicated G

    key = (tuple(buf.shape), str(buf.dtype),
           "overlap" if overlap else "serial")
    smapped = program_cache.cached_program(
        "cholqr_gram_ring", key,
        lambda: jax.shard_map(
            kernel,
            mesh=comm.mesh,
            in_specs=comm.spec(0, 2),
            out_specs=jax.sharding.PartitionSpec(),
            # the tiled all_gather makes the output bitwise-identical on
            # every device, but the varying-axis type system can't infer
            # that through the fori_loop carry
            check_vma=False,
        ),
        comm=comm,
    )
    if audit_cost is not None:
        # the audit lowers the SAME cached program the call executes —
        # one signature shared between registry and auditor memo
        telemetry.hlo.audit_call(
            "cholqr_gram_ring",
            lambda: (smapped, (xt,)),
            predicted=audit_cost,
            key=program_cache.program_key("cholqr_gram_ring", key, comm=comm),
            fields={"gshape": [int(buf.shape[0]), int(buf.shape[1])],
                    "mesh": p},
        )
    return smapped(xt)


def _panel_solve(buf: jax.Array, rinv_pad: jax.Array, comm) -> jax.Array:
    """``Q = A @ R⁻¹`` for column-sharded ``A`` ``(m, n_phys)`` with the
    contraction over the split axis: each device computes its partial
    ``A_local @ R⁻¹[local rows, :]`` and a `psum_scatter` along columns
    returns Q column-sharded — the result never materializes unsharded."""
    axis = comm.axis_name

    def kernel(x, rv):
        partial = x @ rv  # (m, n_phys)
        return jax.lax.psum_scatter(
            partial, axis, scatter_dimension=1, tiled=True
        )  # (m, c)

    smapped = program_cache.cached_program(
        "cholqr_panel_solve", (),
        lambda: jax.shard_map(
            kernel,
            mesh=comm.mesh,
            in_specs=(comm.spec(1, 2), comm.spec(0, 2)),
            out_specs=comm.spec(1, 2),
        ),
        comm=comm,
    )
    return smapped(buf, rinv_pad)


def _cholqr_split1(a: DNDarray, dt, calc_q: bool, audit: bool = False) -> QR:
    """CholeskyQR2 (+ shifted-Cholesky fallback) for tall column-split
    matrices; see module docstring."""
    comm = a.comm
    m, n = a.shape
    n_phys = comm.padded_size(n)
    buf = a._masked(0).astype(dt.jnp_type())  # (m, n_phys), pad cols zeroed

    eye = jnp.eye(n, dtype=buf.dtype)
    eps = float(jnp.finfo(buf.dtype).eps)
    r_factors = []
    passes_left = 2
    shifted = False
    q_buf = buf
    from .. import relayout_planner

    gram_hops = (
        comm.size - 1 if relayout_planner.ring_overlap() and comm.size > 1
        else comm.size
    )
    while passes_left > 0:
        cost, fields, do_audit = telemetry.op_cost(
            telemetry.collectives.gram_ring_cost, m, n, dt.byte_size(),
            comm.size, gram_hops, audit=audit,
        )
        with telemetry.span(
            "cholqr_gram_ring", gshape=[m, n],
            overlap=gram_hops < comm.size, **fields,
        ) as sp:
            g = sp.output(
                _gram_ring(q_buf, comm, audit_cost=cost if do_audit else None)
            )[:n, :n]
        ell = jnp.linalg.cholesky(g)
        # breakdown check on the small factor (one n² host fetch): NaNs or a
        # collapsed diagonal mean G is (numerically) singular on THIS pass —
        # exactly rank-deficient inputs break the refinement pass too, since
        # their deficient Q columns come out zero
        ell_h = np.asarray(ell)
        diag = np.abs(np.diagonal(ell_h))
        if np.isnan(ell_h).any() or diag.min() <= n * eps * max(diag.max(), 1.0):
            # shifted Cholesky (Fukaya et al. 2020): guarantees the
            # factorization exists; an extra refinement pass restores
            # orthogonality of the non-deficient directions
            shift = 11.0 * eps * (m * n + n * (n + 1)) * jnp.trace(g)
            ell = jnp.linalg.cholesky(g + shift * eye)
            if not shifted:
                shifted = True
                passes_left += 1
        linv = jax.scipy.linalg.solve_triangular(ell, eye, lower=True)
        rinv = linv.T  # R = Lᵀ, so R⁻¹ = (L⁻¹)ᵀ
        rinv_pad = jnp.zeros((n_phys, n_phys), dtype=buf.dtype)
        rinv_pad = rinv_pad.at[:n, :n].set(rinv)
        q_buf = _panel_solve(q_buf, rinv_pad, comm)
        r_factors.append(ell.T)
        passes_left -= 1

    r_log = r_factors[0]
    for f in r_factors[1:]:
        r_log = f @ r_log
    r_ht = DNDarray.from_logical(r_log, 1, a.device, comm, dt)
    if not calc_q:
        return QR(None, r_ht)
    q_ht = DNDarray(q_buf, (m, n), dt, 1, a.device, comm, True)
    return QR(q_ht, r_ht)


def _wide_split1(a: DNDarray, dt, calc_q: bool) -> QR:
    """Reduced QR of a wide (m < n) column-split matrix without gathering:
    the Householder reflectors of a wide QR come only from the first ``m``
    columns, so ``Q`` equals the Q of ``A[:, :m]`` (the small m×m leading
    block — the only thing replicated) and ``R = Qᵀ A`` is a shard-local
    GEMM that keeps split=1."""
    comm = a.comm
    m, n = a.shape
    buf = a._masked(0).astype(dt.jnp_type())
    lead_fn = program_cache.cached_program(
        "qr_wide_lead", (m,),
        lambda: (lambda x: x[:, :m]),
        comm=comm, out_shardings=comm.replicated(),
    )
    lead = lead_fn(buf)
    q_log, _ = jnp.linalg.qr(lead)  # (m, m), computed redundantly per device
    # R = Qᵀ A: contraction over rows (not split) — local GEMMs, no comm
    r_buf = jnp.matmul(q_log.T, buf)
    r_ht = DNDarray(r_buf, (m, n), dt, 1, a.device, comm, True)
    if not calc_q:
        return QR(None, r_ht)
    q_ht = DNDarray.from_logical(q_log, 1, a.device, comm, dt)
    return QR(q_ht, r_ht)


def _local_tsqr(x: jax.Array, tiles: int):
    """Local (within-shard) blocked TSQR: split the block into ``tiles``
    row-panels, QR each, then QR the stacked R factors — the reference's
    ``tiles_per_proc`` knob (qr.py:17: SquareDiagTiles subdivides each rank)
    realized as a deeper on-chip reduction tree. Falls back to one dense QR
    when the panels would be wider than tall."""
    c, n = x.shape
    if tiles <= 1 or c % tiles != 0 or c // tiles < n:
        return jnp.linalg.qr(x)
    cb = c // tiles
    panels = x.reshape(tiles, cb, n)
    q1, r1 = jnp.linalg.qr(panels)  # batched: (t, cb, n), (t, n, n)
    q2, r = jnp.linalg.qr(r1.reshape(tiles * n, n))  # (t*n, n), (n, n)
    q2b = q2.reshape(tiles, n, n)
    q = jnp.einsum("tcn,tnk->tck", q1, q2b).reshape(c, n)
    return q, r


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
    audit: bool = False,
) -> QR:
    """Reduced QR factorization ``a = Q @ R`` (reference qr.py:17).

    Row-split tall matrices (``m >= n``) run the TSQR shard_map kernel; the
    per-shard local stage honors ``tiles_per_proc`` as a blocked local TSQR
    (the reference's tile subdivision, re-expressed as an on-chip reduction
    tree). Shards shorter than ``n`` still work — the local R factors are
    ``min(chunk, n)`` tall and the replicated second-stage QR restores the
    full ``(n, n)`` R. Column-split tall matrices run CholeskyQR2 (ring
    Gram + psum_scatter panel solve — the reference's per-tile-column
    algorithm, qr.py:849-1018, re-designed; orthogonality ~eps up to
    κ(A)≈1/√eps, shifted-Cholesky fallback beyond). Column-split wide
    matrices (``m < n``) factor the m×m leading block (the only replicated
    piece) and finish with shard-local GEMMs. Replicated inputs use one XLA
    QR. Column signs of Q/R are not unique — compare ``Q @ R`` and
    ``Q.T @ Q``, as the reference tests do.

    ``audit=True`` (or the global ``HEAT_TPU_HLO_AUDIT=1``) additionally
    lower-compiles the distributed kernel (TSQR / ring Gram) and diffs
    the collectives XLA actually emitted against the analytic cost model
    (telemetry/hlo.py) — docs/OBSERVABILITY.md.
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, but was {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"'a' must be 2-dimensional, but has {a.ndim} dimensions")
    if not isinstance(tiles_per_proc, int):
        raise TypeError(f"tiles_per_proc must be an int, but was {type(tiles_per_proc)}")

    m, n = a.shape
    comm = a.comm
    dt = types.promote_types(a.dtype, types.float32)
    chunk = comm.chunk_size(m)

    # TSQR path: rows sharded over the mesh, global m tall enough for a
    # reduced (m, n) -> (m, n)(n, n) factorization
    if a.split == 0 and comm.size > 1 and m >= n:
        buf = a._masked(0).astype(dt.jnp_type())  # zero pad rows: QR([A;0]) == ([Q;0], R)
        p = comm.size
        axis = comm.axis_name
        spec_row = comm.spec(0, 2)
        k1 = min(chunk, n)  # local R height

        def kernel(x):
            q1, r1 = _local_tsqr(x, tiles_per_proc)  # (c, k1), (k1, n)
            rs = jax.lax.all_gather(r1, axis, tiled=True)  # (p*k1, n)
            q2, r = jnp.linalg.qr(rs)  # (p*k1, kk), (kk, n) with kk=min(p*k1, n)
            i = jax.lax.axis_index(axis)
            q2_i = jax.lax.dynamic_slice_in_dim(q2, i * k1, k1, axis=0)  # (k1, kk)
            q_i = q1 @ q2_i  # (c, kk)
            return q_i, r

        # kk == n always: p*k1 >= min(p*chunk, p*n) >= min(m, n) = n
        cost, fields, do_audit = telemetry.op_cost(
            telemetry.collectives.tsqr_cost, m, n, dt.byte_size(), p,
            audit=audit,
        )
        key = ((m, n), str(buf.dtype), tiles_per_proc)
        smapped = program_cache.cached_program(
            "tsqr", key,
            lambda: jax.shard_map(
                kernel, mesh=comm.mesh, in_specs=spec_row,
                out_specs=(spec_row, spec_row),
            ),
            comm=comm,
        )
        if do_audit:
            telemetry.hlo.audit_call(
                "tsqr",
                lambda: (smapped, (buf,)),
                predicted=cost,
                key=program_cache.program_key("tsqr", key, comm=comm),
                fields={"gshape": [m, n], "mesh": p},
            )
        with telemetry.span("tsqr", gshape=[m, n], mesh=p, **fields) as sp:
            q_phys, r_tiled = smapped(buf)
            sp.output(q_phys)
            sp.output(r_tiled)
        r_log = r_tiled[:n]  # every shard computed the same R; take one copy
        r_ht = DNDarray.from_logical(r_log, None, a.device, comm, dt)
        if not calc_q:
            return QR(None, r_ht)
        q_ht = DNDarray(q_phys, (m, n), dt, 0, a.device, comm, True)
        return QR(q_ht, r_ht)

    # column-split path: CholeskyQR2 ring/scatter kernels (tall) or the
    # leading-block factorization (wide) — no gather, multi-host safe
    if a.split == 1 and comm.size > 1:
        if m >= n:
            return _cholqr_split1(a, dt, calc_q, audit=audit)
        return _wide_split1(a, dt, calc_q)

    # wide row-split: factor the m×m leading block (the small-dim² piece,
    # replicated via the compiled relayout), then R = QᵀA — a contraction
    # over the split rows that matmul renders as one psum. Multi-host safe.
    if a.split == 0 and comm.size > 1 and m < n:
        from .basics import matmul

        lead = a[:, :m]  # split=0 (m, m)
        q_log, _ = jnp.linalg.qr(lead._replicated().astype(dt.jnp_type()))
        qt_ht = DNDarray.from_logical(q_log.T, None, a.device, comm, dt)
        r_ht = matmul(qt_ht, a)
        if not calc_q:
            return QR(None, r_ht)
        q_ht = DNDarray.from_logical(q_log, 0, a.device, comm, dt)
        return QR(q_ht, r_ht)

    # general path: one XLA QR over the logical view (wide/replicated
    # inputs and single-position meshes; XLA gathers as needed)
    log = a._logical().astype(dt.jnp_type())
    q_log, r_log = jnp.linalg.qr(log)
    r_ht = DNDarray.from_logical(r_log, None if a.split != 1 else 1, a.device, comm, dt)
    if not calc_q:
        return QR(None, r_ht)
    q_ht = DNDarray.from_logical(q_log, a.split, a.device, comm, dt)
    return QR(q_ht, r_ht)
