"""Distributed QR decomposition.

Re-design of reference heat/core/linalg/qr.py:17-1018, which implements a
tiled CAQR over `SquareDiagTiles` with hand-written Householder merges and
Bcasts of local Q blocks (after Zheng+2018 / Hadri+2010). On TPU the
row-split case is the classic **TSQR** (communication-avoiding QR) expressed
as a `shard_map`: local QR per shard, all-gather of the small R factors, a
redundant replicated QR of the stacked Rs, and one local GEMM to update Q —
two MXU GEMM stages and a single ICI all-gather instead of the reference's
O(tiles²) message choreography.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax
import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")


def _local_tsqr(x: jax.Array, tiles: int):
    """Local (within-shard) blocked TSQR: split the block into ``tiles``
    row-panels, QR each, then QR the stacked R factors — the reference's
    ``tiles_per_proc`` knob (qr.py:17: SquareDiagTiles subdivides each rank)
    realized as a deeper on-chip reduction tree. Falls back to one dense QR
    when the panels would be wider than tall."""
    c, n = x.shape
    if tiles <= 1 or c % tiles != 0 or c // tiles < n:
        return jnp.linalg.qr(x)
    cb = c // tiles
    panels = x.reshape(tiles, cb, n)
    q1, r1 = jnp.linalg.qr(panels)  # batched: (t, cb, n), (t, n, n)
    q2, r = jnp.linalg.qr(r1.reshape(tiles * n, n))  # (t*n, n), (n, n)
    q2b = q2.reshape(tiles, n, n)
    q = jnp.einsum("tcn,tnk->tck", q1, q2b).reshape(c, n)
    return q, r


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> QR:
    """Reduced QR factorization ``a = Q @ R`` (reference qr.py:17).

    Row-split tall matrices (``m >= n``) run the TSQR shard_map kernel; the
    per-shard local stage honors ``tiles_per_proc`` as a blocked local TSQR
    (the reference's tile subdivision, re-expressed as an on-chip reduction
    tree). Shards shorter than ``n`` still work — the local R factors are
    ``min(chunk, n)`` tall and the replicated second-stage QR restores the
    full ``(n, n)`` R. Wide matrices (``m < n``) and column-split inputs use
    one global XLA QR (documented: there is no communication-avoiding
    row-decomposition to exploit when rows fit on one shard's minor dim).
    Column signs of Q/R are not unique — compare ``Q @ R`` and ``Q.T @ Q``,
    as the reference tests do.
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, but was {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"'a' must be 2-dimensional, but has {a.ndim} dimensions")
    if not isinstance(tiles_per_proc, int):
        raise TypeError(f"tiles_per_proc must be an int, but was {type(tiles_per_proc)}")

    m, n = a.shape
    comm = a.comm
    dt = types.promote_types(a.dtype, types.float32)
    chunk = comm.chunk_size(m)

    # TSQR path: rows sharded over the mesh, global m tall enough for a
    # reduced (m, n) -> (m, n)(n, n) factorization
    if a.split == 0 and comm.size > 1 and m >= n:
        buf = a._masked(0).astype(dt.jnp_type())  # zero pad rows: QR([A;0]) == ([Q;0], R)
        p = comm.size
        axis = comm.axis_name
        spec_row = comm.spec(0, 2)
        k1 = min(chunk, n)  # local R height

        def kernel(x):
            q1, r1 = _local_tsqr(x, tiles_per_proc)  # (c, k1), (k1, n)
            rs = jax.lax.all_gather(r1, axis, tiled=True)  # (p*k1, n)
            q2, r = jnp.linalg.qr(rs)  # (p*k1, kk), (kk, n) with kk=min(p*k1, n)
            i = jax.lax.axis_index(axis)
            q2_i = jax.lax.dynamic_slice_in_dim(q2, i * k1, k1, axis=0)  # (k1, kk)
            q_i = q1 @ q2_i  # (c, kk)
            return q_i, r

        # kk == n always: p*k1 >= min(p*chunk, p*n) >= min(m, n) = n
        q_phys, r_tiled = jax.shard_map(
            kernel, mesh=comm.mesh, in_specs=spec_row, out_specs=(spec_row, spec_row)
        )(buf)
        r_log = r_tiled[:n]  # every shard computed the same R; take one copy
        r_ht = DNDarray.from_logical(r_log, None, a.device, comm, dt)
        if not calc_q:
            return QR(None, r_ht)
        q_ht = DNDarray(q_phys, (m, n), dt, 0, a.device, comm, True)
        return QR(q_ht, r_ht)

    # general path: one XLA QR over the logical view (wide matrices,
    # column-split and replicated inputs; XLA gathers as needed)
    log = a._logical().astype(dt.jnp_type())
    q_log, r_log = jnp.linalg.qr(log)
    r_ht = DNDarray.from_logical(r_log, None if a.split != 1 else 1, a.device, comm, dt)
    if not calc_q:
        return QR(None, r_ht)
    q_ht = DNDarray.from_logical(q_log, a.split, a.device, comm, dt)
    return QR(q_ht, r_ht)
