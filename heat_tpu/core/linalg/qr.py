"""Distributed QR decomposition.

Re-design of reference heat/core/linalg/qr.py:17-1018, which implements a
tiled CAQR over `SquareDiagTiles` with hand-written Householder merges and
Bcasts of local Q blocks (after Zheng+2018 / Hadri+2010). On TPU the
row-split case is the classic **TSQR** (communication-avoiding QR) expressed
as a `shard_map`: local QR per shard, all-gather of the small R factors, a
redundant replicated QR of the stacked Rs, and one local GEMM to update Q —
two MXU GEMM stages and a single ICI all-gather instead of the reference's
O(tiles²) message choreography.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax
import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> QR:
    """Reduced QR factorization ``a = Q @ R`` (reference qr.py:17).

    ``tiles_per_proc`` is accepted for API parity; the TSQR block size is the
    mesh chunk (the reference uses it to subdivide ranks into tiles, a knob
    the XLA schedule does not need). Column signs of Q/R are not unique —
    compare ``Q @ R`` and ``Q.T @ Q``, as the reference tests do.
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, but was {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"'a' must be 2-dimensional, but has {a.ndim} dimensions")
    if not isinstance(tiles_per_proc, int):
        raise TypeError(f"tiles_per_proc must be an int, but was {type(tiles_per_proc)}")

    m, n = a.shape
    comm = a.comm
    dt = types.promote_types(a.dtype, types.float32)
    chunk = comm.chunk_size(m)

    # TSQR path: rows sharded over the mesh and every shard tall enough for a
    # well-shaped local reduced QR
    if a.split == 0 and comm.size > 1 and chunk >= n:
        buf = a._masked(0).astype(dt.jnp_type())  # zero pad rows: QR([A;0]) == ([Q;0], R)
        p = comm.size
        axis = comm.axis_name
        spec_row = comm.spec(0, 2)

        def kernel(x):
            q1, r1 = jnp.linalg.qr(x)  # (c, n), (n, n)
            rs = jax.lax.all_gather(r1, axis, tiled=True)  # (p*n, n)
            q2, r = jnp.linalg.qr(rs)  # (p*n, n), (n, n)
            i = jax.lax.axis_index(axis)
            q2_i = jax.lax.dynamic_slice_in_dim(q2, i * n, n, axis=0)  # (n, n)
            q_i = q1 @ q2_i  # (c, n)
            return q_i, r

        q_phys, r_tiled = jax.shard_map(
            kernel, mesh=comm.mesh, in_specs=spec_row, out_specs=(spec_row, spec_row)
        )(buf)
        r_log = r_tiled[:n]  # every shard computed the same R; take one copy
        r_ht = DNDarray.from_logical(r_log, None, a.device, comm, dt)
        if not calc_q:
            return QR(None, r_ht)
        q_ht = DNDarray(q_phys, (m, n), dt, 0, a.device, comm, True)
        return QR(q_ht, r_ht)

    # general path: one XLA QR over the logical view (column-split and
    # replicated inputs; XLA gathers as needed)
    log = a._logical().astype(dt.jnp_type())
    q_log, r_log = jnp.linalg.qr(log)
    r_ht = DNDarray.from_logical(r_log, None if a.split != 1 else 1, a.device, comm, dt)
    if not calc_q:
        return QR(None, r_ht)
    q_ht = DNDarray.from_logical(q_log, a.split, a.device, comm, dt)
    return QR(q_ht, r_ht)
