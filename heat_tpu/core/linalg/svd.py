"""Distributed SVD.

The reference ships only a stub ("Future file for SVD functions",
reference heat/core/linalg/svd.py:1-5) and works around it with Lanczos.
This module is a capability *extension*: a QR-based tall-skinny SVD — TSQR
(see qr.py) followed by an SVD of the small R on the MXU — plus a general
XLA path.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray

__all__ = ["svd"]

SVD = collections.namedtuple("SVD", "U, S, V")


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """Singular value decomposition ``a = U @ diag(S) @ V.T``.

    For a row-split tall matrix this runs TSQR (one ICI all-gather) and then
    an SVD of the n×n R factor, so the heavy lifting stays on the MXU.
    ``full_matrices=True`` is not supported for the distributed path (the
    reference has no SVD at all)."""
    from .qr import qr as _qr
    from .basics import matmul

    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, but was {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"'a' must be 2-dimensional, but has {a.ndim} dimensions")

    m, n = a.shape
    dt = types.promote_types(a.dtype, types.float32)

    if compute_uv and a.split == 0 and a.comm.size > 1 and m >= n and not full_matrices:
        q, r = _qr(a)
        # R from TSQR is replicated (split=None, no pad) — its physical
        # buffer IS the logical array
        u_r, s_log, vt_log = jnp.linalg.svd(r.larray, full_matrices=False)
        u = matmul(q, DNDarray.from_logical(u_r.astype(dt.jnp_type()), None, a.device, a.comm, dt))
        s_ht = DNDarray.from_logical(s_log.astype(dt.jnp_type()), None, a.device, a.comm, dt)
        v_ht = DNDarray.from_logical(vt_log.T.astype(dt.jnp_type()), None, a.device, a.comm, dt)
        return SVD(u, s_ht, v_ht)

    if compute_uv and a.split == 1 and a.comm.size > 1 and m >= n and not full_matrices:
        # tall column-split: CholeskyQR2 (no gather, qr.py) + small-R SVD;
        # U = Q·u_r is the psum_scatter panel pattern, emitted by matmul
        q, r = _qr(a)
        u_r, s_log, vt_log = jnp.linalg.svd(r._replicated(), full_matrices=False)
        u = matmul(q, DNDarray.from_logical(u_r.astype(dt.jnp_type()), None, a.device, a.comm, dt))
        s_ht = DNDarray.from_logical(s_log.astype(dt.jnp_type()), None, a.device, a.comm, dt)
        v_ht = DNDarray.from_logical(vt_log.T.astype(dt.jnp_type()), None, a.device, a.comm, dt)
        return SVD(u, s_ht, v_ht)

    if compute_uv and a.comm.size > 1 and not full_matrices and (
        (a.split == 1 and n > m) or (a.split == 0 and n > m)
    ):
        # wide: A^T is tall with the complementary split — run the tall path
        # there and swap the factors (A = U S V^T  <=>  A^T = V S U^T)
        from .basics import transpose

        res = svd(transpose(a), full_matrices=False, compute_uv=True)
        return SVD(res.V, res.S, res.U)

    if not compute_uv and a.comm.size > 1 and a.split is not None:
        # singular values only: they equal R's — no Q needed. Wide inputs
        # transpose into the tall form of the complementary split
        # (singular values are transpose-invariant); both tall forms have a
        # no-gather QR (TSQR / CholeskyQR2).
        if n > m:
            from .basics import transpose

            a = transpose(a)
        _, r = _qr(a, calc_q=False)
        s_log = jnp.linalg.svd(
            r._replicated().astype(dt.jnp_type()), compute_uv=False
        )
        return DNDarray.from_logical(s_log, None, a.device, a.comm, dt)

    log = a._logical().astype(dt.jnp_type())
    if not compute_uv:
        s_log = jnp.linalg.svd(log, compute_uv=False)
        return DNDarray.from_logical(s_log, None, a.device, a.comm, dt)
    u_log, s_log, vt_log = jnp.linalg.svd(log, full_matrices=full_matrices)
    u_ht = DNDarray.from_logical(u_log, a.split if a.split == 0 else None, a.device, a.comm, dt)
    s_ht = DNDarray.from_logical(s_log, None, a.device, a.comm, dt)
    v_ht = DNDarray.from_logical(vt_log.T, a.split if a.split == 1 else None, a.device, a.comm, dt)
    return SVD(u_ht, s_ht, v_ht)
