"""Int8 quantized matmul — a Pallas TPU kernel for the inference hot path.

The v5e MXU runs int8 at ~2× its bf16 rate (394 vs 197 TOPS peak); the
reference framework has no quantization support at all, so this is a pure
capability extension on the framework's hottest op. Design:

* :func:`quantize_int8` — symmetric per-row/per-column absmax scaling to
  int8 (the standard W8A8 inference recipe).
* :func:`int8_matmul` — hand-tiled Pallas GEMM: int8 tiles stream
  HBM→VMEM, products accumulate in an int32 VMEM scratch across the K
  grid axis (no overflow: 127·127·K fits int32 for K ≤ 2^17 per tile
  chain), and the f32 rescale (row scale × column scale) fuses into the
  final write.
* :func:`matmul_int8` — convenience: quantize both operands, multiply,
  return f32 — one call to compare against `ht.matmul` accuracy/perf.

Off-TPU the kernel runs under the Pallas interpreter (same program), so
the CPU test mesh exercises the exact kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["quantize_int8", "int8_matmul", "matmul_int8"]

_I0 = np.int32(0)  # index-map literal pinned to i32 (x64 mode, see pallas_attention)


def quantize_int8(x: jax.Array, axis: int) -> Tuple[jax.Array, jax.Array]:
    """Symmetric absmax int8 quantization along ``axis``.

    Returns ``(q, scale)`` with ``q ≈ x / scale`` in int8 and ``scale``
    shaped like ``x`` with ``axis`` reduced (kept as size 1).
    """
    absmax = jnp.max(jnp.abs(x), axis=axis, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def _q_kernel(a_ref, b_ref, sa_ref, sb_ref, o_ref, acc_s):
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)

    acc_s[:] += jax.lax.dot_general(
        a_ref[:], b_ref[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )

    @pl.when(ik == nk - 1)
    def _finalize():
        scale = sa_ref[:] * sb_ref[:]  # (bm, 1) * (1, bn) -> (bm, bn)
        o_ref[:] = (acc_s[:].astype(jnp.float32) * scale).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_m", "block_n", "block_k", "interpret", "out_dtype")
)
def int8_matmul(
    qa: jax.Array,
    sa: jax.Array,
    qb: jax.Array,
    sb: jax.Array,
    *,
    block_m: int = 512,
    block_n: int = 512,
    block_k: int = 512,
    out_dtype=jnp.float32,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """``(qa @ qb) * (sa * sb)`` with int8 MXU accumulation in int32.

    ``qa``: (M, K) int8 with per-row scales ``sa`` (M, 1);
    ``qb``: (K, N) int8 with per-column scales ``sb`` (1, N).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m, k = qa.shape
    k2, n = qb.shape
    if k != k2:
        raise ValueError(f"contraction mismatch: {qa.shape} @ {qb.shape}")
    if m == 0 or n == 0 or k == 0:
        # empty operand: same contract as jnp.matmul (zeros output; a zero
        # contraction dim contributes nothing) — the tiling below assumes
        # at least one tile
        return jnp.zeros((m, n), out_dtype)
    # int8 MXU tiles want (32, 128) minimums; clamp blocks to padded dims
    block_m = min(block_m, -(-m // 32) * 32)
    block_n = min(block_n, -(-n // 128) * 128)
    block_k = min(block_k, -(-k // 128) * 128)
    pm, pn, pk = -m % block_m, -n % block_n, -k % block_k
    if pm or pk:
        qa = jnp.pad(qa, ((0, pm), (0, pk)))
        sa = jnp.pad(sa, ((0, pm), (0, 0)), constant_values=1.0)
    if pk or pn:
        qb = jnp.pad(qb, ((0, pk), (0, pn)))
        sb = jnp.pad(sb, ((0, 0), (0, pn)), constant_values=1.0)
    grid = ((m + pm) // block_m, (n + pn) // block_n, (k + pk) // block_k)

    out = pl.pallas_call(
        _q_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, kk: (i, kk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_k, block_n), lambda i, j, kk: (kk, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, 1), lambda i, j, kk: (i, _I0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_n), lambda i, j, kk: (_I0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, kk: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m + pm, n + pn), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qa, qb, sa, sb)
    return out[:m, :n]


def matmul_int8(a: jax.Array, b: jax.Array, **kw) -> jax.Array:
    """Quantize-then-multiply convenience: W8A8 GEMM of two float arrays."""
    qa, sa = quantize_int8(a, axis=1)
    qb, sb = quantize_int8(b, axis=0)
    return int8_matmul(qa, sa, qb, sb, **kw)
