"""Global indexing: getitem/setitem engine + nonzero/where.

Re-design of the reference's gnarliest code path (reference:
heat/core/dndarray.py:661-1549 `__getitem__`/`__setitem__` translate global
keys to per-rank local keys chunk by chunk; heat/core/indexing.py nonzero/
where). Under a single controller the global array is addressable, so
indexing works on the global view — but the implementation picks the
cheapest physical route:

* **basic keys leaving the split dim whole** (full slice at the split
  position) apply directly to the tail-padded physical buffer — the pad
  travels along, no relayout;
* **1-D integer-array keys** run as a *sharded gather*: the index vector is
  tail-padded and the `jnp.take` is jit-compiled with the result's
  `NamedSharding` as `out_shardings`, so XLA emits the cross-shard gather
  and lays the result out distributed — there is never a replicated
  intermediate (the reference keeps advanced results distributed too,
  dndarray.py:661-1549);
* **setitem** updates the physical buffer in place via ``.at[key].set`` with
  the key normalized against the logical extents (pads can never be hit);
  ragged boolean-mask assignment stays shard-side too (rank-among-True
  cumsum + static gather + where — the value length is static metadata);
  only truly jnp-incompatible keys (e.g. bool arrays mixed inside tuple
  keys) fall back to a host numpy round-trip, and that path emits a loud
  ``UserWarning``;
* everything else (mixed advanced keys, partial boolean masks) goes through
  the logical view; split metadata of results follows Heat's rules:
  slicing keeps the split axis distributed (possibly shifted by dropped or
  inserted dims), an integer index on the split axis collapses it →
  replicated, a full-shape boolean mask yields a 1-D split=0 result.
"""

from __future__ import annotations

import builtins
import warnings
from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import program_cache, types
from .communication import MeshCommunication
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def _normalize_key(key, x: DNDarray):
    """Convert DNDarray keys to jnp arrays, leave the rest untouched.
    Split keys replicate via the compiled relayout (index vectors and masks
    are small next to the data) — multi-host safe, unlike the host-logical
    view."""
    if isinstance(key, DNDarray):
        if key.split is not None and (key.pad_count or key.comm.size > 1):
            return key._relayout(None)
        return key._logical()
    if isinstance(key, tuple):
        return tuple(_normalize_key(k, x) for k in key)
    if isinstance(key, list):
        return jnp.asarray(key)
    return key


def _is_int_array(k) -> bool:
    return (
        hasattr(k, "dtype")
        and np.issubdtype(np.dtype(k.dtype), np.integer)
        and getattr(k, "ndim", 0) >= 1
    )


def _is_bool_array(k, min_ndim: int = 1) -> bool:
    """Boolean array-like of at least ``min_ndim`` dims (shared predicate
    for getitem split metadata and setitem fallback routing)."""
    return (
        hasattr(k, "dtype")
        and np.dtype(k.dtype) == np.bool_
        and getattr(k, "ndim", 0) >= min_ndim
    )


def _is_bool_mask(k, x: DNDarray) -> bool:
    return _is_bool_array(k) and getattr(k, "ndim", 0) == x.ndim


def _expand_key(key, ndim: int):
    """Expand ellipsis / missing dims to a per-dimension key list (entries
    may be None for newaxis; array entries pass through unchanged)."""
    if not isinstance(key, tuple):
        key = (key,)
    n_specified = builtins.sum(1 for k in key if k is not None and k is not Ellipsis)
    expanded = []
    seen_ellipsis = False
    for k in key:
        if k is Ellipsis:
            if seen_ellipsis:
                raise IndexError("an index can only have a single ellipsis ('...')")
            seen_ellipsis = True
            expanded.extend([slice(None)] * (ndim - n_specified))
        else:
            expanded.append(k)
    while builtins.sum(1 for k in expanded if k is not None) < ndim:
        expanded.append(slice(None))
    return expanded


def _result_split(x: DNDarray, key) -> Optional[int]:
    """Split axis of an indexing result per the rules in the module
    docstring."""
    if x.split is None:
        return None
    if not isinstance(key, tuple):
        key = (key,)
    # full-shape boolean mask → 1-D compaction: split inputs land split=0
    # (the layout the distributed compaction path produces), replicated
    # inputs must stay replicated. The branch carries its own guard instead
    # of relying on the early return above — mirroring the row-mask branch
    # below, so neither silently reports split=0 for a replicated input if
    # the top guard ever moves (advisor round-5 finding; pinned by the
    # 1-device test in tests/test_indexing.py)
    if len(key) == 1 and _is_bool_mask(key[0], x):
        return 0 if x.split is not None else None
    # 1-D boolean row mask over the leading axis: the compacted axis
    # replaces axis 0, so a split=0 input stays split=0 — the layout the
    # distributed row-compaction path produces; the single-device
    # fallback must report the same metadata (caught by the 1-device CI
    # sweep: split silently became None)
    if (
        len(key) == 1
        and _is_bool_array(key[0])
        and getattr(key[0], "ndim", 0) == 1
        and x.ndim >= 1
        and tuple(np.shape(key[0])) == (x.shape[0],)
    ):
        # non-leading splits also carry through: the mask only compacts
        # axis 0 and no axes shift
        return x.split
    expanded = _expand_key(key, x.ndim)
    in_dim = 0
    out_dim = 0
    for k in expanded:
        if k is None:
            out_dim += 1
            continue
        if isinstance(k, slice):
            if in_dim == x.split:
                return out_dim
            in_dim += 1
            out_dim += 1
        elif isinstance(k, (builtins.int, np.integer)):
            if in_dim == x.split:
                return None
            in_dim += 1
        else:
            # advanced indexing — replicate (conservative)
            return None
    return None


def _sharded_take_fn(comm: MeshCommunication, axis: int, out_split: Optional[int], ndim: int):
    """Jit-compiled gather whose output is laid out with the result's
    canonical NamedSharding — XLA emits the cross-shard gather + relayout as
    one program, with no replicated intermediate. Memoized in the
    process-global :mod:`.program_cache` registry."""

    def build():
        def take(buf, idx):
            return jnp.take(buf, idx, axis=axis)

        return take

    return program_cache.cached_program(
        "sharded_take", (axis, out_split, ndim), build, comm=comm,
        out_shardings=comm.sharding(out_split, ndim),
    )


def _check_bounds(idx, n: int, axis: int) -> None:
    """Raise IndexError on out-of-range concrete indices (numpy parity).
    Tracers skip the check — a data-dependent raise cannot be traced."""
    if isinstance(idx, jax.core.Tracer) or idx.size == 0:
        return
    # one host transfer for both extrema, not two
    lo, hi = (builtins.int(v) for v in np.asarray(jnp.stack([idx.min(), idx.max()])))
    if lo < -n or hi >= n:
        bad = lo if lo < -n else hi
        raise IndexError(
            f"index {bad} is out of bounds for axis {axis} with size {n}"
        )


def _advanced_take(x: DNDarray, axis: int, idx: jax.Array) -> DNDarray:
    """x indexed by a 1-D integer array along ``axis``, keeping the result
    distributed (reference dndarray.py advanced getitem keeps split)."""
    comm = x.comm
    n = x.shape[axis]
    _check_bounds(idx, n, axis)
    idx = jnp.where(idx < 0, idx + n, idx)
    k = int(idx.shape[0])
    out_gshape = x.shape[:axis] + (k,) + x.shape[axis + 1 :]
    # result split: the indexed axis stays distributed if it was the split
    # axis; other-axis splits are carried through
    out_split = x.split
    P = comm.padded_size(k) if out_split == axis else k
    if P != k:
        idx = jnp.pad(idx, (0, P - k))  # pad entries gather row 0 — they are pad
    # the gather reads only logical (< n) indices, so input pad rows are unread
    fn = _sharded_take_fn(comm, axis, out_split, len(out_gshape))
    res = fn(x.larray, idx)
    return DNDarray(
        res, out_gshape, x.dtype, out_split, x.device, x.comm, True
    )


def _paired_take(x: DNDarray, pos0: int, rows: jax.Array, cols: jax.Array) -> DNDarray:
    """``x[..., rows, cols, ...]`` — two adjacent 1-D integer arrays at dims
    ``(pos0, pos0+1)``, every other dim a full slice. The two dims are
    merged shard-side and the pair becomes ONE linearized sharded gather
    (``row * stride + col`` into the merged axis), so the result comes out
    with its canonical sharding and no replicated intermediate — the second
    mixed-key pattern the reference handles shard-side
    (reference dndarray.py:661-1549)."""
    comm = x.comm
    n0, n1 = x.shape[pos0], x.shape[pos0 + 1]
    _check_bounds(rows, n0, pos0)
    _check_bounds(cols, n1, pos0 + 1)
    rows = jnp.where(rows < 0, rows + n0, rows)
    cols = jnp.where(cols < 0, cols + n1, cols)
    rows, cols = jnp.broadcast_arrays(rows, cols)
    k = builtins.int(rows.shape[0])
    buf = x.larray
    stride = buf.shape[pos0 + 1]  # physical minor extent
    merged = jnp.reshape(
        buf, buf.shape[:pos0] + (buf.shape[pos0] * stride,) + buf.shape[pos0 + 2 :]
    )
    idx = rows * stride + cols  # logical rows/cols never address the pad
    out_gshape = x.shape[:pos0] + (k,) + x.shape[pos0 + 2 :]
    if x.split is None:
        out_split = None
    elif x.split < pos0:
        out_split = x.split
    elif x.split in (pos0, pos0 + 1):
        out_split = pos0  # the advanced dim stays distributed
    else:
        out_split = x.split - 1
    P = comm.padded_size(k) if out_split == pos0 else k
    if P != k:
        idx = jnp.pad(idx, (0, P - k))
    fn = _sharded_take_fn(comm, pos0, out_split, len(out_gshape))
    res = fn(merged, idx)
    return DNDarray(res, out_gshape, x.dtype, out_split, x.device, x.comm, True)


def _normalize_basic_key_physical(expanded, x: DNDarray):
    """Normalize an expanded basic key against the *logical* global shape so
    it can be applied to the padded physical buffer (the pad sits at the
    global tail of the split dim, so normalized indices never touch it)."""
    out = []
    d = 0
    for k in expanded:
        if k is None:
            out.append(None)
            continue
        n = x.shape[d]
        if isinstance(k, slice):
            start, stop, step = k.indices(n)
            # a normalized stop of -1 (negative step running to the front)
            # cannot be spelled as a literal slice bound — use None
            out.append(slice(start, stop if stop >= 0 else None, step))
        elif isinstance(k, (builtins.int, np.integer)):
            kk = builtins.int(k)
            if kk < -n or kk >= n:
                raise IndexError(
                    f"index {kk} is out of bounds for axis {d} with size {n}"
                )
            out.append(kk + n if kk < 0 else kk)
        elif _is_int_array(k):
            # normalize negatives against the *logical* extent — on the
            # padded physical buffer they would otherwise wrap into the pad
            ka = jnp.asarray(k)
            _check_bounds(ka, n, d)
            out.append(jnp.where(ka < 0, ka + n, ka))
        else:
            out.append(k)
        d += 1
    return tuple(out)


def _masked_select_distributed(x: DNDarray, mask: DNDarray) -> DNDarray:
    """``x[mask]`` for a full-shape boolean mask on a split=0 array as a
    DISTRIBUTED compaction (the nonzero design): pad-False mask →
    distributed cumsum assigns global output rows → sharded scatter of the
    VALUES into the (nnz,) split=0 result. Neither the data nor the mask
    ever gathers; only the scalar nnz reaches the host."""
    comm = x.comm
    if mask.split != x.split:
        # relayout of the MASK only (bool, 1 byte/elem) — x never moves
        mask = mask.resplit(x.split)
    flatm = jnp.reshape(mask._masked(False), (-1,))
    flatv = jnp.reshape(x.larray, (-1,))  # pads never selected: mask pad False
    nnz = builtins.int(flatm.sum())
    nnz_pad = comm.padded_size(nnz)
    dest = jnp.where(flatm, jnp.cumsum(flatm) - 1, nnz_pad)
    out = _scatter_compact(comm, (nnz_pad,), flatv.dtype, dest, flatv)
    return DNDarray(out, (nnz,), x.dtype, 0, x.device, x.comm, True)


def _row_mask_select_distributed(x: DNDarray, mask: DNDarray) -> DNDarray:
    """``x[mask]`` for a 1-D boolean mask over the leading (split=0) axis of
    an n-D array: distributed row compaction — pad-False mask → distributed
    cumsum assigns output rows → sharded scatter of whole ROWS into the
    (nnz, ...) split=0 result. Only the scalar nnz reaches the host."""
    comm = x.comm
    if mask.split != 0:
        mask = mask.resplit(0)
    m = mask._masked(False)  # (n_pad,)
    nnz = builtins.int(m.sum())
    nnz_pad = comm.padded_size(nnz)
    dest = jnp.where(m, jnp.cumsum(m) - 1, nnz_pad)
    out_shape = (nnz_pad,) + x.shape[1:]
    out = (
        jnp.zeros(out_shape, dtype=x.larray.dtype)
        .at[dest]
        .set(x.larray, mode="drop")
    )
    out = jax.device_put(out, comm.sharding(0, len(out_shape)))
    return DNDarray(
        out, (nnz,) + x.shape[1:], x.dtype, 0, x.device, x.comm, True
    )


def getitem(x: DNDarray, key) -> DNDarray:
    # full-shape boolean DNDarray mask on a split=0 array: distributed
    # compaction BEFORE _normalize_key (which would gather the mask)
    if (
        isinstance(key, DNDarray)
        and key.dtype == types.bool
        and tuple(key.shape) == tuple(x.shape)
        and x.split == 0
        and x.comm.size > 1
    ):
        return _masked_select_distributed(x, key)
    # 1-D boolean row mask on an n-D split=0 array: distributed ROW
    # compaction (reference dndarray.py:661-1549 handles this shard-side)
    if (
        isinstance(key, DNDarray)
        and key.dtype == types.bool
        and key.ndim == 1
        and x.ndim > 1
        and tuple(key.shape) == (x.shape[0],)
        and x.split == 0
        and x.comm.size > 1
    ):
        return _row_mask_select_distributed(x, key)
    key = _normalize_key(key, x)

    # --- sharded gather: a single 1-D integer-array key -------------------
    if _is_int_array(key) and key.ndim == 1 and x.ndim >= 1:
        return _advanced_take(x, 0, jnp.asarray(key))
    if isinstance(key, tuple) and builtins.sum(1 for k in key if _is_int_array(k)) == 1:
        arr_pos = next(i for i, k in enumerate(key) if _is_int_array(k))
        if (
            key[arr_pos].ndim == 1
            and builtins.all(
                isinstance(k, slice) and k == slice(None)
                for i, k in enumerate(key)
                if i != arr_pos
            )
            and len(key) <= x.ndim
        ):
            return _advanced_take(x, arr_pos, jnp.asarray(key[arr_pos]))

    # --- mixed advanced keys that stay shard-side -------------------------
    if (
        isinstance(key, tuple)
        and len(key) <= x.ndim
        and not builtins.any(k is Ellipsis or k is None for k in key)
    ):
        arr_pos_list = [i for i, k in enumerate(key) if _is_int_array(k)]
        others_basic = builtins.all(
            _is_int_array(k) or isinstance(k, (slice, builtins.int, np.integer))
            for k in key
        )
        # (slice/int…, 1-D int-array): apply the basic part first (shard-
        # friendly), then the sharded gather on the surviving axis. Scalar
        # ints count as advanced when an array key is present — the
        # decomposition keeps numpy's in-place result dim only when the
        # advanced entries are CONSECUTIVE (separated advanced dims move to
        # the front in numpy; that shape juggling stays on the fallback)
        adv_pos = [
            i
            for i, k in enumerate(key)
            if _is_int_array(k) or isinstance(k, (builtins.int, np.integer))
        ]
        adv_consecutive = (
            len(adv_pos) <= 1 or adv_pos[-1] - adv_pos[0] + 1 == len(adv_pos)
        )
        if (
            others_basic
            and adv_consecutive
            and len(arr_pos_list) == 1
            and getattr(key[arr_pos_list[0]], "ndim", 0) == 1
        ):
            i = arr_pos_list[0]
            base = tuple(slice(None) if j == i else k for j, k in enumerate(key))
            nontrivial = builtins.any(
                not (isinstance(k, slice) and k == slice(None)) for k in base
            )
            y = getitem(x, base) if nontrivial else x
            new_axis = i - builtins.sum(
                1
                for j, k in enumerate(key)
                if j < i and isinstance(k, (builtins.int, np.integer))
            )
            return _advanced_take(y, new_axis, jnp.asarray(key[i]))
        # (1-D int-array, 1-D int-array) on adjacent dims, rest full slices:
        # one linearized sharded gather
        if (
            others_basic
            and len(arr_pos_list) == 2
            and arr_pos_list[1] == arr_pos_list[0] + 1
            and builtins.all(
                isinstance(k, slice) and k == slice(None)
                for j, k in enumerate(key)
                if j not in arr_pos_list
            )
            and getattr(key[arr_pos_list[0]], "ndim", 0) == 1
            and getattr(key[arr_pos_list[1]], "ndim", 0) == 1
        ):
            return _paired_take(
                x,
                arr_pos_list[0],
                jnp.asarray(key[arr_pos_list[0]]),
                jnp.asarray(key[arr_pos_list[1]]),
            )

    # --- basic keys -------------------------------------------------------
    is_basic = not isinstance(key, tuple) and (
        isinstance(key, (builtins.int, np.integer, slice)) or key is Ellipsis or key is None
    )
    if isinstance(key, tuple):
        is_basic = builtins.all(
            isinstance(k, (builtins.int, np.integer, slice)) or k is Ellipsis or k is None
            for k in key
        )
    if is_basic:
        expanded = _expand_key(key, x.ndim)
        out_split = _result_split(x, key)
        norm_key = _normalize_basic_key_physical(expanded, x)
        # does the key leave the split dim whole (full slice)? then the
        # physical buffer can be indexed directly and the (possibly padded)
        # split dim carries straight through — no relayout
        split_whole = False
        if x.split is not None:
            d = 0
            for k in expanded:
                if k is None:
                    continue
                if d == x.split:
                    split_whole = isinstance(k, slice) and k == slice(None)
                    break
                d += 1
        if split_whole:
            phys_key = []
            d = 0
            for k in norm_key:
                if k is None:
                    phys_key.append(None)
                    continue
                phys_key.append(slice(None) if d == x.split else k)
                d += 1
            result = x.larray[tuple(phys_key)]
            gshape = _basic_result_gshape(expanded, x)
            if result.ndim == 0:
                return DNDarray(
                    result, (), types.canonical_heat_type(result.dtype), None,
                    x.device, x.comm, True,
                )
            if out_split is not None and out_split >= result.ndim:
                out_split = None
            return DNDarray(result, gshape, x.dtype, out_split, x.device, x.comm, True)
        # keys are normalized against the LOGICAL extents, so they can never
        # reach the tail pad — index the physical buffer directly (compiled,
        # multi-host safe); the result is unpadded and re-laid-out below
        result = x.larray[norm_key]
        if result.ndim == 0:
            return DNDarray(
                result, (), types.canonical_heat_type(result.dtype), None, x.device, x.comm, True
            )
        if out_split is not None and out_split >= result.ndim:
            out_split = None
        return DNDarray.from_logical(result, out_split, x.device, x.comm)

    # --- general fallback (masks, mixed advanced keys) --------------------
    log = x._logical()
    result = log[key]
    out_split = _result_split(x, key)
    if out_split is not None and out_split >= result.ndim:
        out_split = None
    if result.ndim == 0:
        return DNDarray(
            result, (), types.canonical_heat_type(result.dtype), None, x.device, x.comm, True
        )
    return DNDarray.from_logical(result, out_split, x.device, x.comm)


def _basic_result_gshape(expanded, x: DNDarray) -> Tuple[int, ...]:
    """Logical result shape of a basic (slice/int/None) key."""
    gshape = []
    d = 0
    for k in expanded:
        if k is None:
            gshape.append(1)
            continue
        n = x.shape[d]
        if isinstance(k, slice):
            start, stop, step = k.indices(n)
            gshape.append(builtins.max(0, -(-(stop - start) // step) if step > 0 else -(-(start - stop) // -step)))
        # ints drop the dim
        d += 1
    return tuple(gshape)


def _host_fallback_warning(reason: str):
    warnings.warn(
        f"setitem: {reason} — falling back to a host numpy round-trip of the "
        "full global array. This gathers the array to the controller; avoid "
        "on large arrays.",
        UserWarning,
        stacklevel=4,
    )


def setitem(x: DNDarray, key, value) -> None:
    key = _normalize_key(key, x)
    if isinstance(value, DNDarray):
        if value.split is not None and jax.process_count() > 1:
            # compiled relayout — multi-host safe (values are at most the
            # size of the selected region); single-controller keeps the
            # cheaper logical slice
            value = value._replicated()
        else:
            value = value._logical()
    buf = x.larray

    if _is_bool_mask(key, x):
        val = jnp.asarray(value, dtype=buf.dtype)
        mask = jnp.asarray(key)
        padw = [(0, p - l) for p, l in zip(x.padded_shape, x.shape)]
        if x.pad_count:
            mask = jnp.pad(mask, padw, constant_values=False)
        if val.ndim == 0 or val.size == 1:
            new = jnp.where(mask, val.reshape(()), buf)
        elif val.shape == x.shape:
            valp = jnp.pad(val, padw) if x.pad_count else val
            new = jnp.where(mask, valp, buf)
        else:
            # ragged mask assignment, shard-side: the value's length is
            # STATIC, so each True position's value index is its rank among
            # True positions — one cumsum + static-shape gather + where, no
            # dynamic shapes and no host gather. Physical row-major order
            # skips pads (mask False there), so ranks follow logical
            # row-major order for any split (reference handles this
            # shard-side too, dndarray.py:1334-1549). One scalar sync
            # validates the count (numpy parity).
            val1 = val.reshape(-1)
            nnz = builtins.int(jnp.sum(mask))
            if builtins.int(val1.shape[0]) != nnz:
                raise ValueError(
                    f"cannot assign {builtins.int(val1.shape[0])} input "
                    f"values to the {nnz} output values where the mask is true"
                )
            if nnz == 0:
                return
            flatm = jnp.reshape(mask, (-1,))
            ranks = jnp.clip(jnp.cumsum(flatm) - 1, 0, val1.shape[0] - 1)
            taken = jnp.reshape(jnp.take(val1, ranks), buf.shape)
            new = jnp.where(mask, taken, buf)
        x.larray = new
        return

    # partial boolean mask over the leading dims: stays on device — pad the
    # mask with False up to the physical extents it covers
    if (
        hasattr(key, "dtype")
        and np.dtype(key.dtype) == np.bool_
        and 0 < getattr(key, "ndim", 0) < x.ndim
    ):
        mask = jnp.asarray(key)
        if tuple(mask.shape) == x.shape[: mask.ndim]:
            val = jnp.asarray(value, dtype=buf.dtype)
            if x.pad_count and x.split is not None and x.split < mask.ndim:
                padw = [
                    (0, x.padded_shape[d] - x.shape[d]) for d in range(mask.ndim)
                ]
                mask = jnp.pad(mask, padw, constant_values=False)
            try:
                x.larray = buf.at[mask].set(val)
                return
            except (TypeError, IndexError, ValueError):
                pass  # ragged values etc. — host fallback below

    # bool array inside a tuple key (e.g. ``x[mask, 2] = v``): stays on
    # device as a combined per-dim mask + rank-among-True value gather —
    # multi-host safe (the carried ISSUE 6 debt fix; the host fallback
    # below reads `_logical`, which refuses on multi-host padded arrays)
    if isinstance(key, tuple) and builtins.any(_is_bool_array(k) for k in key):
        if _setitem_bool_tuple(x, key, value):
            return
        if jax.process_count() > 1:
            # the forms the device path declines (negative-step slices,
            # n-D masks in tuples, broadcast-mismatched values) fall back
            # to numpy on the host-logical view, which a multi-host
            # topology cannot materialize — raise the contract clearly
            # HERE instead of surfacing _logical's generic padded-view
            # error (or a non-addressable fetch) from halfway down the
            # fallback (carried ISSUE 6 debt, closed ISSUE 8)
            raise NotImplementedError(
                f"setitem with a boolean array inside a tuple key is "
                f"multi-host only for 1-D masks combined with ints and "
                f"non-negative-step slices (shard-side rank-gather path); "
                f"key {key!r} needs the single-controller host fallback — "
                f"reformulate with a full-shape mask or ascending slices"
            )
        _host_fallback_warning(f"key {key!r} mixes mask/advanced entries")
        return _setitem_host_fallback(x, key, value)

    # basic / integer-array keys: normalize against logical extents and
    # update the physical buffer in place — pads are unreachable. Tuple keys
    # containing boolean arrays consume multiple dims per entry and skip the
    # per-dim normalization (host fallback handles them).
    normalizable = (
        isinstance(key, (builtins.int, np.integer, slice))
        or key is Ellipsis
        or _is_int_array(key)
        or (
            isinstance(key, tuple)
            and not builtins.any(_is_bool_array(k) for k in key)
        )
    )
    if normalizable:
        try:
            expanded = _expand_key(key, x.ndim)
            phys_key = _normalize_basic_key_physical(expanded, x)
            new = buf.at[phys_key].set(jnp.asarray(value, dtype=buf.dtype))
            x.larray = new
            return
        except (TypeError, IndexError, ValueError) as e:
            if isinstance(e, IndexError) and "out of bounds" in str(e):
                raise
            _host_fallback_warning(f"key {key!r} is not jnp-compatible ({e})")
    else:
        # un-normalizable keys (e.g. n-D bool arrays inside a tuple) must
        # NOT be applied to the padded physical buffer — negative/global
        # indices would resolve against the physical extent and write pads
        # silently
        _host_fallback_warning(f"key {key!r} mixes mask/advanced entries")
    return _setitem_host_fallback(x, key, value)


def _setitem_host_fallback(x: DNDarray, key, value) -> None:
    """Last-resort eager update: numpy on the host-logical view
    (single-controller only — `_logical` refuses on multi-host padded
    arrays rather than mis-computing)."""
    buf = x.larray

    def _np_key(k):
        if isinstance(k, tuple):
            return tuple(np.asarray(e) if isinstance(e, jnp.ndarray) else e for e in k)
        return np.asarray(k) if isinstance(k, jnp.ndarray) else k

    host = np.array(x._logical())
    host[_np_key(key)] = np.asarray(value)
    x.larray = DNDarray.from_logical(
        jnp.asarray(host, dtype=buf.dtype), x.split, x.device, x.comm, x.dtype
    ).larray


def _setitem_bool_tuple(x: DNDarray, key, value) -> builtins.bool:
    """``x[key] = value`` for a tuple key with exactly ONE 1-D boolean
    array among ints/slices, entirely on device (the carried edge-case
    debt ISSUE 6 closes; reference dndarray.py:1334-1549 does this
    shard-side too). Returns False for shapes this path does not cover —
    the caller falls back to the host.

    Construction: each key entry becomes a per-dim mask over the PHYSICAL
    buffer (the bool vector is padded with False, int/slice masks are
    bounded by the logical extent, so pads are never writable), the masks
    AND together, and the value lands either as a broadcast scalar
    (`where`) or by rank-among-True gather — the physical row-major rank
    of a selected position equals its numpy assignment order because with
    one advanced entry numpy keeps the result dim in place and pads are
    excluded. One scalar sync validates the value count (numpy parity)."""
    bool_pos = [i for i, k in enumerate(key) if _is_bool_array(k)]
    if len(bool_pos) != 1:
        return False
    bp = bool_pos[0]
    kb = np.asarray(key[bp])
    if kb.ndim != 1 or len(key) > x.ndim or x.ndim == 0:
        return False
    for i, k in enumerate(key):
        if i == bp:
            continue
        if not isinstance(k, (builtins.int, np.integer, slice)):
            return False
        if isinstance(k, slice) and k.step is not None and k.step < 0:
            # numpy assigns vector values along the REVERSED traversal of
            # a negative-step slice; the rank-among-True gather below is
            # ascending-order only — keep numpy semantics on the fallback
            return False
    if kb.shape != (x.shape[bp],):
        return False
    buf = x.larray
    nd = x.ndim
    sel = None
    for d in range(nd):
        n = x.shape[d]
        k = key[d] if d < len(key) else slice(None)
        iota = jax.lax.broadcasted_iota(jnp.int32, buf.shape, d)
        if d == bp:
            mvec = jnp.asarray(kb, dtype=jnp.bool_)
            pn = buf.shape[d]
            if pn != n:
                mvec = jnp.pad(mvec, (0, pn - n), constant_values=False)
            shape = [1] * nd
            shape[d] = pn
            m = jnp.broadcast_to(jnp.reshape(mvec, shape), buf.shape)
        elif isinstance(k, (builtins.int, np.integer)):
            kk = builtins.int(k)
            if kk < -n or kk >= n:
                raise IndexError(
                    f"index {kk} is out of bounds for axis {d} with size {n}"
                )
            m = iota == (kk + n if kk < 0 else kk)
        else:
            start, stop, step = k.indices(n)
            m = (iota >= start) & (iota < stop) & (
                (iota - start) % step == 0
            )
        sel = m if sel is None else (sel & m)
    val = jnp.asarray(value, dtype=buf.dtype)
    if val.ndim == 0 or val.size == 1:
        x.larray = jnp.where(sel, jnp.reshape(val, ()), buf)
        return True
    nnz = builtins.int(jnp.sum(sel))  # one scalar sync (numpy parity check)
    val1 = jnp.reshape(val, (-1,))
    if builtins.int(val1.shape[0]) != nnz:
        # partially-broadcast value shapes keep numpy's error/broadcast
        # semantics on the fallback path
        return False
    if nnz == 0:
        return True
    flat = jnp.reshape(sel, (-1,))
    ranks = jnp.clip(jnp.cumsum(flat) - 1, 0, val1.shape[0] - 1)
    taken = jnp.reshape(jnp.take(val1, ranks), buf.shape)
    x.larray = jnp.where(sel, taken, buf)
    return True


def _scatter_compact(comm: MeshCommunication, out_shape, dtype, dest, vals):
    """Scatter-compaction into a split=0 result of ``out_shape``. The
    scatter runs SPMD over the sharded dest/vals (XLA may keep its output
    replicated — forcing out_shardings on a scatter trips a GSPMD override
    assertion); one device_put lays the O(result)-sized output out split=0.
    Only result-sized traffic, never an input gather. Shared by nonzero and
    the boolean masked select."""
    out = jnp.zeros(out_shape, dtype=dtype).at[dest].set(vals, mode="drop")
    return jax.device_put(out, comm.sharding(0, len(out_shape)))


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of nonzero elements as an (nnz, ndim) array, distributed
    along axis 0 when the input is split (reference indexing.py `nonzero`,
    which stacks local torch.nonzero + offset).

    For split=0 inputs this is a DISTRIBUTED algorithm: mask the physical
    buffer (pads masked out), a distributed cumsum assigns every nonzero
    its global output row, and a sharded scatter compacts the multi-indices
    into the (nnz, ndim) split=0 result — only the scalar nnz crosses to
    the host, because output *shape* is host metadata (same design as
    `unique`). The row-major physical order IS the global order when
    split=0 (tail-pad invariant), so results match numpy's ordering."""
    if x.ndim > 0 and x.split == 0 and x.comm.size > 1:
        comm = x.comm
        buf = x._masked(0)
        flat = jnp.reshape(buf, (-1,))
        mask = flat != 0
        nnz = builtins.int(mask.sum())
        nnz_pad = comm.padded_size(nnz)
        # global output row per element; masked-off elements are routed to
        # row nnz_pad, which mode='drop' discards
        dest = jnp.where(mask, jnp.cumsum(mask) - 1, nnz_pad)
        multi = jnp.unravel_index(jnp.arange(flat.shape[0]), buf.shape)
        vals = jnp.stack(multi, axis=1).astype(jnp.int64)
        res = _scatter_compact(comm, (nnz_pad, x.ndim), jnp.int64, dest, vals)
        return DNDarray(
            res, (nnz, x.ndim), types.int64, 0, x.device, x.comm, True
        )
    log = x._logical()
    idx = jnp.stack(jnp.nonzero(log), axis=1) if x.ndim > 0 else jnp.nonzero(log)[0][:, None]
    split = 0 if x.split is not None else None
    return DNDarray.from_logical(idx, split, x.device, x.comm)


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Three-arg elementwise select, or one-arg nonzero (reference
    indexing.py `where`)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y must be given")
    if not isinstance(cond, DNDarray):
        from . import factories

        cond = factories.array(cond)
    from .stride_tricks import broadcast_shape

    operands = [cond, x, y]
    dnd = [o for o in operands if isinstance(o, DNDarray)]
    comm, device = dnd[0].comm, dnd[0].device
    shapes = [o.shape if isinstance(o, DNDarray) else () for o in operands]
    out_shape = shapes[0]
    for s in shapes[1:]:
        out_shape = broadcast_shape(out_shape, s)
    ndim_out = len(out_shape)
    splits = []
    for o in operands:
        if isinstance(o, DNDarray) and o.split is not None:
            splits.append(o.split + (ndim_out - o.ndim))
    out_split = splits[0] if splits else None
    if builtins.any(s != out_split for s in splits):
        raise ValueError("operands are distributed along different axes")
    padded = builtins.any(isinstance(o, DNDarray) and o.pad_count for o in operands)

    def phys(o):
        if not isinstance(o, DNDarray):
            return o
        if padded and o.pad_count == 0 and out_split is not None and o.split is None:
            own = out_split - (ndim_out - o.ndim)
            if own >= 0 and o.shape[own] == out_shape[out_split]:
                P = comm.padded_size(out_shape[out_split])
                pad = [(0, 0)] * o.ndim
                pad[own] = (0, P - o.shape[own])
                return jnp.pad(o.larray, pad)
        return o.larray

    result = jnp.where(phys(cond), phys(x), phys(y))
    return DNDarray(
        result, out_shape, types.canonical_heat_type(result.dtype), out_split, device, comm, True
    )
