"""Global indexing: getitem/setitem engine + nonzero/where.

Re-design of the reference's gnarliest code path (reference:
heat/core/dndarray.py:661-1549 `__getitem__`/`__setitem__` translate global
keys to per-rank local keys chunk by chunk; heat/core/indexing.py nonzero/
where). Under a single controller the global array is addressable, so
indexing is performed on the *logical* global view with jnp/numpy semantics,
and only the result's split metadata needs Heat's rules:

* slicing keeps the split axis distributed (possibly shifted by dropped or
  inserted dims);
* an integer index on the split axis collapses it → result replicated;
* a full-shape boolean mask yields a 1-D result distributed along 0;
* advanced (integer-array) indexing replicates (conservative; reference
  gathers too).
"""

from __future__ import annotations

import builtins
from typing import Any, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def _normalize_key(key, x: DNDarray):
    """Convert DNDarray keys to jnp arrays, leave the rest untouched."""
    if isinstance(key, DNDarray):
        return key._logical()
    if isinstance(key, tuple):
        return tuple(_normalize_key(k, x) for k in key)
    if isinstance(key, list):
        return jnp.asarray(key)
    return key


def _result_split(x: DNDarray, key) -> Optional[int]:
    """Split axis of an indexing result per the rules in the module
    docstring."""
    if x.split is None:
        return None
    if not isinstance(key, tuple):
        key = (key,)
    # full-shape boolean mask
    if len(key) == 1 and hasattr(key[0], "dtype") and np.dtype(key[0].dtype) == np.bool_ \
            and getattr(key[0], "ndim", 0) == x.ndim:
        return 0
    # expand ellipsis
    n_specified = builtins.sum(1 for k in key if k is not None and k is not Ellipsis)
    expanded = []
    for k in key:
        if k is Ellipsis:
            expanded.extend([slice(None)] * (x.ndim - n_specified))
        else:
            expanded.append(k)
    while builtins.sum(1 for k in expanded if k is not None) < x.ndim:
        expanded.append(slice(None))

    in_dim = 0
    out_dim = 0
    for k in expanded:
        if k is None:
            out_dim += 1
            continue
        if isinstance(k, slice):
            if in_dim == x.split:
                return out_dim
            in_dim += 1
            out_dim += 1
        elif isinstance(k, (builtins.int, np.integer)):
            if in_dim == x.split:
                return None
            in_dim += 1
        else:
            # advanced indexing — replicate (conservative)
            return None
    return None


def getitem(x: DNDarray, key) -> DNDarray:
    key = _normalize_key(key, x)
    log = x._logical()
    try:
        result = log[key]
    except IndexError:
        raise
    out_split = _result_split(x, key)
    if out_split is not None and out_split >= result.ndim:
        out_split = None
    if result.ndim == 0:
        return DNDarray(
            result, (), types.canonical_heat_type(result.dtype), None, x.device, x.comm, True
        )
    return DNDarray.from_logical(result, out_split, x.device, x.comm)


def setitem(x: DNDarray, key, value) -> None:
    key = _normalize_key(key, x)
    if isinstance(value, DNDarray):
        value = value._logical()
    log = x._logical()
    is_bool_mask = (
        hasattr(key, "dtype")
        and np.dtype(key.dtype) == np.bool_
        and getattr(key, "ndim", 0) == x.ndim
    )
    if is_bool_mask:
        val = jnp.asarray(value, dtype=log.dtype)
        if val.ndim == 0 or val.shape == log.shape or val.size == 1:
            new = jnp.where(key, jnp.broadcast_to(val, log.shape) if val.ndim else val, log)
        else:
            # ragged mask assignment — host fallback (documented eager path)
            host = np.asarray(log)
            host[np.asarray(key)] = np.asarray(val)
            new = jnp.asarray(host)
    else:
        try:
            new = log.at[key].set(jnp.asarray(value, dtype=log.dtype))
        except (TypeError, IndexError, ValueError):
            host = np.asarray(log)
            host[key if not isinstance(key, jnp.ndarray) else np.asarray(key)] = np.asarray(value)
            new = jnp.asarray(host, dtype=log.dtype)
    repacked = DNDarray.from_logical(new, x.split, x.device, x.comm, x.dtype)
    x._DNDarray__internal_set(repacked.larray, x.shape, x.split)


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of nonzero elements as an (nnz, ndim) array, distributed along
    axis 0 when the input is split (reference indexing.py `nonzero`, which
    stacks local torch.nonzero + offset)."""
    from . import factories

    log = x._logical()
    idx = jnp.stack(jnp.nonzero(log), axis=1) if x.ndim > 0 else jnp.nonzero(log)[0][:, None]
    split = 0 if x.split is not None else None
    return DNDarray.from_logical(idx, split, x.device, x.comm)


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Three-arg elementwise select, or one-arg nonzero (reference
    indexing.py `where`)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y must be given")
    if not isinstance(cond, DNDarray):
        from . import factories

        cond = factories.array(cond)
    from .stride_tricks import broadcast_shape

    operands = [cond, x, y]
    dnd = [o for o in operands if isinstance(o, DNDarray)]
    comm, device = dnd[0].comm, dnd[0].device
    shapes = [o.shape if isinstance(o, DNDarray) else () for o in operands]
    out_shape = shapes[0]
    for s in shapes[1:]:
        out_shape = broadcast_shape(out_shape, s)
    ndim_out = len(out_shape)
    splits = []
    for o in operands:
        if isinstance(o, DNDarray) and o.split is not None:
            splits.append(o.split + (ndim_out - o.ndim))
    out_split = splits[0] if splits else None
    if builtins.any(s != out_split for s in splits):
        raise ValueError("operands are distributed along different axes")
    padded = builtins.any(isinstance(o, DNDarray) and o.pad_count for o in operands)

    def phys(o):
        if not isinstance(o, DNDarray):
            return o
        if padded and o.pad_count == 0 and out_split is not None and o.split is None:
            own = out_split - (ndim_out - o.ndim)
            if own >= 0 and o.shape[own] == out_shape[out_split]:
                P = comm.padded_size(out_shape[out_split])
                pad = [(0, 0)] * o.ndim
                pad[own] = (0, P - o.shape[own])
                return jnp.pad(o.larray, pad)
        return o.larray

    result = jnp.where(phys(cond), phys(x), phys(y))
    return DNDarray(
        result, out_shape, types.canonical_heat_type(result.dtype), out_split, device, comm, True
    )
