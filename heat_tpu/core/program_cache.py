"""Process-global compiled-program registry — the one choke point every
jitted program in the framework routes through.

Motivation (ISSUE 3 / PAPERS.md "Memory-efficient array redistribution"):
Heat's MPI choreography becomes *compiled XLA programs* in this port, so
compile time and program reuse are first-class performance axes. Before this
module, three sites memoized their jitted programs behind ad-hoc
``functools.lru_cache``\\ s (each with its own key convention) while ~18
other ``jax.jit`` call sites rebuilt fresh closures per invocation — every
``resplit``, repeated factory assembly, and re-entered kernel retraced and
recompiled an identical program. Now:

* :func:`cached_program` memoizes jitted executables in one process-global
  LRU registry keyed on ``(site, comm identity, static config, donation)``
  — input *avals* are still handled by jax's own dispatch inside each
  cached wrapper, so one registry entry serves every shape that reaches
  the same program builder while distinct static configs get distinct
  entries. Steady-state dispatch is a dict lookup.
* Telemetry counters (``program_cache.hits`` / ``.misses`` /
  ``.evictions`` plus per-site retrace counts) feed
  :func:`heat_tpu.telemetry.report.summarize` and the Chrome trace (each
  retrace/eviction is an instant event on the *events* track).
* The registry size is tunable via ``HEAT_TPU_PROGRAM_CACHE`` (max
  entries; least-recently-used programs are evicted — the *executables*
  they held are additionally bounded by jax's own caches, which the test
  conftest clears per module).
* ``donate=(argnums...)`` passes through to ``jax.jit(donate_argnums=...)``
  so callers whose source buffer is dead after the call (in-place
  ``resplit_``, ``out=`` paths) let XLA reuse the input memory instead of
  holding source + destination live. Donation is part of the cache key: a
  donating and a non-donating caller never share an executable.
* The fusion engine routes every flushed elementwise chain through site
  ``fusion``; Fusion 2.0 (ISSUE 7) adds ``fusion_reduce`` (chain+reduction
  map+reduce programs, keyed on chain signature + reduce op/axis/neutral)
  and ``fusion_moments`` (chain grafted into the pallas column-moments
  kernel) — absorption reuses this registry, so a repeated fused reduction
  is the same dict-lookup dispatch as any cached program.
* The site/key signature is shared with the HLO collective auditor
  (:func:`heat_tpu.telemetry.hlo.audit_call` sites build their memo key via
  :func:`program_key`), so an audited program and the cached program that
  actually executes carry ONE signature — the audit lowers the very same
  jitted callable the dispatch path runs.

Persistent (cross-process) compilation cache
--------------------------------------------
Orthogonal to the in-process registry, :func:`enable_persistent_cache`
wires JAX's on-disk XLA compilation cache: with
``HEAT_TPU_COMPILE_CACHE=<dir>`` in the environment (read at import, the
same activation pattern as ``HEAT_TPU_TELEMETRY``), repeated CI shards and
benchmark sweep processes skip backend compiles entirely — the measured
dominant cost of the tier-1 suite. ``scripts/run_ci.sh`` and
``benchmarks/_harness.py`` enable it by default; see
docs/TUNING_RUNBOOK.md for the knob semantics.
"""

from __future__ import annotations

import os
import threading
import warnings
from collections import OrderedDict
from typing import Any, Callable, Optional, Sequence, Tuple

import jax

from heat_tpu import _knobs as knobs

from .. import resilience, telemetry

__all__ = [
    "cached_program",
    "program_key",
    "site_stats",
    "stats",
    "reset",
    "clear",
    "enable_persistent_cache",
    "persistent_cache_dir",
    "DEFAULT_MAXSIZE",
]

# Default registry capacity. Entries are jit *wrappers* (closures + jit
# machinery, not executables), so the per-entry footprint is small; the knob
# exists for long-lived services that sweep unbounded shape families.
DEFAULT_MAXSIZE = 512

# A donated buffer whose layout cannot alias the output (e.g. a relayout
# whose physical shapes differ) makes XLA warn "Some donated buffers were
# not usable" at lowering time. The donation is still correct — the
# framework caller declared the buffer dead — so for programs built HERE
# with donate= the warning is pure noise. It is suppressed around those
# calls only (see cached_program), never process-globally: user code
# keeps the diagnostic for its own donate_argnums mistakes.
_DONATION_NOISE = "Some donated buffers were not usable"

_LOCK = threading.RLock()
_PROGRAMS: "OrderedDict[Tuple, Callable]" = OrderedDict()
_STATS = {"hits": 0, "misses": 0, "evictions": 0}
_SITE_STATS: dict = {}


def _maxsize() -> int:
    raw = knobs.raw("HEAT_TPU_PROGRAM_CACHE", "").strip()
    if raw:
        try:
            n = int(raw)
            if n > 0:
                return n
        except ValueError:
            pass
    return DEFAULT_MAXSIZE


def program_key(
    site: str,
    key: Any,
    comm: Any = None,
    donate: Sequence[int] = (),
) -> Tuple:
    """The full registry key for one program site — also the memo key the
    HLO auditor uses for the same program, so audited and cached programs
    share one signature. ``comm`` participates by identity (two
    communicators over the same devices are distinct meshes to XLA too);
    ``key`` is the caller's static config (shapes, dtypes, splits, flags —
    anything that changes the traced program).

    The tiered-lowering state (ISSUE 15: ``HEAT_TPU_HIERARCHICAL`` +
    topology + cross-tier precision) is appended HERE, once, for every
    site: any program built over the MeshCommunication wrappers changes
    shape under the knob, and threading the token through forty caller
    keys is exactly the drift this chokepoint exists to prevent. Flat
    (the default) contributes the constant ``("flat",)``."""
    return (site, comm, key, tuple(donate), _topology_token(comm))


def _topology_token(comm: Any) -> Tuple:
    """The ISSUE 15 cache-token component (see
    :func:`heat_tpu.core.topology.cache_token`); ``("flat",)`` whenever
    tiered lowering is off or unresolvable — the zero-overhead default
    is one knob read."""
    try:
        from . import topology

        p = getattr(comm, "size", None)
        if p is None:
            p = jax.device_count()
        return topology.cache_token(int(p))
    except Exception:  # never let key construction take dispatch down
        return ("flat",)


def cached_program(
    site: str,
    key: Any,
    build: Callable[[], Callable],
    *,
    comm: Any = None,
    out_shardings: Any = None,
    donate: Sequence[int] = (),
    static_argnums: Any = None,
    static_argnames: Any = None,
) -> Callable:
    """Return the memoized jitted program for ``(site, comm, key, donate)``,
    building and jitting it on first use.

    ``build()`` returns the plain python callable to compile — it runs only
    on a registry miss (and must therefore be cheap and side-effect free;
    no tracing happens until the returned program is called).
    ``out_shardings`` / ``static_argnums`` / ``static_argnames`` pass
    through to ``jax.jit``; ``donate`` becomes ``donate_argnums``. The
    returned wrapper handles aval-level dispatch itself, so callers key
    only on *static config* — two calls with the same key but different
    shapes share one registry entry and retrace inside it.

    This is the ONLY sanctioned ``jax.jit`` site in the framework
    (enforced by ``tests/test_no_stray_jit.py``).
    """
    donate = tuple(donate)
    full_key = program_key(site, key, comm=comm, donate=donate)
    if full_key not in _PROGRAMS and knobs.get("HEAT_TPU_AUTOTUNE"):
        # measured-feedback autotuner (ISSUE 11): a registry miss is the
        # cold path (a trace+compile follows), so the tuning-DB consult —
        # a memoized warm start that installs persisted winners into the
        # knob overlay — costs nothing in steady state. Runs OUTSIDE
        # _LOCK: the first warm start may scan an on-disk DB, and
        # holding the registry lock through that would stall concurrent
        # hit-path lookups. The lock-free probe can race a concurrent
        # insert into a false miss; that costs one memoized dict check.
        # Default-off, dispatch is bit-for-bit the untuned path: the hit
        # path pays one dict probe that short-circuits before the flag
        # read, no DB is touched, no new compiles.
        from .. import autotune as _autotune

        _autotune.on_program_miss(site)
    evicted = 0
    miss = False
    with _LOCK:
        fn = _PROGRAMS.get(full_key)
        srow = _SITE_STATS.setdefault(site, {"hits": 0, "misses": 0})
        if fn is not None:
            _PROGRAMS.move_to_end(full_key)
            _STATS["hits"] += 1
            srow["hits"] += 1
        else:
            miss = True
            _STATS["misses"] += 1
            srow["misses"] += 1
            jit_kwargs: dict = {"donate_argnums": donate}
            if out_shardings is not None:
                jit_kwargs["out_shardings"] = out_shardings
            if static_argnums is not None:
                jit_kwargs["static_argnums"] = static_argnums
            if static_argnames is not None:
                jit_kwargs["static_argnames"] = static_argnames
            fn = jax.jit(build(), **jit_kwargs)
            if donate:
                fn = _quiet_donation(fn)
            # resilience dispatch wrapper (ISSUE 5): disarmed it is one
            # flag check; armed, every execution of this program runs the
            # fault injector, the HBM preflight, and the transient-retry
            # guard. Wrapped ONCE here, so the hit path stays a dict
            # lookup returning the already-wrapped callable.
            fn = resilience.wrap_program(site, fn, donated=bool(donate))
            maxsize = _maxsize()
            while len(_PROGRAMS) >= maxsize:
                _PROGRAMS.popitem(last=False)
                _STATS["evictions"] += 1
                evicted += 1
            _PROGRAMS[full_key] = fn
    if telemetry.enabled():
        reg = telemetry.get_registry()
        if miss:
            reg.add("program_cache.misses", 1)
            reg.add(f"program_cache.retrace.{site}", 1)
            # instant event → the Chrome trace's *events* track: when and
            # where a retrace happened (the expensive path)
            reg.emit("program_cache", site, event="retrace", key=repr(key))
        else:
            reg.add("program_cache.hits", 1)
        if evicted:
            reg.add("program_cache.evictions", evicted)
            reg.emit("program_cache", site, event="eviction", count=evicted)
    return fn


def _quiet_donation(jitted: Callable) -> Callable:
    """Wrap a donating jitted program so the lowering-time "donated
    buffers were not usable" warning is suppressed for ITS calls only.
    ``lower`` is forwarded so the HLO auditor can still AOT-compile the
    wrapped program."""

    def call(*args, **kwargs):
        with warnings.catch_warnings():
            warnings.filterwarnings("ignore", message=_DONATION_NOISE)
            return jitted(*args, **kwargs)

    call.lower = jitted.lower
    return call


def stats() -> dict:
    """Snapshot of the registry counters:
    ``{"hits", "misses", "evictions", "size", "maxsize", "sites"}`` with
    per-site hit/miss (retrace) counts under ``sites``."""
    with _LOCK:
        return {
            "hits": _STATS["hits"],
            "misses": _STATS["misses"],
            "evictions": _STATS["evictions"],
            "size": len(_PROGRAMS),
            "maxsize": _maxsize(),
            "sites": {s: dict(row) for s, row in _SITE_STATS.items()},
        }


def site_stats(prefix: str) -> dict:
    """Aggregated ``{"hits", "misses"}`` over every site whose name
    starts with ``prefix`` — e.g. ``site_stats("serve.")`` is the
    serving front end's zero-recompile-after-warmup oracle (a steady
    state shows only the hit counter moving)."""
    with _LOCK:
        out = {"hits": 0, "misses": 0}
        for s, row in _SITE_STATS.items():
            if s.startswith(prefix):
                out["hits"] += row["hits"]
                out["misses"] += row["misses"]
        return out


def reset() -> None:
    """Drop every cached program and zero the counters (tests)."""
    with _LOCK:
        _PROGRAMS.clear()
        _STATS.update(hits=0, misses=0, evictions=0)
        _SITE_STATS.clear()


clear = reset


# -- persistent (cross-process) XLA compilation cache -------------------------

_PERSISTENT_DIR: Optional[str] = None


def enable_persistent_cache(path: str) -> str:
    """Point JAX's on-disk compilation cache at ``path`` (created if
    missing) and drop the min-compile-time threshold to 0 so every
    executable is eligible — the tier-1 suite and the bench sweeps are
    dominated by many *small* compiles, exactly the entries the default
    1-second threshold skips. Returns the path. Idempotent."""
    global _PERSISTENT_DIR
    path = os.fspath(path)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _PERSISTENT_DIR = path
    return path


def persistent_cache_dir() -> Optional[str]:
    """The active on-disk compilation cache directory, or None."""
    return _PERSISTENT_DIR


# Environment activation (mirrors HEAT_TPU_TELEMETRY): HEAT_TPU_COMPILE_CACHE
# names the cache directory; `import heat_tpu` is enough to enable it.
_env_dir = knobs.raw("HEAT_TPU_COMPILE_CACHE", "").strip()
if _env_dir:
    try:
        enable_persistent_cache(_env_dir)
    except Exception as _e:  # pragma: no cover — bad path must not kill import
        warnings.warn(
            f"heat_tpu.program_cache: cannot enable persistent compile "
            f"cache at {_env_dir!r} ({_e}); continuing without it"
        )
del _env_dir
