"""Logical tests and reductions (reference: heat/core/logical.py)."""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from . import types
from ._operations import binary_op, local_op, reduce_op
from .dndarray import DNDarray

__all__ = [
    "all",
    "allclose",
    "any",
    "isclose",
    "isfinite",
    "isinf",
    "isnan",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "signbit",
]


def all(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """True where all elements (along axis) are truthy (reference
    logical.py `all`: local all + Allreduce(LAND)). Passed as the bare
    ``jnp.all`` — a lambda wrapper would decline Fusion 2.0 absorption on
    every pending chain (ISSUE 7 fallback audit)."""
    return reduce_op(jnp.all, x, axis, neutral=True, out=out, keepdims=keepdims)


def allclose(x: DNDarray, y: DNDarray, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> bool:
    """Scalar closeness test (reference logical.py:144: local allclose +
    Allreduce(LAND))."""
    res = isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)
    return bool(all(res).item())


def any(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """True where any element (along axis) is truthy (reference logical.py
    `any`; bare ``jnp.any`` so pending chains absorb — see :func:`all`)."""
    return reduce_op(jnp.any, x, axis, neutral=False, out=out, keepdims=keepdims)


def isclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> DNDarray:
    """Elementwise closeness (reference logical.py:240). Tolerances ride as
    static fn_kwargs (not a closure) so isclose joins fused chains."""
    return binary_op(
        jnp.isclose, x, y,
        fn_kwargs={"rtol": rtol, "atol": atol, "equal_nan": equal_nan},
    )


def isfinite(x) -> DNDarray:
    return local_op(jnp.isfinite, x)


def isinf(x) -> DNDarray:
    return local_op(jnp.isinf, x)


def isnan(x) -> DNDarray:
    return local_op(jnp.isnan, x)


def isneginf(x, out=None) -> DNDarray:
    return local_op(jnp.isneginf, x, out)


def isposinf(x, out=None) -> DNDarray:
    return local_op(jnp.isposinf, x, out)


def logical_and(t1, t2) -> DNDarray:
    return binary_op(jnp.logical_and, t1, t2)


def logical_not(t, out=None) -> DNDarray:
    return local_op(jnp.logical_not, t, out)


def logical_or(t1, t2) -> DNDarray:
    return binary_op(jnp.logical_or, t1, t2)


def logical_xor(t1, t2) -> DNDarray:
    return binary_op(jnp.logical_xor, t1, t2)


def signbit(x, out=None) -> DNDarray:
    """True where the sign bit is set (reference logical.py `signbit`)."""
    return local_op(jnp.signbit, x, out)


DNDarray.all = lambda self, axis=None, out=None, keepdims=False: all(self, axis, out, keepdims)
DNDarray.any = lambda self, axis=None, out=None, keepdims=False: any(self, axis, out, keepdims)
DNDarray.allclose = lambda self, other, rtol=1e-05, atol=1e-08, equal_nan=False: allclose(
    self, other, rtol, atol, equal_nan
)
DNDarray.isclose = lambda self, other, rtol=1e-05, atol=1e-08, equal_nan=False: isclose(
    self, other, rtol, atol, equal_nan
)
