"""sklearn-style estimator base classes (reference: heat/core/base.py:13-267)."""

from __future__ import annotations

import inspect
from typing import Any, Dict, List, Optional

from .dndarray import DNDarray

__all__ = [
    "BaseEstimator",
    "ClassificationMixin",
    "ClusteringMixin",
    "RegressionMixin",
    "TransformMixin",
    "is_classifier",
    "is_estimator",
    "is_regressor",
    "is_transformer",
]


class BaseEstimator:
    """Base for all estimators: parameter introspection get/set (reference
    base.py:13)."""

    @classmethod
    def _parameter_names(cls) -> List[str]:
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        return [
            p.name
            for p in sig.parameters.values()
            if p.name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        ]

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        """Estimator parameters by name (reference base.py:28)."""
        params = {}
        for key in self._parameter_names():
            value = getattr(self, key, None)
            if deep and hasattr(value, "get_params"):
                for sub_key, sub_value in value.get_params().items():
                    params[f"{key}__{sub_key}"] = sub_value
            params[key] = value
        return params

    def set_params(self, **params) -> "BaseEstimator":
        """Set estimator parameters (reference base.py:54)."""
        if not params:
            return self
        valid = self.get_params(deep=True)
        for key, value in params.items():
            key, _, sub_key = key.partition("__")
            if key not in valid:
                raise ValueError(f"Invalid parameter {key} for estimator {self}")
            if sub_key:
                getattr(self, key).set_params(**{sub_key: value})
            else:
                setattr(self, key, value)
        return self

    def __repr__(self, indent: int = 1) -> str:
        params = ", ".join(f"{k}={v!r}" for k, v in self.get_params(deep=False).items())
        return f"{self.__class__.__name__}({params})"


class ClassificationMixin:
    """fit/predict contract for classifiers (reference base.py:98)."""

    def fit(self, x: DNDarray, y: DNDarray):
        raise NotImplementedError()

    def fit_predict(self, x: DNDarray, y: DNDarray) -> DNDarray:
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x: DNDarray) -> DNDarray:
        raise NotImplementedError()


class TransformMixin:
    """fit/transform contract for transformers (reference base.py:176)."""

    def fit(self, x: DNDarray):
        raise NotImplementedError()

    def fit_transform(self, x: DNDarray) -> DNDarray:
        self.fit(x)
        return self.transform(x)

    def transform(self, x: DNDarray) -> DNDarray:
        raise NotImplementedError()


class ClusteringMixin:
    """fit/fit_predict contract for clusterers (reference base.py:145)."""

    def fit(self, x: DNDarray):
        raise NotImplementedError()

    def fit_predict(self, x: DNDarray) -> DNDarray:
        self.fit(x)
        return self.predict(x)


class RegressionMixin:
    """fit/predict contract for regressors (reference base.py:?)."""

    def fit(self, x: DNDarray, y: DNDarray):
        raise NotImplementedError()

    def fit_predict(self, x: DNDarray, y: DNDarray) -> DNDarray:
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x: DNDarray) -> DNDarray:
        raise NotImplementedError()


def is_classifier(estimator: Any) -> bool:
    return isinstance(estimator, ClassificationMixin)


def is_estimator(estimator: Any) -> bool:
    return isinstance(estimator, BaseEstimator)


def is_regressor(estimator: Any) -> bool:
    return isinstance(estimator, RegressionMixin)


def is_transformer(estimator: Any) -> bool:
    return isinstance(estimator, TransformMixin)
