"""Statistical reductions (reference: heat/core/statistics.py, 18 exports).

The reference implements these with custom MPI reduction ops (packed
(value,index) buffers for argmin/argmax, statistics.py:1139-1207) and
hand-rolled moment merges (Welford-style combine :803-828, :1729-1758). Here
each is a masked jnp reduction; XLA derives the cross-shard combines. The
moment computations (var/skew/kurtosis) are two-pass — numerically stronger
than the reference's single-pass merge and free on TPU since the passes fuse.
"""

from __future__ import annotations

import builtins
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from ._operations import binary_op, local_op, reduce_op
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

__all__ = [
    "argmax",
    "argmin",
    "average",
    "bincount",
    "chunk_moments",
    "cov",
    "histc",
    "histogram",
    "kurtosis",
    "max",
    "maximum",
    "mean",
    "median",
    "min",
    "minimum",
    "nanmax",
    "nanmean",
    "nanmin",
    "nanstd",
    "nanvar",
    "percentile",
    "skew",
    "std",
    "var",
]


def _neutral_extreme(x: DNDarray, is_max: bool):
    if issubclass(x.dtype, types.integer):
        info = types.iinfo(x.dtype)
        return info.min if is_max else info.max
    return -float("inf") if is_max else float("inf")


def _arg_reduce(x: DNDarray, axis, is_max: bool, out=None, keepdims: bool = False) -> DNDarray:
    fn = jnp.argmax if is_max else jnp.argmin
    neutral = _neutral_extreme(x, is_max)
    if axis is None:
        buf = x._masked(neutral)
        flat_idx = fn(buf)
        if x.pad_count:
            coords = jnp.unravel_index(flat_idx, buf.shape)
            flat_idx = jnp.ravel_multi_index(coords, x.shape, mode="clip")
        res = flat_idx.astype(jnp.int64)
        if keepdims:
            res = jnp.reshape(res, (1,) * x.ndim)
            out_arr = DNDarray(res, (1,) * x.ndim, types.int64, None, x.device, x.comm, True)
        else:
            out_arr = DNDarray(res, (), types.int64, None, x.device, x.comm, True)
        if out is not None:
            out.larray = res.astype(out.dtype.jnp_type())
            return out
        return out_arr
    axis = sanitize_axis(x.shape, axis)
    buf = x._masked(neutral) if (x.split == axis and x.pad_count) else x.larray
    result = fn(buf, axis=axis)
    if keepdims:
        result = jnp.expand_dims(result, axis)
    split = x.split
    if split is None or split == axis:
        out_split = None if not keepdims or split == axis else split
        out_split = None
    else:
        out_split = split if keepdims else split - (1 if axis < split else 0)
    if keepdims:
        out_gshape = tuple(1 if d == axis else s for d, s in enumerate(x.shape))
    else:
        out_gshape = tuple(s for d, s in enumerate(x.shape) if d != axis)
    res = DNDarray(
        result.astype(jnp.int64), out_gshape, types.int64, out_split, x.device, x.comm, True
    )
    if out is not None:
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def argmax(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Index of the maximum (reference statistics.py `argmax` via custom
    MPI_ARGMAX reduction)."""
    return _arg_reduce(x, axis, True, out, keepdims)


def argmin(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Index of the minimum (reference statistics.py `argmin`)."""
    return _arg_reduce(x, axis, False, out, keepdims)


def _reduced_count(x: DNDarray, axis) -> int:
    if axis is None:
        return x.size
    if isinstance(axis, builtins.int):
        axes = (axis,)
    else:
        axes = tuple(axis)
    n = 1
    for a in axes:
        n *= x.shape[a]
    return n


def average(x: DNDarray, axis=None, weights: Optional[DNDarray] = None, returned: bool = False):
    """Weighted average (reference statistics.py `average`)."""
    if weights is None:
        avg = mean(x, axis)
        from . import factories

        n = _reduced_count(x, sanitize_axis(x.shape, axis) if axis is not None else None)
        wsum = factories.full(avg.shape if avg.ndim else (), float(n), dtype=types.float32,
                              split=avg.split if avg.ndim else None, device=x.device, comm=x.comm)
        return (avg, wsum) if returned else avg
    from . import arithmetics

    if weights.ndim == 1 and axis is not None and isinstance(axis, builtins.int):
        axis = sanitize_axis(x.shape, axis)
        if weights.shape[0] != x.shape[axis]:
            raise ValueError("Length of weights not compatible with specified axis")
        shape = [1] * x.ndim
        shape[axis] = weights.shape[0]
        if axis == x.split and x.comm.size > 1:
            # the weights run along the SPLIT axis — align them to x's
            # chunking (same extent → same tail pads) instead of
            # replicating an axis-length vector; the broadcast multiply
            # then stays shard-local
            wv = weights if weights.split == 0 else weights.resplit(0)
            w = DNDarray(
                jnp.reshape(wv.larray, [1] * axis + [wv.larray.shape[0]] + [1] * (x.ndim - axis - 1)),
                tuple(shape), wv.dtype, axis, x.device, x.comm, True,
            )
        else:
            w = DNDarray.from_logical(
                jnp.reshape(weights._logical(), shape), None, x.device, x.comm
            )
    elif weights.shape == x.shape:
        w = weights
    else:
        raise TypeError("Axis must be specified when shapes of x and weights differ")
    num = arithmetics.sum(arithmetics.mul(x, w), axis)
    den = arithmetics.sum(w, axis)
    avg = arithmetics.div(num, den)
    if returned:
        if tuple(den.shape) != tuple(avg.shape):
            # numpy contract: sum_of_weights carries the average's shape
            from . import factories

            den = arithmetics.mul(
                den,
                factories.ones(
                    avg.shape, dtype=den.dtype, split=avg.split,
                    device=x.device, comm=x.comm,
                ),
            )
        return avg, den
    return avg


def _aligned_weights_buf(x: DNDarray, weights):
    """``weights`` as a physical buffer aligned with ``x``'s shards (resplit
    if laid out differently), or None. Pads need no masking here — callers
    zero them via the validity mask."""
    if weights is None:
        return None
    if isinstance(weights, DNDarray):
        if tuple(weights.shape) != tuple(x.shape):
            raise ValueError("weights must have the same shape as the input")
        if weights.split != x.split:
            weights = weights.resplit(x.split)
        return weights.larray
    w = np.asarray(weights)
    if tuple(w.shape) != tuple(x.shape):
        raise ValueError("weights must have the same shape as the input")
    from . import factories

    # route raw arrays through the factory so they pick up x's tail padding
    # and sharding (a bare device_put of the logical shape would not divide
    # over the mesh when x is padded)
    return factories.array(w, split=x.split, device=x.device, comm=x.comm).larray


def _valid_weights(x: DNDarray, wbuf):
    """Per-element weights over the PHYSICAL shape: the given weights (or 1)
    at logical positions, 0 at tail pads — how pad entries drop out of a
    scatter/histogram without any gather."""
    dt = wbuf.dtype if wbuf is not None else jnp.float64
    ones = jnp.ones(x.larray.shape, dtype=dt) if wbuf is None else wbuf.astype(dt)
    if x.pad_count == 0:
        return ones
    idx = jax.lax.broadcasted_iota(jnp.int32, x.larray.shape, x.split)
    return jnp.where(idx < x.shape[x.split], ones, jnp.zeros((), dtype=dt))


def _global_minmax(x: DNDarray):
    """(min, max) of a DNDarray's logical values — one device dispatch pair,
    ONE host sync. Pads are neutralized per-extreme (dtype max on the
    min side, dtype min on the max side), so any split/pad layout works."""
    from .manipulations import _sort_fill

    if x.pad_count:
        lo_buf = x._masked(_sort_fill(x, descending=False))
        hi_buf = x._masked(_sort_fill(x, descending=True))
    else:
        lo_buf = hi_buf = x.larray
    # XLA's reduce-min/max compare with `lhs < rhs`, which can silently drop
    # NaN depending on reduction order — carry an explicit NaN flag in the
    # same fused transfer (pads are finite fills, so they can't set it)
    nan_flag = jnp.isnan(lo_buf).any().astype(lo_buf.dtype)
    mn, mx, has_nan = np.asarray(
        jnp.stack([jnp.min(lo_buf), jnp.max(hi_buf), nan_flag])
    )
    if has_nan:
        return np.nan, np.nan
    return mn, mx


def _sanitize_range(lo: float, hi: float):
    """numpy's histogram range rules: finite, ordered, degenerate widened."""
    lo, hi = float(lo), float(hi)
    if not (np.isfinite(lo) and np.isfinite(hi)):
        raise ValueError(f"supplied range of [{lo}, {hi}] is not finite")
    if lo > hi:
        raise ValueError("max must be larger than min in range parameter")
    if lo == hi:
        return lo - 0.5, hi + 0.5
    return lo, hi


def bincount(x: DNDarray, weights: Optional[DNDarray] = None, minlength: int = 0) -> DNDarray:
    """Occurrence counts of non-negative ints (reference statistics.py:375:
    local bincount + Allreduce). Result is replicated.

    On a split array this is DISTRIBUTED: a `shard_map` kernel scatter-adds
    each shard's physical buffer into its local (nbins,) histogram (pads
    carry weight 0) and one psum over ICI combines them — only the global
    max crosses to the host (to size the output). The replicated jnp path
    handles the rest."""
    if x.ndim != 1:
        raise ValueError("object too deep for desired array")
    if x.split is not None and x.comm.size > 1 and x.size > 0:
        comm = x.comm
        mn, mx = (builtins.int(v) for v in _global_minmax(x))
        if mn < 0:
            raise ValueError("bincount: input must have no negative elements")
        nbins = builtins.max(mx + 1, builtins.int(minlength))
        wbuf = _aligned_weights_buf(x, weights)
        vw = _valid_weights(x, wbuf)
        acc = jnp.float64 if weights is not None else jnp.int64
        buf = x._masked(0)  # pads scatter into bin 0 with weight 0

        def kernel(vals, w):
            h = jnp.zeros((nbins,), dtype=acc).at[vals].add(w.astype(acc))
            # histogram counts are exact by contract — never compressed
            return comm.psum(h, precision="off")

        spec = comm.spec(0, 1)
        hist = jax.shard_map(
            kernel, mesh=comm.mesh, in_specs=(spec, spec),
            out_specs=comm.spec(None, 1),
        )(buf, vw)
        return DNDarray.from_logical(hist, None, x.device, x.comm)
    log = x._logical()
    if x.size > 0 and builtins.int(jnp.min(log)) < 0:
        # numpy raises; jnp.bincount silently drops negatives
        raise ValueError("bincount: input must have no negative elements")
    w = weights._logical() if isinstance(weights, DNDarray) else weights
    res = jnp.bincount(log, weights=w, minlength=minlength)
    return DNDarray.from_logical(res, None, x.device, x.comm)


def cov(m: DNDarray, y: Optional[DNDarray] = None, rowvar: bool = True, bias: bool = False, ddof: Optional[int] = None) -> DNDarray:
    """Covariance matrix estimate (reference statistics.py `cov`, built on
    distributed matmul). Variables × observations layout per rowvar."""
    if ddof is not None and not isinstance(ddof, builtins.int):
        raise ValueError("ddof must be integer")
    if m.ndim > 2:
        raise ValueError("m has more than 2 dimensions")
    x = m
    if x.ndim == 1:
        x = DNDarray.from_logical(x._logical()[None, :], None, x.device, x.comm)
    if not rowvar and x.shape[0] != 1:
        from .linalg import transpose

        x = transpose(x)
    if y is not None:
        yy = y
        if yy.ndim == 1:
            yy = DNDarray.from_logical(yy._logical()[None, :], None, y.device, y.comm)
        if not rowvar and yy.shape[0] != 1:
            from .linalg import transpose

            yy = transpose(yy)
        from . import manipulations

        x = manipulations.concatenate([x, yy], axis=0)
    if ddof is None:
        ddof = 0 if bias else 1
    n = x.shape[1]
    from . import arithmetics
    from .linalg import matmul, transpose

    mu = mean(x, axis=1)
    centered = arithmetics.sub(x, DNDarray.from_logical(mu._logical()[:, None], None, x.device, x.comm))
    fact = n - ddof
    c = matmul(centered, transpose(centered))
    return arithmetics.div(c, fact)


def _hist_distributed(x: DNDarray, edges: np.ndarray, weights):
    """Histogram counts of a split array as a DISTRIBUTED algorithm: each
    shard histograms its (raveled) physical buffer locally — tail pads carry
    weight 0, binning is order-independent so ANY split axis works — and one
    psum over ICI combines the per-shard counts (the reference's local hist
    + Allreduce, statistics.py:375/:509, as one shard_map kernel).
    ``edges`` are the precomputed float64 bin edges. Returns the replicated
    (nbins,) float64 counts."""
    comm = x.comm
    wbuf = _aligned_weights_buf(x, weights)
    vw = _valid_weights(x, wbuf)
    buf = x._masked(0)

    def kernel(vals, w):
        # bin in float64 against float64 edges on EVERY path (weighted,
        # unweighted, distributed, replicated): jnp.histogram's binning
        # dtype otherwise shifts with the weights argument, making the same
        # f32 data land differently per path. The f64 comparison is the
        # exact binning; numpy's f32 uniform-bin fast path computes indices
        # in f32 and may differ by O(1) counts on edge-straddling values
        # (numpy f32 disagrees with numpy f64 on the same data) — we match
        # numpy exactly for f64 input and match exact-comparison semantics
        # for everything else
        h, _ = jnp.histogram(
            vals.ravel().astype(jnp.float64), bins=edges, weights=w.ravel()
        )
        return comm.psum(h, precision="off")  # exact counts

    spec = comm.spec(x.split, x.ndim)
    return jax.shard_map(
        kernel, mesh=comm.mesh, in_specs=(spec, spec), out_specs=comm.spec(None, 1)
    )(buf, vw)


def histc(input: DNDarray, bins: int = 100, min: float = 0.0, max: float = 0.0, out=None) -> DNDarray:
    """Histogram with equal-width bins in [min, max]; values outside the
    range are ignored (reference statistics.py `histc`; local hist +
    Allreduce). Replicated result; distributed algorithm on split inputs
    (:func:`_hist_distributed`)."""
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0 and input.size > 0:
        lo, hi = _global_minmax(input)  # fused pass, one host sync
    lo, hi = _sanitize_range(lo, hi)
    edges = np.linspace(lo, hi, builtins.int(bins) + 1)
    if input.split is not None and input.comm.size > 1 and input.size > 0:
        hist = _hist_distributed(input, edges, None)
    else:
        hist, _ = jnp.histogram(
            input._logical().ravel().astype(jnp.float64), bins=edges
        )
    res = DNDarray.from_logical(hist.astype(input.dtype.jnp_type()), None, input.device, input.comm)
    if out is not None:
        out.larray = res.larray
        return out
    return res


def histogram(a: DNDarray, bins: int = 10, range=None, normed=None, weights=None, density=None):
    """numpy-style histogram (reference statistics.py `histogram`).
    Distributed algorithm on split inputs — per-shard counts + psum
    (:func:`_hist_distributed`); ``weights`` follows numpy semantics on
    every path."""
    if hasattr(bins, "__len__"):
        edges_np = np.asarray(bins, dtype=np.float64)
    else:
        if range is not None:
            lo, hi = float(range[0]), float(range[1])
        elif a.size:
            lo, hi = _global_minmax(a)  # fused pass, one host sync
        else:
            lo, hi = 0.0, 1.0
        lo, hi = _sanitize_range(lo, hi)
        edges_np = np.linspace(lo, hi, builtins.int(bins) + 1)
    if a.split is not None and a.comm.size > 1 and a.size > 0:
        hist = _hist_distributed(a, edges_np, weights)
        if weights is None:
            hist = hist.astype(jnp.int64)
    else:
        w = weights._logical().ravel() if isinstance(weights, DNDarray) else (
            jnp.asarray(weights).ravel() if weights is not None else None
        )
        hist, _ = jnp.histogram(
            a._logical().ravel().astype(jnp.float64), bins=edges_np, weights=w
        )
    if density:
        db = jnp.asarray(np.diff(edges_np))
        hist = hist / db / hist.sum()
    return (
        DNDarray.from_logical(hist, None, a.device, a.comm),
        DNDarray.from_logical(jnp.asarray(edges_np), None, a.device, a.comm),
    )


def _pallas_moments_fused(
    x: DNDarray, want: str, ddof: int = 0, interpret: bool = False
):
    """Graft ``x``'s pending fused elementwise chain into the pallas
    column-moments kernel (Fusion 2.0 pre-map): ONE cached program (site
    ``fusion_moments``) computing chain → pad-zero mask → single-read
    Welford moments — the chain never flushes into its own dispatch.
    Returns the replicated result buffer (mean for ``want='mean'``,
    ``M2/(n-ddof)`` for ``want='var'``) or None when nothing is pending /
    Fusion 2.0 is off."""
    from . import fusion, program_cache
    from .pallas_moments import column_moments, sharded_column_moments

    if not fusion.reduce_active():
        return None
    plan = fusion.pending_plan(x)
    if plan is None:
        return None
    sig, plan_t, args = plan
    comm = x.comm
    n = int(x.shape[0])
    sharded = comm.size > 1
    need_mask = bool(sharded and x.pad_count)
    key = sig + (
        ("moments", want, int(ddof), n, sharded, need_mask, interpret),
    )

    def build():
        chain = fusion.plan_program(plan_t)

        def prog(*bufs):
            val = chain(*bufs)
            if need_mask:
                # mask AFTER the chain: pad rows must enter the kernel
                # finite (0·inf inside the Welford combine would poison)
                val = fusion._mask_fill(val, dim=0, extent=n, fill=0.0)
            if sharded:
                mu, m2 = sharded_column_moments(
                    comm, val, n, interpret=interpret
                )
            else:
                mu, m2 = column_moments(val, n, interpret=interpret)
            if want == "mean":
                return mu
            return m2 / (n - ddof)

        return prog

    fn = program_cache.cached_program(
        "fusion_moments", key, build, comm=comm,
        out_shardings=comm.replicated() if sharded else None,
    )
    buf = fn(*args)
    fusion._note_absorbed(x, "moments_absorb", want=want)
    return buf


def chunk_moments(x: DNDarray, interpret: bool = False) -> Tuple:
    """Per-chunk column-moment carry ``(n, mean (d,), M2 (d,))`` over the
    rows of a 2-D chunk — the device half of
    :class:`heat_tpu.streaming.StreamingMoments` (ISSUE 16).

    ONE :func:`~heat_tpu.core.program_cache.cached_program` per
    (chunk shape, split) at site ``streaming.moments``: a steady stream
    of equal-shaped chunks re-enters the same warm executable every
    ``partial_fit`` (the zero-compile oracle pins
    ``site_stats("streaming.")``). On TPU the program drives the
    single-HBM-read pallas Welford kernel
    (:func:`~heat_tpu.core.pallas_moments.column_moments` /
    the sharded psum-merge variant); elsewhere a masked one-pass XLA
    form computes the identical carry. Chunk carries combine across
    ``partial_fit`` calls via :func:`pallas_moments.chan_merge` — the
    same merge rule the kernel applies across row blocks."""
    from . import program_cache
    from .pallas_moments import (
        column_moments,
        pallas_moments_applicable,
        sharded_column_moments,
    )

    if not isinstance(x, DNDarray):
        raise TypeError(f"chunk_moments needs a DNDarray, got {type(x)}")
    if x.ndim != 2:
        raise ValueError("chunk_moments needs a 2-D (rows, features) chunk")
    comm = x.comm
    n = builtins.int(x.shape[0])
    if n == 0:
        raise ValueError("chunk_moments: empty chunk (0 rows)")
    d = builtins.int(x.shape[1])
    xb = x._masked(0)  # tail pads zeroed (and weighted out below)
    sharded = comm.size > 1 and x.split is not None
    use_pallas = pallas_moments_applicable(
        comm.size, x.split, x.ndim, 0, d, xb.dtype
    )
    key = (
        "chunk_moments", tuple(xb.shape), str(xb.dtype), x.split, n,
        use_pallas, interpret,
    )

    def build():
        def prog(xv):
            if use_pallas:
                if comm.size > 1:
                    mu, m2 = sharded_column_moments(
                        comm, xv, n, interpret=interpret
                    )
                else:
                    mu, m2 = column_moments(xv, n, interpret=interpret)
                return mu, m2
            # XLA fallback: masked one-pass (sum, centered square sum).
            # Pad rows sit at GLOBAL tail indices (the physical-buffer
            # invariant every fitter relies on, cf. lasso._cd_fit)
            w = (jnp.arange(xv.shape[0]) < n).astype(xv.dtype)
            ns = jnp.sum(w)
            mu = (w @ xv) / ns
            dc = (xv - mu[None, :]) * w[:, None]
            m2 = jnp.sum(dc * dc, axis=0)
            return mu, m2

        return prog

    fn = program_cache.cached_program(
        "streaming.moments", key, build, comm=comm,
        out_shardings=comm.replicated() if sharded else None,
    )
    mu, m2 = fn(xb)
    return n, mu, m2


def _central_moment(x: DNDarray, axis, k: int):
    """E[(x-μ)^k] with pad-safe masking."""
    from . import arithmetics

    mu = mean(x, axis, keepdims_internal=True)
    d = arithmetics.sub(x, mu)
    p = arithmetics.pow(d, k)
    return mean(p, axis)


def kurtosis(x: DNDarray, axis=None, fisher: bool = True, bias: bool = True) -> DNDarray:
    """Kurtosis (Fisher by default; reference statistics.py `kurtosis`)."""
    from . import arithmetics

    m2 = _central_moment(x, axis, 2)
    m4 = _central_moment(x, axis, 4)
    res = arithmetics.div(m4, arithmetics.pow(m2, 2))
    if not bias:
        n = float(_reduced_count(x, sanitize_axis(x.shape, axis) if axis is not None else None))
        # standard unbiased correction
        g2 = res - 3.0
        res = ((n - 1) / ((n - 2) * (n - 3))) * ((n + 1) * g2 + 6.0) + 3.0
    if fisher:
        res = arithmetics.sub(res, 3.0)
    return res


def max(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Maximum along axis (reference statistics.py `max` via Allreduce MAX)."""
    return reduce_op(jnp.max, x, axis, neutral=_neutral_extreme(x, True), out=out, keepdims=keepdims)


def maximum(x1, x2, out=None) -> DNDarray:
    """Elementwise maximum (reference statistics.py `maximum`)."""
    return binary_op(jnp.maximum, x1, x2, out)


def mean(x: DNDarray, axis=None, keepdims_internal: bool = False, keepdims: bool = False) -> DNDarray:
    """Arithmetic mean (reference statistics.py `mean`: single-pass (n, μ)
    Allreduce merge :803-828; here masked sum / logical count).

    The TPU f32 axis-0 2-D case routes through the SAME
    `column_moments` Pallas call as :func:`var` — deliberately identical
    operands, so a program computing both (the statistical-moments
    pattern) CSEs the two custom calls into ONE kernel execution: mean
    AND var from a single HBM read of X."""
    from . import arithmetics

    if (
        axis == 0
        and not keepdims
        and not keepdims_internal
        and isinstance(x, DNDarray)
        and x.ndim == 2  # gate BEFORE x.shape[1] — 1-D axis=0 is legal
        and x.split in (None, 0)
    ):
        from .pallas_moments import (
            column_moments,
            pallas_moments_applicable,
            sharded_column_moments,
        )

        if pallas_moments_applicable(
            x.comm.size, x.split, x.ndim, 0, x.shape[1],
            x.dtype.jnp_type(),  # metadata, so a pending chain stays pending
        ):
            try:
                mu = _pallas_moments_fused(x, "mean")
                if mu is None:
                    if x.comm.size > 1:
                        mu, _m2 = sharded_column_moments(
                            x.comm, x._masked(0), x.shape[0]
                        )
                    else:
                        mu, _m2 = column_moments(x.larray, x.shape[0])
                import jax

                jax.block_until_ready(mu)  # surface Mosaic faults HERE
                return DNDarray.from_logical(
                    mu, None, x.device, x.comm,
                    types.canonical_heat_type(mu.dtype),
                )
            except Exception as e:  # pragma: no cover — TPU-runtime only
                import warnings

                warnings.warn(f"pallas mean fell back to sum/count: {e!r}")

    keep = keepdims or keepdims_internal
    s = arithmetics.sum(x, axis, keepdims=keep)
    n = _reduced_count(x, sanitize_axis(x.shape, axis) if axis is not None else None)
    return arithmetics.div(s, n)


def median(x: DNDarray, axis=None, keepdims: bool = False) -> DNDarray:
    """Median (reference statistics.py `median` = percentile 50)."""
    return percentile(x, 50.0, axis=axis, keepdims=keepdims)


def min(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    return reduce_op(jnp.min, x, axis, neutral=_neutral_extreme(x, False), out=out, keepdims=keepdims)


def _is_inexact(x: DNDarray) -> bool:
    return jnp.issubdtype(x.dtype.jnp_type(), jnp.inexact)


def _with_out(res: DNDarray, out: Optional[DNDarray]) -> DNDarray:
    """numpy ``out=`` contract for the exact-int nan-variant routes, with
    the SAME shape/split/device validation the inexact routes get from
    ``reduce_op`` (a mismatched ``out`` must raise the sanitation error,
    not a low-level physical-shape one)."""
    if out is None:
        return res
    from . import sanitation

    sanitation.sanitize_out(out, tuple(res.shape), res.split, res.device)
    out.larray = res.larray.astype(out.dtype.jnp_type())
    return out


def nanmax(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Maximum ignoring NaN (reference statistics.py nan-family). Tail
    pads are filled with NaN inside the reduction — a value nanmax
    *ignores* — so pad rows can never win AND an all-NaN lane still
    yields NaN exactly as numpy does. Rides ``reduce_op``: a pending
    fused chain is absorbed into one map+reduce program (Fusion 2.0).
    Exact ints cannot hold NaN and route to :func:`max`."""
    if not _is_inexact(x):
        return max(x, axis, out=out, keepdims=keepdims)
    return reduce_op(jnp.nanmax, x, axis, neutral=float("nan"), out=out, keepdims=keepdims)


def nanmin(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Minimum ignoring NaN (see :func:`nanmax` for pad semantics)."""
    if not _is_inexact(x):
        return min(x, axis, out=out, keepdims=keepdims)
    return reduce_op(jnp.nanmin, x, axis, neutral=float("nan"), out=out, keepdims=keepdims)


def nanmean(x: DNDarray, axis=None, out=None, keepdims: bool = False) -> DNDarray:
    """Arithmetic mean ignoring NaN. The NaN pad fill keeps tail pads out
    of BOTH the numerator and the divisor (a 0 fill would silently count
    them)."""
    if not _is_inexact(x):
        return _with_out(mean(x, axis, keepdims=keepdims), out)
    return reduce_op(jnp.nanmean, x, axis, neutral=float("nan"), out=out, keepdims=keepdims)


def nanvar(x: DNDarray, axis=None, ddof: int = 0, out=None, keepdims: bool = False) -> DNDarray:
    """Variance ignoring NaN (``ddof`` rides as a static kwarg, so the
    call still fuses with a pending chain)."""
    if not _is_inexact(x):
        return _with_out(var(x, axis, ddof=ddof, keepdims=keepdims), out)
    return reduce_op(
        jnp.nanvar, x, axis, neutral=float("nan"), out=out,
        keepdims=keepdims, ddof=builtins.int(ddof),
    )


def nanstd(x: DNDarray, axis=None, ddof: int = 0, out=None, keepdims: bool = False) -> DNDarray:
    """Standard deviation ignoring NaN."""
    if not _is_inexact(x):
        return _with_out(std(x, axis, ddof=ddof, keepdims=keepdims), out)
    return reduce_op(
        jnp.nanstd, x, axis, neutral=float("nan"), out=out,
        keepdims=keepdims, ddof=builtins.int(ddof),
    )


def minimum(x1, x2, out=None) -> DNDarray:
    return binary_op(jnp.minimum, x1, x2, out)


_PERCENTILE_METHODS = ("linear", "lower", "higher", "midpoint", "nearest")


def _percentile_sorted_axis(x: DNDarray, qa, interpolation: str, ax: builtins.int):
    """Distributed percentile along the SPLIT axis (any rank; ndim==1 is
    the ax=0 special case) — beats the reference's rank-0 gather
    (statistics.py:1406-1441): distributed sort along the axis (odd-even
    merge network over ICI, each lane independent), then a replicated
    sharded gather of ONLY the order-statistic slices the interpolation
    method reads. Returns a float64 jnp array shaped (len(q), *rest) with
    the reduced axis moved out, numpy-style."""
    from . import logical as lg
    from . import manipulations
    from .indexing import _sharded_take_fn

    n = x.shape[ax]
    q_flat = np.atleast_1d(np.asarray(qa, dtype=np.float64))
    vals, _ = manipulations.sort(x, axis=ax)
    # bracketing order statistics; indices are host-computable (q, n
    # static). np.round is exact half-to-even — numpy's 'nearest' rule
    pos = q_flat / 100.0 * (n - 1)
    m = len(q_flat)
    if interpolation == "lower":
        idx = np.floor(pos).astype(np.int64)
    elif interpolation == "higher":
        idx = np.ceil(pos).astype(np.int64)
    elif interpolation == "nearest":
        idx = np.round(pos).astype(np.int64)
    else:  # linear / midpoint need both brackets
        i0 = np.floor(pos).astype(np.int64)
        idx = np.concatenate([i0, np.ceil(pos).astype(np.int64)])
    take = _sharded_take_fn(x.comm, ax, None, x.ndim)
    pl = take(vals.larray, jnp.asarray(idx))
    pl = jnp.moveaxis(pl, ax, 0).astype(jnp.float64)  # (m or 2m, *rest)
    if interpolation == "linear":
        frac = jnp.asarray(pos - i0).reshape((m,) + (1,) * (x.ndim - 1))
        res = pl[:m] + (pl[m:] - pl[:m]) * frac
    elif interpolation == "midpoint":
        res = (pl[:m] + pl[m:]) / 2.0
    else:  # lower / higher / nearest gathered exactly their picks
        res = pl
    if jnp.issubdtype(x.dtype.jnp_type(), jnp.floating):
        # numpy: a NaN anywhere in a lane makes that lane's percentiles NaN
        # (the sort pushed NaNs to the lane tail, so picks alone can't tell)
        nan_lane = lg.any(lg.isnan(x), axis=ax).larray  # replicated (*rest)
        res = jnp.where(nan_lane[None] if x.ndim > 1 else nan_lane, jnp.nan, res)
    return res


def percentile(x: DNDarray, q, axis=None, out=None, interpolation: str = "linear", keepdims: bool = False) -> DNDarray:
    """q-th percentile. Reductions over the split axis (1-D global, or n-D
    along the split axis) are a DISTRIBUTED algorithm —
    :func:`_percentile_sorted_axis`: distributed sort + order-statistic
    slice gather; otherwise one jnp.percentile over the logical view
    (reference statistics.py:1406-1441 gathers per-rank partials). Result
    replicated either way."""
    qa = jnp.asarray(q, dtype=jnp.float64)
    qv = np.asarray(qa)
    if np.any(~((qv >= 0.0) & (qv <= 100.0))):
        # numpy raises on every path (incl. NaN q, which compares False to
        # both bounds); jnp.percentile does not — check here
        raise ValueError("percentiles must be in the range [0, 100]")
    q_shape = tuple(qa.shape)
    if qa.ndim > 1:
        # numpy accepts n-D q with the q dims leading the result; jnp only
        # takes rank<=1 — flatten here, restore the q shape at the end
        qa = qa.ravel()
    ax = sanitize_axis(x.shape, axis) if axis is not None else None
    if (
        x.split is not None
        and x.comm.size > 1
        and x.shape[x.split] > 0
        and qa.size > 0
        and interpolation in _PERCENTILE_METHODS
        and (
            (x.ndim == 1 and (ax is None or ax == 0 or ax == (0,)))
            or (x.ndim > 1 and (ax == x.split or ax == (x.split,)))
        )
    ):
        res = _percentile_sorted_axis(x, qa, interpolation, x.split)
        if not qa.ndim:
            res = res[0]  # scalar q: rest dims only
        if keepdims:
            off = 1 if qa.ndim else 0
            res = jnp.expand_dims(res, x.split + off)
        # falls through to the shared reshape/astype/wrap/out epilogue
    elif interpolation == "nearest":
        log = x._logical()
        # jnp.percentile's 'nearest' rounds half positions down; numpy
        # rounds half to even — select from the sorted values with
        # jnp.round (which IS half-to-even). Works for any axis form by
        # collapsing the reduced axes into one; NaN propagation restored
        # explicitly (jnp.sort pushes NaN to the end).
        axes = (
            tuple(range(log.ndim))
            if ax is None
            else ((ax,) if isinstance(ax, builtins.int) else tuple(ax))
        )
        rest = log.ndim - len(axes)
        moved = jnp.moveaxis(log, axes, tuple(range(rest, log.ndim)))
        arr2 = moved.reshape(moved.shape[:rest] + (-1,))
        n = arr2.shape[-1]
        srt = jnp.sort(arr2, axis=-1)
        # indices are host-computable (q and n are static) — np.round is
        # exact half-to-even, while jnp.round under the TPU backend's
        # emulated float64 mis-rounds exact half positions
        idx = jnp.asarray(
            np.round(np.asarray(qa) / 100.0 * (n - 1)).astype(np.int32)
        )
        res = jnp.take(srt, idx, axis=-1)
        if qa.ndim:
            res = jnp.moveaxis(res, -1, 0)  # the q dim leads, as in numpy
        nanmask = jnp.isnan(arr2).any(axis=-1)
        res = jnp.where(nanmask, jnp.nan, res)
        if keepdims:
            # re-insert length-1 dims at the original reduced positions
            # (shifted by one when a leading q dim is present)
            off = 1 if qa.ndim else 0
            # result currently carries the non-reduced dims in their
            # original relative order — map each kept dim back, inserting
            # the reduced ones
            for a in sorted(axes):
                res = jnp.expand_dims(res, a + off)
    else:
        res = jnp.percentile(x._logical(), qa, axis=axis, method=interpolation, keepdims=keepdims)
    if len(q_shape) > 1:
        res = res.reshape(q_shape + tuple(res.shape[1:]))
    res = res.astype(jnp.float64)
    out_arr = (
        DNDarray(res, tuple(res.shape), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)
        if res.ndim
        else DNDarray(res, (), types.canonical_heat_type(res.dtype), None, x.device, x.comm, True)
    )
    if out is not None:
        out.larray = out_arr.larray.astype(out.dtype.jnp_type())
        return out
    return out_arr


def skew(x: DNDarray, axis=None, unbiased: bool = True) -> DNDarray:
    """Skewness (reference statistics.py `skew`)."""
    from . import arithmetics, exponential

    m2 = _central_moment(x, axis, 2)
    m3 = _central_moment(x, axis, 3)
    res = arithmetics.div(m3, arithmetics.pow(m2, 1.5))
    if unbiased:
        n = float(_reduced_count(x, sanitize_axis(x.shape, axis) if axis is not None else None))
        if n > 2:
            res = arithmetics.mul(res, float(np.sqrt(n * (n - 1)) / (n - 2)))
    return res


def std(x: DNDarray, axis=None, ddof: int = 0, keepdims: bool = False) -> DNDarray:
    """Standard deviation (reference statistics.py `std`)."""
    from . import exponential

    return exponential.sqrt(var(x, axis, ddof=ddof, keepdims=keepdims))


def var(x: DNDarray, axis=None, ddof: int = 0, keepdims: bool = False) -> DNDarray:
    """Variance, two-pass (reference statistics.py `var`: Welford-style
    single-pass combine :1729-1758 — the two passes here fuse under XLA)."""
    from . import arithmetics

    if not isinstance(ddof, builtins.int):
        raise ValueError(f"ddof must be integer, is {type(ddof)}")
    if ddof not in (0, 1):
        raise ValueError("Heat currently supports ddof of 0 or 1 only")

    # single-device TPU f32 axis-0 on 2-D: one-HBM-read Welford kernel
    # (pallas_moments) instead of the two-read two-pass form
    if (
        axis == 0
        and not keepdims
        and isinstance(x, DNDarray)
        and x.ndim == 2  # gate BEFORE x.shape[1] — 1-D axis=0 is legal
        and x.split in (None, 0)
    ):
        from .pallas_moments import (
            column_moments,
            pallas_moments_applicable,
            sharded_column_moments,
        )

        if pallas_moments_applicable(
            x.comm.size, x.split, x.ndim, 0, x.shape[1],
            x.dtype.jnp_type(),  # metadata, so a pending chain stays pending
        ):
            try:
                out = _pallas_moments_fused(x, "var", ddof=ddof)
                if out is None:
                    if x.comm.size > 1:
                        _mu, m2 = sharded_column_moments(
                            x.comm, x._masked(0), x.shape[0]
                        )
                    else:
                        _mu, m2 = column_moments(x.larray, x.shape[0])
                    out = m2 / (x.shape[0] - ddof)
                import jax

                jax.block_until_ready(out)  # surface Mosaic faults HERE
                return DNDarray.from_logical(
                    out, None, x.device, x.comm,
                    types.canonical_heat_type(out.dtype),
                )
            except Exception as e:  # pragma: no cover — TPU-runtime only
                import warnings

                warnings.warn(f"pallas var fell back to two-pass: {e!r}")

    mu = mean(x, axis, keepdims_internal=True)
    d = arithmetics.sub(x, mu)
    sq = arithmetics.mul(d, d)
    s = arithmetics.sum(sq, axis, keepdims=keepdims)
    n = _reduced_count(x, sanitize_axis(x.shape, axis) if axis is not None else None)
    return arithmetics.div(s, n - ddof)


DNDarray.argmax = lambda self, axis=None, out=None, keepdims=False: argmax(self, axis, out, keepdims)
DNDarray.argmin = lambda self, axis=None, out=None, keepdims=False: argmin(self, axis, out, keepdims)
DNDarray.max = lambda self, axis=None, out=None, keepdims=False: max(self, axis, out, keepdims)
DNDarray.min = lambda self, axis=None, out=None, keepdims=False: min(self, axis, out, keepdims)
DNDarray.mean = lambda self, axis=None, keepdims=False: mean(self, axis, keepdims=keepdims)
DNDarray.std = lambda self, axis=None, ddof=0, keepdims=False: std(self, axis, ddof, keepdims)
DNDarray.var = lambda self, axis=None, ddof=0, keepdims=False: var(self, axis, ddof, keepdims)
DNDarray.average = lambda self, axis=None, weights=None, returned=False: average(self, axis, weights, returned)
DNDarray.median = lambda self, axis=None, keepdims=False: median(self, axis, keepdims)
