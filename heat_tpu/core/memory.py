"""Memory layout helpers (reference: heat/core/memory.py:13-87).

XLA owns physical layout on TPU (tiled, not strided), so the C/F-order
enforcement of the reference is metadata-only here; `copy` remains a real
deep copy.
"""

from __future__ import annotations

from .dndarray import DNDarray

__all__ = ["copy", "sanitize_memory_layout"]


def copy(x: DNDarray) -> DNDarray:
    """Deep copy (reference memory.py:13)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
    import jax.numpy as jnp

    return DNDarray(
        jnp.copy(x.larray), x.shape, x.dtype, x.split, x.device, x.comm, True
    )


def sanitize_memory_layout(x, order: str = "C"):
    """Accepted for API parity (reference memory.py:42 re-strides torch
    tensors); XLA arrays have no user-visible stride order."""
    if order not in ("C", "F"):
        raise ValueError(f"invalid memory layout {order!r}, expected 'C' or 'F'")
    return x
