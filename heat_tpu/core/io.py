"""Parallel I/O (reference: heat/core/io.py:55-972).

The reference reads per-rank slices of HDF5/NetCDF/CSV files
(``f[dataset][slices]``, io.py:710 byte-range CSV splitting). Under a single
controller the host reads the file once and `device_put` shards it; on
multi-host deployments each host would read its slice and assemble with
`jax.make_array_from_process_local_data` — the `split` argument carries the
same meaning. HDF5/NetCDF support is gated on the optional libraries
(reference gates on h5py/netCDF4 the same way, io.py:13-35); `.npy`/`.csv`
always work, and `save_checkpoint`/`load_checkpoint` (orbax-backed) are a
TPU-native extension for sharded array checkpointing (SURVEY §5).
"""

from __future__ import annotations

import os
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from . import types
from .communication import CommunicationError, sanitize_comm
from .devices import sanitize_device
from .dndarray import DNDarray
from .factories import array as _array
from .stride_tricks import sanitize_axis

__all__ = [
    "dataset_shape",
    "load",
    "load_csv",
    "load_npy",
    "save_npy",
    "save",
    "save_csv",
    "supports_checkpoint",
    "supports_hdf5",
    "supports_netcdf",
]

try:  # pragma: no cover - availability depends on environment
    import h5py

    __HDF5 = True
except ImportError:
    __HDF5 = False

try:  # pragma: no cover
    import netCDF4

    __NETCDF = True
except ImportError:
    netCDF4 = None
    __NETCDF = False

try:  # pragma: no cover — NetCDF-3 fallback backend when netCDF4 is absent
    from scipy.io import netcdf_file as _scipy_netcdf

    __NETCDF_SCIPY = True
except ImportError:
    _scipy_netcdf = None
    __NETCDF_SCIPY = False

# unmangled aliases for use inside the adapter class bodies (a leading-__
# module global would name-mangle to _NcRead__NETCDF there)
_HAS_NC4 = __NETCDF
_HAS_NC_SCIPY = __NETCDF_SCIPY
_HAS_H5 = __HDF5


class _NcRead:
    """Read adapter over the available NetCDF backend: netCDF4 when
    installed, else scipy.io (classic NetCDF-3), else h5py (NetCDF-4 files
    ARE HDF5 files, so simple variables read fine). Variables expose
    ``.shape`` and numpy-yielding ``__getitem__`` in every branch."""

    def __init__(self, path: str):
        if _HAS_NC4:
            self._h = netCDF4.Dataset(path, "r")
            self._get = lambda name: self._h[name]
        elif _HAS_NC_SCIPY:
            try:
                self._h = _scipy_netcdf(path, "r", mmap=False)
                self._get = lambda name: self._h.variables[name]
            except Exception:
                # not classic format — likely a NetCDF-4 (HDF5) file
                if not _HAS_H5:
                    raise
                self._h = h5py.File(path, "r")
                self._get = lambda name: self._h[name]
        else:  # pragma: no cover — supports_netcdf() gates callers
            raise RuntimeError(
                "netcdf is required for this operation "
                "(neither netCDF4 nor scipy is available)"
            )

    def var(self, name: str):
        return self._get(name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._h.close()
        return False


class _NcWrite:
    """Write adapter: netCDF4 when installed, else scipy.io NetCDF-3
    (classic dtypes only — i8/i16/i32/f32/f64; int64 raises the backend's
    own clear error). ``mode`` follows the netCDF4 convention ('w' create,
    'r+' modify)."""

    def __init__(self, path: str, mode: str):
        if _HAS_NC4:
            self._h = netCDF4.Dataset(path, mode)
        elif _HAS_NC_SCIPY:
            self._h = _scipy_netcdf(
                path, "w" if mode == "w" else "a", mmap=False
            )
        else:  # pragma: no cover — supports_netcdf() gates callers
            raise RuntimeError(
                "netcdf is required for this operation "
                "(neither netCDF4 nor scipy is available)"
            )

    def create(self, variable: str, dtype, shape):
        dims = []
        for i, s in enumerate(shape):
            name = f"{variable}_dim{i}"
            self._h.createDimension(name, int(s))
            dims.append(name)
        return self._h.createVariable(variable, dtype, tuple(dims))

    def var(self, name: str):
        if _HAS_NC4:
            return self._h[name]
        return self._h.variables[name]

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._h.close()
        return False


def _atomic_write(path: str, write_fn) -> None:
    """Partial-write hardening (ISSUE 5 satellite): run ``write_fn(tmp)``
    against a sibling temp path, then atomically rename over ``path`` —
    a crash or exception mid-write leaves the previous file intact and no
    temp debris behind. Single-writer paths only (the multi-host slab
    rings modify one shared file in place and keep their own barrier
    protocol)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        write_fn(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        raise


def supports_hdf5() -> bool:
    """Whether h5py is available (reference io.py `supports_hdf5`)."""
    return __HDF5


def supports_checkpoint() -> bool:
    """Whether orbax-backed checkpointing is available."""
    try:  # lazy probe: orbax pulls tensorstore — only needed to checkpoint
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


def supports_netcdf() -> bool:
    """Whether a NetCDF backend is available (reference io.py
    `supports_netcdf`): netCDF4, or the scipy.io NetCDF-3 fallback."""
    return __NETCDF or __NETCDF_SCIPY


def load(path: str, *args, **kwargs) -> DNDarray:
    """Load by file extension (reference io.py:659)."""
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    ext = os.path.splitext(path)[-1]
    if ext in (".h5", ".hdf5"):
        return load_hdf5(path, *args, **kwargs)
    if ext in (".nc", ".netcdf"):
        return load_netcdf(path, *args, **kwargs)
    if ext == ".csv":
        return load_csv(path, *args, **kwargs)
    if ext == ".npy":
        return load_npy(path, *args, **kwargs)
    raise ValueError(f"Unsupported file extension {ext}")


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a CSV file (reference io.py:710 splits byte ranges per rank).

    Single-controller: one host read (native multithreaded tokenizer when
    available) + shard. Multi-host: each process tokenizes ONLY its
    canonical row block (`csv_parse_range` — just the newline scan touches
    the whole file) and the blocks assemble via ``is_split`` — the
    reference's per-rank byte-range design with canonical chunking."""
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    if not isinstance(sep, str):
        raise TypeError(f"Expected sep to be str, but was {type(sep)}")
    if not isinstance(header_lines, int):
        raise TypeError(f"Expected header_lines to be int, but was {type(header_lines)}")
    from .. import native

    import jax

    def _genfromtxt_2d():
        """numpy fallback read, always (rows, cols) — genfromtxt collapses
        single rows/columns to 1-D and a single value to 0-D; recover the
        column count from the first data line."""
        data = np.genfromtxt(
            path, delimiter=sep, skip_header=header_lines, encoding=encoding
        )
        if data.ndim < 2:
            with open(path, "r", encoding=encoding) as f:
                for _ in range(header_lines):
                    f.readline()
                line = f.readline().strip()
            ncols = len(line.split(sep)) if line else 1
            data = data.reshape(-1, ncols)
        return data

    if jax.process_count() > 1:
        if split != 0:
            raise NotImplementedError(
                "multi-host load_csv supports split=0 (row-sharded) only"
            )
        c = sanitize_comm(comm)
        dims = None
        if encoding.replace("-", "").lower() in ("utf8", "ascii"):
            dims = native.csv_dims(path, sep, header_lines)
        full = None
        if dims is not None:
            rows, cols = dims
        else:
            # no native lib / exotic encoding: every process reads the file
            # and keeps its canonical block — wasteful IO, correct assembly
            full = _genfromtxt_2d()
            rows, cols = full.shape
        # this process's canonical row block: the chunks of ITS devices in
        # the communicator's mesh (a sub-mesh comm may own fewer devices
        # than jax.local_device_count())
        lo, hi = _process_slab(c, rows)
        if full is not None:
            block = full[lo:hi]
        else:
            block = native.parse_csv_range(path, sep, header_lines, lo, hi - lo, cols)
        return _array(block, dtype=dtype, is_split=0, device=device, comm=comm)

    data = None
    if encoding.replace("-", "").lower() in ("utf8", "ascii"):
        # the native tokenizer reads raw bytes; other encodings go through
        # numpy's decoding path
        data = native.parse_csv(path, sep=sep, header_lines=header_lines)
    if data is None:  # no compiler / exotic separator/encoding: numpy path
        data = _genfromtxt_2d()
    return _array(data, dtype=dtype, split=split, device=device, comm=comm)


def save_csv(data: DNDarray, path: str, header_lines: Optional[str] = None, sep: str = ","):
    """Save to CSV (reference io.py `save_csv`).

    Multi-host with a row-split array: process 0 truncates the file and
    writes the header + its rows, later processes append theirs in process
    order (serialized slab writes — no host gathers the global array).
    Replicated arrays are written by process 0 only; column-split arrays
    would need a cross-host relayout and raise."""
    import jax

    def header_text():
        if not header_lines:
            return ""
        return "".join("# " + ln + "\n" for ln in str(header_lines).splitlines())

    if jax.process_count() > 1:
        if data.split == 0:
            from jax.experimental import multihost_utils

            block, lo, hi = _local_block(data)
            # the append-in-process-order design assumes the per-process
            # slabs tile [0, n) contiguously in process-index order; a comm
            # built over an interleaved device list would scramble rows —
            # validate full coverage, not just monotonicity
            spans = np.asarray(
                multihost_utils.process_allgather(
                    np.asarray([lo, hi], dtype=np.int64)
                )
            ).reshape(-1, 2)
            n_rows = data.shape[0]
            contiguous = (
                spans[0, 0] == 0
                and spans[-1, 1] == n_rows
                and (spans[1:, 0] == spans[:-1, 1]).all()
            )
            if not contiguous:
                raise NotImplementedError(
                    "multi-host save_csv requires the per-process slabs to "
                    "tile the rows contiguously in process order (got spans "
                    f"{spans.tolist()} for {n_rows} rows); use save_hdf5, "
                    "which writes explicit slices"
                )

            from .. import native

            def write(p):
                if p == 0:
                    with open(path, "w") as f:
                        f.write(header_text())
                if hi > lo:
                    blk2 = block if block.ndim == 2 else block[:, None]
                    if not native.write_csv(path, blk2, sep=sep, append=True):
                        with open(path, "a") as f:
                            np.savetxt(f, block, delimiter=sep)

            _serialized_slab_write(write, "csv")
            return
        if data.split is None:

            def write0(p):
                if p == 0:
                    np.savetxt(path, data.numpy(), delimiter=sep, header=header_lines or "")

            _serialized_slab_write(write0, "csv0")
            return
        raise NotImplementedError(
            "multi-host save_csv supports split=0 (row-sharded) or replicated "
            "arrays only; resplit_(0) first"
        )
    from .. import native

    host = data.numpy()

    def write(tmp):
        if host.ndim in (1, 2) and np.issubdtype(host.dtype, np.floating):
            h2 = host if host.ndim == 2 else host[:, None]
            with open(tmp, "w") as f:
                f.write(header_text())
            if native.write_csv(tmp, h2, sep=sep, append=True):
                return
        np.savetxt(tmp, host, delimiter=sep, header=header_lines or "")

    _atomic_write(path, write)


def _check_chunks(chunks, nrows: int, path: str) -> tuple:
    """Validate a ``chunks=(start, stop)`` half-open row range against a
    file's leading dimension (ISSUE 16: the out-of-core read path).
    Returns the normalized ``(start, stop)`` ints; raises the documented
    clear errors instead of letting a silent short read through."""
    try:
        start, stop = (int(chunks[0]), int(chunks[1]))
        if len(chunks) != 2:
            raise TypeError
    except (TypeError, ValueError, IndexError):
        raise TypeError(
            f"chunks must be a (start, stop) row-range pair, got {chunks!r}"
        ) from None
    if start < 0 or stop < 0:
        raise ValueError(
            f"chunks=({start}, {stop}): negative row indices are not "
            f"supported for chunked reads"
        )
    if start >= stop:
        raise ValueError(
            f"chunks=({start}, {stop}) is an empty row range — a chunked "
            f"read needs start < stop"
        )
    if stop > nrows:
        raise ValueError(
            f"chunks=({start}, {stop}) is a truncated final chunk: "
            f"{path!r} has only {nrows} rows — clamp stop to the row "
            f"count (ChunkStream does this for you)"
        )
    return start, stop


def dataset_shape(path: str, dataset: Optional[str] = None) -> tuple:
    """The on-disk shape of an array file WITHOUT materializing it:
    ``.npy`` header peek (memory map) or HDF5 dataset metadata. The
    chunk-sizing primitive of :class:`heat_tpu.streaming.ChunkStream`."""
    if dataset is not None or path.endswith((".h5", ".hdf5")):
        if not __HDF5:
            raise RuntimeError(
                "hdf5 is required for this operation (h5py not available)"
            )
        if dataset is None:
            raise ValueError(
                f"dataset_shape({path!r}) needs dataset= for HDF5 files"
            )
        with h5py.File(path, "r") as handle:
            return tuple(handle[dataset].shape)
    try:
        data = np.load(path, mmap_mode="r", allow_pickle=False)
    except (ValueError, OSError, EOFError) as e:
        raise ValueError(
            f"dataset_shape: {path!r} is not a readable .npy array file "
            f"({e})"
        ) from None
    return tuple(data.shape)


def load_npy(
    path: str, dtype=None, split=None, device=None, comm=None, chunks=None
) -> DNDarray:
    """Load a numpy .npy file (extension; memory-maps then shards).

    Multi-host with ``split``: the memory map means each process touches
    ONLY its canonical slab's pages — per-process slab reads for free.

    ``chunks=(start, stop)`` (ISSUE 16) reads ONLY that half-open row
    block — the memory map touches just those pages, so a caller can
    walk a file far larger than the budget. Out-of-bounds ranges raise
    (see :func:`_check_chunks`) rather than silently short-reading."""
    import jax

    try:
        data = np.load(path, mmap_mode="r", allow_pickle=False)
    except (ValueError, OSError, EOFError) as e:
        # truncated file, non-.npy content, pickled object arrays — surface
        # one clear error instead of a raw numpy traceback (ISSUE 5
        # satellite)
        raise ValueError(
            f"load_npy: {path!r} is not a readable .npy array file ({e})"
        ) from None
    if data.dtype == object or data.dtype.hasobject:
        raise ValueError(
            f"load_npy: {path!r} holds dtype=object data, which has no "
            "DNDarray representation — save numeric arrays only"
        )
    if chunks is not None:
        if jax.process_count() > 1:
            raise NotImplementedError(
                "chunked (row-range) reads are single-controller; "
                "multi-host runs use the per-process slab path instead"
            )
        if data.ndim == 0:
            raise ValueError(
                f"load_npy: {path!r} is 0-d — chunked reads need a row axis"
            )
        start, stop = _check_chunks(chunks, data.shape[0], path)
        return _array(
            np.asarray(data[start:stop]), dtype=dtype, split=split,
            device=device, comm=comm,
        )
    if jax.process_count() > 1 and split is not None:
        c = sanitize_comm(comm)
        split_s = sanitize_axis(data.shape, split)
        lo, hi = _process_slab(c, data.shape[split_s])
        sl = [slice(None)] * data.ndim
        sl[split_s] = slice(lo, hi)
        return _array(
            np.asarray(data[tuple(sl)]), dtype=dtype, is_split=split_s,
            device=device, comm=comm,
        )
    return _array(np.asarray(data), dtype=dtype, split=split, device=device, comm=comm)


def save_npy(data: DNDarray, path: str) -> None:
    """Save to .npy. Multi-host with a split array: process 0 creates the
    file at the global shape via a memory map, then every process writes
    only its slab (serialized barrier ring — no gather)."""
    import jax

    if jax.process_count() > 1 and data.split is not None:
        block, lo, hi = _local_block(data)
        gshape = tuple(data.shape)
        sl = [slice(None)] * data.ndim
        sl[data.split] = slice(lo, hi)

        def write(p):
            mm = np.lib.format.open_memmap(
                path,
                mode="w+" if p == 0 else "r+",
                dtype=block.dtype if p == 0 else None,
                shape=gshape if p == 0 else None,
            )
            if hi > lo:
                mm[tuple(sl)] = block
            mm.flush()

        _serialized_slab_write(write, "npy")
        return
    # open() the temp handle ourselves: np.save(path_without_suffix)
    # would append ".npy" to the temp name and the rename would miss it
    def _write_npy(tmp):
        with open(tmp, "wb") as f:
            np.save(f, data.numpy())

    _atomic_write(path, _write_npy)


def _process_slab(comm, n: int):
    """This process's canonical logical range ``[lo, hi)`` along a split
    dimension of length ``n``: the union of the ceil-rule chunks of its
    (contiguous) devices in the communicator mesh. The same arithmetic as the
    multi-host ``load_csv`` path."""
    import jax

    c = comm.chunk_size(n)
    ldc = sum(1 for d in comm.devices if d.process_index == jax.process_index())
    first = comm.first_local_position()
    lo = min(first * c, n)
    hi = min((first + ldc) * c, n)
    return lo, hi


def _local_block(x: DNDarray):
    """Process-local *logical* data of a split DNDarray as one numpy block,
    plus its global bounds ``(block, lo, hi)`` along the split axis.

    Concatenates this process's addressable shards in mesh order and trims
    the physical tail pad — no cross-host traffic, so (unlike ``.numpy()``)
    this is multi-host safe."""
    split = x.split
    comm = x.comm
    n = x.shape[split]
    lo, hi = _process_slab(comm, n)
    shards = sorted(
        x.larray.addressable_shards,
        key=lambda s: s.index[split].start or 0,
    )
    seen = set()
    parts = []
    for s in shards:
        key = s.index[split].start or 0
        if key in seen:  # replicated non-split dims can duplicate shards
            continue
        seen.add(key)
        parts.append(np.asarray(s.data))
    if not parts:
        # a process owning none of the comm's devices still participates in
        # the collective write — with an empty slab (hi == lo)
        eshape = list(x.padded_shape)
        eshape[split] = 0
        return np.empty(eshape, dtype=x.larray.dtype), lo, lo
    block = parts[0] if len(parts) == 1 else np.concatenate(parts, axis=split)
    sl = [slice(None)] * x.ndim
    sl[split] = slice(0, hi - lo)  # physical block may carry tail pad
    return block[tuple(sl)], lo, hi


def _serialized_slab_write(writer, n_header: str):
    """Run ``writer(process_id)`` on each process in process order, with a
    global device barrier between turns.

    TPU pods have no MPI-IO; concurrent writes to one HDF5/NetCDF file are
    unsafe without it. Serializing the per-process slab writes keeps the
    memory-scalability of parallel I/O (no host ever gathers the global
    array — better than the reference's serial fallback, which resplits to
    rank 0 first, reference io.py:44-47) at the cost of write-time overlap.
    Assumes the path is on a filesystem all processes see.

    A writer failure on one process must not strand the others at the
    barrier: the exception is held until the ring completes, then an ok-flag
    allgather raises on EVERY process (the file may be partially written)."""
    import jax
    from jax.experimental import multihost_utils

    err = None
    for p in range(jax.process_count()):
        if p == jax.process_index() and err is None:
            try:
                writer(p)
            except Exception as e:  # noqa: BLE001 — re-raised after the ring
                err = e
        multihost_utils.sync_global_devices(f"ht.io.slab:{n_header}:{p}")
    oks = np.asarray(
        multihost_utils.process_allgather(np.asarray([err is None], dtype=np.int32))
    ).ravel()
    if err is not None:
        raise err
    if not oks.all():
        raise CommunicationError(
            f"slab write failed on process(es) {np.nonzero(oks == 0)[0].tolist()} "
            "— the file is incomplete"
        )


def load_hdf5(
    path: str,
    dataset: str,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
    chunks=None,
) -> DNDarray:
    """Load an HDF5 dataset (reference io.py:55 reads per-rank slices
    ``f[dataset][slices]``).

    Single-controller: one host read + shard. Multi-host with ``split``:
    every process reads ONLY its canonical slab of the dataset (an h5py
    range read — the file is never materialized whole on any host) and the
    slabs assemble via ``is_split``.

    ``chunks=(start, stop)`` (ISSUE 16) reads ONLY that half-open row
    block (an h5py range read — the reference's ``PartialH5Dataset``
    access pattern, feeding :class:`heat_tpu.streaming.ChunkStream`).
    Out-of-bounds ranges raise the documented truncated-final-chunk /
    empty-range errors instead of silently short-reading."""
    if not __HDF5:
        raise RuntimeError("hdf5 is required for this operation (h5py not available)")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    if not isinstance(dataset, str):
        raise TypeError(f"dataset must be str, not {type(dataset)}")
    import jax

    if chunks is not None:
        if jax.process_count() > 1:
            raise NotImplementedError(
                "chunked (row-range) reads are single-controller; "
                "multi-host runs use the per-process slab path instead"
            )
        with h5py.File(path, "r") as handle:
            ds = handle[dataset]
            if len(ds.shape) == 0:
                raise ValueError(
                    f"load_hdf5: {path!r}:{dataset} is 0-d — chunked "
                    f"reads need a row axis"
                )
            start, stop = _check_chunks(chunks, ds.shape[0], path)
            block = np.asarray(ds[start:stop])
        return _array(
            block, dtype=dtype, split=split, device=device, comm=comm
        )
    if jax.process_count() > 1 and split is not None:
        c = sanitize_comm(comm)
        with h5py.File(path, "r") as handle:
            ds = handle[dataset]
            gshape = tuple(ds.shape)
            split_s = sanitize_axis(gshape, split)
            lo, hi = _process_slab(c, gshape[split_s])
            sl = [slice(None)] * len(gshape)
            sl[split_s] = slice(lo, hi)
            block = np.asarray(ds[tuple(sl)])
        return _array(block, dtype=dtype, is_split=split_s, device=device, comm=comm)

    with h5py.File(path, "r") as handle:
        data = np.asarray(handle[dataset])
    return _array(data, dtype=dtype, split=split, device=device, comm=comm)


def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs):
    """Save to an HDF5 dataset (reference io.py:147 writes per-rank slices,
    MPI-parallel when h5py has MPI).

    Multi-host with a split array: process 0 creates the dataset at the
    global shape, then every process writes ONLY its slab (serialized via a
    barrier ring — see ``_serialized_slab_write``). No host gathers the
    global array."""
    if not __HDF5:
        raise RuntimeError("hdf5 is required for this operation (h5py not available)")
    import jax

    if jax.process_count() > 1 and data.split is not None:
        block, lo, hi = _local_block(data)
        gshape = tuple(data.shape)
        sl = [slice(None)] * data.ndim
        sl[data.split] = slice(lo, hi)

        def write(p):
            with h5py.File(path, mode if p == 0 else "r+") as handle:
                if p == 0:
                    handle.create_dataset(
                        dataset, shape=gshape, dtype=block.dtype, **kwargs
                    )
                if hi > lo:
                    handle[dataset][tuple(sl)] = block

        _serialized_slab_write(write, f"h5:{dataset}")
        return
    if jax.process_count() > 1:
        # replicated array on multi-host: exactly one writer, all wait
        def write0(p):
            if p == 0:
                with h5py.File(path, mode) as handle:
                    handle.create_dataset(dataset, data=data.numpy(), **kwargs)

        _serialized_slab_write(write0, f"h5r:{dataset}")
        return
    if mode == "w":
        # fresh-file writes go through the atomic temp+rename protocol;
        # append/modify modes ("a"/"r+") edit an existing file in place and
        # cannot be made atomic without copying it wholesale
        def write(tmp):
            with h5py.File(tmp, "w") as handle:
                handle.create_dataset(dataset, data=data.numpy(), **kwargs)

        _atomic_write(path, write)
        return
    with h5py.File(path, mode) as handle:
        handle.create_dataset(dataset, data=data.numpy(), **kwargs)


def load_netcdf(
    path: str,
    variable: str,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
) -> DNDarray:
    """Load a NetCDF variable (reference io.py:265 reads per-rank slices).

    Multi-host with ``split``: per-process slab reads + ``is_split``
    assembly, same design as :func:`load_hdf5`."""
    if not supports_netcdf():
        raise RuntimeError(
            "netcdf is required for this operation "
            "(neither netCDF4 nor scipy is available)"
        )
    import jax

    if jax.process_count() > 1 and split is not None:
        c = sanitize_comm(comm)
        with _NcRead(path) as handle:
            var = handle.var(variable)
            gshape = tuple(var.shape)
            split_s = sanitize_axis(gshape, split)
            lo, hi = _process_slab(c, gshape[split_s])
            sl = [slice(None)] * len(gshape)
            sl[split_s] = slice(lo, hi)
            block = np.asarray(var[tuple(sl)])
        return _array(block, dtype=dtype, is_split=split_s, device=device, comm=comm)

    with _NcRead(path) as handle:
        data = np.asarray(handle.var(variable)[:])
    return _array(data, dtype=dtype, split=split, device=device, comm=comm)


def save_netcdf(data: DNDarray, path: str, variable: str, mode: str = "w", **kwargs):
    """Save to a NetCDF variable (reference io.py:348).

    Multi-host with a split array: process 0 creates dimensions + variable
    at the global shape, then per-process slab writes (serialized, no
    gather), as in :func:`save_hdf5`."""
    if not supports_netcdf():
        raise RuntimeError(
            "netcdf is required for this operation "
            "(neither netCDF4 nor scipy is available)"
        )
    import jax

    if jax.process_count() > 1 and data.split is not None:
        block, lo, hi = _local_block(data)
        gshape = tuple(data.shape)
        sl = [slice(None)] * data.ndim
        sl[data.split] = slice(lo, hi)

        def write(p):
            with _NcWrite(path, mode if p == 0 else "r+") as handle:
                if p == 0:
                    handle.create(variable, block.dtype, gshape)
                if hi > lo:
                    handle.var(variable)[tuple(sl)] = block

        _serialized_slab_write(write, f"nc:{variable}")
        return
    if jax.process_count() > 1:

        def write0(p):
            if p == 0:
                save_netcdf_local(data, path, variable, mode, **kwargs)

        _serialized_slab_write(write0, f"ncr:{variable}")
        return
    save_netcdf_local(data, path, variable, mode, **kwargs)


def save_netcdf_local(data: DNDarray, path: str, variable: str, mode: str = "w", **kwargs):
    """Single-writer NetCDF save (the local body of :func:`save_netcdf`).
    Fresh-file writes (``mode="w"``) are atomic (temp + rename); modify
    modes edit in place."""
    np_data = data.numpy()

    def write(target):
        with _NcWrite(target, mode) as handle:
            var = handle.create(variable, np_data.dtype, np_data.shape)
            var[:] = np_data

    if mode == "w":
        _atomic_write(path, write)
    else:
        write(path)


if __HDF5:
    __all__ += ["load_hdf5", "save_hdf5"]
if supports_netcdf():
    __all__ += ["load_netcdf", "save_netcdf"]


def save_checkpoint(state, path: str) -> None:
    """Checkpoint a pytree of arrays/DNDarrays with orbax (TPU-native
    extension; the reference's checkpoint story is array save/load via HDF5,
    SURVEY §5 — orbax adds per-shard parallel writes via TensorStore/ocdbt).

    DNDarrays are stored as their *sharded* device buffers (orbax writes one
    TensorStore chunk per shard in parallel — no host gather) plus
    gshape/split metadata, and are restored as DNDarrays by
    :func:`load_checkpoint`."""
    if not supports_checkpoint():
        raise RuntimeError(
            "checkpointing requires orbax (pip install 'heat_tpu[checkpoint]')"
        )
    import jax
    import orbax.checkpoint as ocp

    def pack(x):
        if isinstance(x, DNDarray):
            return {
                "__dndarray__": x.larray,  # padded sharded buffer, as-is
                # length-prefixed so 0-d arrays don't produce a zero-size
                # metadata array (orbax refuses those)
                "gshape": np.asarray((x.ndim,) + tuple(x.shape), dtype=np.int64),
                "split": -1 if x.split is None else x.split,
            }
        return x

    packed = [pack(x) for x in jax.tree.leaves(state)]
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(path), {"leaves": packed}, force=True)


def load_checkpoint(path: str, like=None, comm=None, device=None):
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``like`` (optional) supplies the treedef to rebuild nested structure —
    pass any pytree with the same structure (e.g. the state object the
    checkpoint was created from). Without it a flat leaf list is returned.
    DNDarray leaves come back re-sharded over ``comm``."""
    if not supports_checkpoint():
        raise RuntimeError(
            "checkpointing requires orbax (pip install 'heat_tpu[checkpoint]')"
        )
    import jax
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.abspath(path))
    leaves = restored["leaves"]

    def unpack(x):
        if isinstance(x, dict) and "__dndarray__" in x:
            split = int(x["split"])
            split = None if split < 0 else split
            meta = np.asarray(x["gshape"])
            buf_ndim = np.asarray(x["__dndarray__"]).ndim
            if meta.size == buf_ndim + 1 and int(meta[0]) == buf_ndim:
                # length-prefixed record: [ndim, *shape]
                gshape = tuple(int(s) for s in meta[1 : 1 + int(meta[0])])
            else:
                # pre-prefix record: the raw shape
                gshape = tuple(int(s) for s in meta)
            buf = np.asarray(x["__dndarray__"])
            if split is not None:
                # stored buffer is the padded physical layout; slice back to
                # the logical extent before resharding (the current mesh may
                # differ from the one that wrote the checkpoint)
                sl = [slice(None)] * buf.ndim
                sl[split] = slice(0, gshape[split])
                buf = buf[tuple(sl)]
            return _array(buf, split=split, comm=comm, device=device)
        return x

    leaves = [unpack(x) for x in leaves]
    if like is not None:
        return jax.tree.unflatten(jax.tree.structure(like), leaves)
    return leaves


__all__ += ["save_checkpoint", "load_checkpoint"]


def save(data: DNDarray, path: str, *args, **kwargs):
    """Save by file extension (reference io.py:923)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"Expected data to be DNDarray, but was {type(data)}")
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    ext = os.path.splitext(path)[-1]
    if ext in (".h5", ".hdf5"):
        return save_hdf5(data, path, *args, **kwargs)
    if ext in (".nc", ".netcdf"):
        return save_netcdf(data, path, *args, **kwargs)
    if ext == ".csv":
        return save_csv(data, path, *args, **kwargs)
    if ext == ".npy":
        return save_npy(data, path)
    raise ValueError(f"Unsupported file extension {ext}")
