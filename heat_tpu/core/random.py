"""Pseudo-random number generation.

The reference implements its own counter-based Threefry-12 generator
(reference: heat/core/random.py:39-1065) so that every rank can generate only
its slice of one global bit-stream, bit-identical at any process count. JAX's
PRNG is the same construction natively (counter-based threefry, Salmon et al.
2011), so this module is a thin stateful façade over `jax.random`: a global
``(seed, counter)`` pair (reference random.py:39-42) derives one fresh key per
call, and results are device-count-invariant by construction.
"""

from __future__ import annotations

import builtins
import time
from typing import Optional, Tuple, Type, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .communication import sanitize_comm
from .devices import sanitize_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_shape

__all__ = [
    "get_state",
    "normal",
    "permutation",
    "rand",
    "ranf",
    "randint",
    "random_integer",
    "randn",
    "random",
    "random_sample",
    "randperm",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
    "uniform",
]

# global generator state (reference random.py:39-42)
__seed: int = 0
__counter: int = 0


def __init_seed() -> None:
    global __seed, __counter
    if __seed is None:
        __seed = int(time.time() * 1000) & 0x7FFFFFFF
        __counter = 0


def _next_key() -> jax.Array:
    """One fresh threefry key per draw: fold the call counter into the seed
    key (the reference advances a 128-bit counter, random.py:55)."""
    global __counter
    key = jax.random.fold_in(jax.random.PRNGKey(__seed), __counter)
    __counter += 1
    return key


def _wrap(data, split, device, comm, dtype=None) -> DNDarray:
    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    return DNDarray.from_logical(data, split, device, comm, dtype)


def get_state() -> Tuple[str, int, int, int, float]:
    """Internal state tuple ('Threefry', seed, counter, 0, 0.0) (reference
    random.py:203)."""
    return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple) -> None:
    """Restore generator state (reference random.py:778)."""
    global __seed, __counter
    if not isinstance(state, tuple) or len(state) not in (3, 5):
        raise ValueError("state needs to be a 3- or 5-tuple")
    if state[0] != "Threefry":
        raise ValueError("algorithm must be 'Threefry'")
    __seed = builtins.int(state[1])
    __counter = builtins.int(state[2])


def seed(seed: Optional[int] = None) -> None:
    """(Re-)seed the global generator (reference random.py:760)."""
    global __seed, __counter
    if seed is None:
        seed = int(time.time() * 1000) & 0x7FFFFFFF
    __seed = builtins.int(seed)
    __counter = 0


def normal(mean=0.0, std=1.0, shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Normal distribution with given mean/std (reference random.py:268)."""
    if shape is None:
        shape = ()
    shape = sanitize_shape(shape) if shape != () else ()
    dtype = types.canonical_heat_type(dtype)
    if not issubclass(dtype, types.floating):
        raise ValueError("dtype must be a float type")
    data = jax.random.normal(_next_key(), shape, dtype=dtype.jnp_type())
    data = data * jnp.asarray(std, data.dtype) + jnp.asarray(mean, data.dtype)
    return _wrap(data, split, device, comm, dtype)


def uniform(low=0.0, high=1.0, size=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform samples in [low, high) (numpy-style extension; the
    reference's uniform surface is ``rand``/``random_sample``, reference
    random.py:396). Array-valued bounds broadcast, as in numpy."""
    if size is None:
        # numpy semantics: sample shape follows the broadcast bounds
        shape = np.broadcast_shapes(np.shape(low), np.shape(high))
    else:
        shape = sanitize_shape(size)
    dtype = types.canonical_heat_type(dtype)
    if not issubclass(dtype, types.floating):
        raise ValueError("dtype must be a float type")
    jt = dtype.jnp_type()
    data = jax.random.uniform(
        _next_key(), shape, dtype=jt,
        minval=jnp.asarray(low, jt), maxval=jnp.asarray(high, jt),
    )
    return _wrap(data, split, device, comm, dtype)


def permutation(x: Union[int, DNDarray]) -> DNDarray:
    """Random permutation of range(x) or a global shuffle of x's first axis
    (reference random.py:326)."""
    if isinstance(x, builtins.int):
        return randperm(x)
    if not isinstance(x, DNDarray):
        raise TypeError(f"x must be int or DNDarray, got {type(x)}")
    perm = jax.random.permutation(_next_key(), x.shape[0])
    if x.split is not None and x.comm.size > 1:
        # sharded gather keeps the shuffle distributed — no replicated
        # intermediate (the advanced-indexing engine carries ANY split
        # through a row gather: axis-0 take leaves other-axis pads alone)
        from .indexing import _advanced_take

        return _advanced_take(x, 0, perm)
    data = jnp.take(x._logical(), perm, axis=0)
    return DNDarray.from_logical(data, x.split, x.device, x.comm, x.dtype)


def rand(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples (reference random.py:396)."""
    if not d:
        shape = ()
    else:
        shape = sanitize_shape(d)
    dtype = types.canonical_heat_type(dtype)
    if not issubclass(dtype, types.floating):
        raise ValueError("dtype must be a float type")
    data = jax.random.uniform(_next_key(), shape, dtype=dtype.jnp_type())
    return _wrap(data, split, device, comm, dtype)


def randint(low, high=None, size=None, dtype=types.int32, split=None, device=None, comm=None) -> DNDarray:
    """Random integers in [low, high) (reference random.py:473)."""
    if high is None:
        low, high = 0, low
    if low >= high:
        raise ValueError(f"low >= high ({low} >= {high})")
    if size is None:
        size = ()
    elif isinstance(size, builtins.int):
        size = (size,)
    else:
        size = sanitize_shape(size)
    dtype = types.canonical_heat_type(dtype)
    if not issubclass(dtype, types.integer):
        raise ValueError("dtype must be an integer type")
    data = jax.random.randint(_next_key(), size, low, high, dtype=dtype.jnp_type())
    return _wrap(data, split, device, comm, dtype)


random_integer = randint


def randn(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard normal samples (reference random.py:580, Box-Muller via
    Kundu inverse there; jax.random.normal here)."""
    return normal(0.0, 1.0, d if d else (), dtype=dtype, split=split, device=device, comm=comm)


def random_sample(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) with a shape argument (reference random.py aliases)."""
    if shape is None:
        shape = ()
    shape = sanitize_shape(shape) if shape != () else ()
    return rand(*shape, dtype=dtype, split=split, device=device, comm=comm)


random = random_sample
ranf = random_sample
sample = random_sample


def randperm(n: int, dtype=types.int64, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of [0, n) (reference random.py:637)."""
    if not isinstance(n, builtins.int):
        raise TypeError(f"n must be int, got {type(n)}")
    data = jax.random.permutation(_next_key(), n).astype(
        types.canonical_heat_type(dtype).jnp_type()
    )
    return _wrap(data, split, device, comm)


def standard_normal(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard normal with a shape argument (reference random.py)."""
    if shape is None:
        shape = ()
    shape = sanitize_shape(shape) if shape != () else ()
    return normal(0.0, 1.0, shape, dtype=dtype, split=split, device=device, comm=comm)
