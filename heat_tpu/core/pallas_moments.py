"""Pallas TPU kernel: single-HBM-read column moments (mean + M2).

``ht.var`` is the numerically-safe two-pass form (mean, then centered
square sum) — under one jit that is two full HBM reads of X, capping the
statistical-moments benchmark at ~50% of the bandwidth roofline. This
kernel computes both moments in ONE pass using the chunk-parallel Welford
combine (the same merge rule the reference applies across MPI ranks,
statistics.py:803-828, applied here across row blocks): each block's
(count, mean, M2) is computed stably in VMEM and merged into running
accumulators — X is read exactly once and the result matches the two-pass
form to f32 accuracy (no E[x^2]-E[x]^2 cancellation).

Wired into :func:`heat_tpu.core.statistics.var` (and through it ``std``)
for the single-device TPU f32 axis-0 reduction on 2-D arrays — the
benchmark shape and the common "feature statistics" case. Everything else
keeps the two-pass form.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "chan_merge",
    "column_moments",
    "sharded_column_moments",
    "pallas_moments_applicable",
]

_I0 = np.int32(0)
_MAX_D = 4096  # (bm, dp) f32 block + 4 (8, dp) accumulators must fit VMEM


def _round_up(v: int, m: int) -> int:
    return -(-v // m) * m


def chan_merge(na, mean_a, m2_a, nb, mean_b, m2_b):
    """Chan/Welford pairwise combine of two (count, mean, M2) moment
    carries — the SAME merge rule the kernel applies across row blocks
    (``_moments_kernel``) and :func:`sharded_column_moments` applies
    across shards, exposed as the mergeable-carry algebra of
    :class:`heat_tpu.streaming.StreamingMoments`: ``partial_fit`` chunks
    combine associatively through this exact formula, so a resumed
    stream reproduces the uninterrupted carry bit-for-bit. Host-side
    arithmetic (python/numpy operands — the streaming carry is kept in
    float64 on the host); an empty pair (``tot == 0``) passes the left
    side through unchanged."""
    tot = na + nb
    if float(tot) == 0.0:
        return tot, mean_a, m2_a
    delta = mean_b - mean_a
    mean = mean_a + delta * (nb / tot)
    m2 = m2_a + m2_b + delta * delta * (na * nb / tot)
    return tot, mean, m2


def _moments_kernel(lim_ref, x_ref, mean_ref, m2_ref, mean_s, m2_s, cnt_s, *, bm):
    """Grid = (num_row_blocks,), sequential; Welford-combine across blocks."""
    i = pl.program_id(0)
    nb = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        mean_s[:] = jnp.zeros_like(mean_s)
        m2_s[:] = jnp.zeros_like(m2_s)
        cnt_s[0] = jnp.float32(0.0)

    xb = x_ref[:]  # (bm, dp) f32
    row = i * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
    # LOCAL valid-row count (inside shard_map each shard passes its own
    # limit; block round-up pads past it drop out)
    valid = (row < lim_ref[0]).astype(jnp.float32)  # (bm, 1)
    nv = jnp.sum(valid)  # block count (scalar f32)

    @pl.when(nv > 0)
    def _combine():
        xv = xb * valid
        bsum = jnp.sum(xv, axis=0, keepdims=True)  # (1, dp)
        bmean = bsum / nv
        d = (xb - bmean) * valid
        bm2 = jnp.sum(d * d, axis=0, keepdims=True)  # (1, dp)
        cnt = cnt_s[0]
        tot = cnt + nv
        delta = bmean - mean_s[0:1, :]
        mean_new = mean_s[0:1, :] + delta * (nv / tot)
        m2_new = m2_s[0:1, :] + bm2 + delta * delta * (cnt * nv / tot)
        mean_s[:] = jnp.broadcast_to(mean_new, mean_s.shape)
        m2_s[:] = jnp.broadcast_to(m2_new, m2_s.shape)
        cnt_s[0] = tot

    @pl.when(i == nb - 1)
    def _flush():
        mean_ref[:] = mean_s[:]
        m2_ref[:] = m2_s[:]


@functools.partial(
    jax.jit, static_argnames=("n", "block_m", "interpret", "pre_map")
)
def column_moments(
    x: jax.Array, n: int, block_m: int = 1024, interpret: bool = False,
    lim=None, pre_map=None,
):
    """(mean (d,), M2 (d,)) over the first axis of an (m, d) f32 array,
    counting only the first ``n`` rows (tail-pad aware). One HBM read.

    ``pre_map`` (static) grafts a single-array elementwise prologue into
    the same program — the moments of ``pre_map(x)`` from one read of
    ``x``. This is the DIRECT-caller graft slot; the statistics layer's
    chain grafting (``statistics._pallas_moments_fused``) instead
    composes the pending chain around this kernel at the program level
    (site ``fusion_moments``): chain scalars are *runtime* arguments
    there (programs shared across scalar values — baking them into a
    static ``pre_map`` closure would fork one executable per value), and
    the pad mask must apply to GLOBAL row indices, which a per-shard
    ``pre_map`` inside ``shard_map`` cannot express. ``pre_map`` output
    must be finite on rows past ``n`` (the validity multiply would turn
    ``0·inf`` into NaN)."""
    if pre_map is not None:
        x = pre_map(x)
    m, d = x.shape
    dp = _round_up(d, 64)  # 64-lane granularity: d=64 stays unpadded
    bm = min(block_m, _round_up(m, 8))
    mp = _round_up(m, bm)
    if (mp, dp) != (m, d):
        x = jnp.pad(x.astype(jnp.float32), ((0, mp - m), (0, dp - d)))
    else:
        x = x.astype(jnp.float32)
    if lim is None:
        lim = jnp.full((1,), n, jnp.int32)
    mean_o, m2_o = pl.pallas_call(
        functools.partial(_moments_kernel, bm=bm),
        grid=(mp // bm,),
        in_specs=[
            # explicit i32 index map: a bare SMEM BlockSpec synthesizes a
            # default map whose literals trace as i64 under jax_enable_x64,
            # which Mosaic cannot legalize ("func.return(i64)")
            pl.BlockSpec((1,), lambda i: (_I0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((bm, dp), lambda i: (i, _I0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((8, dp), lambda i: (_I0, _I0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, dp), lambda i: (_I0, _I0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((8, dp), jnp.float32),
            jax.ShapeDtypeStruct((8, dp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, dp), jnp.float32),
            pltpu.VMEM((8, dp), jnp.float32),
            pltpu.SMEM((1,), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(lim.astype(jnp.int32), x)
    return mean_o[0, :d], m2_o[0, :d]


@functools.partial(
    jax.jit, static_argnames=("comm", "n", "block_m", "interpret", "pre_map")
)
def sharded_column_moments(
    comm, x: jax.Array, n: int, block_m: int = 1024, interpret: bool = False,
    pre_map=None,
):
    """Multi-device variant: per-shard (count, mean, M2) from the fused
    kernel, then the closed-form Welford merge across shards with two
    psums — mean_g = psum(n_s mean_s)/n; M2_g = psum(M2_s) +
    psum(n_s (mean_s - mean_g)^2). X is still read exactly once.
    ``pre_map`` applies per shard before the kernel (elementwise, so
    shard-local) — see :func:`column_moments`."""
    p = comm.size
    m, _d = x.shape
    c_rows = m // p

    def shard_fn(xs):
        rank = comm.axis_index()
        lim = jnp.clip(n - rank * c_rows, 0, c_rows).astype(jnp.int32)
        mean_s, m2_s = column_moments(
            xs, n, block_m=block_m, interpret=interpret,
            lim=lim.reshape((1,)), pre_map=pre_map,
        )
        ns = lim.astype(jnp.float32)
        # comm wrapper (not raw lax.psum) so the hops are visible to the
        # HLO auditor/cost model; pinned exact — the Chan/Welford merge is
        # bit-pinned by tests and predates the collective-precision knob
        # (heatlint HL002)
        mean_g = comm.psum(ns * mean_s, precision="off") / jnp.float32(n)
        dlt = mean_s - mean_g
        m2_g = comm.psum(m2_s + ns * dlt * dlt, precision="off")
        return mean_g, m2_g

    return jax.shard_map(
        shard_fn,
        mesh=comm.mesh,
        in_specs=(comm.spec(0, 2),),
        out_specs=(comm.spec(None, 1), comm.spec(None, 1)),
        check_vma=False,
    )(x)


def pallas_moments_applicable(comm_size: int, split, ndim: int, axis, d: int, jnp_dtype) -> bool:
    """TPU f32 axis-0 reductions on 2-D arrays; multi-device needs the
    rows sharded (split=0)."""
    return (
        jax.default_backend() == "tpu"
        and (comm_size == 1 or split == 0)
        and ndim == 2
        and axis == 0
        and d <= _MAX_D
        and jnp_dtype == jnp.float32
    )
