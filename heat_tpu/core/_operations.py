"""Generic operation machinery.

Re-design of reference heat/core/_operations.py:25-481, whose four wrappers
(`__binary_op`, `__local_op`, `__reduce_op`, `__cum_op`) each hand-roll MPI
traffic for the split axis (Bcast of broadcast dims, Allreduce of partial
reductions, Exscan for cumulative ops). Under XLA the wrappers reduce to
dispatching a jnp computation with correct *metadata* (result split, dtype)
and correct handling of the tail-pad region:

* fast path — no operand is padded: apply jnp directly to the physical
  buffers; XLA propagates shardings and inserts any collectives.
* padded reductions/scans crossing the split axis first neutralize the pad
  via ``DNDarray._masked(neutral)``; reductions along other axes simply carry
  the pad through (pad in → pad out).
* binary ops with one padded operand pad the other operand's aligned
  dimension so physical shapes broadcast.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Optional, Sequence, Tuple, Type, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import sanitation
from . import types
from .communication import MeshCommunication
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = ["binary_op", "local_op", "reduce_op", "cum_op"]

Scalar = (builtins.int, builtins.float, builtins.bool, builtins.complex)


def _as_operand(x, comm_hint=None, device_hint=None):
    """Normalize an operand: DNDarrays and python scalars pass through (weak
    typing preserves numpy promotion), everything else becomes a replicated
    DNDarray."""
    from . import factories

    if isinstance(x, DNDarray) or isinstance(x, Scalar) or isinstance(x, np.generic):
        return x
    return factories.array(x, device=device_hint, comm=comm_hint)


def binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Generic binary operation with broadcasting and split reconciliation
    (reference _operations.py:25-181)."""
    fn_kwargs = fn_kwargs or {}
    arrays = [a for a in (t1, t2) if isinstance(a, DNDarray)]
    comm = arrays[0].comm if arrays else None
    device = arrays[0].device if arrays else None
    t1 = _as_operand(t1, comm, device)
    t2 = _as_operand(t2, comm, device)
    arrays = [a for a in (t1, t2) if isinstance(a, DNDarray)]
    if not arrays:
        raise TypeError(
            f"expected at least one DNDarray operand, got {type(t1)}, {type(t2)}"
        )
    comm = arrays[0].comm
    device = arrays[0].device

    shape1 = t1.shape if isinstance(t1, DNDarray) else ()
    shape2 = t2.shape if isinstance(t2, DNDarray) else ()
    out_shape = broadcast_shape(shape1, shape2)
    ndim_out = len(out_shape)

    # map each operand's split into the output frame (right-aligned broadcast)
    def out_split_of(a):
        if not isinstance(a, DNDarray) or a.split is None:
            return None
        return a.split + (ndim_out - a.ndim)

    s1, s2 = out_split_of(t1), out_split_of(t2)
    if s1 is not None and s2 is not None and s1 != s2:
        raise ValueError(
            f"operands are distributed along different axes (splits {t1.split}/{t2.split}); "
            f"resplit one operand first"
        )
    out_split = s1 if s1 is not None else s2

    padded = any(isinstance(a, DNDarray) and a.pad_count for a in (t1, t2))

    if out is None:
        from . import fusion

        if fusion.active():
            deferred = fusion.defer_binary(
                operation, t1, t2, fn_kwargs, out_shape, out_split,
                comm, device, padded,
            )
            if deferred is not None:
                return deferred

    def phys(a):
        if not isinstance(a, DNDarray):
            return a
        buf = a.larray
        if out_split is not None and padded:
            # align this operand's dim with the output split dim and pad it to
            # the physical size if it spans the full logical extent
            own_dim = out_split - (ndim_out - a.ndim)
            if own_dim >= 0 and a.split is None and buf.shape[own_dim] == out_shape[out_split]:
                P = comm.padded_size(out_shape[out_split])
                pad = [(0, 0)] * a.ndim
                pad[own_dim] = (0, P - buf.shape[own_dim])
                buf = jnp.pad(buf, pad)
        return buf

    result = operation(phys(t1), phys(t2), **fn_kwargs)

    out_gshape = out_shape
    res = DNDarray(
        result,
        out_gshape,
        types.canonical_heat_type(result.dtype),
        out_split,
        device,
        comm,
        True,
    )
    # physical sanity: result must obey the tail-pad invariant
    expected = comm.padded_shape(out_gshape, out_split)
    if tuple(result.shape) != expected:
        res = DNDarray.from_logical(result[tuple(slice(0, n) for n in out_gshape)]
                                    if tuple(result.shape) != out_gshape else result,
                                    out_split, device, comm)
    if out is not None:
        sanitation.sanitize_out(out, out_gshape, out_split, device)
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    **kwargs,
) -> DNDarray:
    """Elementwise operation, embarrassingly parallel across shards
    (reference _operations.py:281-352)."""
    sanitation.sanitize_in(x)
    if out is None:
        from . import fusion

        if fusion.active():
            deferred = fusion.defer_local(operation, x, kwargs)
            if deferred is not None:
                return deferred
    result = operation(x.larray, **kwargs)
    res = DNDarray(
        result,
        x.shape,
        types.canonical_heat_type(result.dtype),
        x.split,
        x.device,
        x.comm,
        True,
    )
    if out is not None:
        sanitation.sanitize_out(out, x.shape, x.split, x.device)
        out.larray = result.astype(out.dtype.jnp_type())
        return out
    return res


def reduce_op(
    operation: Callable,
    x: DNDarray,
    axis: Union[int, Tuple[int, ...], None],
    neutral: Any,
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    dtype: Optional[Type[types.datatype]] = None,
    **kwargs,
) -> DNDarray:
    """Generic reduction (reference _operations.py:355-478: local partial
    reduce + Allreduce over the split axis, neutral elements for empty
    shards). Here: neutralize the pad when the reduction crosses the split
    axis, then one jnp reduction — XLA inserts the cross-shard combine.

    A pending fused elementwise chain on ``x`` is not flushed first: with
    Fusion 2.0 on (``HEAT_TPU_FUSION_REDUCE``, default) the chain is
    *absorbed* — chain, masked-neutral pad fill, reduction and collective
    tail compile as ONE cached program (core/fusion.py `absorb_reduce`)."""
    sanitation.sanitize_in(x)
    axes = sanitize_axis(x.shape, axis)
    if axes is None:
        red_axes = tuple(range(x.ndim))
    elif isinstance(axes, builtins.int):
        red_axes = (axes,)
    else:
        red_axes = tuple(axes)

    split = x.split
    crosses_split = split is not None and split in red_axes

    # output metadata (before dispatch: the absorbing path pins the result
    # sharding from it)
    if split is None or crosses_split:
        out_split = None
    else:
        if keepdims:
            out_split = split
        else:
            out_split = split - sum(1 for a in red_axes if a < split)
    if keepdims:
        out_gshape = tuple(1 if d in red_axes else s for d, s in enumerate(x.shape))
    else:
        out_gshape = tuple(s for d, s in enumerate(x.shape) if d not in red_axes)
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)

    result = None
    from . import fusion

    if fusion.active():
        result = fusion.absorb_reduce(
            operation, x, red_axes, axis, neutral, keepdims, kwargs,
            out_gshape, out_split, crosses_split,
            dtype.jnp_type() if dtype is not None else None,
        )
    if result is None:
        buf = x._masked(neutral) if (crosses_split and x.pad_count) else x.larray
        result = operation(buf, axis=red_axes if axis is not None else None, keepdims=keepdims, **kwargs)
        if dtype is not None:
            result = result.astype(dtype.jnp_type())

    res = DNDarray(
        result,
        out_gshape,
        types.canonical_heat_type(result.dtype),
        out_split,
        x.device,
        x.comm,
        True,
    )
    if out is not None:
        sanitation.sanitize_out(out, out_gshape, out_split, x.device)
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res


def cum_op(
    operation: Callable,
    x: DNDarray,
    axis: int,
    neutral: Any,
    out: Optional[DNDarray] = None,
    dtype: Optional[Type[types.datatype]] = None,
) -> DNDarray:
    """Generic cumulative operation (reference _operations.py:184-278: local
    cum + Exscan + combine). Tail-pad sits at the global end of the split
    dim, so a masked single jnp scan is exact on the logical region; XLA
    lowers the cross-shard carry."""
    sanitation.sanitize_in(x)
    axis = sanitize_axis(x.shape, axis)
    if not isinstance(axis, builtins.int):
        raise TypeError(f"axis must be an integer, got {axis!r}")
    buf = x._masked(neutral) if (x.split == axis and x.pad_count) else x.larray
    result = operation(buf, axis=axis)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jnp_type())
    res = DNDarray(
        result,
        x.shape,
        types.canonical_heat_type(result.dtype),
        x.split,
        x.device,
        x.comm,
        True,
    )
    if out is not None:
        sanitation.sanitize_out(out, x.shape, x.split, x.device)
        out.larray = res.larray.astype(out.dtype.jnp_type())
        return out
    return res
