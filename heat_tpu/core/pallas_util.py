"""Shared Pallas-kernel helpers.

``dot_f32`` is the precision dispatcher for in-kernel f32 contractions.
Besides the ``jax.lax.Precision`` tiers it accepts ``"bf16x3"``: an
explicit three-pass bf16 split-product — ``a·b ≈ hi(a)·hi(b) +
hi(a)·lo(b) + lo(a)·hi(b)`` with ``hi(x) = bf16(x)`` and
``lo(x) = bf16(x − hi(x))`` — which is numerically the classical bf16x3
compensation (the same error class as ``Precision.HIGH``) but built from
three DEFAULT-tier dots that Mosaic provably lowers onto the MXU. The
round-5 on-chip capture (artifacts/bench_tpu_session_r5a.json) measured
the HIGH-tier in-kernel dot at ~36× below the cdist write roofline —
consistent with an off-MXU (VPU-loop) lowering — so guaranteed-MXU
multi-pass form matters independently of the enum tiers.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp

__all__ = ["dot_f32", "DotPrecision"]

DotPrecision = Union[jax.lax.Precision, str]


def dot_f32(a, b, dimension_numbers, precision: DotPrecision):
    """f32-accumulated dot_general with a sweepable precision strategy.

    ``precision`` is a ``jax.lax.Precision`` tier (the enum or its name as
    a string, e.g. ``"HIGHEST"``) passed through to one ``dot_general``,
    or the string ``"bf16x3"`` for the explicit MXU-guaranteed three-pass
    split product.
    """
    if isinstance(precision, str) and precision != "bf16x3":
        precision = getattr(jax.lax.Precision, precision)
    if precision == "bf16x3":
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
        a_hi = a.astype(jnp.bfloat16)
        b_hi = b.astype(jnp.bfloat16)
        a_lo = (a - a_hi.astype(jnp.float32)).astype(jnp.bfloat16)
        b_lo = (b - b_hi.astype(jnp.float32)).astype(jnp.bfloat16)

        def _d(x, y):
            return jax.lax.dot_general(
                x, y, dimension_numbers,
                preferred_element_type=jnp.float32,
            )

        # hi·lo + lo·hi first: the small terms accumulate before the
        # dominant hi·hi lands (marginally better rounding, same passes)
        return (_d(a_hi, b_lo) + _d(a_lo, b_hi)) + _d(a_hi, b_hi)
    return jax.lax.dot_general(
        a, b, dimension_numbers,
        precision=precision,
        preferred_element_type=jnp.float32,
    )
