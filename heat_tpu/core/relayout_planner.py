"""Communication-aware relayout planning (ISSUE 6 tentpole).

Every resplit used to lower as ONE monolithic slice→repad→reshard program
(`DNDarray._relayout`). That is the right call when it fits — one dispatch,
minimal wire volume — but near the HBM ceiling the monolithic program's
temporaries are what break first: `memory_guard` could only degrade
(fusion window-flush, gc) and then **error**. "Memory-efficient array
redistribution through portable collective communication"
(arXiv:2112.01075) observes that any resplit decomposes into chains of
smaller collectives with *bounded peak memory*; this module is that
observation made operational:

* :func:`plan` enumerates candidate plans for a relayout
  ``(gshape, itemsize, src split, dst split, mesh)``:

  - **monolithic** — today's single cached program, kept verbatim as the
    fast path (site ``relayout``; auto mode with no budget never builds
    anything else, so dispatch stays one dict lookup);
  - **alltoall** — an explicit `shard_map` kernel (pad the destination
    axis locally, one ``lax.all_to_all``, slice the source axis locally).
    Same wire volume as monolithic with a *pinned* collective schedule —
    the plan to force when XLA's monolithic lowering must not be trusted;
  - **chunked** — ``k`` destination-shard-aligned column blocks, each
    moved by its own small cached program into a donated accumulator.
    Each stage is exactly ONE all-gather of ``~B/k`` bytes (verified by
    the per-stage HLO audit), so peak temp memory is ``O(B/k)`` instead
    of ``O(B)`` — the bounded-memory decomposition. The price is wire
    volume: an aligned chunk lands whole on one destination shard, so a
    stage all-gathers ``chunk·(p-1)`` bytes and the chunked total is
    ``~B·(p-1)`` vs the monolithic all-to-all's ``B·(p-1)/p``. The
    planner therefore picks chunked ONLY when monolithic cannot fit.

* scoring uses the analytic collective cost model
  (:mod:`heat_tpu.telemetry.collectives`) for wire bytes plus a
  per-device temp-memory model calibrated against XLA CPU
  ``memory_analysis()`` (tests pin measured ≤ model); feasibility under
  ``HEAT_TPU_HBM_BUDGET`` mirrors `memory_guard.preflight` arithmetic
  (``live + temp + output ≤ budget``), so a plan the planner emits is a
  plan the pre-flight guard will admit — plan selection *replaces* the
  error-at-the-ceiling ladder step for relayouts.

* :func:`run` executes a decomposed plan as a chain of
  :func:`~heat_tpu.core.program_cache.cached_program` stages — each stage
  carries its own structural signature (site ``relayout_chunk`` /
  ``relayout_a2a`` / ``relayout_init``), its own HLO audit
  (``relayout_stage`` records, predicted per-stage cost), and the
  resilience retry guard every cached program gets. Repeat dispatch of
  the same plan is pure cache hits (CompileWatcher: zero recompiles).

Knob: ``HEAT_TPU_RELAYOUT_PLAN=auto|monolithic|chunked|alltoall``
(default ``auto``). ``monolithic`` restores the pre-planner behavior
bit-for-bit; ``chunked``/``alltoall`` force the decomposition regardless
of budget (chunk count then sized from
:func:`heat_tpu.resilience.memory_guard.temp_budget`). docs/TUNING_RUNBOOK.md
§0.8 discusses when each wins.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from heat_tpu import _knobs as knobs

from .. import telemetry

__all__ = [
    "PlanStage",
    "RelayoutPlan",
    "mode",
    "ring_overlap",
    "plan",
    "maybe_plan",
    "run",
    "plan_memory",
    "bench_field",
    "monolithic_need",
    "chunk_stage_need",
    "MAX_CHUNKS",
]

_ENV_MODE = "HEAT_TPU_RELAYOUT_PLAN"
_MODES = ("auto", "monolithic", "chunked", "alltoall")

# Hard cap on decomposition width: each chunk is its own small cached
# program, so k bounds both registry entries and per-plan compile count.
MAX_CHUNKS = 32

# Per-device temp model, calibrated against XLA CPU memory_analysis():
# a monolithic s->t relayout measures ~1.75x its per-device shard in
# temporaries; a chunk stage measures ~1.25x its chunk. Both models round
# UP (2x / 1.5x) so "the model says it fits" stays conservative.
_MONO_TEMP_FACTOR = 2.0
_CHUNK_TEMP_FACTOR = 1.5


def mode() -> str:
    """The active ``HEAT_TPU_RELAYOUT_PLAN`` value (malformed -> auto)."""
    raw = (knobs.raw(_ENV_MODE, "") or "").strip().lower()
    return raw if raw in _MODES else "auto"


def ring_overlap() -> bool:
    """Whether the double-buffered ring schedule is active
    (``HEAT_TPU_RING_OVERLAP``, default on): the ring kernels
    (spatial cdist/manhattan/rbf, TSQR gram ring) issue the next hop's
    ``ppermute`` *before* consuming the current block — the permute is
    data-independent of the local GEMM, so XLA's latency-hiding
    scheduler can ride it under the compute — and skip the final hop
    that only returns each block home (``p-1`` hops instead of ``p``).
    Tile values and update order are unchanged, so results are
    bit-identical to the serial schedule; ``HEAT_TPU_RING_OVERLAP=0``
    restores the serial p-hop kernels verbatim."""
    return knobs.raw("HEAT_TPU_RING_OVERLAP", "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


@dataclass(frozen=True)
class PlanStage:
    """One chunk stage: destination-axis block ``[lo, hi)`` moved by one
    cached program, with its analytic collective cost and per-device temp
    estimate."""

    lo: int
    hi: int
    cost: "telemetry.collectives.CollectiveCost"
    temp_bytes: int

    def summary(self) -> dict:
        return {
            "lo": self.lo, "hi": self.hi, "collective": self.cost.kind,
            "wire_bytes": self.cost.bytes, "temp_bytes": self.temp_bytes,
        }


@dataclass(frozen=True)
class RelayoutPlan:
    """The selected relayout schedule for one layout signature."""

    kind: str                       # "monolithic" | "alltoall" | "chunked"
    gshape: Tuple[int, ...]
    itemsize: int
    src_split: Optional[int]
    dst_split: Optional[int]
    chunk_axis: Optional[int]       # destination axis the chunks tile
    stages: Tuple[PlanStage, ...]   # empty for monolithic/alltoall
    predicted_bytes: int            # total wire bytes over all stages
    temp_bytes: int                 # analytic peak per-device temp (model)
    reason: str                     # why this plan won (event/debugging)

    @property
    def chunks(self) -> int:
        return len(self.stages)

    def summary(self) -> dict:
        """The ``relayout_plan`` telemetry-event payload (schema in
        docs/OBSERVABILITY.md)."""
        return {
            "plan": self.kind,
            "gshape": list(self.gshape),
            "src_split": self.src_split,
            "dst_split": self.dst_split,
            "chunks": self.chunks,
            "stages": self.chunks if self.kind == "chunked" else 1,
            "predicted_bytes": self.predicted_bytes,
            "temp_bytes": self.temp_bytes,
            "reason": self.reason,
        }


def _phys_numel(gshape: Sequence[int], split: Optional[int], nproc: int) -> int:
    """Element count of the tail-padded physical buffer."""
    n = 1
    for d, s in enumerate(gshape):
        if d == split:
            s = -(-int(s) // nproc) * nproc
        n *= int(s)
    return n


def monolithic_need(
    gshape: Sequence[int],
    itemsize: int,
    src_split: Optional[int],
    dst_split: Optional[int],
    nproc: int,
) -> int:
    """Analytic per-device (temp + output) bytes of the monolithic
    relayout program — the quantity `memory_guard.preflight` budgets.
    Replicated destinations hold the whole output on every device."""
    if nproc <= 1 or src_split == dst_split:
        return 0
    b_src = _phys_numel(gshape, src_split, nproc) * int(itemsize)
    b_dst = _phys_numel(gshape, dst_split, nproc) * int(itemsize)
    out = b_dst if dst_split is None else b_dst // nproc
    if src_split is None:
        return out  # local slice, no temp
    if dst_split is None:
        return out  # all-gather: measured temp ~0, output dominates
    return int(_MONO_TEMP_FACTOR * b_src / nproc) + out


def chunk_stage_need(
    gshape: Sequence[int],
    itemsize: int,
    src_split: int,
    dst_split: int,
    width: int,
    nproc: int,
) -> Tuple[int, int]:
    """(per-device temp, per-device output) byte estimates for one chunk
    stage of ``width`` destination-axis columns."""
    other = _phys_numel(gshape, src_split, nproc) // max(
        1, int(gshape[dst_split])
    )
    chunk = other * int(width) * int(itemsize)
    out = _phys_numel(gshape, dst_split, nproc) * int(itemsize) // nproc
    return int(_CHUNK_TEMP_FACTOR * chunk), out


def _monolithic(gshape, itemsize, src, dst, nproc, reason) -> RelayoutPlan:
    cost = telemetry.collectives.relayout_cost(
        gshape, itemsize, src, dst, nproc
    )
    return RelayoutPlan(
        kind="monolithic", gshape=tuple(int(s) for s in gshape),
        itemsize=int(itemsize), src_split=src, dst_split=dst,
        chunk_axis=None, stages=(),
        predicted_bytes=int(cost.bytes),
        temp_bytes=monolithic_need(gshape, itemsize, src, dst, nproc),
        reason=reason,
    )


def _alltoall(gshape, itemsize, src, dst, nproc, reason) -> RelayoutPlan:
    cost = telemetry.collectives.relayout_cost(
        gshape, itemsize, src, dst, nproc
    )
    return RelayoutPlan(
        kind="alltoall", gshape=tuple(int(s) for s in gshape),
        itemsize=int(itemsize), src_split=src, dst_split=dst,
        chunk_axis=None, stages=(),
        predicted_bytes=int(cost.bytes),
        temp_bytes=monolithic_need(gshape, itemsize, src, dst, nproc),
        reason=reason,
    )


def _chunked(
    gshape, itemsize, src, dst, nproc, width: int, reason: str
) -> RelayoutPlan:
    """Build the chunked plan: destination-shard-aligned blocks of
    ``width`` columns along ``dst`` (clipped at shard and logical edges),
    one stage per block."""
    gshape = tuple(int(s) for s in gshape)
    extent = gshape[dst]
    pad_extent = -(-extent // nproc) * nproc
    cm = pad_extent // nproc  # destination shard width
    width = max(1, min(int(width), cm))
    # Even subdivision keeps the CHUNK SHAPES to at most two (full blocks
    # + one clipped logical tail) — which is what bounds the per-stage
    # HLO-audit memo and temp-model variety. Each stage still bakes its
    # static (lo, hi) into its own small program (k compiles, k registry
    # entries, capped by MAX_CHUNKS): that is deliberate — a shared
    # program with a RUNTIME start index was measured to lower with extra
    # collective-permutes and ~2x the temp bytes on the sharded slice,
    # defeating the bounded-memory point.
    per_shard = -(-cm // width)
    width = -(-cm // per_shard)
    stages = []
    for shard in range(nproc):
        base = shard * cm
        for q in range(per_shard):
            lo = base + q * width
            hi = min(lo + width, min(base + cm, extent))
            if hi <= lo:
                continue
            cshape = list(gshape)
            cshape[dst] = hi - lo
            cost = telemetry.collectives.relayout_chunk_cost(
                gshape, itemsize, src, dst, hi - lo, nproc
            )
            temp, _ = chunk_stage_need(
                gshape, itemsize, src, dst, hi - lo, nproc
            )
            stages.append(PlanStage(lo=lo, hi=hi, cost=cost, temp_bytes=temp))
    return RelayoutPlan(
        kind="chunked", gshape=gshape, itemsize=int(itemsize),
        src_split=src, dst_split=dst, chunk_axis=dst,
        stages=tuple(stages),
        predicted_bytes=sum(int(s.cost.bytes) for s in stages),
        temp_bytes=max((s.temp_bytes for s in stages), default=0),
        reason=reason,
    )


def _chunk_width_for(gshape, itemsize, src, dst, nproc, avail: int) -> int:
    """Largest chunk width whose stage temp model fits ``avail`` bytes,
    clamped so the plan stays within :data:`MAX_CHUNKS` stages (best
    effort beyond that — a too-narrow plan is still better than the
    guaranteed overflow it replaces)."""
    extent = int(gshape[dst])
    pad_extent = -(-extent // nproc) * nproc
    cm = max(1, pad_extent // nproc)
    other = _phys_numel(gshape, src, nproc) // max(1, extent)
    per_col = max(1, int(_CHUNK_TEMP_FACTOR * other * itemsize))
    width = max(1, min(cm, avail // per_col))
    # respect the stage-count cap: k = nproc * ceil(cm / width)
    min_width = -(-cm // max(1, MAX_CHUNKS // nproc))
    return max(width, min_width)


def plan(
    gshape: Sequence[int],
    itemsize: int,
    src_split: Optional[int],
    dst_split: Optional[int],
    comm,
    *,
    budget: Optional[int] = None,
    live: int = 0,
    measured_need: Optional[int] = None,
    plan_mode: Optional[str] = None,
) -> RelayoutPlan:
    """Select the relayout plan for one layout signature.

    Pure given its inputs (the golden tests sweep ``budget`` with
    ``live=0``): ``budget``/``live`` are bytes in `memory_guard`'s
    convention, ``measured_need`` optionally replaces the analytic
    monolithic (temp+output) estimate with the compiled program's
    ``memory_analysis()`` figure. ``plan_mode`` overrides the env knob.

    Selection in ``auto``: monolithic when it fits (``live + need <=
    budget``, or no budget at all); otherwise the chunked decomposition
    with the chunk width sized to the remaining headroom. Decompositions
    require both splits to be real axes — split→replicated keeps the
    monolithic program (its memory is dominated by the replicated
    *output*, which no decomposition shrinks) and replicated→split is a
    zero-comm local slice.
    """
    nproc = getattr(comm, "size", comm if isinstance(comm, int) else 1)
    m = plan_mode if plan_mode in _MODES else mode()
    gshape = tuple(int(s) for s in gshape)
    decomposable = (
        nproc > 1
        and src_split is not None
        and dst_split is not None
        and src_split != dst_split
        and gshape[dst_split] > 0
        and all(s > 0 for s in gshape)
    )
    if m == "monolithic" or (not decomposable and m != "auto"):
        reason = (
            "forced by HEAT_TPU_RELAYOUT_PLAN=monolithic"
            if m == "monolithic"
            else f"{m} forced but relayout is not decomposable; monolithic"
        )
        return _monolithic(gshape, itemsize, src_split, dst_split, nproc,
                           reason)
    if m == "alltoall":
        return _alltoall(gshape, itemsize, src_split, dst_split, nproc,
                         "forced by HEAT_TPU_RELAYOUT_PLAN=alltoall")
    if m == "chunked":
        from ..resilience import memory_guard

        width = _chunk_width_for(
            gshape, itemsize, src_split, dst_split, nproc,
            memory_guard.temp_budget(),
        )
        return _chunked(gshape, itemsize, src_split, dst_split, nproc, width,
                        "forced by HEAT_TPU_RELAYOUT_PLAN=chunked")
    # -- auto ---------------------------------------------------------------
    if budget is None or not decomposable:
        return _monolithic(gshape, itemsize, src_split, dst_split, nproc,
                           "auto: no budget" if budget is None
                           else "auto: not decomposable")
    need = (
        int(measured_need)
        if measured_need is not None and measured_need > 0
        else monolithic_need(gshape, itemsize, src_split, dst_split, nproc)
    )
    if live + need <= budget:
        return _monolithic(
            gshape, itemsize, src_split, dst_split, nproc,
            f"auto: monolithic fits (live {live} + need {need} <= "
            f"budget {budget})",
        )
    temp_min, out = chunk_stage_need(
        gshape, itemsize, src_split, dst_split, 1, nproc
    )
    if live + temp_min + out > budget:
        # even a single-column chunk cannot fit: decomposing would only
        # move the failure to a stage site — keep the monolithic program
        # so memory_guard's ladder raises its classic, actionable error
        return _monolithic(
            gshape, itemsize, src_split, dst_split, nproc,
            f"auto: no feasible decomposition (budget {budget} B below "
            f"even a width-1 chunk's need, live {live} B)",
        )
    avail = max(1, budget - live - out)
    width = _chunk_width_for(
        gshape, itemsize, src_split, dst_split, nproc, avail
    )
    return _chunked(
        gshape, itemsize, src_split, dst_split, nproc, width,
        f"auto: monolithic needs {need} B over budget {budget} B "
        f"(live {live} B); chunked width {width}",
    )


def active() -> bool:
    """Whether planning can change anything: a non-auto knob or an armed
    HBM budget. One env-var check each — the cost `_relayout` pays on the
    fast path."""
    if mode() != "auto":
        return True
    from ..resilience import memory_guard

    return memory_guard.budget_bytes() is not None


def maybe_plan(
    gshape,
    itemsize: int,
    src_split: Optional[int],
    dst_split: Optional[int],
    comm,
    measure: Optional[Callable[[], int]] = None,
) -> Optional[RelayoutPlan]:
    """The `_relayout` entry point: returns None on the fast path (auto
    mode, no budget — the monolithic program dispatches exactly as before
    planning existed), else the selected plan. ``measure()`` lazily
    supplies the monolithic program's measured (temp+output) bytes; it is
    only invoked when a budget decision actually needs it."""
    if not active():
        return None
    if comm.size <= 1 or src_split == dst_split:
        return None
    from ..resilience import memory_guard

    budget = memory_guard.budget_bytes()
    measured = None
    live = 0
    # split→replicated / replicated→split can never decompose — skip the
    # measure + gc + live-array walk entirely (these are the HOT small
    # relayouts: every `_replicated()` index-vector/centroid read), the
    # decision is "monolithic" regardless
    decomposable = (
        src_split is not None and dst_split is not None
        and all(int(s) > 0 for s in gshape)
    )
    if budget is not None and decomposable:
        # measure the monolithic program FIRST (the AOT compile can leave
        # collectable per-shard garbage that would inflate the live-bytes
        # reading), then gc — the same ordering memory_guard's ladder
        # uses — so the live figure the decision sees is the real working
        # set. Budgeted relayouts are rare, heavyweight events; the gc is
        # noise next to the compile.
        if measure is not None and mode() == "auto":
            try:
                measured = measure()
            except Exception:
                measured = None
        import gc

        gc.collect()
        live = memory_guard._live_total()
    p = plan(
        gshape, itemsize, src_split, dst_split, comm,
        budget=budget, live=live, measured_need=measured,
    )
    if telemetry.enabled():
        reg = telemetry.get_registry()
        reg.add(f"relayout_plan.{p.kind}", 1)
        reg.emit(
            "relayout_plan", p.kind, budget=budget, live_bytes=live,
            measured_need=measured, **p.summary(),
        )
    return p


# -- plan execution -----------------------------------------------------------


def _dst_sharding(comm, dst_split: Optional[int], ndim: int):
    if comm.size <= 1:
        return None
    if dst_split is None:
        return comm.replicated()
    return comm.sharding(dst_split, ndim)


def _init_program(plan_: RelayoutPlan, comm, dtype_str: str):
    """Zero-filled accumulator in the destination layout (donated through
    the stage chain, so only one accumulator is ever live)."""
    from . import program_cache

    pshape = comm.padded_shape(plan_.gshape, plan_.dst_split)
    tgt = _dst_sharding(comm, plan_.dst_split, len(plan_.gshape))
    # dst_split is part of the key: two destination splits can share one
    # padded shape (divisible extents), and program_key does not see
    # out_shardings — without it they would share a wrongly-sharded
    # accumulator that every stage then reshards
    return program_cache.cached_program(
        "relayout_init", (pshape, dtype_str, plan_.dst_split),
        lambda: (lambda: jnp.zeros(pshape, dtype_str)),
        comm=comm, out_shardings=tgt,
    )


def _wire_for(dtype_str: str, wire: Optional[str]) -> str:
    """Effective collective-compression mode for a stage payload
    (ISSUE 9): the caller-resolved wire mode, demoted to off for
    non-float dtypes."""
    from . import collective_prec

    if not wire or wire == "off":
        return "off"
    return collective_prec.effective(dtype_str, wire)


def _stage_key(
    plan_: RelayoutPlan, stage: PlanStage, dtype_str: str, wire: str = "off"
):
    return (
        plan_.gshape, dtype_str, plan_.src_split, plan_.dst_split,
        stage.lo, stage.hi, wire,
    )


def _stage_program(
    plan_: RelayoutPlan, stage: PlanStage, comm, dtype_str, wire: str = "off"
):
    from . import program_cache

    gshape = plan_.gshape
    nd = len(gshape)
    ax = plan_.chunk_axis
    lo, hi = stage.lo, stage.hi
    tgt = _dst_sharding(comm, plan_.dst_split, nd)
    src_split = plan_.src_split

    def build():
        sl = tuple(
            slice(lo, hi) if d == ax else slice(0, gshape[d])
            for d in range(nd)
        )
        starts = tuple(
            jnp.int32(lo if d == ax else 0) for d in range(nd)
        )

        def stage_fn(src, acc):
            # logical slice of the source (drops the src tail pad), then
            # one placed update into the destination-layout accumulator;
            # the block is destination-shard-aligned, so XLA emits exactly
            # one all-gather of the chunk (per-stage HLO audit pins this).
            # Under a compressed wire mode the chunk is quantized with ONE
            # per-chunk scale (narrow chunks make blockwise scale overhead
            # comparable to the payload) and the gather moves int8/bf16.
            chunk = src[sl]
            if wire != "off":
                from . import collective_prec

                chunk = collective_prec.gspmd_reshard(
                    chunk, comm, src_split, None,
                    "bf16" if wire == "bf16" else "int8",
                )
            return jax.lax.dynamic_update_slice(acc, chunk, starts)

        return stage_fn

    return program_cache.cached_program(
        "relayout_chunk", _stage_key(plan_, stage, dtype_str, wire), build,
        comm=comm, out_shardings=tgt, donate=(1,),
    )


def _a2a_program(plan_: RelayoutPlan, comm, dtype_str, wire: str = "off"):
    from . import program_cache

    gshape = plan_.gshape
    nd = len(gshape)
    s, t = plan_.src_split, plan_.dst_split
    pad_t = -(-gshape[t] // comm.size) * comm.size

    def build():
        def kernel(b):
            # local t-pad up to the padded extent, then one all-to-all,
            # then a local slice back to the logical s extent; the comm
            # wrapper compresses the payload under the stage's wire mode
            widths = [(0, 0)] * nd
            widths[t] = (0, pad_t - b.shape[t])
            if pad_t != b.shape[t]:
                b = jnp.pad(b, widths)
            out = comm.all_to_all(
                b, split_axis=t, concat_axis=s, precision=wire
            )
            sl = [slice(None)] * nd
            sl[s] = slice(0, gshape[s])
            return out[tuple(sl)]

        return jax.shard_map(
            kernel, mesh=comm.mesh,
            in_specs=comm.spec(s, nd), out_specs=comm.spec(t, nd),
        )

    # the tiered-lowering state (ISSUE 15) is appended by program_key
    # itself, so flipping HEAT_TPU_HIERARCHICAL keys a fresh build here
    # like at every other site
    return program_cache.cached_program(
        "relayout_a2a", (gshape, dtype_str, s, t, wire), build, comm=comm,
    )


def run(
    plan_: RelayoutPlan, buf: jax.Array, comm, *, audit: bool = False,
    wire: str = "off",
):
    """Execute a decomposed plan on a physical source buffer; returns the
    destination-layout physical buffer. Each stage is its own cached
    program (structural signature + resilience guard); ``audit=True``
    lower-compiles every distinct stage once and diffs the emitted
    collectives against the per-stage analytic cost (memoized —
    ``relayout_stage`` records in `telemetry.hlo.recent()`). ``wire`` is
    the caller-resolved collective-compression mode (ISSUE 9): stage
    payloads move compressed, stage keys and the per-stage audit
    predictions carry the mode."""
    from . import program_cache

    dtype_str = str(buf.dtype)
    wire = _wire_for(dtype_str, wire)
    if plan_.kind == "alltoall":
        fn = _a2a_program(plan_, comm, dtype_str, wire)
        if audit:
            phys = list(plan_.gshape)
            for axx in (plan_.src_split, plan_.dst_split):
                if axx is not None:
                    phys[axx] = -(-phys[axx] // comm.size) * comm.size
            from . import collective_prec, topology

            # the shard_map a2a kernel quantizes per outgoing slab —
            # scales ride their own all-to-all, the per-slab max-abs is
            # local — a2a_kernel_cost mirrors the wrapper byte-for-byte;
            # under the tiered lowering (ISSUE 15) the wrapper's cross
            # wire mode and hierarchical_a2a_cost take over, still
            # byte-for-byte
            topo = topology.active(comm.size)
            if topo is not None:
                a2a_wire = topology.cross_mode(buf.dtype, wire or None)
                phys_numel = 1
                for s_ in phys:
                    phys_numel *= int(s_)
                a2a_cost = telemetry.collectives.hierarchical_a2a_cost(
                    phys_numel, plan_.itemsize, topo.node, topo.local,
                    a2a_wire, block=collective_prec.block_size(),
                )
            else:
                a2a_cost = telemetry.collectives.a2a_kernel_cost(
                    phys, plan_.itemsize, comm.size, precision=wire,
                    block=collective_prec.block_size(),
                )
            telemetry.hlo.audit_call(
                "relayout_stage",
                lambda: (fn, (buf,)),
                predicted=a2a_cost,
                key=program_cache.program_key(
                    "relayout_a2a",
                    (plan_.gshape, dtype_str, plan_.src_split,
                     plan_.dst_split, wire),
                    comm=comm,
                ),
                fields={"plan": "alltoall", "wire": wire},
            )
        return fn(buf)
    if plan_.kind != "chunked":
        raise ValueError(
            f"run() executes decomposed plans; got {plan_.kind!r} "
            "(monolithic dispatches through DNDarray._relayout directly)"
        )
    # chunk stages always use per-chunk (per-tensor) scales, so blockwise
    # and int8 build IDENTICAL programs — demote before keying so a mode
    # sweep shares one cache entry per stage instead of recompiling
    if wire == "blockwise":
        wire = "int8"
    acc = _init_program(plan_, comm, dtype_str)()
    for stage in plan_.stages:
        fn = _stage_program(plan_, stage, comm, dtype_str, wire)
        if audit:
            predicted = stage.cost
            if wire != "off":
                predicted = telemetry.collectives.relayout_chunk_cost(
                    plan_.gshape, plan_.itemsize, plan_.src_split,
                    plan_.dst_split, stage.hi - stage.lo, comm.size,
                    precision=wire,
                )
            telemetry.hlo.audit_call(
                "relayout_stage",
                (lambda fn=fn, acc=acc: (fn, (buf, acc))),
                predicted=predicted,
                key=program_cache.program_key(
                    "relayout_chunk",
                    _stage_key(plan_, stage, dtype_str, wire),
                    comm=comm, donate=(1,),
                ),
                fields={"plan": "chunked", "lo": stage.lo, "hi": stage.hi,
                        "wire": wire},
            )
        acc = fn(buf, acc)
    return acc


def bench_field(gshape: Tuple[int, ...] = (4096, 64), itemsize: int = 4) -> dict:
    """The ``relayout_plan`` field for BENCH summaries (bench.py /
    docs/BENCHMARKS.md): what the active policy would do with the
    canonical resplit-bench shape on the live mesh — plan kind, stage
    count, predicted wire bytes — plus the HLO-**audited** wire bytes of
    the very programs that plan dispatches (AOT lower-compile only;
    nothing executes). ``audited_wire_bytes`` is None when lowering is
    unavailable."""
    from .communication import get_comm
    from ..resilience import memory_guard

    comm = get_comm()
    budget = memory_guard.budget_bytes()
    live = memory_guard._live_total() if budget is not None else 0
    pl = plan(gshape, itemsize, 0, 1, comm, budget=budget, live=live)
    field = {
        "plan": pl.kind,
        "stages": pl.chunks if pl.kind == "chunked" else 1,
        "mode": mode(),
        "budget": budget,
        "ring_overlap": ring_overlap(),
        "predicted_wire_bytes": pl.predicted_bytes,
        "audited_wire_bytes": None,
    }
    try:
        from . import factories, types

        x = factories.zeros(gshape, dtype=types.float32, split=0, comm=comm)
        buf = x.larray
        dtype_str = str(buf.dtype)
        # the probe audits the very programs the active policy would
        # dispatch — collective-compression wire mode included (ISSUE 9)
        from . import collective_prec

        wire = collective_prec.effective(dtype_str)
        field["wire"] = wire
        audited = 0
        if pl.kind == "chunked":
            # same demotion as run(): chunk stages key blockwise as int8
            stage_wire = "int8" if wire == "blockwise" else wire
            acc = _init_program(pl, comm, dtype_str)()
            for stage in pl.stages:
                fn = _stage_program(pl, stage, comm, dtype_str, stage_wire)
                audited += telemetry.hlo.audit_computation(
                    fn, buf, acc
                ).total_wire()
        elif pl.kind == "alltoall":
            fn = _a2a_program(pl, comm, dtype_str, wire)
            audited = telemetry.hlo.audit_computation(fn, buf).total_wire()
        else:
            fn = x._relayout_executable(pl.dst_split)
            audited = telemetry.hlo.audit_computation(fn, buf).total_wire()
        field["audited_wire_bytes"] = int(audited)
    except Exception:  # pragma: no cover — the probe must never kill a bench
        pass
    return field


def plan_memory(plan_: RelayoutPlan, buf: jax.Array, comm) -> dict:
    """Ground-truth per-stage memory of a decomposed plan: lower-compile
    every stage program (AOT — compiles, never executes) and read
    ``memory_analysis()``. Returns ``{"stage_temp_bytes": [...],
    "peak_temp_bytes": int, "model_temp_bytes": int}`` — the CI planner
    gate asserts ``peak_temp_bytes <= HEAT_TPU_HBM_BUDGET``."""
    dtype_str = str(buf.dtype)
    temps = []
    if plan_.kind == "chunked":
        acc = _init_program(plan_, comm, dtype_str)()
        for stage in plan_.stages:
            fn = _stage_program(plan_, stage, comm, dtype_str)
            try:
                ma = fn.lower(buf, acc).compile().memory_analysis()
                temps.append(int(getattr(ma, "temp_size_in_bytes", 0)))
            except Exception:
                temps.append(-1)
    elif plan_.kind in ("monolithic", "alltoall"):
        temps.append(-1)
    measured = [t for t in temps if t >= 0]
    return {
        "stage_temp_bytes": temps,
        "peak_temp_bytes": max(measured) if measured else -1,
        "model_temp_bytes": plan_.temp_bytes,
    }
