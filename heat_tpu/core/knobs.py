"""Public face of the central ``HEAT_TPU_*`` knob registry (ISSUE 10).

The implementation lives in :mod:`heat_tpu._knobs`, a stdlib-only leaf
module, because ``heat_tpu.telemetry`` and ``heat_tpu.resilience`` must
read knobs while ``heat_tpu.core`` is still unimported (package init
order). Import THIS module from user code and from core modules::

    from heat_tpu.core import knobs
    knobs.get("HEAT_TPU_FUSION")      # typed read
    knobs.raw("HEAT_TPU_FAULTS", "")  # raw string, registered-name-checked
    knobs.REGISTRY                    # name -> Knob(type, default, doc)

Early-loading package internals use ``from heat_tpu import _knobs as
knobs`` instead — same object, no ``heat_tpu.core`` import.
"""

from heat_tpu._knobs import (  # noqa: F401
    FALSY,
    TRUTHY,
    Knob,
    REGISTRY,
    Tunable,
    clear_overrides,
    default_raw,
    get,
    markdown_table,
    names,
    overlay,
    overrides,
    raw,
    set_override,
    tunables,
)

__all__ = [
    "FALSY",
    "TRUTHY",
    "Knob",
    "REGISTRY",
    "Tunable",
    "clear_overrides",
    "default_raw",
    "get",
    "markdown_table",
    "names",
    "overlay",
    "overrides",
    "raw",
    "set_override",
    "tunables",
]
