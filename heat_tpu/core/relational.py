"""Elementwise comparisons (reference: heat/core/relational.py, 12 exports)."""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import binary_op
from .dndarray import DNDarray

__all__ = [
    "eq",
    "equal",
    "ge",
    "greater",
    "greater_equal",
    "gt",
    "le",
    "less",
    "less_equal",
    "lt",
    "ne",
    "not_equal",
]


def eq(t1, t2) -> DNDarray:
    """Elementwise == (reference relational.py `eq`)."""
    return binary_op(jnp.equal, t1, t2)


def equal(t1, t2) -> bool:
    """True if both arrays have the same global shape and all elements equal
    (reference relational.py `equal`: resplits + local compare + Allreduce)."""
    from . import factories, logical

    if not isinstance(t1, DNDarray):
        t1 = factories.array(t1)
    if not isinstance(t2, DNDarray):
        t2 = factories.array(t2)
    if t1.shape != t2.shape:
        return False
    if t1.split != t2.split:
        t2 = t2.resplit(t1.split)
    return bool(logical.all(eq(t1, t2)).item())


def ge(t1, t2) -> DNDarray:
    return binary_op(jnp.greater_equal, t1, t2)


greater_equal = ge


def gt(t1, t2) -> DNDarray:
    return binary_op(jnp.greater, t1, t2)


greater = gt


def le(t1, t2) -> DNDarray:
    return binary_op(jnp.less_equal, t1, t2)


less_equal = le


def lt(t1, t2) -> DNDarray:
    return binary_op(jnp.less, t1, t2)


less = lt


def ne(t1, t2) -> DNDarray:
    return binary_op(jnp.not_equal, t1, t2)


not_equal = ne


DNDarray.__eq__ = lambda self, other: eq(self, other)
DNDarray.__ne__ = lambda self, other: ne(self, other)
DNDarray.__lt__ = lambda self, other: lt(self, other)
DNDarray.__le__ = lambda self, other: le(self, other)
DNDarray.__gt__ = lambda self, other: gt(self, other)
DNDarray.__ge__ = lambda self, other: ge(self, other)
DNDarray.__hash__ = None
