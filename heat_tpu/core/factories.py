"""Array construction routines.

Re-design of reference heat/core/factories.py:40-1323. The reference builds
the full array on every rank and slices out the local chunk
(factories.py:381-384), or stitches pre-distributed local shards together via
a neighbor handshake (``is_split``, factories.py:386-429). Here construction
is one `device_put` with a `NamedSharding` (single-controller), and the
``is_split`` path maps onto assembling a global array from per-position
blocks.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence, Tuple, Type, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import program_cache, types
from .communication import MeshCommunication, sanitize_comm
from .devices import Device, sanitize_device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "full",
    "full_like",
    "linspace",
    "logspace",
    "meshgrid",
    "ones",
    "ones_like",
    "zeros",
    "zeros_like",
]


def _wrap(
    data: jax.Array,
    split: Optional[int],
    device: Device,
    comm: MeshCommunication,
    dtype: Optional[Type[types.datatype]] = None,
) -> DNDarray:
    return DNDarray.from_logical(data, split, device, comm, dtype)


def arange(*args, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """Evenly spaced values in [start, stop) with step (reference
    factories.py:40)."""
    num_of_param = len(args)
    if num_of_param == 1:
        start, stop, step = 0, args[0], 1
    elif num_of_param == 2:
        start, stop, step = args[0], args[1], 1
    elif num_of_param == 3:
        start, stop, step = args
    else:
        raise TypeError(f"function takes minimum one and at most 3 positional arguments ({num_of_param} given)")

    if dtype is None:
        # numpy semantics: all-int args give the platform int, else float32
        if all(isinstance(a, int) for a in (start, stop, step)):
            dtype = types.int64
        else:
            dtype = types.float32
    dtype = types.canonical_heat_type(dtype)
    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    data = jnp.arange(start, stop, step, dtype=dtype.jnp_type())
    return _wrap(data, sanitize_axis(data.shape, split), device, comm, dtype)


def array(
    obj: Any,
    dtype: Optional[Type[types.datatype]] = None,
    copy: Optional[bool] = True,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[MeshCommunication] = None,
) -> DNDarray:
    """The main constructor (reference factories.py:150).

    ``split`` distributes the given *global* data along an axis; ``is_split``
    declares ``obj`` to be this process's *local* shard of a distributed
    array (the reference infers the global shape via a neighbor handshake,
    factories.py:386-429; under a single controller every position holds the
    same block list, so the global shape is locally computable).
    """
    if split is not None and is_split is not None:
        raise ValueError(f"split and is_split are mutually exclusive parameters")
    device = sanitize_device(device)
    comm = sanitize_comm(comm)

    if isinstance(obj, DNDarray):
        if dtype is None and split is None and is_split is None:
            if copy:
                # a real buffer copy, not an aliasing wrapper: the source
                # may later be resplit_ in place, which DONATES its buffer
                # (core/program_cache.py) — an aliased "copy" would die
                # with it on backends that honor the donation
                return DNDarray(
                    jnp.copy(obj.larray), obj.shape, obj.dtype, obj.split,
                    device, comm, True,
                )
            return obj
        import jax as _jax

        if obj.split is not None and _jax.process_count() > 1:
            data = obj._replicated()  # compiled relayout; _wrap re-shards
        else:
            data = obj._logical()
        if dtype is not None:
            data = data.astype(types.canonical_heat_type(dtype).jnp_type())
        tgt_split = split if split is not None else (obj.split if is_split is None else is_split)
        return _wrap(data, tgt_split, device, comm)

    if isinstance(obj, (jnp.ndarray,)):
        data = obj
    else:
        data = np.asarray(obj, order=order)

    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        data = jnp.asarray(data, dtype=dtype.jnp_type())
    else:
        if isinstance(data, np.ndarray) and data.dtype == np.float64 and not isinstance(obj, np.ndarray):
            # python floats default to float32 (reference types promotion)
            data = jnp.asarray(data, dtype=jnp.float32)
        else:
            data = jnp.asarray(data)
        dtype = types.canonical_heat_type(data.dtype)

    while data.ndim < ndmin:
        data = data[None]

    if is_split is not None:
        # reference semantics: the given array is this *process's* local
        # shard and the global shape is inferred from all processes
        # (factories.py:386-429, neighbor handshake). Single-controller JAX
        # has one process, so the local portion IS the global array;
        # multi-host assembles the shards via
        # jax.make_array_from_process_local_data (SURVEY §7 stage 1).
        is_split = sanitize_axis(data.shape, is_split)
        if jax.process_count() > 1:
            return _assemble_is_split(data, is_split, device, comm, dtype)
        return _wrap(data, is_split, device, comm, dtype)

    split = sanitize_axis(data.shape, split)
    return _wrap(data, split, device, comm, dtype)


def _assemble_ragged(
    local,
    split: int,
    gshape,
    all_shapes,
    device,
    comm,
    dtype,
) -> "DNDarray":
    """Assemble arbitrary ragged per-process blocks into the canonical
    layout. Stage 1: every process pads its block into a uniform slot of
    ``c_stage = max_p ceil(len_p / ldc_p)`` rows per device, so the staged
    array is canonically sharded by construction. Stage 2: one compiled
    gather maps canonical positions to staged positions (the index map is
    host-computable from the allgathered lengths) and lands with the
    result's sharding."""
    import jax

    lens = all_shapes[:, split].astype(np.int64)
    n = int(lens.sum())
    nprocs = jax.process_count()
    # per-process device counts, in process order
    ldc = np.zeros((nprocs,), dtype=np.int64)
    for dev in comm.devices:
        ldc[dev.process_index] += 1
    if (ldc == 0).any():
        raise NotImplementedError(
            "ragged is_split needs every process to own mesh devices"
        )
    c_stage = int(max(-(-int(l) // int(d)) for l, d in zip(lens, ldc)))
    c_stage = max(c_stage, 1)
    slot = ldc * c_stage  # rows per process in the staging layout
    n_stage = int(slot.sum())  # == c_stage * comm.size

    ht_dtype = (
        types.canonical_heat_type(dtype)
        if dtype is not None
        else types.canonical_heat_type(local.dtype)
    )
    block = np.asarray(local).astype(ht_dtype.jnp_type())
    pidx = jax.process_index()
    padw = [(0, 0)] * block.ndim
    padw[split] = (0, int(slot[pidx]) - block.shape[split])
    block = np.pad(block, padw)
    stage_shape = gshape[:split] + (n_stage,) + gshape[split + 1 :]
    staged = jax.make_array_from_process_local_data(
        comm.sharding(split, len(gshape)), block, stage_shape
    )

    # canonical position j < n reads staged position slot_start[q] + (j -
    # prefix[q]) where q owns global row j; pads read row 0
    prefix = np.concatenate([[0], np.cumsum(lens)])
    slot_start = np.concatenate([[0], np.cumsum(slot)])
    n_pad = comm.padded_size(n)
    j = np.arange(n_pad, dtype=np.int64)
    q = np.searchsorted(prefix, np.minimum(j, n - 1), side="right") - 1
    src = np.where(j < n, slot_start[q] + (j - prefix[q]), 0)
    idx = jnp.asarray(src)

    # one cached compiled re-chunk gather: the index map is data (an
    # argument), so repeated is_split assemblies over the same (split,
    # rank) layout reuse one program even when the per-process lengths —
    # and hence the map's values — differ
    gather = program_cache.cached_program(
        "is_split_gather", (split, len(gshape)),
        lambda: (lambda b, ix: jnp.take(b, ix, axis=split)),
        comm=comm, out_shardings=comm.sharding(split, len(gshape)),
    )
    buf = gather(staged, idx)
    return DNDarray(buf, gshape, ht_dtype, split, device, comm, True)


def _assemble_is_split(
    data,
    split: int,
    device: Device,
    comm: MeshCommunication,
    dtype: Optional[Type[types.datatype]],
) -> DNDarray:
    """Assemble a global DNDarray from per-controller-process local shards
    (the reference's ``is_split`` neighbor handshake, factories.py:386-429).

    Every process calls this with *its* block along ``split``; blocks are
    ordered by process index. The global extent is inferred by all-gathering
    the local shapes (the handshake analog); non-split dims must agree.

    Blocks matching the canonical ceil-rule chunks (the layout produced by
    per-host sharded data loading) assemble directly; arbitrary RAGGED
    extents go through :func:`_assemble_ragged` — a staging layout plus one
    compiled re-chunk gather (the branch is decided collectively from the
    allgathered shapes).
    """
    from jax.experimental import multihost_utils

    local = np.asarray(data)
    pidx = jax.process_index()
    # handshake: gather (shape..., dtype code) from every process in one go
    meta = np.asarray(list(local.shape) + [np.dtype(local.dtype).num], dtype=np.int64)
    all_meta = np.asarray(multihost_utils.process_allgather(meta)).reshape(
        jax.process_count(), local.ndim + 1
    )
    all_shapes = all_meta[:, :-1]
    for d in range(local.ndim):
        if d != split and len(set(all_shapes[:, d].tolist())) != 1:
            raise ValueError(
                f"is_split: non-split dimension {d} differs across processes: "
                f"{sorted(set(all_shapes[:, d].tolist()))}"
            )
    if dtype is None and len(set(all_meta[:, -1].tolist())) != 1:
        raise ValueError(
            "is_split: local shard dtypes differ across processes "
            f"(numpy dtype codes {sorted(set(all_meta[:, -1].tolist()))}); "
            "pass dtype= explicitly"
        )
    n = int(all_shapes[:, split].sum())
    gshape = tuple(local.shape[:split]) + (n,) + tuple(local.shape[split + 1 :])

    c = comm.chunk_size(n)
    mesh_positions = [
        i for i, dev in enumerate(comm.devices) if dev.process_index == pidx
    ]
    if not mesh_positions or mesh_positions != list(
        range(mesh_positions[0], mesh_positions[0] + len(mesh_positions))
    ):
        raise NotImplementedError(
            "is_split requires this process's devices to be contiguous in the "
            "communicator mesh"
        )
    first, count = mesh_positions[0], len(mesh_positions)
    # canonical-vs-ragged is decided COLLECTIVELY from the allgathered
    # shapes — every process computes every process's (have, want) spans and
    # agrees on the branch, because the two branches issue different
    # collective programs (a per-process decision could deadlock the job)
    lens_all = all_shapes[:, split].astype(np.int64)
    prefixes = np.concatenate([[0], np.cumsum(lens_all)])
    nprocs = jax.process_count()
    first_all = np.full((nprocs,), -1, dtype=np.int64)
    ldc_all = np.zeros((nprocs,), dtype=np.int64)
    for i, dev in enumerate(comm.devices):
        if first_all[dev.process_index] < 0:
            first_all[dev.process_index] = i
        ldc_all[dev.process_index] += 1
    canonical = True
    for p_i in range(nprocs):
        w_lo = min(int(first_all[p_i]) * c, n)
        w_hi = min((int(first_all[p_i]) + int(ldc_all[p_i])) * c, n)
        if (int(prefixes[p_i]), int(prefixes[p_i + 1])) != (w_lo, w_hi):
            canonical = False
            break
    if not canonical:
        # RAGGED blocks (the reference accepts any per-rank extents,
        # factories.py:386-429): stage the blocks in a uniform-slot layout,
        # then one compiled index-map gather re-chunks to canonical — the
        # DCN all-to-all the relayout requires, emitted by XLA
        return _assemble_ragged(
            local, split, gshape, all_shapes, device, comm, dtype
        )
    phys_rows = count * c
    if local.shape[split] < phys_rows:
        padw = [(0, 0)] * local.ndim
        padw[split] = (0, phys_rows - local.shape[split])
        local = np.pad(local, padw)

    ht_dtype = (
        types.canonical_heat_type(dtype)
        if dtype is not None
        else types.canonical_heat_type(local.dtype)
    )
    local = local.astype(ht_dtype.jnp_type())
    pshape = comm.padded_shape(gshape, split)
    arr = jax.make_array_from_process_local_data(
        comm.sharding(split, len(gshape)), local, pshape
    )
    return DNDarray(arr, gshape, ht_dtype, split, device, comm, True)


def asarray(obj, dtype=None, copy=None, order="C", is_split=None, device=None, comm=None) -> DNDarray:
    """Convert to DNDarray without copying when possible (reference
    factories.py: `asarray`)."""
    if isinstance(obj, DNDarray) and dtype is None and is_split is None and device is None:
        return obj
    return array(obj, dtype=dtype, copy=copy, is_split=is_split, device=device, comm=comm)


def __factory(shape, dtype, split, fill, device, comm, order="C") -> DNDarray:
    shape = sanitize_shape(shape)
    dtype = types.canonical_heat_type(dtype)
    split = sanitize_axis(shape, split)
    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    data = fill(shape, dtype=dtype.jnp_type())
    return _wrap(data, split, device, comm, dtype)


def empty(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Uninitialized (zero-filled on XLA) array (reference factories.py:513)."""
    return __factory(shape, dtype, split, jnp.zeros, device, comm, order)


def full(shape, fill_value, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """Constant-filled array (reference factories.py:722)."""

    def filler(s, dtype):
        return jnp.full(s, fill_value, dtype=dtype)

    return __factory(shape, dtype, split, filler, device, comm, order)


def ones(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory(shape, dtype, split, jnp.ones, device, comm, order)


def zeros(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory(shape, dtype, split, jnp.zeros, device, comm, order)


def __factory_like(a, dtype, split, factory, device, comm, order="C", **kwargs) -> DNDarray:
    shape = a.shape if isinstance(a, DNDarray) else np.asarray(a).shape
    if dtype is None:
        dtype = a.dtype if isinstance(a, DNDarray) else types.canonical_heat_type(np.asarray(a).dtype)
    if split is None:
        split = a.split if isinstance(a, DNDarray) else None
    if device is None and isinstance(a, DNDarray):
        device = a.device
    if comm is None and isinstance(a, DNDarray):
        comm = a.comm
    return factory(shape, dtype=dtype, split=split, device=device, comm=comm, order=order, **kwargs)


def empty_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, empty, device, comm, order)


def full_like(a, fill_value, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, full, device, comm, order, fill_value=fill_value)


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, ones, device, comm, order)


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return __factory_like(a, dtype, split, zeros, device, comm, order)


def eye(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """2-D identity-like array (reference factories.py:589)."""
    if isinstance(shape, (int, np.integer)):
        gshape = (int(shape), int(shape))
    else:
        shape = tuple(shape)
        gshape = (int(shape[0]), int(shape[1] if len(shape) > 1 else shape[0]))
    dtype = types.canonical_heat_type(dtype)
    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    data = jnp.eye(gshape[0], gshape[1], dtype=dtype.jnp_type())
    return _wrap(data, sanitize_axis(gshape, split), device, comm, dtype)


def linspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    retstep: bool = False,
    dtype=None,
    split=None,
    device=None,
    comm=None,
):
    """num evenly spaced samples over [start, stop] (reference
    factories.py:899)."""
    num = int(num)
    if num <= 0:
        raise ValueError(f"number of samples 'num' must be non-negative integer, but was {num}")
    start = float(start)
    stop = float(stop)
    step = (stop - start) / max(1, (num - 1 if endpoint else num))
    device = sanitize_device(device)
    comm = sanitize_comm(comm)
    data = jnp.linspace(start, stop, num, endpoint=endpoint, dtype=None)
    if dtype is not None:
        data = data.astype(types.canonical_heat_type(dtype).jnp_type())
    elif data.dtype == jnp.float64:
        data = data.astype(jnp.float32)
    ht = _wrap(data, sanitize_axis(data.shape, split), device, comm)
    if retstep:
        return ht, step
    return ht


def logspace(
    start, stop, num=50, endpoint=True, base=10.0, dtype=None, split=None, device=None, comm=None
) -> DNDarray:
    """num samples on a log scale (reference factories.py:985)."""
    y = linspace(start, stop, num=num, endpoint=endpoint, split=split, device=device, comm=comm)
    from . import arithmetics

    result = arithmetics.pow(float(base), y)
    if dtype is None:
        return result
    return result.astype(types.canonical_heat_type(dtype))


def meshgrid(*arrays, indexing: str = "xy") -> List[DNDarray]:
    """Coordinate matrices from 1-D coordinate vectors (reference
    factories.py:1048). Distributed: if any input is split, the first two
    output grids are split consistently along their major dims."""
    if indexing not in ("xy", "ij"):
        raise ValueError(f"indexing must be 'xy' or 'ij', got {indexing}")
    if len(arrays) == 0:
        return []
    hts = [a if isinstance(a, DNDarray) else array(a) for a in arrays]
    split_in = [a.split for a in hts]
    if sum(s is not None for s in split_in) > 1:
        raise ValueError("split axis can be defined for at most one input")
    comm = hts[0].comm
    device = hts[0].device
    logs = [a._logical() for a in hts]
    outs = jnp.meshgrid(*logs, indexing=indexing)
    # output split: if input i was split, every output is split along the dim
    # that carries input i's coordinate
    out_split = None
    which = next((i for i, s in enumerate(split_in) if s is not None), None)
    if which is not None:
        if indexing == "xy" and which in (0, 1) and len(hts) > 1:
            out_split = 1 - which if which < 2 else which
        else:
            out_split = which
    return [DNDarray.from_logical(o, out_split, device, comm) for o in outs]
