"""The DNDarray — a distributed n-dimensional array over a TPU device mesh.

Re-design of the reference's core data structure (reference:
heat/core/dndarray.py:38-1663). The reference `DNDarray` is a *per-process*
object: global metadata replicated on every MPI rank plus one process-local
``torch.Tensor`` shard; every op hand-writes the collectives for the split
axis. Here a DNDarray is a *single-controller* object wrapping one sharded
:class:`jax.Array` laid out over the communicator's device mesh; XLA
materializes the collectives from the sharding.

Storage invariant (the tail-pad rule)
-------------------------------------
XLA requires a sharded dimension to divide evenly across the mesh. A DNDarray
therefore stores, for ``split=s``:

``self.larray.shape == comm.padded_shape(gshape, s)``   (split dim rounded up
to ``ceil(n/p)*p``), sharded with ``NamedSharding(mesh, P(..., 'proc', ...))``.

Elements at global index ``>= gshape[s]`` along the split dim are **pad**:
their values are unspecified and must never influence a result. Consumers
that combine values *across* the split axis (reductions, scans, sort, matmul
contractions, …) first overwrite the pad region with a neutral element via
:meth:`_masked` — everything elementwise simply carries the pad along. All
host-side exports (`numpy()`, `tolist()`, `item()`) slice to the logical
shape. Because the pad sits at the global tail, the logical data of position
``r`` is exactly ``[r*c, min((r+1)*c, n))`` — the ceil-rule chunk, which is
what `lshape_map` reports. Arrays are hence always "balanced" in the
reference's sense (reference `balance_` dndarray.py:474 becomes a no-op).
"""

from __future__ import annotations

import builtins
from typing import Iterable, List, Optional, Sequence, Tuple, Type, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .communication import MeshCommunication, sanitize_comm
from .devices import Device, get_device
from .stride_tricks import sanitize_axis
from .. import telemetry

__all__ = ["DNDarray", "perf_stats", "reset_perf_stats"]

Scalar = Union[int, float, bool, complex]

# Relayout bookkeeping (diagnostic): `logical_slices` counts physical→logical
# tail-pad slices, `repads` counts logical→physical re-pads, `device_puts`
# counts explicit resharding device_puts. Op chains that stay on the physical
# buffer (the fast paths in manipulations/_operations) leave all three at 0.
_PERF_STATS = {"logical_slices": 0, "repads": 0, "device_puts": 0}


def perf_stats() -> dict:
    """Snapshot of the relayout counters (see module comment)."""
    return dict(_PERF_STATS)


def reset_perf_stats() -> None:
    for k in _PERF_STATS:
        _PERF_STATS[k] = 0


class LocalIndex:
    """Proxy for indexing the process-local data directly, mirroring the
    reference's ``lloc`` accessor (reference dndarray.py:300-339). On the
    single-controller runtime "local" means the full (padded) buffer."""

    def __init__(self, obj: "DNDarray"):
        self.obj = obj

    def __getitem__(self, key):
        return self.obj.larray[key]

    def __setitem__(self, key, value):
        self.obj.larray = self.obj.larray.at[key].set(value)


class DNDarray:
    """Distributed N-Dimensional array (reference dndarray.py:38).

    Parameters
    ----------
    array : jax.Array
        The physical (possibly tail-padded) global buffer.
    gshape : tuple of int
        Logical global shape.
    dtype : heat type
    split : int or None
        Sharded dimension; None = replicated.
    device : Device
    comm : MeshCommunication
    balanced : bool
        Kept for API parity; always True under the tail-pad layout.
    """

    def __init__(
        self,
        array: jax.Array,
        gshape: Tuple[int, ...],
        dtype: Type[types.datatype],
        split: Optional[int],
        device: Device,
        comm: MeshCommunication,
        balanced: Optional[bool] = True,
    ):
        self.__array = array
        self.__pshape = tuple(array.shape) if array is not None else None
        self.__fused = None
        self.__leaf_captured = False
        self.__gshape = tuple(int(s) for s in gshape)
        self.__dtype = dtype
        self.__split = split
        self.__device = device
        self.__comm = comm
        self.__balanced = True if balanced is None else balanced
        self.__lshape_map = None

    # --------------------------------------------------------- fusion state

    @classmethod
    def _from_fused(
        cls, node, gshape, dtype, split, device, comm, pshape
    ) -> "DNDarray":
        """Wrap a pending :class:`heat_tpu.core.fusion.FusedNode` — the
        physical buffer does not exist yet; any ``larray`` read
        materializes the whole chain as ONE cached program."""
        obj = cls(None, gshape, dtype, split, device, comm, True)
        obj.__fused = node
        obj.__pshape = tuple(int(s) for s in pshape)
        return obj

    def _fused_node(self):
        """The pending fusion DAG node, or None when materialized."""
        return self.__fused

    def _fusion_flush(self) -> None:
        """Materialize a pending fused chain into the physical buffer
        (no-op when already materialized)."""
        node = self.__fused
        if node is None:
            return
        self.__array = node.materialize(self.__comm)
        self.__fused = None
        # a chain another DAG consumed leaves its flushed buffer reachable
        # (node.buffer re-enters those DAGs as a leaf) — donating it would
        # hand their later flush a deleted array
        self.__leaf_captured = bool(node.shared)

    def _mark_leaf_captured(self) -> None:
        """Called by the fusion engine when the CURRENT buffer is captured
        by value into a deferred DAG: it must not be donated to XLA while
        that chain may still flush (see :meth:`resplit_`)."""
        self.__leaf_captured = True

    def _buffer_donatable(self) -> bool:
        """Whether the current physical buffer is provably unreferenced by
        any pending fused chain (safe to ``donate_argnums``)."""
        return not self.__leaf_captured

    # ------------------------------------------------------------------ meta

    @property
    def larray(self) -> jax.Array:
        """The underlying physical jax.Array (the reference's process-local
        torch tensor, dndarray.py:106; here the padded sharded global
        buffer). Reading it is THE fusion flush boundary: a pending
        elementwise chain materializes here as one cached program."""
        if self.__array is None:
            self._fusion_flush()
        return self.__array

    @larray.setter
    def larray(self, array: jax.Array):
        if self.__fused is not None:
            # out=-style overwrite of a deferred destination: if another
            # DAG consumed the pending node, flush first so it can reuse
            # the computed buffer; otherwise the pending value is dead —
            # discard it without compiling a program whose result the
            # overwrite would immediately throw away. Either way the
            # destination never serves a stale deferred value
            # (tests/test_fusion.py).
            if self.__fused.shared:
                self._fusion_flush()
            else:
                self.__fused = None
        if tuple(array.shape) != tuple(self.__pshape):
            raise ValueError(
                f"larray setter: shape {tuple(array.shape)} does not match physical shape "
                f"{tuple(self.__pshape)}"
            )
        self.__array = array
        self.__leaf_captured = False
        self._invalidate_halo()

    @property
    def lloc(self) -> LocalIndex:
        return LocalIndex(self)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def gshape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def dtype(self) -> Type[types.datatype]:
        return self.__dtype

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def device(self) -> Device:
        return self.__device

    @property
    def comm(self) -> MeshCommunication:
        return self.__comm

    @property
    def balanced(self) -> bool:
        return self.__balanced

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def size(self) -> int:
        return int(np.prod(self.__gshape, dtype=np.int64)) if self.__gshape else 1

    gnumel = size

    @property
    def lnumel(self) -> int:
        return int(np.prod(self.lshape, dtype=np.int64))

    @property
    def nbytes(self) -> int:
        return self.size * self.__dtype.byte_size()

    gnbytes = nbytes

    @property
    def lnbytes(self) -> int:
        return self.lnumel * self.__dtype.byte_size()

    @property
    def lshape(self) -> Tuple[int, ...]:
        """Logical chunk shape of this process's first mesh position
        (reference dndarray.py:170; see module docstring for the layout).
        Under multi-host the position is this process's first device in the
        mesh, not the process index — a process owning devices [2,3] of an
        8-position mesh reports position 2's chunk."""
        _, lshape, _ = self.__comm.chunk(
            self.__gshape, self.__split, self.__comm.first_local_position()
        )
        return lshape

    @property
    def lshape_map(self) -> np.ndarray:
        """(mesh size, ndim) map of every position's logical chunk shape
        (reference dndarray.py:222)."""
        if self.__lshape_map is None:
            self.__lshape_map = self.__comm.lshape_map(self.__gshape, self.__split)
        return self.__lshape_map.copy()

    @property
    def padded_shape(self) -> Tuple[int, ...]:
        """Physical (tail-padded) shape — metadata, so reading it never
        flushes a pending fused chain."""
        return tuple(self.__pshape)

    @property
    def pad_count(self) -> int:
        """Number of pad positions along the split dim (0 when divisible or
        replicated)."""
        if self.__split is None:
            return 0
        return self.__pshape[self.__split] - self.__gshape[self.__split]

    @property
    def imag(self) -> "DNDarray":
        from . import complex_math

        return complex_math.imag(self)

    @property
    def real(self) -> "DNDarray":
        from . import complex_math

        return complex_math.real(self)

    @property
    def T(self) -> "DNDarray":
        from .linalg import transpose

        return transpose(self)

    # ------------------------------------------------------ pad bookkeeping

    def _masked(self, fill_value) -> jax.Array:
        """The physical buffer with pad positions replaced by ``fill_value``
        — call before any computation that crosses the split axis."""
        buf = self.larray
        if self.pad_count == 0:
            return buf
        s = self.__split
        idx = jax.lax.broadcasted_iota(jnp.int32, buf.shape, s)
        fill = jnp.asarray(fill_value, dtype=buf.dtype)
        return jnp.where(idx < self.__gshape[s], buf, fill)

    def _logical(self) -> jax.Array:
        """The buffer sliced to the logical global shape (drops tail pad).
        The result is generally not evenly shardable; use only at host/compute
        boundaries."""
        if self.pad_count == 0:
            return self.larray
        if jax.process_count() > 1:
            # slicing off the tail pad yields a non-canonically-shardable
            # array; on multi-host XLA would relayout it over DCN invisibly
            # per op — refuse rather than mis-compute (SURVEY §7 stage 1)
            raise NotImplementedError(
                "the host-logical view of a padded array is single-controller "
                "only; multi-host code must stay on pad-aware physical paths"
            )
        _PERF_STATS["logical_slices"] += 1
        sl = tuple(slice(0, n) for n in self.__gshape)
        return self.larray[sl]

    def _relayout(
        self, new_split: Optional[int], *, audit: bool = False,
        donate: bool = False, audit_site: str = "relayout",
        precision: Optional[str] = None,
    ) -> jax.Array:
        """Physical buffer re-laid-out to the canonical layout of
        ``new_split``: ONE cached compiled program (logical slice, tail
        re-pad, target sharding as ``out_shardings`` — XLA emits the
        all-to-all/all-gather), so — unlike :meth:`_logical`, which hands
        the host a non-canonically-shardable view — this is the ONE
        sanctioned relayout primitive and is multi-host safe. The program
        is memoized in :mod:`heat_tpu.core.program_cache` keyed on
        (gshape, dtype, old split, new split, comm): the second identical
        relayout compiles nothing and dispatches through a dict lookup.

        ``donate=True`` (the in-place ``resplit_`` path, where the source
        buffer is dead after the call) donates the input buffer to XLA so
        its memory can be reused instead of holding source + destination
        live; donating and non-donating callers never share a program.

        The ONE primitive is also the one instrumentation point: with
        telemetry enabled, every relayout is a ``relayout`` span carrying
        the analytic collective kind and wire bytes
        (telemetry/collectives.py) and blocking on the result before the
        clock stops. ``audit=True`` additionally lower-compiles the same
        cached program and diffs the collectives XLA actually emitted
        against that prediction (telemetry/hlo.py). Op-level callers
        (`resplit`) audit at their own site, so the global
        ``HEAT_TPU_HLO_AUDIT`` flag is deliberately NOT consulted here —
        one relayout must never produce two audit records.

        With the relayout planner armed (``HEAT_TPU_RELAYOUT_PLAN`` set
        non-auto, or an ``HEAT_TPU_HBM_BUDGET``), the layout change may
        instead execute as a decomposed plan — an explicit all-to-all
        kernel or a bounded-memory chain of chunk programs
        (core/relayout_planner.py). ``auto`` with no budget never plans:
        this method stays the single-dict-lookup monolithic dispatch.

        ``precision`` (ISSUE 9, ``HEAT_TPU_COLLECTIVE_PREC``): the wire
        payload of the relayout collective is compressed under the
        resolved mode — quantize, reshard the compressed tensor, dequant
        in the destination layout, all in the same cached program. The
        effective wire mode is part of the program signature (and of the
        HLO-audit prediction), so modes key separate cache entries and
        ``off`` dispatches the exact pre-knob program."""
        wire = self._wire_mode(new_split, precision)
        plan = self._relayout_plan(new_split)
        _cost, fields, do_audit = telemetry.op_cost(
            self.__comm.relayout_cost, self.__gshape,
            self.__dtype.byte_size(), self.__split, new_split, wire,
            audit=audit, use_global=False,
        )
        decomposed = plan is not None and plan.kind != "monolithic"
        if do_audit and not decomposed:
            self._audit_relayout(new_split, site=audit_site, wire=wire)
        if telemetry.enabled():
            if decomposed:
                fields = dict(fields, plan=plan.kind, stages=plan.chunks)
            with telemetry.span(
                "relayout", old_split=self.__split, new_split=new_split,
                gshape=list(self.__gshape), **fields,
            ) as sp:
                return sp.output(
                    self.__relayout_impl(
                        new_split, donate, plan, do_audit, wire
                    )
                )
        return self.__relayout_impl(new_split, donate, plan, do_audit, wire)

    def _wire_mode(
        self, new_split: Optional[int], precision: Optional[str] = None
    ) -> str:
        """The effective collective-compression mode for this relayout:
        the resolved knob/override, demoted to ``off`` for non-float
        dtypes and for layouts that move no payload over the wire
        (1-position meshes, same-split, replicated sources — a local
        slice)."""
        from . import collective_prec

        if (
            self.__comm.size <= 1
            or new_split == self.__split
            or self.__split is None
        ):
            # still VALIDATE an explicit override (typos must not pass
            # silently just because this layout happens to be local)
            collective_prec.resolve(precision)
            return "off"
        return collective_prec.effective(
            self.__dtype.jnp_type(), precision
        )

    def _relayout_plan(self, new_split: Optional[int]):
        """Consult the relayout planner (None on the unplanned fast
        path). The measured monolithic (temp+output) bytes are supplied
        lazily — only a budgeted `auto` decision compiles the monolithic
        program ahead of time (AOT, memoized in memory_guard)."""
        from . import relayout_planner

        if not relayout_planner.active():
            return None

        def measure() -> int:
            from ..resilience import memory_guard

            return memory_guard.program_bytes(
                self.__relayout_program(new_split), (self.larray,)
            )

        return relayout_planner.maybe_plan(
            self.__gshape, self.__dtype.byte_size(), self.__split,
            new_split, self.__comm, measure=measure,
        )

    def _audit_relayout(
        self, new_split: Optional[int], site: str, wire: str = "off"
    ):
        """Ground-truth the relayout: lower-and-compile the equivalent
        single XLA program (slice → re-pad → re-shard, the same steps as
        :meth:`__relayout_impl`) and record the emitted collectives diffed
        against the analytic prediction for that program's (padded,
        physical) shapes (telemetry/hlo.py). Memoized on the layout
        signature; never raises. No-op on 1-position meshes and
        split→same-split (no communication to audit)."""
        from ..telemetry import hlo

        comm = self.__comm
        if comm.size <= 1 or new_split == self.__split:
            return None
        gshape = self.__gshape
        buf = self.larray

        # the compare target is the cost of the PROGRAM BEING AUDITED: XLA
        # moves the tail-padded physical buffer (padded along both the old
        # and the new split), not the logical array, so predicting on the
        # logical shape would flag spurious byte-drift on any shape the
        # mesh does not divide (83% over on a (7,5)/4-mesh resplit). The
        # span/phase accounting keeps the logical `cost` — two different
        # questions, two different volumes.
        phys_shape = list(gshape)
        for ax in (self.__split, new_split):
            if ax is not None:
                phys_shape[ax] = comm.padded_size(gshape[ax])
        from . import collective_prec

        phys_cost = telemetry.collectives.relayout_cost(
            phys_shape, self.__dtype.byte_size(), self.__split, new_split,
            comm.size, precision=wire, block=collective_prec.block_size(),
        )
        from . import program_cache

        # the audit lowers the SAME cached jitted program the dispatch path
        # executes, under the same registry signature — one program, one key
        return hlo.audit_call(
            site,
            lambda: (self.__relayout_program(new_split, wire=wire), (buf,)),
            predicted=phys_cost,
            key=program_cache.program_key(
                "relayout", self._relayout_key(new_split, wire), comm=comm
            ),
            fields={"old_split": self.__split, "new_split": new_split,
                    "gshape": list(gshape), "wire": wire},
        )

    def _relayout_key(
        self, new_split: Optional[int], wire: str = "off"
    ) -> tuple:
        """Static-config portion of the relayout program signature. The
        effective collective-compression mode is part of it — a bf16-wire
        and an exact relayout are different programs (ISSUE 9)."""
        return (
            self.__gshape, str(self.__array.dtype), self.__split, new_split,
            wire,
        )

    def _relayout_executable(
        self, new_split: Optional[int], donate: bool = False,
        precision: Optional[str] = None,
    ):
        """The cached monolithic relayout program (for AOT consumers:
        memory_guard budgeting, the planner's measured-need decision, the
        bench `relayout_plan` / `collective_prec` probes, tests). Building
        it never traces or executes."""
        return self.__relayout_program(
            new_split, donate, wire=self._wire_mode(new_split, precision)
        )

    def __relayout_program(
        self, new_split: Optional[int], donate: bool = False,
        wire: str = "off",
    ):
        """The cached compiled relayout program for this layout signature:
        logical slice → tail re-pad → canonical ``out_shardings``. With a
        compressed wire mode the re-shard happens on the quantized tensor
        (collective_prec.gspmd_reshard): the emitted collective moves the
        compressed dtype, and dequantization lands in the destination
        layout inside the same program."""
        from . import program_cache

        comm = self.__comm
        gshape = self.__gshape
        pshape = comm.padded_shape(gshape, new_split)
        pad_count = self.pad_count
        src_split = self.__split
        if comm.size > 1:
            tgt = (
                comm.sharding(new_split, len(gshape))
                if new_split is not None
                else comm.replicated()
            )
        else:
            tgt = None

        def build():
            if wire != "off":
                from . import collective_prec

                blk = collective_prec.block_size()

                def compressed_relayout(b):
                    if pad_count != 0:
                        b = b[tuple(slice(0, g) for g in gshape)]
                    if tuple(b.shape) != pshape:
                        b = jnp.pad(
                            b, [(0, p - s) for p, s in zip(pshape, b.shape)]
                        )
                    return collective_prec.gspmd_reshard(
                        b, comm, src_split, new_split, wire, blk
                    )

                return compressed_relayout

            def relayout_program(b):
                if pad_count != 0:
                    b = b[tuple(slice(0, g) for g in gshape)]
                if tuple(b.shape) != pshape:
                    b = jnp.pad(
                        b, [(0, p - s) for p, s in zip(pshape, b.shape)]
                    )
                return b

            return relayout_program

        return program_cache.cached_program(
            "relayout", self._relayout_key(new_split, wire), build,
            comm=comm, out_shardings=tgt, donate=(0,) if donate else (),
        )

    def __relayout_impl(
        self, new_split: Optional[int], donate: bool = False,
        plan=None, audit: bool = False, wire: str = "off",
    ) -> jax.Array:
        buf = self.larray
        pshape = self.__comm.padded_shape(self.__gshape, new_split)
        if (
            self.pad_count == 0
            and tuple(buf.shape) == pshape
            and self.__comm.size <= 1
        ):
            return buf
        # host-side bookkeeping mirrors what the compiled program does, so
        # the perf-counter contract (fast paths stay at 0) is unchanged
        logical = self.__gshape if self.pad_count else tuple(buf.shape)
        if pshape != tuple(logical):
            _PERF_STATS["repads"] += 1
        if self.__comm.size > 1:
            _PERF_STATS["device_puts"] += 1
        if plan is not None and plan.kind != "monolithic":
            # decomposed plan: chain of cached stage programs (the source
            # buffer must stay live through every stage, so donation — if
            # requested — is simply dropped; the chunk accumulator chain
            # donates internally instead)
            from . import relayout_planner

            return relayout_planner.run(
                plan, buf, self.__comm, audit=audit, wire=wire
            )
        fn = self.__relayout_program(new_split, donate, wire)
        return fn(buf)

    def _replicated(self) -> jax.Array:
        """Logical global array replicated on every device — the raw buffer
        when already replicated, one compiled :meth:`_relayout` otherwise.
        The multi-host-safe way to read a SMALL array whole (index vectors,
        centroids, class statistics); unlike :meth:`_logical` it never hands
        the host a non-canonically-shardable view."""
        if self.__split is None:
            return self.larray
        return self._relayout(None)

    @classmethod
    def from_logical(
        cls,
        array: jax.Array,
        split: Optional[int],
        device: Optional[Device] = None,
        comm: Optional[MeshCommunication] = None,
        dtype: Optional[Type[types.datatype]] = None,
    ) -> "DNDarray":
        """Wrap an unpadded logical jax array: tail-pad the split dim and lay
        it out on the mesh."""
        device = device if device is not None else get_device()
        comm = sanitize_comm(comm)
        gshape = tuple(int(s) for s in array.shape)
        split = sanitize_axis(gshape, split)
        pshape = comm.padded_shape(gshape, split)
        if pshape != gshape:
            _PERF_STATS["repads"] += 1
            pad = [(0, p - g) for p, g in zip(pshape, gshape)]
            array = jnp.pad(array, pad)
        if split is not None and comm.size > 1:
            _PERF_STATS["device_puts"] += 1
            array = jax.device_put(array, comm.sharding(split, len(gshape)))
        elif comm.size > 1:
            _PERF_STATS["device_puts"] += 1
            array = jax.device_put(array, comm.replicated())
        ht_dtype = dtype if dtype is not None else types.canonical_heat_type(array.dtype)
        return cls(array, gshape, ht_dtype, split, device, comm, True)

    # ---------------------------------------------------------- conversions

    def _host_view(self) -> jax.Array:
        """Logical global array safe to hand to the host from ANY process
        topology. Single-controller: the cheap :meth:`_logical` slice.
        Multi-host with a padded split axis: one compiled
        :meth:`_replicated` relayout (the reference gathers via Allgatherv,
        dndarray.py:1256; here XLA's all-gather does it and the result is
        fully replicated, hence addressable on every process)."""
        if self.pad_count and jax.process_count() > 1:
            return self._replicated()
        return self._logical()

    def numpy(self) -> np.ndarray:
        """Gather the logical global array to host numpy (reference
        dndarray.py: `numpy`). Multi-host safe: padded split arrays relayout
        through one compiled all-gather instead of refusing."""
        return np.asarray(self._host_view())

    def __array__(self, dtype=None) -> np.ndarray:
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def tolist(self) -> list:
        return self.numpy().tolist()

    def item(self):
        """The single element of a size-1 array as a python scalar (reference
        dndarray.py:952)."""
        if self.size != 1:
            raise ValueError("only one-element DNDarrays can be converted to python scalars")
        return self._host_view().reshape(()).item()

    def __bool__(self) -> bool:
        return bool(self.__cast(builtins.bool))

    def __float__(self) -> float:
        return self.__cast(builtins.float)

    def __int__(self) -> int:
        return self.__cast(builtins.int)

    def __complex__(self) -> complex:
        return self.__cast(builtins.complex)

    def __cast(self, cast_function):
        # scalar casts (reference dndarray.py:520: allreduce+bcast; here the
        # logical value is globally addressable)
        if self.size == 1:
            return cast_function(self.item())
        raise TypeError("only size-1 arrays can be converted to Python scalars")

    # -------------------------------------------------------------- methods

    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        """Cast to the given heat type (reference dndarray.py:424).
        ``copy=True`` returns a REAL buffer copy even for a same-dtype
        cast (jax's convert_element_type is a no-op then and would alias
        the source — which a later donating ``resplit_`` of either array
        could invalidate; same fix class as ``ht.array(copy=True)``)."""
        dtype = types.canonical_heat_type(dtype)
        casted = self.larray.astype(dtype.jnp_type())
        if copy:
            if casted is self.larray:
                casted = jnp.copy(casted)
            return DNDarray(
                casted, self.__gshape, dtype, self.__split, self.__device, self.__comm, True
            )
        self.__array = casted
        self.__dtype = dtype
        self._invalidate_halo()
        return self

    def cpu(self) -> "DNDarray":
        """Copy to the CPU platform (reference dndarray.py: `cpu`)."""
        from . import devices as _devices

        return self._to_device(_devices.cpu)

    def _to_device(self, device: Device) -> "DNDarray":
        comm = MeshCommunication(device=device, axis=self.__comm.axis_name)
        return DNDarray.from_logical(
            jnp.asarray(np.asarray(self._logical())), self.__split, device, comm, self.__dtype
        )

    def is_distributed(self) -> bool:
        """True if data lives on more than one device (reference
        dndarray.py:585)."""
        return self.__split is not None and self.__comm.size > 1

    def is_balanced(self, force_check: bool = False) -> bool:
        """Tail-pad layout is balanced by construction (reference
        dndarray.py:600)."""
        return True

    def balance_(self) -> None:
        """No-op under the tail-pad layout (reference dndarray.py:474
        re-chunks ragged shards)."""
        return None

    def counts_displs(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-position counts/displacements along the split dim (reference
        dndarray.py:552)."""
        if self.__split is None:
            raise ValueError("Non-distributed DNDarray has no counts and displacements")
        return self.__comm.counts_displs(self.__gshape[self.__split])

    def resplit_(self, axis: Optional[int] = None) -> "DNDarray":
        """In-place redistribution to a new split axis (reference
        dndarray.py:1213). On TPU this is one cached compiled relayout
        (slice to logical, re-pad for the new axis, canonical target
        sharding — XLA emits the all-to-all). The source buffer is dead
        after the call, so it is **donated** to XLA (the ``out=``-style
        memory contract): its storage may be reused for the result instead
        of holding both layouts live. Any previously captured ``.larray``
        handle is invalidated by the donation — EXCEPT buffers a pending
        fused chain captured by value (core/fusion.py marks them via
        :meth:`_mark_leaf_captured`): those relayouts skip donation so the
        chain's later flush never sees a deleted array."""
        axis = sanitize_axis(self.__gshape, axis)
        if axis == self.__split:
            return self
        # donation requires the source buffer to be truly dead: a pending
        # fused chain that captured it by value (core/fusion.py) would
        # flush against a deleted array, so those relayouts copy instead.
        # Flush OUR OWN pending chain first — flushing is what discovers
        # whether the result buffer is shared with sibling DAGs
        # (node.shared), so deciding donate before the flush would donate
        # a buffer a sibling still references.
        self._fusion_flush()
        self.__array = self._relayout(axis, donate=self._buffer_donatable())
        self.__pshape = tuple(self.__array.shape)
        self.__leaf_captured = False
        self._invalidate_halo()
        self.__split = axis
        self.__lshape_map = None
        return self

    def resplit(
        self, axis: Optional[int] = None, *, audit: bool = False,
        precision: Optional[str] = None,
    ) -> "DNDarray":
        from . import manipulations

        return manipulations.resplit(
            self, axis, audit=audit, precision=precision
        )

    def redistribute_(self, lshape_map=None, target_map=None) -> None:
        """API-parity shim (reference dndarray.py:1007 reshuffles to an
        arbitrary ragged target map via MPI p2p).

        FORMALLY CLOSED for ragged targets — the design decision is
        documented in PARITY.md ("redistribute_ and ragged target maps"):
        the XLA layout model admits exactly one physical layout per
        (gshape, split, mesh) — equal ceil-rule shards with a tail pad —
        so "rank 0 holds 7 rows, rank 1 holds 2" has no representation to
        redistribute *to*; any compiled op would relayout it back first.
        Every layout this framework can produce IS the canonical map, so:

        * a canonical ``target_map`` (or None) is already satisfied —
          accepted as a no-op, matching the reference's fast path;
        * a non-canonical map raises NotImplementedError naming the
          supported relayouts (``resplit_`` to change the axis,
          ``balance_`` to canonicalize ragged ``is_split`` inputs) —
          deliberate imbalance on TPU meshes is expressed by reshaping
          the mesh or masking work, not by ragged shards.
        """
        if target_map is None:
            return None
        want = np.asarray(target_map)
        have = self.lshape_map
        if want.shape == have.shape and (want == have).all():
            return None
        raise NotImplementedError(
            "redistribute_ to a non-canonical (ragged) lshape_map is "
            "formally closed on the XLA tail-pad layout — every sharded "
            "dim has exactly one physical layout per (gshape, split, "
            "mesh); see PARITY.md 'redistribute_ and ragged target maps'. "
            "Use resplit_() to change the distribution axis, balance_() "
            "to canonicalize, or ht.ragged (core/ragged.py) to carry a "
            "rank-proportional ownership intent on the canonical layout "
            "— Ragged.redistribute(new_counts) is the zero-copy form of "
            "this call"
        )

    def create_lshape_map(self, force_check: bool = False) -> np.ndarray:
        return self.lshape_map

    def fill_diagonal(self, value) -> "DNDarray":
        """Fill the main diagonal in place (reference dndarray.py: 2-D only).
        Runs on the physical buffer: global position (i, i) is a physical
        position too (tail pads only extend the split dim), so a masked
        where against a positional iota pair touches no pad and gathers
        nothing."""
        if self.ndim != 2:
            raise ValueError("DNDarray must be 2D")
        k = min(self.__gshape)
        buf = self.larray
        rows = jax.lax.broadcasted_iota(jnp.int32, buf.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, buf.shape, 1)
        on_diag = (rows == cols) & (rows < k) & (cols < k)
        self.__array = jnp.where(
            on_diag, jnp.asarray(value, buf.dtype), buf
        )
        self._invalidate_halo()
        return self

    # ---------------------------------------------------------------- halos

    def __check_halo_size(self, halo_size: int) -> None:
        """Uniform validation regardless of device count, so code tested on
        one device fails the same way on a pod."""
        if not isinstance(halo_size, builtins.int) or halo_size <= 0:
            raise ValueError(
                f"halo_size needs to be a positive integer, got {halo_size}"
            )
        if self.__split is not None and self.__comm.size > 1:
            min_chunk = int(self.lshape_map[:, self.__split].min())
            if halo_size > min_chunk:
                raise ValueError(
                    f"halo_size {halo_size} exceeds the smallest local chunk "
                    f"({min_chunk}) along split {self.__split}"
                )

    def __halo_parts(self, halo_size: int):
        """``(from_prev, from_next)`` neighbor slices via the shared ring
        kernel (:func:`heat_tpu.parallel.halo.halo_exchange`). Pads are
        masked to zero BEFORE slicing so a non-divisible split dim can never
        leak unspecified pad values into a neighbor's halo (the module's pad
        invariant); edge positions get zero blocks."""
        from ..parallel.halo import halo_exchange

        buf = self._masked(0) if self.pad_count else self.larray
        return halo_exchange(
            buf, halo_size, comm=self.__comm, axis=self.__split,
            return_parts=True,
        )

    def get_halo(self, halo_size: int) -> None:
        """Fetch boundary slices of neighboring shards (reference
        dndarray.py:360: Isend/Irecv with prev/next rank). Stores the
        neighbor slices for :attr:`halo_prev` / :attr:`halo_next` — computed
        once here, so the property reads are cached-array lookups."""
        self.__check_halo_size(halo_size)
        if self.__split is None or self.__comm.size == 1:
            self.__halo_prev = self.__halo_next = None
            return
        self.__halo_prev, self.__halo_next = self.__halo_parts(halo_size)
        self.__halo_fetched_size = halo_size

    def _invalidate_halo(self) -> None:
        """Drop cached halos — called by every storage mutator so a stale
        fetch can never be served after resplit_/setitem/fill_diagonal."""
        self.__halo_prev = self.__halo_next = None
        self.__halo_fetched_size = None

    @property
    def halo_prev(self) -> Optional[jax.Array]:
        """Slice received from the previous mesh position by the last
        :meth:`get_halo` (reference dndarray.py ``halo_prev``), as a sharded
        ``(…, halo_size, …)`` buffer — one block per position, zero at the
        global edge. ``None`` before any halo fetch (or after a mutation
        invalidated it)."""
        return getattr(self, "_DNDarray__halo_prev", None)

    @property
    def halo_next(self) -> Optional[jax.Array]:
        """Slice received from the next mesh position — see :attr:`halo_prev`."""
        return getattr(self, "_DNDarray__halo_next", None)

    def stride(self) -> Tuple[int, ...]:
        """Element strides of the local shard, C-order (reference delegates
        to ``torch.Tensor.stride``)."""
        return self.strides

    @property
    def strides(self) -> Tuple[int, ...]:
        """Element strides of the local shard, C-order."""
        lshape = self.lshape
        strides = []
        acc = 1
        for dim in reversed(lshape):
            strides.append(acc)
            acc *= max(dim, 1)
        return tuple(reversed(strides))

    def array_with_halos(self, halo_size: int) -> jax.Array:
        """Physical buffer where every shard is extended with ``halo_size``
        rows of both neighbors along the split axis (zero-filled at the
        global edges and in masked pad positions; the reference leaves edge
        ranks one-sided, dndarray.py:333). Built on the same exchange kernel
        as :meth:`get_halo`; halos cached by a matching ``get_halo`` are
        reused instead of re-running the exchange."""
        self.__check_halo_size(halo_size)
        if self.__split is None or self.__comm.size == 1:
            return self.larray
        comm = self.__comm
        s = self.__split
        cached = (
            getattr(self, "_DNDarray__halo_prev", None) is not None
            and getattr(self, "_DNDarray__halo_fetched_size", None) == halo_size
        )
        # both paths take the pad-masked center so the result is identical
        # whether or not a prior get_halo populated the cache
        buf = self._masked(0) if self.pad_count else self.larray
        if cached:
            spec = comm.spec(s, self.ndim)
            return jax.shard_map(
                lambda hp, x, hn: jnp.concatenate([hp, x, hn], axis=s),
                mesh=comm.mesh, in_specs=(spec, spec, spec), out_specs=spec,
            )(self.__halo_prev, buf, self.__halo_next)
        from ..parallel.halo import halo_exchange

        return halo_exchange(buf, halo_size, comm=comm, axis=s)

    # ------------------------------------------------------------- printing

    def __repr__(self) -> str:
        from . import printing

        return printing.__str__(self)

    __str__ = __repr__

    # ---------------------------------------------------------- item access

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.__gshape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, key) -> "DNDarray":
        from . import indexing

        return indexing.getitem(self, key)

    def __setitem__(self, key, value) -> None:
        from . import indexing

        indexing.setitem(self, key, value)

    def __internal_set(self, array: jax.Array, gshape, split) -> None:
        """Mutate storage after an indexing update (internal)."""
        self.__array = array
        self.__fused = None
        self.__leaf_captured = False
        self.__pshape = tuple(array.shape)
        self.__gshape = tuple(gshape)
        self.__split = split
        self.__lshape_map = None
        self._invalidate_halo()

    # (arithmetic/relational/etc. dunders are attached by the op modules at
    # import time — same pattern as the reference, which assigns them at the
    # bottom of each op module.)


# attach scalar conversion aliases expected by numpy interop
DNDarray.__index__ = DNDarray.__int__
