"""Shape & layout manipulations (reference: heat/core/manipulations.py, 4040
LoC, the comm-heaviest module: reshape via Alltoallv :1962, parallel
sample-sort :2258-2409, ring roll :2061, rank-mirror flip :876).

Design here: every function computes on the **logical global view** and
relays out through `DNDarray.from_logical`, which restores the tail-pad
layout — the explicit Alltoall/Gatherv choreography of the reference becomes
XLA relayout. Ops whose semantics cross the split axis on *padded* arrays
(sort/topk) neutralize the pad first; `unique`/`nonzero` run eagerly (dynamic
shapes are jit-hostile — the documented host path, SURVEY §7 hard parts).
"""

from __future__ import annotations

import builtins
import operator
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import program_cache, types
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape
from .. import telemetry

__all__ = [
    "balance",
    "column_stack",
    "concatenate",
    "diag",
    "diagonal",
    "dsplit",
    "expand_dims",
    "flatten",
    "flip",
    "fliplr",
    "flipud",
    "hsplit",
    "hstack",
    "moveaxis",
    "pad",
    "ravel",
    "redistribute",
    "repeat",
    "reshape",
    "resplit",
    "roll",
    "rot90",
    "row_stack",
    "shape",
    "sort",
    "split",
    "squeeze",
    "stack",
    "swapaxes",
    "tile",
    "topk",
    "unique",
    "vsplit",
    "vstack",
]


def _rewrap(log, split, proto: DNDarray, dtype=None) -> DNDarray:
    return DNDarray.from_logical(log, split, proto.device, proto.comm, dtype)


def _canonical(buf, comm, split):
    """Ensure ``buf`` carries the canonical NamedSharding for ``split``.
    A no-op (and uncounted) when XLA's sharding propagation already chose
    it; otherwise one counted resharding device_put — so the perf counters
    keep their contract: physical fast paths that move no data stay at 0."""
    want = comm.sharding(split, buf.ndim)
    try:
        if buf.sharding.is_equivalent_to(want, buf.ndim):
            return buf
    except Exception:
        pass
    from .dndarray import _PERF_STATS

    _PERF_STATS["device_puts"] += 1
    return jax.device_put(buf, want)


def balance(array: DNDarray, copy: bool = False) -> DNDarray:
    """Balanced copy (reference manipulations.py `balance`); the tail-pad
    layout is always balanced, so this is (a copy of) the input."""
    from .memory import copy as _copy

    return _copy(array) if copy else array


def _reshape_split_fn(comm, in_shape, out_shape, out_split):
    """Cached jitted slice→reshape→re-pad program for a reshape that crosses
    the split axis — the genuine all-to-all data movement (the reference's
    Alltoallv relayout, manipulations.py:1962) as ONE compiled XLA program
    laid out to the result's canonical sharding; multi-host safe. Memoized
    in the process-global :mod:`..program_cache` registry."""
    pshape = comm.padded_shape(out_shape, out_split)
    out_shardings = (
        comm.replicated()
        if out_split is None
        else comm.sharding(out_split, len(out_shape))
    )

    def build():
        def f(buf):
            log = buf[tuple(slice(0, g) for g in in_shape)]
            res = jnp.reshape(log, out_shape)
            pad = [(0, p - g) for p, g in zip(pshape, out_shape)]
            return jnp.pad(res, pad)

        return f

    return program_cache.cached_program(
        "reshape_split", (in_shape, out_shape, out_split), build,
        comm=comm, out_shardings=out_shardings,
    )


def _concat_split_fn(comm, axis, out_split, in_shapes, gshape, out_dtype):
    """Cached jitted slice→concat→re-pad program for concatenation along
    the split axis (keyed on shapes/dtype in the process-global
    :mod:`..program_cache` registry so repeated calls reuse the compile)."""
    pshape = comm.padded_shape(gshape, out_split)
    jdt = out_dtype.jnp_type()

    def build():
        def cat(*bufs):
            logs = [
                b[tuple(slice(0, g) for g in shp)].astype(jdt)
                for b, shp in zip(bufs, in_shapes)
            ]
            res = jnp.concatenate(logs, axis=axis)
            pad = [(0, p - g) for p, g in zip(pshape, gshape)]
            return jnp.pad(res, pad)

        return cat

    return program_cache.cached_program(
        "concat_split", (axis, out_split, in_shapes, gshape, str(jdt)),
        build, comm=comm,
        out_shardings=comm.sharding(out_split, len(gshape)),
    )


def concatenate(arrays: Sequence[DNDarray], axis: int = 0) -> DNDarray:
    """Join arrays along an existing axis (reference manipulations.py:188,
    with the split-combination case table :377-443).

    Split-combination rules (mirroring the reference's case table):

    * all inputs replicated → replicated result;
    * any input split → result carries that split (all split inputs must
      agree on the axis);
    * concatenation along a non-split axis runs on the **physical** buffers —
      per-position pads line up, so no relayout happens (replicated inputs
      are tail-padded to the physical extent first);
    * concatenation along the split axis itself is relayout-inherent (the
      reference's resplit/Alltoall cases) and goes through the logical view.
    """
    from . import factories

    arrays = [a if isinstance(a, DNDarray) else factories.array(a) for a in arrays]
    if len(arrays) < 1:
        raise ValueError("need at least one array to concatenate")
    axis = sanitize_axis(arrays[0].shape, axis)
    splits = {a.split for a in arrays if a.split is not None}
    if len(splits) > 1:
        raise RuntimeError(
            f"concatenate inputs are distributed along different axes {sorted(splits)}; "
            "resplit first (reference manipulations.py:377 raises here too)"
        )
    out_split = next(iter(splits), None)
    out_dtype = arrays[0].dtype
    for a in arrays[1:]:
        out_dtype = types.promote_types(out_dtype, a.dtype)

    comm = arrays[0].comm
    if out_split is not None and axis != out_split:
        # physical fast path: pads sit at the same positions in every input
        P = comm.padded_size(arrays[0].shape[out_split])
        bufs = []
        for a in arrays:
            buf = a.larray.astype(out_dtype.jnp_type())
            if a.split is None and buf.shape[out_split] < P:
                pad = [(0, 0)] * a.ndim
                pad[out_split] = (0, P - buf.shape[out_split])
                buf = jnp.pad(buf, pad)
            bufs.append(buf)
        res = jnp.concatenate(bufs, axis=axis)
        gshape = list(arrays[0].shape)
        gshape[axis] = builtins.sum(a.shape[axis] for a in arrays)
        return DNDarray(
            res, tuple(gshape), out_dtype, out_split, arrays[0].device, comm, True
        )

    if out_split is not None:
        # concatenation ALONG the split axis: one compiled
        # slice→concat→re-pad program laid out to the result's canonical
        # sharding — XLA emits the relayout collectives, multi-host safe
        gshape = list(arrays[0].shape)
        gshape[axis] = builtins.sum(a.shape[axis] for a in arrays)
        gshape = tuple(gshape)
        fn = _concat_split_fn(
            comm,
            axis,
            out_split,
            tuple(tuple(a.shape) for a in arrays),
            gshape,
            out_dtype,
        )
        res = fn(*[a.larray for a in arrays])
        return DNDarray(
            res, gshape, out_dtype, out_split, arrays[0].device, comm, True
        )

    logs = [a._logical().astype(out_dtype.jnp_type()) for a in arrays]
    res = jnp.concatenate(logs, axis=axis)
    return _rewrap(res, out_split, arrays[0], out_dtype)


def column_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack 1-D/2-D arrays as columns of a 2-D array (reference
    manipulations.py `column_stack`)."""
    prepared = [expand_dims(a, 1) if a.ndim == 1 else a for a in arrays]
    return concatenate(prepared, axis=1)


def diag(a: DNDarray, offset: int = 0) -> DNDarray:
    """Extract a diagonal or construct a diagonal matrix (reference
    manipulations.py `diag`)."""
    if a.ndim == 1:
        # construction: the 1-D source replicates (compiled relayout, small
        # next to its n² result) and the matrix lays out sharded
        res = jnp.diag(a._replicated(), k=offset)
        return _rewrap(res, a.split, a)
    return diagonal(a, offset=offset)


def diagonal(a: DNDarray, offset: int = 0, dim1: int = 0, dim2: int = 1) -> DNDarray:
    """Diagonal view (reference manipulations.py `diagonal`). 2-D split
    inputs extract shard-side through the paired (rows, cols) sharded
    gather — multi-host safe, no replicated intermediate."""
    dim1 = sanitize_axis(a.shape, dim1)
    dim2 = sanitize_axis(a.shape, dim2)
    if dim1 == dim2:
        raise ValueError("dim1 and dim2 need to be different")
    if a.ndim == 2 and a.split is not None and a.comm.size > 1:
        if (dim1, dim2) == (1, 0):
            return diagonal(swapaxes(a, 0, 1), offset=offset)
        n0, n1 = a.shape
        if offset >= 0:
            klen = builtins.min(n0, n1 - offset)
            r0, c0 = 0, offset
        else:
            klen = builtins.min(n0 + offset, n1)
            r0, c0 = -offset, 0
        klen = builtins.max(klen, 0)
        from .indexing import getitem

        rows = jnp.arange(klen) + r0
        cols = jnp.arange(klen) + c0
        return getitem(a, (rows, cols))
    res = jnp.diagonal(a._logical(), offset=offset, axis1=dim1, axis2=dim2)
    out_split = None
    if a.split is not None and a.split not in (dim1, dim2):
        s = a.split
        s -= builtins.sum(1 for d in (dim1, dim2) if d < s)
        out_split = s
    elif a.split is not None:
        out_split = res.ndim - 1
    return _rewrap(res, out_split, a)


def dsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 2 (reference manipulations.py `dsplit`)."""
    return split(x, indices_or_sections, axis=2)


def expand_dims(a: DNDarray, axis: int) -> DNDarray:
    """Insert a size-1 dimension (reference manipulations.py `expand_dims`).
    Pure metadata + a physical reshape — the pad travels with the split dim,
    no relayout."""
    axis = sanitize_axis(tuple(a.shape) + (1,), axis)
    res = jnp.expand_dims(a.larray, axis)
    out_split = a.split
    if out_split is not None and axis <= out_split:
        out_split += 1
    gshape = a.shape[:axis] + (1,) + a.shape[axis:]
    return DNDarray(res, gshape, a.dtype, out_split, a.device, a.comm, True)


def flatten(a: DNDarray) -> DNDarray:
    """1-D copy of the array (reference manipulations.py `flatten`).
    Delegates to :func:`reshape`, whose zero-comm fast paths apply when the
    layout allows."""
    return reshape(a, (-1,), new_split=0 if a.split is not None else None)


def _permute_split_axis(a: DNDarray, idx_of: "jnp.ndarray") -> "jax.Array":
    """Physical buffer with the padded split axis permuted by a logical
    index map: output position ``j < n`` reads input position ``idx_of[j]``;
    pad positions read themselves. One cached compiled sharded gather (XLA
    emits the collective permutes) — no host relayout, multi-host safe.
    The index map is data (an argument), so every flip/roll over the same
    layout shares one program (the roll/pad passes of ISSUE 3)."""
    s = a.split
    n = a.shape[s]
    comm = a.comm
    ndim = a.ndim
    sharded = comm.size > 1

    def build():
        def permute(buf, idx_of):
            iota = jnp.arange(buf.shape[s])
            idx = jnp.where(iota < n, idx_of, iota)
            out = jnp.take(buf, idx, axis=s)
            if sharded:
                out = jax.lax.with_sharding_constraint(
                    out, comm.sharding(s, ndim)
                )
            return out

        return permute

    fn = program_cache.cached_program(
        "permute_split_axis", (s, n, ndim, sharded), build, comm=comm,
    )
    return fn(a.larray, idx_of)


def flip(a: DNDarray, axis=None) -> DNDarray:
    """Reverse element order along axis (reference manipulations.py:876 swaps
    mirrored ranks p2p). Non-split axes flip shard-locally; a padded split
    dim flips via one index-map gather on the physical buffer (the pad stays
    at the tail) — no logical-view relayout either way."""
    if axis is None:
        axes = tuple(range(a.ndim))
    else:
        ax = sanitize_axis(a.shape, axis)
        axes = (ax,) if isinstance(ax, builtins.int) else tuple(ax)
    if a.pad_count == 0 or a.split not in axes:
        res = jnp.flip(a.larray, axis=axes)
        return DNDarray(res, a.shape, a.dtype, a.split, a.device, a.comm, True)
    s = a.split
    n = a.shape[s]
    iota = jnp.arange(a.larray.shape[s])
    res = _permute_split_axis(a, n - 1 - iota)
    other = tuple(ax for ax in axes if ax != s)
    if other:
        res = jnp.flip(res, axis=other)
    return DNDarray(res, a.shape, a.dtype, a.split, a.device, a.comm, True)


def fliplr(a: DNDarray) -> DNDarray:
    if a.ndim < 2:
        raise IndexError("expected at least a 2-D array")
    return flip(a, 1)


def flipud(a: DNDarray) -> DNDarray:
    return flip(a, 0)


def hsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 1 (axis 0 for 1-D; reference `hsplit`)."""
    if x.ndim < 2:
        return split(x, indices_or_sections, axis=0)
    return split(x, indices_or_sections, axis=1)


def hstack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Horizontal stack (reference `hstack`)."""
    arrays = list(arrays)
    if builtins.all(a.ndim == 1 for a in arrays):
        return concatenate(arrays, axis=0)
    return concatenate(arrays, axis=1)


def moveaxis(x: DNDarray, source, destination) -> DNDarray:
    """Move axes to new positions (reference `moveaxis`)."""
    if isinstance(source, builtins.int):
        source = (source,)
    if isinstance(destination, builtins.int):
        destination = (destination,)
    source = [sanitize_axis(x.shape, s) for s in source]
    destination = [sanitize_axis(x.shape, d) for d in destination]
    if len(source) != len(destination):
        raise ValueError("source and destination arguments must have the same number of elements")
    order = [n for n in range(x.ndim) if n not in source]
    for dest, src in sorted(zip(destination, source)):
        order.insert(dest, src)
    from .linalg import transpose

    return transpose(x, order)


def pad(array: DNDarray, pad_width, mode: str = "constant", constant_values=0) -> DNDarray:
    """Pad the logical array (reference manipulations.py:1126)."""
    log = array._logical()
    if mode == "constant":
        res = jnp.pad(log, pad_width, mode=mode, constant_values=constant_values)
    else:
        res = jnp.pad(log, pad_width, mode=mode)
    return _rewrap(res, array.split, array)


def ravel(a: DNDarray) -> DNDarray:
    """Flatten (reference `ravel`)."""
    return flatten(a)


def redistribute(arr: DNDarray, lshape_map=None, target_map=None) -> DNDarray:
    """Non-inplace redistribute (reference manipulations.py `redistribute`);
    see DNDarray.redistribute_ for the layout discussion."""
    from .memory import copy as _copy

    out = _copy(arr)
    out.redistribute_(lshape_map, target_map)
    return out


def repeat(a: DNDarray, repeats, axis: Optional[int] = None) -> DNDarray:
    """Repeat elements (reference `repeat`). Scalar repeats off the split
    axis run shard-locally on the physical buffer — zero communication."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if isinstance(repeats, DNDarray):
        repeats = repeats._logical()
    elif isinstance(repeats, (list, tuple)):
        repeats = jnp.asarray(repeats)  # numpy accepts sequences; jnp doesn't
    if (
        axis is not None
        and a.split is not None
        and sanitize_axis(a.shape, axis) != a.split
        and np.ndim(repeats) == 0
    ):
        ax = sanitize_axis(a.shape, axis)
        res = _canonical(jnp.repeat(a.larray, repeats, axis=ax), a.comm, a.split)
        gshape = tuple(
            s * builtins.int(repeats) if d == ax else s
            for d, s in enumerate(a.shape)
        )
        return DNDarray(res, gshape, a.dtype, a.split, a.device, a.comm, True)
    res = jnp.repeat(a._logical(), repeats, axis=axis)
    if axis is None:
        out_split = 0 if a.split is not None else None
    else:
        out_split = a.split
    return _rewrap(res, out_split, a)


def reshape(a: DNDarray, *shape, new_split: Optional[int] = None) -> DNDarray:
    """Reshape to a new global shape (reference manipulations.py:1815, which
    redistributes via Alltoallv :1962).

    Reshapes that leave the split axis intact run PER-SHARD on the physical
    buffer with zero communication — trailing reshape (split axis and every
    dim before it unchanged) and leading reshape (split axis and every dim
    after it unchanged); tail pads ride along untouched. Only a reshape
    that actually crosses the split axis pays the logical-view relayout
    (the genuine all-to-all data movement)."""
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    shape = list(shape)
    # resolve -1 placeholder
    neg = [i for i, s in enumerate(shape) if s == -1]
    if len(neg) > 1:
        raise ValueError("can only specify one unknown dimension")
    if neg:
        known = 1
        for i, s in enumerate(shape):
            if i != neg[0]:
                known *= s
        if known == 0:
            # numpy raises ValueError here; bare // would ZeroDivisionError
            raise ValueError(
                f"cannot reshape array of size {a.size} into shape {tuple(shape)}"
            )
        shape[neg[0]] = a.size // known
    shape = sanitize_shape(tuple(shape))
    if int(np.prod(shape)) != a.size:
        raise ValueError(f"cannot reshape array of size {a.size} into shape {tuple(shape)}")
    if new_split is None:
        if a.split is None:
            new_split = None
        elif a.split < len(shape):
            new_split = a.split
        else:
            # rank-reducing reshape: default to the position where the split
            # dim survives (leading dims collapsed) so the zero-comm leading
            # fast path applies by default; fall back to 0 otherwise
            cand = len(shape) - (a.ndim - a.split)
            if (
                cand >= 0
                and tuple(shape[cand:]) == tuple(a.shape[a.split :])
                and int(np.prod(shape[:cand], initial=1))
                == int(np.prod(a.shape[: a.split], initial=1))
            ):
                new_split = cand
            else:
                new_split = 0
    new_split = sanitize_axis(shape, new_split)
    s = a.split
    if s is not None and a.comm.size > 1:
        shape_t = tuple(shape)
        # trailing reshape: dims [0..s] unchanged, new split stays at s
        if new_split == s and shape_t[: s + 1] == tuple(a.shape[: s + 1]):
            phys = a.larray.shape[: s + 1] + shape_t[s + 1 :]
            buf = _canonical(jnp.reshape(a.larray, phys), a.comm, s)
            return DNDarray(buf, shape_t, a.dtype, s, a.device, a.comm, True)
        # leading reshape: dims [s..] unchanged and land at new_split
        if (
            shape_t[new_split:] == tuple(a.shape[s:])
            and int(np.prod(shape_t[:new_split], initial=1)) == int(np.prod(a.shape[:s], initial=1))
        ):
            phys = shape_t[:new_split] + a.larray.shape[s:]
            buf = _canonical(jnp.reshape(a.larray, phys), a.comm, new_split)
            return DNDarray(buf, shape_t, a.dtype, new_split, a.device, a.comm, True)
    if a.split is not None and a.comm.size > 1:
        # reshape CROSSING the split axis: one compiled relayout program
        fn = _reshape_split_fn(a.comm, tuple(a.shape), tuple(shape), new_split)
        res = fn(a.larray)
        return DNDarray(
            res, tuple(shape), a.dtype, new_split, a.device, a.comm, True
        )
    res = jnp.reshape(a._logical(), shape)
    return _rewrap(res, new_split, a)


def resplit(
    arr: DNDarray, axis: Optional[int] = None, *, audit: bool = False,
    precision: Optional[str] = None,
) -> DNDarray:
    """Out-of-place redistribution to a new split axis (reference
    manipulations.py:3351). One compiled relayout — multi-host safe.

    With telemetry enabled the op is a ``resplit`` span carrying the
    analytic collective kind and wire bytes; the inner ``relayout`` span
    (the primitive) nests under it. With ``audit=True`` (or the global
    ``HEAT_TPU_HLO_AUDIT=1`` opt-in) the equivalent program is also
    lower-compiled and the collectives XLA actually emitted are diffed
    against the analytic prediction — docs/OBSERVABILITY.md.

    ``precision`` (ISSUE 9): per-call collective-compression override —
    ``"off"``/``"bf16"``/``"int8"``/``"blockwise"`` — defaulting to the
    global ``HEAT_TPU_COLLECTIVE_PREC`` knob. Compressed modes move the
    relayout payload at the reduced wire dtype (docs/TUNING_RUNBOOK.md
    §0.11 has the accuracy contract); float dtypes only, ``off`` is
    bit-identical to the unknobbed op."""
    axis = sanitize_axis(arr.shape, axis)
    wire = arr._wire_mode(axis, precision)
    _cost, fields, do_audit = telemetry.op_cost(
        arr.comm.relayout_cost, arr.shape, arr.dtype.byte_size(),
        arr.split, axis, wire, audit=audit,
    )
    # the audit site rides down into the primitive: a monolithic plan is
    # audited once as "resplit", a planner-decomposed plan once per stage
    # as "relayout_stage" — never both (core/relayout_planner.py)
    if telemetry.enabled():
        with telemetry.span(
            "resplit", old_split=arr.split, new_split=axis,
            gshape=list(arr.shape), **fields,
        ) as sp:
            buf = sp.output(
                arr._relayout(
                    axis, audit=do_audit, audit_site="resplit",
                    precision=precision,
                )
            )
    else:
        buf = arr._relayout(
            axis, audit=do_audit, audit_site="resplit", precision=precision
        )
    return DNDarray(buf, arr.shape, arr.dtype, axis, arr.device, arr.comm, True)


def roll(x: DNDarray, shift, axis=None) -> DNDarray:
    """Circular shift (reference manipulations.py:1980, Isend/Irecv ring
    :2061-2069; XLA collective-permute here). Rolls off the padded split dim
    run shard-locally; a roll along the padded split dim is one index-map
    gather on the physical buffer (wrapping around the logical extent, pads
    untouched). Only the flattened ``axis=None`` form of a padded
    multi-dim array needs a relayout, via :func:`flatten`."""
    if axis is not None:
        ax = sanitize_axis(x.shape, axis)
        axes = (ax,) if isinstance(ax, builtins.int) else tuple(ax)
        shifts = (
            tuple(shift) if isinstance(shift, (tuple, list)) else (shift,) * len(axes)
        )
        if len(shifts) != len(axes):
            raise ValueError(
                f"shift and axis must match in length, got {len(shifts)} and {len(axes)}"
            )
        if x.pad_count == 0 or x.split not in axes:
            res = jnp.roll(x.larray, shifts, axis=axes)
            return DNDarray(res, x.shape, x.dtype, x.split, x.device, x.comm, True)
        s = x.split
        n = x.shape[s]
        s_shift = builtins.sum(sh for sh, ax_ in zip(shifts, axes) if ax_ == s)
        iota = jnp.arange(x.larray.shape[s])
        res = _permute_split_axis(x, (iota - s_shift) % n)
        rest = [(sh, ax_) for sh, ax_ in zip(shifts, axes) if ax_ != s]
        if rest:
            res = jnp.roll(res, tuple(r[0] for r in rest), axis=tuple(r[1] for r in rest))
        return DNDarray(res, x.shape, x.dtype, x.split, x.device, x.comm, True)
    if x.pad_count == 0 and x.ndim == 1:
        res = jnp.roll(x.larray, shift)
        return DNDarray(res, x.shape, x.dtype, x.split, x.device, x.comm, True)
    if x.ndim == 1:  # padded 1-D: the split-axis gather form
        return roll(x, shift, axis=0)
    # numpy semantics: roll the flattened array, restore the shape
    flat = roll(flatten(x), shift, axis=0)
    return reshape(flat, x.shape, new_split=x.split)


def rot90(m: DNDarray, k: int = 1, axes=(0, 1)) -> DNDarray:
    """Rotate by 90° in the axes plane (reference `rot90`) — composed from
    :func:`flip` and :func:`swapaxes` (numpy's construction), so it inherits
    their physical no-relayout paths."""
    a0, a1 = (sanitize_axis(m.shape, a) for a in axes)
    if a0 == a1:
        raise ValueError("rot90 axes must be different")
    k = k % 4
    if k == 0:
        # buffer copy, not an alias: a later donating resplit_ of ``m``
        # must not invalidate the rotation result
        return DNDarray(
            jnp.copy(m.larray), m.shape, m.dtype, m.split, m.device, m.comm, True
        )
    if k == 2:
        return flip(flip(m, a0), a1)
    if k == 1:
        return swapaxes(flip(m, a1), a0, a1)
    return flip(swapaxes(m, a0, a1), a1)


def row_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack arrays as rows (reference `row_stack`)."""
    arrays = list(arrays)
    if builtins.all(a.ndim == 1 for a in arrays):
        # uniform 1-D inputs: expanded rows all carry split→1, so the
        # concatenate below stays on the physical fast path
        prepared = [expand_dims(a, 0) for a in arrays]
    else:
        prepared = []
        for a in arrays:
            if a.ndim == 1:
                # align with the 2-D inputs' split frame: replicate the row
                prepared.append(_rewrap(a._logical()[None, :], None, a))
            else:
                prepared.append(a)
    return concatenate(prepared, axis=0)


vstack = row_stack


def shape(a: DNDarray) -> Tuple[int, ...]:
    """Global shape (reference `shape`)."""
    return a.shape


def sort(a: DNDarray, axis: int = -1, descending: bool = False, out=None):
    """Global sort along axis, returning (values, indices) like the reference
    (manipulations.py:2258: parallel sample-sort — local sort, Bcast pivots,
    partition-matrix Allreduce, Alltoallv of values+indices).

    TPU-native distributed algorithm (NOT a port of the sample-sort): when
    the sort axis is the split axis on a multi-device mesh, a `shard_map`
    **odd-even transposition merge-split network** runs: each shard sorts
    locally, then ``p`` rounds of partner block exchange over ICI
    (`ppermute`) + two-key merge (value, global index) keep every shape
    static — the Alltoallv/dynamic-counts choreography of a sample-sort does
    not survive XLA, a fixed merge network does. Cost: p rounds × chunk
    bytes; the two-key sort makes ties break by global index (numpy-stable).
    Other-axis sorts are shard-local single jnp sorts."""
    axis = sanitize_axis(a.shape, axis)
    comm = a.comm
    if a.split == axis and comm.size > 1:
        vals, idx = _oddeven_sort_physical(a, axis, descending)
        values = DNDarray(vals, a.shape, a.dtype, a.split, a.device, a.comm, True)
        indices = DNDarray(idx.astype(jnp.int64), a.shape, types.int64, a.split, a.device, a.comm, True)
    else:
        fill = _sort_fill(a, descending)
        buf = a._masked(fill) if (a.split == axis and a.pad_count) else a.larray
        idx = jnp.argsort(buf, axis=axis, stable=True, descending=descending)
        vals = jnp.take_along_axis(buf, idx, axis=axis)
        values = DNDarray(vals, a.shape, a.dtype, a.split, a.device, a.comm, True)
        indices = DNDarray(idx.astype(jnp.int64), a.shape, types.int64, a.split, a.device, a.comm, True)
    if out is not None:
        out.larray = values.larray
        return values, indices
    return values, indices


def _oddeven_partner_perms(p: int):
    """The two static ppermute partner permutations (even / odd rounds) of
    the odd-even transposition network; unpaired shards self-send."""

    def _perm(b):
        perm, paired = [], set()
        for lo in range(b, p - 1, 2):
            perm += [(lo, lo + 1), (lo + 1, lo)]
            paired |= {lo, lo + 1}
        return perm + [(k, k) for k in range(p) if k not in paired]

    return (_perm(0), _perm(1))


def _oddeven_sort_physical(a: DNDarray, axis: int, descending: bool):
    """Distributed sort of the physical buffer along the split axis.

    Ascending two-key (value, global-index) sort; pads are filled with the
    dtype extreme and index sentinels so they land exactly at the global
    tail (ascending) / front (descending, flipped to the tail afterwards).
    Returns (values, indices) physical buffers obeying the tail-pad
    invariant.
    """
    comm = a.comm
    p = comm.size
    n = a.shape[axis]
    fill = _sort_fill(a, descending)
    buf = a._masked(fill) if a.pad_count else a.larray

    pshape = buf.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, pshape, axis)
    if descending:
        # descending = ascending two-key sort on (value, -index) + flip:
        # within a tie group the NEGATED index orders descending, so after
        # the flip ties come out in ascending index order — matching the
        # stable single-device path regardless of mesh size. Pads (fill =
        # dtype minimum) carry the largest iota, hence the smallest -iota:
        # they sort to the global front and the flip sends them to the tail.
        idx0 = -iota
    else:
        idx0 = iota  # pads already carry the largest global indices

    c = pshape[axis] // p  # local chunk length along the sort axis
    perms = _oddeven_partner_perms(p)

    def kernel(v, i):
        # the p rounds run as a fori_loop with lax.cond selecting between the
        # two static partner permutations (even/odd parity) — compiling ONE
        # round body instead of p unrolled rounds (~30x faster compiles)
        v, i = jax.lax.sort((v, i), dimension=axis, num_keys=2, is_stable=False)
        me = comm.axis_index()

        def exchange(perm, vv, ii):
            # sort circulates the VALUES being ordered — a lossy wire
            # (HEAT_TPU_COLLECTIVE_PREC) would corrupt them, so pin exact
            ov = comm.ppermute(vv, perm, precision="off")
            oi = comm.ppermute(ii, perm, precision="off")
            mv = jnp.concatenate([vv, ov], axis=axis)
            mi = jnp.concatenate([ii, oi], axis=axis)
            return jax.lax.sort((mv, mi), dimension=axis, num_keys=2, is_stable=False)

        def round_body(r, carry):
            v, i = carry
            b = r % 2
            mv, mi = jax.lax.cond(
                b == 0,
                lambda a: exchange(perms[0], *a),
                lambda a: exchange(perms[1], *a),
                (v, i),
            )
            low_v = jax.lax.slice_in_dim(mv, 0, c, axis=axis)
            high_v = jax.lax.slice_in_dim(mv, c, 2 * c, axis=axis)
            low_i = jax.lax.slice_in_dim(mi, 0, c, axis=axis)
            high_i = jax.lax.slice_in_dim(mi, c, 2 * c, axis=axis)
            is_low = (me % 2 == b) & (me + 1 < p)
            is_high = (me >= 1) & ((me - 1) % 2 == b)
            sel_v = jnp.where(is_low, low_v, high_v)
            sel_i = jnp.where(is_low, low_i, high_i)
            return (
                jnp.where(is_low | is_high, sel_v, v),
                jnp.where(is_low | is_high, sel_i, i),
            )

        return jax.lax.fori_loop(0, p, round_body, (v, i))

    spec = comm.spec(axis, a.ndim)
    # the merge network program is cached per (axis, chunk, rank) layout —
    # repeated sorts of the same shape family dispatch a dict lookup
    # instead of re-tracing the shard_map closure (descending is handled
    # entirely outside the kernel, so both directions share one program)
    smapped = program_cache.cached_program(
        "oddeven_sort", (axis, c, a.ndim),
        lambda: jax.shard_map(
            kernel, mesh=comm.mesh, in_specs=(spec, spec),
            out_specs=(spec, spec),
        ),
        comm=comm,
    )
    vals, idx = smapped(buf, idx0)
    if descending:
        vals = jnp.flip(vals, axis=axis)
        idx = -jnp.flip(idx, axis=axis)
    return vals, idx


def split(x: DNDarray, indices_or_sections, axis: int = 0) -> List[DNDarray]:
    """Split into sub-arrays along axis (reference manipulations.py `split`).
    Off the split axis the pieces slice the physical buffer shard-locally —
    the distribution dim (and its pads) carries straight through."""
    axis = sanitize_axis(x.shape, axis)
    if isinstance(indices_or_sections, (builtins.int, np.integer)):
        indices_or_sections = builtins.int(indices_or_sections)
        if x.shape[axis] % indices_or_sections != 0:
            raise ValueError("array split does not result in an equal division")
        sections = indices_or_sections
    else:
        if isinstance(indices_or_sections, DNDarray):
            indices_or_sections = indices_or_sections.tolist()
        sections = list(indices_or_sections)
    out_split = x.split
    if out_split is not None and axis != out_split:
        pieces = jnp.split(x.larray, sections, axis=axis)
        out = []
        for p in pieces:
            gshape = tuple(
                p.shape[d] if d != out_split else x.shape[out_split]
                for d in range(x.ndim)
            )
            p = _canonical(p, x.comm, out_split)
            out.append(DNDarray(p, gshape, x.dtype, out_split, x.device, x.comm, True))
        return out
    pieces = jnp.split(x._logical(), sections, axis=axis)
    return [_rewrap(p, out_split, x) for p in pieces]


def squeeze(x: DNDarray, axis=None) -> DNDarray:
    """Remove size-1 dimensions (reference `squeeze`)."""
    if axis is not None:
        ax = sanitize_axis(x.shape, axis)
        axes = (ax,) if isinstance(ax, builtins.int) else ax
        for a in axes:
            if x.shape[a] != 1:
                raise ValueError(f"cannot select an axis to squeeze out which has size not equal to one, got axis {a}")
    else:
        axes = tuple(d for d, s in enumerate(x.shape) if s == 1)
    out_split = x.split
    if out_split is not None:
        if out_split in axes:
            out_split = None
        else:
            out_split -= builtins.sum(1 for a in axes if a < out_split)
    if x.split not in axes:
        # squeezed dims are size-1 and never the padded split dim — physical
        res = jnp.squeeze(x.larray, axis=axes)
        gshape = tuple(s for d, s in enumerate(x.shape) if d not in axes)
        return DNDarray(res, gshape, x.dtype, out_split, x.device, x.comm, True)
    # the (size-1) split dim itself is squeezed away: one compiled take of
    # logical position 0 along the padded axis + replication — no host path
    buf = jnp.take(x.larray, jnp.array([0]), axis=x.split)
    res = jnp.squeeze(buf, axis=axes)
    if x.comm.size > 1:
        res = jax.device_put(res, x.comm.replicated())
    gshape = tuple(s for d, s in enumerate(x.shape) if d not in axes)
    return DNDarray(res, gshape, x.dtype, out_split, x.device, x.comm, True)


def stack(arrays: Sequence[DNDarray], axis: int = 0, out=None) -> DNDarray:
    """Join along a new axis (reference `stack`). When every input shares the
    proto's split, inputs have identical physical shapes and the stack runs on
    the physical buffers — pads line up, no relayout."""
    from . import factories

    arrays = [a if isinstance(a, DNDarray) else factories.array(a) for a in arrays]
    if len(arrays) < 1:
        raise ValueError("need at least one array to stack")
    splits = {a.split for a in arrays if a.split is not None}
    if len(splits) > 1:
        raise RuntimeError(
            f"stack inputs are distributed along different axes {sorted(splits)}; "
            "resplit first"
        )
    proto = arrays[0]
    ndim_out = proto.ndim + 1
    ax = axis % ndim_out
    in_split = next(iter(splits), None)
    out_split = in_split
    if out_split is not None and ax <= out_split:
        out_split += 1
    if builtins.all(a.split == in_split and a.shape == proto.shape for a in arrays):
        res = jnp.stack([a.larray for a in arrays], axis=ax)
        gshape = proto.shape[:ax] + (len(arrays),) + proto.shape[ax:]
        result = DNDarray(
            res, gshape, types.canonical_heat_type(res.dtype), out_split,
            proto.device, proto.comm, True,
        )
    else:
        logs = [a._logical() for a in arrays]
        res = jnp.stack(logs, axis=ax)
        result = _rewrap(res, out_split, proto)
    if out is not None:
        out.larray = result.larray
        return out
    return result


def swapaxes(x: DNDarray, axis1: int, axis2: int) -> DNDarray:
    """Interchange two axes (reference `swapaxes`)."""
    from .linalg import transpose

    axis1 = sanitize_axis(x.shape, axis1)
    axis2 = sanitize_axis(x.shape, axis2)
    order = list(range(x.ndim))
    order[axis1], order[axis2] = order[axis2], order[axis1]
    return transpose(x, order)


def tile(x: DNDarray, reps) -> DNDarray:
    """Tile the array (reference `tile`). When the split axis is not
    repeated (its rep factor is 1) the tile runs shard-locally on the
    physical buffer — the distribution dim and its pads are untouched."""
    if isinstance(reps, DNDarray):
        reps = reps.tolist()
    try:
        # operator.index rejects floats (numpy/jnp raise for 2.5 reps) while
        # accepting python and numpy integers
        reps_t = tuple(operator.index(r) for r in reps)
    except TypeError:
        reps_t = (operator.index(reps),)
    if x.split is not None:
        ndim_out = builtins.max(x.ndim, len(reps_t))
        new_split = x.split + (ndim_out - x.ndim)
        reps_full = (1,) * (ndim_out - len(reps_t)) + reps_t
        if reps_full[new_split] == 1:
            res = _canonical(jnp.tile(x.larray, reps_t), x.comm, new_split)
            gshape = tuple(
                r * s
                for r, s in zip(
                    reps_full, (1,) * (ndim_out - x.ndim) + tuple(x.shape)
                )
            )
            return DNDarray(res, gshape, x.dtype, new_split, x.device, x.comm, True)
    res = jnp.tile(x._logical(), reps_t)
    out_split = x.split
    if out_split is not None and res.ndim != x.ndim:
        out_split += res.ndim - x.ndim
    return _rewrap(res, out_split, x)


def _local_topk(buf, k: int, largest: bool):
    """Per-buffer top-k along the last axis → (values, indices), sorted,
    ties by lowest index first."""
    if largest:
        return jax.lax.top_k(buf, k)
    # negation wraps for unsigned/bool dtypes — take the k smallest via a
    # full argsort instead of reusing top_k on -x
    order = jnp.argsort(buf, axis=-1, stable=True)
    idx = order[..., :k]
    return jnp.take_along_axis(buf, idx, axis=-1), idx


def _topk_distributed(a: DNDarray, k: int, dim: int, largest: bool):
    """Two-stage distributed top-k along the split axis: each shard selects
    its local k candidates, an all_gather moves the p·k (value, global
    index) pairs — O(p·k) over ICI instead of gathering the whole O(n)
    axis — and a final select reduces them. Replicated (..., k) results;
    ties break toward the lowest global index on both stages."""
    comm = a.comm
    p = comm.size
    fill = _sort_fill(a, descending=largest)
    buf = jnp.moveaxis(a._masked(fill) if a.pad_count else a.larray, dim, -1)
    chunk = buf.shape[-1] // p
    axis_name = comm.axis_name

    def kernel(loc):
        lv, li = _local_topk(loc, k, largest)
        gi = li + comm.axis_index() * chunk  # global logical positions
        cv = jax.lax.all_gather(lv, axis_name, axis=lv.ndim - 1, tiled=True)
        ci = jax.lax.all_gather(gi, axis_name, axis=gi.ndim - 1, tiled=True)
        # candidates arrive in shard-rank order, so a stable argsort keeps
        # the lowest global index among tied values
        order = jnp.argsort(cv, axis=-1, stable=True, descending=largest)[..., :k]
        return (
            jnp.take_along_axis(cv, order, axis=-1),
            jnp.take_along_axis(ci, order, axis=-1),
        )

    nd = buf.ndim
    # check_vma=False: after the tiled all_gather every shard holds the same
    # candidate set, so the P() outputs ARE replicated — the static checker
    # just cannot infer it through the gather+select
    vals, idx = jax.shard_map(
        kernel, mesh=comm.mesh,
        in_specs=(comm.spec(nd - 1, nd),),
        out_specs=(comm.spec(None, nd), comm.spec(None, nd)),
        check_vma=False,
    )(buf)
    return jnp.moveaxis(vals, -1, dim), jnp.moveaxis(idx, -1, dim)


def topk(a: DNDarray, k: int, dim: int = -1, largest: bool = True, sorted: bool = True, out=None):
    """k largest/smallest elements along dim, returning (values, indices)
    (reference manipulations.py:3856). Masked selection — tail pads can
    never be chosen. Along the split axis on a multi-device mesh this is a
    DISTRIBUTED two-stage select (:func:`_topk_distributed`) moving only
    O(p·k) candidates over ICI."""
    dim = sanitize_axis(a.shape, dim)
    phys = a.larray.shape[dim]
    if (
        a.split == dim
        and a.comm.size > 1
        and k <= phys // a.comm.size  # local stage needs k per shard
    ):
        vals, idx = _topk_distributed(a, k, dim, largest)
    else:
        fill = _sort_fill(a, descending=largest)
        buf = a._masked(fill) if (a.split == dim and a.pad_count) else a.larray
        moved = jnp.moveaxis(buf, dim, -1)
        vals, idx = _local_topk(moved, k, largest)
        vals = jnp.moveaxis(vals, -1, dim)
        idx = jnp.moveaxis(idx, -1, dim)
    if a.split is not None and a.split != dim:
        # physical fast path: the split axis kept its padded layout, so the
        # result is a physical buffer (pad rows hold pad top-k values) — wrap
        # it directly with the logical gshape, as flip/roll do
        out_gshape = tuple(k if d == dim else s for d, s in enumerate(a.shape))
        values = DNDarray(vals, out_gshape, a.dtype, a.split, a.device, a.comm, a.balanced)
        indices = DNDarray(
            idx.astype(jnp.int64), out_gshape, types.int64, a.split, a.device, a.comm, a.balanced
        )
    else:
        values = DNDarray.from_logical(vals, None, a.device, a.comm, a.dtype)
        indices = DNDarray.from_logical(idx.astype(jnp.int64), None, a.device, a.comm, types.int64)
    if out is not None:
        out[0].larray = values.larray
        out[1].larray = indices.larray
        return values, indices
    return values, indices


def _sort_fill(a: DNDarray, descending: bool):
    if issubclass(a.dtype, types.integer):
        info = types.iinfo(a.dtype)
        return info.min if descending else info.max
    if issubclass(a.dtype, types.bool):
        return False if descending else True
    return -float("inf") if descending else float("inf")


def unique(a: DNDarray, sorted: bool = False, return_inverse: bool = False, axis: Optional[int] = None):
    """Unique elements (reference manipulations.py:3077).

    1-D split arrays on a multi-device mesh run the **distributed
    algorithm** (two device programs + one scalar sync for the output
    size): distributed sort (the odd-even merge-split network), a
    `shard_map` boundary-mask pass (each shard compares against its left
    neighbor's last element via `ppermute`, then an all_gather exscan
    assigns every element its global group id), and a scatter+psum
    compaction into the (U,)-sized split=0 result. No host gather of the
    data — only the scalar count U crosses to the host, because output
    *shape* is host-level metadata in this framework.

    n-D inputs with ``axis=None`` relayout once to a flat split=0 vector
    and run the same distributed algorithm (inverses come back
    input-shaped, numpy semantics). ``axis=k`` (row-unique) on split
    arrays is ALSO distributed (:func:`_distributed_unique_rows_nd`):
    lexicographic odd-even row sort → neighbor row-equality mask →
    row compaction — no host gather, no size ceiling. Rows up to
    ``_ROW_UNIQUE_MAX_WIDTH`` real elements sort on the value columns
    directly; wider rows and complex dtypes sort on **packed
    order-preserving uint64 keys** (:func:`_row_sort_keys`: each element
    maps to an order-isomorphic unsigned integer, several narrow keys
    pack per 64-bit lane), which bounds the sort network's operand count
    — ISSUE 6 closed the carried >256-wide and complex edge-case debt
    this way. Only replicated/0-d flows and rows whose PACKED lane count
    still exceeds the cap (e.g. float64 rows wider than 256) keep the
    eager host path (single-controller; bounded by host memory — and,
    like every eager `_logical` flow, it raises on multi-host padded
    arrays rather than mis-computing).
    """
    if (
        axis is None and a.split is not None
        and a.comm.size > 1 and a.size > 0
    ):
        flat = a if a.ndim == 1 else reshape(a, (a.size,))
        if return_inverse:
            vals, inv = _distributed_unique(flat, True)
            if a.ndim > 1:
                inv = reshape(inv, tuple(a.shape))
            return vals, inv
        return _distributed_unique(flat, False)
    if (
        axis is None and a.split is None
        and a.comm.size > 1 and a.size > 0 and a.ndim >= 1
    ):
        # replicated inputs route through the SAME distributed algorithm
        # (VERDICT r5 Missing #3): resplit a flat view to split=0, run the
        # device-side sort → boundary-mask → compaction, and relayout the
        # (U,)-sized result back to replicated — no host jnp.unique, so
        # the path is multi-host safe and the eager raise list shrinks to
        # 0-d flows and the documented axis=k edge cases.
        flat = (a if a.ndim == 1 else reshape(a, (a.size,))).resplit(0)
        if return_inverse:
            vals, inv = _distributed_unique(flat, True)
            vals = vals.resplit(None)
            inv = inv.resplit(None)
            inv = reshape(inv, tuple(a.shape)) if a.ndim > 1 else inv
            return vals, inv
        return _distributed_unique(flat, False).resplit(None)
    if (
        axis is not None and a.split is not None
        and a.comm.size > 1 and a.size > 0
    ):
        ax = sanitize_axis(a.shape, axis)
        if a.ndim == 1 and _row_unique_mode(a.dtype, 1) is not None:
            # 1-D axis=0 runs the ROWS path on (n, 1) so it gets numpy's
            # axis semantics (NaN entries stay distinct — the flat path's
            # equal_nan collapse would diverge from the axis oracle)
            b2 = reshape(a, (a.shape[0], 1))
            out = _distributed_unique_rows_nd(b2, 0, return_inverse)
            if return_inverse:
                res, inv = out
                return reshape(res, (res.shape[0],)), inv
            return reshape(out, (out.shape[0],))
        if (
            a.ndim > 1
            and _row_unique_mode(a.dtype, a.size // a.shape[ax]) is not None
        ):
            return _distributed_unique_rows_nd(a, ax, return_inverse)
    log = a._logical()
    if axis is not None and jnp.issubdtype(log.dtype, jnp.inexact):
        # numpy, not jnp: np.unique(axis=k) compares rows with elementwise
        # == where NaN != NaN, so NaN-carrying duplicate rows stay
        # DISTINCT — the oracle the distributed rows path implements.
        # jnp.unique collapses them (structural NaN equality), which
        # diverged on single-device meshes. Only inexact dtypes can carry
        # NaN, and this is the eager host fallback already, so the host
        # round trip costs nothing new where it applies.
        ax = sanitize_axis(a.shape, axis)
        host = np.asarray(log)
        if return_inverse:
            res, inverse = np.unique(host, return_inverse=True, axis=ax)
            res_ht = _rewrap(
                jnp.asarray(res), 0 if a.split is not None else None, a
            )
            return res_ht, _rewrap(jnp.asarray(inverse), None, a)
        res = np.unique(host, axis=ax)
        return _rewrap(
            jnp.asarray(res), 0 if a.split is not None else None, a
        )
    if axis is not None:
        axis = sanitize_axis(a.shape, axis)
    if return_inverse:
        res, inverse = jnp.unique(log, return_inverse=True, axis=axis)
        res_ht = _rewrap(res, 0 if a.split is not None else None, a)
        # keep the inverse's layout consistent with the distributed path:
        # 1-D split input -> split inverse
        inv_split = 0 if (a.split is not None and a.ndim == 1 and axis is None) else None
        inv_ht = _rewrap(inverse, inv_split, a)
        return res_ht, inv_ht
    res = jnp.unique(log, axis=axis)
    return _rewrap(res, 0 if a.split is not None else None, a)


def _distributed_unique(a: DNDarray, return_inverse: bool):
    """Distributed unique of a 1-D split array — see :func:`unique`.

    Cost: one distributed sort (p ppermute rounds), one mask pass, and a
    scatter+psum whose per-device memory is O(U_pad) for the values (and
    O(N_pad) for the inverse) — the same order as the reference's
    Allgather-based resolution, but staying on-device end to end.
    """
    comm = a.comm
    p = comm.size
    n = a.shape[0]
    axis_name = comm.axis_name
    spec = comm.spec(0, 1)

    values, indices = sort(a)  # ascending; pads carry original tail indices
    vbuf = values.larray
    ibuf = indices.larray  # int64 (sort's contract; iota itself caps at 2^31)
    n_pad = vbuf.shape[0]
    c = n_pad // p
    inexact = jnp.issubdtype(vbuf.dtype, jnp.inexact)

    def mask_kernel(v, oi):
        rank = comm.axis_index()
        # left neighbor's last element, one ppermute hop
        prev_last = jax.lax.ppermute(
            v[-1:], axis_name, [(i, (i + 1) % p) for i in range(p)]
        )
        left = jnp.concatenate([prev_last, v[:-1]])
        isf = v != left
        if inexact:
            # numpy's equal_nan default: all NaNs collapse to one unique
            # (NaN != NaN would otherwise count each as a fresh group)
            isf = isf & ~(jnp.isnan(v) & jnp.isnan(left))
        isf = isf.at[0].set(jnp.where(rank == 0, True, isf[0]))
        # a pad's ORIGINAL index is its physical tail position >= n — robust
        # even for float inputs whose NaNs sort past the +inf pad fill
        isf = isf & (oi < n)
        local_cum = jnp.cumsum(isf.astype(jnp.int64))
        # exscan of per-shard first-counts → global group ids: gid[i] is
        # (#firsts at sorted positions <= i) - 1, valid for EVERY element
        totals = jax.lax.all_gather(local_cum[-1], axis_name)
        before = jnp.where(
            jnp.arange(p, dtype=jnp.int64) < rank, totals, 0
        ).sum()
        gid = before + local_cum - 1
        return isf, gid

    isf_buf, gid_buf = jax.shard_map(
        mask_kernel, mesh=comm.mesh, in_specs=(spec, spec),
        out_specs=(spec, spec),
    )(vbuf, ibuf)

    u = builtins.int(jnp.sum(isf_buf))  # the one host sync: the output SIZE
    cu = comm.chunk_size(u)  # u >= 1: the dispatch guard requires n > 0
    u_pad = cu * p
    # psum promotes bool to int — scatter in int32 and cast back after
    scatter_dt = jnp.int32 if vbuf.dtype == jnp.bool_ else vbuf.dtype

    def compact_kernel(v, isf, gid):
        rank = comm.axis_index()
        tgt = jnp.where(isf, gid, u_pad)  # non-firsts → out of range → drop
        contrib = jnp.zeros((u_pad,), scatter_dt).at[tgt].set(
            v.astype(scatter_dt), mode="drop"
        )
        full = jax.lax.psum(contrib, axis_name)  # each slot written once
        return jax.lax.dynamic_slice_in_dim(full, rank * cu, cu).astype(v.dtype)

    out_buf = jax.shard_map(
        compact_kernel, mesh=comm.mesh, in_specs=(spec, spec, spec),
        out_specs=spec,
    )(vbuf, isf_buf, gid_buf)
    res_ht = DNDarray(out_buf, (u,), a.dtype, 0, a.device, a.comm, True)
    if not return_inverse:
        return res_ht

    def inverse_kernel(orig_idx, gid):
        rank = comm.axis_index()
        tgt = jnp.where(orig_idx < n, orig_idx, n_pad)  # sorted pads dropped
        contrib = jnp.zeros((n_pad,), jnp.int64).at[tgt].set(gid, mode="drop")
        full = jax.lax.psum(contrib, axis_name)
        return jax.lax.dynamic_slice_in_dim(full, rank * c, c)

    inv_buf = jax.shard_map(
        inverse_kernel, mesh=comm.mesh, in_specs=(spec, spec), out_specs=spec
    )(ibuf, gid_buf)
    inv_ht = DNDarray(inv_buf, (n,), types.int64, 0, a.device, a.comm, True)
    return res_ht, inv_ht


# Widest row (in sort OPERANDS) the distributed row-unique network takes
# on: the lexicographic merge sorts its operands jointly per round, so
# compile time grows with the operand count. Narrow real rows use one
# operand per column; wider rows and complex dtypes first pack each
# element into an order-preserving unsigned key and fuse several keys per
# uint64 lane (_row_sort_keys), so e.g. float32 rows stay distributed up
# to 2*256 columns and int8 rows up to 8*256. Only rows whose packed lane
# count still exceeds the cap keep the eager path (bounded by host
# memory, not by a correctness cap).
_ROW_UNIQUE_MAX_WIDTH = 256


def _row_unique_mode(ht_dtype, width: int):
    """How the distributed row-unique handles rows of ``width`` elements:
    ``"direct"`` (value columns as sort operands — the historical path),
    ``"packed"`` (order-preserving uint64 key lanes), or None (eager
    fallback: packed lane count would still exceed the cap)."""
    is_complex = issubclass(ht_dtype, types.complexfloating)
    if not is_complex and width <= _ROW_UNIQUE_MAX_WIDTH:
        return "direct"
    comp_bytes = ht_dtype.byte_size() // (2 if is_complex else 1)
    comps = width * (2 if is_complex else 1)
    per_lane = max(1, 8 // comp_bytes)
    lanes = -(-comps // per_lane)
    return "packed" if lanes <= _ROW_UNIQUE_MAX_WIDTH else None


def _elem_sort_key(col: jax.Array) -> jax.Array:
    """Map one element column to an UNSIGNED integer of the same bit
    width whose ``<`` order equals the value order (the classic radix
    bijection), with ``-0.0`` canonicalized onto ``+0.0`` so rows equal
    under ``==`` get identical keys. NaNs map to keys above +inf —
    row-unique keeps NaN rows distinct anyway (plain ``!=`` in the mask
    phase), the keys only need to keep bitwise-equal rows adjacent."""
    dt = col.dtype
    if dt == jnp.bool_:
        return col.astype(jnp.uint8)
    if jnp.issubdtype(dt, jnp.unsignedinteger):
        return col
    nbits = dt.itemsize * 8
    udt = jnp.dtype(f"uint{nbits}")
    if jnp.issubdtype(dt, jnp.signedinteger):
        return jax.lax.bitcast_convert_type(col, udt) ^ jnp.array(
            1 << (nbits - 1), udt
        )
    # floats (incl. bfloat16): +0.0 canonicalization, then sign-fold
    col = col + jnp.zeros((), dt)
    b = jax.lax.bitcast_convert_type(col, udt)
    top = jnp.array(1 << (nbits - 1), udt)
    return jnp.where((b & top) != 0, ~b, b | top)


def _row_sort_keys(buf: jax.Array) -> jax.Array:
    """Pack an ``(n, R)`` row buffer into ``(n, K)`` uint64 sort-key
    lanes whose joint lexicographic order refines the rows' elementwise
    lexicographic order (complex columns contribute (real, imag) key
    pairs — numpy's complex sort order). ``K = ceil(R·comp_bytes / 8)``,
    which is what bounds the sort network's operand count for wide
    rows."""
    if jnp.issubdtype(buf.dtype, jnp.complexfloating):
        parts = jnp.stack([buf.real, buf.imag], axis=-1)
        buf = parts.reshape(buf.shape[0], -1)
    n, comps = buf.shape
    keys = _elem_sort_key(buf)  # (n, comps) unsigned
    nbytes = keys.dtype.itemsize
    per_lane = max(1, 8 // nbytes)
    lanes = -(-comps // per_lane)
    if per_lane == 1:
        return keys.astype(jnp.uint64)
    pad = lanes * per_lane - comps
    if pad:
        keys = jnp.pad(keys, ((0, 0), (0, pad)))  # zero keys: order-neutral
    keys = keys.astype(jnp.uint64).reshape(n, lanes, per_lane)
    shifts = jnp.arange(per_lane - 1, -1, -1, dtype=jnp.uint64) * (8 * nbytes)
    return jnp.sum(keys << shifts, axis=-1)


def _distributed_unique_rows_nd(a: DNDarray, axis: int, return_inverse: bool):
    """Distributed ``unique(a, axis=k)`` — unique subarrays along ``axis``
    (reference manipulations.py:3077 resolves this with Alltoallv; here it
    is three device programs + one scalar sync, the same shape as the 1-D
    distributed unique):

    1. canonicalize: resplit to ``split == axis`` if needed, move the axis
       to the front (shard-local transpose), flatten trailing dims — a
       zero-comm trailing reshape — giving (n, R) rows split=0;
    2. :func:`_distributed_unique_rows` (lexicographic odd-even row sort →
       neighbor row-equality mask → scatter+psum row compaction);
    3. reshape/moveaxis the (U, R) result back around the original axis.
    """
    b = a if a.split == axis else resplit(a, axis)
    if axis != 0:
        b = moveaxis(b, axis, 0)
    n = b.shape[0]
    rest = b.shape[1:]
    b2 = b if b.ndim == 2 else reshape(b, (n, builtins.int(np.prod(rest))))
    if _row_unique_mode(a.dtype, b2.shape[1]) == "packed":
        vals2, inv = _distributed_unique_rows_packed(b2, return_inverse)
    else:
        vals2, inv = _distributed_unique_rows(b2, return_inverse)
    u = vals2.shape[0]
    res = vals2 if len(rest) == 1 else reshape(vals2, (u,) + rest)
    if axis != 0:
        res = moveaxis(res, 0, axis)
    if return_inverse:
        return res, inv
    return res


def _distributed_unique_rows(a: DNDarray, return_inverse: bool):
    """Distributed unique of the rows of an (n, R) split=0 array.

    The 1-D design (:func:`_distributed_unique`) generalized to rows: the
    odd-even merge network sorts LEXICOGRAPHICALLY by the R columns plus the
    global row index (``lax.sort`` takes them as R+1 key operands, so every
    shape stays static), the boundary mask compares full neighbor rows with
    plain ``!=`` (numpy's axis semantics keep NaN rows DISTINCT — unlike
    the flat path's equal_nan collapse), and the compaction scatters whole
    rows.
    Only the scalar U reaches the host. Cost: p merge rounds x chunk rows,
    then one O(U_pad * R) psum.
    """
    comm = a.comm
    p = comm.size
    n, R = a.shape
    axis_name = comm.axis_name
    spec2 = comm.spec(0, 2)
    spec1 = comm.spec(0, 1)

    fill = _sort_fill(a, False)
    buf = a._masked(fill) if a.pad_count else a.larray
    n_pad = buf.shape[0]
    c = n_pad // p
    idx0 = jax.lax.broadcasted_iota(jnp.int32, (n_pad,), 0)
    perms = _oddeven_partner_perms(p)

    def lexsort_block(vv, ii):
        ops = tuple(vv[:, j] for j in range(R)) + (ii,)
        out = jax.lax.sort(ops, dimension=0, num_keys=R + 1)
        return jnp.stack(out[:R], axis=1), out[R]

    def sort_kernel(v, i):
        me = comm.axis_index()
        v, i = lexsort_block(v, i)

        def exchange(perm, vv, ii):
            # exact-value circulation (see the sort-network note above)
            ov = comm.ppermute(vv, perm, precision="off")
            oi = comm.ppermute(ii, perm, precision="off")
            return lexsort_block(
                jnp.concatenate([vv, ov], axis=0),
                jnp.concatenate([ii, oi], axis=0),
            )

        def round_body(r, carry):
            v, i = carry
            b = r % 2
            mv, mi = jax.lax.cond(
                b == 0,
                lambda t: exchange(perms[0], *t),
                lambda t: exchange(perms[1], *t),
                (v, i),
            )
            is_low = (me % 2 == b) & (me + 1 < p)
            is_high = (me >= 1) & ((me - 1) % 2 == b)
            sel_v = jnp.where(is_low, mv[:c], mv[c : 2 * c])
            sel_i = jnp.where(is_low, mi[:c], mi[c : 2 * c])
            return (
                jnp.where(is_low | is_high, sel_v, v),
                jnp.where(is_low | is_high, sel_i, i),
            )

        return jax.lax.fori_loop(0, p, round_body, (v, i))

    vbuf, ibuf = jax.shard_map(
        sort_kernel, mesh=comm.mesh, in_specs=(spec2, spec1),
        out_specs=(spec2, spec1),
    )(buf, idx0)
    return _rows_mask_compact(a, vbuf, ibuf, return_inverse)


def _distributed_unique_rows_packed(a: DNDarray, return_inverse: bool):
    """The wide-row / complex variant of :func:`_distributed_unique_rows`
    (ISSUE 6 carried-debt fix): the odd-even merge network sorts PACKED
    order-preserving uint64 key lanes (:func:`_row_sort_keys`) plus the
    global row index instead of one operand per column — the operand
    count is ``ceil(R·comp_bytes/8) + 1`` however wide the rows get —
    and the sorted VALUE rows are then materialized with one global
    gather by the sorted original indices. Mask/compaction/inverse are
    shared with the direct path (plain ``!=`` on the value rows, so NaN
    rows stay distinct exactly as numpy's axis-unique keeps them)."""
    comm = a.comm
    p = comm.size
    n = a.shape[0]
    spec1 = comm.spec(0, 1)
    spec2 = comm.spec(0, 2)

    fill = _sort_fill(a, False)
    buf = a._masked(fill) if a.pad_count else a.larray
    n_pad = buf.shape[0]
    c = n_pad // p
    keys = _row_sort_keys(buf)  # (n_pad, K) uint64
    K = keys.shape[1]
    idx0 = jax.lax.broadcasted_iota(jnp.int32, (n_pad,), 0)
    perms = _oddeven_partner_perms(p)

    def lexsort_block(kk, ii):
        ops = tuple(kk[:, j] for j in range(K)) + (ii,)
        out = jax.lax.sort(ops, dimension=0, num_keys=K + 1)
        return jnp.stack(out[:K], axis=1), out[K]

    def sort_kernel(k, i):
        me = comm.axis_index()
        k, i = lexsort_block(k, i)

        def exchange(perm, kk, ii):
            # exact-value circulation (see the sort-network note above)
            ov = comm.ppermute(kk, perm, precision="off")
            oi = comm.ppermute(ii, perm, precision="off")
            return lexsort_block(
                jnp.concatenate([kk, ov], axis=0),
                jnp.concatenate([ii, oi], axis=0),
            )

        def round_body(r, carry):
            k, i = carry
            b = r % 2
            mk, mi = jax.lax.cond(
                b == 0,
                lambda t: exchange(perms[0], *t),
                lambda t: exchange(perms[1], *t),
                (k, i),
            )
            is_low = (me % 2 == b) & (me + 1 < p)
            is_high = (me >= 1) & ((me - 1) % 2 == b)
            sel_k = jnp.where(is_low, mk[:c], mk[c : 2 * c])
            sel_i = jnp.where(is_low, mi[:c], mi[c : 2 * c])
            return (
                jnp.where(is_low | is_high, sel_k, k),
                jnp.where(is_low | is_high, sel_i, i),
            )

        return jax.lax.fori_loop(0, p, round_body, (k, i))

    _, ibuf = jax.shard_map(
        sort_kernel, mesh=comm.mesh, in_specs=(spec2, spec1),
        out_specs=(spec2, spec1),
    )(keys, idx0)
    # one global gather lands the sorted VALUE rows (the keys only fix
    # the order); canonical split=0 for the shared mask/compaction half
    vbuf = jax.device_put(
        jnp.take(buf, ibuf, axis=0), comm.sharding(0, 2)
    )
    return _rows_mask_compact(a, vbuf, ibuf, return_inverse)


def _rows_mask_compact(a: DNDarray, vbuf, ibuf, return_inverse: bool):
    """Shared tail of the distributed row-unique paths: neighbor
    row-equality mask over the SORTED rows → exscan group ids →
    scatter+psum row compaction (+ optional inverse). ``vbuf`` are the
    sorted (pad-filled) value rows, ``ibuf`` their original global
    indices."""
    comm = a.comm
    p = comm.size
    n, R = a.shape
    axis_name = comm.axis_name
    spec1 = comm.spec(0, 1)
    spec2 = comm.spec(0, 2)
    n_pad = vbuf.shape[0]
    c = n_pad // p

    def mask_kernel(v, oi):
        rank = comm.axis_index()
        prev_last = jax.lax.ppermute(
            v[-1:], axis_name, [(i, (i + 1) % p) for i in range(p)]
        )
        left = jnp.concatenate([prev_last, v[:-1]], axis=0)
        # numpy's axis-unique keeps NaN rows DISTINCT (unlike the flat
        # path's equal_nan collapse) — plain != matches that: NaN != NaN
        # makes every NaN-bearing row a fresh group
        neq = v != left
        isf = jnp.any(neq, axis=1)
        isf = isf.at[0].set(jnp.where(rank == 0, True, isf[0]))
        isf = isf & (oi < n)  # sorted pad rows carry tail iota >= n
        local_cum = jnp.cumsum(isf.astype(jnp.int64))
        totals = jax.lax.all_gather(local_cum[-1], axis_name)
        before = jnp.where(
            jnp.arange(p, dtype=jnp.int64) < rank, totals, 0
        ).sum()
        gid = before + local_cum - 1
        return isf, gid

    isf_buf, gid_buf = jax.shard_map(
        mask_kernel, mesh=comm.mesh, in_specs=(spec2, spec1),
        out_specs=(spec1, spec1),
    )(vbuf, ibuf)

    u = builtins.int(jnp.sum(isf_buf))  # the one host sync: the output SIZE
    cu = comm.chunk_size(u)
    u_pad = cu * p
    scatter_dt = jnp.int32 if vbuf.dtype == jnp.bool_ else vbuf.dtype

    def compact_kernel(v, isf, gid):
        rank = comm.axis_index()
        tgt = jnp.where(isf, gid, u_pad)
        contrib = jnp.zeros((u_pad, R), scatter_dt).at[tgt].set(
            v.astype(scatter_dt), mode="drop"
        )
        full = jax.lax.psum(contrib, axis_name)
        return jax.lax.dynamic_slice_in_dim(full, rank * cu, cu, axis=0).astype(v.dtype)

    out_buf = jax.shard_map(
        compact_kernel, mesh=comm.mesh, in_specs=(spec2, spec1, spec1),
        out_specs=spec2,
    )(vbuf, isf_buf, gid_buf)
    res_ht = DNDarray(out_buf, (u, R), a.dtype, 0, a.device, a.comm, True)
    if not return_inverse:
        return res_ht, None

    def inverse_kernel(orig_idx, gid):
        rank = comm.axis_index()
        tgt = jnp.where(orig_idx < n, orig_idx, n_pad)
        contrib = jnp.zeros((n_pad,), jnp.int64).at[tgt].set(gid, mode="drop")
        full = jax.lax.psum(contrib, axis_name)
        return jax.lax.dynamic_slice_in_dim(full, rank * c, c)

    inv_buf = jax.shard_map(
        inverse_kernel, mesh=comm.mesh, in_specs=(spec1, spec1), out_specs=spec1
    )(ibuf.astype(jnp.int64), gid_buf)
    inv_ht = DNDarray(inv_buf, (n,), types.int64, 0, a.device, a.comm, True)
    return res_ht, inv_ht


def vsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 0 (reference `vsplit`)."""
    return split(x, indices_or_sections, axis=0)


DNDarray.expand_dims = lambda self, axis: expand_dims(self, axis)
DNDarray.flatten = lambda self: flatten(self)
DNDarray.ravel = lambda self: ravel(self)
DNDarray.reshape = lambda self, *shape, new_split=None: reshape(self, *shape, new_split=new_split)
DNDarray.resplit = lambda self, axis=None, audit=False, precision=None: resplit(
    self, axis, audit=audit, precision=precision
)
DNDarray.squeeze = lambda self, axis=None: squeeze(self, axis)
DNDarray.unique = lambda self, sorted=False, return_inverse=False, axis=None: unique(
    self, sorted, return_inverse, axis
)
