"""The heat_tpu dtype hierarchy.

Re-design of the reference type system (reference: heat/core/types.py:64-1056 —
class hierarchy ``datatype → bool/number→integer/floating/complex``, each
backed by a torch dtype, plus `canonical_heat_type`, `heat_type_of`,
`promote_types`, `result_type`, `can_cast`, `finfo`, `iinfo`). Differences by
design:

* every class is backed by a **numpy/jax dtype** instead of a torch dtype
  (``jnp_type()`` replaces the reference's ``torch_type()``);
* ``bfloat16`` and ``float16`` are first-class public types — the TPU-native
  extension the reference could not offer (it smuggles bf16 through MPI INT16
  buffers only inside DASO, reference communication.py:130-143);
* promotion delegates to jnp/numpy promotion (with x64 enabled this matches
  numpy semantics exactly), instead of a hand-maintained table.

Instantiating a type *casts*: ``ht.float32(x)`` returns a DNDarray, matching
reference types.py:85 (``datatype.__new__``).
"""

from __future__ import annotations

import builtins
from typing import Any, Iterator, Type, Union

import numpy as np
import jax.numpy as jnp

__all__ = [
    "datatype",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "bool",
    "bool_",
    "floating",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int64",
    "long",
    "uint8",
    "ubyte",
    "uint16",
    "uint32",
    "uint64",
    "float16",
    "half",
    "bfloat16",
    "float32",
    "float",
    "float_",
    "float64",
    "double",
    "flexible",
    "complex64",
    "cfloat",
    "csingle",
    "complex128",
    "cdouble",
    "can_cast",
    "canonical_heat_type",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "heat_type_is_complexfloating",
    "iscomplex",
    "isreal",
    "issubdtype",
    "heat_type_of",
    "promote_types",
    "result_type",
    "finfo",
    "iinfo",
]

_bfloat16_np = jnp.bfloat16  # ml_dtypes-backed numpy scalar type


class datatype:
    """Generic data type; the root of the hierarchy (reference types.py:64)."""

    _np: Any = None  # numpy scalar type backing this heat type

    def __new__(cls, *value, device=None, comm=None):
        # instantiating a type casts (reference types.py:85-130)
        from . import factories

        if cls._np is None:
            raise TypeError(f"cannot instantiate abstract type {cls.__name__}")
        if len(value) == 0:
            value = ((0,),)
        if len(value) == 1:
            value = value[0]
        return factories.array(value, dtype=cls, device=device, comm=comm, copy=None)

    @classmethod
    def jnp_type(cls) -> np.dtype:
        """The jax/numpy dtype backing this heat type (the reference's
        ``torch_type()`` analog, types.py:67)."""
        if cls._np is None:
            raise TypeError(f"abstract type {cls.__name__} has no jnp equivalent")
        return np.dtype(cls._np)

    @classmethod
    def char(cls) -> str:
        """Single-character dtype code (reference types.py:76)."""
        return np.dtype(cls._np).char

    @classmethod
    def byte_size(cls) -> builtins.int:
        return np.dtype(cls._np).itemsize


class bool(datatype):
    """Boolean (True/False)."""

    _np = np.bool_


bool_ = bool


class number(datatype):
    pass


class integer(number):
    pass


class signedinteger(integer):
    pass


class unsignedinteger(integer):
    pass


class floating(number):
    pass


class flexible(datatype):
    pass


class complexfloating(number):
    pass


class int8(signedinteger):
    _np = np.int8


class int16(signedinteger):
    _np = np.int16


class int32(signedinteger):
    _np = np.int32


class int64(signedinteger):
    _np = np.int64


class uint8(unsignedinteger):
    _np = np.uint8


class uint16(unsignedinteger):
    _np = np.uint16


class uint32(unsignedinteger):
    _np = np.uint32


class uint64(unsignedinteger):
    _np = np.uint64


class float16(floating):
    _np = np.float16


class bfloat16(floating):
    """Brain float — native on the TPU MXU; public-type extension over the
    reference (which has no public bf16, types.py has none)."""

    _np = _bfloat16_np


class float32(floating):
    _np = np.float32


class float64(floating):
    _np = np.float64


class complex64(complexfloating):
    _np = np.complex64


class complex128(complexfloating):
    _np = np.complex128


# short-hand aliases (reference types.py exports the same names)
byte = int8
short = int16
int = int32
long = int64
ubyte = uint8
half = float16
float = float32
float_ = float32
double = float64
cfloat = complex64
csingle = complex64
cdouble = complex128

_COMPLETE_TYPES = [
    bool,
    int8,
    int16,
    int32,
    int64,
    uint8,
    uint16,
    uint32,
    uint64,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
]

# numpy char → heat type
_CHAR_MAP = {np.dtype(t._np).name: t for t in _COMPLETE_TYPES}
# python builtins / strings
_ALIAS_MAP = {
    builtins.bool: bool,
    builtins.int: int64,
    builtins.float: float32,
    builtins.complex: complex64,
    "bool": bool,
    "b": int8,
    "h": int16,
    "i": int32,
    "l": int64,
    "B": uint8,
    "f": float32,
    "d": float64,
}


def canonical_heat_type(a_type: Any) -> Type[datatype]:
    """Canonicalize a heat type / numpy dtype / python type / string into the
    corresponding heat type class (reference types.py:495)."""
    if isinstance(a_type, type) and issubclass(a_type, datatype):
        return a_type
    try:
        if a_type in _ALIAS_MAP:
            return _ALIAS_MAP[a_type]
    except TypeError:
        pass
    try:
        name = np.dtype(a_type).name
    except TypeError:
        raise TypeError(f"data type {a_type!r} not understood") from None
    if name in _CHAR_MAP:
        return _CHAR_MAP[name]
    raise TypeError(f"data type {a_type!r} not understood")


def heat_type_of(obj: Any) -> Type[datatype]:
    """The heat type of an arbitrary object's elements (reference
    types.py:565)."""
    from .dndarray import DNDarray

    if isinstance(obj, DNDarray):
        return obj.dtype
    if isinstance(obj, (jnp.ndarray, np.ndarray)) or hasattr(obj, "dtype"):
        return canonical_heat_type(obj.dtype)
    if isinstance(obj, (builtins.bool, np.bool_)):
        return bool
    if isinstance(obj, builtins.int):
        return int64
    if isinstance(obj, builtins.float):
        return float32
    if isinstance(obj, builtins.complex):
        return complex64
    if isinstance(obj, (list, tuple)):
        return canonical_heat_type(np.asarray(obj).dtype)
    try:
        return canonical_heat_type(np.asarray(obj).dtype)
    except Exception:
        raise TypeError(f"data type of {obj!r} not understood") from None


def heat_type_is_exact(ht_dtype: Any) -> builtins.bool:
    """True if the type is an integer-exact type (reference types.py)."""
    try:
        t = canonical_heat_type(ht_dtype)
    except TypeError:
        return False
    return issubclass(t, (integer, bool))


def heat_type_is_inexact(ht_dtype: Any) -> builtins.bool:
    try:
        t = canonical_heat_type(ht_dtype)
    except TypeError:
        return False
    return issubclass(t, (floating, complexfloating))


def heat_type_is_complexfloating(ht_dtype: Any) -> builtins.bool:
    try:
        t = canonical_heat_type(ht_dtype)
    except TypeError:
        return False
    return issubclass(t, complexfloating)


def issubdtype(arg1: Any, arg2: Any) -> builtins.bool:
    """numpy-style abstract dtype lattice check (reference types.py)."""
    abstract = {
        number: (integer, floating, complexfloating),
        integer: (signedinteger, unsignedinteger),
    }

    def _resolve(a):
        if isinstance(a, type) and issubclass(a, datatype):
            return a
        return canonical_heat_type(a)

    t1 = _resolve(arg1)
    t2 = _resolve(arg2)
    return issubclass(t1, t2)


def promote_types(type1: Any, type2: Any) -> Type[datatype]:
    """Smallest type to which both may be safely cast (reference
    types.py:836). Delegates to jnp promotion (numpy semantics under x64)."""
    t1 = canonical_heat_type(type1)
    t2 = canonical_heat_type(type2)
    return canonical_heat_type(jnp.promote_types(t1.jnp_type(), t2.jnp_type()))


def result_type(*args: Any) -> Type[datatype]:
    """Result heat type of an operation on the given operands (reference
    types.py:868)."""
    from .dndarray import DNDarray

    conv = []
    for a in args:
        if isinstance(a, DNDarray):
            conv.append(a.dtype.jnp_type())
        elif isinstance(a, type) and issubclass(a, datatype):
            conv.append(a.jnp_type())
        elif isinstance(a, (builtins.int, builtins.float, builtins.complex, builtins.bool)):
            conv.append(a)
        else:
            try:
                conv.append(np.dtype(a))
            except TypeError:
                conv.append(np.asarray(a).dtype)
    return canonical_heat_type(jnp.result_type(*conv))


def can_cast(from_: Any, to: Any, casting: str = "intuitive") -> builtins.bool:
    """Whether a cast is possible under the given rule (reference
    types.py:671). Casting rules: 'no', 'safe', 'same_kind', 'unsafe', and
    the reference's default 'intuitive' (safe + int→float + bool→any)."""
    try:
        frm = canonical_heat_type(from_) if not np.isscalar(from_) else None
    except TypeError:
        frm = None
    if frm is None:
        try:
            frm = heat_type_of(from_)
        except TypeError:
            raise TypeError(f"cannot cast from {from_!r}") from None
    to_t = canonical_heat_type(to)
    f_np, t_np = frm.jnp_type(), to_t.jnp_type()
    if casting == "intuitive":
        if f_np == t_np:
            return True
        if issubclass(frm, bool):
            return True
        if issubclass(frm, integer) and issubclass(to_t, (integer, floating, complexfloating)):
            return True
        return np.can_cast(f_np, t_np, casting="safe")
    if casting not in ("no", "safe", "same_kind", "unsafe"):
        raise ValueError(
            f"casting must be one of 'no', 'safe', 'same_kind', 'unsafe', 'intuitive', got {casting!r}"
        )
    try:
        return np.can_cast(f_np, t_np, casting=casting)
    except TypeError:
        # bfloat16 vs numpy casting table — fall back to promotion check
        if casting == "unsafe":
            return True
        return jnp.promote_types(f_np, t_np) == t_np


def iscomplex(x):
    """Elementwise test for non-zero imaginary part (reference types.py).
    Composed from fusable framework ops (``imag`` then ``!= 0``) instead
    of a lambda, so it joins pending fused chains (PR 4 left this as a
    per-call fallback)."""
    from . import complex_math, factories, relational
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        x = factories.array(x)
    if issubclass(x.dtype, complexfloating):
        return relational.ne(complex_math.imag(x), 0)
    return factories.zeros(x.shape, dtype=bool, split=x.split, device=x.device, comm=x.comm)


def isreal(x):
    """Elementwise test for zero imaginary part (reference types.py); see
    :func:`iscomplex` for the fusable composition."""
    from . import complex_math, factories, relational
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        x = factories.array(x)
    if issubclass(x.dtype, complexfloating):
        return relational.eq(complex_math.imag(x), 0)
    return factories.ones(x.shape, dtype=bool, split=x.split, device=x.device, comm=x.comm)


class finfo:
    """Machine limits for floating point types (reference types.py:950)."""

    def __new__(cls, dtype):
        t = canonical_heat_type(dtype)
        if not issubclass(t, (floating, complexfloating)):
            raise TypeError(f"data type {t!r} not inexact")
        info = jnp.finfo(t.jnp_type())
        self = object.__new__(cls)
        self.bits = info.bits
        self.eps = builtins.float(info.eps)
        self.max = builtins.float(info.max)
        self.min = builtins.float(info.min)
        self.tiny = builtins.float(info.tiny)
        return self


class iinfo:
    """Machine limits for integer types (reference types.py:1007)."""

    def __new__(cls, dtype):
        t = canonical_heat_type(dtype)
        if issubclass(t, bool):
            raise TypeError("data type bool not an integer")
        if not issubclass(t, integer):
            raise TypeError(f"data type {t!r} not an integer")
        info = np.iinfo(t.jnp_type())
        self = object.__new__(cls)
        self.bits = info.bits
        self.max = builtins.int(info.max)
        self.min = builtins.int(info.min)
        return self
