"""First-class ragged (rank-proportional) layouts — the sanctioned
substitute for the reference's ``redistribute_(target_map)``.

The reference framework lets MPI rank ``r`` own an arbitrary number of
split-dim rows because Alltoallv makes ragged shards first-class. The XLA
layout model admits exactly ONE physical layout per ``(gshape, split,
mesh)`` — equal ceil-rule shards with a tail pad — so that design point is
formally closed here (PARITY.md, "redistribute_ and ragged target maps"
and ``DNDarray.redistribute_``). What the reference actually *uses* ragged
maps for survives, as this module's :class:`Ragged`:

* the data stays on the **canonical** layout (one compiled-program family,
  every op works unchanged);
* the ragged intent — "position ``i`` owns ``counts[i]`` rows" — is
  carried as metadata: an ``owner`` map plus per-position masks/blocks
  that ride the same sharding as the data, so "position i's work" is a
  mask multiply, not a ragged shard;
* **redistribution of the intent is free**: :meth:`Ragged.redistribute`
  rewrites ``counts`` without moving a byte (the reference's
  ``redistribute_`` moves the whole array through Alltoallv for the same
  outcome);
* **redistribution of the layout** (changing the split axis) goes through
  the canonical :meth:`DNDarray.resplit` — which, since ISSUE 6, is
  planner-managed: near the HBM ceiling the communication-aware relayout
  planner (:mod:`heat_tpu.core.relayout_planner`) decomposes the move
  into a bounded-memory chunked program chain instead of raising, so a
  ragged workload can change layout at sizes where the monolithic
  relayout cannot.

This promotes the ``examples/ragged_layout.py`` demo (the PR-3-era
substitute) to API: :func:`ragged` builds a :class:`Ragged` from
per-position blocks (the reference's construction) or from data plus an
explicit ``counts`` vector.
"""

from __future__ import annotations

import builtins
from typing import Optional, Sequence

import numpy as np

from .dndarray import DNDarray

__all__ = ["Ragged", "ragged"]


class Ragged:
    """A canonical-layout array carrying a ragged ownership intent.

    ``counts[i]`` is the number of logical positions along ``axis`` that
    mesh position ``i`` owns *logically* — the physical shards stay the
    canonical ceil-rule chunks. See the module docstring for why this is
    the TPU-native form of a ragged layout.
    """

    def __init__(self, array: DNDarray, counts: Sequence[int], axis: int = 0):
        if not isinstance(array, DNDarray):
            raise TypeError(f"array must be a DNDarray, got {type(array)}")
        counts = np.asarray(counts, dtype=np.int64).reshape(-1)
        p = array.comm.size
        if counts.shape[0] != p:
            raise ValueError(
                f"counts must have one entry per mesh position "
                f"({p}), got {counts.shape[0]}"
            )
        if (counts < 0).any():
            raise ValueError(f"counts must be non-negative, got {counts.tolist()}")
        axis = int(axis)
        if not 0 <= axis < array.ndim:
            raise ValueError(f"axis {axis} out of range for {array.ndim}-d array")
        if int(counts.sum()) != array.shape[axis]:
            raise ValueError(
                f"counts sum to {int(counts.sum())} but the array has "
                f"{array.shape[axis]} positions along axis {axis}"
            )
        self.__array = array
        self.__counts = counts
        self.__axis = axis
        self.__owner = None

    # -- metadata -------------------------------------------------------------

    @property
    def array(self) -> DNDarray:
        """The canonical-layout data."""
        return self.__array

    @property
    def axis(self) -> int:
        return self.__axis

    @property
    def counts(self) -> np.ndarray:
        """Per-position logical extents (a copy)."""
        return self.__counts.copy()

    @property
    def displs(self) -> np.ndarray:
        """Per-position logical start offsets along ``axis``."""
        return np.concatenate([[0], np.cumsum(self.__counts)[:-1]])

    @property
    def owner(self) -> DNDarray:
        """``owner[j]`` = mesh position that logically owns index ``j``
        along ``axis`` — a 1-D int64 DNDarray sharded like the data's
        ``axis`` (so ``owner == i`` masks are shard-aligned with the
        rows they gate). Built once, cached."""
        if self.__owner is None:
            from . import factories

            arr = self.__array
            vec = np.repeat(
                np.arange(self.__counts.shape[0], dtype=np.int64),
                self.__counts,
            )
            split = 0 if arr.split == self.__axis else None
            self.__owner = factories.array(
                vec, split=split, device=arr.device, comm=arr.comm
            )
        return self.__owner

    def __repr__(self) -> str:
        return (
            f"Ragged(counts={self.__counts.tolist()}, axis={self.__axis}, "
            f"array=<{self.__array.shape} split={self.__array.split}>)"
        )

    # -- per-position views ---------------------------------------------------

    def mask(self, position: int) -> DNDarray:
        """Boolean mask selecting position ``position``'s logical indices
        along ``axis`` — shard-aligned with the data, so ``x * mask``
        touches only that position's rows on the canonical layout."""
        from . import relational

        p = self.__counts.shape[0]
        position = builtins.int(position)
        if not 0 <= position < p:
            raise ValueError(f"position {position} out of range for {p}")
        return relational.eq(self.owner, position)

    def block(self, position: int) -> DNDarray:
        """Position ``position``'s logical slice along ``axis`` (the rows
        a ragged shard would hold) — a canonical-layout DNDarray."""
        p = self.__counts.shape[0]
        position = builtins.int(position)
        if not 0 <= position < p:
            raise ValueError(f"position {position} out of range for {p}")
        lo = builtins.int(self.displs[position])
        hi = lo + builtins.int(self.__counts[position])
        key = tuple(
            slice(lo, hi) if d == self.__axis else slice(None)
            for d in range(self.__array.ndim)
        )
        return self.__array[key]

    # -- redistribution -------------------------------------------------------

    def redistribute(self, counts: Sequence[int]) -> "Ragged":
        """A new :class:`Ragged` with the ownership intent rewritten to
        ``counts`` — ZERO data movement (the canonical layout already
        holds every row where XLA wants it; only the metadata changes).
        This is the operation the reference's ``redistribute_`` pays an
        Alltoallv for."""
        return Ragged(self.__array, counts, self.__axis)

    def resplit(self, axis: Optional[int] = None) -> "Ragged":
        """Change the *physical* distribution axis of the canonical data
        (the intent is unchanged). Planner-managed: under an
        ``HEAT_TPU_HBM_BUDGET`` the relayout decomposes into a
        bounded-memory chunked program chain instead of erroring at the
        ceiling (core/relayout_planner.py)."""
        return Ragged(self.__array.resplit(axis), self.__counts, self.__axis)


def ragged(
    blocks_or_data,
    counts: Optional[Sequence[int]] = None,
    *,
    axis: int = 0,
    split: Optional[int] = 0,
    dtype=None,
    device=None,
    comm=None,
) -> Ragged:
    """Build a :class:`Ragged` layout.

    Two forms:

    * ``ht.ragged([b0, b1, ...])`` — one array-like block per mesh
      position, concatenated along ``axis``; ``counts`` are the block
      extents (the reference's per-rank construction);
    * ``ht.ragged(data, counts)`` — existing data (array-like or
      DNDarray) plus an explicit per-position counts vector.

    The data lands on the canonical layout with the given ``split``
    (DNDarray inputs keep theirs); the ragged intent is metadata. See
    :class:`Ragged` for the operations it supports and
    ``examples/ragged_layout.py`` for a worked tour.
    """
    from . import factories
    from .communication import sanitize_comm

    comm = sanitize_comm(
        comm if comm is not None
        else (blocks_or_data.comm if isinstance(blocks_or_data, DNDarray) else None)
    )
    if counts is None:
        blocks = list(blocks_or_data)
        if len(blocks) != comm.size:
            raise ValueError(
                f"ragged(blocks) needs one block per mesh position "
                f"({comm.size}), got {len(blocks)}"
            )
        blocks = [np.asarray(b) for b in blocks]
        counts = [b.shape[axis] for b in blocks]
        data = np.concatenate(blocks, axis=axis) if blocks else np.empty((0,))
        arr = factories.array(
            data, dtype=dtype, split=split, device=device, comm=comm
        )
        return Ragged(arr, counts, axis)
    if isinstance(blocks_or_data, DNDarray):
        arr = blocks_or_data
        if dtype is not None:
            arr = arr.astype(dtype)
    else:
        arr = factories.array(
            np.asarray(blocks_or_data), dtype=dtype, split=split,
            device=device, comm=comm,
        )
    return Ragged(arr, counts, axis)
