"""Complex number ops (reference: heat/core/complex_math.py)."""

from __future__ import annotations

import jax.numpy as jnp

from . import types
from ._operations import local_op
from .dndarray import DNDarray

__all__ = ["angle", "conj", "conjugate", "imag", "real"]


def angle(x: DNDarray, deg: bool = False, out=None) -> DNDarray:
    """Argument of a complex array, in radians (degrees if deg)
    (reference complex_math.py `angle`)."""
    return local_op(jnp.angle, x, out, deg=deg)


def conjugate(x: DNDarray, out=None) -> DNDarray:
    """Elementwise complex conjugate (reference complex_math.py `conj`)."""
    return local_op(jnp.conjugate, x, out)


conj = conjugate


def imag(x: DNDarray) -> DNDarray:
    """Imaginary part (zeros for real input; reference complex_math.py)."""
    if issubclass(x.dtype, types.complexfloating):
        return local_op(jnp.imag, x)
    from . import factories

    return factories.zeros_like(x)


def real(x: DNDarray) -> DNDarray:
    """Real part (reference complex_math.py `real`)."""
    if issubclass(x.dtype, types.complexfloating):
        return local_op(jnp.real, x)
    return x


DNDarray.conj = lambda self, out=None: conjugate(self, out)
