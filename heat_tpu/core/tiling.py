"""Tile decompositions (reference: heat/core/tiling.py, 1245 LoC).

The reference maintains per-MPI-rank tile bookkeeping because every rank can
only touch its local shard: `SplitTiles` (reference tiling.py:14) is the
P×…×P chunk-rule grid used by `resplit_`'s Alltoallw shuffle, and
`SquareDiagTiles` (:331) the diagonal-square grid driving tiled QR. Under
the single-controller TPU runtime any tile is addressable as a slice of the
sharded global array (XLA materializes the transfer), so this module keeps
the *index calculus* — tile boundaries from the ceil chunk rule, tile →
mesh-position ownership, start/stop arithmetic — and drops the rank-local
get/set split: ``tiles[i, j]`` reads and ``tiles[i, j] = v`` writes the
global array directly.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


def _chunk_bounds(n: int, parts: int) -> np.ndarray:
    """Boundaries (len parts+1) of the ceil-rule chunking of ``n`` into
    ``parts`` — the layout rule of the framework (communication.chunk)."""
    c = -(-n // parts) if parts else n
    ends = np.minimum(np.arange(1, parts + 1) * c, n)
    return np.concatenate([[0], ends])


class SplitTiles:
    """Chunk-rule tile grid: the array cut into ``comm.size`` blocks along
    *every* dimension (reference tiling.py:14-330).

    ``tiles[key]`` with per-dimension integer/slice keys returns the
    corresponding block of the global array; assignment writes it back into
    the wrapped DNDarray.
    """

    def __init__(self, arr: DNDarray):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        self.__arr = arr
        p = arr.comm.size
        self.__bounds = [_chunk_bounds(s, p) for s in arr.shape]

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def lshape_map(self) -> np.ndarray:
        return self.__arr.lshape_map

    @property
    def tile_dimensions(self) -> np.ndarray:
        """(ndim, p) sizes of the tiles in each dimension (reference
        tiling.py:173)."""
        return np.stack([np.diff(b) for b in self.__bounds])

    @property
    def tile_ends_g(self) -> np.ndarray:
        """(ndim, p) global end index of each tile per dimension (reference
        tiling.py:162)."""
        return np.stack([b[1:] for b in self.__bounds])

    @property
    def tile_locations(self) -> np.ndarray:
        """Mesh position owning each tile (reference tiling.py:151): a
        (p, …, p) grid; ownership follows the split dimension's chunk index
        (replicated arrays are owned everywhere, marked -1)."""
        p = self.__arr.comm.size
        shape = (p,) * self.__arr.ndim
        if self.__arr.split is None:
            return np.full(shape, -1)
        grid = np.zeros(shape, dtype=np.int64)
        # ownership follows the chunk index along the split dimension
        view = np.moveaxis(grid, self.__arr.split, -1)
        view[...] = np.arange(p)
        return grid

    def get_tile_size(self, key) -> Tuple[int, ...]:
        """Shape of the tile addressed by ``key`` (reference tiling.py:282)."""
        slices = self.__key_to_slices(key)
        return tuple(s.stop - s.start for s in slices)

    def __key_to_slices(self, key) -> List[slice]:
        if not isinstance(key, tuple):
            key = (key,)
        if len(key) > self.__arr.ndim:
            raise ValueError(
                f"key has {len(key)} dims, array has {self.__arr.ndim}"
            )
        key = key + (slice(None),) * (self.__arr.ndim - len(key))
        out = []
        for dim, (k, bounds) in enumerate(zip(key, self.__bounds)):
            p = len(bounds) - 1
            if isinstance(k, int):
                if not -p <= k < p:
                    raise IndexError(f"tile index {k} out of range for dim {dim}")
                k = k % p
                out.append(slice(int(bounds[k]), int(bounds[k + 1])))
            elif isinstance(k, slice):
                start, stop, stride = k.indices(p)
                if stride != 1:
                    raise ValueError("strided tile slices are not supported")
                out.append(slice(int(bounds[start]), int(bounds[stop])))
            else:
                raise TypeError(f"invalid tile key element: {type(k)}")
        return out

    def __getitem__(self, key) -> jnp.ndarray:
        slices = self.__key_to_slices(key)
        return self.__arr._logical()[tuple(slices)]

    def __setitem__(self, key, value) -> None:
        slices = self.__key_to_slices(key)
        logical = self.__arr._logical().at[tuple(slices)].set(value)
        new = DNDarray.from_logical(
            logical, self.__arr.split, self.__arr.device, self.__arr.comm
        )
        self.__arr.larray = new.larray


class SquareDiagTiles:
    """Square tiles along the matrix diagonal (reference tiling.py:331-1245).

    Block decomposition for tiled QR: the diagonal is covered with square
    ``tiles_per_proc``-per-chunk blocks; rows/columns beyond the diagonal
    square inherit the adjacent boundaries. Exposes the index calculus
    (row/col boundaries, tile map, per-process counts) plus global get/set.

    Parameters
    ----------
    arr : DNDarray
        2-D array, split 0 or 1.
    tiles_per_proc : int
        Number of diagonal tiles per mesh position (reference :375).
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 2):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        if arr.ndim != 2:
            raise ValueError(f"arr must be 2D, got {arr.ndim}D")
        if tiles_per_proc < 1:
            raise ValueError("tiles_per_proc must be >= 1")
        if arr.split not in (0, 1):
            raise ValueError("SquareDiagTiles requires split 0 or 1")
        self.__arr = arr
        m, n = arr.shape
        p = arr.comm.size
        diag = min(m, n)
        # cut the split dimension with the chunk rule, then split each chunk
        # into tiles_per_proc tiles; clamp boundaries into the diagonal
        # square and extend the final row/col to cover any overhang
        split_len = m if arr.split == 0 else n
        outer = _chunk_bounds(split_len, p)
        inds = [0]
        for r in range(p):
            lo, hi = int(outer[r]), int(outer[r + 1])
            hi_d = min(hi, diag)
            lo_d = min(lo, diag)
            span = hi_d - lo_d
            if span <= 0:
                continue
            t = min(tiles_per_proc, span)
            sub = _chunk_bounds(span, t) + lo_d
            inds.extend(int(x) for x in sub[1:])
        if inds[-1] < diag:
            inds.append(diag)
        # the diagonal boundaries apply to both axes; the longer axis keeps
        # a final overhang tile
        row_bounds = list(inds)
        if row_bounds[-1] < m:
            row_bounds.append(m)
        col_bounds = list(inds)
        if col_bounds[-1] < n:
            col_bounds.append(n)
        self.__row_bounds = np.asarray(row_bounds)
        self.__col_bounds = np.asarray(col_bounds)
        self.__tiles_per_proc = tiles_per_proc

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def lshape_map(self) -> np.ndarray:
        return self.__arr.lshape_map

    @property
    def row_indices(self) -> List[int]:
        """Global start row of each tile row (reference :745)."""
        return [int(x) for x in self.__row_bounds[:-1]]

    @property
    def col_indices(self) -> List[int]:
        """Global start column of each tile column (reference :723)."""
        return [int(x) for x in self.__col_bounds[:-1]]

    @property
    def tile_rows(self) -> int:
        return len(self.__row_bounds) - 1

    @property
    def tile_columns(self) -> int:
        return len(self.__col_bounds) - 1

    def __owner_of(self, start: int) -> int:
        split_len = self.__arr.shape[self.__arr.split]
        p = self.__arr.comm.size
        c = -(-split_len // p)
        return min(start // c, p - 1) if c else 0

    @property
    def tile_rows_per_process(self) -> List[int]:
        """Tiles owned per mesh position along the rows (reference :809)."""
        p = self.__arr.comm.size
        counts = [0] * p
        if self.__arr.split == 0:
            for s in self.__row_bounds[:-1]:
                counts[self.__owner_of(int(s))] += 1
        else:
            counts = [self.tile_rows] * p
        return counts

    @property
    def tile_columns_per_process(self) -> List[int]:
        p = self.__arr.comm.size
        counts = [0] * p
        if self.__arr.split == 1:
            for s in self.__col_bounds[:-1]:
                counts[self.__owner_of(int(s))] += 1
        else:
            counts = [self.tile_columns] * p
        return counts

    @property
    def last_diagonal_process(self) -> int:
        """Mesh position owning the last diagonal element (reference :738)."""
        diag = min(self.__arr.shape) - 1
        return self.__owner_of(diag)

    @property
    def tile_map(self) -> np.ndarray:
        """(tile_rows, tile_columns, 3) of [row_start, col_start, owner]
        (reference :766)."""
        tm = np.zeros((self.tile_rows, self.tile_columns, 3), dtype=np.int64)
        for i, rs in enumerate(self.row_indices):
            for j, cs in enumerate(self.col_indices):
                owner = self.__owner_of(rs if self.__arr.split == 0 else cs)
                tm[i, j] = (rs, cs, owner)
        return tm

    def get_start_stop(self, key) -> Tuple[int, int, int, int]:
        """(row_start, row_stop, col_start, col_stop) of a (row, col) tile
        key (reference :815)."""
        i, j = key
        i = i % self.tile_rows
        j = j % self.tile_columns
        return (
            int(self.__row_bounds[i]),
            int(self.__row_bounds[i + 1]),
            int(self.__col_bounds[j]),
            int(self.__col_bounds[j + 1]),
        )

    def __getitem__(self, key) -> jnp.ndarray:
        r0, r1, c0, c1 = self.get_start_stop(key)
        return self.__arr._logical()[r0:r1, c0:c1]

    def __setitem__(self, key, value) -> None:
        r0, r1, c0, c1 = self.get_start_stop(key)
        logical = self.__arr._logical().at[r0:r1, c0:c1].set(value)
        new = DNDarray.from_logical(
            logical, self.__arr.split, self.__arr.device, self.__arr.comm
        )
        self.__arr.larray = new.larray
