"""heat_tpu core namespace assembly (reference: heat/core/__init__.py)."""

from .communication import *
from . import program_cache
from .devices import *
from .dndarray import *
from .types import *
from .constants import *
from .factories import *
from .memory import *
from .stride_tricks import *
from .sanitation import *
from ._operations import *
from . import fusion
from .fusion import fuse, fusing
from .arithmetics import *
from .relational import *
from .rounding import *
from .exponential import *
from .trigonometrics import *
from .complex_math import *
from .logical import *
from .indexing import *
from .printing import *
from .statistics import *
from .manipulations import *
from .io import *
from .base import *
from . import tiling
from .tiling import *
from . import random
from . import linalg
from .linalg import *
from . import version
from .version import version as __version__
