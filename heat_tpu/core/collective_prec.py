"""Quantized & compressed collective payloads (ISSUE 9 tentpole).

Heat's design splits every op into local compute plus explicit
collectives, so at scale the wire is the bottleneck. EQuARX
(arXiv:2506.17615) shows block-wise quantized all-reduce inside XLA
winning ~2x for small/medium tensors, and cross-replica weight-update
sharding (arXiv:2004.13336) shows the gradient path tolerates
reduced-precision aggregation when done carefully. This module
generalizes the one ad-hoc instance the repo already shipped — DASO's
bf16 cross-node parameter average — into a first-class, knob-controlled,
HLO-audited collective-precision layer:

* ``HEAT_TPU_COLLECTIVE_PREC=off|bf16|int8|blockwise`` (default ``off``)
  plus a per-call ``precision=`` override on every instrumented surface
  (:meth:`MeshCommunication.psum` & friends, ``manipulations.resplit``,
  ``DataParallel.make_train_step``, ``DASO``).
* ``bf16`` — cast → collective → upcast in the same trace. 2x wire
  reduction for f32 payloads (4x for f64), ~3 decimal digits kept.
* ``int8`` — EQuARX per-tensor scheme: one max-abs scale, symmetric
  round-to-nearest onto [-127, 127], the collective moves int8 + the
  bf16 scale, dequantize on the far side. ~4x wire reduction for f32.
* ``blockwise`` — the same scheme with one scale per block
  (``HEAT_TPU_COLLECTIVE_PREC_BLOCK`` elements, default 128), so a
  single outlier only poisons its own block's resolution. ~3.9x wire
  reduction for f32 at the default block.

Two execution contexts, same arithmetic:

* **shard_map kernels** (the :class:`MeshCommunication` wrapper family):
  per-shard payloads are quantized locally (no extra collective — the
  max-abs runs on the local block) and the scale rides the same
  collective as the payload. A quantized ``psum`` is the EQuARX
  two-phase form: quantize → all-to-all (the reduce-scatter phase) →
  dequantize + accumulate → requantize → all-gather → dequantize, i.e.
  ``2·B/4·(p-1)`` wire bytes instead of the f32 ring all-reduce's
  ``2·B·(p-1)``.
* **GSPMD programs** (the relayout family): quantize, pin the *wire*
  tensor's layout with ``with_sharding_constraint`` so the emitted
  collective moves the compressed dtype, dequantize after. Per-tensor
  scales cost one scalar cross-shard max all-reduce; blockwise scales
  (blocked along the last axis, which stays shard-local) are replicated
  by one small all-gather.

Every compressed program is ground-truthed: the analytic cost model
(:mod:`heat_tpu.telemetry.collectives`) takes a ``precision=`` argument
and the HLO auditor verifies the compiled program's emitted collectives
move the predicted *smaller* dtype/byte volume (drift fails CI).

Accuracy contract (pinned by ``tests/test_collective_prec.py``):

* ``off`` — bit-identical to the pre-knob programs (the default);
* ``bf16`` — per-element error bounded by bf16 rounding of the payload
  (~2^-8 relative);
* ``int8``/``blockwise`` — per-element error bounded relative to the
  max-abs of the scale group: one quantization step is at most
  ``amax/254``; a two-phase psum over ``p`` shards accumulates at most
  ``(p+1)`` steps. Integer/bool payloads always pass through exact;
  non-finite payloads (inf/nan) are outside the contract.

Only lossy-tolerant data movement honors the global knob: exactness-
critical sites (sort/unique index circulation, histogram counts, the QR
rings) pin ``precision="off"`` at the call site.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from heat_tpu import _knobs as knobs

from ..telemetry import collectives as _cost

__all__ = [
    "MODES",
    "DEFAULT_BLOCK",
    "mode",
    "block_size",
    "resolve",
    "effective",
    "compressible",
    "blockwise_axis_ok",
    "psum",
    "pmean",
    "reduce_scatter",
    "all_gather",
    "ppermute",
    "all_to_all",
    "gspmd_reshard",
    "local_roundtrip",
    "quant_error_bound",
    "allreduce_wire_dtype",
    "bench_field",
]

MODES = ("off", "bf16", "int8", "blockwise")
_ENV_MODE = "HEAT_TPU_COLLECTIVE_PREC"
_ENV_BLOCK = "HEAT_TPU_COLLECTIVE_PREC_BLOCK"

# One scale per this many payload elements in blockwise mode. 128 keeps the
# bf16 scale overhead at 1/64 of the int8 payload (~1.6%) while localizing
# outliers; the cost model (telemetry/collectives.py DEFAULT_WIRE_BLOCK)
# carries the same default so predictions and programs agree.
DEFAULT_BLOCK = _cost.DEFAULT_WIRE_BLOCK


def mode() -> str:
    """The active ``HEAT_TPU_COLLECTIVE_PREC`` value (malformed -> off)."""
    raw = (knobs.raw(_ENV_MODE, "") or "").strip().lower()
    return raw if raw in MODES else "off"


def block_size() -> int:
    """Blockwise scale granularity (``HEAT_TPU_COLLECTIVE_PREC_BLOCK``,
    default :data:`DEFAULT_BLOCK`; malformed or non-positive -> default)."""
    raw = (knobs.raw(_ENV_BLOCK, "") or "").strip()
    if raw:
        try:
            n = int(raw)
            if n > 0:
                return n
        except ValueError:
            pass
    return DEFAULT_BLOCK


def resolve(precision: Optional[str] = None) -> str:
    """Per-call override semantics: an explicit ``precision=`` wins over
    the env knob; ``None`` consults :func:`mode`. Unknown values raise —
    a typo'd mode must never silently run exact (or lossy)."""
    if precision is None:
        return mode()
    p = str(precision).strip().lower()
    if p not in MODES:
        raise ValueError(
            f"precision must be one of {MODES}, got {precision!r}"
        )
    return p


def compressible(dtype) -> bool:
    """Only floating payloads are lossy-compressible; integer/bool/complex
    payloads (indices, counts, sort keys) always move exact."""
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def effective(dtype, precision: Optional[str] = None) -> str:
    """The wire mode one payload actually gets: the resolved mode, demoted
    to ``off`` for non-float dtypes. This is the value program-cache keys
    must carry — it fully determines the traced program."""
    m = resolve(precision)
    if m == "off" or not compressible(dtype):
        return "off"
    return m


def blockwise_axis_ok(shape: Sequence[int], split: Optional[int]) -> bool:
    """Whether the GSPMD blockwise layout applies: blocks run along the
    last axis, which must be a real axis distinct from the sharded one so
    every block is shard-local (its max-abs needs no collective)."""
    return len(shape) >= 2 and split != len(shape) - 1 and int(shape[-1]) > 0


def blockwise_segments(extent: int, block: int) -> Tuple[int, int]:
    """(n_blocks, segment) decomposition of a last-axis ``extent`` for the
    GSPMD path: even ``block``-sized segments when they divide the axis,
    else one whole-row segment (no wire-wasting pad). The cost model
    mirrors this rule exactly."""
    extent = int(extent)
    if extent >= block and extent % block == 0:
        return extent // block, block
    return 1, extent


# -- quantization arithmetic (pure jnp; runs inside any trace) ----------------


def _scale_of(amax):
    """Zero-safe symmetric scale: q = round(x/scale) targets [-127, 127];
    an all-zero group quantizes through scale 1 (payload stays zero).
    The scale ships in **bf16** — half the scale wire traffic of f32,
    and since quantization divides by the bf16-rounded value the
    roundtrip error stays one quantization step (the extra ~2^-8 scale
    rounding only rescales the step, it does not compound)."""
    s = jnp.where(amax > 0, amax / 127.0, jnp.ones_like(amax))
    return s.astype(jnp.bfloat16)


def _quant_tensor(x) -> Tuple[jax.Array, jax.Array]:
    """Per-tensor int8 quantization: (q int8, scale bf16 scalar)."""
    xf = x.astype(jnp.float32)
    s = _scale_of(jnp.max(jnp.abs(xf)))
    q = jnp.clip(jnp.round(xf / s.astype(jnp.float32)), -127.0, 127.0)
    return q.astype(jnp.int8), s


def _quant_flat_blocks(x, block: int) -> Tuple[jax.Array, jax.Array]:
    """Flat blockwise quantization: the payload raveled and zero-padded to
    ``nblk * block``; returns (q int8 (nblk, block), scales bf16 (nblk,))."""
    n = x.size
    block = max(1, min(block, n))  # a payload smaller than one block
    # must not be zero-padded up to it (16x wire blowup for tiny tensors)
    nblk = max(1, -(-n // block))
    flat = jnp.ravel(x).astype(jnp.float32)
    if nblk * block != n:
        flat = jnp.pad(flat, (0, nblk * block - n))
    b = flat.reshape(nblk, block)
    s = _scale_of(jnp.max(jnp.abs(b), axis=1))
    q = jnp.clip(
        jnp.round(b / s.astype(jnp.float32)[:, None]), -127.0, 127.0
    ).astype(jnp.int8)
    return q, s


def _deq(q, s):
    """int8 payload × bf16 scale in f32."""
    return q.astype(jnp.float32) * s.astype(jnp.float32)


def _move_u16(collective, w):
    """Run a data-movement collective on a bf16 tensor's uint16 bit
    pattern. Movement never does arithmetic on the payload, and the
    bitcast keeps backends honest: XLA CPU's bf16 normalization pass
    would otherwise upcast a bf16 collective operand to f32 — doubling
    the very wire bytes the mode exists to halve (psum is the exception:
    its wire arithmetic must stay in the payload dtype)."""
    u = jax.lax.bitcast_convert_type(w, jnp.uint16)
    return jax.lax.bitcast_convert_type(collective(u), jnp.bfloat16)


def _dequant_flat_blocks(q, s, n: int, shape, dtype):
    flat = _deq(q, s[..., None]).reshape(-1)[:n]
    return flat.reshape(shape).astype(dtype)


def local_roundtrip(x, mode_: str, block: Optional[int] = None):
    """quantize→dequantize without any collective — the payload a
    compressed ppermute/all_gather delivers to its peer. The parity
    oracles in tests pin ``compressed_collective(x) ==
    exact_collective(local_roundtrip(x))`` bitwise."""
    if mode_ == "off" or not compressible(x.dtype):
        return x
    if mode_ == "bf16":
        return x.astype(jnp.bfloat16).astype(x.dtype)
    if mode_ == "int8":
        q, s = _quant_tensor(x)
        return _deq(q, s).astype(x.dtype)
    block = block or block_size()
    q, s = _quant_flat_blocks(x, block)
    return _dequant_flat_blocks(q, s, x.size, x.shape, x.dtype)


def quant_error_bound(x, mode_: str, hops: int = 1) -> float:
    """Documented per-element absolute error bound of ``hops``
    quantization steps of ``x`` under wire mode ``mode_`` — the
    tolerance the parity gates use when a lossy wire is opted in (the
    module-docstring accuracy contract as a number):

    * ``off`` (or a non-compressible dtype) — ``0.0``, bit-exact;
    * ``bf16`` — ``2^-8`` relative to the max-abs per hop (bf16 has 8
      significand bits);
    * ``int8``/``blockwise`` — one step is at most ``amax/254``
      (symmetric round-to-nearest onto ±127) per hop; blockwise bounds
      by the per-block max-abs, which this conservative form upper-
      bounds with the global max-abs.

    ``x`` may be an array or a known max-abs float. Non-finite payloads
    are outside the contract (returns ``inf``)."""
    import numpy as np

    if hasattr(x, "dtype") and not compressible(x.dtype):
        return 0.0
    amax = float(np.max(np.abs(np.asarray(x)))) if hasattr(x, "ndim") \
        else float(x)
    if not np.isfinite(amax):
        return float("inf")
    if mode_ == "off":
        return 0.0
    if mode_ == "bf16":
        return amax * (2.0 ** -8) * max(1, int(hops))
    return amax / 254.0 * max(1, int(hops))


def allreduce_wire_dtype(dtype, platform: Optional[str] = None) -> str:
    """The element type a SUMMING all-reduce of this payload actually
    moves on ``platform`` (default: the attached backend) — the
    carried-debt PR 9 caveat as a queryable table. XLA's CPU backend
    legalizes a bf16 (and f16) summing all-reduce to f32 — the wire
    moves 2x the payload bytes and the audit sees ``f32`` — while TPU
    keeps the native narrow type. Every other float payload reduces in
    its own dtype on both backends. The bench harness and the FSDP gate
    consult this so cross-tier compression claims on the emulated CPU
    mesh name the legalization instead of reporting a bare drift."""
    if platform is None:
        platform = jax.devices()[0].platform
    name = jnp.dtype(dtype).name
    wire = {"bfloat16": "bf16", "float16": "f16", "float32": "f32",
            "float64": "f64"}.get(name, name)
    if platform == "cpu" and wire in ("bf16", "f16"):
        return "f32"
    return wire


# -- shard_map-level compressed collectives -----------------------------------
# Per-shard payloads: the max-abs runs on the LOCAL block (no collective),
# and scales ride the same collective kind as the payload.


def ppermute(x, axis_name: str, perm, mode_: str, block: Optional[int] = None):
    """Compressed ``lax.ppermute``: the hop moves int8/bf16 + scales; the
    receiver dequantizes. Re-quantizing per hop means ring kernels
    compound one quantization step per hop (documented accuracy
    contract)."""
    if mode_ == "off" or not compressible(x.dtype):
        return jax.lax.ppermute(x, axis_name, perm=perm)
    if mode_ == "bf16":
        w = x if x.dtype == jnp.bfloat16 else x.astype(jnp.bfloat16)
        hop = lambda u: jax.lax.ppermute(u, axis_name, perm=perm)  # noqa: E731
        return _move_u16(hop, w).astype(x.dtype)
    hop = lambda u: jax.lax.ppermute(u, axis_name, perm=perm)  # noqa: E731
    if mode_ == "int8":
        q, s = _quant_tensor(x)
        q = hop(q)
        s = _move_u16(hop, s)
        return _deq(q, s).astype(x.dtype)
    block = block or block_size()
    q, s = _quant_flat_blocks(x, block)
    q = hop(q)
    s = _move_u16(hop, s)
    return _dequant_flat_blocks(q, s, x.size, x.shape, x.dtype)


def all_gather(
    x, axis_name: str, mode_: str, block: Optional[int] = None,
    tiled: bool = True,
):
    """Compressed ``lax.all_gather``: every shard quantizes its block,
    gathers int8 + scales, dequantizes the full set locally."""
    if mode_ == "off" or not compressible(x.dtype):
        return jax.lax.all_gather(x, axis_name, tiled=tiled)
    gather = lambda u: jax.lax.all_gather(u, axis_name)  # noqa: E731
    if mode_ == "bf16":
        w = x if x.dtype == jnp.bfloat16 else x.astype(jnp.bfloat16)
        return _move_u16(
            lambda u: jax.lax.all_gather(u, axis_name, tiled=tiled), w
        ).astype(x.dtype)
    if mode_ == "int8":
        q, s = _quant_tensor(x)
        qg = gather(q)                                 # (p,) + x.shape
        sg = _move_u16(gather, s)                      # (p,)
        p = qg.shape[0]
        deq = _deq(qg, sg.reshape((p,) + (1,) * x.ndim))
    else:
        block = block or block_size()
        q, s = _quant_flat_blocks(x, block)
        qg = gather(q)                                 # (p, nblk, block)
        sg = _move_u16(gather, s)                      # (p, nblk)
        p = qg.shape[0]
        deq = _deq(qg, sg[..., None]).reshape(p, -1)
        deq = deq[:, : x.size].reshape((p,) + x.shape)
    deq = deq.astype(x.dtype)
    if tiled and x.ndim >= 1:
        return deq.reshape((p * x.shape[0],) + x.shape[1:])
    return deq


def _quant_scatter_phase(x, axis_name: str, nproc: int, mode_: str,
                         block: int, groups):
    """The EQuARX FIRST phase: quantize this device's partial into
    ``nproc`` per-destination sub-chunks, all-to-all them (each device
    collects everyone's partial of its 1/p chunk), dequantize and
    accumulate in f32. Returns ``(red, chunk)`` where ``red`` is the
    f32 ``(chunk,)`` group-sum chunk this position owns — a quantized
    reduce-scatter standing alone, and the front half of the quantized
    :func:`psum`. ``groups`` (``axis_index_groups``) scopes every
    collective to a tier's replica groups (ISSUE 15); ``nproc`` is then
    the GROUP size, not the axis size."""
    n = x.size
    chunk = -(-n // nproc)
    if mode_ == "blockwise":
        block = max(1, min(block, chunk))  # no pad blowup for small chunks
        chunk = -(-chunk // block) * block
    pad_n = chunk * nproc
    flat = jnp.ravel(x).astype(jnp.float32)
    if pad_n != n:
        flat = jnp.pad(flat, (0, pad_n - n))
    parts = flat.reshape(nproc, chunk)                  # row i -> device i
    if mode_ == "int8":
        s = _scale_of(jnp.max(jnp.abs(parts)))          # scalar
        q = jnp.clip(jnp.round(parts / s), -127.0, 127.0).astype(jnp.int8)
        qt = jax.lax.all_to_all(
            q, axis_name, 0, 0, tiled=True, axis_index_groups=groups
        )
        sg = _move_u16(
            lambda u: jax.lax.all_gather(
                u, axis_name, axis_index_groups=groups
            ), s
        )                                               # (p,)
        deq = _deq(qt, sg[:, None])
    else:
        b3 = parts.reshape(nproc, chunk // block, block)
        s = _scale_of(jnp.max(jnp.abs(b3), axis=2))     # (p, nb)
        q = jnp.clip(jnp.round(b3 / s[..., None]), -127.0, 127.0)
        q = q.astype(jnp.int8)
        qt = jax.lax.all_to_all(
            q, axis_name, 0, 0, tiled=True, axis_index_groups=groups
        )
        st = _move_u16(
            lambda u: jax.lax.all_to_all(
                u, axis_name, 0, 0, tiled=True, axis_index_groups=groups
            ), s
        )
        deq = _deq(qt, st[..., None]).reshape(nproc, chunk)
    return jnp.sum(deq, axis=0), chunk                  # this device's chunk


def reduce_scatter(x, axis_name: str, nproc: int, mode_: str,
                   block: Optional[int] = None, groups=None):
    """Reduce-scatter of a payload flattened and zero-padded to ``nproc``
    equal chunks: position ``i`` (within its group) returns the 1-D
    ``(ceil(numel/nproc),)`` chunk ``i`` of the group sum, in the
    payload's dtype. ``off`` is the native ring ``lax.psum_scatter``;
    ``bf16`` the same on a bf16 payload; ``int8``/``blockwise`` the
    EQuARX first phase (:func:`_quant_scatter_phase`) standing alone —
    the ZeRO gradient-sharding primitive (ISSUE 15). Blockwise pads the
    chunk to whole blocks, so the returned chunk can be one block-pad
    longer than ``ceil(numel/nproc)``; callers slice by their own
    arithmetic."""
    n = x.size
    chunk = -(-n // nproc)
    if mode_ == "off" or not compressible(x.dtype):
        flat = jnp.ravel(x)
        if chunk * nproc != n:
            flat = jnp.pad(flat, (0, chunk * nproc - n))
        return jax.lax.psum_scatter(
            flat, axis_name, scatter_dimension=0,
            axis_index_groups=groups, tiled=True,
        )
    if mode_ == "bf16":
        flat = jnp.ravel(x).astype(jnp.bfloat16)
        if chunk * nproc != n:
            flat = jnp.pad(flat, (0, chunk * nproc - n))
        return jax.lax.psum_scatter(
            flat, axis_name, scatter_dimension=0,
            axis_index_groups=groups, tiled=True,
        ).astype(x.dtype)
    red, _chunk = _quant_scatter_phase(
        x, axis_name, nproc, mode_, block or block_size(), groups
    )
    return red.astype(x.dtype)


def psum(x, axis_name: str, nproc: int, mode_: str,
         block: Optional[int] = None, groups=None):
    """Compressed ``lax.psum`` — the EQuARX two-phase quantized
    all-reduce. ``bf16`` keeps the native all-reduce on a bf16 payload;
    ``int8``/``blockwise`` run quantize → all-to-all (each device
    collects everyone's partial of its 1/p chunk) → dequantize +
    accumulate in f32 → requantize → all-gather → dequantize. Two int8
    passes instead of one f32 ring: ``2·(B/4)·(p-1)`` wire bytes, a 4x
    reduction, at ≤ (p+1) quantization steps of error per element.
    ``groups`` scopes every collective to ``axis_index_groups`` (the
    ISSUE 15 cross-node tier); ``nproc`` is then the group size."""
    if mode_ == "off" or not compressible(x.dtype):
        return jax.lax.psum(x, axis_name, axis_index_groups=groups)
    if mode_ == "bf16":
        w = x if x.dtype == jnp.bfloat16 else x.astype(jnp.bfloat16)
        return jax.lax.psum(
            w, axis_name, axis_index_groups=groups
        ).astype(x.dtype)
    block = block or block_size()
    n = x.size
    red, chunk = _quant_scatter_phase(
        x, axis_name, nproc, mode_, block, groups
    )
    if mode_ == "blockwise":
        block = max(1, min(block, -(-n // nproc)))
    if mode_ == "int8":
        s2 = _scale_of(jnp.max(jnp.abs(red)))
        q2 = jnp.clip(jnp.round(red / s2), -127.0, 127.0).astype(jnp.int8)
        q2g = jax.lax.all_gather(
            q2, axis_name, axis_index_groups=groups
        )                                               # (p, chunk)
        s2g = _move_u16(
            lambda u: jax.lax.all_gather(
                u, axis_name, axis_index_groups=groups
            ), s2
        )                                               # (p,)
        out = _deq(q2g, s2g[:, None])
    else:
        rb = red.reshape(chunk // block, block)
        s2 = _scale_of(jnp.max(jnp.abs(rb), axis=1))
        q2 = jnp.clip(jnp.round(rb / s2[:, None]), -127.0, 127.0)
        q2 = q2.astype(jnp.int8)
        q2g = jax.lax.all_gather(
            q2, axis_name, axis_index_groups=groups
        )                                               # (p, nb, block)
        s2g = _move_u16(
            lambda u: jax.lax.all_gather(
                u, axis_name, axis_index_groups=groups
            ), s2
        )                                               # (p, nb)
        out = _deq(q2g, s2g[..., None]).reshape(nproc, chunk)
    return out.reshape(-1)[:n].reshape(x.shape).astype(x.dtype)


def pmean(x, axis_name: str, nproc: int, mode_: str,
          block: Optional[int] = None):
    """Compressed mean: compressed :func:`psum` divided by the axis size
    in the payload's compute dtype (f32 for f32 payloads)."""
    if mode_ == "off" or not compressible(x.dtype):
        return jax.lax.pmean(x, axis_name)
    return (psum(x, axis_name, nproc, mode_, block) / nproc).astype(x.dtype)


def all_to_all(
    x, axis_name: str, nproc: int, split_axis: int, concat_axis: int,
    mode_: str, block: Optional[int] = None,
):
    """Compressed tiled ``lax.all_to_all``. Each outgoing slab (the 1/p of
    the split axis headed to one peer) is quantized independently —
    per-slab scales in ``int8`` mode, per-slab flat blocks in
    ``blockwise`` — and the scales ride their own (tiny) all-to-all, so
    every receiver can dequantize its slabs by source."""
    if mode_ == "off" or not compressible(x.dtype):
        return jax.lax.all_to_all(
            x, axis_name, split_axis, concat_axis, tiled=True
        )
    if mode_ == "bf16":
        w = x if x.dtype == jnp.bfloat16 else x.astype(jnp.bfloat16)
        return _move_u16(
            lambda u: jax.lax.all_to_all(
                u, axis_name, split_axis, concat_axis, tiled=True
            ),
            w,
        ).astype(x.dtype)
    block = block or block_size()
    w = x.shape[split_axis] // nproc
    xm = jnp.moveaxis(x, split_axis, 0)                 # (S, *rest)
    rest = xm.shape[1:]
    m = w
    for d in rest:
        m *= d
    slabs = xm.reshape(nproc, m)                        # slab i -> peer i
    if mode_ == "int8":
        nb, seg = 1, m
    else:
        seg = max(1, min(block, m))  # no pad blowup for small slabs
        nb = max(1, -(-m // seg))
        if nb * seg != m:
            slabs = jnp.pad(slabs, ((0, 0), (0, nb * seg - m)))
    b3 = slabs.reshape(nproc, nb, seg)
    s = _scale_of(jnp.max(jnp.abs(b3), axis=2))         # (p, nb)
    q = jnp.clip(jnp.round(b3 / s[..., None]), -127.0, 127.0).astype(jnp.int8)
    qt = jax.lax.all_to_all(q, axis_name, 0, 0, tiled=True)
    st = _move_u16(
        lambda u: jax.lax.all_to_all(u, axis_name, 0, 0, tiled=True), s
    )
    deq = _deq(qt, st[..., None]).reshape(nproc, -1)[:, :m]
    deq = deq.reshape((nproc, w) + rest)
    # restore each slab's original axis order (w sits where split_axis was),
    # then merge the leading source axis into the concat axis source-major —
    # exactly the tiled all_to_all layout
    deq = jnp.moveaxis(deq, 1, 1 + split_axis)
    deq = jnp.moveaxis(deq, 0, concat_axis)
    shp = list(deq.shape)
    shp[concat_axis : concat_axis + 2] = [
        shp[concat_axis] * shp[concat_axis + 1]
    ]
    return deq.reshape(shp).astype(x.dtype)


# -- GSPMD-level compressed reshard -------------------------------------------


def gspmd_reshard(
    b, comm, src_split: Optional[int], dst_split: Optional[int],
    mode_: str, block: Optional[int] = None,
):
    """Inside a jit program: move ``b`` (sharded along ``src_split``) to
    the ``dst_split`` canonical layout with the wire payload compressed.

    The trick is a constraint PAIR: the quantized tensor is pinned to the
    *source* sharding first and to the destination sharding second, so
    GSPMD has no freedom to hoist the resharding collective onto the
    uncompressed input (one constraint alone lets the partitioner
    reshard the f32 operand and cast locally — measured on XLA CPU). The
    collective (all-to-all for split→split, all-gather for
    split→replicated) therefore moves the int8/bf16 payload;
    dequantization happens after, already in the destination layout.
    Scales:

    * per-tensor (``int8``, and ``blockwise`` on shapes where the block
      axis would be the sharded one): the max-abs over the sharded array
      costs one scalar cross-shard **max all-reduce** (8·(p-1) audited
      wire bytes) and the resulting scalar is replicated for free;
    * ``blockwise`` (blocks along the last, unsharded axis — see
      :func:`blockwise_segments`): scales are computed shard-locally and
      replicated by one small **all-gather**.

    The analytic prediction (`telemetry.collectives.relayout_cost` with
    ``precision=``) names these exact compounds, so the HLO audit stays
    zero-drift."""
    ndim = b.ndim
    tgt = (
        comm.sharding(dst_split, ndim)
        if dst_split is not None
        else comm.replicated()
    )

    def move(w, src_sharding, out=None):
        w = jax.lax.with_sharding_constraint(w, src_sharding)
        return jax.lax.with_sharding_constraint(
            w, out if out is not None else tgt
        )

    def move_bf16(w, src_sharding, out=None):
        # a bf16 payload travels as its uint16 bit pattern: the algebraic
        # simplifier folds a narrow-cast/up-cast pair across the
        # constraints into one f32 reduce-precision (putting the f32
        # tensor back on the wire — measured on XLA CPU), but a bitcast
        # is opaque to it, so the collective is pinned to the 2-byte
        # dtype
        u = jax.lax.bitcast_convert_type(w, jnp.uint16)
        u = move(u, src_sharding, out)
        return jax.lax.bitcast_convert_type(u, jnp.bfloat16)

    src_sh = comm.sharding(src_split, ndim)
    if mode_ == "bf16":
        w = b if b.dtype == jnp.bfloat16 else b.astype(jnp.bfloat16)
        return move_bf16(w, src_sh).astype(b.dtype)
    block = block or block_size()
    if mode_ == "blockwise" and blockwise_axis_ok(b.shape, src_split):
        nb, seg = blockwise_segments(b.shape[-1], block)
        xb = b.astype(jnp.float32).reshape(b.shape[:-1] + (nb, seg))
        s = _scale_of(jnp.max(jnp.abs(xb), axis=-1))    # shard-local blocks
        q = jnp.clip(jnp.round(xb / s.astype(jnp.float32)[..., None]),
                     -127.0, 127.0)
        q = q.astype(jnp.int8).reshape(b.shape)
        q = move(q, src_sh)
        # scales inherit the source split (their axes are b's minus the
        # blocked last one) and replicate through the same pinned pair
        s = move_bf16(
            s, comm.sharding(src_split, s.ndim), out=comm.replicated()
        )
        deq = _deq(
            q.reshape(b.shape[:-1] + (nb, seg)), s[..., None]
        ).reshape(b.shape)
        return deq.astype(b.dtype)
    # per-tensor: the max-abs spans shards -> one scalar max all-reduce
    q, s = _quant_tensor(b)
    q = move(q, src_sh)
    return _deq(q, s).astype(b.dtype)


# -- bench probe ---------------------------------------------------------------


def bench_field(gshape: Tuple[int, ...] = (4096, 64)) -> dict:
    """The ``collective_prec`` wire-bytes-vs-accuracy frontier for BENCH
    summaries (bench.py / docs/BENCHMARKS.md): for the canonical f32
    resplit(0→1) on the live mesh, per mode — analytic predicted wire
    bytes, HLO-audited emitted wire bytes of the very program that mode
    dispatches, and the executed max relative error vs the exact
    program (amax-normalized). The active env mode is reported alongside;
    `on_chip` honesty rides on the surrounding bench summary as always."""
    import numpy as np

    from . import factories, types
    from .communication import get_comm
    from ..telemetry import hlo

    comm = get_comm()
    rng = np.random.default_rng(0)
    xn = rng.standard_normal(gshape).astype(np.float32)
    x = factories.array(xn, split=0, comm=comm)
    field = {"mode": mode(), "block": block_size(), "gshape": list(gshape),
             "modes": {}}
    ref = None
    for m in MODES:
        row = {"predicted_wire_bytes": None, "audited_wire_bytes": None,
               "max_rel_err": None}
        try:
            phys = comm.padded_shape(
                comm.padded_shape(gshape, 0), 1
            )
            row["predicted_wire_bytes"] = int(
                _cost.relayout_cost(
                    phys, 4, 0, 1, comm.size, precision=m,
                    block=block_size(),
                ).bytes
            )
            fn = x._relayout_executable(1, precision=m)
            row["audited_wire_bytes"] = int(
                hlo.audit_computation(fn, x.larray).total_wire()
            )
            out = np.asarray(fn(x.larray))
            if m == "off":
                ref = out
                row["max_rel_err"] = 0.0
            elif ref is not None:
                denom = float(np.max(np.abs(ref))) or 1.0
                row["max_rel_err"] = float(
                    np.max(np.abs(out - ref)) / denom
                )
        except Exception as e:  # pragma: no cover — probe must never kill bench
            row["error"] = repr(e)
        field["modes"][m] = row
    return field
