"""Version information for heat_tpu.

Mirrors the reference version module layout (reference: heat/core/version.py:3-7)
but versions this framework independently.
"""

major: int = 0
"""Major version number."""
minor: int = 1
"""Minor version number."""
micro: int = 0
"""Micro version number."""
extension: str = None
"""Version extension tag (e.g. dev, rc)."""

if not extension:
    version: str = f"{major}.{minor}.{micro}"
else:
    version: str = f"{major}.{minor}.{micro}-{extension}"
