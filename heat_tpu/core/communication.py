"""Mesh-backed communication layer — the TPU-native replacement for MPI.

The reference routes *all* inter-process traffic through hand-written MPI
calls (reference: heat/core/communication.py:120-1864, `MPICommunication`
wrapping an `MPI.Comm` with Send/Recv, Bcast, Allreduce, Allgatherv,
Alltoall(v/w), Scatterv/Gatherv, derived datatypes and GPU staging buffers).
On TPU none of that choreography survives: a :class:`Communication` here wraps
a :class:`jax.sharding.Mesh` over the chips of one platform, arrays are
sharded `jax.Array`s, and XLA emits the collectives (over ICI within a slice,
DCN across slices) from sharding annotations. What remains of the reference
layer — and what this module provides — is:

* the **chunk arithmetic** that defines which global indices each mesh
  position owns (`chunk`, `lshape_map`, `counts_displs`); the reference's
  balanced rule (communication.py:161-209: ``n//p`` with the first ``n%p``
  ranks one larger) is replaced by the **ceil rule** (``ceil(n/p)`` per shard,
  short/empty tail shards) because that is the physical layout XLA uses for a
  sharded dimension; arrays whose split dimension is not divisible are stored
  **tail-padded** to ``ceil(n/p)*p`` (see dndarray.py for the invariant);
* `NamedSharding` factories translating Heat's single ``split`` axis into
  `PartitionSpec`s over the mesh;
* explicit in-`shard_map` collectives (`psum`, `all_gather`, `ppermute`,
  `all_to_all`) for the few kernels where we hand-schedule (ring cdist, TSQR),
  mirroring the reference inventory in spirit;
* the global communicator registry (`WORLD` analog, `get_comm`/`use_comm`,
  reference communication.py:1867-1914).
"""

from __future__ import annotations

import math
import os
from typing import List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .devices import Device, get_device
from .. import resilience, telemetry

__all__ = [
    "Communication",
    "MeshCommunication",
    "get_comm",
    "init_distributed",
    "sanitize_comm",
    "use_comm",
    "CommunicationError",
]


class CommunicationError(RuntimeError):
    pass


class Communication:
    """Abstract base (reference communication.py:88-117)."""

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def chunk(self, shape, split, rank=None):
        raise NotImplementedError()


class MeshCommunication(Communication):
    """A communicator backed by a 1-D device mesh.

    ``size`` is the number of mesh positions (devices), the analog of the MPI
    world size; ``rank`` is the host process index (0 in single-controller
    runs — per-shard identity lives inside `shard_map` kernels as the mesh
    axis index, not in Python).

    Parameters
    ----------
    devices : sequence of jax.Device, optional
        Devices to build the mesh over. Defaults to all devices of the
        current default platform.
    axis : str
        Mesh axis name used in PartitionSpecs (default ``"proc"``).
    """

    def __init__(
        self,
        devices: Optional[Sequence["jax.Device"]] = None,
        axis: str = "proc",
        device: Optional[Device] = None,
    ):
        if devices is None:
            dev = device if device is not None else get_device()
            devices = dev.jax_devices()
        self.__devices = list(devices)
        self.__axis = axis
        self.__first_local_position = None
        self.__mesh = Mesh(np.asarray(self.__devices), (axis,))

    # -- identity ------------------------------------------------------------

    @property
    def mesh(self) -> Mesh:
        return self.__mesh

    @property
    def axis_name(self) -> str:
        return self.__axis

    @property
    def size(self) -> int:
        """Number of mesh positions — the world size analog."""
        return len(self.__devices)

    @property
    def rank(self) -> int:
        """Host process index (0 under single-controller JAX)."""
        return jax.process_index()

    def first_local_position(self) -> int:
        """Mesh position of this process's first device — the position whose
        chunk `DNDarray.lshape` reports (on a single controller: 0).

        Fixed for the mesh's lifetime, so the device-list scan runs once
        (`lshape` consults this on every access)."""
        cached = self.__first_local_position
        if cached is None:
            pidx = jax.process_index()
            cached = 0
            for i, dev in enumerate(self.__devices):
                if dev.process_index == pidx:
                    cached = i
                    break
            self.__first_local_position = cached
        return cached

    @property
    def devices(self) -> List["jax.Device"]:
        return list(self.__devices)

    @staticmethod
    def is_distributed() -> bool:
        return jax.process_count() > 1

    def __eq__(self, other):
        if isinstance(other, MeshCommunication):
            return self.__devices == other.devices and self.__axis == other.axis_name
        return NotImplemented

    def __hash__(self):
        return hash((tuple(self.__devices), self.__axis))

    def __repr__(self):
        plat = self.__devices[0].platform if self.__devices else "?"
        return f"MeshCommunication(size={self.size}, axis={self.__axis!r}, platform={plat!r})"

    # -- chunk arithmetic (the layout contract) ------------------------------

    def chunk_size(self, n: int) -> int:
        """Per-position physical chunk length for a dimension of logical
        length ``n``: ``ceil(n/size)`` (the XLA shard size)."""
        if self.size == 0:
            return n
        return -(-n // self.size)

    def padded_size(self, n: int) -> int:
        """Physical (padded) global length: ``chunk_size * size``."""
        return self.chunk_size(n) * self.size

    def padded_shape(self, gshape: Sequence[int], split: Optional[int]) -> Tuple[int, ...]:
        """Physical storage shape for a logical global shape: identical except
        the split dimension is rounded up to a multiple of ``size``."""
        gshape = tuple(int(s) for s in gshape)
        if split is None:
            return gshape
        return gshape[:split] + (self.padded_size(gshape[split]),) + gshape[split + 1 :]

    def chunk(
        self,
        shape: Sequence[int],
        split: Optional[int],
        rank: Optional[int] = None,
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Logical sub-chunk of mesh position ``rank`` (default: all identical
        when split is None). Returns ``(offset, local_shape, slices)`` —
        same contract as the reference (communication.py:161-209) but with the
        ceil distribution rule: position ``r`` owns global indices
        ``[r*c, min((r+1)*c, n))`` with ``c = ceil(n/size)``; tail positions
        may own empty ranges."""
        shape = tuple(int(s) for s in shape)
        dims = len(shape)
        if split is None:
            return 0, shape, tuple(slice(0, end) for end in shape)
        if rank is None:
            rank = 0
        n = shape[split]
        c = self.chunk_size(n)
        start = min(rank * c, n)
        end = min((rank + 1) * c, n)
        lshape = shape[:split] + (end - start,) + shape[split + 1 :]
        slices = tuple(
            slice(start, end) if d == split else slice(0, shape[d]) for d in range(dims)
        )
        return start, lshape, slices

    def lshape_map(self, gshape: Sequence[int], split: Optional[int]) -> np.ndarray:
        """(size, ndim) int array of every position's logical chunk shape
        (reference dndarray.py:222 `lshape_map` property)."""
        out = np.empty((self.size, len(gshape)), dtype=np.int64)
        for r in range(self.size):
            _, lshape, _ = self.chunk(gshape, split, r)
            out[r] = lshape
        return out

    def counts_displs(self, n: int) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-position logical counts and displacements along a split
        dimension of length ``n`` (reference dndarray.py:552)."""
        c = self.chunk_size(n)
        counts = tuple(max(0, min((r + 1) * c, n) - min(r * c, n)) for r in range(self.size))
        displs = tuple(min(r * c, n) for r in range(self.size))
        return counts, displs

    # -- sharding factories --------------------------------------------------

    def spec(self, split: Optional[int], ndim: int) -> PartitionSpec:
        """PartitionSpec placing the mesh axis on dimension ``split``."""
        if split is None:
            return PartitionSpec()
        axes = [None] * ndim
        axes[split] = self.__axis
        return PartitionSpec(*axes)

    def sharding(self, split: Optional[int], ndim: int) -> NamedSharding:
        """NamedSharding for a DNDarray with the given split."""
        return NamedSharding(self.__mesh, self.spec(split, ndim))

    def replicated(self, ndim: int = 0) -> NamedSharding:
        return NamedSharding(self.__mesh, PartitionSpec())

    # -- collective cost model ----------------------------------------------

    def relayout_cost(
        self,
        gshape: Sequence[int],
        itemsize: int,
        old_split: Optional[int],
        new_split: Optional[int],
        precision: str = "off",
    ) -> "telemetry.collectives.CollectiveCost":
        """Analytic collective kind + wire bytes of a relayout on this mesh
        (telemetry/collectives.py — the observability analog of the
        reference's explicit Alltoallv volume). ``precision`` prices the
        compressed-wire program (ISSUE 9); callers pass the *effective*
        wire mode they resolved for the payload's dtype."""
        from . import collective_prec

        return telemetry.collectives.relayout_cost(
            gshape, itemsize, old_split, new_split, self.size,
            precision=precision, block=collective_prec.block_size(),
        )

    # -- 2-level topology (ISSUE 15) -----------------------------------------

    def topology(self):
        """The resolved 2-level ``(node, local)`` factorization of this
        mesh (:mod:`heat_tpu.core.topology`): the ``HEAT_TPU_TOPOLOGY``
        knob when declared, else auto-detection (host-process structure
        on real hardware; the DASO-style emulated two-node split on a
        single even host mesh). Resolved per call — the knob may change
        between traces."""
        from . import topology as _topo

        return _topo.resolve(self.size)

    def _hier(self):
        """The topology to lower tiered against, or None for flat:
        requires ``HEAT_TPU_HIERARCHICAL=1`` and a nontrivial
        factorization."""
        from . import topology as _topo

        return _topo.active(self.size)

    def hier_token(self):
        """The tiered-lowering program-cache key component
        (:func:`heat_tpu.core.topology.cache_token`). Callers caching
        programs built over the payload-moving wrappers must include
        this alongside ``collective_prec.effective(dtype)`` — same
        contract, same reason."""
        from . import topology as _topo

        return _topo.cache_token(self.size)

    def _cross_wire(self, x, precision: Optional[str]) -> str:
        """The cross-node tier's wire mode for one payload (per-call
        override → ``HEAT_TPU_HIERARCHICAL_PREC`` →
        ``HEAT_TPU_COLLECTIVE_PREC``; off for non-floats)."""
        from . import topology as _topo

        return _topo.cross_mode(x.dtype, precision)

    # -- explicit collectives (for hand-written shard_map kernels) -----------
    # These are thin curried wrappers so kernels don't hard-code axis names.
    # With telemetry enabled each wrapper records a trace-time event: the
    # wrappers run while a shard_map/jit body is being TRACED, so the event
    # stream names the collectives that entered a compiled program. A hot
    # cached program emits nothing — but a caller that builds a fresh
    # traced closure per invocation (the ring kernels) misses the cache
    # and re-emits on every call, so trace-event counts are per-trace,
    # not per-program.
    #
    # ``precision`` (ISSUE 9, HEAT_TPU_COLLECTIVE_PREC): every payload-
    # moving wrapper compresses its wire payload under the resolved mode
    # (global knob, or the per-call override). Float payloads only —
    # integer/bool payloads (indices, counts, sort keys) always move
    # exact — and exactness-critical kernels pin ``precision="off"`` at
    # their call site. The wire mode is part of the traced program, so
    # callers caching programs built over these wrappers must key on
    # ``collective_prec.effective(dtype)``.

    def _coll(self, name: str, fn, *args, **kwargs):
        """One collective wrapper body: with the resilience subsystem armed
        (ISSUE 5), the lax call runs under the fault injector + transient-
        retry guard at site ``collective.<name>`` — the wrappers execute
        while a program is being *traced*, so a retried transient simply
        re-issues the lax op into the same trace (nothing recompiles).
        Disarmed, the cost is one flag check."""
        if resilience.armed():
            return resilience.guarded_call(f"collective.{name}", fn, args, kwargs)
        return fn(*args, **kwargs)

    def _wire(self, x, precision: Optional[str]) -> str:
        """The effective wire mode for one payload (off for non-floats)."""
        from . import collective_prec

        return collective_prec.effective(x.dtype, precision)

    def psum(self, x, precision: Optional[str] = None):
        from . import collective_prec

        topo = self._hier()
        if topo is not None:
            from . import topology as _topo

            wire = self._cross_wire(x, precision)
            telemetry.trace_event(
                "psum", axis=self.__axis, wire=wire, hier=topo.describe(),
                **telemetry.collectives.hierarchical_allreduce_cost(
                    x.size, x.dtype.itemsize, topo.node, topo.local,
                    wire, collective_prec.block_size(),
                ).as_fields(),
            )
            return self._coll(
                "psum", _topo.hier_psum, x, self.__axis, topo, wire,
                collective_prec.block_size(),
            )
        wire = self._wire(x, precision)
        telemetry.trace_event("psum", axis=self.__axis, wire=wire)
        if wire != "off":
            return self._coll(
                "psum", collective_prec.psum, x, self.__axis, self.size, wire,
            )
        return self._coll("psum", jax.lax.psum, x, self.__axis)

    def reduce_scatter(self, x, precision: Optional[str] = None):
        """Reduce-scatter of this payload, flattened: position ``i``
        returns the 1-D ``(ceil(numel/p),)`` chunk ``i`` of the global
        sum (the ZeRO gradient primitive — arXiv:2004.13336). Flat it is
        one ``psum_scatter`` (quantized modes: the EQuARX first phase);
        tiered it is in-node reduce-scatter (exact) then cross-node
        reduce-scatter of the 1/local shard (``precision`` compresses
        the cross tier only)."""
        from . import collective_prec

        topo = self._hier()
        if topo is not None:
            from . import topology as _topo

            wire = self._cross_wire(x, precision)
            telemetry.trace_event(
                "reduce_scatter", axis=self.__axis, wire=wire,
                hier=topo.describe(),
                **telemetry.collectives.hierarchical_reduce_scatter_cost(
                    x.size, x.dtype.itemsize, topo.node, topo.local,
                    wire, collective_prec.block_size(),
                ).as_fields(),
            )
            return self._coll(
                "reduce_scatter", _topo.hier_reduce_scatter, x,
                self.__axis, topo, wire, collective_prec.block_size(),
            )
        wire = self._wire(x, precision)
        telemetry.trace_event(
            "reduce_scatter", axis=self.__axis, wire=wire
        )
        return self._coll(
            "reduce_scatter", collective_prec.reduce_scatter, x,
            self.__axis, self.size, wire,
        )

    def pmax(self, x):
        # extremes are exactness-critical (argmin/argmax tie-breaking,
        # guard thresholds) — never compressed
        telemetry.trace_event("pmax", axis=self.__axis)
        return self._coll("pmax", jax.lax.pmax, x, self.__axis)

    def pmin(self, x):
        telemetry.trace_event("pmin", axis=self.__axis)
        return self._coll("pmin", jax.lax.pmin, x, self.__axis)

    def axis_index(self):
        return jax.lax.axis_index(self.__axis)

    def all_gather(self, x, tiled: bool = True,
                   precision: Optional[str] = None):
        from . import collective_prec

        topo = self._hier()
        if topo is not None:
            from . import topology as _topo

            wire = self._cross_wire(x, precision)
            telemetry.trace_event(
                "all_gather", axis=self.__axis, wire=wire,
                hier=topo.describe(),
                **telemetry.collectives.hierarchical_allgather_cost(
                    x.size, x.dtype.itemsize, topo.node, topo.local,
                    wire, collective_prec.block_size(),
                ).as_fields(),
            )
            return self._coll(
                "all_gather", _topo.hier_all_gather, x, self.__axis,
                topo, wire, collective_prec.block_size(), tiled=tiled,
            )
        wire = self._wire(x, precision)
        telemetry.trace_event("all_gather", axis=self.__axis, wire=wire)
        if wire != "off":
            return self._coll(
                "all_gather", collective_prec.all_gather, x, self.__axis,
                wire, tiled=tiled,
            )
        return self._coll("all_gather", jax.lax.all_gather, x, self.__axis, tiled=tiled)

    def ppermute(self, x, perm, precision: Optional[str] = None):
        from . import collective_prec

        wire = self._wire(x, precision)
        telemetry.trace_event("ppermute", axis=self.__axis, wire=wire)
        if wire != "off":
            return self._coll(
                "ppermute", collective_prec.ppermute, x, self.__axis, perm,
                wire,
            )
        return self._coll("ppermute", jax.lax.ppermute, x, self.__axis, perm=perm)

    def ring_permute(self, x, shift: int = 1,
                     precision: Optional[str] = None):
        """Circulate shards around the ring: position i sends to i+shift."""
        n = self.size
        perm = [(i, (i + shift) % n) for i in range(n)]
        from . import collective_prec

        wire = self._wire(x, precision)
        telemetry.trace_event(
            "ppermute", axis=self.__axis, ring_shift=shift, wire=wire
        )
        if wire != "off":
            return self._coll(
                "ppermute", collective_prec.ppermute, x, self.__axis, perm,
                wire,
            )
        return self._coll("ppermute", jax.lax.ppermute, x, self.__axis, perm=perm)

    def all_to_all(self, x, split_axis: int, concat_axis: int,
                   precision: Optional[str] = None):
        from . import collective_prec

        topo = self._hier()
        if topo is not None:
            from . import topology as _topo

            wire = self._cross_wire(x, precision)
            phys = x.size * self.size  # per-shard payload × participants
            telemetry.trace_event(
                "all_to_all", axis=self.__axis, wire=wire,
                hier=topo.describe(),
                **telemetry.collectives.hierarchical_a2a_cost(
                    phys, x.dtype.itemsize, topo.node, topo.local,
                    wire, collective_prec.block_size(),
                ).as_fields(),
            )
            return self._coll(
                "all_to_all", _topo.hier_all_to_all, x, self.__axis,
                topo, split_axis, concat_axis, wire,
                collective_prec.block_size(),
            )
        wire = self._wire(x, precision)
        telemetry.trace_event("all_to_all", axis=self.__axis, wire=wire)
        if wire != "off":
            return self._coll(
                "all_to_all", collective_prec.all_to_all, x, self.__axis,
                self.size, split_axis, concat_axis, wire,
            )
        return self._coll(
            "all_to_all", jax.lax.all_to_all, x, self.__axis,
            split_axis=split_axis, concat_axis=concat_axis, tiled=True,
        )


# -- global communicator registry --------------------------------------------

__default_comm: Optional[MeshCommunication] = None


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> MeshCommunication:
    """Bootstrap the multi-host runtime and rebuild the default communicator
    over the full global device set (SURVEY §7 stage 1; the analog of the
    reference's ``mpirun`` launch + ``MPI_WORLD`` construction, reference
    communication.py:1867).

    Call once per host process before any array construction. On managed
    TPU pods the arguments are auto-detected from the environment
    (``jax.distributed.initialize()`` with no args); on manual clusters pass
    the coordinator's ``host:port``, the world size, and this process's
    rank. After initialization the default communicator's mesh spans every
    device of every host, sharded collectives ride ICI within a slice and
    DCN across hosts, and ``comm.rank``/``jax.process_index()`` report this
    host's rank."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    comm = MeshCommunication()
    use_comm(comm)
    return comm


def get_comm() -> MeshCommunication:
    """The globally-set default communicator (reference communication.py:1874).

    Built lazily over all devices of the default platform so that test
    harnesses can select the CPU platform before first use."""
    global __default_comm
    if __default_comm is None:
        __default_comm = MeshCommunication()
    return __default_comm


def use_comm(comm: Optional[MeshCommunication] = None) -> None:
    """Set the globally-used default communicator (reference
    communication.py:1904)."""
    global __default_comm
    if comm is not None and not isinstance(comm, MeshCommunication):
        raise TypeError(f"Unknown communication, must be MeshCommunication, got {comm!r}")
    __default_comm = comm if comm is not None else MeshCommunication()


def sanitize_comm(comm: Optional[Communication]) -> MeshCommunication:
    """Validate or default a communicator argument (reference
    communication.py:1881)."""
    if comm is None:
        return get_comm()
    if isinstance(comm, MeshCommunication):
        return comm
    raise TypeError(f"Unknown communication, must be MeshCommunication, got {comm!r}")
