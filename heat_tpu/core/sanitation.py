"""Input/output validation helpers (reference: heat/core/sanitation.py:30-207)."""

from __future__ import annotations

from typing import Any, Sequence, Union

import numpy as np
import jax.numpy as jnp

from . import types
from .communication import MeshCommunication

__all__ = [
    "sanitize_in",
    "sanitize_infinity",
    "sanitize_in_tensor",
    "sanitize_lshape",
    "sanitize_out",
    "sanitize_sequence",
    "scalar_to_1d",
]


def sanitize_in(x: Any) -> None:
    """Raise TypeError unless ``x`` is a DNDarray (reference sanitation.py:30)."""
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")


def sanitize_infinity(x) -> Union[int, float]:
    """Largest representable value for x's dtype — used as a +inf stand-in for
    integer types (reference sanitation.py)."""
    dtype = x.dtype if hasattr(x, "dtype") else types.heat_type_of(x)
    dtype = types.canonical_heat_type(dtype)
    if issubclass(dtype, types.integer):
        return types.iinfo(dtype).max
    return float("inf")


def sanitize_in_tensor(x: Any) -> None:
    """Raise TypeError unless ``x`` is a jax array (the reference's local
    torch.Tensor check, sanitation.py)."""
    if not isinstance(x, (jnp.ndarray, np.ndarray)):
        raise TypeError(f"input needs to be a jax array, but was {type(x)}")


def sanitize_lshape(array, tensor) -> None:
    """Verify a local tensor is a legal shard of the global array
    (reference sanitation.py)."""
    tshape = tuple(tensor.shape)
    gshape = array.shape
    if tshape == gshape:
        return
    split = array.split
    if split is None:
        raise ValueError(f"local tensor of shape {tshape} is not compatible with global shape {gshape}")
    wrong_dims = [
        d for d in range(len(gshape)) if d != split and tshape[d] != gshape[d]
    ]
    if wrong_dims or len(tshape) != len(gshape):
        raise ValueError(
            f"local tensor of shape {tshape} is not a valid shard of global shape {gshape} split {split}"
        )


def sanitize_out(out, output_shape, output_split, output_device, output_comm=None) -> None:
    """Validate an ``out`` buffer's metadata against the expected result
    (reference sanitation.py:103)."""
    from .dndarray import DNDarray

    if not isinstance(out, DNDarray):
        raise TypeError(f"expected out buffer to be a DNDarray but was {type(out)}")
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"Expecting output buffer of shape {tuple(output_shape)}, got {out.shape}")
    if out.split != output_split:
        raise ValueError(f"Expecting output buffer with split {output_split}, got {out.split}")
    if output_device is not None and out.device != output_device:
        raise ValueError(f"Device mismatch: out is on {out.device}, expected {output_device}")


def sanitize_sequence(seq: Any) -> list:
    """Normalize a sequence-like (list/tuple/replicated DNDarray) to a python
    list (reference sanitation.py)."""
    from .dndarray import DNDarray

    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    if isinstance(seq, DNDarray):
        if seq.split is None:
            return seq.tolist()
        raise ValueError(f"seq must not be distributed, got split={seq.split}")
    raise TypeError(f"seq must be a list, tuple or non-distributed DNDarray, got {type(seq)}")


def scalar_to_1d(x):
    """Turn a scalar DNDarray into a 1-element 1-D DNDarray (reference
    sanitation.py)."""
    from .dndarray import DNDarray

    if x.ndim == 1:
        return x
    if x.ndim != 0:
        raise ValueError(f"expected a scalar DNDarray, got ndim={x.ndim}")
    return DNDarray(
        jnp.reshape(x.larray, (1,)),
        gshape=(1,),
        dtype=x.dtype,
        split=None,
        device=x.device,
        comm=x.comm,
        balanced=True,
    )
