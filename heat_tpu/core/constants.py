"""Mathematical constants exported into the top-level namespace.

Parity with reference heat/core/constants.py (exports e, Euler, inf and aliases,
nan and aliases, pi).
"""

import math

__all__ = ["e", "Euler", "inf", "Inf", "Infty", "Infinity", "nan", "NaN", "pi"]

INF = float("inf")
NAN = float("nan")
PI = math.pi
E = math.e

e = E
Euler = E
inf = INF
Inf = INF
Infty = INF
Infinity = INF
nan = NAN
NaN = NAN
pi = PI

# sanitation.sanitize_infinity uses per-dtype "largest value" semantics; keep the
# generic float infinity here and let callers specialize.
