"""Trigonometric and hyperbolic ops (reference: heat/core/trigonometrics.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import binary_op, local_op
from .dndarray import DNDarray

__all__ = [
    "acos",
    "acosh",
    "asin",
    "asinh",
    "atan",
    "atan2",
    "atanh",
    "arccos",
    "arccosh",
    "arcsin",
    "arcsinh",
    "arctan",
    "arctan2",
    "arctanh",
    "cos",
    "cosh",
    "deg2rad",
    "degrees",
    "rad2deg",
    "radians",
    "sin",
    "sinh",
    "tan",
    "tanh",
]


def acos(x, out=None) -> DNDarray:
    return local_op(jnp.arccos, x, out)


arccos = acos


def acosh(x, out=None) -> DNDarray:
    return local_op(jnp.arccosh, x, out)


arccosh = acosh


def asin(x, out=None) -> DNDarray:
    return local_op(jnp.arcsin, x, out)


arcsin = asin


def asinh(x, out=None) -> DNDarray:
    return local_op(jnp.arcsinh, x, out)


arcsinh = asinh


def atan(x, out=None) -> DNDarray:
    return local_op(jnp.arctan, x, out)


arctan = atan


def atan2(t1, t2) -> DNDarray:
    """Elementwise quadrant-correct arctan(t1/t2) (reference
    trigonometrics.py `atan2`)."""
    return binary_op(jnp.arctan2, t1, t2)


arctan2 = atan2


def atanh(x, out=None) -> DNDarray:
    return local_op(jnp.arctanh, x, out)


arctanh = atanh


def cos(x, out=None) -> DNDarray:
    return local_op(jnp.cos, x, out)


def cosh(x, out=None) -> DNDarray:
    return local_op(jnp.cosh, x, out)


def deg2rad(x, out=None) -> DNDarray:
    return local_op(jnp.deg2rad, x, out)


radians = deg2rad


def rad2deg(x, out=None) -> DNDarray:
    return local_op(jnp.rad2deg, x, out)


degrees = rad2deg


def sin(x, out=None) -> DNDarray:
    return local_op(jnp.sin, x, out)


def sinh(x, out=None) -> DNDarray:
    return local_op(jnp.sinh, x, out)


def tan(x, out=None) -> DNDarray:
    return local_op(jnp.tan, x, out)


def tanh(x, out=None) -> DNDarray:
    return local_op(jnp.tanh, x, out)


DNDarray.cos = lambda self, out=None: cos(self, out)
DNDarray.sin = lambda self, out=None: sin(self, out)
DNDarray.tan = lambda self, out=None: tan(self, out)
DNDarray.cosh = lambda self, out=None: cosh(self, out)
DNDarray.sinh = lambda self, out=None: sinh(self, out)
DNDarray.tanh = lambda self, out=None: tanh(self, out)
