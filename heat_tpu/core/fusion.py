"""Eager fusion engine — defer-and-fuse elementwise chains into ONE cached
XLA program (ISSUE 4).

Heat's op machinery pays one dispatch per public op (reference
heat/core/_operations.py); the port kept that granularity, so a chain like
``ht.exp(a) - b * 2`` used to launch three separately compiled XLA programs
with intermediate buffers materialized between each. This module makes the
elementwise wrappers *lazy*: ``local_op`` / ``binary_op`` append a node to a
per-result :class:`FusedNode` DAG carried on the DNDarray instead of
dispatching, and the whole chain compiles as ONE jitted program — through
:func:`heat_tpu.core.program_cache.cached_program`, so repeated chains hit
the existing LRU registry and the HLO auditor lowers the very program that
executes — the first time any consumer touches the physical buffer.

Flush (materialization) boundaries
----------------------------------
Every read of ``DNDarray.larray`` flushes a pending chain, which makes the
boundary set *emergent* rather than enumerated: scans (``_masked``),
resplit/relayout, indexing, comm wrappers, ``.numpy()`` / ``__repr__`` /
I/O, halo exchanges, ``out=`` aliasing (the ``larray`` setter force-flushes
a pending destination) — anything that is not itself a deferrable op
materializes the chain first. Deferral additionally stops at the depth/node
caps (``HEAT_TPU_FUSION_DEPTH``, default 16; node cap is 4x the depth cap),
at non-allowlisted callables (lambdas, partials), at non-static kwargs, and
whenever the abstract result would not obey the tail-pad invariant — those
fall back to the exact eager path and count as ``fusion.fallbacks``.

Fusion 2.0 — through-reduction fusion and epilogue grafting (ISSUE 7)
---------------------------------------------------------------------
Reductions are no longer hard flush boundaries: a ``__reduce_op``-family
call (sum/mean/prod/min/max/any/all/var/std and the nan-variants, any
axis form, keepdims or not) whose operand carries a pending chain *absorbs*
the chain — :func:`absorb_reduce` compiles ONE map+reduce program through
``program_cache.cached_program`` under site ``fusion_reduce`` (structural
signature = chain signature + reduce op + axis/neutral/keepdims). The
cross-split case keeps the exact masked-neutral pad semantics *inside* the
fused program (an explicit ``__mask__`` node), and the ``psum``-style
collective tail XLA derives from the pinned ``out_shardings`` rides in the
same trace (HLO-auditable against
:func:`heat_tpu.telemetry.collectives.fusion_reduce_cost`).

Symmetrically, :func:`defer_matmul` makes ``matmul`` a lazy *kernel node*:
pending operand chains are grafted in as a pre-map, and downstream
elementwise ops (bias add, activation, Lasso's soft-threshold tail) graft
onto the kernel's output as an *epilogue* — ``matmul + bias + activation``
flushes as one cached program. ``HEAT_TPU_FUSION_REDUCE=0`` disables both
absorption paths, restoring the flush-at-reduction dispatch bit for bit;
unsupported ops / non-static kwargs count as ``fusion.fallbacks`` and
flush exactly as before. Counters ``fusion.reductions_absorbed`` /
``fusion.epilogues_grafted`` feed ``report.summarize()`` and the Chrome
trace.

Pad semantics
-------------
A fused chain propagates the tail-pad invariant exactly as the eager path
does: operands that span the full logical extent of the output's split dim
while replicated get an explicit ``pad`` node (the lazy twin of eager
``binary_op``'s ``phys()`` re-pad), so physical shapes broadcast inside the
single program and pad positions of the result depend only on pad positions
of the operands — nothing chain-internal can leak a pad value into a logical
position, mirroring eager op-by-op behavior bit for bit.

Program identity
----------------
The cached-program key is the DAG's *structural signature*: post-order op
ids, static kwargs, operand slot wiring, leaf physical shapes/dtypes,
scalar-vs-array operand kinds, and the result split (it pins
``out_shardings``). **Float/complex scalar values are runtime arguments**
— ``x * 2.0`` and ``x * 3.0`` (or a changing learning rate) share one
executable — while **integer/bool scalars are static constants** baked
into the program so XLA folds them exactly as eager dispatch does
(``x ** 3`` lowers to repeated multiplication in both modes — the
bit-for-bit parity contract). The compiled plan holds no buffer
references — a registry entry can never pin a device allocation alive.

Knobs / API
-----------
* ``HEAT_TPU_FUSION=0`` restores pure-eager dispatch (bit-for-bit identical
  results); default is on.
* ``HEAT_TPU_FUSION_DEPTH`` bounds chain depth before a forced flush
  (default 16; the node cap is 4x).
* :func:`fusing` — ``with ht.fusing():`` scoped (thread-local) override.
* :func:`fuse` — ``@ht.fuse`` decorator: enables fusion inside the call and
  flushes returned DNDarrays on exit.
* Telemetry counters ``fusion.deferred`` / ``fusion.flushes`` /
  ``fusion.nodes_flushed`` / ``fusion.fallbacks`` plus one instant
  ``fusion`` event per flush feed ``report.summarize()`` (which derives
  ``nodes_per_flush``) and the Chrome trace.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from heat_tpu import _knobs as knobs

from .. import telemetry

__all__ = [
    "fuse",
    "fusing",
    "active",
    "reduce_active",
    "depth_cap",
    "node_cap",
    "set_pressure_cap",
    "pressure_cap",
    "stats",
    "reset_stats",
    "register_elementwise",
    "DEFAULT_DEPTH",
]

DEFAULT_DEPTH = 16

# Memory-pressure override (resilience/memory_guard.py, ISSUE 5): when the
# pre-flight HBM budget predicts an overflow, the guard drops this to 1 so
# pending elementwise DAGs flush in minimal windows instead of accumulating
# wide programs with large temporaries; cleared again once a later
# preflight sees comfortable headroom. None = no pressure.
_PRESSURE_CAP: Optional[int] = None

# kwarg values that may be folded into a program key (static config)
_STATIC_KW = (int, float, bool, str, bytes, type(None))

_TLS = threading.local()
_LOCK = threading.Lock()
# Always-on lightweight counters (ints behind one lock) — the bench and the
# tests read dispatch counts here without enabling full telemetry.
_STATS = {
    "deferred": 0, "flushes": 0, "nodes_flushed": 0, "fallbacks": 0,
    "reductions_absorbed": 0, "epilogues_grafted": 0,
}


# -- enablement ---------------------------------------------------------------


def _env_enabled() -> bool:
    return knobs.raw("HEAT_TPU_FUSION", "1").strip().lower() not in (
        "0", "false", "off",
    )


def active() -> bool:
    """Whether elementwise deferral is currently on for this thread: a
    :func:`fusing` override wins, else ``HEAT_TPU_FUSION`` (default on).
    Read per call so tests/CLIs can flip the env var without a reload."""
    ov = getattr(_TLS, "override", None)
    if ov is not None:
        return ov
    return _env_enabled()


def reduce_active() -> bool:
    """Whether Fusion 2.0 absorption (through-reduction fusion and
    matmul/moments epilogue grafting) is on: requires :func:`active` AND
    ``HEAT_TPU_FUSION_REDUCE`` (default on). ``HEAT_TPU_FUSION_REDUCE=0``
    restores the flush-at-reduction dispatch bit for bit while plain
    elementwise fusion keeps running."""
    if not active():
        return False
    return knobs.raw("HEAT_TPU_FUSION_REDUCE", "1").strip().lower() not in (
        "0", "false", "off",
    )


def depth_cap() -> int:
    """Max chain depth before a forced flush (``HEAT_TPU_FUSION_DEPTH``;
    clamped down by the memory guard's pressure cap while the HBM budget
    predicts overflow — see :func:`set_pressure_cap`)."""
    cap = DEFAULT_DEPTH
    raw = knobs.raw("HEAT_TPU_FUSION_DEPTH", "").strip()
    if raw:
        try:
            n = int(raw)
            if n > 0:
                cap = n
        except ValueError:
            pass
    if _PRESSURE_CAP is not None:
        cap = min(cap, _PRESSURE_CAP)
    return cap


def set_pressure_cap(cap: Optional[int]) -> None:
    """Install (or with None clear) the memory-pressure window cap — the
    degradation lever the resilience memory guard pulls before failing a
    dispatch (resilience/memory_guard.py)."""
    global _PRESSURE_CAP
    _PRESSURE_CAP = int(cap) if cap is not None else None


def pressure_cap() -> Optional[int]:
    """The active memory-pressure cap, or None."""
    return _PRESSURE_CAP


def node_cap() -> int:
    """Max DAG size before a forced flush (4x the depth cap: a bushy tree
    of modest depth can still grow a program XLA chews on for seconds)."""
    return 4 * depth_cap()


class fusing:
    """``with ht.fusing():`` — scoped (thread-local) fusion enable;
    ``fusing(False)`` scopes a disable. Nestable and exception-safe."""

    def __init__(self, enable: bool = True):
        self._enable = bool(enable)
        self._prev: Any = None

    def __enter__(self) -> "fusing":
        self._prev = getattr(_TLS, "override", None)
        _TLS.override = self._enable
        return self

    def __exit__(self, *exc) -> bool:
        _TLS.override = self._prev
        return False


def _flush_tree(obj):
    """Materialize every DNDarray reachable through (nested) tuples, lists
    and dict values — the decorator's exit boundary."""
    from .dndarray import DNDarray

    if isinstance(obj, DNDarray):
        obj.larray  # property read flushes
    elif isinstance(obj, (tuple, list)):
        for v in obj:
            _flush_tree(v)
    elif isinstance(obj, dict):
        for v in obj.values():
            _flush_tree(v)
    return obj


def fuse(fn: Callable) -> Callable:
    """Decorator: run ``fn`` with fusion enabled and flush returned
    DNDarrays on exit, so the function boundary is a materialization
    boundary (``@ht.fuse`` on a step function = one program per chain)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with fusing(True):
            out = fn(*args, **kwargs)
        return _flush_tree(out)

    return wrapper


def stats() -> dict:
    """Snapshot of the fusion counters, plus the derived mean
    ``nodes_per_flush``."""
    with _LOCK:
        out = dict(_STATS)
    out["nodes_per_flush"] = (
        round(out["nodes_flushed"] / out["flushes"], 3) if out["flushes"] else 0.0
    )
    return out


def reset_stats() -> None:
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _count(key: str, delta: int = 1) -> None:
    with _LOCK:
        _STATS[key] += delta


# -- DAG ----------------------------------------------------------------------


class _Leaf:
    """A materialized operand: one committed jax.Array entering the chain.
    Captured **by value** at defer time, so later in-place mutation of the
    source DNDarray cannot change an already-issued chain (exactly the
    eager snapshot semantics)."""

    __slots__ = ("buffer",)

    def __init__(self, buffer):
        self.buffer = buffer


class _ScalarOperand:
    """A python / numpy scalar operand. The *kind* (python type or numpy
    dtype) is part of the program signature; float/complex values are
    runtime arguments (chains differing only in those share one
    executable), int/bool values are static constants (exact eager
    constant-folding parity) — see ``_compile_plan``."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


class FusedNode:
    """One deferred elementwise op. ``operands`` are ``FusedNode`` /
    ``_Leaf`` / ``_ScalarOperand``; ``op_id`` of ``"__pad__"`` marks the
    lazy twin of eager ``binary_op``'s ``phys()`` tail re-pad (``kwargs``
    then holds the static pad widths). ``buffer`` caches the materialized
    result, so a node that was flushed as a *root* re-enters later
    consumers as a leaf instead of recomputing; an *interior* node shared
    by two DAGs (``t = log(a); u = t+1; v = t*2`` with ``t`` never read)
    is re-traced inside each consumer's program — duplicated elementwise
    device work bounded by the depth cap, never duplicated buffers.
    *Kernel* nodes (deferred matmul) are the exception: a second consumer
    materializes them once via ``_entry_of`` — duplicating a contraction
    is not "bounded elementwise work".
    ``split`` is the result's logical split (set on root wrap — it pins
    the program's ``out_shardings``)."""

    __slots__ = (
        "op_id", "fn", "kwargs", "operands",
        "pshape", "dtype", "split", "depth", "nnodes", "buffer", "shared",
        "kernel",
    )

    def __init__(self, op_id, fn, kwargs, operands, pshape, dtype):
        self.op_id = op_id
        self.fn = fn
        self.kwargs = kwargs
        self.operands = tuple(operands)
        self.pshape = tuple(int(s) for s in pshape)
        self.dtype = dtype  # jnp dtype of the (strong-typed) result
        self.split = None
        # True for a deferred *kernel* node (matmul): elementwise consumers
        # deferring onto it are epilogue grafts (counted in
        # _commit_captures), and the kernel+tail flush as one program.
        self.kernel = False
        # True once another DAG consumed this node as an operand: the
        # owner's eventual flush result may then be referenced by other
        # pending chains, so its buffer must never be donated to XLA
        # (DNDarray._fusion_flush propagates this into the owner's
        # donation guard).
        self.shared = False
        d = 1
        n = 1
        for o in self.operands:
            if isinstance(o, FusedNode):
                d = max(d, o.depth + 1)
                n += o.nnodes
        self.depth = d
        self.nnodes = n
        self.buffer = None

    # -- materialization ------------------------------------------------------

    def materialize(self, comm):
        """Compile-or-reuse the chain as ONE cached program and run it.
        Idempotent (the result is cached on the node, so sibling DNDarrays
        sharing a sub-DAG reuse the buffer instead of recomputing)."""
        if self.buffer is not None:
            return self.buffer
        sig, plan, leaf_bufs, scalar_vals = _compile_plan(self)
        from . import program_cache

        if comm is not None and comm.size > 1:
            tgt = (
                comm.sharding(self.split, len(self.pshape))
                if self.split is not None
                else comm.replicated()
            )
        else:
            tgt = None

        def build():
            return _plan_program(plan)

        fn = program_cache.cached_program(
            "fusion", sig, build, comm=comm, out_shardings=tgt
        )
        buf = fn(*leaf_bufs, *scalar_vals)
        self.buffer = buf
        _count("flushes")
        _count("nodes_flushed", self.nnodes)
        if telemetry.enabled():
            reg = telemetry.get_registry()
            reg.add("fusion.flushes", 1)
            reg.add("fusion.nodes_flushed", self.nnodes)
            reg.emit(
                "fusion", "flush", nodes=self.nnodes, depth=self.depth,
                leaves=len(leaf_bufs), scalars=len(scalar_vals),
            )
        return buf


def _compile_plan(root: FusedNode):
    """Post-order walk of the DAG producing
    ``(signature, plan, leaf_buffers, scalar_values)``.

    ``plan`` is a buffer-free instruction list (the only thing the compiled
    closure captures): ``("leaf", argpos)`` / ``("scalar", argpos)`` /
    ``("pad", widths, slot)`` / ``("op", fn, kwargs, slots)``; each
    instruction's result occupies the next slot, the final slot is the
    chain result. The signature serializes the same walk with leaf
    shapes/dtypes and scalar kinds in place of values, making it injective
    over program structure: two DAGs with equal signatures compile to
    interchangeable executables with identical argument order."""
    plan: List[tuple] = []
    sig: List[tuple] = []
    leaf_bufs: List[Any] = []
    scalar_vals: List[Any] = []
    leaf_pos: Dict[int, int] = {}      # id(buffer) -> arg index
    scalar_pos: Dict[tuple, int] = {}  # (kind, value) -> scalar index
    slot_of: Dict[int, int] = {}       # id(node) -> slot

    def scalar_kind(v):
        if isinstance(v, np.generic):
            return ("np", str(v.dtype))
        return ("py", type(v).__name__)

    def walk(entry) -> int:
        if isinstance(entry, FusedNode) and entry.buffer is not None:
            # a chain another consumer already flushed re-enters as a leaf
            entry = _Leaf(entry.buffer)
        if isinstance(entry, _Leaf):
            buf = entry.buffer
            pos = leaf_pos.get(id(buf))
            if pos is None:
                pos = leaf_pos[id(buf)] = len(leaf_bufs)
                leaf_bufs.append(buf)
            plan.append(("leaf", pos))
            sig.append(("leaf", pos, tuple(buf.shape), str(buf.dtype)))
            return len(plan) - 1
        if isinstance(entry, _ScalarOperand):
            v = entry.value
            kind = scalar_kind(v)
            if isinstance(v, (bool, int, np.bool_, np.integer)):
                # integer/bool scalars are STATIC constants baked into the
                # program, not runtime args: XLA then folds them exactly
                # as eager dispatch does (x**3 lowers to repeated
                # multiplication, not generic pow — bit-for-bit parity),
                # at the cost of one program per distinct value. Float
                # scalars stay runtime args (empirically bit-clean across
                # mul/div/add/pow/mod — the traced-vs-constant battery in
                # tests/test_fusion.py pins the pow case).
                plan.append(("const", v))
                sig.append(("const",) + kind + (repr(v),))
                return len(plan) - 1
            # dedup key uses repr, not ==: python equality merges 0.0 with
            # -0.0 (and 1 with 1.0), which would silently substitute one
            # scalar for the other in sign-sensitive ops like copysign
            key = (kind, repr(v))
            pos = scalar_pos.get(key)
            if pos is None:
                pos = len(scalar_vals)
                scalar_vals.append(v)
                scalar_pos[key] = pos
            plan.append(("scalar", pos))
            sig.append(("scalar", pos) + kind)
            return len(plan) - 1
        # FusedNode
        slot = slot_of.get(id(entry))
        if slot is not None:
            return slot
        opnd_slots = tuple(walk(o) for o in entry.operands)
        if entry.op_id == "__pad__":
            widths = entry.kwargs["pad"]
            plan.append(("pad", widths, opnd_slots[0]))
            sig.append(("pad", widths, opnd_slots[0]))
        else:
            plan.append(("op", entry.fn, entry.kwargs, opnd_slots))
            kw_key = tuple(sorted(entry.kwargs.items())) if entry.kwargs else ()
            sig.append(("op", entry.op_id, kw_key, opnd_slots))
        slot = len(plan) - 1
        slot_of[id(entry)] = slot
        return slot

    out_slot = walk(root)
    sig.append(("out", out_slot, root.split))
    return (
        tuple(sig),
        (tuple(plan), out_slot, len(leaf_bufs)),
        leaf_bufs,
        scalar_vals,
    )


def _plan_program(plan_tuple):
    """Build the traced callable for one plan. Captures only the plan
    (fns + static config + slot ints) — never device buffers."""
    plan, out_slot, n_leaves = plan_tuple

    def fused_program(*args):
        slots: List[Any] = []
        for ins in plan:
            kind = ins[0]
            if kind == "leaf":
                slots.append(args[ins[1]])
            elif kind == "scalar":
                slots.append(args[n_leaves + ins[1]])
            elif kind == "const":
                slots.append(ins[1])
            elif kind == "pad":
                slots.append(jnp.pad(slots[ins[2]], ins[1]))
            else:  # ("op", fn, kwargs, slots)
                _, fn, kw, opnds = ins
                slots.append(fn(*(slots[i] for i in opnds), **kw))
        return slots[out_slot]

    return fused_program


# -- absorption building blocks (Fusion 2.0, ISSUE 7) -------------------------


def _mask_fill(x, *, dim, extent, fill):
    """Pad neutralization INSIDE a fused program — the traced twin of
    ``DNDarray._masked``: positions at global index >= ``extent`` along
    ``dim`` are replaced with ``fill`` (a static constant baked into the
    program)."""
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, dim)
    return jnp.where(idx < extent, x, jnp.asarray(fill, dtype=x.dtype))


def _cast_fn(x, *, dtype):
    """Static dtype cast as a fusable node (matmul's operand promotion)."""
    return x.astype(dtype)


def _masked_node(entry, dim: int, extent: int, fill) -> FusedNode:
    """Wrap ``entry`` in a ``__mask__`` node (see :func:`_mask_fill`)."""
    sds = _entry_sds(entry)
    return FusedNode(
        "__mask__", _mask_fill,
        {"dim": int(dim), "extent": int(extent), "fill": fill},
        (entry,), sds.shape, sds.dtype,
    )


def _cast_node(entry, dtype) -> FusedNode:
    sds = _entry_sds(entry)
    return FusedNode(
        "__cast__", _cast_fn, {"dtype": str(np.dtype(dtype))},
        (entry,), sds.shape, dtype,
    )


def _padded_node(entry, widths) -> FusedNode:
    sds = _entry_sds(entry)
    pshape = tuple(s + w0 + w1 for s, (w0, w1) in zip(sds.shape, widths))
    return FusedNode(
        "__pad__", None, {"pad": tuple(tuple(w) for w in widths)},
        (entry,), pshape, sds.dtype,
    )


def pending_plan(x):
    """``(signature, plan_tuple, args)`` for ``x``'s pending fused chain —
    the raw material an absorbing consumer (reduction, pallas moments)
    composes its own program from — or None when nothing is pending.
    ``plan_program(plan_tuple)`` rebuilds the chain callable; ``args`` is
    the positional argument list (leaf buffers then runtime scalars) the
    composed program must be called with, in signature order."""
    node = x._fused_node()
    if node is None or node.buffer is not None:
        return None
    if node.kernel and node.shared:
        return None  # materialize-once rule — see _entry_of
    sig, plan, leaf_bufs, scalar_vals = _compile_plan(node)
    return sig, plan, list(leaf_bufs) + list(scalar_vals)


# rebuilds the chain callable from a pending_plan plan tuple (public alias
# for absorbing consumers outside this module, e.g. statistics' fused
# pallas-moments program)
plan_program = _plan_program


def _note_absorbed(x, site: str, **fields) -> None:
    """Count one chain absorbed into a consumer's program: the chain DID
    materialize (inside the consumer's trace), so the flush counters keep
    their meaning, plus the Fusion 2.0 absorption counter and one instant
    event for the Chrome trace."""
    node = x._fused_node()
    nodes = node.nnodes if node is not None else 0
    _count("flushes")
    _count("nodes_flushed", nodes)
    _count("reductions_absorbed")
    if telemetry.enabled():
        reg = telemetry.get_registry()
        reg.add("fusion.flushes", 1)
        reg.add("fusion.nodes_flushed", nodes)
        reg.add("fusion.reductions_absorbed", 1)
        reg.emit("fusion", site, nodes=nodes, **fields)


def absorb_reduce(
    operation: Callable,
    x,
    red_axes: Tuple[int, ...],
    axis_arg,
    neutral,
    keepdims: bool,
    fn_kwargs: dict,
    out_gshape: Tuple[int, ...],
    out_split: Optional[int],
    crosses_split: bool,
    dtype_jnp,
):
    """Through-reduction fusion: compile ``x``'s pending elementwise chain
    PLUS the reduction as ONE cached program (site ``fusion_reduce``) and
    execute it, returning the result buffer — or None to fall back to the
    flush-then-eager-reduce path.

    The program replays the eager pipeline exactly: chain → masked-neutral
    pad fill (only when the reduction crosses the split axis of a padded
    operand) → ``operation(..., axis=, keepdims=)`` → optional static dtype
    cast. ``out_shardings`` pins the result layout, so the cross-shard
    combine (an all-reduce for split-crossing reductions) is part of the
    same trace — one program, one dispatch. Declined absorptions with a
    pending chain count as ``fusion.fallbacks`` and flush exactly as
    before."""
    node = x._fused_node()
    if node is None or node.buffer is not None:
        return None
    if node.kernel and node.shared:
        # a kernel node another chain already consumed: absorbing would
        # re-run the contraction inside the reduce program too — flush
        # once instead (the larray read below reuses the cached buffer)
        return None
    if not reduce_active():
        return None
    op_id = _op_id(operation)
    if op_id is None or not _static_kwargs(fn_kwargs):
        return _fallback()
    if not isinstance(neutral, _STATIC_KW):
        return _fallback()
    sig, plan, leaf_bufs, scalar_vals = _compile_plan(node)
    mask = None
    mask_key = None
    if crosses_split and x.pad_count:
        mask = (int(x.split), int(x.shape[x.split]), neutral)
        # key on repr, never the raw value: float('nan') hashes by object
        # identity, so a raw-NaN neutral (every nan-variant) would miss
        # the program registry on EVERY call and recompile per dispatch
        # (same rule as _compile_plan's scalar dedup)
        mask_key = (mask[0], mask[1], repr(neutral))
    axes = tuple(red_axes) if axis_arg is not None else None
    kw_key = tuple(
        (k, repr(v) if isinstance(v, float) else v)
        for k, v in sorted(fn_kwargs.items())
    )
    dt_key = None if dtype_jnp is None else str(np.dtype(dtype_jnp))
    rsig = sig + (
        ("reduce", op_id, axes, bool(keepdims), kw_key, mask_key, out_split,
         dt_key),
    )
    comm = x.comm
    if comm is not None and comm.size > 1:
        tgt = (
            comm.sharding(out_split, len(out_gshape))
            if out_split is not None
            else comm.replicated()
        )
    else:
        tgt = None

    def build():
        inner = _plan_program(plan)

        def fused_reduce(*args):
            val = inner(*args)
            if mask is not None:
                val = _mask_fill(
                    val, dim=mask[0], extent=mask[1], fill=mask[2]
                )
            r = operation(val, axis=axes, keepdims=keepdims, **fn_kwargs)
            if dtype_jnp is not None:
                r = r.astype(dtype_jnp)
            return r

        return fused_reduce

    from . import program_cache

    fn = program_cache.cached_program(
        "fusion_reduce", rsig, build, comm=comm, out_shardings=tgt
    )
    buf = fn(*leaf_bufs, *scalar_vals)
    _note_absorbed(
        x, "reduce_absorb", op=op_id, axes=list(red_axes),
        crosses_split=bool(crosses_split),
    )
    _maybe_audit_reduce(
        fn, rsig, comm, buf, out_gshape, crosses_split,
        (leaf_bufs, scalar_vals), op_id,
    )
    return buf


def _maybe_audit_reduce(
    fn, rsig, comm, buf, out_gshape, crosses_split, args, op_id
) -> None:
    """Ground-truth the fused collective tail: with the global HLO audit
    armed, lower the very cached program that just executed and diff its
    emitted collectives against the analytic all-reduce prediction
    (telemetry/collectives.fusion_reduce_cost). Memoized on the shared
    program signature; never raises; no-op when no collective is expected
    (1-position mesh or a reduction that keeps the split)."""
    if comm is None or comm.size <= 1 or not crosses_split:
        return
    from ..telemetry import hlo

    if not hlo.audit_enabled():
        return
    from . import program_cache

    leaf_bufs, scalar_vals = args
    predicted = telemetry.collectives.fusion_reduce_cost(
        out_gshape, buf.dtype.itemsize, comm.size
    )
    hlo.audit_call(
        "fusion_reduce",
        lambda: (fn, (*leaf_bufs, *scalar_vals)),
        predicted=predicted,
        key=program_cache.program_key("fusion_reduce", rsig, comm=comm),
        fields={"op": op_id, "out_gshape": list(out_gshape)},
    )


# -- deferral entry points (called by _operations) ----------------------------


# Framework-owned module-level elementwise helpers allowlisted for deferral
# by OBJECT identity (never by name): a module-level ``def`` has one stable
# identity per process, so — unlike lambdas/partials, which stay refused —
# keying the process-global program cache on its registered id is safe.
_REGISTERED_OPS: Dict[Callable, str] = {}


def register_elementwise(fn: Callable) -> Callable:
    """Allowlist a module-level framework helper for fusion (decorator).
    The registered op id is ``module.qualname`` — stable per process and
    unique per function object."""
    _REGISTERED_OPS[fn] = f"{fn.__module__}.{fn.__qualname__}"
    return fn


def _op_id(fn: Callable) -> Optional[str]:
    """Stable identity for an allowlisted elementwise callable, or None.

    Only module-level ``jax.numpy`` functions — plus framework helpers
    explicitly allowlisted via :func:`register_elementwise` — qualify:
    their (module, name) uniquely identifies the computation. Lambdas and
    partials are refused — two closures over different constants share a
    qualname, and keying a process-global program cache on one would
    silently reuse the wrong program."""
    reg = _REGISTERED_OPS.get(fn)
    if reg is not None:
        return reg
    name = getattr(fn, "__qualname__", None) or getattr(fn, "__name__", None)
    mod = getattr(fn, "__module__", None)
    if not name or not mod or "<" in name:
        return None
    if not (mod == "jax.numpy" or mod.startswith("jax.numpy.")
            or mod.startswith("jax._src.numpy")):
        return None
    return f"{mod}.{name}"


def _static_kwargs(kwargs: dict) -> bool:
    return all(isinstance(v, _STATIC_KW) for v in kwargs.values())


def _entry_of(a):
    """DNDarray -> DAG entry: its pending node (elementwise chains are
    never flushed here!) or a by-value leaf of its physical buffer.
    Capture marks are applied by :func:`_commit_captures` only once the
    op has actually deferred, so a fallback to eager dispatch leaves no
    stale non-donatable flags behind.

    One exception to no-side-effects: a *kernel* node (deferred matmul)
    that a previous chain already consumed (``shared``) materializes
    here and enters as a leaf — re-tracing it per consumer would
    duplicate a full O(n·k·m) contraction in every consumer's program,
    which the depth-cap rationale that bounds duplicated *elementwise*
    work cannot excuse. The single-consumer path (bias+activation
    epilogues) keeps full kernel fusion."""
    node = a._fused_node()
    if node is not None and node.buffer is None:
        if node.kernel and node.shared:
            node.materialize(a.comm)
            return _Leaf(node.buffer)
        return node
    if node is not None:
        return _Leaf(node.buffer)
    return _Leaf(a.larray)


def _commit_captures(pairs):
    """Record that a new node consumed these operands: the source arrays'
    CURRENT buffers (or their future flush results) are now reachable
    from another DAG, so they are marked non-donatable — an in-place
    ``resplit_`` donating one to XLA would hand a later flush a deleted
    array (eager dispatch computed consumers immediately, so this
    ordering could never fail there). ``pairs`` holds ``(entry, source
    DNDarray)`` for the pre-pad operand entries. Consuming a pending
    *kernel* node (a deferred matmul) is an epilogue graft — the
    elementwise tail rides into the kernel's program — and counts as
    ``fusion.epilogues_grafted``."""
    for entry, src in pairs:
        if isinstance(entry, FusedNode) and entry.buffer is None:
            entry.shared = True
            if entry.kernel:
                _count("epilogues_grafted")
                if telemetry.enabled():
                    reg = telemetry.get_registry()
                    reg.add("fusion.epilogues_grafted", 1)
                    reg.emit("fusion", "epilogue_graft", kernel=entry.op_id)
        else:
            src._mark_leaf_captured()


def _entry_sds(entry):
    """Abstract value of an entry for ``jax.eval_shape``. Nodes/leaves are
    strong-typed arrays (every node has at least one array operand, so its
    dtype is never weak); scalars pass through as concrete values so jax's
    own weak-type promotion applies exactly as in eager mode."""
    if isinstance(entry, FusedNode):
        return jax.ShapeDtypeStruct(entry.pshape, entry.dtype)
    if isinstance(entry, _Leaf):
        return jax.ShapeDtypeStruct(tuple(entry.buffer.shape), entry.buffer.dtype)
    return entry.value


def _entry_pshape(entry) -> Tuple[int, ...]:
    if isinstance(entry, FusedNode):
        return entry.pshape
    return tuple(entry.buffer.shape)


def _fallback():
    _count("fallbacks")
    if telemetry.enabled():
        telemetry.get_registry().add("fusion.fallbacks", 1)
    return None


def _wrap_deferred(node: FusedNode, gshape, out_split, device, comm):
    """Attach the result split and hand back a deferred DNDarray — or, at
    the depth/node caps, flush immediately so unbounded chains degrade to
    windowed fusion instead of unbounded program growth."""
    from . import types
    from .dndarray import DNDarray

    node.split = out_split
    ht_dtype = types.canonical_heat_type(node.dtype)
    _count("deferred")
    if telemetry.enabled():
        telemetry.get_registry().add("fusion.deferred", 1)
    if node.depth >= depth_cap() or node.nnodes >= node_cap():
        buf = node.materialize(comm)
        return DNDarray(buf, gshape, ht_dtype, out_split, device, comm, True)
    return DNDarray._from_fused(
        node, gshape, ht_dtype, out_split, device, comm, node.pshape
    )


def defer_local(operation: Callable, x, kwargs: dict):
    """Lazy twin of eager ``local_op``: returns a deferred DNDarray, or
    None to fall back. The result must preserve the physical shape (the
    elementwise contract) — anything else eagers out."""
    if not active():
        return None
    op_id = _op_id(operation)
    if op_id is None or not _static_kwargs(kwargs):
        return _fallback()
    entry = _entry_of(x)
    try:
        out = jax.eval_shape(
            functools.partial(operation, **kwargs), _entry_sds(entry)
        )
    except Exception:
        return _fallback()
    if tuple(out.shape) != _entry_pshape(entry):
        return _fallback()
    _commit_captures([(entry, x)])
    node = FusedNode(op_id, operation, dict(kwargs), (entry,), out.shape, out.dtype)
    return _wrap_deferred(node, x.shape, x.split, x.device, x.comm)


def defer_binary(
    operation: Callable,
    t1,
    t2,
    fn_kwargs: dict,
    out_shape: Tuple[int, ...],
    out_split: Optional[int],
    comm,
    device,
    padded: bool,
):
    """Lazy twin of eager ``binary_op`` (operands already normalized and
    split-reconciled by the caller). Re-creates the eager ``phys()`` pad
    alignment as explicit pad nodes, abstractly evaluates the result, and
    defers only when the physical result obeys the tail-pad invariant."""
    from .dndarray import DNDarray

    if not active():
        return None
    op_id = _op_id(operation)
    if op_id is None or not _static_kwargs(fn_kwargs):
        return _fallback()
    ndim_out = len(out_shape)
    entries = []
    captures = []
    for a in (t1, t2):
        if isinstance(a, DNDarray):
            e = _entry_of(a)
            captures.append((e, a))
            if out_split is not None and padded:
                # eager phys(): a replicated operand spanning the full
                # logical extent of the output's split dim is tail-padded
                # so physical shapes broadcast — here as a lazy pad node
                own_dim = out_split - (ndim_out - a.ndim)
                eshape = _entry_pshape(e)
                if (
                    own_dim >= 0
                    and a.split is None
                    and eshape[own_dim] == out_shape[out_split]
                ):
                    P = comm.padded_size(out_shape[out_split])
                    if P != eshape[own_dim]:
                        widths = [(0, 0)] * a.ndim
                        widths[own_dim] = (0, P - eshape[own_dim])
                        pshape = tuple(
                            s + w[1] for s, w in zip(eshape, widths)
                        )
                        e = FusedNode(
                            "__pad__", None, {"pad": tuple(widths)}, (e,),
                            pshape, _entry_sds(e).dtype,
                        )
            entries.append(e)
        else:
            entries.append(_ScalarOperand(a))
    try:
        out = jax.eval_shape(
            lambda u, v: operation(u, v, **fn_kwargs),
            *(_entry_sds(e) for e in entries),
        )
    except Exception:
        return _fallback()
    expected = comm.padded_shape(out_shape, out_split)
    if tuple(out.shape) != tuple(expected):
        return _fallback()
    _commit_captures(captures)
    node = FusedNode(
        op_id, operation, dict(fn_kwargs), entries, out.shape, out.dtype
    )
    return _wrap_deferred(node, out_shape, out_split, device, comm)


def defer_matmul(a, b, out_dtype_jnp, out_gshape, out_split, device, comm):
    """Lazy kernel node for ``linalg.matmul`` (epilogue grafting, ISSUE 7):
    instead of dispatching, wrap mask → cast → pad-align → ``jnp.matmul``
    as a *kernel* FusedNode. Pending operand chains graft in as the
    kernel's pre-map; downstream elementwise ops (bias add, activation,
    soft-threshold tails) defer onto the node as its epilogue — the whole
    ``matmul + tail`` flushes as ONE cached program with the result split
    pinning ``out_shardings`` (XLA derives the contraction collective
    inside the same trace). Returns a deferred DNDarray, or None to run
    today's eager kernel (counted as a fallback only when a pending chain
    would have been flushed by it).

    Mirrors the eager path op for op: operands are pad-masked to 0, cast
    to the promoted dtype, and contraction-side pads are aligned with
    explicit pad nodes — bit-equal semantics, one program."""
    if not reduce_active():
        return None
    ea0, eb0 = _entry_of(a), _entry_of(b)
    captures = [(ea0, a), (eb0, b)]
    had_pending = any(
        isinstance(e, FusedNode) and e.buffer is None for e in (ea0, eb0)
    )

    def decline():
        return _fallback() if had_pending else None

    def prep(entry, arr):
        if arr.pad_count:
            entry = _masked_node(
                entry, arr.split, arr.shape[arr.split], 0
            )
        if _entry_sds(entry).dtype != out_dtype_jnp:
            entry = _cast_node(entry, out_dtype_jnp)
        return entry

    ea, eb = prep(ea0, a), prep(eb0, b)
    ash, bsh = _entry_pshape(ea), _entry_pshape(eb)

    # contraction-side pad alignment (the eager branch structure verbatim:
    # when one operand's contraction dim is physically padded, the other
    # operand pads its matching dim so the contraction extents agree; the
    # masked zeros contribute nothing)
    def pad_entry(entry, ndim, dim, delta):
        if delta < 0:
            return None  # shapes the eager path would reject — let it
        widths = [(0, 0)] * ndim
        widths[dim] = (0, delta)
        return _padded_node(entry, widths)

    if a.ndim >= 2 and a.split == a.ndim - 1 and a.pad_count:
        dim = -2 if b.ndim > 1 else 0
        eb = pad_entry(eb, b.ndim, dim, ash[-1] - bsh[dim])
    elif b.ndim >= 2 and b.split == b.ndim - 2 and b.pad_count:
        ea = pad_entry(ea, a.ndim, -1, bsh[-2] - ash[-1])
    elif b.ndim == 1 and b.split == 0 and b.pad_count:
        ea = pad_entry(ea, a.ndim, -1, bsh[0] - ash[-1])
    elif a.ndim == 1 and a.split == 0 and a.pad_count and b.ndim > 1:
        eb = pad_entry(eb, b.ndim, -2, ash[0] - bsh[-2])
    if ea is None or eb is None:
        return decline()
    try:
        out = jax.eval_shape(jnp.matmul, _entry_sds(ea), _entry_sds(eb))
    except Exception:
        return decline()
    expected = comm.padded_shape(out_gshape, out_split)
    if tuple(out.shape) != tuple(expected):
        # result needs the eager path's slice/reshape repair — run it there
        return decline()
    _commit_captures(captures)
    node = FusedNode("__matmul__", jnp.matmul, {}, (ea, eb), out.shape, out.dtype)
    node.kernel = True
    return _wrap_deferred(node, out_gshape, out_split, device, comm)
