"""Eager fusion engine — defer-and-fuse elementwise chains into ONE cached
XLA program (ISSUE 4).

Heat's op machinery pays one dispatch per public op (reference
heat/core/_operations.py); the port kept that granularity, so a chain like
``ht.exp(a) - b * 2`` used to launch three separately compiled XLA programs
with intermediate buffers materialized between each. This module makes the
elementwise wrappers *lazy*: ``local_op`` / ``binary_op`` append a node to a
per-result :class:`FusedNode` DAG carried on the DNDarray instead of
dispatching, and the whole chain compiles as ONE jitted program — through
:func:`heat_tpu.core.program_cache.cached_program`, so repeated chains hit
the existing LRU registry and the HLO auditor lowers the very program that
executes — the first time any consumer touches the physical buffer.

Flush (materialization) boundaries
----------------------------------
Every read of ``DNDarray.larray`` flushes a pending chain, which makes the
boundary set *emergent* rather than enumerated: reductions and scans
(``_masked``), resplit/relayout, indexing, comm wrappers, ``.numpy()`` /
``__repr__`` / I/O, halo exchanges, ``out=`` aliasing (the ``larray`` setter
force-flushes a pending destination) — anything that is not itself a
deferrable elementwise op materializes the chain first. Deferral additionally
stops at the depth/node caps (``HEAT_TPU_FUSION_DEPTH``, default 16; node cap
is 4x the depth cap), at non-allowlisted callables (lambdas, partials), at
non-static kwargs, and whenever the abstract result would not obey the
tail-pad invariant — those fall back to the exact eager path and count as
``fusion.fallbacks``.

Pad semantics
-------------
A fused chain propagates the tail-pad invariant exactly as the eager path
does: operands that span the full logical extent of the output's split dim
while replicated get an explicit ``pad`` node (the lazy twin of eager
``binary_op``'s ``phys()`` re-pad), so physical shapes broadcast inside the
single program and pad positions of the result depend only on pad positions
of the operands — nothing chain-internal can leak a pad value into a logical
position, mirroring eager op-by-op behavior bit for bit.

Program identity
----------------
The cached-program key is the DAG's *structural signature*: post-order op
ids, static kwargs, operand slot wiring, leaf physical shapes/dtypes,
scalar-vs-array operand kinds, and the result split (it pins
``out_shardings``). **Float/complex scalar values are runtime arguments**
— ``x * 2.0`` and ``x * 3.0`` (or a changing learning rate) share one
executable — while **integer/bool scalars are static constants** baked
into the program so XLA folds them exactly as eager dispatch does
(``x ** 3`` lowers to repeated multiplication in both modes — the
bit-for-bit parity contract). The compiled plan holds no buffer
references — a registry entry can never pin a device allocation alive.

Knobs / API
-----------
* ``HEAT_TPU_FUSION=0`` restores pure-eager dispatch (bit-for-bit identical
  results); default is on.
* ``HEAT_TPU_FUSION_DEPTH`` bounds chain depth before a forced flush
  (default 16; the node cap is 4x).
* :func:`fusing` — ``with ht.fusing():`` scoped (thread-local) override.
* :func:`fuse` — ``@ht.fuse`` decorator: enables fusion inside the call and
  flushes returned DNDarrays on exit.
* Telemetry counters ``fusion.deferred`` / ``fusion.flushes`` /
  ``fusion.nodes_flushed`` / ``fusion.fallbacks`` plus one instant
  ``fusion`` event per flush feed ``report.summarize()`` (which derives
  ``nodes_per_flush``) and the Chrome trace.
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry

__all__ = [
    "fuse",
    "fusing",
    "active",
    "depth_cap",
    "node_cap",
    "set_pressure_cap",
    "pressure_cap",
    "stats",
    "reset_stats",
    "DEFAULT_DEPTH",
]

DEFAULT_DEPTH = 16

# Memory-pressure override (resilience/memory_guard.py, ISSUE 5): when the
# pre-flight HBM budget predicts an overflow, the guard drops this to 1 so
# pending elementwise DAGs flush in minimal windows instead of accumulating
# wide programs with large temporaries; cleared again once a later
# preflight sees comfortable headroom. None = no pressure.
_PRESSURE_CAP: Optional[int] = None

# kwarg values that may be folded into a program key (static config)
_STATIC_KW = (int, float, bool, str, bytes, type(None))

_TLS = threading.local()
_LOCK = threading.Lock()
# Always-on lightweight counters (ints behind one lock) — the bench and the
# tests read dispatch counts here without enabling full telemetry.
_STATS = {"deferred": 0, "flushes": 0, "nodes_flushed": 0, "fallbacks": 0}


# -- enablement ---------------------------------------------------------------


def _env_enabled() -> bool:
    return os.environ.get("HEAT_TPU_FUSION", "1").strip().lower() not in (
        "0", "false", "off",
    )


def active() -> bool:
    """Whether elementwise deferral is currently on for this thread: a
    :func:`fusing` override wins, else ``HEAT_TPU_FUSION`` (default on).
    Read per call so tests/CLIs can flip the env var without a reload."""
    ov = getattr(_TLS, "override", None)
    if ov is not None:
        return ov
    return _env_enabled()


def depth_cap() -> int:
    """Max chain depth before a forced flush (``HEAT_TPU_FUSION_DEPTH``;
    clamped down by the memory guard's pressure cap while the HBM budget
    predicts overflow — see :func:`set_pressure_cap`)."""
    cap = DEFAULT_DEPTH
    raw = os.environ.get("HEAT_TPU_FUSION_DEPTH", "").strip()
    if raw:
        try:
            n = int(raw)
            if n > 0:
                cap = n
        except ValueError:
            pass
    if _PRESSURE_CAP is not None:
        cap = min(cap, _PRESSURE_CAP)
    return cap


def set_pressure_cap(cap: Optional[int]) -> None:
    """Install (or with None clear) the memory-pressure window cap — the
    degradation lever the resilience memory guard pulls before failing a
    dispatch (resilience/memory_guard.py)."""
    global _PRESSURE_CAP
    _PRESSURE_CAP = int(cap) if cap is not None else None


def pressure_cap() -> Optional[int]:
    """The active memory-pressure cap, or None."""
    return _PRESSURE_CAP


def node_cap() -> int:
    """Max DAG size before a forced flush (4x the depth cap: a bushy tree
    of modest depth can still grow a program XLA chews on for seconds)."""
    return 4 * depth_cap()


class fusing:
    """``with ht.fusing():`` — scoped (thread-local) fusion enable;
    ``fusing(False)`` scopes a disable. Nestable and exception-safe."""

    def __init__(self, enable: bool = True):
        self._enable = bool(enable)
        self._prev: Any = None

    def __enter__(self) -> "fusing":
        self._prev = getattr(_TLS, "override", None)
        _TLS.override = self._enable
        return self

    def __exit__(self, *exc) -> bool:
        _TLS.override = self._prev
        return False


def _flush_tree(obj):
    """Materialize every DNDarray reachable through (nested) tuples, lists
    and dict values — the decorator's exit boundary."""
    from .dndarray import DNDarray

    if isinstance(obj, DNDarray):
        obj.larray  # property read flushes
    elif isinstance(obj, (tuple, list)):
        for v in obj:
            _flush_tree(v)
    elif isinstance(obj, dict):
        for v in obj.values():
            _flush_tree(v)
    return obj


def fuse(fn: Callable) -> Callable:
    """Decorator: run ``fn`` with fusion enabled and flush returned
    DNDarrays on exit, so the function boundary is a materialization
    boundary (``@ht.fuse`` on a step function = one program per chain)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with fusing(True):
            out = fn(*args, **kwargs)
        return _flush_tree(out)

    return wrapper


def stats() -> dict:
    """Snapshot of the fusion counters, plus the derived mean
    ``nodes_per_flush``."""
    with _LOCK:
        out = dict(_STATS)
    out["nodes_per_flush"] = (
        round(out["nodes_flushed"] / out["flushes"], 3) if out["flushes"] else 0.0
    )
    return out


def reset_stats() -> None:
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _count(key: str, delta: int = 1) -> None:
    with _LOCK:
        _STATS[key] += delta


# -- DAG ----------------------------------------------------------------------


class _Leaf:
    """A materialized operand: one committed jax.Array entering the chain.
    Captured **by value** at defer time, so later in-place mutation of the
    source DNDarray cannot change an already-issued chain (exactly the
    eager snapshot semantics)."""

    __slots__ = ("buffer",)

    def __init__(self, buffer):
        self.buffer = buffer


class _ScalarOperand:
    """A python / numpy scalar operand. The *kind* (python type or numpy
    dtype) is part of the program signature; float/complex values are
    runtime arguments (chains differing only in those share one
    executable), int/bool values are static constants (exact eager
    constant-folding parity) — see ``_compile_plan``."""

    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


class FusedNode:
    """One deferred elementwise op. ``operands`` are ``FusedNode`` /
    ``_Leaf`` / ``_ScalarOperand``; ``op_id`` of ``"__pad__"`` marks the
    lazy twin of eager ``binary_op``'s ``phys()`` tail re-pad (``kwargs``
    then holds the static pad widths). ``buffer`` caches the materialized
    result, so a node that was flushed as a *root* re-enters later
    consumers as a leaf instead of recomputing; an *interior* node shared
    by two DAGs (``t = log(a); u = t+1; v = t*2`` with ``t`` never read)
    is re-traced inside each consumer's program — duplicated elementwise
    device work bounded by the depth cap, never duplicated buffers.
    ``split`` is the result's logical split (set on root wrap — it pins
    the program's ``out_shardings``)."""

    __slots__ = (
        "op_id", "fn", "kwargs", "operands",
        "pshape", "dtype", "split", "depth", "nnodes", "buffer", "shared",
    )

    def __init__(self, op_id, fn, kwargs, operands, pshape, dtype):
        self.op_id = op_id
        self.fn = fn
        self.kwargs = kwargs
        self.operands = tuple(operands)
        self.pshape = tuple(int(s) for s in pshape)
        self.dtype = dtype  # jnp dtype of the (strong-typed) result
        self.split = None
        # True once another DAG consumed this node as an operand: the
        # owner's eventual flush result may then be referenced by other
        # pending chains, so its buffer must never be donated to XLA
        # (DNDarray._fusion_flush propagates this into the owner's
        # donation guard).
        self.shared = False
        d = 1
        n = 1
        for o in self.operands:
            if isinstance(o, FusedNode):
                d = max(d, o.depth + 1)
                n += o.nnodes
        self.depth = d
        self.nnodes = n
        self.buffer = None

    # -- materialization ------------------------------------------------------

    def materialize(self, comm):
        """Compile-or-reuse the chain as ONE cached program and run it.
        Idempotent (the result is cached on the node, so sibling DNDarrays
        sharing a sub-DAG reuse the buffer instead of recomputing)."""
        if self.buffer is not None:
            return self.buffer
        sig, plan, leaf_bufs, scalar_vals = _compile_plan(self)
        from . import program_cache

        if comm is not None and comm.size > 1:
            tgt = (
                comm.sharding(self.split, len(self.pshape))
                if self.split is not None
                else comm.replicated()
            )
        else:
            tgt = None

        def build():
            return _plan_program(plan)

        fn = program_cache.cached_program(
            "fusion", sig, build, comm=comm, out_shardings=tgt
        )
        buf = fn(*leaf_bufs, *scalar_vals)
        self.buffer = buf
        _count("flushes")
        _count("nodes_flushed", self.nnodes)
        if telemetry.enabled():
            reg = telemetry.get_registry()
            reg.add("fusion.flushes", 1)
            reg.add("fusion.nodes_flushed", self.nnodes)
            reg.emit(
                "fusion", "flush", nodes=self.nnodes, depth=self.depth,
                leaves=len(leaf_bufs), scalars=len(scalar_vals),
            )
        return buf


def _compile_plan(root: FusedNode):
    """Post-order walk of the DAG producing
    ``(signature, plan, leaf_buffers, scalar_values)``.

    ``plan`` is a buffer-free instruction list (the only thing the compiled
    closure captures): ``("leaf", argpos)`` / ``("scalar", argpos)`` /
    ``("pad", widths, slot)`` / ``("op", fn, kwargs, slots)``; each
    instruction's result occupies the next slot, the final slot is the
    chain result. The signature serializes the same walk with leaf
    shapes/dtypes and scalar kinds in place of values, making it injective
    over program structure: two DAGs with equal signatures compile to
    interchangeable executables with identical argument order."""
    plan: List[tuple] = []
    sig: List[tuple] = []
    leaf_bufs: List[Any] = []
    scalar_vals: List[Any] = []
    leaf_pos: Dict[int, int] = {}      # id(buffer) -> arg index
    scalar_pos: Dict[tuple, int] = {}  # (kind, value) -> scalar index
    slot_of: Dict[int, int] = {}       # id(node) -> slot

    def scalar_kind(v):
        if isinstance(v, np.generic):
            return ("np", str(v.dtype))
        return ("py", type(v).__name__)

    def walk(entry) -> int:
        if isinstance(entry, FusedNode) and entry.buffer is not None:
            # a chain another consumer already flushed re-enters as a leaf
            entry = _Leaf(entry.buffer)
        if isinstance(entry, _Leaf):
            buf = entry.buffer
            pos = leaf_pos.get(id(buf))
            if pos is None:
                pos = leaf_pos[id(buf)] = len(leaf_bufs)
                leaf_bufs.append(buf)
            plan.append(("leaf", pos))
            sig.append(("leaf", pos, tuple(buf.shape), str(buf.dtype)))
            return len(plan) - 1
        if isinstance(entry, _ScalarOperand):
            v = entry.value
            kind = scalar_kind(v)
            if isinstance(v, (bool, int, np.bool_, np.integer)):
                # integer/bool scalars are STATIC constants baked into the
                # program, not runtime args: XLA then folds them exactly
                # as eager dispatch does (x**3 lowers to repeated
                # multiplication, not generic pow — bit-for-bit parity),
                # at the cost of one program per distinct value. Float
                # scalars stay runtime args (empirically bit-clean across
                # mul/div/add/pow/mod — the traced-vs-constant battery in
                # tests/test_fusion.py pins the pow case).
                plan.append(("const", v))
                sig.append(("const",) + kind + (repr(v),))
                return len(plan) - 1
            # dedup key uses repr, not ==: python equality merges 0.0 with
            # -0.0 (and 1 with 1.0), which would silently substitute one
            # scalar for the other in sign-sensitive ops like copysign
            key = (kind, repr(v))
            pos = scalar_pos.get(key)
            if pos is None:
                pos = len(scalar_vals)
                scalar_vals.append(v)
                scalar_pos[key] = pos
            plan.append(("scalar", pos))
            sig.append(("scalar", pos) + kind)
            return len(plan) - 1
        # FusedNode
        slot = slot_of.get(id(entry))
        if slot is not None:
            return slot
        opnd_slots = tuple(walk(o) for o in entry.operands)
        if entry.op_id == "__pad__":
            widths = entry.kwargs["pad"]
            plan.append(("pad", widths, opnd_slots[0]))
            sig.append(("pad", widths, opnd_slots[0]))
        else:
            plan.append(("op", entry.fn, entry.kwargs, opnd_slots))
            kw_key = tuple(sorted(entry.kwargs.items())) if entry.kwargs else ()
            sig.append(("op", entry.op_id, kw_key, opnd_slots))
        slot = len(plan) - 1
        slot_of[id(entry)] = slot
        return slot

    out_slot = walk(root)
    sig.append(("out", out_slot, root.split))
    return (
        tuple(sig),
        (tuple(plan), out_slot, len(leaf_bufs)),
        leaf_bufs,
        scalar_vals,
    )


def _plan_program(plan_tuple):
    """Build the traced callable for one plan. Captures only the plan
    (fns + static config + slot ints) — never device buffers."""
    plan, out_slot, n_leaves = plan_tuple

    def fused_program(*args):
        slots: List[Any] = []
        for ins in plan:
            kind = ins[0]
            if kind == "leaf":
                slots.append(args[ins[1]])
            elif kind == "scalar":
                slots.append(args[n_leaves + ins[1]])
            elif kind == "const":
                slots.append(ins[1])
            elif kind == "pad":
                slots.append(jnp.pad(slots[ins[2]], ins[1]))
            else:  # ("op", fn, kwargs, slots)
                _, fn, kw, opnds = ins
                slots.append(fn(*(slots[i] for i in opnds), **kw))
        return slots[out_slot]

    return fused_program


# -- deferral entry points (called by _operations) ----------------------------


def _op_id(fn: Callable) -> Optional[str]:
    """Stable identity for an allowlisted elementwise callable, or None.

    Only module-level ``jax.numpy`` functions qualify: their
    (module, name) uniquely identifies the computation. Lambdas and
    partials are refused — two closures over different constants share a
    qualname, and keying a process-global program cache on one would
    silently reuse the wrong program."""
    name = getattr(fn, "__qualname__", None) or getattr(fn, "__name__", None)
    mod = getattr(fn, "__module__", None)
    if not name or not mod or "<" in name:
        return None
    if not (mod == "jax.numpy" or mod.startswith("jax.numpy.")
            or mod.startswith("jax._src.numpy")):
        return None
    return f"{mod}.{name}"


def _static_kwargs(kwargs: dict) -> bool:
    return all(isinstance(v, _STATIC_KW) for v in kwargs.values())


def _entry_of(a):
    """DNDarray -> DAG entry: its pending node (never flushed here!) or a
    by-value leaf of its physical buffer. Side-effect free — capture
    marks are applied by :func:`_commit_captures` only once the op has
    actually deferred, so a fallback to eager dispatch leaves no stale
    non-donatable flags behind."""
    node = a._fused_node()
    if node is not None and node.buffer is None:
        return node
    if node is not None:
        return _Leaf(node.buffer)
    return _Leaf(a.larray)


def _commit_captures(pairs):
    """Record that a new node consumed these operands: the source arrays'
    CURRENT buffers (or their future flush results) are now reachable
    from another DAG, so they are marked non-donatable — an in-place
    ``resplit_`` donating one to XLA would hand a later flush a deleted
    array (eager dispatch computed consumers immediately, so this
    ordering could never fail there). ``pairs`` holds ``(entry, source
    DNDarray)`` for the pre-pad operand entries."""
    for entry, src in pairs:
        if isinstance(entry, FusedNode) and entry.buffer is None:
            entry.shared = True
        else:
            src._mark_leaf_captured()


def _entry_sds(entry):
    """Abstract value of an entry for ``jax.eval_shape``. Nodes/leaves are
    strong-typed arrays (every node has at least one array operand, so its
    dtype is never weak); scalars pass through as concrete values so jax's
    own weak-type promotion applies exactly as in eager mode."""
    if isinstance(entry, FusedNode):
        return jax.ShapeDtypeStruct(entry.pshape, entry.dtype)
    if isinstance(entry, _Leaf):
        return jax.ShapeDtypeStruct(tuple(entry.buffer.shape), entry.buffer.dtype)
    return entry.value


def _entry_pshape(entry) -> Tuple[int, ...]:
    if isinstance(entry, FusedNode):
        return entry.pshape
    return tuple(entry.buffer.shape)


def _fallback():
    _count("fallbacks")
    if telemetry.enabled():
        telemetry.get_registry().add("fusion.fallbacks", 1)
    return None


def _wrap_deferred(node: FusedNode, gshape, out_split, device, comm):
    """Attach the result split and hand back a deferred DNDarray — or, at
    the depth/node caps, flush immediately so unbounded chains degrade to
    windowed fusion instead of unbounded program growth."""
    from . import types
    from .dndarray import DNDarray

    node.split = out_split
    ht_dtype = types.canonical_heat_type(node.dtype)
    _count("deferred")
    if telemetry.enabled():
        telemetry.get_registry().add("fusion.deferred", 1)
    if node.depth >= depth_cap() or node.nnodes >= node_cap():
        buf = node.materialize(comm)
        return DNDarray(buf, gshape, ht_dtype, out_split, device, comm, True)
    return DNDarray._from_fused(
        node, gshape, ht_dtype, out_split, device, comm, node.pshape
    )


def defer_local(operation: Callable, x, kwargs: dict):
    """Lazy twin of eager ``local_op``: returns a deferred DNDarray, or
    None to fall back. The result must preserve the physical shape (the
    elementwise contract) — anything else eagers out."""
    if not active():
        return None
    op_id = _op_id(operation)
    if op_id is None or not _static_kwargs(kwargs):
        return _fallback()
    entry = _entry_of(x)
    try:
        out = jax.eval_shape(
            functools.partial(operation, **kwargs), _entry_sds(entry)
        )
    except Exception:
        return _fallback()
    if tuple(out.shape) != _entry_pshape(entry):
        return _fallback()
    _commit_captures([(entry, x)])
    node = FusedNode(op_id, operation, dict(kwargs), (entry,), out.shape, out.dtype)
    return _wrap_deferred(node, x.shape, x.split, x.device, x.comm)


def defer_binary(
    operation: Callable,
    t1,
    t2,
    fn_kwargs: dict,
    out_shape: Tuple[int, ...],
    out_split: Optional[int],
    comm,
    device,
    padded: bool,
):
    """Lazy twin of eager ``binary_op`` (operands already normalized and
    split-reconciled by the caller). Re-creates the eager ``phys()`` pad
    alignment as explicit pad nodes, abstractly evaluates the result, and
    defers only when the physical result obeys the tail-pad invariant."""
    from .dndarray import DNDarray

    if not active():
        return None
    op_id = _op_id(operation)
    if op_id is None or not _static_kwargs(fn_kwargs):
        return _fallback()
    ndim_out = len(out_shape)
    entries = []
    captures = []
    for a in (t1, t2):
        if isinstance(a, DNDarray):
            e = _entry_of(a)
            captures.append((e, a))
            if out_split is not None and padded:
                # eager phys(): a replicated operand spanning the full
                # logical extent of the output's split dim is tail-padded
                # so physical shapes broadcast — here as a lazy pad node
                own_dim = out_split - (ndim_out - a.ndim)
                eshape = _entry_pshape(e)
                if (
                    own_dim >= 0
                    and a.split is None
                    and eshape[own_dim] == out_shape[out_split]
                ):
                    P = comm.padded_size(out_shape[out_split])
                    if P != eshape[own_dim]:
                        widths = [(0, 0)] * a.ndim
                        widths[own_dim] = (0, P - eshape[own_dim])
                        pshape = tuple(
                            s + w[1] for s, w in zip(eshape, widths)
                        )
                        e = FusedNode(
                            "__pad__", None, {"pad": tuple(widths)}, (e,),
                            pshape, _entry_sds(e).dtype,
                        )
            entries.append(e)
        else:
            entries.append(_ScalarOperand(a))
    try:
        out = jax.eval_shape(
            lambda u, v: operation(u, v, **fn_kwargs),
            *(_entry_sds(e) for e in entries),
        )
    except Exception:
        return _fallback()
    expected = comm.padded_shape(out_shape, out_split)
    if tuple(out.shape) != tuple(expected):
        return _fallback()
    _commit_captures(captures)
    node = FusedNode(
        op_id, operation, dict(fn_kwargs), entries, out.shape, out.dtype
    )
    return _wrap_deferred(node, out_shape, out_split, device, comm)
